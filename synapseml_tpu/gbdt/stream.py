"""Out-of-core GBDT: train on datasets far larger than device memory by
re-streaming host-cached QUANTIZED chunks through the shared ingestion layer.

The resident growers (grower.py / grower_depthwise.py) require the whole
binned matrix on device; past single-chip HBM the dataset size — not FLOPs —
is the wall (ROADMAP item 2). GPU tree-boosting work (arXiv:1706.08359)
showed that streaming a COMPRESSED feature matrix chunk-wise with per-chunk
histogram accumulation recovers near-resident throughput far beyond memory;
this module is that data plane:

* :class:`StreamedDataset` — ingests raw row chunks ONCE (dense or scipy
  sparse), learns bin boundaries with a one-pass
  :class:`~synapseml_tpu.ops.quantize.StreamingQuantileSketch` (bit-identical
  to the resident boundaries while the stream fits the sample buffer), and
  caches the quantized rows host-side as uniform feature-major uint8 chunks
  — 4x smaller than the raw floats, the compressed stream the device pulls.

* :func:`train_booster_streamed` — level-synchronous depthwise growth.
  Per level, every chunk makes one device trip: a single jitted program
  routes the chunk's rows against the previous level's
  :class:`~synapseml_tpu.gbdt.grower_depthwise._LevelPlan` and scatter-adds
  the (L, FP, B, 3) frontier histogram (ops/hist_kernel._hist_level_xla);
  chunk partials sum on device and flow through the SAME
  ``hist_allreduce_dtype`` ladder / split search / bookkeeping as the
  resident depthwise grower (the helpers are shared, not copied). Chunks
  move through a threaded :class:`~synapseml_tpu.io.ingest.ChunkPump`
  (transfer of chunk k+1 overlaps compute on chunk k), and every chunk
  boundary is a preemption point + watchdog heartbeat
  (phase ``"gbdt.stream.chunk"``), so PR 2 checkpoints and PR 10 elastic
  watchdogs compose with streaming for free.

* :func:`predict_streamed` — out-of-core scoring: raw chunks in, per-chunk
  predictions out, through the same pump.

Parity contract (tests/test_oocore.py): ``resident=True`` runs the IDENTICAL
jitted programs over pre-staged device-resident chunks — the pump, the
double-buffering, and the preemption machinery are bitwise-transparent, so
streamed == resident-mode trees bit for bit. Versus the classic resident
``train_booster`` the accumulation GEOMETRY differs (per-chunk partial sums
vs one whole-matrix scatter), so cross-path parity is a quality bound (AUC
within 1e-3 on the breast-cancer fixture), while boundary parity is exact
whenever the sketch never overflowed. See docs/out-of-core.md.

v1 scope (raise loud, never silently degrade): single chip, gbdt boosting,
binary/regression-family objectives (num_class == 1), no bagging / GOSS /
DART / feature sampling, no validation-driven early stopping. Multi-chip
streaming (per-chunk psum over a sharded pump) is the documented follow-up.
"""

from __future__ import annotations

import dataclasses
import time as _time
import warnings
from typing import Callable, Iterable, List, NamedTuple, Optional, Sequence

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..io.ingest import ChunkPump, stream_chunk_rows, stream_depth
from ..ops.hist_kernel import _hist_level_xla, features_padded, pad_bins
from ..ops.quantize import (BinMapper, CsrBinner, StreamingQuantileSketch,
                            apply_bins)
from .boosting import Booster, BoosterConfig, _ckpt_load_gbdt, _ckpt_save_gbdt
from .grower import (BITS, GrowerConfig, _best_for_leaf, _finalize_tree,
                     _init_split_state, _maybe_psum)
from .grower_depthwise import (_apply_level_splits, _level_candidates,
                               _route_level)
from .objectives import get_objective

STREAM_PHASE = "gbdt.stream.chunk"


def _is_sparse(x) -> bool:
    return hasattr(x, "tocoo")


class StreamedDataset:
    """Out-of-core training data: a re-iterable chunk source plus the
    host-cached quantized form ``train_booster_streamed`` streams from.

    ``batches`` is a CALLABLE returning an iterator of chunks — each chunk a
    dense ``(c, F)`` array or scipy sparse matrix, optionally tupled with
    per-chunk labels/weights: ``X``, ``(X, y)`` or ``(X, y, w)``. The
    callable is invoked once per ingest pass (twice total when boundaries
    must be sketched: sketch pass, then bin+cache pass), so generators must
    be wrapped in a function, not passed pre-consumed.

    ``prepare(config)`` resolves the chunk geometry (io/ingest.py:
    explicit > env > tuned file > bandwidth micro-probe, capped by the
    ``SYNAPSEML_TPU_STREAM_MEM_BUDGET`` device budget), learns boundaries
    (sketch — or adopts ``mapper``), and re-chunks the stream into uniform
    ``(FP, C)`` feature-major quantized host chunks (the last chunk padded
    with zero-mass rows so every device program compiles ONCE). Sparse
    chunks are quantized on device through
    :class:`~synapseml_tpu.ops.quantize.CsrBinner` — implicit zeros never
    densify at dataset scale.
    """

    def __init__(self, batches: Callable[[], Iterable],
                 num_features: Optional[int] = None,
                 mapper: Optional[BinMapper] = None,
                 categorical_features: Optional[Sequence[int]] = None,
                 chunk_rows: Optional[int] = None,
                 depth: Optional[int] = None,
                 exact_second_pass: Optional[bool] = None):
        if not callable(batches):
            raise TypeError(
                "StreamedDataset needs a CALLABLE returning an iterator of "
                "chunks (a consumed iterator cannot support the multiple "
                "ingest passes); wrap it: StreamedDataset(lambda: chunks)")
        self._batches = batches
        self.num_features = num_features
        self.mapper = mapper
        self._user_mapper = mapper is not None
        self.categorical_features = (list(categorical_features)
                                     if categorical_features else None)
        self._chunk_rows_arg = chunk_rows
        self._depth_arg = depth
        # exact second sketch pass when the one-pass sketch overflowed its
        # sample budget (ROADMAP 2d): None = let core/perfmodel price it,
        # True/False forces — the explicit bypass
        self._exact_second_pass = exact_second_pass
        self.second_pass_decision: Optional[dict] = None
        self._rows_sketched = 0
        self.chunk_rows: Optional[int] = None     # C, after prepare()
        self.depth: Optional[int] = None
        self.chunks: List[dict] = []              # bT (FP, C), y/w/m (C,)
        self.chunk_real: List[int] = []           # real (unpadded) rows
        self.n_rows = 0
        self.sketch_exact: Optional[bool] = None  # None = mapper was given
        self._prepared_for = None

    @classmethod
    def from_arrays(cls, X, y=None, w=None, source_chunk: int = 65536,
                    **kwargs) -> "StreamedDataset":
        """Wrap in-memory arrays (dense or scipy sparse rows) as a chunk
        source — the fits-in-memory path of the parity tests and benches."""
        n = X.shape[0]
        f = X.shape[1]

        def batches():
            for i in range(0, n, source_chunk):
                sl = slice(i, min(i + source_chunk, n))
                yield (X[sl],
                       None if y is None else y[sl],
                       None if w is None else w[sl])

        return cls(batches, num_features=f, **kwargs)

    # -- ingest ------------------------------------------------------------
    def _norm_chunk(self, chunk):
        """(X, y, w) from any accepted chunk shape."""
        if isinstance(chunk, tuple):
            X = chunk[0]
            y = chunk[1] if len(chunk) > 1 else None
            w = chunk[2] if len(chunk) > 2 else None
        else:
            X, y, w = chunk, None, None
        if self.num_features is None:
            self.num_features = int(X.shape[1])
        elif int(X.shape[1]) != self.num_features:
            raise ValueError(f"chunk has {X.shape[1]} features, dataset has "
                             f"{self.num_features}")
        return X, y, w

    def _sketch_pass(self, cfg: BoosterConfig) -> None:
        seed = (cfg.seed if cfg.data_random_seed is None
                else int(cfg.data_random_seed))
        sketch = None
        for chunk in self._batches():
            X, _, _ = self._norm_chunk(chunk)
            if sketch is None:
                sketch = StreamingQuantileSketch(
                    self.num_features, cfg.max_bin, cfg.bin_sample_count,
                    self.categorical_features, seed=seed,
                    min_data_in_bin=cfg.min_data_in_bin,
                    max_bin_by_feature=cfg.max_bin_by_feature)
            if _is_sparse(X):
                coo = X.tocoo()
                sketch.update_csr(coo.data, coo.row, coo.col, X.shape[0])
            else:
                sketch.update(np.asarray(X, np.float32))
        if sketch is None or sketch.rows_seen == 0:
            raise ValueError("StreamedDataset source yielded no rows")
        self.sketch_exact = sketch.exact
        self._rows_sketched = int(sketch.rows_seen)
        self.mapper = sketch.finalize()

    def _maybe_exact_second_pass(self, cfg: BoosterConfig,
                                 pass_s: float) -> None:
        """ROADMAP 2d: the one-pass sketch overflowed its sample budget, so
        boundaries are reservoir-sampled. A second full pass with the budget
        raised to the stream length makes them exact — worth it only when
        that pass is cheap next to training. core/perfmodel prices the pass
        (measured sketch rate from THIS stream as the analytic prior) against
        the estimated training cost: num_iterations x tree levels re-streams
        of the same data. ``exact_second_pass=True/False`` bypasses."""
        from ..core import perfmodel

        rows, nfeat = self._rows_sketched, self.num_features
        if self._exact_second_pass is not None:
            take = bool(self._exact_second_pass)
            self.second_pass_decision = {"kind": "gbdt_sketch_pass",
                                         "arm": "exact" if take else "skip",
                                         "source": "explicit"}
        else:
            levels = max(1, int(np.ceil(np.log2(max(cfg.num_leaves, 2)))))
            train_est = pass_s * max(cfg.num_iterations, 1) * levels
            rate = rows / pass_s if pass_s > 0 else None
            take, dec = perfmodel.suggest_sketch_second_pass(
                float(rows), float(nfeat), rate, train_est)
            # an exact sketch buffers the full stream host-side — never
            # trade boundaries for an OOM
            if take and rows * nfeat * 4 > (2 << 30):
                take = False
                dec.arm, dec.used_fallback = "skip", True
                dec.source = "host_budget"
            self.second_pass_decision = dec.audit(observed_s=None)
        if not take:
            return
        t0 = _time.perf_counter()
        self._sketch_pass(dataclasses.replace(
            cfg, bin_sample_count=max(rows, cfg.bin_sample_count)))
        if isinstance(self.second_pass_decision, dict) and \
                self.second_pass_decision.get("source") != "explicit":
            self.second_pass_decision["observed_s"] = round(
                _time.perf_counter() - t0, 6)

    def _bin_chunk(self, X, binner: Optional[CsrBinner]) -> np.ndarray:
        """(c, F) quantized host rows for one raw chunk."""
        if _is_sparse(X):
            coo = X.tocoo()
            return np.asarray(binner(coo.data, coo.row, coo.col, X.shape[0]))
        return np.asarray(apply_bins(self.mapper, np.asarray(X, np.float32)))

    def prepare(self, config: BoosterConfig) -> "StreamedDataset":
        """Idempotent per binning config: sketch (unless a mapper was given),
        resolve chunk geometry, quantize + cache the stream."""
        key = (config.max_bin, config.bin_sample_count,
               config.min_data_in_bin,
               tuple(config.max_bin_by_feature or ()),
               config.seed if config.data_random_seed is None
               else int(config.data_random_seed))
        if self._prepared_for == key:
            return self
        if self._prepared_for is not None and self._user_mapper is False:
            # re-preparing under different binning would silently retrain on
            # different boundaries — make the caller rebuild the dataset
            raise ValueError(
                f"StreamedDataset already prepared for binning {self._prepared_for}; "
                f"got {key} — build a fresh StreamedDataset")
        if self.mapper is None:
            t0 = _time.perf_counter()
            self._sketch_pass(config)
            pass_s = _time.perf_counter() - t0
            if self.sketch_exact is False:
                self._maybe_exact_second_pass(config, pass_s)
        if self.mapper.max_bin != config.max_bin:
            raise ValueError(
                f"mapper has max_bin={self.mapper.max_bin} but config asks "
                f"{config.max_bin}")

        F = self.num_features
        FP = features_padded(F)
        # one streamed row's device footprint: quantized bins (feature-major
        # uint8/16) + y/w/m/score f32 + node i32
        unit = 1 if self.mapper.max_bin <= 256 else 2
        row_bytes = FP * unit + 20
        self.depth = stream_depth(self._depth_arg)
        C = stream_chunk_rows(row_bytes, explicit=self._chunk_rows_arg,
                              depth=self.depth)
        self.chunk_rows = C
        # perfmodel provenance when the probe branch picked the geometry
        # (None under the explicit/env/tuned bypass)
        from ..io import ingest as _ingest

        self.chunk_decision = _ingest.last_chunk_decision()
        bin_dtype = np.uint8 if unit == 1 else np.uint16

        self.chunks, self.chunk_real, self.n_rows = [], [], 0
        binner = CsrBinner(self.mapper)
        buf_b = np.zeros((C, F), bin_dtype)
        buf_y = np.zeros(C, np.float32)
        buf_w = np.zeros(C, np.float32)
        fill = 0

        def flush():
            nonlocal fill, C
            if fill == 0:
                return
            if not self.chunks and fill < C:
                # the whole stream fit one partial chunk: shrink the chunk
                # to the real row count instead of padding (a probe-derived
                # C far above n_rows would otherwise make every device
                # program chew mostly zero-mass padding)
                C = fill
                self.chunk_rows = C
            bT = np.zeros((FP, C), bin_dtype)
            bT[:F, :fill] = buf_b[:fill].T
            m = np.zeros(C, np.float32)
            m[:fill] = 1.0
            self.chunks.append({
                "bT": np.ascontiguousarray(bT),
                "y": buf_y[:C].copy(), "w": buf_w[:C].copy(), "m": m})
            self.chunk_real.append(fill)
            buf_y[:] = 0.0
            buf_w[:] = 0.0
            fill = 0

        for chunk in self._batches():
            X, y, w = self._norm_chunk(chunk)
            c = int(X.shape[0])
            if c == 0:
                continue
            binned = self._bin_chunk(X, binner)
            y = (np.zeros(c, np.float32) if y is None
                 else np.asarray(y, np.float32))
            w = (np.ones(c, np.float32) if w is None
                 else np.asarray(w, np.float32))
            off = 0
            while off < c:
                take = min(C - fill, c - off)
                buf_b[fill:fill + take] = binned[off:off + take]
                buf_y[fill:fill + take] = y[off:off + take]
                buf_w[fill:fill + take] = w[off:off + take]
                fill += take
                off += take
                if fill == C:
                    flush()
        flush()
        self.n_rows = int(sum(self.chunk_real))
        if self.n_rows == 0:
            raise ValueError("StreamedDataset source yielded no rows")
        self._prepared_for = key
        return self

    # -- host-side label access (1/F the data size; see docs/out-of-core.md)
    def labels(self) -> np.ndarray:
        return np.concatenate([ch["y"][:r] for ch, r in
                               zip(self.chunks, self.chunk_real)])

    def weights(self) -> np.ndarray:
        return np.concatenate([ch["w"][:r] for ch, r in
                               zip(self.chunks, self.chunk_real)])


# ---------------------------------------------------------------------------
# Per-chunk device programs — ONE compile each per (geometry, objective):
# mapper-dependent vectors (featp/catp/monop/nanp/catb) are ARGUMENTS, never
# closed-over constants, so the lru_cache can only ever key on static shape
# ---------------------------------------------------------------------------

class _StreamState(NamedTuple):
    """Streamed level-synchronous growth state: the shared bookkeeping fields
    of grower._init_split_state plus the depthwise driver scalars. Satisfies
    the state contract of _apply_level_splits/_finalize_tree."""

    mask_id: jnp.ndarray
    level: jnp.ndarray
    progress: jnp.ndarray
    hist: jnp.ndarray
    bgain: jnp.ndarray
    bfeat: jnp.ndarray
    bbin: jnp.ndarray
    bdl: jnp.ndarray
    bcl: jnp.ndarray
    depth: jnp.ndarray
    leaf_parent: jnp.ndarray
    leaf_is_right: jnp.ndarray
    split_feature: jnp.ndarray
    split_bin: jnp.ndarray
    split_gain: jnp.ndarray
    split_type: jnp.ndarray
    default_left: jnp.ndarray
    cat_bitset: jnp.ndarray
    left_child: jnp.ndarray
    right_child: jnp.ndarray
    internal_value: jnp.ndarray
    internal_count: jnp.ndarray
    num_splits: jnp.ndarray


class _Programs(NamedTuple):
    root_chunk: Callable
    route_chunk: Callable
    root_finish: Callable
    plan_level: Callable
    commit_level: Callable
    update_score: Callable
    finalize: Callable

    def cache_sizes(self) -> dict:
        """Compiled-executable counts per program (steady-state recompile
        guard in tests/test_oocore.py)."""
        return {name: getattr(fn, "_cache_size", lambda: -1)()
                for name, fn in zip(self._fields, self)}


@functools.lru_cache(maxsize=16)
def _stream_programs(gcfg: GrowerConfig, B: int, L: int, FP: int, bw: int,
                     C: int, obj_key: tuple) -> _Programs:
    obj = get_objective(obj_key[0], num_class=1, sigmoid=obj_key[1],
                        alpha=obj_key[2], fair_c=obj_key[3],
                        poisson_max_delta_step=obj_key[4],
                        tweedie_variance_power=obj_key[5])
    l1 = jnp.float32(gcfg.lambda_l1)
    l2 = jnp.float32(gcfg.lambda_l2)
    wire = gcfg.hist_allreduce_dtype

    def _gh(score, y, w, m):
        # padding rows carry w=0 but some objectives floor the hessian
        # (binary: max(h*w, 1e-16)) — the explicit mask multiply keeps them
        # at exactly zero, matching the resident growers' grad*in_bag
        g, h = obj.grad_hess(score, y, w)
        return g * m, h * m

    @jax.jit
    def root_chunk(bT, y, w, m, score):
        g, h = _gh(score, y, w, m)
        node = jnp.zeros(C, jnp.int32)
        return _hist_level_xla(bT.astype(jnp.int32), g, h, m, node, B, L)

    @jax.jit
    def route_chunk(bT, y, w, m, score, node, plan, nanp):
        bT32 = bT.astype(jnp.int32)
        node2 = _route_level(bT32, node, plan, nanp, gcfg, bw)
        g, h = _gh(score, y, w, m)
        hist = _hist_level_xla(bT32, g, h, m, node2, B, L)
        return node2, hist

    @jax.jit
    def root_finish(hist, featp, catp, monop, nanp, catb):
        exists0 = jnp.arange(L) == 0
        hist = jnp.where(exists0[:, None, None, None], hist, 0.0)
        hist = _maybe_psum(hist, None, wire)
        rg, rf, rb, rdl, rcl, _ = _best_for_leaf(
            hist[0], featp, catp, monop, nanp, gcfg, l1, l2, catb)
        base = _init_split_state(L, B, bw, hist[0], rg, rf, rb, rdl, rcl, FP)
        return _StreamState(
            mask_id=jnp.full(L, 2 * (L - 1), jnp.int32),
            level=jnp.int32(0), progress=jnp.bool_(True), **base)

    @jax.jit
    def plan_level(s, catp, catb):
        do, order = _level_candidates(s, gcfg, L)
        s2, plan = _apply_level_splits(s, do, order, catp, catb, gcfg, B, bw,
                                       L)
        return s2, plan, do.any()

    @jax.jit
    def commit_level(s, hist, do_any, featp, catp, monop, nanp, catb):
        exists2 = jnp.arange(L) <= s.num_splits
        hist = jnp.where(exists2[:, None, None, None], hist, 0.0)
        hist = _maybe_psum(hist, None, wire)
        bg, bf, bb, bdl_, bcl, _ = jax.vmap(
            lambda hl: _best_for_leaf(hl, featp, catp, monop, nanp, gcfg,
                                      l1, l2, catb))(hist)
        return s._replace(
            hist=hist, bgain=jnp.where(exists2, bg, -jnp.inf),
            bfeat=bf, bbin=bb, bdl=bdl_, bcl=bcl,
            level=s.level + 1, progress=do_any)

    @jax.jit
    def update_score(score, node, leaf_value, m):
        return score + leaf_value[node] * m

    finalize = jax.jit(lambda s: _finalize_tree(s, gcfg, L))
    return _Programs(root_chunk, route_chunk, root_finish, plan_level,
                     commit_level, update_score, finalize)


# ---------------------------------------------------------------------------
# Streamed training
# ---------------------------------------------------------------------------

def _check_supported(cfg: BoosterConfig) -> None:
    bad = []
    if cfg.boosting_type != "gbdt":
        bad.append(f"boosting_type={cfg.boosting_type!r}")
    if cfg.objective in ("multiclass", "softmax", "multiclassova",
                         "lambdarank") or cfg.num_class > 1:
        bad.append(f"objective={cfg.objective!r}/num_class={cfg.num_class}")
    if (cfg.bagging_fraction < 1.0 or cfg.bagging_freq > 0
            or cfg.pos_bagging_fraction < 1.0
            or cfg.neg_bagging_fraction < 1.0):
        bad.append("bagging")
    if cfg.feature_fraction < 1.0 or cfg.feature_fraction_bynode < 1.0:
        bad.append("feature sampling")
    if cfg.early_stopping_round > 0:
        bad.append("early stopping (needs a validation stream)")
    if bad:
        raise NotImplementedError(
            "out-of-core streamed training does not support: "
            + ", ".join(bad) + " (use the resident train_booster path)")
    if cfg.growth_policy == "leafwise":
        warnings.warn(
            "out-of-core streamed training grows depthwise "
            "(level-synchronous); growth_policy='leafwise' is the resident "
            "default but is not streamable yet — training depthwise instead",
            UserWarning, stacklevel=3)


def _tree_to_host(tree) -> "tuple":
    return type(tree)(*(np.asarray(jax.device_get(a)) for a in tree))


def _stream_fingerprint(cfg: BoosterConfig, data: StreamedDataset) -> str:
    """Resume identity: config + chunk geometry + label digest. The chunk
    geometry is part of the identity because per-chunk partial sums make the
    accumulation order — and therefore the grown trees — a function of C."""
    import hashlib
    import zlib

    h = hashlib.sha256()
    h.update(repr(sorted(dataclasses.asdict(cfg).items())).encode())
    h.update(repr((int(data.n_rows), int(data.num_features),
                   int(data.chunk_rows),
                   zlib.crc32(np.ascontiguousarray(
                       data.labels()).tobytes()))).encode())
    return h.hexdigest()


def train_booster_streamed(
    data: StreamedDataset,
    config: BoosterConfig,
    *,
    resident: bool = False,
    measures=None,
    checkpoint_store=None,
    checkpoint_every: int = 0,
    resume: bool = True,
    feature_names: Optional[List[str]] = None,
) -> Booster:
    """Grow ``config.num_iterations`` trees over an out-of-core dataset.

    Each tree makes ``levels + 2`` passes over the quantized chunk stream
    (one root-histogram pass, one route+histogram pass per grown level, one
    leaf-value score update pass); every pass is a fresh
    :class:`~synapseml_tpu.io.ingest.ChunkPump` with globally monotonic
    boundary steps, so a preemption lands at a unique chunk boundary and
    resume (tree-boundary snapshots through ``checkpoint_store``) replays to
    a bit-identical model.

    ``resident=True`` pre-stages every chunk on device and drives the SAME
    jitted programs without the pump — the bitwise baseline the parity tests
    compare against, and the honest denominator for the streaming-overhead
    bench (identical math, zero transfer).
    """
    from ..core.logging import InstrumentationMeasures

    if measures is None:
        measures = InstrumentationMeasures()
    cfg = config
    _check_supported(cfg)
    with measures.span("streamIngest"):
        data.prepare(cfg)
    mapper = data.mapper
    F = data.num_features
    C = int(data.chunk_rows)
    FP = features_padded(F)
    B = pad_bins(cfg.max_bin)
    L = cfg.num_leaves
    bw = (B + BITS - 1) // BITS
    has_cat = bool(np.asarray(mapper.is_categorical).any())
    gcfg = cfg.grower(has_categorical=has_cat)
    max_levels = gcfg.max_depth if gcfg.max_depth > 0 else L - 1

    # per-feature device constants (arguments to every program — see the
    # _stream_programs cache-keying note)
    featp = jnp.zeros(FP, bool).at[:F].set(True)
    catp = jnp.zeros(FP, bool).at[:F].set(jnp.asarray(mapper.is_categorical))
    mono = np.zeros(F, np.int32)
    if cfg.monotone_constraints is not None:
        mc = np.asarray(cfg.monotone_constraints, np.int32)
        mono[:len(mc)] = mc
    monop = jnp.zeros(FP, jnp.int32).at[:F].set(jnp.asarray(mono))
    nanp = jnp.full(FP, 0x7FFF, jnp.int32).at[:F].set(
        jnp.asarray(np.asarray(mapper.nan_bins, np.int32)))
    _cc = (np.asarray(mapper.cat_counts, np.int32)
           if getattr(mapper, "cat_counts", None) is not None
           else np.asarray(mapper.num_bins, np.int32) - 1)
    catb = jnp.full(FP, B, jnp.int32).at[:F].set(jnp.asarray(
        np.where(np.asarray(mapper.is_categorical), _cc, np.int32(0x7FFF))))

    obj_key = (cfg.objective, cfg.sigmoid, cfg.alpha, cfg.fair_c,
               cfg.poisson_max_delta_step, cfg.tweedie_variance_power)
    progs = _stream_programs(gcfg, B, L, FP, bw, C, obj_key)

    obj = get_objective(cfg.objective, num_class=1, sigmoid=cfg.sigmoid,
                        alpha=cfg.alpha, fair_c=cfg.fair_c,
                        poisson_max_delta_step=cfg.poisson_max_delta_step,
                        tweedie_variance_power=cfg.tweedie_variance_power)
    if cfg.boost_from_average:
        ys, ws = data.labels(), data.weights()
        base = np.atleast_1d(np.asarray(
            obj.init_score(jnp.asarray(ys), jnp.asarray(ws)), np.float64))
    else:
        base = np.zeros(1)

    nchunks = len(data.chunks)
    # per-chunk mutable state. Streamed: host arrays re-placed per pass
    # (the whole point — only depth+1 chunks of device state exist at once).
    # Resident: everything device-pinned once; same programs, same values.
    scores = [np.full(C, np.float32(base[0]), np.float32)
              for _ in range(nchunks)]
    nodes = [np.zeros(C, np.int32) for _ in range(nchunks)]
    dev_static = None
    if resident:
        dev_static = [tuple(jax.device_put(ch[k])
                            for k in ("bT", "y", "w", "m"))
                      for ch in data.chunks]
        scores = [jax.device_put(s) for s in scores]
        nodes = [jax.device_put(nd) for nd in nodes]

    # --- crash-safe snapshots at tree boundaries (PR 2 CheckpointStore) ---
    ckpt_store = checkpoint_store
    if isinstance(ckpt_store, str):
        from ..core.checkpoint import CheckpointStore

        ckpt_store = CheckpointStore(ckpt_store)
    if ckpt_store is not None and checkpoint_every <= 0:
        checkpoint_every = 1
    fingerprint = (None if ckpt_store is None
                   else _stream_fingerprint(cfg, data))
    ckpt_path = "train_booster_streamed"

    trees: List = []
    start_iter = 0
    if ckpt_store is not None and resume:
        saved = _ckpt_load_gbdt(ckpt_store, fingerprint, ckpt_path)
        if saved is not None:
            start_iter = int(saved["iteration"])
            from .grower import TreeArrays

            trees = [TreeArrays(*[np.asarray(a) for a in t])
                     for t in saved["trees"]]
            flat = np.asarray(saved["score"], np.float32)
            off = 0
            for i, r in enumerate(data.chunk_real):
                sc = np.full(C, np.float32(base[0]), np.float32)
                sc[:r] = flat[off:off + r]
                off += r
                scores[i] = jax.device_put(sc) if resident else sc

    step_base = 0       # globally monotonic chunk-boundary step counter

    def passes():
        """One pass over the chunk stream: yields (idx, device chunk state).
        Streamed mode pumps host chunks through a producer thread (place =
        device_put, so transfer k+1 overlaps compute on k); resident mode
        walks the pre-staged device list."""
        nonlocal step_base
        if resident:
            for i in range(nchunks):
                yield i, dev_static[i] + (scores[i], nodes[i])
            return

        def src():
            for i, ch in enumerate(data.chunks):
                yield (i, ch["bT"], ch["y"], ch["w"], ch["m"],
                       scores[i], nodes[i])

        def place(item):
            return (item[0],) + tuple(jax.device_put(a) for a in item[1:])

        pump = ChunkPump(src(), place=place, depth=data.depth, threaded=True,
                         phase=STREAM_PHASE, step_base=step_base,
                         name="gbdt")
        try:
            for item in pump:
                yield item[0], item[1:]
        finally:
            step_base += max(pump.chunks_consumed, pump.chunks_produced)

    with measures.span("trainingIteration"):
        for t in range(start_iter, cfg.num_iterations):
            # ---- root histogram pass --------------------------------------
            hist = None
            for i, (bT, y, w, m, sc, nd) in passes():
                hc = progs.root_chunk(bT, y, w, m, sc)
                hist = hc if hist is None else hist + hc
                nodes[i] = (jnp.zeros(C, jnp.int32) if resident
                            else np.zeros(C, np.int32))
            s = progs.root_finish(hist, featp, catp, monop, nanp, catb)

            # ---- level-synchronous growth ---------------------------------
            progress, num_splits, level = True, 0, 0
            while progress and num_splits < L - 1 and level < max_levels:
                s, plan, do_any = progs.plan_level(s, catp, catb)
                hist = None
                for i, (bT, y, w, m, sc, nd) in passes():
                    node2, hc = progs.route_chunk(bT, y, w, m, sc, nd, plan,
                                                  nanp)
                    nodes[i] = node2 if resident else np.asarray(node2)
                    hist = hc if hist is None else hist + hc
                s = progs.commit_level(s, hist, do_any, featp, catp, monop,
                                       nanp, catb)
                progress = bool(s.progress)
                num_splits = int(s.num_splits)
                level = int(s.level)

            tree = _tree_to_host(progs.finalize(s))
            trees.append(tree)

            # ---- streamed score update ------------------------------------
            lv = jnp.asarray(tree.leaf_value)
            for i, (bT, y, w, m, sc, nd) in passes():
                sc2 = progs.update_score(sc, nd, lv, m)
                scores[i] = sc2 if resident else np.asarray(sc2)

            if (ckpt_store is not None
                    and (t + 1) % max(checkpoint_every, 1) == 0):
                flat = np.concatenate(
                    [np.asarray(scores[i])[:r]
                     for i, r in enumerate(data.chunk_real)])
                _ckpt_save_gbdt(
                    ckpt_store, t + 1,
                    {"iteration": t + 1,
                     "trees": [tuple(np.asarray(a) for a in tr)
                               for tr in trees],
                     "score": flat},
                    fingerprint, ckpt_path, measures)

    booster = Booster(
        mapper, cfg, trees, [1.0] * len(trees), base,
        feature_names=feature_names,
        metadata={"streamed": {
            "chunk_rows": C, "num_chunks": nchunks,
            "rows": int(data.n_rows), "resident": bool(resident),
            "sketch_exact": data.sketch_exact,
            "chunk_boundaries_visited": int(step_base),
            **({"sketch_second_pass": data.second_pass_decision}
               if data.second_pass_decision else {}),
            **({"chunk_decision": data.chunk_decision}
               if getattr(data, "chunk_decision", None) else {}),
        }})
    return booster


def predict_streamed(booster: Booster, batches: Iterable,
                     chunk_rows: Optional[int] = None,
                     depth: Optional[int] = None, **predict_kwargs):
    """Out-of-core scoring: iterate raw ``X`` chunks (dense or scipy sparse)
    through the shared pump and yield one prediction array per chunk. The
    pump's synchronous lookahead dispatches the next chunk's quantize +
    transfer while the consumer holds the previous result — the dl
    ``_prefetch`` overlap shape applied to scoring."""
    def src():
        for chunk in batches:
            X = chunk[0] if isinstance(chunk, tuple) else chunk
            yield np.asarray(X.todense() if _is_sparse(X) else X, np.float32)

    pump = ChunkPump(src(), place=None, depth=stream_depth(depth),
                     threaded=False, name="gbdt-predict")
    for X in pump:
        yield np.asarray(booster.predict(X, **predict_kwargs))
