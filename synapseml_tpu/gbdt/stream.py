"""Out-of-core GBDT: train on datasets far larger than device memory by
re-streaming host-cached QUANTIZED chunks through the shared ingestion layer.

The resident growers (grower.py / grower_depthwise.py) require the whole
binned matrix on device; past single-chip HBM the dataset size — not FLOPs —
is the wall (ROADMAP item 2). GPU tree-boosting work (arXiv:1706.08359)
showed that streaming a COMPRESSED feature matrix chunk-wise with per-chunk
histogram accumulation recovers near-resident throughput far beyond memory;
this module is that data plane:

* :class:`StreamedDataset` — ingests raw row chunks ONCE (dense or scipy
  sparse), learns bin boundaries with a one-pass
  :class:`~synapseml_tpu.ops.quantize.StreamingQuantileSketch` (bit-identical
  to the resident boundaries while the stream fits the sample buffer), and
  caches the quantized rows host-side as uniform feature-major uint8 chunks
  — 4x smaller than the raw floats, the compressed stream the device pulls.
  ``cache_dir=`` spills the quantized chunks to disk (.npy, re-read through
  :func:`~synapseml_tpu.io.ingest.read_chunk_file`'s mmap path) so even the
  QUANTIZED stream need not fit host RAM; pair with a
  :class:`~synapseml_tpu.io.ingest.DiskChunkSource` for a fully disk-backed
  pipeline.

* :func:`train_booster_streamed` — streamed tree growth, leafwise (the
  resident default: one best-gain split per pass) or level-synchronous
  depthwise. Per growth step, every chunk makes one device trip: a single
  jitted program routes the chunk's rows against the applied
  :class:`~synapseml_tpu.gbdt.grower_depthwise._LevelPlan` and scatter-adds
  the frontier histogram (ops/hist_kernel._hist_level_xla); chunk partials
  sum on device and flow through the SAME ``hist_allreduce_dtype`` ladder /
  split search / bookkeeping as the resident growers (the helpers are
  shared, not copied). With a ``mesh``, every per-chunk array is sharded
  over :data:`~synapseml_tpu.parallel.mesh.DATA_AXIS` and the per-step
  frontier partials cross the fabric ONCE per growth step through
  ``grower._maybe_psum`` — the {f32, bf16, int8} wire ladder with the
  exact-totals side wire, priced by ``grower.resolve_wire_dtype`` exactly
  like resident runs. Per-iteration bagging / GOSS / feature sampling use
  the SAME fold_in RNG streams as the resident path, generated from each
  chunk's global row offsets, so kill→resume stays bit-for-bit. A held-out
  stream (``valid_data=``) is scored incrementally per tree for
  validation-driven early stopping. Chunks move through a threaded
  :class:`~synapseml_tpu.io.ingest.ChunkPump` (transfer of chunk k+1
  overlaps compute on chunk k), and every chunk boundary is a preemption
  point + watchdog heartbeat (phase ``"gbdt.stream.chunk"``), so PR 2
  checkpoints and PR 10 elastic watchdogs compose with streaming for free.

* :func:`predict_streamed` — out-of-core scoring: raw chunks in, per-chunk
  predictions out, through the same pump.

Parity contract (tests/test_oocore.py): ``resident=True`` runs the IDENTICAL
jitted programs over pre-staged device-resident chunks — the pump, the
double-buffering, and the preemption machinery are bitwise-transparent, so
streamed == resident-mode trees bit for bit. Versus the classic resident
``train_booster`` the accumulation GEOMETRY differs (per-chunk partial sums
vs one whole-matrix scatter), so cross-path parity is a quality bound (AUC
within 1e-3 on the breast-cancer fixture), while boundary parity is exact
whenever the sketch never overflowed. See docs/out-of-core.md.

Remaining scope limits (raise loud, never silently degrade): gbdt/goss
boosting only (no dart/rf), binary/regression-family objectives
(num_class == 1), no ranking validation metrics, single-controller meshes
(``jax.process_count() == 1``).
"""

from __future__ import annotations

import collections
import dataclasses
import os
import time as _time
from typing import Callable, Iterable, List, NamedTuple, Optional, Sequence

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..io.ingest import (ChunkPump, read_chunk_file, stream_chunk_rows,
                         stream_depth)
from ..ops.hist_kernel import _hist_level_xla, features_padded, pad_bins
from ..ops.quantize import (BinMapper, CsrBinner, StreamingQuantileSketch,
                            apply_bins)
from .boosting import (Booster, BoosterConfig, _ckpt_load_gbdt,
                       _ckpt_save_gbdt, _default_metric, _eval_metric,
                       _is_rank_metric, _node_key_data, _sample_features_impl,
                       _train_metadata, _tree_assign_binned)
from .grower import (BITS, GrowerConfig, _best_for_leaf, _finalize_tree,
                     _init_split_state, _maybe_psum, _node_mask_fn,
                     _select_split_leaf)
from .grower_depthwise import (_apply_level_splits, _level_candidates,
                               _route_level)
from .objectives import HIGHER_IS_BETTER, get_objective

STREAM_PHASE = "gbdt.stream.chunk"


def _is_sparse(x) -> bool:
    return hasattr(x, "tocoo")


class StreamedDataset:
    """Out-of-core training data: a re-iterable chunk source plus the
    host-cached quantized form ``train_booster_streamed`` streams from.

    ``batches`` is a CALLABLE returning an iterator of chunks — each chunk a
    dense ``(c, F)`` array or scipy sparse matrix, optionally tupled with
    per-chunk labels/weights: ``X``, ``(X, y)`` or ``(X, y, w)``. The
    callable is invoked once per ingest pass (twice total when boundaries
    must be sketched: sketch pass, then bin+cache pass), so generators must
    be wrapped in a function, not passed pre-consumed. A
    :class:`~synapseml_tpu.io.ingest.DiskChunkSource` qualifies and
    additionally contributes its measured disk bandwidth to the chunk
    geometry choice.

    ``prepare(config)`` resolves the chunk geometry (io/ingest.py:
    explicit > env > tuned file > bandwidth micro-probe, capped by the
    ``SYNAPSEML_TPU_STREAM_MEM_BUDGET`` device budget), learns boundaries
    (sketch — or adopts ``mapper``), and re-chunks the stream into uniform
    ``(FP, C)`` feature-major quantized host chunks (the last chunk padded
    with zero-mass rows so every device program compiles ONCE). Sparse
    chunks are quantized on device through
    :class:`~synapseml_tpu.ops.quantize.CsrBinner` — implicit zeros never
    densify at dataset scale.

    ``cache_dir`` spills the quantized chunks to ``.npy`` files instead of
    keeping them in host RAM; training re-reads them per pass through the
    mmap reader (``io.ingest.read_chunk_file``). Labels/weights/masks stay
    resident (1/F the data size — see docs/out-of-core.md).
    """

    def __init__(self, batches: Callable[[], Iterable],
                 num_features: Optional[int] = None,
                 mapper: Optional[BinMapper] = None,
                 categorical_features: Optional[Sequence[int]] = None,
                 chunk_rows: Optional[int] = None,
                 depth: Optional[int] = None,
                 exact_second_pass: Optional[bool] = None,
                 cache_dir: Optional[str] = None):
        if not callable(batches):
            raise TypeError(
                "StreamedDataset needs a CALLABLE returning an iterator of "
                "chunks (a consumed iterator cannot support the multiple "
                "ingest passes); wrap it: StreamedDataset(lambda: chunks)")
        self._batches = batches
        self.num_features = num_features
        self.mapper = mapper
        self._user_mapper = mapper is not None
        self.categorical_features = (list(categorical_features)
                                     if categorical_features else None)
        self._chunk_rows_arg = chunk_rows
        self._depth_arg = depth
        # exact second sketch pass when the one-pass sketch overflowed its
        # sample budget (ROADMAP 2d): None = let core/perfmodel price it,
        # True/False forces — the explicit bypass
        self._exact_second_pass = exact_second_pass
        self.second_pass_decision: Optional[dict] = None
        self._cache_dir = cache_dir
        self._rows_sketched = 0
        self.chunk_rows: Optional[int] = None     # C, after prepare()
        self.depth: Optional[int] = None
        self.chunks: List[dict] = []              # bT (FP, C), y/w/m (C,)
        self.chunk_real: List[int] = []           # real (unpadded) rows
        self.n_rows = 0
        self.sketch_exact: Optional[bool] = None  # None = mapper was given
        self._prepared_for = None

    @classmethod
    def from_arrays(cls, X, y=None, w=None, source_chunk: int = 65536,
                    **kwargs) -> "StreamedDataset":
        """Wrap in-memory arrays (dense or scipy sparse rows) as a chunk
        source — the fits-in-memory path of the parity tests and benches."""
        n = X.shape[0]
        f = X.shape[1]

        def batches():
            for i in range(0, n, source_chunk):
                sl = slice(i, min(i + source_chunk, n))
                yield (X[sl],
                       None if y is None else y[sl],
                       None if w is None else w[sl])

        return cls(batches, num_features=f, **kwargs)

    # -- ingest ------------------------------------------------------------
    def _norm_chunk(self, chunk):
        """(X, y, w) from any accepted chunk shape."""
        if isinstance(chunk, tuple):
            X = chunk[0]
            y = chunk[1] if len(chunk) > 1 else None
            w = chunk[2] if len(chunk) > 2 else None
        else:
            X, y, w = chunk, None, None
        if self.num_features is None:
            self.num_features = int(X.shape[1])
        elif int(X.shape[1]) != self.num_features:
            raise ValueError(f"chunk has {X.shape[1]} features, dataset has "
                             f"{self.num_features}")
        return X, y, w

    def _sketch_pass(self, cfg: BoosterConfig) -> None:
        seed = (cfg.seed if cfg.data_random_seed is None
                else int(cfg.data_random_seed))
        sketch = None
        for chunk in self._batches():
            X, _, _ = self._norm_chunk(chunk)
            if sketch is None:
                sketch = StreamingQuantileSketch(
                    self.num_features, cfg.max_bin, cfg.bin_sample_count,
                    self.categorical_features, seed=seed,
                    min_data_in_bin=cfg.min_data_in_bin,
                    max_bin_by_feature=cfg.max_bin_by_feature)
            if _is_sparse(X):
                coo = X.tocoo()
                sketch.update_csr(coo.data, coo.row, coo.col, X.shape[0])
            else:
                sketch.update(np.asarray(X, np.float32))
        if sketch is None or sketch.rows_seen == 0:
            raise ValueError("StreamedDataset source yielded no rows")
        self.sketch_exact = sketch.exact
        self._rows_sketched = int(sketch.rows_seen)
        self.mapper = sketch.finalize()

    def _maybe_exact_second_pass(self, cfg: BoosterConfig,
                                 pass_s: float) -> None:
        """ROADMAP 2d: the one-pass sketch overflowed its sample budget, so
        boundaries are reservoir-sampled. A second full pass with the budget
        raised to the stream length makes them exact — worth it only when
        that pass is cheap next to training. core/perfmodel prices the pass
        (measured sketch rate from THIS stream as the analytic prior) against
        the estimated training cost: num_iterations x tree levels re-streams
        of the same data. ``exact_second_pass=True/False`` bypasses."""
        from ..core import perfmodel

        rows, nfeat = self._rows_sketched, self.num_features
        if self._exact_second_pass is not None:
            take = bool(self._exact_second_pass)
            self.second_pass_decision = {"kind": "gbdt_sketch_pass",
                                         "arm": "exact" if take else "skip",
                                         "source": "explicit"}
        else:
            levels = max(1, int(np.ceil(np.log2(max(cfg.num_leaves, 2)))))
            train_est = pass_s * max(cfg.num_iterations, 1) * levels
            rate = rows / pass_s if pass_s > 0 else None
            take, dec = perfmodel.suggest_sketch_second_pass(
                float(rows), float(nfeat), rate, train_est)
            # an exact sketch buffers the full stream host-side — never
            # trade boundaries for an OOM
            if take and rows * nfeat * 4 > (2 << 30):
                take = False
                dec.arm, dec.used_fallback = "skip", True
                dec.source = "host_budget"
            self.second_pass_decision = dec.audit(observed_s=None)
        if not take:
            return
        t0 = _time.perf_counter()
        self._sketch_pass(dataclasses.replace(
            cfg, bin_sample_count=max(rows, cfg.bin_sample_count)))
        if isinstance(self.second_pass_decision, dict) and \
                self.second_pass_decision.get("source") != "explicit":
            self.second_pass_decision["observed_s"] = round(
                _time.perf_counter() - t0, 6)

    def _bin_chunk(self, X, binner: Optional[CsrBinner]) -> np.ndarray:
        """(c, F) quantized host rows for one raw chunk."""
        if _is_sparse(X):
            coo = X.tocoo()
            return np.asarray(binner(coo.data, coo.row, coo.col, X.shape[0]))
        return np.asarray(apply_bins(self.mapper, np.asarray(X, np.float32)))

    def prepare(self, config: BoosterConfig,
                row_multiple: int = 1) -> "StreamedDataset":
        """Idempotent per binning config: sketch (unless a mapper was given),
        resolve chunk geometry, quantize + cache the stream.

        ``row_multiple`` rounds the chunk row count up to a multiple (mesh
        training shards each chunk over the data axis, so C must divide by
        the worker count); a dataset already prepared under the same binning
        re-chunks — without re-sketching — when the multiple changes."""
        mult = max(int(row_multiple), 1)
        key = (config.max_bin, config.bin_sample_count,
               config.min_data_in_bin,
               tuple(config.max_bin_by_feature or ()),
               config.seed if config.data_random_seed is None
               else int(config.data_random_seed))
        if (self._prepared_for == key and self.chunk_rows
                and self.chunk_rows % mult == 0):
            return self
        if (self._prepared_for is not None and self._prepared_for != key
                and self._user_mapper is False):
            # re-preparing under different binning would silently retrain on
            # different boundaries — make the caller rebuild the dataset
            raise ValueError(
                f"StreamedDataset already prepared for binning {self._prepared_for}; "
                f"got {key} — build a fresh StreamedDataset")
        if self.mapper is None:
            t0 = _time.perf_counter()
            self._sketch_pass(config)
            pass_s = _time.perf_counter() - t0
            if self.sketch_exact is False:
                self._maybe_exact_second_pass(config, pass_s)
        if self.mapper.max_bin != config.max_bin:
            raise ValueError(
                f"mapper has max_bin={self.mapper.max_bin} but config asks "
                f"{config.max_bin}")

        F = self.num_features
        FP = features_padded(F)
        # one streamed row's device footprint: quantized bins (feature-major
        # uint8/16) + y/w/m/score f32 + node i32
        unit = 1 if self.mapper.max_bin <= 256 else 2
        row_bytes = FP * unit + 20
        self.depth = stream_depth(self._depth_arg)
        read_bps = None
        try:
            read_bps = self._batches.read_bytes_per_s
        except Exception:
            read_bps = None
        C = stream_chunk_rows(row_bytes, explicit=self._chunk_rows_arg,
                              depth=self.depth, read_bps=read_bps)
        if C % mult:
            C += mult - C % mult
        self.chunk_rows = C
        # perfmodel provenance when the probe branch picked the geometry
        # (None under the explicit/env/tuned bypass)
        from ..io import ingest as _ingest

        self.chunk_decision = _ingest.last_chunk_decision()
        bin_dtype = np.uint8 if unit == 1 else np.uint16
        if self._cache_dir is not None:
            os.makedirs(self._cache_dir, exist_ok=True)

        self.chunks, self.chunk_real, self.n_rows = [], [], 0
        binner = CsrBinner(self.mapper)
        buf_b = np.zeros((C, F), bin_dtype)
        buf_y = np.zeros(C, np.float32)
        buf_w = np.zeros(C, np.float32)
        fill = 0

        def flush():
            nonlocal fill, C
            if fill == 0:
                return
            if not self.chunks and fill < C:
                # the whole stream fit one partial chunk: shrink the chunk
                # to the real row count instead of padding (a probe-derived
                # C far above n_rows would otherwise make every device
                # program chew mostly zero-mass padding) — still a multiple
                # of the mesh worker count
                C = max(-(-fill // mult) * mult, mult)
                self.chunk_rows = C
            bT = np.zeros((FP, C), bin_dtype)
            bT[:F, :fill] = buf_b[:fill].T
            m = np.zeros(C, np.float32)
            m[:fill] = 1.0
            entry = {"y": buf_y[:C].copy(), "w": buf_w[:C].copy(), "m": m}
            bT = np.ascontiguousarray(bT)
            if self._cache_dir is not None:
                path = os.path.join(self._cache_dir,
                                    f"chunk{len(self.chunks):05d}.npy")
                np.save(path, bT)
                entry["bT_path"] = path
            else:
                entry["bT"] = bT
            self.chunks.append(entry)
            self.chunk_real.append(fill)
            buf_y[:] = 0.0
            buf_w[:] = 0.0
            fill = 0

        for chunk in self._batches():
            X, y, w = self._norm_chunk(chunk)
            c = int(X.shape[0])
            if c == 0:
                continue
            binned = self._bin_chunk(X, binner)
            y = (np.zeros(c, np.float32) if y is None
                 else np.asarray(y, np.float32))
            w = (np.ones(c, np.float32) if w is None
                 else np.asarray(w, np.float32))
            off = 0
            while off < c:
                take = min(C - fill, c - off)
                buf_b[fill:fill + take] = binned[off:off + take]
                buf_y[fill:fill + take] = y[off:off + take]
                buf_w[fill:fill + take] = w[off:off + take]
                fill += take
                off += take
                if fill == C:
                    flush()
        flush()
        self.n_rows = int(sum(self.chunk_real))
        if self.n_rows == 0:
            raise ValueError("StreamedDataset source yielded no rows")
        self._prepared_for = key
        return self

    def chunk_bT(self, i: int) -> np.ndarray:
        """Quantized (FP, C) bins of chunk ``i`` — RAM-resident, or re-read
        from the ``cache_dir`` spill through the mmap reader (so the chaos
        disk-fault hook and a real dying disk both surface here, loudly)."""
        ch = self.chunks[i]
        bT = ch.get("bT")
        if bT is not None:
            return bT
        arr = read_chunk_file(ch["bT_path"], i)
        want = (features_padded(self.num_features), int(self.chunk_rows))
        if tuple(arr.shape) != want:
            raise OSError(
                f"torn read of spilled chunk {ch['bT_path']!r}: got shape "
                f"{tuple(arr.shape)}, want {want}")
        return arr

    # -- host-side label access (1/F the data size; see docs/out-of-core.md)
    def labels(self) -> np.ndarray:
        return np.concatenate([ch["y"][:r] for ch, r in
                               zip(self.chunks, self.chunk_real)])

    def weights(self) -> np.ndarray:
        return np.concatenate([ch["w"][:r] for ch, r in
                               zip(self.chunks, self.chunk_real)])


# ---------------------------------------------------------------------------
# Per-chunk device programs — ONE compile each per (geometry, objective,
# mesh): mapper-dependent vectors (featp/catp/monop/nanp/catb), sample
# weights, and RNG keys are ARGUMENTS, never closed-over constants, so the
# lru_cache can only ever key on static shape
# ---------------------------------------------------------------------------

class _StreamState(NamedTuple):
    """Streamed growth state: the shared bookkeeping fields of
    grower._init_split_state plus the driver scalars. Satisfies the state
    contract of _apply_level_splits/_finalize_tree."""

    mask_id: jnp.ndarray
    level: jnp.ndarray
    progress: jnp.ndarray
    hist: jnp.ndarray
    bgain: jnp.ndarray
    bfeat: jnp.ndarray
    bbin: jnp.ndarray
    bdl: jnp.ndarray
    bcl: jnp.ndarray
    depth: jnp.ndarray
    leaf_parent: jnp.ndarray
    leaf_is_right: jnp.ndarray
    split_feature: jnp.ndarray
    split_bin: jnp.ndarray
    split_gain: jnp.ndarray
    split_type: jnp.ndarray
    default_left: jnp.ndarray
    cat_bitset: jnp.ndarray
    left_child: jnp.ndarray
    right_child: jnp.ndarray
    internal_value: jnp.ndarray
    internal_count: jnp.ndarray
    num_splits: jnp.ndarray


class _Programs(NamedTuple):
    root_chunk: Callable
    route_chunk: Callable
    child_chunk: Callable
    root_finish: Callable
    plan_level: Callable
    commit_level: Callable
    plan_leaf: Callable
    commit_leaf: Callable
    update_score: Callable
    finalize: Callable
    # mesh-only cross-shard reductions (None single-chip — _maybe_psum with
    # axis None is the identity, so the bookkeeping programs are shared)
    reduce_level: Optional[Callable] = None
    reduce_child: Optional[Callable] = None

    def cache_sizes(self) -> dict:
        """Compiled-executable counts per program (steady-state recompile
        guard in tests/test_oocore.py)."""
        return {name: getattr(fn, "_cache_size", lambda: -1)()
                for name, fn in zip(self._fields, self) if fn is not None}


@functools.lru_cache(maxsize=16)
def _stream_programs(gcfg: GrowerConfig, B: int, L: int, FP: int, bw: int,
                     C: int, obj_key: tuple, mesh=None) -> _Programs:
    obj = get_objective(obj_key[0], num_class=1, sigmoid=obj_key[1],
                        alpha=obj_key[2], fair_c=obj_key[3],
                        poisson_max_delta_step=obj_key[4],
                        tweedie_variance_power=obj_key[5])
    l1 = jnp.float32(gcfg.lambda_l1)
    l2 = jnp.float32(gcfg.lambda_l2)
    wire = gcfg.hist_allreduce_dtype

    def _gh(score, y, w, m):
        # padding rows carry w=0 but some objectives floor the hessian
        # (binary: max(h*w, 1e-16)) — the explicit mask multiply keeps them
        # at exactly zero, matching the resident growers' grad*in_bag
        g, h = obj.grad_hess(score, y, w)
        return g * m, h * m

    # ---- per-chunk local bodies (row dim from the ARGUMENT shape, so the
    # same body traces over full chunks single-chip and C/W-row shards
    # under shard_map). ``sw`` is the per-row sample weight: ones when
    # bagging/GOSS are off (multiplying by exactly 1.0 is bitwise-neutral),
    # {0,1} bagging masks, {0,amp,1} GOSS amplification — grad/hess scale by
    # it and the histogram mask drops sw==0 rows, mirroring the resident
    # samplers' (g*wmask, in_bag) contract.
    def _root_local(bT, y, w, m, score, sw):
        g, h = _gh(score, y, w, m)
        g, h = g * sw, h * sw
        m2 = m * (sw > 0)
        node = jnp.zeros(y.shape[0], jnp.int32)
        return _hist_level_xla(bT.astype(jnp.int32), g, h, m2, node, B, L)

    def _route_local(bT, y, w, m, score, node, plan, nanp, sw):
        bT32 = bT.astype(jnp.int32)
        node2 = _route_level(bT32, node, plan, nanp, gcfg, bw)
        g, h = _gh(score, y, w, m)
        g, h = g * sw, h * sw
        m2 = m * (sw > 0)
        return node2, _hist_level_xla(bT32, g, h, m2, node2, B, L)

    def _child_local(bT, y, w, m, score, node, plan, nanp, sw, new_right):
        # leafwise: route, then histogram ONLY the fresh right child — a
        # (1, FP, B, 3) partial, 1/L the depthwise wire bytes; the left
        # child comes from parent-minus-right on the committed state
        bT32 = bT.astype(jnp.int32)
        node2 = _route_level(bT32, node, plan, nanp, gcfg, bw)
        g, h = _gh(score, y, w, m)
        rsel = (node2 == new_right).astype(jnp.float32)
        g, h = g * sw * rsel, h * sw * rsel
        m2 = m * (sw > 0) * rsel
        hist = _hist_level_xla(bT32, g, h, m2,
                               jnp.zeros(y.shape[0], jnp.int32), B, 1)
        return node2, hist

    def _update_local(score, node, leaf_value, m):
        return score + leaf_value[node] * m

    reduce_level = reduce_child = None
    if mesh is None:
        root_chunk = jax.jit(_root_local)
        route_chunk = jax.jit(_route_local)
        child_chunk = jax.jit(_child_local)
        update_score = jax.jit(_update_local)
    else:
        from jax.sharding import PartitionSpec as P

        from ..parallel.collectives import shard_apply
        from ..parallel.mesh import DATA_AXIS as _DA

        _pv, _pr, _pm = P(_DA), P(), P(None, _DA)
        # chunk programs keep their histogram partial SHARD-LOCAL (out_specs
        # stack the (1, ...) local partials to (W, ...)); the host
        # accumulates shard-locally across chunks and ONE reduce program per
        # growth step crosses the fabric — chunks/step psums collapse to 1
        root_chunk = jax.jit(shard_apply(
            mesh, lambda *a: _root_local(*a)[None],
            in_specs=(_pm, _pv, _pv, _pv, _pv, _pv), out_specs=_pv))
        route_chunk = jax.jit(shard_apply(
            mesh,
            lambda *a: (lambda nd, hh: (nd, hh[None]))(*_route_local(*a)),
            in_specs=(_pm, _pv, _pv, _pv, _pv, _pv, _pr, _pr, _pv),
            out_specs=(_pv, _pv)))
        child_chunk = jax.jit(shard_apply(
            mesh, _child_local,
            in_specs=(_pm, _pv, _pv, _pv, _pv, _pv, _pr, _pr, _pv, _pr),
            out_specs=(_pv, _pv)))
        update_score = jax.jit(shard_apply(
            mesh, _update_local,
            in_specs=(_pv, _pv, _pr, _pv), out_specs=_pv))

        def _reduce_level_local(hw, ns):
            h = hw[0]
            # mask non-existent leaves BEFORE the wire: the exists predicate
            # is shard-UNIFORM (num_splits is replicated), so every shard
            # zeroes the same slots and the psum'd garbage never rides the
            # quantized rungs (grower_depthwise level_pass invariant)
            exists = jnp.arange(L) <= ns
            h = jnp.where(exists[:, None, None, None], h, 0.0)
            return _maybe_psum(h, _DA, wire)

        reduce_level = jax.jit(shard_apply(
            mesh, _reduce_level_local, in_specs=(_pv, _pr), out_specs=_pr))
        reduce_child = jax.jit(shard_apply(
            mesh, lambda hw: _maybe_psum(hw[0], _DA, wire)[None],
            in_specs=(_pv,), out_specs=_pr))

    # ---- bookkeeping programs (shared single-chip/mesh: their internal
    # _maybe_psum(axis=None) is the identity; mesh reductions happened in
    # reduce_level/reduce_child, so re-masking here is idempotent) --------
    @jax.jit
    def root_finish(hist, featp, catp, monop, nanp, catb, node_key):
        exists0 = jnp.arange(L) == 0
        hist = jnp.where(exists0[:, None, None, None], hist, 0.0)
        hist = _maybe_psum(hist, None, wire)
        nmask = _node_mask_fn(gcfg, featp, 0, node_key)
        rg, rf, rb, rdl, rcl, _ = _best_for_leaf(
            hist[0], nmask(jnp.int32(2 * (L - 1))), catp, monop, nanp, gcfg,
            l1, l2, catb)
        base = _init_split_state(L, B, bw, hist[0], rg, rf, rb, rdl, rcl, FP)
        return _StreamState(
            mask_id=jnp.full(L, 2 * (L - 1), jnp.int32),
            level=jnp.int32(0), progress=jnp.bool_(True), **base)

    @jax.jit
    def plan_level(s, catp, catb):
        do, order = _level_candidates(s, gcfg, L)
        s2, plan = _apply_level_splits(s, do, order, catp, catb, gcfg, B, bw,
                                       L)
        return s2, plan, do.any()

    @jax.jit
    def commit_level(s, hist, do_any, featp, catp, monop, nanp, catb,
                     node_key):
        exists2 = jnp.arange(L) <= s.num_splits
        hist = jnp.where(exists2[:, None, None, None], hist, 0.0)
        hist = _maybe_psum(hist, None, wire)
        nmask = _node_mask_fn(gcfg, featp, 0, node_key)
        masks = jax.vmap(nmask)(s.mask_id)
        bg, bf, bb, bdl_, bcl, _ = jax.vmap(
            lambda hl, fm: _best_for_leaf(hl, fm, catp, monop, nanp, gcfg,
                                          l1, l2, catb))(hist, masks)
        return s._replace(
            hist=hist, bgain=jnp.where(exists2, bg, -jnp.inf),
            bfeat=bf, bbin=bb, bdl=bdl_, bcl=bcl,
            level=s.level + 1, progress=do_any)

    @jax.jit
    def plan_leaf(s, catp, catb):
        # leafwise growth step: apply the single best-gain split (the
        # resident default policy) as a one-hot level plan — the SAME
        # bookkeeping (_apply_level_splits) the depthwise path uses
        l, do = _select_split_leaf(s, gcfg, L)
        do_vec = (jnp.arange(L) == l) & do
        order = jnp.arange(L, dtype=jnp.int32)
        s2, plan = _apply_level_splits(s, do_vec, order, catp, catb, gcfg, B,
                                       bw, L)
        return s2, plan, do, l

    @jax.jit
    def commit_leaf(s, child, l, featp, catp, monop, nanp, catb, node_key):
        nr = s.num_splits               # right-child leaf slot (post-apply)
        hist = _maybe_psum(child, None, wire)
        hist_r = hist[0]
        hist_l = s.hist[l] - hist_r     # parent-minus-right, exact in f32
        nmask = _node_mask_fn(gcfg, featp, 0, node_key)
        gl, fl, bl, dll, cll, _ = _best_for_leaf(
            hist_l, nmask(s.mask_id[l]), catp, monop, nanp, gcfg, l1, l2,
            catb)
        gr, fr, br, dlr, clr, _ = _best_for_leaf(
            hist_r, nmask(s.mask_id[nr]), catp, monop, nanp, gcfg, l1, l2,
            catb)
        return s._replace(
            hist=s.hist.at[l].set(hist_l).at[nr].set(hist_r),
            bgain=s.bgain.at[l].set(gl).at[nr].set(gr),
            bfeat=s.bfeat.at[l].set(fl).at[nr].set(fr),
            bbin=s.bbin.at[l].set(bl).at[nr].set(br),
            bdl=s.bdl.at[l].set(dll).at[nr].set(dlr),
            bcl=s.bcl.at[l].set(cll).at[nr].set(clr),
            level=s.level + 1, progress=jnp.bool_(True))

    finalize = jax.jit(lambda s: _finalize_tree(s, gcfg, L))
    return _Programs(root_chunk, route_chunk, child_chunk, root_finish,
                     plan_level, commit_level, plan_leaf, commit_leaf,
                     update_score, finalize, reduce_level, reduce_child)


# ---------------------------------------------------------------------------
# Streamed training
# ---------------------------------------------------------------------------

def _check_supported(cfg: BoosterConfig, has_valid: bool = False) -> None:
    bad = []
    if cfg.boosting_type not in ("gbdt", "goss"):
        bad.append(f"boosting_type={cfg.boosting_type!r}")
    if cfg.objective in ("multiclass", "softmax", "multiclassova",
                         "lambdarank") or cfg.num_class > 1:
        bad.append(f"objective={cfg.objective!r}/num_class={cfg.num_class}")
    if cfg.early_stopping_round > 0 and not has_valid:
        bad.append("early stopping without a held-out stream "
                   "(pass valid_data=)")
    if has_valid and _is_rank_metric(cfg.metric
                                     or _default_metric(cfg.objective)):
        bad.append("ranking validation metrics")
    if bad:
        raise NotImplementedError(
            "out-of-core streamed training does not support: "
            + ", ".join(bad) + " (use the resident train_booster path)")


def _stream_sample_weights(cfg: BoosterConfig, n: int, key0, it: int,
                           gnorm, in_bag_cur, yj):
    """Per-iteration (n,) sample-weight vector — the weight-vector
    formulation of boosting._sample_rows_impl, drawing from the SAME fold_in
    RNG streams so a streamed run samples the rows a resident run would.
    Returns ``(sw, in_bag)``: ``sw`` is None when sampling is off this
    iteration's config, else the f32 per-row weights ({0,1} bagging,
    {0, amp, 1} GOSS); ``in_bag`` is the bagging mask carried across
    iterations (refreshed every ``bagging_freq`` rounds — checkpointed so
    kill→resume replays identically)."""
    goss_mode = cfg.boosting_type == "goss"
    stratified = (cfg.pos_bagging_fraction < 1.0
                  or cfg.neg_bagging_fraction < 1.0)
    do_bag = (cfg.bagging_freq > 0
              and (cfg.bagging_fraction < 1.0 or stratified))
    key0 = jax.random.PRNGKey(cfg.seed) if key0 is None else key0
    if goss_mode:
        top_n = int(cfg.top_rate * n)
        rand_n = int(cfg.other_rate * n)
        amp = (1.0 - cfg.top_rate) / max(cfg.other_rate, 1e-12)
        order = jnp.argsort(-gnorm)
        ranks = jnp.zeros(n, jnp.int32).at[order].set(
            jnp.arange(n, dtype=jnp.int32))
        kg = (jax.random.fold_in(key0, cfg.extra_seed) if cfg.extra_seed
              else key0)   # default 0 keeps the established stream
        u = jax.random.uniform(jax.random.fold_in(kg, it), (n,))
        rest = ranks >= top_n
        pick = rest & (u < (rand_n / max(n - top_n, 1)))
        sw = jnp.where(ranks < top_n, 1.0, jnp.where(pick, amp, 0.0))
        return sw.astype(jnp.float32), in_bag_cur
    if do_bag:
        kb = (jax.random.fold_in(key0, cfg.bagging_seed)
              if cfg.bagging_seed != 3 else key0)  # default keeps the stream
        u = jax.random.uniform(
            jax.random.fold_in(kb, 20_000_000 + it), (n,))
        if stratified and yj is not None:
            frac = jnp.where(yj > 0, cfg.pos_bagging_fraction,
                             cfg.neg_bagging_fraction)
        else:
            frac = cfg.bagging_fraction
        fresh = (u < frac).astype(jnp.float32)
        bag = fresh if it % max(cfg.bagging_freq, 1) == 0 else in_bag_cur
        return bag, bag
    return None, in_bag_cur


def _tree_to_host(tree) -> "tuple":
    return type(tree)(*(np.asarray(jax.device_get(a)) for a in tree))


def _stream_fingerprint(cfg: BoosterConfig, data: StreamedDataset,
                        mesh=None) -> str:
    """Resume identity: config + chunk geometry + mesh shape + label digest.
    The chunk geometry is part of the identity because per-chunk partial
    sums make the accumulation order — and therefore the grown trees — a
    function of C; the mesh axes likewise fix the shard-local accumulation
    and wire-reduction order."""
    import hashlib
    import zlib

    mesh_axes = (None if mesh is None
                 else tuple(sorted(dict(mesh.shape).items())))
    h = hashlib.sha256()
    h.update(repr(sorted(dataclasses.asdict(cfg).items())).encode())
    h.update(repr((int(data.n_rows), int(data.num_features),
                   int(data.chunk_rows), mesh_axes,
                   zlib.crc32(np.ascontiguousarray(
                       data.labels()).tobytes()))).encode())
    return h.hexdigest()


def train_booster_streamed(
    data: StreamedDataset,
    config: BoosterConfig,
    *,
    resident: bool = False,
    mesh=None,
    valid_data=None,
    measures=None,
    checkpoint_store=None,
    checkpoint_every: int = 0,
    resume: bool = True,
    feature_names: Optional[List[str]] = None,
) -> Booster:
    """Grow ``config.num_iterations`` trees over an out-of-core dataset.

    Leafwise growth makes ``2 + num_splits`` passes over the quantized chunk
    stream per tree (root histogram, one right-child histogram per split,
    leaf-value score update); depthwise makes ``levels + 2``. Every pass is
    a fresh :class:`~synapseml_tpu.io.ingest.ChunkPump` with globally
    monotonic boundary steps, so a preemption lands at a unique chunk
    boundary and resume (tree-boundary snapshots through
    ``checkpoint_store``) replays to a bit-identical model — bagging/GOSS
    masks are re-derived from the per-iteration fold_in streams and the
    checkpointed scores/in-bag state, never from mutable RNG.

    ``mesh`` shards every per-chunk array over
    :data:`~synapseml_tpu.parallel.mesh.DATA_AXIS` (single-controller; C is
    rounded to a worker multiple by ``prepare``): chunk histograms stay
    shard-local and ONE reduction per growth step crosses the fabric through
    the ``hist_allreduce_dtype`` wire ladder.

    ``valid_data`` (a ``(Xv, yv[, wv])`` tuple or a prepared
    :class:`StreamedDataset` sharing this dataset's mapper) is scored
    incrementally per tree — one leaf-assignment pass over the held-out
    chunks — and drives LightGBM-style best-iteration tracking / early
    stopping identically to the resident path.

    ``resident=True`` pre-stages every chunk on device and drives the SAME
    jitted programs without the pump — the bitwise baseline the parity tests
    compare against, and the honest denominator for the streaming-overhead
    bench (identical math, zero transfer).
    """
    from ..core.logging import InstrumentationMeasures

    if measures is None:
        measures = InstrumentationMeasures()
    cfg = config
    has_valid = valid_data is not None
    _check_supported(cfg, has_valid)

    W = 1
    if mesh is not None:
        if jax.process_count() > 1:
            raise NotImplementedError(
                "mesh-streamed GBDT is single-controller: "
                "jax.process_count() must be 1 (multi-process stage groups "
                "route through the resident train_booster path)")
        from ..parallel.mesh import DATA_AXIS as _DA_NAME
        W = int(dict(mesh.shape).get(_DA_NAME, 1))

    _fit_t0 = _time.perf_counter()
    autoconfig_info = dict(getattr(cfg, "_autoconfig", None) or {})

    with measures.span("streamIngest"):
        data.prepare(cfg, row_multiple=W)
    mapper = data.mapper
    F = data.num_features
    C = int(data.chunk_rows)
    FP = features_padded(F)
    B = pad_bins(cfg.max_bin)
    L = cfg.num_leaves
    bw = (B + BITS - 1) // BITS
    n = int(data.n_rows)

    # auto-configuration: the wire rung and the tree-learner route resolve
    # through the same perf-model surfaces as resident runs (ISSUE 15 —
    # streamed runs are priced, not special-cased)
    if cfg.hist_allreduce_dtype == "auto":
        from .grower import resolve_wire_dtype

        wd, wdec = resolve_wire_dtype(cfg, mesh, n, F)
        cfg.hist_allreduce_dtype = wd
        autoconfig_info["wire_dtype"] = wdec.provenance()
    routing_info = None
    if cfg.tree_learner == "auto":
        choice = "data" if W > 1 else "serial"
        cfg.tree_learner = choice
        routing_info = {"tree_learner": choice,
                        "router": "streamed_data_plane", "workers": W}
    elif mesh is not None and cfg.tree_learner in ("voting", "feature"):
        raise NotImplementedError(
            f"mesh-streamed GBDT shards over the data axis only "
            f"(tree_learner='data'); got {cfg.tree_learner!r}")

    has_cat = bool(np.asarray(mapper.is_categorical).any())
    gcfg = cfg.grower(has_categorical=has_cat)
    leafwise = cfg.growth_policy == "leafwise"
    max_levels = gcfg.max_depth if gcfg.max_depth > 0 else L - 1

    # per-feature device constants (arguments to every program — see the
    # _stream_programs cache-keying note)
    featp = jnp.zeros(FP, bool).at[:F].set(True)
    catp = jnp.zeros(FP, bool).at[:F].set(jnp.asarray(mapper.is_categorical))
    mono = np.zeros(F, np.int32)
    if cfg.monotone_constraints is not None:
        mc = np.asarray(cfg.monotone_constraints, np.int32)
        mono[:len(mc)] = mc
    monop = jnp.zeros(FP, jnp.int32).at[:F].set(jnp.asarray(mono))
    nanp = jnp.full(FP, 0x7FFF, jnp.int32).at[:F].set(
        jnp.asarray(np.asarray(mapper.nan_bins, np.int32)))
    _cc = (np.asarray(mapper.cat_counts, np.int32)
           if getattr(mapper, "cat_counts", None) is not None
           else np.asarray(mapper.num_bins, np.int32) - 1)
    catb = jnp.full(FP, B, jnp.int32).at[:F].set(jnp.asarray(
        np.where(np.asarray(mapper.is_categorical), _cc, np.int32(0x7FFF))))

    obj_key = (cfg.objective, cfg.sigmoid, cfg.alpha, cfg.fair_c,
               cfg.poisson_max_delta_step, cfg.tweedie_variance_power)
    progs = _stream_programs(gcfg, B, L, FP, bw, C, obj_key, mesh)

    obj = get_objective(cfg.objective, num_class=1, sigmoid=cfg.sigmoid,
                        alpha=cfg.alpha, fair_c=cfg.fair_c,
                        poisson_max_delta_step=cfg.poisson_max_delta_step,
                        tweedie_variance_power=cfg.tweedie_variance_power)
    ys_host, ws_host = data.labels(), data.weights()
    if cfg.boost_from_average:
        base = np.atleast_1d(np.asarray(
            obj.init_score(jnp.asarray(ys_host), jnp.asarray(ws_host)),
            np.float64))
    else:
        base = np.zeros(1)

    # ---- placement: mesh shards the row dim over DATA_AXIS ---------------
    if mesh is not None:
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import DATA_AXIS as _DA_NAME

        _sh_mat = NamedSharding(mesh, P(None, _DA_NAME))
        _sh_vec = NamedSharding(mesh, P(_DA_NAME))

        def _put_mat(a):
            return jax.device_put(a, _sh_mat)

        def _put_vec(a):
            return jax.device_put(a, _sh_vec)

        def _put_chunk(tail):
            # ONE batched device_put for the whole chunk tuple ((mat,
            # vec...); None slots pass through as empty pytree nodes,
            # already-placed shared constants are returned as-is) — per-call
            # dispatch overhead is the dominant streaming cost on small
            # chunks, so one call per chunk instead of seven
            shs = tuple(None if a is None else (_sh_mat if k == 0
                                                else _sh_vec)
                        for k, a in enumerate(tail))
            return jax.device_put(tail, shs)
    else:
        _put_mat = _put_vec = jax.device_put

        def _put_chunk(tail):
            return jax.device_put(tail)

    # ---- per-iteration sampling state ------------------------------------
    goss_mode = cfg.boosting_type == "goss"
    stratified = (cfg.pos_bagging_fraction < 1.0
                  or cfg.neg_bagging_fraction < 1.0)
    do_bag = (cfg.bagging_freq > 0
              and (cfg.bagging_fraction < 1.0 or stratified))
    sampling = goss_mode or do_bag
    do_feat = cfg.feature_fraction < 1.0
    key0 = jax.random.PRNGKey(cfg.seed)
    in_bag_vec = np.ones(n, np.float32)
    offs = np.concatenate([[0], np.cumsum(data.chunk_real)]).astype(np.int64)
    yj_dev = jnp.asarray(ys_host) if (do_bag and stratified) else None
    if goss_mode:
        y_flat_dev = jnp.asarray(ys_host)
        w_flat_dev = jnp.asarray(ws_host)

    nchunks = len(data.chunks)
    # per-chunk mutable state. Streamed: host arrays re-placed per pass
    # (the whole point — only depth+1 chunks of device state exist at once).
    # Resident: everything device-pinned once; same programs, same values.
    scores = [np.full(C, np.float32(base[0]), np.float32)
              for _ in range(nchunks)]
    ones_sw_host = np.ones(C, np.float32)
    dev_static = None
    # shared device constants for BOTH modes: the all-rows-at-root node
    # vector and the inactive sample-weight vector are identical for every
    # chunk, so place them once — re-placing an already-committed array is
    # a no-op, which removes two of the per-chunk puts from streamed passes
    zero_nodes_dev = _put_vec(np.zeros(C, np.int32))
    ones_sw_dev = _put_vec(ones_sw_host)
    nodes = [zero_nodes_dev] * nchunks
    if resident:
        dev_static = [(_put_mat(data.chunk_bT(i)),
                       _put_vec(data.chunks[i]["y"]),
                       _put_vec(data.chunks[i]["w"]),
                       _put_vec(data.chunks[i]["m"]))
                      for i in range(nchunks)]
        scores = [_put_vec(s) for s in scores]
    sw_ones = [ones_sw_dev] * nchunks

    # ---- held-out validation stream --------------------------------------
    if has_valid:
        if isinstance(valid_data, StreamedDataset):
            vd = valid_data
        else:
            Xv = valid_data[0]
            yv_in = valid_data[1]
            wv_in = valid_data[2] if len(valid_data) > 2 else None
            vd = StreamedDataset.from_arrays(Xv, yv_in, wv_in)
        if vd.mapper is None:
            # the held-out stream scores against the TRAINING boundaries
            vd.mapper = mapper
            vd._user_mapper = True
        vd.prepare(cfg)
        if vd.num_features != F:
            raise ValueError(
                f"valid_data has {vd.num_features} features, train has {F}")
        yv_host = vd.labels()
        wv_all = vd.weights()
        wv_eval = (None if np.all(wv_all == 1.0)
                   else jnp.asarray(wv_all, jnp.float32))
        nv = int(vd.n_rows)
        score_v = np.full(nv, np.float32(base[0]), np.float32)
        metric_name = cfg.metric or _default_metric(cfg.objective)
        higher_better = metric_name.split("@")[0] in HIGHER_IS_BETTER
        nanv = jnp.asarray(np.asarray(mapper.nan_bins, np.int32))
        best_metric, best_iter = None, -1
        stopped_early = False

    # --- crash-safe snapshots at tree boundaries (PR 2 CheckpointStore) ---
    ckpt_store = checkpoint_store
    if isinstance(ckpt_store, str):
        from ..core.checkpoint import CheckpointStore

        ckpt_store = CheckpointStore(ckpt_store)
    if ckpt_store is not None and checkpoint_every <= 0:
        checkpoint_every = 1
    fingerprint = (None if ckpt_store is None
                   else _stream_fingerprint(cfg, data, mesh))
    ckpt_path = "train_booster_streamed"

    trees: List = []
    start_iter = 0
    if ckpt_store is not None and resume:
        saved = _ckpt_load_gbdt(ckpt_store, fingerprint, ckpt_path)
        if saved is not None:
            start_iter = int(saved["iteration"])
            from .grower import TreeArrays

            trees = [TreeArrays(*[np.asarray(a) for a in t])
                     for t in saved["trees"]]
            flat = np.asarray(saved["score"], np.float32)
            off = 0
            for i, r in enumerate(data.chunk_real):
                sc = np.full(C, np.float32(base[0]), np.float32)
                sc[:r] = flat[off:off + r]
                off += r
                scores[i] = _put_vec(sc) if resident else sc
            bag_saved = saved.get("in_bag")
            if bag_saved is not None:
                in_bag_vec = np.asarray(bag_saved, np.float32)
            if has_valid and saved.get("score_v") is not None:
                score_v = np.asarray(saved["score_v"], np.float32)
                bm = saved.get("best_metric")
                best_metric = (None if bm is None
                               or not np.isfinite(np.float64(bm))
                               else float(bm))
                best_iter = int(saved.get("best_iter", -1))

    step_base = 0       # globally monotonic chunk-boundary step counter

    def passes(sw_list, need_data=True, need_nodes=True):
        """One pass over the chunk stream: yields (idx, device chunk state).
        Streamed mode pumps host chunks through a producer thread (place =
        one batched device_put per chunk, so transfer k+1 overlaps compute
        on k; disk-spilled chunks re-read through the mmap reader inside
        the producer); resident mode walks the pre-staged device list.
        ``need_data=False`` is the score-update pass: ``update_score``
        consumes only (score, node, mask), so the feature matrix is
        neither re-read from its source (a full extra disk pass for
        spilled/disk-backed chunks) nor placed. ``need_nodes=False`` is
        the root pass, which ignores the node vector. Neither flag changes
        the chunk-boundary step count."""
        nonlocal step_base
        if resident:
            for i in range(nchunks):
                yield i, dev_static[i] + (scores[i], nodes[i], sw_list[i])
            return

        def src():
            for i in range(nchunks):
                ch = data.chunks[i]
                if need_data:
                    yield (i, data.chunk_bT(i), ch["y"], ch["w"], ch["m"],
                           scores[i], nodes[i] if need_nodes else None,
                           sw_list[i])
                else:
                    yield (i, None, None, None, ch["m"],
                           scores[i], nodes[i], sw_list[i])

        def place(item):
            return (item[0],) + tuple(_put_chunk(tuple(item[1:])))

        # a producer thread only buys overlap when there is a spare core to
        # run it on; on a single-core host the thread just steals GIL
        # slices from program dispatch, so fall back to the pump's
        # synchronous lookahead (identical chunk order and step counting)
        pump = ChunkPump(src(), place=place, depth=data.depth,
                         threaded=(os.cpu_count() or 2) > 1,
                         phase=STREAM_PHASE, step_base=step_base,
                         name="gbdt")
        try:
            for item in pump:
                yield item[0], item[1:]
        finally:
            step_base += max(pump.chunks_consumed, pump.chunks_produced)

    # Bounded-lag D2H: a pass's per-chunk (C,) result used to be pulled to
    # host synchronously (np.asarray), which blocked Python on the full
    # program+transfer latency of EVERY chunk — the resident path instead
    # dispatches all chunk programs asynchronously and syncs once per
    # growth step, which is exactly why it is faster. So park the device
    # array, start its host copy asynchronously, and materialize it lagged
    # behind the consumer. A parked result is C*4 bytes vs the chunk's
    # C*row_bytes H2D footprint, so capping parked chunks at
    # (depth+1)*row_bytes/4 keeps D2H staging inside the SAME byte
    # envelope the in-flight budget already grants the H2D side — and lets
    # typical passes park everything, collapsing per-chunk host waits into
    # one pass-end sync. Values are untouched, so streamed stays
    # bit-for-bit with resident mode, and the pump producer only ever
    # reads slots AHEAD of the consumer (previous-pass values), so the
    # lagged write can never race a read.
    d2h_lag = max(int(data.depth), (int(data.depth) + 1) * (FP + 20) // 4)

    def _park(pending, out_list, i, dev_arr):
        copy_async = getattr(dev_arr, "copy_to_host_async", None)
        if copy_async is not None:
            copy_async()
        pending.append((i, dev_arr))
        while len(pending) > d2h_lag:
            j, a = pending.popleft()
            out_list[j] = np.asarray(a)

    def _flush(pending, out_list):
        while pending:
            j, a = pending.popleft()
            out_list[j] = np.asarray(a)

    def _tree_sample_weights(t):
        """Per-chunk (C,) sample-weight slices for iteration ``t``, cut from
        the full (n,) vector by each chunk's global row offsets (padding
        rows get sw=0 — already zero-mass through m)."""
        nonlocal in_bag_vec
        gnorm = None
        if goss_mode:
            flat = np.concatenate([np.asarray(scores[i])[:r]
                                   for i, r in enumerate(data.chunk_real)])
            g, _ = obj.grad_hess(jnp.asarray(flat), y_flat_dev, w_flat_dev)
            gnorm = jnp.abs(g)
        sw_vec, bag = _stream_sample_weights(
            cfg, n, key0, t, gnorm, jnp.asarray(in_bag_vec), yj_dev)
        in_bag_vec = np.asarray(bag, np.float32)
        if sw_vec is None:
            return sw_ones
        sw_np = np.asarray(sw_vec, np.float32)
        out = []
        for i, r in enumerate(data.chunk_real):
            v = np.zeros(C, np.float32)
            v[:r] = sw_np[offs[i]:offs[i] + r]
            out.append(_put_vec(v) if resident else v)
        return out

    with measures.span("trainingIteration"):
        for t in range(start_iter, cfg.num_iterations):
            sw_list = _tree_sample_weights(t) if sampling else sw_ones
            if do_feat:
                featm = _sample_features_impl(cfg, F, key0, t)
                featp_t = featp & jnp.zeros(FP, bool).at[:F].set(featm)
            else:
                featp_t = featp
            nk = _node_key_data(key0, t, 0)

            # ---- root histogram pass --------------------------------------
            hist = None
            for i, (bT, y, w, m, sc, nd, sw) in passes(sw_list,
                                                       need_nodes=False):
                hc = progs.root_chunk(bT, y, w, m, sc, sw)
                hist = hc if hist is None else hist + hc
                nodes[i] = zero_nodes_dev
            if progs.reduce_level is not None:
                hist = progs.reduce_level(hist, jnp.int32(0))
            s = progs.root_finish(hist, featp_t, catp, monop, nanp, catb, nk)

            if leafwise:
                # ---- leafwise growth: one split (one stream pass) each ----
                splits = 0
                while splits < L - 1:
                    s, plan, do, l = progs.plan_leaf(s, catp, catb)
                    if not bool(do):
                        break
                    nr = s.num_splits
                    child = None
                    pend = collections.deque()
                    for i, (bT, y, w, m, sc, nd, sw) in passes(sw_list):
                        node2, hc = progs.child_chunk(bT, y, w, m, sc, nd,
                                                      plan, nanp, sw, nr)
                        if resident:
                            nodes[i] = node2
                        else:
                            _park(pend, nodes, i, node2)
                        child = hc if child is None else child + hc
                    _flush(pend, nodes)
                    if progs.reduce_child is not None:
                        child = progs.reduce_child(child)
                    s = progs.commit_leaf(s, child, l, featp_t, catp, monop,
                                          nanp, catb, nk)
                    splits = int(s.num_splits)
            else:
                # ---- level-synchronous depthwise growth -------------------
                progress, num_splits, level = True, 0, 0
                while progress and num_splits < L - 1 and level < max_levels:
                    s, plan, do_any = progs.plan_level(s, catp, catb)
                    hist = None
                    pend = collections.deque()
                    for i, (bT, y, w, m, sc, nd, sw) in passes(sw_list):
                        node2, hc = progs.route_chunk(bT, y, w, m, sc, nd,
                                                      plan, nanp, sw)
                        if resident:
                            nodes[i] = node2
                        else:
                            _park(pend, nodes, i, node2)
                        hist = hc if hist is None else hist + hc
                    _flush(pend, nodes)
                    if progs.reduce_level is not None:
                        hist = progs.reduce_level(hist, s.num_splits)
                    s = progs.commit_level(s, hist, do_any, featp_t, catp,
                                           monop, nanp, catb, nk)
                    progress = bool(s.progress)
                    num_splits = int(s.num_splits)
                    level = int(s.level)

            tree = _tree_to_host(progs.finalize(s))
            trees.append(tree)

            # ---- held-out stream: incremental scoring + early stop --------
            if has_valid:
                lv_np = np.asarray(tree.leaf_value)
                off = 0
                for i, r in enumerate(vd.chunk_real):
                    binned = jnp.asarray(np.ascontiguousarray(
                        vd.chunk_bT(i)[:F, :r].T).astype(np.int32))
                    leaf = np.asarray(_tree_assign_binned(tree, binned,
                                                          nanv))
                    score_v[off:off + r] += lv_np[leaf]
                    off += r
                raw_v = jnp.asarray(score_v, jnp.float32)[:, None]
                pred_v = obj.transform(raw_v[:, 0])
                mval = float(_eval_metric(metric_name, yv_host, pred_v,
                                          raw_v, (None, yv_host), 1, cfg,
                                          wv_eval))
                tol = cfg.improvement_tolerance
                improved = (best_metric is None
                            or (mval > best_metric + tol if higher_better
                                else mval < best_metric - tol))
                if improved:
                    best_metric, best_iter = mval, t
                if (cfg.early_stopping_round > 0
                        and t - best_iter >= cfg.early_stopping_round):
                    trees = trees[:best_iter + 1]
                    stopped_early = True
                    break

            # ---- streamed score update ------------------------------------
            lv = np.asarray(tree.leaf_value)
            pend = collections.deque()
            for i, (bT, y, w, m, sc, nd, sw) in passes(sw_list,
                                                       need_data=False):
                sc2 = progs.update_score(sc, nd, lv, m)
                if resident:
                    scores[i] = sc2
                else:
                    _park(pend, scores, i, sc2)
            _flush(pend, scores)

            if (ckpt_store is not None
                    and (t + 1) % max(checkpoint_every, 1) == 0):
                flat = np.concatenate(
                    [np.asarray(scores[i])[:r]
                     for i, r in enumerate(data.chunk_real)])
                payload = {
                    "iteration": t + 1,
                    "trees": [tuple(np.asarray(a) for a in tr)
                              for tr in trees],
                    "score": flat,
                    "in_bag": np.asarray(in_bag_vec, np.float32)}
                if has_valid:
                    payload["score_v"] = score_v.copy()
                    payload["best_metric"] = np.float64(
                        np.nan if best_metric is None else best_metric)
                    payload["best_iter"] = int(best_iter)
                _ckpt_save_gbdt(ckpt_store, t + 1, payload, fingerprint,
                                ckpt_path, measures)

    meta = _train_metadata(routing_info, autoconfig_info, _fit_t0) or {}
    meta["streamed"] = {
        "chunk_rows": C, "num_chunks": nchunks,
        "rows": int(data.n_rows), "resident": bool(resident),
        "sketch_exact": data.sketch_exact,
        "chunk_boundaries_visited": int(step_base),
        "growth_policy": cfg.growth_policy,
        "workers": W,
        **({"sketch_second_pass": data.second_pass_decision}
           if data.second_pass_decision else {}),
        **({"chunk_decision": data.chunk_decision}
           if getattr(data, "chunk_decision", None) else {}),
    }
    if has_valid:
        meta["streamed"]["stopped_early"] = bool(stopped_early)
    booster = Booster(
        mapper, cfg, trees, [1.0] * len(trees), base,
        feature_names=feature_names,
        best_iteration=(best_iter if has_valid else -1),
        best_score=(best_metric if has_valid else None),
        metadata=meta)
    return booster


def predict_streamed(booster: Booster, batches: Iterable,
                     chunk_rows: Optional[int] = None,
                     depth: Optional[int] = None, **predict_kwargs):
    """Out-of-core scoring: iterate raw ``X`` chunks (dense or scipy sparse)
    through the shared pump and yield one prediction array per chunk. The
    pump's synchronous lookahead dispatches the next chunk's quantize +
    transfer while the consumer holds the previous result — the dl
    ``_prefetch`` overlap shape applied to scoring."""
    def src():
        for chunk in batches:
            X = chunk[0] if isinstance(chunk, tuple) else chunk
            yield np.asarray(X.todense() if _is_sparse(X) else X, np.float32)

    pump = ChunkPump(src(), place=None, depth=stream_depth(depth),
                     threaded=False, name="gbdt-predict")
    for X in pump:
        yield np.asarray(booster.predict(X, **predict_kwargs))
