from .grower import Forest, GrowerConfig, TreeArrays, forest_predict, grow_tree, stack_trees  # noqa: F401
from .objectives import METRICS, Objective, get_objective, make_grouped, ndcg_at_k  # noqa: F401
from .boosting import Booster, BoosterConfig, train_booster  # noqa: F401
from .dataset import Dataset  # noqa: F401
from .stream import (StreamedDataset, predict_streamed,  # noqa: F401
                     train_booster_streamed)
