"""Boosting driver: the training-iteration loop and the Booster model.

The analog of the reference's TrainUtils.scala (booster creation :16-29, iteration
loop with early stopping + custom fobj :77-135, eval-metric extraction :137-151)
plus the serializable model of booster/LightGBMBooster.scala. The per-iteration
work (gradients → tree growth → score update) is jitted XLA; the loop itself is
host Python (one dispatch per tree), matching the reference's structure where the
JVM loop calls LGBM_BoosterUpdateOneIter per iteration.

Boosting modes (SURVEY §2.1 N1): gbdt, rf (bagged trees, averaged output), dart
(tree dropout with 1/(k+1) normalization), goss (top-|g| keep + amplified random
sample of the rest). GOSS/bagging/instance weights all funnel into the same
(grad, hess, in_bag) triple consumed by the grower.
"""

from __future__ import annotations

import dataclasses
import functools
import math
import os
import time as _time
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core import tuned as _tuned
from ..ops.quantize import BinMapper, apply_bins, bin_threshold_to_value, compute_bin_mapper

# default_factory marker for engine knobs resolved via core/tuned.py: lets
# __post_init__ distinguish "user passed nothing" from an explicit value
_TUNED_SENTINEL = "__tuned__"
from .dataset import Dataset, _is_sparse
from .grower import (Forest, GrowerConfig, TreeArrays, forest_max_depth,
                     forest_predict, grow_tree, stack_trees)
from .objectives import (METRICS, HIGHER_IS_BETTER, Objective, get_objective,
                         lambdarank_objective, make_grouped,
                         map_at_k, metric_kwargs, ndcg_at_k)
from ..parallel.elastic import current_watchdog


@dataclasses.dataclass
class BoosterConfig:
    """Training configuration — the native-param surface the reference renders
    through ParamsStringBuilder (LightGBMBase.scala:374-386). Field names follow
    LightGBM's canonical param names."""

    objective: str = "regression"
    boosting_type: str = "gbdt"          # gbdt | rf | dart | goss
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    max_bin: int = 255
    max_depth: int = -1
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    pos_bagging_fraction: float = 1.0
    neg_bagging_fraction: float = 1.0
    feature_fraction: float = 1.0
    feature_fraction_bynode: float = 1.0
    top_rate: float = 0.2                # goss
    other_rate: float = 0.1              # goss
    drop_rate: float = 0.1               # dart
    max_drop: int = 50
    skip_drop: float = 0.5
    uniform_drop: bool = False
    num_class: int = 1
    sigmoid: float = 1.0
    alpha: float = 0.9                   # huber / quantile
    fair_c: float = 1.0
    poisson_max_delta_step: float = 0.7
    tweedie_variance_power: float = 1.5
    max_delta_step: float = 0.0
    cat_smooth: float = 10.0
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4
    min_data_per_group: int = 100
    xgboost_dart_mode: bool = False
    monotone_constraints: Optional[Sequence[int]] = None
    early_stopping_round: int = 0
    metric: Optional[str] = None
    seed: int = 0
    boost_from_average: bool = True
    bin_sample_count: int = 200_000
    min_data_in_bin: int = 3              # merge under-filled bins (minDataPerBin)
    max_bin_by_feature: Optional[Sequence[int]] = None
    cat_l2: float = 10.0                  # categorical split L2 (catl2)
    # derived sampling seeds (LightGBM exposes independent seeds; 0 = derive
    # purely from `seed`)
    drop_seed: int = 0
    feature_fraction_seed: int = 0
    extra_seed: int = 0
    start_iteration: int = 0              # prediction start (predict window)
    # distributed tree learner: "auto" (default) routes per dataset through
    # the measured cost model in gbdt/voting.py at fit time (falls back to
    # "serial" off-mesh; the decision + model inputs land in
    # Booster.metadata["routing"]); "serial"/"data" aggregate all features'
    # histograms; "voting" selects top-2k features per tree by shard votes
    # (PV-Tree; LightGBM voting_parallel + topK — LightGBMParams.scala:25-27);
    # "feature" is the owned-feature reduce-scatter grower (each device keeps
    # 1/world of the reduced histogram and per-leaf winners are exchanged —
    # LightGBM data_parallel's actual wire pattern). Explicit values force.
    tree_learner: str = "auto"
    top_k: int = 20
    # row-partition primitive inside the grower ("sort" | "sort32" | "scan"
    # | "scatter"); see GrowerConfig.partition_impl. Default resolution
    # (core/tuned.py): SYNAPSEML_TPU_PARTITION_IMPL env > the on-chip
    # measured winner in docs/tuned_defaults.json (written by
    # tools/perf_tune.py, applied only under the TPU backend) > "sort".
    # Resolved in __post_init__ (validated there — a typo'd env var /
    # corrupt file fails fast); when the config is constructed BEFORE the
    # jax backend initializes, the tuned-file lookup is re-run once at
    # grower() time so all tuned knobs (incl. hist_kernel's chunk, which
    # resolves at trace time) apply consistently.
    partition_impl: str = dataclasses.field(
        default_factory=lambda: _TUNED_SENTINEL)
    # grower row layout ("partition" | "masked" | "gather");
    # see GrowerConfig.row_layout — same tuned-default resolution
    row_layout: str = dataclasses.field(
        default_factory=lambda: _TUNED_SENTINEL)
    # segmented histogram kernel: None = auto (TPU + on-device selftest);
    # True/False forces — the perf_tune A/B differential. The tuned file may
    # pin it when the A/B measured a real difference on chip.
    use_segmented: Optional[bool] = dataclasses.field(
        default_factory=lambda: _TUNED_SENTINEL)
    # growth policy: "leafwise" (LightGBM parity) | "depthwise"
    # (level-batched opt-in; see grower_depthwise.py)
    growth_policy: str = "leafwise"
    # histogram allreduce wire precision ladder ("f32" | "bf16" | "int8") —
    # grad/hess ride the wire at reduced width (counts stay exact), cutting
    # per-split collective bytes to 2/3 (bf16) or ~1/2 (int8 blockwise-
    # quantized allreduce, EQuARX-style incl. per-block scales) on
    # multi-host fabrics; see GrowerConfig.hist_allreduce_dtype. "auto"
    # resolves at fit time through core/perfmodel (grower.resolve_wire_dtype):
    # the learned model picks the ladder rung only on measured evidence for a
    # matching workload, else the conservative f32 wire; the decision lands
    # in Booster.metadata["autoconfig"]["wire_dtype"]
    hist_allreduce_dtype: str = "f32"
    # lambdarank
    lambdarank_truncation_level: int = 30
    max_position: int = 30
    # relevance gain per label value (LightGBMRankerParams labelGain; empty
    # = the default 2^label - 1 table)
    label_gain: tuple = ()
    # bagging stream seed (LightGBM bagging_seed, default 3)
    bagging_seed: int = 3
    # minimum metric improvement for early stopping (improvementTolerance)
    improvement_tolerance: float = 0.0
    # bin-boundary sampling seed override (LightGBM data_random_seed);
    # None = use `seed` (legacy behavior)
    data_random_seed: object = None
    # features' missing code becomes zero (zeroAsMissing): the estimator
    # layer maps 0 -> NaN before binning and traversal routes |x|<=1e-35
    # (and coerced NaN) to the default side
    zero_as_missing: bool = False
    # NDCG eval positions (LightGBMRankerParams evalAt, default 1-5 at the
    # estimator layer): when set, the FIRST position drives validation/early
    # stopping, matching the reference (maxPosition truncates the lambdarank
    # objective via lambdarank_truncation_level, not the eval metric). Empty
    # = legacy engine-level behavior: evaluate at max_position.
    eval_at: tuple = ()

    def __post_init__(self):
        self._resolve_tuned()
        # env/tuned-file-sourced fields are validated HERE, not at trace time
        # deep inside grow_tree: a typo'd SYNAPSEML_TPU_* value (or a corrupt
        # docs/tuned_defaults.json) must fail at construction with a message
        # naming its source (ADVICE r3)
        for field, env in (("partition_impl", "SYNAPSEML_TPU_PARTITION_IMPL"),
                           ("row_layout", "SYNAPSEML_TPU_ROW_LAYOUT")):
            v = getattr(self, field)
            allowed = _tuned.ALLOWED[field]
            if v not in allowed:
                raise ValueError(
                    f"BoosterConfig.{field}={v!r} is not one of {allowed} "
                    f"(check the {env} env var / docs/tuned_defaults.json)")
        if self.growth_policy not in ("leafwise", "depthwise"):
            raise ValueError(
                f"BoosterConfig.growth_policy={self.growth_policy!r} is not "
                "one of ('leafwise', 'depthwise')")
        if self.hist_allreduce_dtype not in ("auto", "f32", "bf16", "int8"):
            raise ValueError(
                f"BoosterConfig.hist_allreduce_dtype="
                f"{self.hist_allreduce_dtype!r} is not one of "
                "('auto', 'f32', 'bf16', 'int8')")
        if self.tree_learner not in ("auto", "serial", "data", "voting",
                                     "feature"):
            raise ValueError(
                f"BoosterConfig.tree_learner={self.tree_learner!r} is not "
                "one of ('auto', 'serial', 'data', 'voting', 'feature')")

    def _resolve_tuned(self):
        """Fill sentinel-defaulted engine knobs from env > tuned file >
        hardcoded. Explicitly passed values are never sentinels, so user
        intent is never overridden. When the jax backend is not initialized
        yet, the tuned-file gate is closed (core/tuned.py); the affected
        fields are remembered and re-resolved ONCE at grower() time — by
        then the training path has initialized the backend, so construction
        order can't produce a half-tuned config."""
        deferred = []
        closed = not _tuned.backend_is_tpu()
        untuned = []
        for field, env, fallback in (
                ("partition_impl", "SYNAPSEML_TPU_PARTITION_IMPL", "sort"),
                ("row_layout", "SYNAPSEML_TPU_ROW_LAYOUT", "partition"),
                ("use_segmented", None, None)):
            if getattr(self, field) is not _TUNED_SENTINEL:
                continue
            v = os.environ.get(env) if env else None
            if v:
                setattr(self, field, v)
                continue
            td = _tuned.tuned_engine_defaults()
            setattr(self, field, td.get(field, fallback))
            if field in ("partition_impl", "row_layout") and field not in td:
                untuned.append(field)
            if closed:
                deferred.append((field, fallback))
        self._deferred_tuned = deferred
        self._autoconfig = {}
        self._suggest_kernel_variant(untuned)

    def _suggest_kernel_variant(self, untuned):
        """Where neither env nor tuned file pinned the kernel variant, let
        the learned perf model suggest one from recorded kernel-sweep rows
        (same arms tools/perf_tune.py measures). Low confidence — e.g. no
        rows for this platform — keeps the hardcoded fallback, so behavior
        off-TPU is unchanged. The decision is auditable via
        Booster.metadata["autoconfig"]["kernel_variant"]."""
        if not untuned:
            return
        from ..core import perfmodel

        variant, dec = perfmodel.suggest_kernel_variant()
        self._autoconfig["kernel_variant"] = dec.provenance()
        if variant:
            for field in untuned:
                setattr(self, field, variant[field])

    def _finalize_tuned(self):
        """Re-resolve fields whose tuned-file lookup was skipped because the
        backend was uninitialized at construction (called from grower())."""
        if getattr(self, "_deferred_tuned", None) and _tuned.backend_is_tpu():
            td = _tuned.tuned_engine_defaults()
            untuned = []
            for field, fallback in self._deferred_tuned:
                setattr(self, field, td.get(field, fallback))
                if field in ("partition_impl", "row_layout") and \
                        field not in td:
                    untuned.append(field)
            self._deferred_tuned = []
            self._suggest_kernel_variant(untuned)

    def grower(self, has_categorical: bool = False,
               feature_shards: int = 1) -> GrowerConfig:
        self._finalize_tuned()
        lr = 1.0 if self.boosting_type == "rf" else self.learning_rate
        feature_mode = self.tree_learner == "feature" and feature_shards > 1
        return GrowerConfig(
            hist_reduce="scatter" if feature_mode else "allreduce",
            feature_shards=feature_shards if feature_mode else 1,
            has_categorical=has_categorical,
            num_leaves=self.num_leaves,
            num_bins=self.max_bin,
            max_depth=self.max_depth,
            lambda_l1=self.lambda_l1,
            lambda_l2=self.lambda_l2,
            min_data_in_leaf=self.min_data_in_leaf,
            min_sum_hessian_in_leaf=self.min_sum_hessian_in_leaf,
            min_gain_to_split=self.min_gain_to_split,
            feature_fraction_bynode=self.feature_fraction_bynode,
            learning_rate=lr,
            max_delta_step=self.max_delta_step,
            cat_smooth=self.cat_smooth,
            cat_l2=self.cat_l2,
            max_cat_threshold=self.max_cat_threshold,
            max_cat_to_onehot=self.max_cat_to_onehot,
            min_data_per_group=self.min_data_per_group,
            partition_impl=self.partition_impl,
            row_layout=self.row_layout,
            use_segmented=self.use_segmented,
            growth_policy=self.growth_policy,
            hist_allreduce_dtype=self.hist_allreduce_dtype,
        )


class Booster:
    """A trained forest + binning metadata; the LightGBMBooster analog
    (booster/LightGBMBooster.scala): scoring, leaf prediction, SHAP, model-string
    save/load, feature importances."""

    def __init__(self, mapper: BinMapper, config: BoosterConfig,
                 trees: List[TreeArrays], tree_weights: List[float],
                 base_score: np.ndarray, feature_names: Optional[List[str]] = None,
                 best_iteration: int = -1,
                 thresholds: Optional[List[np.ndarray]] = None,
                 missing_types: Optional[List[np.ndarray]] = None,
                 best_score: Optional[float] = None,
                 metadata: Optional[dict] = None):
        self.mapper = mapper
        # training provenance (e.g. the parallelism router's decision and the
        # measured inputs it saw); empty for loaded native models
        self.metadata: dict = dict(metadata) if metadata else {}
        self.config = config
        self.trees = trees
        self.tree_weights = list(tree_weights)
        self.base_score = np.atleast_1d(np.asarray(base_score, np.float64))
        self.feature_names = feature_names or [f"Column_{i}" for i in range(mapper.num_features)]
        self.best_iteration = best_iteration
        # the best validation metric value (LightGBM Booster.best_score)
        self.best_score = best_score
        # real-valued thresholds per tree; None → resolve from the bin mapper.
        # Loaded native models carry raw thresholds directly (no mapper).
        self.thresholds = thresholds
        # per-split LightGBM missing-type codes (0 none / 1 zero / 2 nan);
        # loaded native models parse them from decision_type, trained models
        # derive them from the mapper's NaN mask (_missing_types)
        self.missing_types = missing_types
        self._forest_cache: Optional[Forest] = None
        self._depth_cache: Optional[int] = None
        # bucketed serving runners keyed by max_batch_size (serving_fn /
        # batched predict share the same compiled bucket ladder)
        self._serving_cache: dict = {}

    # --- structure ------------------------------------------------------
    @property
    def num_class(self) -> int:
        return max(self.config.num_class, 1)

    @property
    def models_per_iter(self) -> int:
        return self.num_class if self.config.objective in ("multiclass", "softmax", "multiclassova") else 1

    @property
    def num_trees(self) -> int:
        return len(self.trees)

    @property
    def average_output(self) -> bool:
        return self.config.boosting_type == "rf"

    @property
    def trees_per_class(self) -> int:
        """Full-model rf averaging divisor (forest()); SHAP uses the
        start_iteration-windowed count to match raw_score's rescale."""
        return max(len(self.trees) // self.models_per_iter, 1)

    def _thresholds(self, index: int) -> np.ndarray:
        # per-entry None = resolve from the mapper (warm starts merge loaded
        # trees' parsed thresholds with None slots for newly grown trees)
        if self.thresholds is not None:
            t = (self.thresholds[index]
                 if index < len(self.thresholds) else None)
            if t is not None:
                return np.asarray(t, np.float32)
        tree = self.trees[index]
        sf = np.asarray(tree.split_feature)
        sb = np.asarray(tree.split_bin)
        vals = np.array([bin_threshold_to_value(self.mapper, int(f), int(b))
                         for f, b in zip(sf, sb)], np.float64)
        # top-bin sentinel is 1e308 (finite in f64 model strings); map it to an
        # INTENTIONAL f32 inf (not a clamp to f32max: +inf feature values must
        # still satisfy x <= threshold and go left, matching the binned path
        # where apply_bins clamps inf into the last real-value bin)
        f32max = np.float64(np.finfo(np.float32).max)
        return np.where(vals >= f32max, np.inf,
                        np.clip(vals, -f32max, f32max)).astype(np.float32)

    def _missing_types(self, index: int) -> np.ndarray:
        """(L-1,) missing-type codes for one tree: parsed values for loaded
        models, else nan (2) for features with a NaN bin AND for categorical
        splits (NaN categories are never set members) / 0 otherwise — the
        codes the model-string writer emits in decision_type, so in-memory
        traversal and a save/load round trip route missing rows identically."""
        if self.missing_types is not None:
            m = (self.missing_types[index]
                 if index < len(self.missing_types) else None)
            if m is not None:
                return np.asarray(m, np.int32)
        tree = self.trees[index]
        sf = np.asarray(tree.split_feature).astype(np.int64)
        stype = np.asarray(tree.split_type)
        has_nan = np.asarray(self.mapper.nan_mask)
        sf_safe = np.clip(sf, 0, len(has_nan) - 1)
        # zeroAsMissing trains with zeros mapped to NaN; traversal and the
        # serialized decision_type must route zeros (code 1), not just NaN
        nan_code = 1 if getattr(self.config, "zero_as_missing", False) else 2
        return np.where(stype[: len(sf)] == 1, 2,
                        np.where(has_nan[sf_safe], nan_code,
                                 0)).astype(np.int32)

    def unweighted(self) -> "Booster":
        """Copy with unit tree weights and zero base — used to recover raw
        per-tree contributions (dart drop candidates / rf validation).
        Thresholds/missing codes ride along: a from_model_string booster has
        a synthetic all-inf mapper, so dropping its parsed thresholds would
        send every row left."""
        return Booster(self.mapper, self.config, self.trees,
                       [1.0] * len(self.trees),
                       np.zeros_like(self.base_score),
                       thresholds=self.thresholds,
                       missing_types=self.missing_types)

    def forest(self) -> Forest:
        if self._forest_cache is None or self._forest_cache.num_trees != len(self.trees):
            trees = self.trees
            weights = np.asarray(self.tree_weights, np.float32)
            if self.average_output:
                weights = weights / self.trees_per_class
            weighted = [t._replace(leaf_value=jnp.asarray(t.leaf_value) * w)
                        for t, w in zip(trees, weights)]
            self._forest_cache = stack_trees(
                weighted, [self._thresholds(i) for i in range(len(trees))],
                [self._missing_types(i) for i in range(len(trees))])
            self._depth_cache = forest_max_depth(trees)
        return self._forest_cache

    # --- inference ------------------------------------------------------
    def serving_fn(self, max_batch_size: int = 64, bucketed: bool = True):
        """Callable ``X (N, F) -> prediction`` for low-latency serving:
        forest traversal, base score, and the objective's output transform
        compiled into a single XLA program — one device dispatch per request
        batch instead of predict()'s traversal + transform round trips. This
        is the handler-side analog of the reference's served fitted models
        (README Spark Serving cell; HTTPSourceV2.scala:485-713 transport +
        a model transform).

        By default the fused program runs through a shape-bucketed runner
        (core/inference.py, docs/serving-perf.md): batches pad up to a
        geometric ladder of bucket sizes so XLA compiles once per bucket —
        not once per observed batch size — with padded rows masked out of
        the result. The returned callable carries ``.runner`` (per-bucket
        compile/hit counters) and ``.warmup()`` (AOT-compile every bucket;
        ServingServer.start() calls it before accepting traffic).
        ``bucketed=False`` returns the raw fused jit for callers that manage
        their own shapes."""
        import jax

        forest = self.forest()
        obj = self._objective_for_transform()
        depth = self._depth_cache
        k = self.models_per_iter
        base = jnp.asarray(self.base_score[:max(k, 1)], jnp.float32)
        # the config's prediction window applies to serving too (raw_score
        # parity — code-review r5: a windowed booster must not serve
        # different probabilities than predict())
        start = max(int(getattr(self.config, "start_iteration", 0)), 0)

        def fn(X):
            if k == 1 and not start and not self.average_output:
                raw = forest_predict(forest, X, output="sum",
                                     depth=depth) + base[0]
            else:
                per_tree = forest_predict(forest, X, output="per_tree",
                                          depth=depth)
                n, t = per_tree.shape
                per_iter = per_tree.reshape(n, t // k, k)
                if start:
                    per_iter = per_iter[:, start:]
                if self.average_output and per_iter.shape[1] != t // k:
                    # rf leaves were pre-divided by the FULL tree count
                    per_iter = per_iter * ((t // k)
                                           / max(per_iter.shape[1], 1))
                raw = per_iter.sum(axis=1) + base[None]
                if k == 1:
                    raw = raw[:, 0]
            return obj.transform(raw)

        if not bucketed:
            return jax.jit(fn)

        from ..core.inference import BucketedRunner

        # fn is deliberately NOT pre-jitted here: the runner owns the jit
        # boundary (one AOT-compiled executable per bucket)
        runner = BucketedRunner(fn, max_batch_size=max_batch_size,
                                name="gbdt.serving_fn")
        num_features = self.mapper.num_features

        def serve(X):
            return runner(np.asarray(X))

        def warmup(dtype=np.float32):
            return runner.warmup(np.zeros((1, num_features), dtype))

        serve.runner = runner
        serve.warmup = warmup
        return serve

    def raw_score(self, X, binned: bool = False, num_iteration: int = -1,
                  start_iteration: Optional[int] = None) -> np.ndarray:
        """(N,) or (N, K) raw margin. ``num_iteration`` > 0 scores with only
        that many boosting rounds; ``start_iteration`` (default: the config's
        predict-time window) skips leading rounds. Training-side margin
        rebuilds pass start_iteration=0 explicitly — the window is a
        prediction feature and must not leak into warm starts."""
        X = _densify(X)
        nb = jnp.asarray(self.mapper.nan_bins) if binned else None
        forest = self.forest()
        k = self.models_per_iter
        if start_iteration is None:
            start_iteration = max(
                int(getattr(self.config, "start_iteration", 0)), 0)
        if (k == 1 and not start_iteration
                and (not num_iteration or num_iteration < 0)
                and not self.average_output):
            # no prediction window active: sum inside the traversal scan —
            # the (N, T) per-tree matrix is 4 GB at 11M rows x 100 trees and
            # exists only to support windowing/rf rescale
            out = forest_predict(forest, jnp.asarray(X), binned=binned,
                                 output="sum", nan_bins=nb,
                                 depth=self._depth_cache)
            return np.asarray(out + self.base_score[0])
        per_tree = forest_predict(forest, jnp.asarray(X), binned=binned,
                                  output="per_tree", nan_bins=nb,
                                  depth=self._depth_cache)  # (N, T)
        n, t = per_tree.shape
        per_iter = per_tree.reshape(n, t // k, k)
        if start_iteration:
            per_iter = per_iter[:, start_iteration:]
        if num_iteration and num_iteration > 0:
            per_iter = per_iter[:, :num_iteration]
        if self.average_output and per_iter.shape[1] != t // k:
            # rf leaves were pre-divided by the FULL tree count; rescale so
            # the windowed average stays an average of the summed trees
            per_iter = per_iter * ((t // k) / max(per_iter.shape[1], 1))
        out = per_iter.sum(axis=1) + self.base_score[None, :k]
        return np.asarray(out[:, 0] if k == 1 else out)

    def predict(self, X, binned: bool = False, num_iteration: int = -1,
                batch_size: Optional[int] = None) -> np.ndarray:
        """Probability / response-space prediction.

        ``batch_size`` routes batch predict through the shared bucketed
        serving runner (core/inference.py): rows are processed in
        ``batch_size`` chunks with a bucket-padded tail, so repeated calls
        with varying N reuse one compiled ladder instead of compiling a
        fresh XLA program per observed shape. The runner is cached per
        ``batch_size``, shared with ``serving_fn(max_batch_size=...)``."""
        if batch_size is not None:
            if binned or (num_iteration and num_iteration > 0):
                raise ValueError(
                    "predict(batch_size=...) serves the full raw-value "
                    "model; binned inputs or an iteration window need the "
                    "unbatched path")
            serve = self._serving_cache.get(batch_size)
            if serve is None:
                serve = self.serving_fn(max_batch_size=batch_size)
                self._serving_cache[batch_size] = serve
            return serve(_densify(X))
        raw = self.raw_score(X, binned=binned, num_iteration=num_iteration)
        obj = self._objective_for_transform()
        return np.asarray(obj.transform(jnp.asarray(raw)))

    def predict_leaf(self, X) -> np.ndarray:
        """(N, T) leaf indices (predictLeaf parity, LightGBMBooster.scala:408)."""
        forest = self.forest()
        leaves = np.asarray(forest_predict(forest, jnp.asarray(_densify(X)),
                                           output="leaf",
                                           depth=self._depth_cache))
        start = max(int(getattr(self.config, "start_iteration", 0)), 0)
        return leaves[:, start * self.models_per_iter:] if start else leaves

    def feature_importances(self, importance_type: str = "split") -> np.ndarray:
        """split count or total gain per feature (getFeatureImportances parity,
        LightGBMBooster.scala:490-505)."""
        imp = np.zeros(self.mapper.num_features)
        for t in self.trees:
            ns = int(t.num_splits)
            sf = np.asarray(t.split_feature)[:ns]
            if importance_type == "gain":
                np.add.at(imp, sf, np.asarray(t.split_gain)[:ns])
            else:
                np.add.at(imp, sf, 1.0)
        return imp

    def feature_shap(self, X) -> np.ndarray:
        from .shap import forest_shap
        return forest_shap(self, np.asarray(_densify(X), np.float32))

    def _objective_for_transform(self) -> Objective:
        cfg = self.config
        name = cfg.objective
        if name == "lambdarank":
            from .objectives import regression_objective
            return regression_objective()
        return get_objective(name, num_class=self.num_class, sigmoid=cfg.sigmoid,
                             alpha=cfg.alpha, fair_c=cfg.fair_c,
                             poisson_max_delta_step=cfg.poisson_max_delta_step,
                             tweedie_variance_power=cfg.tweedie_variance_power)

    # --- persistence ----------------------------------------------------
    def dump_model(self, num_iteration: int = -1) -> str:
        """LightGBM-format JSON dump (dumpModel parity,
        LightGBMBooster.scala:458-516)."""
        from .model_io import booster_dump_json

        return booster_dump_json(self, num_iteration)

    def model_string(self) -> str:
        from .model_io import booster_to_string
        return booster_to_string(self)

    @staticmethod
    def from_model_string(s: str) -> "Booster":
        from .model_io import booster_from_string
        return booster_from_string(s)

    def save_native(self, path: str) -> None:
        """saveNativeModel parity (LightGBMBooster.scala:458-470)."""
        with open(path, "w") as f:
            f.write(self.model_string())

    def to_onnx(self, input_name: str = "input", num_iteration: int = -1):
        """ONNX TreeEnsemble export — the native analog of the reference's
        documented onnxmltools.convert_lightgbm workflow (website Quickstart
        - ONNX Model Inference.md); serve the result through ONNXModel."""
        from ..onnx.treeensemble import booster_to_onnx

        return booster_to_onnx(self, input_name, num_iteration)


# ---------------------------------------------------------------------------
# Training
# ---------------------------------------------------------------------------

def _densify(X):
    """scipy sparse -> dense float32 (predict/valid inputs accept CSR the same
    as training); pass-through for anything else."""
    if _is_sparse(X):
        return np.asarray(X.tocsr().todense(), np.float32)
    return X


@jax.jit
def _leaf_gather(leaf_value, node_of_row):
    return leaf_value[node_of_row]


# ---------------------------------------------------------------------------
# Shared per-iteration sampling (device-side; used by the fused scan and the
# host loop so both paths sample identically from fold_in(seed, it))
# ---------------------------------------------------------------------------

def _sample_rows_impl(cfg, n, key0, valid_mask, it, g, h, in_bag_cur, yj=None):
    goss_mode = cfg.boosting_type == "goss"
    stratified = (cfg.pos_bagging_fraction < 1.0
                  or cfg.neg_bagging_fraction < 1.0)
    do_bag = ((cfg.boosting_type == "rf" or cfg.bagging_freq > 0)
              and (cfg.bagging_fraction < 1.0 or stratified))
    if goss_mode:
        gnorm = jnp.abs(g).sum(axis=1)
        top_n = int(cfg.top_rate * n)
        rand_n = int(cfg.other_rate * n)
        amp = (1.0 - cfg.top_rate) / max(cfg.other_rate, 1e-12)
        order = jnp.argsort(-gnorm)
        ranks = jnp.zeros(n, jnp.int32).at[order].set(
            jnp.arange(n, dtype=jnp.int32))
        kg = (jax.random.fold_in(key0, cfg.extra_seed) if cfg.extra_seed
              else key0)   # default 0 keeps the established stream
        u = jax.random.uniform(jax.random.fold_in(kg, it), (n,))
        rest = ranks >= top_n
        pick = rest & (u < (rand_n / max(n - top_n, 1)))
        wmask = (jnp.where(ranks < top_n, 1.0,
                           jnp.where(pick, amp, 0.0)) * valid_mask)
        return (wmask > 0).astype(jnp.float32), g * wmask[:, None], \
            h * wmask[:, None], in_bag_cur
    if do_bag:
        kb = (jax.random.fold_in(key0, cfg.bagging_seed)
              if cfg.bagging_seed != 3 else key0)  # default keeps the stream
        u = jax.random.uniform(
            jax.random.fold_in(kb, 20_000_000 + it), (n,))
        if stratified and yj is not None:
            # posBaggingFraction / negBaggingFraction (binary objectives):
            # per-class keep probability, refreshed every bagging_freq rounds
            frac = jnp.where(yj > 0, cfg.pos_bagging_fraction,
                             cfg.neg_bagging_fraction)
        else:
            frac = cfg.bagging_fraction
        fresh = ((u < frac).astype(jnp.float32) * valid_mask)
        bag = jnp.where(it % max(cfg.bagging_freq, 1) == 0, fresh, in_bag_cur)
        return bag, g, h, bag
    return valid_mask, g, h, in_bag_cur


def _sample_features_impl(cfg, nfeat, key0, it):
    if cfg.feature_fraction >= 1.0:
        return jnp.ones(nfeat, bool)
    nf_keep = max(1, int(math.ceil(cfg.feature_fraction * nfeat)))
    kf = (jax.random.fold_in(key0, cfg.feature_fraction_seed)
          if cfg.feature_fraction_seed else key0)  # 0 keeps the default stream
    perm = jax.random.permutation(
        jax.random.fold_in(kf, 10_000_000 + it), nfeat)
    return jnp.zeros(nfeat, bool).at[perm[:nf_keep]].set(True)


def _node_key_data(key0, it, cls):
    """Per-tree raw key for feature_fraction_bynode: shared derivation so the
    fused scan and the host loop sample identical per-node feature subsets."""
    return jax.random.key_data(
        jax.random.fold_in(jax.random.fold_in(key0, 30_000_000 + cls), it))


def _make_grow_fn(grower_cfg, mesh):
    """The per-tree grower, shard_map'd over the data axis when distributed
    (one histogram psum per split — the socket-ring allreduce analog)."""
    if mesh is not None:
        from jax.sharding import PartitionSpec as P
        from ..parallel.collectives import shard_apply
        from ..parallel.mesh import DATA_AXIS as _DA

        def _grow_sharded(binned_s, g_s, h_s, bag_s, fa, ic, mo, nb, nk, cb):
            return grow_tree(binned_s, g_s, h_s, bag_s, fa, ic, mo,
                             grower_cfg, nan_bins=nb, axis_name=_DA,
                             node_key=nk, cat_nbins=cb)

        return shard_apply(
            mesh, _grow_sharded,
            in_specs=(P(_DA, None), P(_DA), P(_DA), P(_DA),
                      P(None), P(None), P(None), P(None), P(None), P(None)),
            out_specs=(P(), P(_DA)))

    def grow_fn(binned_s, g_s, h_s, bag_s, fa, ic, mo, nb, nk, cb):
        return grow_tree(binned_s, g_s, h_s, bag_s, fa, ic, mo,
                         grower_cfg, nan_bins=nb, node_key=nk, cat_nbins=cb)

    return grow_fn


def _route_features(cfg, n_rows, nfeat, n_workers):
    """The tree-learner featurization shared by the router, bench.py's
    training-row writer, and the ci.sh auto-config guard — one schema, so
    rows recorded by a bench arm are matchable by the live router."""
    from ..core import perfmodel

    return perfmodel.featurize(
        wire_dtype=cfg.hist_allreduce_dtype, rows=n_rows, nfeat=nfeat,
        workers=n_workers, max_bin=cfg.max_bin, top_k=cfg.top_k,
        num_leaves=cfg.num_leaves)


def _perfmodel_route(cfg, n_rows, nfeat, n_workers, choice, info,
                     feature_ok):
    """Layer the learned perf model over ``route_parallelism``'s analytic
    choice: the analytic per-tree predictions become priors, and recorded
    training rows for a matching workload (kind ``gbdt_tree_learner``) can
    confidently override the hand-tuned cost model. Low confidence — the
    usual case on shapes never benched — keeps the analytic choice, so
    this layer strictly adds measured evidence. Provenance lands in
    ``info["perfmodel"]`` either way."""
    from ..core import perfmodel

    feats = _route_features(cfg, n_rows, nfeat, n_workers)
    pred = info.get("predicted_s_per_tree") or {}
    arms = ["data", "voting"] + (["feature"] if feature_ok else [])
    cands = [perfmodel.Candidate("gbdt_tree_learner", arm, feats,
                                 analytic_s=pred.get(arm), config=arm)
             for arm in arms]
    try:
        dec = perfmodel.choose(cands, fallback_arm=choice)
    except Exception:  # model failure keeps router choice
        return choice
    info["perfmodel"] = dec.provenance()
    if not dec.used_fallback and dec.arm != choice:
        info["tree_learner"] = dec.arm
        info["router"] = "measured+perfmodel"
        return dec.arm
    return choice


def _train_metadata(routing_info, autoconfig_info, fit_t0):
    """Assemble Booster.metadata: the router's decision plus every
    auto-configuration decision's provenance, stamped with the observed fit
    wall time so predicted-vs-observed runtime is auditable per model."""
    meta = {}
    if routing_info:
        meta["routing"] = routing_info
    if autoconfig_info:
        autoconfig_info["observed_fit_s"] = round(
            _time.perf_counter() - fit_t0, 6)
        meta["autoconfig"] = autoconfig_info
    return meta or None


def _auto_route(cfg, mesh, binned, nfeat, n_rows, multiproc,
                has_categorical):
    """Resolve ``tree_learner='auto'`` into a concrete learner.

    Single-process mesh: measure the link (one timed ~1MB allreduce) and —
    when voting is even a candidate (F > 2k) — the selection pass, both
    cached per mesh in ``core.tuned``'s measurement store, then let
    ``voting.route_parallelism`` pick data / voting / feature from the
    quantization-aware cost model. Multi-process training skips the probes
    (a timed collective would need every process in lockstep before shapes
    are agreed) and falls back to the static ``recommend_tree_learner``
    model, as before. Returns ``(choice, info)``; ``info`` lands in
    ``Booster.metadata['routing']`` so the decision is auditable.
    """
    from .voting import recommend_tree_learner, route_parallelism

    if mesh is None:
        return "data", {"tree_learner": "data", "router": "static",
                        "reason": "no mesh: serial == data-parallel-of-1"}
    from ..parallel.mesh import DATA_AXIS as _DA

    n_workers = int(dict(mesh.shape).get(_DA, 1))
    if multiproc or n_workers <= 1:
        choice = recommend_tree_learner(
            nfeat, cfg.max_bin, cfg.top_k, cfg.num_leaves,
            n_hosts=jax.process_count(), rows_per_host=n_rows,
            dtype_bytes=(8 / 3 if cfg.hist_allreduce_dtype == "bf16" else 4))
        reason = "multi-process: static model (no probes)" \
            if multiproc else "single worker"
        if choice == "voting" and multiproc:
            import warnings

            warnings.warn(
                "tree_learner='auto': the collective cost model prefers "
                "voting-parallel at this shape, but multi-process training "
                "does not support the voting learner yet — falling back to "
                "data-parallel. Set tree_learner='voting' on a "
                "single-process mesh to use it.")
            choice = "data"
        return choice, {"tree_learner": choice, "router": "static",
                        "reason": reason}

    from ..core import tuned
    from ..parallel.collectives import probe_link_bandwidth

    try:
        fp = tuned.mesh_fingerprint(mesh)
        link = tuned.measured_or(("link_bytes_per_s", fp),
                                 lambda: probe_link_bandwidth(mesh))
        sel_s, sel_frac = None, 1.0
        if nfeat > 2 * cfg.top_k:
            from .voting import time_selection

            sel_s, sel_frac = tuned.measured_or(
                ("selection_s_per_tree", fp, int(binned.shape[0]), nfeat,
                 cfg.max_bin, cfg.top_k),
                lambda: time_selection(
                    binned, mesh, cfg.top_k, cfg.max_bin,
                    lambda_l2=cfg.lambda_l2,
                    min_data=max(cfg.min_data_in_leaf, 1)))
        from ..ops.hist_kernel import features_padded as _fpad

        feature_ok = (not has_categorical
                      and cfg.growth_policy == "leafwise"
                      and cfg.row_layout == "partition"
                      and _fpad(nfeat) % n_workers == 0)
        choice, info = route_parallelism(
            nfeat, cfg.max_bin, cfg.top_k, cfg.num_leaves,
            n_workers=n_workers,
            rows_per_worker=max(n_rows // n_workers, 1),
            link_bytes_per_s=link,
            selection_s_per_tree=sel_s,
            selection_fraction_of_rows=sel_frac,
            wire_dtype=cfg.hist_allreduce_dtype,
            feature_parallel_ok=feature_ok)
        info["router"] = "measured"
        choice = _perfmodel_route(cfg, n_rows, nfeat, n_workers, choice,
                                  info, feature_ok)
        return choice, info
    except Exception as e:                   # pragma: no cover - probe escape
        import warnings

        warnings.warn(f"tree_learner='auto': probe failed ({e!r}); "
                      "using the static cost model")
        choice = recommend_tree_learner(
            nfeat, cfg.max_bin, cfg.top_k, cfg.num_leaves,
            n_hosts=jax.process_count(), rows_per_host=n_rows,
            dtype_bytes=(8 / 3 if cfg.hist_allreduce_dtype == "bf16" else 4))
        return choice, {"tree_learner": choice, "router": "static",
                        "reason": f"probe failed: {e!r}"}


# ---------------------------------------------------------------------------
# Fused-scan runner cache: the jitted whole-training program is cached ACROSS
# train_booster calls (keyed by the static config + shapes), so a warmup call
# with identical config compiles the exact executable the timed/production
# call reuses. Without this, every fit would recompile the scan — minutes
# through a remote-compile tunnel.
# ---------------------------------------------------------------------------

_FUSED_RUNNERS: dict = {}


def _fused_static_key(cfg, grower_cfg, n, nfeat, k, nv, metric_name, mesh):
    mono = tuple(cfg.monotone_constraints or ())
    return (cfg.objective, cfg.boosting_type, cfg.learning_rate, cfg.num_class,
            cfg.sigmoid, cfg.alpha, cfg.fair_c, cfg.poisson_max_delta_step,
            cfg.tweedie_variance_power, cfg.top_rate, cfg.other_rate,
            cfg.bagging_fraction, cfg.bagging_freq, cfg.feature_fraction,
            cfg.pos_bagging_fraction, cfg.neg_bagging_fraction,
            cfg.lambdarank_truncation_level, mono, grower_cfg,
            # seeds are folded into the traced program as Python ints
            # (_sample_rows_impl/_sample_features_impl): two configs that
            # differ only here must NOT share an executable
            cfg.extra_seed, cfg.feature_fraction_seed, cfg.bagging_seed,
            tuple(cfg.label_gain or ()),
            n, nfeat, k, nv, metric_name, mesh)


def _get_fused_runner(cfg, grower_cfg, n, nfeat, k, nv, metric_name, mesh):
    """Jitted fn(binned, yj, wj, valid_mask, key0, is_cat, mono, nan_bins,
    base_k, gidx, binned_v, yv_j, wv_j, gidx_v, score0, bag0, sv0, start,
    count[static]) → (carry, (stacked_trees, mvals)). ``nv`` is the
    validation row count (0 = no validation)."""
    key = _fused_static_key(cfg, grower_cfg, n, nfeat, k, nv, metric_name,
                            mesh)
    if key in _FUSED_RUNNERS:
        return _FUSED_RUNNERS[key]

    has_valid = nv > 0
    rf_mode = cfg.boosting_type == "rf"
    is_ranking = cfg.objective == "lambdarank"
    grow_fn = _make_grow_fn(grower_cfg, mesh)
    if not is_ranking:
        obj = get_objective(cfg.objective, num_class=max(k, 1),
                            sigmoid=cfg.sigmoid, alpha=cfg.alpha,
                            fair_c=cfg.fair_c,
                            poisson_max_delta_step=cfg.poisson_max_delta_step,
                            tweedie_variance_power=cfg.tweedie_variance_power)

    def body_for(args):
        (binned, yj, wj, valid_mask, key0, is_cat, mono, nan_bins, cat_nbins,
         base_k, gidx, binned_v, yv_j, wv_j, gidx_v) = args
        if not jnp.issubdtype(key0.dtype, jax.dtypes.prng_key):
            key0 = jax.random.wrap_key_data(key0)   # multi-process raw key
        if is_ranking:
            obj_l = lambdarank_objective(gidx, cfg.sigmoid,
                                         cfg.lambdarank_truncation_level,
                                         cfg.label_gain)
            gh_fn, transform = obj_l.grad_hess, (lambda sc: sc)
        else:
            gh_fn, transform = obj.grad_hess, obj.transform

        def body(carry, it):
            score_c, in_bag_c, score_v_c = carry
            g, h = gh_fn(score_c[:, 0] if k == 1 else score_c, yj, wj)
            g = jnp.reshape(g, (n, k))
            h = jnp.reshape(h, (n, k))
            in_bag, g, h, in_bag_c = _sample_rows_impl(
                cfg, n, key0, valid_mask, it, g, h, in_bag_c, yj)
            feat_mask = _sample_features_impl(cfg, nfeat, key0, it)
            cls_trees = []
            for cls in range(k):
                tree, node = grow_fn(binned, g[:, cls], h[:, cls], in_bag,
                                     feat_mask, is_cat, mono, nan_bins,
                                     _node_key_data(key0, it, cls), cat_nbins)
                cls_trees.append(tree)
                if not rf_mode:
                    score_c = score_c.at[:, cls].add(
                        _leaf_gather(tree.leaf_value, node))
                if has_valid:
                    leaf_v = _tree_assign_binned(tree, binned_v, nan_bins)
                    score_v_c = score_v_c.at[:, cls].add(
                        jnp.asarray(tree.leaf_value)[leaf_v])
            stacked = jax.tree.map(lambda *x: jnp.stack(x), *cls_trees)
            if has_valid:
                # rf averages the trees grown so far
                raw_v = (score_v_c if not rf_mode else
                         base_k[None, :]
                         + (score_v_c - base_k[None, :])
                         / (it + 1).astype(jnp.float32))
                pred_v = transform(raw_v[:, 0] if k == 1 else raw_v)
                if _is_rank_metric(metric_name):
                    at = (int(metric_name.split("@")[1])
                          if "@" in metric_name else 5)
                    if metric_name.startswith("map"):
                        mval = map_at_k(yv_j, raw_v[:, 0], gidx_v, at)
                    else:
                        mval = ndcg_at_k(yv_j, raw_v[:, 0], gidx_v, at,
                                         cfg.label_gain)
                else:
                    mval = METRICS[metric_name](yv_j, pred_v, weight=wv_j,
                                                **metric_kwargs(cfg))
            else:
                mval = jnp.float32(0)
            return (score_c, in_bag_c, score_v_c), (stacked, mval)

        return body

    @functools.partial(jax.jit, static_argnames=("count",))
    def run_scan(binned, yj, wj, valid_mask, key0, is_cat, mono, nan_bins,
                 cat_nbins, base_k, gidx, binned_v, yv_j, wv_j, gidx_v,
                 score0,
                 bag0, sv0, start, count):
        body = body_for((binned, yj, wj, valid_mask, key0, is_cat, mono,
                         nan_bins, cat_nbins, base_k, gidx, binned_v, yv_j,
                         wv_j, gidx_v))
        return lax.scan(body, (score0, bag0, sv0),
                        start + jnp.arange(count, dtype=jnp.int32))

    if len(_FUSED_RUNNERS) > 16:
        # LRU-ish: evict the oldest entry, keep hot executables (a full clear
        # would force minute-scale remote recompiles under config churn)
        _FUSED_RUNNERS.pop(next(iter(_FUSED_RUNNERS)))
    _FUSED_RUNNERS[key] = run_scan
    return run_scan


def _tree_assign_binned(tree: TreeArrays, binned, nan_bins=None) -> jnp.ndarray:
    """Leaf assignment of (already-binned) rows for one tree — used for
    validation-score streaming updates."""
    f = Forest(split_feature=tree.split_feature[None], threshold=jnp.zeros_like(
        tree.split_gain)[None], split_bin=tree.split_bin[None],
        split_type=tree.split_type[None], default_left=tree.default_left[None],
        cat_bitset=tree.cat_bitset[None],
        left_child=tree.left_child[None], right_child=tree.right_child[None],
        leaf_value=tree.leaf_value[None])
    return forest_predict(f, binned, binned=True, output="leaf",
                          nan_bins=nan_bins)[:, 0]


def train_booster(
    X: np.ndarray,
    y: np.ndarray,
    config: BoosterConfig,
    sample_weight: Optional[np.ndarray] = None,
    init_score: Optional[np.ndarray] = None,
    categorical_features: Optional[Sequence[int]] = None,
    group_sizes: Optional[np.ndarray] = None,
    valid: Optional[tuple] = None,            # (Xv, yv) or (Xv, yv, wv, group_sizes_v) for ranking
    fobj: Optional[Callable] = None,          # custom objective (FObjTrait analog)
    feature_names: Optional[List[str]] = None,
    init_model: Optional[Booster] = None,     # warm start (modelString param analog)
    callbacks: Optional[List[Callable]] = None,
    mapper: Optional[BinMapper] = None,       # pre-computed reference dataset analog
    mesh=None,                                # jax.sharding.Mesh: shard rows over DATA_AXIS
    measures=None,                            # InstrumentationMeasures (§5.1)
    checkpoint_store=None,                    # CheckpointStore or directory path
    checkpoint_every: int = 0,                # snapshot every K iterations (0 = default 10)
    resume: bool = True,                      # continue from the newest matching snapshot
) -> Booster:
    from ..core.logging import InstrumentationMeasures

    if measures is None:
        measures = InstrumentationMeasures()
    cfg = config
    # out-of-core route: a StreamedDataset carries its own labels/weights and
    # trains through the chunk-streamed level-synchronous grower
    # (gbdt/stream.py — local import: stream imports this module)
    from .stream import StreamedDataset, train_booster_streamed

    if isinstance(X, StreamedDataset):
        unsupported = [name for name, v in [
            ("y", y), ("sample_weight", sample_weight),
            ("init_score", init_score), ("group_sizes", group_sizes),
            ("fobj", fobj), ("init_model", init_model),
            ("callbacks", callbacks or None)]
            if v is not None]
        if unsupported:
            raise NotImplementedError(
                f"train_booster(StreamedDataset) does not take {unsupported}"
                " — labels/weights ride the stream; the other features are "
                "resident-path only (see gbdt/stream.py)")
        if mapper is not None and X.mapper is None:
            X.mapper = mapper
            X._user_mapper = True
        if categorical_features is not None and X.categorical_features is None:
            X.categorical_features = list(categorical_features)
        return train_booster_streamed(
            X, config, mesh=mesh, valid_data=valid, measures=measures,
            checkpoint_store=checkpoint_store,
            checkpoint_every=checkpoint_every, resume=resume,
            feature_names=feature_names)
    # --- crash-safe snapshots (core/checkpoint.py): periodic forest + loop
    # state, resumable bit-for-bit because all per-iteration sampling is
    # stateless fold_in(seed, it) and the carried score is saved exactly
    ckpt_store = checkpoint_store
    if isinstance(ckpt_store, str):
        from ..core.checkpoint import CheckpointStore

        ckpt_store = CheckpointStore(ckpt_store)
    if ckpt_store is not None and checkpoint_every <= 0:
        checkpoint_every = 10
    # multi-process snapshots: the carry is gathered to host on every rank
    # (_pack_gbdt_carry is collective) and committed by rank 0 through a
    # shared checkpoint directory; snapshots are trimmed to the original
    # unpadded global rows so a shrunken/regrown mesh can resume them
    # (parallel/elastic.py consensus restart path)
    if _is_sparse(X):
        if mesh is not None or init_model is not None:
            # these paths need raw dense rows anyway (padding / rescoring) and
            # would discard a pre-binned matrix — densify once, skip the wrap
            X = _densify(X)
        else:
            # scipy CSR/CSC rows: bin chunk-wise through the sparse Dataset
            # path (the reference's isSparse election, BulkPartitionTask CSR)
            X = Dataset(X, mapper=mapper, max_bin=cfg.max_bin,
                        bin_sample_count=cfg.bin_sample_count,
                        categorical_features=categorical_features,
                        seed=cfg.seed, min_data_in_bin=cfg.min_data_in_bin,
                        max_bin_by_feature=cfg.max_bin_by_feature)
    # LightGBM Dataset analog: pre-binned device-resident data skips the
    # quantization pass and the raw-float host→device transfer entirely
    dataset = X if isinstance(X, Dataset) else None
    prebinned = None
    if dataset is not None:
        if y is None:
            y = dataset.label
        if y is None:
            raise ValueError("no label: pass y explicitly or build the "
                             "Dataset with label=...")
        if sample_weight is None:
            sample_weight = dataset.weight
        if init_score is None:
            init_score = dataset.init_score
        if group_sizes is None:
            group_sizes = dataset.group_sizes
        if categorical_features is None:
            categorical_features = dataset.categorical_features
        ds_binning = (getattr(dataset, "min_data_in_bin", 3),
                      tuple(dataset.max_bin_by_feature)
                      if getattr(dataset, "max_bin_by_feature", None) else None)
        cfg_binning = (cfg.min_data_in_bin,
                       tuple(cfg.max_bin_by_feature)
                       if cfg.max_bin_by_feature else None)
        if (ds_binning != cfg_binning and mapper is None
                and not getattr(dataset, "_user_mapper", False)):
            # (an explicit user mapper defines the binning outright — the
            # Dataset's unused binning knobs cannot conflict with anything)
            raise ValueError(
                f"Dataset was binned with (min_data_in_bin, max_bin_by_feature)"
                f"={ds_binning} but the config asks for {cfg_binning}; rebuild "
                "the Dataset with matching binning params")
        if mapper is not None and mapper is not dataset.mapper:
            # explicit conflicting mapper (reference-dataset warm-start style):
            # the pre-binned ids were assigned under dataset.mapper's
            # boundaries, so fall back to re-binning the raw rows under the
            # user's mapper rather than decoding splits against the wrong one
            pass
        else:
            mapper = dataset.mapper
            if init_model is None and (mesh is None
                                       or jax.process_count() == 1):
                # fast path: reuse the binned matrix. Warm start still needs
                # raw rows (init-model rescoring); single-process mesh pads
                # the BINNED rows below, so streamed datasets (from_batches:
                # raw floats never kept) shard across a mesh too. Multi-
                # process keeps the raw path (global ingest re-stages rows).
                prebinned = dataset.binned
        if prebinned is not None:
            # shape-only placeholder when no dense raw rows are held (sparse
            # or keep_raw=False): broadcast view, zero memory, never read
            X = (dataset.X if dataset.X is not None
                 else np.broadcast_to(np.float32(0.0), dataset.shape))
        else:
            X = dataset.raw_dense()
            if X is None:
                raise ValueError("Dataset was built with keep_raw=False; this "
                                 "training path (mesh / warm start) needs raw "
                                 "rows")
    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.float32)
    if X.ndim != 2 or X.shape[0] == 0:
        raise ValueError(f"training data must be a non-empty 2-D matrix, got shape {X.shape}")
    if len(y) != X.shape[0]:
        raise ValueError(f"label length {len(y)} != row count {X.shape[0]}")
    n_orig, nfeat = X.shape
    w = (np.ones(n_orig, np.float32) if sample_weight is None
         else np.asarray(sample_weight, np.float32))
    rng = np.random.default_rng(cfg.seed)

    multiproc = mesh is not None and jax.process_count() > 1
    if mapper is None and not multiproc:
        # sampling + bin-boundary phase (reference: samplingParameters /
        # columnStatistics spans in LightGBMPerformance.scala); the multiproc
        # path instead samples across ALL processes below
        with measures.span("referenceDataset"):
            mapper = compute_bin_mapper(
                X, cfg.max_bin, cfg.bin_sample_count, categorical_features,
                (cfg.seed if cfg.data_random_seed is None
                 else int(cfg.data_random_seed)),
                min_data_in_bin=cfg.min_data_in_bin,
                max_bin_by_feature=cfg.max_bin_by_feature)
    if mapper is not None and mapper.max_bin != cfg.max_bin:
        # every mapper source (Dataset, explicit mapper=, warm start) funnels
        # through here: bin ids outside the grower's num_bins range would
        # silently drop from histograms, so a mismatch is an error
        raise ValueError(
            f"bin mapper has max_bin={mapper.max_bin} but config.max_bin="
            f"{cfg.max_bin}; rebuild the Dataset/mapper with the matching "
            "max_bin")

    # Multi-PROCESS (multi-host) mode: X/y are THIS process's row shard of one
    # global mesh; bin boundaries broadcast from process 0 so every host bins
    # identically, and all row arrays are assembled into global sharded arrays
    # (the reference's distributed mode instead rendezvouses a socket ring).
    if multiproc:
        unsupported = [name for name, v in [
            ("fobj", fobj), ("callbacks", callbacks or None),
            ("init_model", init_model), ("valid", valid),
            ("init_score", init_score), ("group_sizes", group_sizes)]
            if v is not None]
        if unsupported or cfg.boosting_type == "dart" \
                or cfg.tree_learner in ("voting", "feature"):
            raise NotImplementedError(
                "multi-process training currently supports the fused path "
                f"only (gbdt/goss/rf, serial learner); got {unsupported or cfg}")
        from jax.experimental import multihost_utils

        from ..parallel.mesh import (assert_equal_across_processes,
                                     local_mesh_devices)

        local_mesh_devices(mesh)        # mesh must span every process evenly
        assert_equal_across_processes((n_orig, nfeat),
                                      "local row count / feature count")
        if mapper is None:
            # bin boundaries from a sample gathered across ALL processes (the
            # reference samples across all partitions on the driver,
            # LightGBMBase.getSampledRows); deterministic on the gathered
            # union, so every process computes the identical mapper
            per = max(1, min(n_orig,
                             -(-cfg.bin_sample_count // jax.process_count())))
            sub = np.random.default_rng(cfg.seed).choice(
                n_orig, size=per, replace=False)
            gathered = np.asarray(multihost_utils.process_allgather(
                np.ascontiguousarray(X[np.sort(sub)])))
            X_samp = gathered.reshape(-1, X.shape[1])
            # NaN election over the FULL global matrix, not just the sample
            local_nan = np.ascontiguousarray(np.isnan(X).any(axis=0)[None])
            has_nan_g = np.asarray(multihost_utils.process_allgather(
                local_nan)).reshape(-1, X.shape[1]).any(axis=0)
            # categorical bin occupancy over the FULL global matrix: local
            # presence bitmaps OR-reduced across processes (maxCatToOnehot
            # must not depend on which rows the boundary sample drew)
            cat_presence_g = None
            if categorical_features:
                from ..ops.quantize import cat_presence_bitmap

                pres_l = np.zeros((X.shape[1], cfg.max_bin), np.uint8)
                for cj in categorical_features:
                    pres_l[cj] = cat_presence_bitmap(X[:, cj], cfg.max_bin)
                cat_presence_g = np.asarray(multihost_utils.process_allgather(
                    pres_l[None])).reshape(-1, X.shape[1], cfg.max_bin).any(0)
            mapper = compute_bin_mapper(
                X_samp, cfg.max_bin, cfg.bin_sample_count,
                categorical_features, cfg.seed, has_nan=has_nan_g,
                min_data_in_bin=cfg.min_data_in_bin,
                max_bin_by_feature=cfg.max_bin_by_feature,
                cat_presence=cat_presence_g)
        else:
            bnd, nb_, cat_, hn_ = multihost_utils.broadcast_one_to_all(
                (mapper.boundaries, np.asarray(mapper.num_bins),
                 np.asarray(mapper.is_categorical),
                 np.asarray(mapper.nan_mask)))
            # NaNs on ANY process must have a dedicated bin in the broadcast
            # mapper — a local mapper that never saw them would silently route
            # those NaNs into the last real-value bin
            any_nan = np.asarray(multihost_utils.process_allgather(
                np.ascontiguousarray(np.isnan(X).any(axis=0)[None]))
                ).reshape(-1, X.shape[1]).any(axis=0)
            if (any_nan & ~np.asarray(hn_)).any():
                raise ValueError(
                    "explicit mapper lacks NaN bins for features with missing "
                    "values on some process; pass mapper=None so boundaries "
                    "are sampled across all processes")
            mapper = BinMapper(boundaries=np.asarray(bnd),
                               num_bins=np.asarray(nb_),
                               is_categorical=np.asarray(cat_),
                               max_bin=mapper.max_bin,
                               has_nan=np.asarray(hn_))


    # Multi-chip: pad rows to the data-axis size and shard. The padding rows get
    # in_bag = 0, so they contribute nothing to histograms or leaf stats; GSPMD
    # then turns the histogram scatter into per-shard partials + one psum over
    # ICI — the entire replacement for LightGBM's socket-ring allreduce.
    valid_mask_np = np.ones(n_orig, np.float32)
    if mesh is not None:
        from ..parallel.mesh import DATA_AXIS as _DA
        ndata = mesh.shape[_DA]
        if multiproc:
            # local rows pad to the per-process shard multiple; every process
            # must contribute equally-sized shards
            nproc = jax.process_count()
            if ndata % nproc:
                raise ValueError(f"data axis ({ndata}) must divide evenly "
                                 f"across {nproc} processes")
            ndata = ndata // nproc
        rem = (-n_orig) % ndata
        if rem:
            if prebinned is not None:
                # pad the BINNED rows directly (in_bag=0 keeps padding out
                # of every histogram); the raw-X placeholder stays a
                # zero-memory broadcast view at the new length
                pb = np.asarray(prebinned)
                prebinned = np.concatenate(
                    [pb, np.repeat(pb[-1:], rem, axis=0)])
                X = np.broadcast_to(np.float32(0.0),
                                    (n_orig + rem, X.shape[1]))
            else:
                X = np.concatenate([X, np.repeat(X[-1:], rem, axis=0)])
            y = np.concatenate([y, np.zeros(rem, np.float32)])
            w = np.concatenate([w, np.zeros(rem, np.float32)])
            valid_mask_np = np.concatenate([valid_mask_np, np.zeros(rem, np.float32)])
            if init_score is not None:
                init_score = np.concatenate(
                    [np.asarray(init_score), np.zeros(rem, np.float32)])
    n = X.shape[0]
    with measures.span("dataPreparation"):
        binned = prebinned if prebinned is not None else apply_bins(mapper, X)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.mesh import DATA_AXIS as _DA
        row2 = NamedSharding(mesh, P(_DA, None))
        row1 = NamedSharding(mesh, P(_DA))
        if multiproc:
            from ..parallel.mesh import to_global_rows
            binned = to_global_rows(mesh, P(_DA, None), np.asarray(binned))
            n = n * jax.process_count()       # n is GLOBAL from here on
        else:
            binned = jax.device_put(binned, row2)

    # objective
    k = cfg.num_class if cfg.objective in ("multiclass", "softmax", "multiclassova") else 1
    # lambdarank group index; 1-length dummy otherwise (it would replicate at
    # GLOBAL length onto every device in multi-process mode)
    gidx_arr = (np.zeros(1, np.int32) if multiproc else jnp.zeros(1, jnp.int32))
    if cfg.objective == "lambdarank":
        if group_sizes is None:
            raise ValueError("lambdarank requires group_sizes")
        if cfg.label_gain:
            max_label = int(np.max(y)) if len(y) else 0
            if max_label >= len(cfg.label_gain):
                # LightGBM fails fast here too ("Label ... is not less than
                # the number of label gains") — silent clipping would
                # optimize the wrong objective
                raise ValueError(
                    f"label {max_label} needs a label_gain table of at "
                    f"least {max_label + 1} entries, got "
                    f"{len(cfg.label_gain)}")
        gidx = make_grouped(y, group_sizes)
        gidx_arr = jnp.asarray(gidx)
        obj = lambdarank_objective(gidx_arr, cfg.sigmoid,
                                   cfg.lambdarank_truncation_level,
                                   cfg.label_gain)
    else:
        obj = get_objective(cfg.objective, num_class=k, sigmoid=cfg.sigmoid,
                            alpha=cfg.alpha, fair_c=cfg.fair_c,
                            poisson_max_delta_step=cfg.poisson_max_delta_step,
                            tweedie_variance_power=cfg.tweedie_variance_power)

    if ((cfg.pos_bagging_fraction < 1.0 or cfg.neg_bagging_fraction < 1.0)
            and cfg.objective not in ("binary",)):
        # native LightGBM rejects stratified bagging for non-binary objectives
        raise ValueError("pos_bagging_fraction / neg_bagging_fraction require "
                         f"objective='binary' (got {cfg.objective!r})")
    if cfg.boosting_type == "rf" and not (cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0
                                          or cfg.feature_fraction < 1.0):
        # native LightGBM rejects the same degenerate config (identical trees)
        raise ValueError("boosting_type='rf' requires bagging (bagging_freq > 0 and "
                         "bagging_fraction < 1) and/or feature_fraction < 1")

    if multiproc:
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import to_global_rows

        yj = to_global_rows(mesh, P(_DA), y)
        wj = to_global_rows(mesh, P(_DA), w)
        valid_mask = to_global_rows(mesh, P(_DA), valid_mask_np)
        if cfg.boost_from_average:
            # base score from GLOBAL label stats: jit over the sharded labels
            # inserts the cross-process reductions (one-shot per fit, so the
            # throwaway jit wrapper is deliberate)
            base_g = jax.jit(obj.init_score,  # lint-ok: recompile
                             out_shardings=NamedSharding(mesh, P()))(yj, wj)
            base = np.atleast_1d(np.asarray(jax.device_get(base_g), np.float64))
        else:
            base = np.zeros(max(k, 1))
        local_margin = (np.zeros((len(y), k), np.float32)
                        + base[None, :k].astype(np.float32))
        score = to_global_rows(mesh, P(_DA, None), local_margin)
    else:
        yj, wj = jnp.asarray(y), jnp.asarray(w)
        valid_mask = jnp.asarray(valid_mask_np)
        base = (np.atleast_1d(np.asarray(obj.init_score(yj, wj), np.float64))
                if cfg.boost_from_average else np.zeros(max(k, 1)))
        # the fixed margin every iteration starts from: base score + init_score
        init_margin = jnp.zeros((n, k)) + jnp.asarray(base[None, :k], jnp.float32)
        if init_score is not None:
            init_margin = init_margin + jnp.asarray(
                np.asarray(init_score).reshape(n, -1), jnp.float32)
        score = init_margin
        if mesh is not None:
            score = jax.device_put(score, row2)
            yj = jax.device_put(yj, row1)
            wj = jax.device_put(wj, row1)
            valid_mask = jax.device_put(valid_mask, row1)

    trees: List[TreeArrays] = []
    tree_weights: List[float] = []
    # dart only: per-tree train contribution, stored as (class, (N,) values)
    tree_contribs: List[tuple] = []
    # warm start: the continued model bins against a NEW mapper, so the init
    # trees' real-valued thresholds / missing codes must be resolved against
    # the INIT model's own mapper (or its parsed values) and carried verbatim;
    # newly grown trees get None slots (= resolve from the training mapper)
    init_thresholds: Optional[List] = None
    init_mtypes: Optional[List] = None
    if init_model is not None:
        trees = list(init_model.trees)
        tree_weights = list(init_model.tree_weights)
        base = init_model.base_score
        prior_k = init_model.models_per_iter
        init_thresholds = [init_model._thresholds(i)
                           for i in range(len(trees))]
        init_mtypes = [init_model._missing_types(i)
                       for i in range(len(trees))]
        score = jnp.asarray(
            init_model.raw_score(X, start_iteration=0).reshape(n, k),
            jnp.float32)
        init_margin = jnp.zeros((n, k)) + jnp.asarray(
            init_model.base_score[None, :k], jnp.float32)
        if init_score is not None:
            extra = jnp.asarray(np.asarray(init_score).reshape(n, -1), jnp.float32)
            score = score + extra
            init_margin = init_margin + extra
        if cfg.boosting_type == "dart":
            # warm-started DART needs per-tree contributions of the PRIOR trees
            # too (they are drop candidates); recover them by raw traversal with
            # weights divided back out
            from .grower import forest_predict as _fp

            unweighted = init_model.unweighted()
            uf = unweighted.forest()
            per_tree = np.asarray(_fp(uf, jnp.asarray(X), output="per_tree",
                                      depth=unweighted._depth_cache))  # (N, T)
            for ti in range(per_tree.shape[1]):
                tree_contribs.append((ti % prior_k, per_tree[:, ti].astype(np.float32)))
    n_init_trees = len(trees)

    # tree_learner routing happens BEFORE the grower config is derived: the
    # resolved learner decides the grower's reduction strategy (feature-
    # parallel = owned-feature reduce-scatter). The resolved value lands on
    # cfg for provenance (as the old cost-model block did) and the router's
    # inputs/decision land in Booster.metadata["routing"].
    has_cat = bool(mapper.is_categorical.any())
    # decision provenance for the learned auto-configuration layer
    # (core/perfmodel): every model-made choice — and every fallback — is
    # auditable from Booster.metadata["autoconfig"]
    autoconfig_info = dict(getattr(cfg, "_autoconfig", None) or {})
    _fit_t0 = _time.perf_counter()
    if cfg.hist_allreduce_dtype == "auto":
        from .grower import resolve_wire_dtype

        wd, wdec = resolve_wire_dtype(cfg, mesh, n, nfeat)
        cfg.hist_allreduce_dtype = wd
        autoconfig_info["wire_dtype"] = wdec.provenance()
    routing_info = None
    if cfg.tree_learner == "auto":
        choice, routing_info = _auto_route(cfg, mesh, binned, nfeat, n,
                                           multiproc, has_cat)
        cfg.tree_learner = choice
    feature_shards = 1
    if cfg.tree_learner == "feature" and mesh is not None:
        from ..parallel.mesh import DATA_AXIS as _DAf

        feature_shards = int(dict(mesh.shape).get(_DAf, 1))
        from ..ops.hist_kernel import features_padded as _fpad

        if feature_shards > 1 and _fpad(nfeat) % feature_shards:
            # an elastic shrink/regrow can change the data-axis size after a
            # previous call routed this cfg to feature-parallel; the owned-
            # feature scatter needs the padded feature count to divide evenly,
            # so degrade to data-parallel histograms rather than raising at
            # trace time mid-restart
            import warnings

            warnings.warn(
                f"tree_learner='feature': features_padded({nfeat})="
                f"{_fpad(nfeat)} is not divisible by the {feature_shards}-way "
                f"data axis of this mesh; falling back to data-parallel "
                f"histograms")
            cfg.tree_learner = "data"
            feature_shards = 1
            if routing_info is not None:
                routing_info = dict(routing_info, tree_learner="data",
                                    fallback="feature_shards_indivisible")
    grower_cfg = cfg.grower(has_categorical=has_cat,
                            feature_shards=feature_shards)
    _wrap = np.asarray if multiproc else jnp.asarray
    is_cat = _wrap(mapper.is_categorical)
    nan_bins = _wrap(np.asarray(mapper.nan_bins, np.int32))
    # static per-feature DISTINCT category counts drive the one-vs-rest
    # decision (sparse id encodings make num_bins an overcount; fall back to
    # it for mappers predating cat_counts)
    _cc = (np.asarray(mapper.cat_counts, np.int32)
           if getattr(mapper, "cat_counts", None) is not None
           else np.asarray(mapper.num_bins, np.int32) - 1)
    cat_nbins = _wrap(np.where(np.asarray(mapper.is_categorical), _cc,
                               np.int32(0x7FFF)))
    mono = np.zeros(nfeat, np.int32)
    if cfg.monotone_constraints is not None:
        mc = np.asarray(cfg.monotone_constraints, np.int32)
        mono[: len(mc)] = mc
    mono = _wrap(mono)

    grow_fn = _make_grow_fn(grower_cfg, mesh)

    # validation state
    has_valid = valid is not None
    if has_valid:
        Xv = np.asarray(_densify(valid[0]), np.float32)
        yv = np.asarray(valid[1], np.float32)
        binned_v = apply_bins(mapper, Xv)
        score_v = jnp.zeros((Xv.shape[0], k)) + jnp.asarray(base[None, :k], jnp.float32)
        if init_model is not None:
            score_v = jnp.asarray(
                init_model.raw_score(Xv, start_iteration=0).reshape(
                    Xv.shape[0], k), jnp.float32)
        metric_name = cfg.metric or _default_metric(cfg.objective)
        if metric_name in ("ndcg", "map") or (
                cfg.metric is None and metric_name.startswith("ndcg")):
            # evalAt (LightGBMRankerParams, default 1-5) sets the ndcg/map
            # eval positions; early stopping tracks the FIRST position,
            # matching the reference. Engine-level configs that never set
            # eval_at keep the max_position behavior.
            first_at = (cfg.eval_at[0] if cfg.eval_at else cfg.max_position)
            metric_name = f"{metric_name.split('@')[0]}@{int(first_at)}"
        best_metric, best_iter = None, -1
        higher_better = metric_name.split("@")[0] in HIGHER_IS_BETTER
        # dart/rf: per-tree validation contributions (weights change later)
        valid_contribs: List[tuple] = []
        if init_model is not None and cfg.boosting_type in ("dart", "rf"):
            unw = init_model.unweighted()
            uf_v = unw.forest()
            pt_v = forest_predict(uf_v, jnp.asarray(Xv), output="per_tree",
                                  depth=unw._depth_cache)   # (Nv, T)
            pk = init_model.models_per_iter
            for ti in range(pt_v.shape[1]):
                valid_contribs.append((ti % pk, pt_v[:, ti]))

    gh_fn = fobj if fobj is not None else obj.grad_hess
    rf_mode, dart_mode, goss_mode = (cfg.boosting_type == "rf", cfg.boosting_type == "dart",
                                     cfg.boosting_type == "goss")
    if multiproc:
        from jax.sharding import PartitionSpec as P
        from ..parallel.mesh import DATA_AXIS as _DA2

        from ..parallel.mesh import to_global_rows as _tgr

        in_bag_cur = _tgr(
            mesh, P(_DA2), np.ones(n // jax.process_count(), np.float32))
    else:
        in_bag_cur = jnp.ones(n, jnp.float32)

    # ------------------------------------------------------------------
    # Fused fast path: the WHOLE boosting loop is one lax.scan under one
    # jit — a single device dispatch for all iterations. The reference's
    # loop is one LGBM_BoosterUpdateOneIter native call per iteration
    # (TrainUtils.scala:98-135); on TPU (especially through a remote
    # tunnel, ~15ms per dispatch) the fused program is essential.
    # dart / custom fobj / callbacks / warm start keep the host loop.
    # ------------------------------------------------------------------
    fused = (fobj is None and not callbacks and init_model is None
             and cfg.boosting_type in ("gbdt", "goss", "rf")
             and cfg.tree_learner != "voting")

    # per-iteration sampling — ONE device-side implementation shared by the
    # fused scan and the host loop (GOSS top-|g| + amplified rest; bagging;
    # feature_fraction), all keyed off fold_in(seed, it) so both paths sample
    # identically
    key0 = jax.random.PRNGKey(cfg.seed)
    if multiproc:
        # raw key data (identical host value on every process -> replicated);
        # run_scan re-wraps it into a typed key
        key0 = np.asarray(jax.random.key_data(key0))

    def sample_rows(it, g, h, in_bag_cur):
        return _sample_rows_impl(cfg, n, key0, valid_mask, it, g, h,
                                 in_bag_cur, yj)

    def sample_features(it):
        return _sample_features_impl(cfg, nfeat, key0, it)

    if fused:
        T = cfg.num_iterations
        nv = Xv.shape[0] if has_valid else 0
        run_scan = _get_fused_runner(cfg, grower_cfg, n, nfeat, k, nv,
                                     metric_name if has_valid else "", mesh)
        base_k = _wrap(np.asarray(base[:k], np.float32))
        if has_valid:
            yv_j = jnp.asarray(yv)
            if _is_rank_metric(metric_name):
                if len(valid) < 4:
                    raise ValueError("ranking validation requires "
                                     "valid=(Xv, yv, wv_or_None, group_sizes_v)")
                gidx_v = jnp.asarray(make_grouped(yv, valid[3]))
            else:
                gidx_v = jnp.zeros(nv, jnp.int32)
            # validation sample weights (valid[2]) weight the POINTWISE
            # eval metrics, as in LightGBM (ndcg/map stay per-query
            # unweighted here); absent -> uniform
            wv_raw = valid[2] if len(valid) > 2 else None
            wv_j = (jnp.asarray(np.asarray(wv_raw, np.float32))
                    if wv_raw is not None else jnp.ones(nv, jnp.float32))
            bv_arg = binned_v
        else:
            zeros = np.zeros if multiproc else jnp.zeros
            yv_j = zeros(1, np.float32)
            wv_j = zeros(1, np.float32)
            gidx_v = zeros(1, np.int32)
            bv_arg = zeros((1, nfeat), binned.dtype)

        score_v0 = (score_v if has_valid
                    else (np.zeros((1, k), np.float32) if multiproc
                          else jnp.zeros((1, k))))

        # With early stopping the scan runs in chunks with a host-side stop
        # check between them, so a run that converges at iteration 40 does
        # not burn the full num_iterations on device.
        chunk = T
        if has_valid and cfg.early_stopping_round > 0:
            chunk = min(T, max(2 * cfg.early_stopping_round, 16))
        carry = (score, in_bag_cur, score_v0)
        mvals_list = []
        done = 0
        if ckpt_store is not None:
            from ..core.checkpoint import preemption_point

            # snapshot boundaries must fall on chunk boundaries (the carry is
            # only exact between scan invocations)
            chunk = min(chunk, max(1, checkpoint_every))
            n_fp, y_fp = _elastic_label_identity(y, n_orig, multiproc)
            fingerprint = _train_fingerprint(cfg, n_fp, nfeat, y_fp,
                                             n_init_trees)
            state = _ckpt_load_gbdt(ckpt_store, fingerprint, "fused") \
                if resume else None
            if state is not None:
                done = int(state["iteration"])
                trees = list(state["trees"])
                tree_weights = list(state["tree_weights"])
                mvals_list = [np.asarray(m) for m in state["mvals"]]
                carry = _place_gbdt_carry(
                    state["carry"], n, n_orig, mesh, multiproc,
                    row2 if mesh is not None else None,
                    row1 if mesh is not None else None, score_v0)
        with measures.span("trainingIterations"):
            wd = current_watchdog()
            while done < T:
                if ckpt_store is not None:
                    preemption_point("gbdt.chunk", done)
                if wd is not None:
                    wd.beat("gbdt.chunk", done)
                c = min(chunk, T - done)

                def _run_chunk(_d=done, _c=c):
                    cc, (st, mv_) = run_scan(
                        binned, yj, wj, valid_mask, key0, is_cat, mono,
                        nan_bins, cat_nbins, base_k, gidx_arr, bv_arg, yv_j,
                        wv_j, gidx_v, *carry, _d, _c)
                    # device_get INSIDE the guard: this transfer is the host
                    # sync point where a hung peer's psum would stall forever
                    return cc, (jax.device_get(st), mv_)

                if wd is not None:
                    carry, (stacked_trees, mv) = wd.run(
                        _run_chunk, op="gbdt.chunk")
                else:
                    carry, (stacked_trees, mv) = _run_chunk()
                for ti in range(c):
                    for cls in range(k):
                        trees.append(jax.tree.map(lambda a: a[ti, cls],
                                                  stacked_trees))
                        tree_weights.append(1.0)
                done += c
                stop = False
                if has_valid:
                    mvals_list.append(np.asarray(mv))
                    if cfg.early_stopping_round > 0:
                        series = np.concatenate(mvals_list)
                        series = series if higher_better else -series
                        b = _best_so_far(series, cfg.improvement_tolerance)
                        stop = (done - 1 - int(b[-1])
                                >= cfg.early_stopping_round)
                if ckpt_store is not None and (done >= T or not stop):
                    # pack is collective (all ranks); only rank 0 commits to
                    # the (shared) store — one writer, no torn races
                    carry_h = _pack_gbdt_carry(carry, n, n_orig, multiproc)
                    if not multiproc or jax.process_index() == 0:
                        _ckpt_save_gbdt(
                            ckpt_store, done,
                            {"iteration": done, "trees": trees,
                             "tree_weights": tree_weights,
                             "mvals": mvals_list, "carry": carry_h,
                             "n_orig": n_fp},
                            fingerprint, "fused", measures)
                if stop:
                    break
        score = carry[0]
        measures.count("iterations", done)

        best_iter = -1
        if has_valid:
            mvals = np.concatenate(mvals_list)
            tdone = len(mvals)
            series = mvals if higher_better else -mvals
            # earliest best index (LightGBM keeps the first best)
            bests = _best_so_far(series, cfg.improvement_tolerance)
            stop = tdone - 1
            if cfg.early_stopping_round > 0:
                waited = np.arange(tdone) - bests
                hit = np.nonzero(waited >= cfg.early_stopping_round)[0]
                if len(hit):
                    stop = int(hit[0])
            best_iter = int(bests[stop])
            best_metric = float(mvals[best_iter])
            if cfg.early_stopping_round > 0:
                cut = (best_iter + 1) * k
                trees = trees[:cut]
                tree_weights = tree_weights[:cut]

        trees = jax.device_get(trees)
        return Booster(mapper, cfg, trees, tree_weights, base, feature_names,
                       best_iteration=(best_iter if has_valid else -1),
                       best_score=(best_metric if has_valid else None),
                       metadata=_train_metadata(routing_info,
                                                autoconfig_info, _fit_t0))

    # validation weights converted to device ONCE (per-iteration eval would
    # otherwise redo the H2D transfer every round)
    wv_dev = None
    if has_valid and len(valid) > 2 and valid[2] is not None:
        wv_dev = jnp.asarray(np.asarray(valid[2], np.float32))
    start_it = 0
    if ckpt_store is not None:
        from ..core.checkpoint import CheckpointError, preemption_point

        # host path is single-process only; fingerprint + snapshots use the
        # original unpadded rows so a resume survives a mesh-shape change
        n_fp, y_fp = _elastic_label_identity(y, n_orig, False)
        fingerprint = _train_fingerprint(cfg, n_fp, nfeat, y_fp, n_init_trees)
        state = _ckpt_load_gbdt(ckpt_store, fingerprint, "host") \
            if resume else None
        if state is not None:
            start_it = int(state["iteration"])
            trees = list(state["trees"])
            tree_weights = list(state["tree_weights"])
            tree_contribs = [(c, jnp.asarray(_repad_rows(v, n)))
                             for c, v in state["tree_contribs"]]
            score = jnp.asarray(_repad_rows(state["score"], n))
            in_bag_cur = jnp.asarray(_repad_rows(state["in_bag_cur"], n))
            if mesh is not None:
                score = jax.device_put(score, row2)
                in_bag_cur = jax.device_put(in_bag_cur, row1)
            # dart's drop decisions come from this stateful host Generator;
            # restoring it is what makes the resumed drop sequence identical
            rng = state["rng"]
            if has_valid:
                sv = np.asarray(state["score_v"], np.float32)
                if sv.shape != tuple(np.shape(score_v)):
                    raise CheckpointError(
                        f"validation score shape changed {sv.shape} -> "
                        f"{tuple(np.shape(score_v))}; resume with the "
                        "original validation set (or pass resume=False)")
                score_v = jnp.asarray(sv)
                valid_contribs = list(state["valid_contribs"])
                best_metric = state["best_metric"]
                best_iter = int(state["best_iter"])
    wd = current_watchdog()
    for it in range(start_it, cfg.num_iterations):
        if ckpt_store is not None:
            preemption_point("gbdt.iteration", it)
        if wd is not None:
            wd.beat("gbdt.iteration", it)
        # ---- dart: drop trees and de-weight the score -------------------
        if dart_mode and trees:
            nt = len(trees)
            # sequence seeding gives independent streams per (drop_seed, it)
            drop_rng = (np.random.default_rng([cfg.drop_seed, it])
                        if cfg.drop_seed else rng)
            if drop_rng.random() >= cfg.skip_drop:
                if cfg.uniform_drop:
                    p = np.full(nt, cfg.drop_rate)
                else:
                    # weighted drop (LightGBM default): drop probability
                    # proportional to each tree's current weight, normalized
                    # so the expected drop count stays drop_rate * nt
                    w = np.asarray(tree_weights[:nt], np.float64)
                    p = np.minimum(cfg.drop_rate * w * nt / max(w.sum(), 1e-12),
                                   1.0)
                drop = np.nonzero(drop_rng.random(nt) < p)[0][: cfg.max_drop]
            else:
                drop = np.array([], np.int64)
            kdrop = len(drop)
            if kdrop:
                # device-side: sum the dropped trees' weighted contributions
                dropped = jnp.zeros((n, k), jnp.float32)
                for j in drop:
                    cls_j, vec = tree_contribs[j]
                    dropped = dropped.at[:, cls_j].add(tree_weights[j] * vec)
                score_it = score - dropped
            else:
                score_it = score
        else:
            score_it, drop, kdrop = score, None, 0

        g, h = gh_fn(score_it[:, 0] if k == 1 else score_it, yj, wj)
        g = jnp.reshape(g, (n, k))
        h = jnp.reshape(h, (n, k))

        # ---- row + feature sampling (shared device-side implementation) --
        in_bag, g, h, in_bag_cur = sample_rows(it, g, h, in_bag_cur)
        feat_mask = sample_features(it)

        # ---- grow K trees ----------------------------------------------
        new_weight = 1.0
        if dart_mode and kdrop:
            if cfg.xgboost_dart_mode:
                # leaf values already carry the learning rate (grower), so
                # the extra multiplier is 1/(k+lr): effective lr/(k+lr), the
                # DART-paper / LightGBM xgboost-mode weight
                new_weight = 1.0 / (kdrop + cfg.learning_rate)
            else:
                new_weight = 1.0 / (kdrop + 1.0)
        # voting-parallel: pick top-2k features per tree by shard votes, grow
        # on the sliced columns so in-loop histogram allreduce is O(top_k)
        # ("auto" resolved to a concrete learner before the fused-path
        # decision above)
        voting = (cfg.tree_learner == "voting" and mesh is not None
                  and nfeat > 2 * cfg.top_k)
        for cls in range(k):
            if voting:
                from .voting import remap_tree_features, voting_select

                sel_idx = voting_select(
                    binned, g[:, cls] * in_bag, h[:, cls] * in_bag, in_bag,
                    mesh, cfg.top_k, cfg.max_bin, cfg.lambda_l2,
                    max(cfg.min_data_in_leaf, 1), feature_active=feat_mask)
                sel_j = jnp.asarray(sel_idx)
                # bynode sampling applies WITHIN the vote winners (the
                # searchable subset — LightGBM ColSampler semantics)
                tree, node = grow_fn(
                    binned[:, sel_j], g[:, cls], h[:, cls], in_bag,
                    feat_mask[sel_j], is_cat[sel_j], mono[sel_j],
                    nan_bins[sel_j], _node_key_data(key0, it, cls),
                    cat_nbins[sel_j])
                tree = remap_tree_features(tree, sel_idx)
            else:
                tree, node = grow_fn(binned, g[:, cls], h[:, cls], in_bag,
                                     feat_mask, is_cat, mono, nan_bins,
                                     _node_key_data(key0, it, cls), cat_nbins)
            contrib = _leaf_gather(tree.leaf_value, node)          # (N,)
            if dart_mode:
                tree_contribs.append((cls, contrib))               # device-side
                if kdrop and cls == k - 1:
                    # dropped trees scaled by kdrop/(kdrop+1), then rebuild the
                    # score from the fixed init margin + all weighted per-tree
                    # contributions — one stacked matvec on device instead of a
                    # host numpy loop (VERDICT weak #7)
                    factor = (kdrop / (kdrop + cfg.learning_rate)
                              if cfg.xgboost_dart_mode
                              else kdrop / (kdrop + 1.0))
                    for j in drop:
                        tree_weights[j] *= factor
                    stack = jnp.stack([v for _, v in tree_contribs])  # (T, N)
                    # THIS iteration's k trees are appended below, after the
                    # rebuild: extend explicitly or the newest contributions
                    # gather stale (clamped) weights
                    wts_now = (tree_weights
                               + [new_weight] * (len(tree_contribs)
                                                 - len(tree_weights)))
                    wts = jnp.asarray(wts_now, jnp.float32)
                    cls_ids = np.asarray([c for c, _ in tree_contribs])
                    total = jnp.zeros((n, k))
                    for cj in range(k):
                        sel = np.nonzero(cls_ids == cj)[0]
                        if len(sel):
                            total = total.at[:, cj].set(
                                jnp.einsum("tn,t->n", stack[sel], wts[sel]))
                    score = init_margin + total
                elif not kdrop:
                    score = score.at[:, cls].add(contrib * new_weight)
            elif rf_mode:
                pass  # rf: gradients always from the base score; trees averaged at predict
            else:
                score = score.at[:, cls].add(contrib)
            # trees stay device-resident until fit ends (one host pull at the
            # end instead of one per iteration — VERDICT weak #7)
            trees.append(tree)
            tree_weights.append(new_weight)

            if has_valid:
                # streaming validation contribution for every mode; dart/rf
                # re-weight the stacked per-tree contributions below instead
                # of re-scoring the whole forest per iteration (the former
                # O(T^2) full rebuild — VERDICT weak #7)
                leaf_v = _tree_assign_binned(trees[-1], binned_v, nan_bins)
                contrib_v = jnp.asarray(trees[-1].leaf_value)[leaf_v]
                if rf_mode or dart_mode:
                    valid_contribs.append((cls, contrib_v))
                else:
                    score_v = score_v.at[:, cls].add(contrib_v * new_weight)

        # ---- validation metric / early stopping ------------------------
        if has_valid:
            if rf_mode or dart_mode:
                stack_v = jnp.stack([v for _, v in valid_contribs])  # (T, Nv)
                wts_v = jnp.asarray(tree_weights, jnp.float32)
                if rf_mode:
                    wts_v = wts_v / max(len(trees) // k, 1)
                cls_v = np.asarray([c for c, _ in valid_contribs])
                raw_v = jnp.zeros((stack_v.shape[1], k)) + jnp.asarray(
                    base[None, :k], jnp.float32)
                for cj in range(k):
                    sel = np.nonzero(cls_v == cj)[0]
                    if len(sel):
                        raw_v = raw_v.at[:, cj].add(
                            jnp.einsum("tn,t->n", stack_v[sel], wts_v[sel]))
            else:
                raw_v = score_v
            pred_v = obj.transform(raw_v[:, 0] if k == 1 else raw_v)
            mval = float(_eval_metric(metric_name, yv, pred_v, raw_v,
                                      valid, k, cfg, wv_dev))
            tol = cfg.improvement_tolerance
            improved = (best_metric is None
                        or (mval > best_metric + tol if higher_better
                            else mval < best_metric - tol))
            if improved:
                best_metric, best_iter = mval, it
            if cfg.early_stopping_round > 0 and it - best_iter >= cfg.early_stopping_round:
                # best_iter counts NEW iterations: keep every warm-start tree
                cut = n_init_trees + (best_iter + 1) * k
                trees = trees[:cut]
                tree_weights = tree_weights[:cut]
                break

        if callbacks:
            for cb in callbacks:
                cb(it, trees)

        if ckpt_store is not None and (it + 1) % checkpoint_every == 0:
            # per-row state trimmed to the original rows (mesh-independent;
            # see _pack_gbdt_carry for why dropping padding rows is exact)
            payload = {
                "iteration": it + 1,
                "trees": jax.device_get(trees),
                "tree_weights": list(tree_weights),
                "tree_contribs": [(c, np.asarray(jax.device_get(v))[:n_orig])
                                  for c, v in tree_contribs],
                "score": np.asarray(jax.device_get(score))[:n_orig],
                "in_bag_cur": np.asarray(jax.device_get(in_bag_cur))[:n_orig],
                "rng": rng,
                "n_orig": n_orig,
            }
            if has_valid:
                payload["score_v"] = np.asarray(jax.device_get(score_v))
                payload["valid_contribs"] = [
                    (c, np.asarray(jax.device_get(v)))
                    for c, v in valid_contribs]
                payload["best_metric"] = best_metric
                payload["best_iter"] = best_iter
            _ckpt_save_gbdt(ckpt_store, it + 1, payload, fingerprint, "host",
                            measures)

    # single batched device→host transfer of the whole forest (the per-tree
    # pulls were VERDICT weak #7)
    trees = jax.device_get(trees)
    merged_thr = merged_mt = None
    if init_thresholds is not None:
        # warm-start trees keep their origin-resolved thresholds/missing
        # codes; new trees (None slots) resolve from this training's mapper
        merged_thr = (init_thresholds
                      + [None] * (len(trees) - len(init_thresholds)))[
                          : len(trees)]
        merged_mt = (init_mtypes
                     + [None] * (len(trees) - len(init_mtypes)))[: len(trees)]
    # best_iter counts NEW iterations; best_iteration addresses the full
    # returned forest, so warm-start iterations offset it
    return Booster(mapper, cfg, trees, tree_weights, base, feature_names,
                   best_iteration=(n_init_trees // max(k, 1) + best_iter
                                   if has_valid else -1),
                   thresholds=merged_thr, missing_types=merged_mt,
                   best_score=(best_metric if has_valid else None),
                   metadata=_train_metadata(routing_info,
                                            autoconfig_info, _fit_t0))


def _train_fingerprint(cfg, n, nfeat, y, n_init_trees) -> str:
    """Identity of a training run for resume-compatibility: config + data
    shape + label digest + warm-start length. A snapshot whose fingerprint
    differs belongs to a DIFFERENT run and must not be resumed from."""
    import hashlib
    import zlib

    h = hashlib.sha256()
    h.update(repr(sorted(dataclasses.asdict(cfg).items())).encode())
    h.update(repr((int(n), int(nfeat), int(n_init_trees),
                   zlib.crc32(np.ascontiguousarray(
                       np.asarray(y, np.float32)).tobytes()))).encode())
    return h.hexdigest()


def _elastic_label_identity(y, n_orig, multiproc):
    """(global original row count, global original labels) for the resume
    fingerprint. Padded counts/labels are MESH-DEPENDENT (padding varies
    with the data-axis size), so hashing them would pin a snapshot to one
    mesh shape and block the elastic shrink/regrow resume path
    (parallel/elastic.py); the original rows identify the run for any mesh.
    Collective in multi-process mode (label allgather — every rank calls)."""
    y_loc = np.ascontiguousarray(np.asarray(y, np.float32)[:n_orig])
    if not multiproc:
        return int(n_orig), y_loc
    from jax.experimental import multihost_utils

    # stacked (nproc, n_orig) -> rank-order concat == global row order,
    # because to_global_rows lays process blocks contiguously
    g = np.asarray(multihost_utils.process_allgather(y_loc))
    return int(n_orig) * jax.process_count(), g.reshape(-1)


def _repad_rows(a, n):
    """Zero-pad trimmed per-row snapshot state back to THIS run's padded row
    count. Exact, not approximate: padding rows carry in_bag=0 / weight 0,
    so their (discarded) evolved values never touched a histogram or leaf
    stat and zeros are indistinguishable going forward."""
    from ..core.checkpoint import CheckpointError

    a = np.asarray(a, np.float32)
    if a.shape[0] > n:
        raise CheckpointError(
            f"snapshot has {a.shape[0]} rows but this run has {n}; the "
            "snapshot belongs to different data")
    if a.shape[0] == n:
        return a
    pad = np.zeros((n - a.shape[0],) + a.shape[1:], np.float32)
    return np.concatenate([a, pad])


def _pack_gbdt_carry(carry, n, n_orig, multiproc):
    """Host snapshot of the fused-scan carry trimmed to the ORIGINAL rows in
    global row order — mesh-independent, so a shrunken/regrown mesh can
    restore it (_place_gbdt_carry re-pads for the new layout). Collective in
    multi-process mode: the host_copy allgather runs on EVERY rank even
    though only rank 0 commits the resulting checkpoint."""
    score, in_bag, score_v = carry
    if multiproc:
        from ..parallel.mesh import host_copy

        nproc = jax.process_count()
        blk = n // nproc                    # padded rows per process block
        keep = np.concatenate([np.arange(p * blk, p * blk + n_orig)
                               for p in range(nproc)])
        score = np.asarray(host_copy(score))[keep]
        in_bag = np.asarray(host_copy(in_bag))[keep]
        if isinstance(score_v, jax.Array) and not (
                score_v.is_fully_addressable or score_v.is_fully_replicated):
            score_v = host_copy(score_v)
    else:
        score = np.asarray(jax.device_get(score))[:n_orig]
        in_bag = np.asarray(jax.device_get(in_bag))[:n_orig]
    return score, in_bag, np.asarray(jax.device_get(score_v))


def _place_gbdt_carry(saved, n, n_orig, mesh, multiproc, row2, row1,
                      score_v_like):
    """Inverse of _pack_gbdt_carry: zero-pad the trimmed carry back to THIS
    run's padded row count and place it on THIS run's mesh. A resume across
    a different mesh shape therefore converges to the same model as the
    uninterrupted run, and a same-shape resume stays bit-for-bit (trees
    never read padded-row state)."""
    from ..core.checkpoint import CheckpointError

    sc = np.asarray(saved[0], np.float32)
    ib = np.asarray(saved[1], np.float32)
    sv = np.asarray(saved[2], np.float32)
    if sv.shape != tuple(np.shape(score_v_like)):
        raise CheckpointError(
            f"validation score shape changed {sv.shape} -> "
            f"{tuple(np.shape(score_v_like))}; resume with the original "
            "validation set (or pass resume=False)")
    if multiproc:
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import DATA_AXIS as _DA
        from ..parallel.mesh import to_global_rows

        nproc, rank = jax.process_count(), jax.process_index()
        if sc.shape[0] != n_orig * nproc:
            raise CheckpointError(
                f"snapshot has {sc.shape[0]} rows but this run has "
                f"{n_orig * nproc} original rows; different data")
        blk = n // nproc

        def _mine(a):
            loc = a[rank * n_orig:(rank + 1) * n_orig]
            pad = np.zeros((blk - n_orig,) + a.shape[1:], np.float32)
            return np.concatenate([loc, pad])

        score = to_global_rows(mesh, P(_DA, None), _mine(sc))
        in_bag = to_global_rows(mesh, P(_DA), _mine(ib))
        return score, in_bag, sv        # multiproc keeps host-side score_v
    sc, ib = _repad_rows(sc, n), _repad_rows(ib, n)
    score, in_bag = jnp.asarray(sc), jnp.asarray(ib)
    if mesh is not None:
        score = jax.device_put(score, row2)
        in_bag = jax.device_put(in_bag, row1)
    return score, in_bag, jnp.asarray(sv)


def _ckpt_save_gbdt(store, iteration, payload, fingerprint, path, measures):
    import pickle

    with measures.span("checkpointSave"):
        store.save(int(iteration),
                   {"state.pkl": pickle.dumps(payload, protocol=4)},
                   meta={"kind": "gbdt", "path": path,
                         "fingerprint": fingerprint})


def _ckpt_load_gbdt(store, fingerprint, path):
    """Newest verified snapshot matching this run, or None (fresh start)."""
    import pickle

    from ..core.logging import record_failure

    ckpt = store.load_latest()
    if ckpt is None:
        return None
    if (ckpt.meta.get("kind") != "gbdt" or ckpt.meta.get("path") != path
            or ckpt.meta.get("fingerprint") != fingerprint):
        record_failure("checkpoint.fingerprint_mismatch", base=ckpt.base,
                       ckpt_kind=ckpt.meta.get("kind"))
        return None
    return pickle.loads(ckpt.artifacts["state.pkl"])


def _best_so_far(series: np.ndarray, tol: float = 0.0) -> np.ndarray:
    """bests[i] = index of the best value within series[:i+1], where a new
    best must beat the incumbent by MORE than ``tol`` (improvementTolerance;
    series is pre-negated for lower-is-better metrics). LightGBM keeps the
    FIRST best on exact ties."""
    bests = np.zeros(len(series), np.int64)
    best, bi = -np.inf, 0
    for i, v in enumerate(series):
        if v > best + tol:
            best, bi = float(v), i
        bests[i] = bi
    return bests


def _is_rank_metric(name: str) -> bool:
    """ndcg/ndcg@k/map/map@k — NOT mape (startswith would match it)."""
    return name.split("@")[0] in ("ndcg", "map")


def _default_metric(objective: str) -> str:
    return {
        "binary": "auc",
        "multiclass": "multi_logloss",
        "softmax": "multi_logloss",
        "multiclassova": "multi_logloss",
        "regression_l1": "mae",
        "lambdarank": "ndcg@5",
        # exp-family / robust objectives early-stop on their OWN loss
        # (LightGBM's default metric = the objective)
        "poisson": "poisson",
        "gamma": "gamma",
        "tweedie": "tweedie",
        "quantile": "quantile",
        "huber": "huber",
        "fair": "fair",
        "mape": "mape",
        "cross_entropy": "cross_entropy",
        "xentropy": "cross_entropy",
    }.get(objective, "rmse")


def _eval_metric(name, yv, pred_v, raw_v, valid, k, cfg=None, wv=None):
    if _is_rank_metric(name):
        at = int(name.split("@")[1]) if "@" in name else 5
        if len(valid) < 4:
            raise ValueError(
                "ranking validation requires valid=(Xv, yv, wv_or_None, group_sizes_v)")
        gidx = make_grouped(yv, valid[3])
        if name.startswith("map"):
            return map_at_k(jnp.asarray(yv), raw_v[:, 0], jnp.asarray(gidx),
                            at)
        return ndcg_at_k(jnp.asarray(yv), raw_v[:, 0], jnp.asarray(gidx), at,
                         cfg.label_gain if cfg is not None else ())
    fn = METRICS[name]
    return fn(jnp.asarray(yv), pred_v, weight=wv, **metric_kwargs(cfg))
