"""TreeSHAP feature contributions.

Parity target: the reference's ``featuresShap`` output (predict_contrib through
LGBM_BoosterPredictForMat, booster/LightGBMBooster.scala:424-432 and
LightGBMModelMethods.scala getFeatureShaps). Implements the polynomial-time
TreeSHAP recursion (Lundberg & Lee, "Consistent Individualized Feature
Attribution for Tree Ensembles") host-side in numpy; trees are small so the
recursion cost is negligible next to device work. Returns (N, F+1) —
per-feature contributions plus the expected value in the last column — or
(N, K*(F+1)) per-class blocks for multiclass: LightGBM's
predict(pred_contrib=True) layout.
"""

from __future__ import annotations

import numpy as np


class _Path:
    """Decomposed path state: parallel arrays over path elements."""

    __slots__ = ("feat", "zero", "one", "w")

    def __init__(self, capacity: int):
        self.feat = np.full(capacity, -1, np.int64)
        self.zero = np.zeros(capacity)
        self.one = np.zeros(capacity)
        self.w = np.zeros(capacity)

    def copy(self, depth: int) -> "_Path":
        p = _Path(len(self.feat))
        p.feat[: depth + 1] = self.feat[: depth + 1]
        p.zero[: depth + 1] = self.zero[: depth + 1]
        p.one[: depth + 1] = self.one[: depth + 1]
        p.w[: depth + 1] = self.w[: depth + 1]
        return p


def _extend(p: _Path, depth: int, pz: float, po: float, pi: int) -> None:
    p.feat[depth] = pi
    p.zero[depth] = pz
    p.one[depth] = po
    p.w[depth] = 1.0 if depth == 0 else 0.0
    for i in range(depth - 1, -1, -1):
        p.w[i + 1] += po * p.w[i] * (i + 1) / (depth + 1)
        p.w[i] = pz * p.w[i] * (depth - i) / (depth + 1)


def _unwind(p: _Path, depth: int, idx: int) -> None:
    one, zero = p.one[idx], p.zero[idx]
    nxt = p.w[depth]
    for i in range(depth - 1, -1, -1):
        if one != 0:
            tmp = p.w[i]
            p.w[i] = nxt * (depth + 1) / ((i + 1) * one)
            nxt = tmp - p.w[i] * zero * (depth - i) / (depth + 1)
        else:
            p.w[i] = p.w[i] * (depth + 1) / (zero * (depth - i))
    for i in range(idx, depth):
        p.feat[i] = p.feat[i + 1]
        p.zero[i] = p.zero[i + 1]
        p.one[i] = p.one[i + 1]


def _unwound_sum(p: _Path, depth: int, idx: int) -> float:
    one, zero = p.one[idx], p.zero[idx]
    nxt = p.w[depth]
    total = 0.0
    for i in range(depth - 1, -1, -1):
        if one != 0:
            tmp = nxt * (depth + 1) / ((i + 1) * one)
            total += tmp
            nxt = p.w[i] - tmp * zero * (depth - i) / (depth + 1)
        else:
            total += p.w[i] * (depth + 1) / (zero * (depth - i))
    return total


def _shap_recurse(tree, x, phi, node, depth, path: _Path, pz, po, pi):
    path = path.copy(depth - 1 if depth > 0 else 0)
    _extend(path, depth, pz, po, pi)
    if node < 0:  # leaf
        leaf_val = tree["lv"][~node]
        for i in range(1, depth + 1):
            w = _unwound_sum(path, depth, i)
            phi[path.feat[i]] += w * (path.one[i] - path.zero[i]) * leaf_val
        return
    f = int(tree["sf"][node])
    # the HOT child must be the one the PREDICTION path takes, including
    # LightGBM's missing routing (grower._descend semantics) — otherwise
    # contributions on NaN/zero-missing rows stop summing to raw_score
    mt = int(tree["mt"][node])
    if tree["stype"][node] == 1:
        xv = x[f]
        # identical conversion to grower._descend: NaN -> 0 unless mt=nan
        # (-1 there), then clip into [-1, last tracked bit] and truncate —
        # so -0.5 tests category 0 and out-of-range/inf tests the last bit,
        # exactly as the prediction path does
        cf = (0.0 if mt != 2 else -1.0) if np.isnan(xv) else xv
        c = int(np.clip(cf, -1, tree["bits"].shape[1] * 32 - 1))
        in_set = (c >= 0 and
                  bool((tree["bits"][node, c >> 5] >> (c & 31)) & 1))
        hot, cold = ((tree["lc"][node], tree["rc"][node]) if in_set
                     else (tree["rc"][node], tree["lc"][node]))
    else:
        xv = x[f]
        isnan = np.isnan(xv)
        if isnan and mt != 2:
            xv = 0.0                        # NaN coerces unless mt=nan
        missing = ((mt == 1 and abs(xv) <= 1e-35)
                   or (mt == 2 and isnan))
        go_left = bool(tree["dleft"][node]) if missing \
            else bool(xv <= tree["thr"][node])
        hot, cold = ((tree["lc"][node], tree["rc"][node]) if go_left
                     else (tree["rc"][node], tree["lc"][node]))

    def cover(nd):
        return tree["leaf_cover"][~nd] if nd < 0 else tree["cover"][nd]

    iz, io = 1.0, 1.0
    found = -1
    for i in range(1, depth + 1):
        if path.feat[i] == f:
            found = i
            break
    if found >= 0:
        iz, io = path.zero[found], path.one[found]
        _unwind(path, depth, found)
        depth -= 1
    hz = cover(hot) / tree["cover"][node]
    cz = cover(cold) / tree["cover"][node]
    _shap_recurse(tree, x, phi, hot, depth + 1, path, iz * hz, io, f)
    _shap_recurse(tree, x, phi, cold, depth + 1, path, iz * cz, 0.0, f)


def forest_shap(booster, X: np.ndarray) -> np.ndarray:
    """(N, F+1) contributions, or (N, K*(F+1)) for multiclass — per-class
    blocks of [per-feature..., expected_value], LightGBM's
    predict(pred_contrib=True) layout."""
    n, nfeat = X.shape
    k = booster.models_per_iter
    out = np.zeros((n, k, nfeat + 1), np.float64)
    out[:, :, -1] += booster.base_score[None, :k]

    start = max(int(getattr(booster.config, "start_iteration", 0)), 0) * k
    weights = np.asarray(booster.tree_weights, np.float64)
    if booster.average_output:
        # the served prediction averages over the WINDOWED trees (raw_score's
        # rescale), so contributions must use the same divisor
        weights = weights / max((len(booster.trees) - start) // k, 1)

    for ti, t in enumerate(booster.trees):
        if ti < start:
            continue        # pred_contrib honors the prediction window
        cls = ti % k
        ns = int(t.num_splits)
        nleaves = ns + 1
        lv = np.asarray(t.leaf_value, np.float64)[:nleaves] * weights[ti]
        if ns == 0:
            out[:, cls, -1] += lv[0]
            continue
        leaf_cover = np.maximum(np.asarray(t.leaf_count, np.float64)[:nleaves], 1.0)
        tree = {
            "sf": np.asarray(t.split_feature)[:ns],
            "thr": booster._thresholds(ti)[:ns].astype(np.float64),
            "lc": np.asarray(t.left_child)[:ns],
            "rc": np.asarray(t.right_child)[:ns],
            "lv": lv,
            "cover": np.maximum(np.asarray(t.internal_count, np.float64)[:ns], 1.0),
            "leaf_cover": leaf_cover,
            "stype": np.asarray(t.split_type)[:ns],
            "bits": np.asarray(t.cat_bitset)[:ns],
            "dleft": np.asarray(t.default_left)[:ns],
            "mt": booster._missing_types(ti)[:ns],
        }
        ev = float((lv * leaf_cover).sum() / leaf_cover.sum())
        out[:, cls, -1] += ev
        cap = ns + 3
        for r in range(n):
            phi = np.zeros(nfeat + 1)
            _shap_recurse(tree, X[r].astype(np.float64), phi, 0, 0, _Path(cap), 1.0, 1.0, -1)
            out[r, cls, :nfeat] += phi[:nfeat]
    return out[:, 0, :] if k == 1 else out.reshape(n, k * (nfeat + 1))
