"""Pre-binned training data — the LightGBM ``Dataset`` concept on TPU.

LightGBM separates dataset construction (``LGBM_DatasetCreateFromMat`` —
quantile binning, the expensive O(N·F·log B) pass) from training
(``LGBM_BoosterUpdateOneIter``); the reference builds the dataset once per
fit and benchmarks only the iteration loop (SURVEY §3.1; reference
dataset/DatasetUtils.scala + LightGBMBase.scala:509-550 do exactly this
split). ``Dataset`` is that same separation TPU-side: binning runs once on
device at construction, the quantized (N, F) uint8/uint16 matrix stays
HBM-resident, and every subsequent ``train_booster(dataset, ...)`` call
skips quantization AND the host→device transfer of the raw floats — which
matters doubly when the chip sits behind a network tunnel.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..ops.quantize import BinMapper, apply_bins, compute_bin_mapper


def _is_sparse(X) -> bool:
    return hasattr(X, "tocsr") and hasattr(X, "nnz")


def bin_sparse(X_csr, mapper: BinMapper, max_bin: int,
               bin_sample_count: int, categorical_features, seed: int,
               chunk_rows: int = 65_536, min_data_in_bin: int = 3,
               max_bin_by_feature=None):
    """Bin a scipy CSR matrix chunk-wise (the reference's sparse dataset path
    — BulkPartitionTask CSR push + isSparse election — re-shaped for TPU:
    sparse rows stream through host densification into the device-resident
    quantized matrix, which is uint8/16 and therefore 4-32x smaller than the
    dense floats the CSR avoided). Returns (mapper, binned_device)."""
    import jax.numpy as jnp

    X_csr = X_csr.tocsr()
    n, f = X_csr.shape
    if mapper is None:
        rng = np.random.default_rng(seed)
        take = (np.sort(rng.choice(n, size=bin_sample_count, replace=False))
                if n > bin_sample_count else np.arange(n))
        sample = np.asarray(X_csr[take].todense(), np.float32)
        # NaN-bin election must see the FULL matrix (a NaN only in unsampled
        # rows still needs its dedicated bin); explicit CSR entries carry all
        # NaNs — implicit zeros are never NaN
        nan_mask = np.isnan(X_csr.data)
        has_nan = np.zeros(f, bool)
        if nan_mask.any():
            has_nan[np.unique(X_csr.indices[nan_mask])] = True
        # categorical bin occupancy likewise from the FULL matrix (explicit
        # CSC entries per column + the implicit-zero bin), so the
        # maxCatToOnehot decision can't flip with the sampling seed
        cat_presence = None
        if categorical_features:
            from ..ops.quantize import cat_presence_bitmap

            csc = X_csr.tocsc()
            cat_presence = np.zeros((f, max_bin), bool)
            for j in categorical_features:
                vals = csc.data[csc.indptr[j]: csc.indptr[j + 1]]
                cat_presence[j] = cat_presence_bitmap(vals, max_bin)
                if vals.size < n:          # at least one implicit zero
                    cat_presence[j, 0] = True
        mapper = compute_bin_mapper(sample, max_bin, bin_sample_count,
                                    categorical_features, seed,
                                    has_nan=has_nan,
                                    min_data_in_bin=min_data_in_bin,
                                    max_bin_by_feature=max_bin_by_feature,
                                    cat_presence=cat_presence)
    # Device-side sparse binning (VERDICT r2 #7): each chunk's binned matrix
    # starts as a broadcast of the per-feature zero-bin, then ONLY the nnz
    # entries' bins scatter in — O(F + nnz) work and O(nnz) host→device
    # bytes per chunk instead of the dense detour's O(rows·F), preserving
    # CSR's memory advantage through ingest. Chunk-local row ids come from
    # indptr diffs (cheap host O(nnz)).
    from ..ops.quantize import CsrBinner

    binner = CsrBinner(mapper)       # mapper state ships to device ONCE
    chunks = []
    indptr = X_csr.indptr
    for lo in range(0, n, chunk_rows):
        hi = min(lo + chunk_rows, n)
        s, e = int(indptr[lo]), int(indptr[hi])
        counts = np.diff(indptr[lo:hi + 1]).astype(np.int64)
        rows_local = np.repeat(np.arange(hi - lo, dtype=np.int32),
                               counts)
        chunks.append(binner(X_csr.data[s:e], rows_local,
                             X_csr.indices[s:e], hi - lo))
    return mapper, jnp.concatenate(chunks, axis=0)


class Dataset:
    """Bins ``X`` once (device-resident) for repeated training runs.

    Parameters mirror the binning-relevant subset of ``BoosterConfig``
    (max_bin / bin_sample_count / categorical_features / seed). ``label`` /
    ``weight`` / ``init_score`` / ``group_sizes`` ride along so a Dataset is
    a self-contained training input, as in LightGBM's Python API.
    """

    def __init__(
        self,
        X: np.ndarray,
        label: Optional[np.ndarray] = None,
        weight: Optional[np.ndarray] = None,
        init_score: Optional[np.ndarray] = None,
        group_sizes: Optional[np.ndarray] = None,
        categorical_features: Optional[Sequence[int]] = None,
        max_bin: int = 255,
        bin_sample_count: int = 200_000,
        seed: int = 0,
        mapper: Optional[BinMapper] = None,
        keep_raw: bool = True,
        min_data_in_bin: int = 3,
        max_bin_by_feature=None,
    ):
        self.min_data_in_bin = min_data_in_bin
        self.max_bin_by_feature = max_bin_by_feature
        # binning came entirely from a user mapper: the binning knobs above
        # were never used, so config mismatches against them are meaningless
        self._user_mapper = mapper is not None
        if _is_sparse(X):
            X = X.tocsr()                 # one conversion shared by all uses
            self.num_rows, self.num_features = X.shape
            if self.num_rows == 0:
                raise ValueError("Dataset requires a non-empty matrix")
            self.mapper, self.binned = bin_sparse(
                X, mapper, max_bin, bin_sample_count, categorical_features,
                seed, min_data_in_bin=min_data_in_bin,
                max_bin_by_feature=max_bin_by_feature)
            # raw sparse rows kept as-is (cheap); densified lazily by the few
            # paths that need raw floats (warm start / mesh padding)
            self._sparse = X if keep_raw else None
            self.X = None
        else:
            self._sparse = None
            X = np.asarray(X, np.float32)
            if X.ndim != 2 or X.shape[0] == 0:
                raise ValueError(
                    f"Dataset requires a non-empty 2-D matrix, got {X.shape}")
            self.num_rows, self.num_features = X.shape
            self.mapper = mapper if mapper is not None else compute_bin_mapper(
                X, max_bin, bin_sample_count, categorical_features, seed,
                min_data_in_bin=min_data_in_bin,
                max_bin_by_feature=max_bin_by_feature)
            self.binned = apply_bins(self.mapper, X)  # device (N, F) uint8/16
            # raw floats kept host-side for paths that need them (warm start /
            # mesh row padding); drop with keep_raw=False to halve host memory
            self.X = X if keep_raw else None
        self.label = None if label is None else np.asarray(label, np.float32)
        self.weight = None if weight is None else np.asarray(weight, np.float32)
        self.init_score = init_score
        self.group_sizes = group_sizes
        self.categorical_features = categorical_features

    @classmethod
    def from_batches(
        cls,
        batches,
        categorical_features: Optional[Sequence[int]] = None,
        max_bin: int = 255,
        bin_sample_count: int = 200_000,
        seed: int = 0,
        mapper: Optional[BinMapper] = None,
        min_data_in_bin: int = 3,
        max_bin_by_feature=None,
    ) -> "Dataset":
        """Bounded-memory construction from an ITERATOR of chunks — the
        streaming analog of ``Dataset(X, y)`` for data that never fits in
        memory as raw floats (the reference streams partition data into the
        native dataset the same way, LightGBMBase.scala:608-628 mapPartitions
        → chunked dataset appends).

        ``batches`` yields ``X_chunk`` or ``(X_chunk, y_chunk)`` or
        ``(X_chunk, y_chunk, w_chunk)``. Each chunk is binned to uint8 as it
        arrives and the raw floats are dropped; peak memory is
        O(bin_sample_count raw rows + total binned bytes), not O(N raw).

        When ``mapper`` is None the bin boundaries come from the FIRST
        ``bin_sample_count`` rows (a prefix sample — fine for shuffled
        streams; pass a mapper computed from a reservoir sample, as
        ``spark_adapter.dataset_from_spark`` does, when the stream is
        ordered). A NaN appearing in a feature AFTER the mapper was fixed
        without a missing bin raises loudly rather than silently clamping
        into a value bin. Ranking group sizes and init scores are not
        streamable here — build those datasets whole."""
        user_mapper = mapper is not None
        binned_parts: list = []
        y_parts: list = []
        w_parts: list = []
        raw_buf: list = []                  # raw chunks held pre-mapper only
        buffered = 0
        nan_seen = None                     # per-feature, across ALL chunks

        def _bin(Xb):
            # device-binned, pulled back to host uint8: accumulation stays
            # host-side so the device never holds parts + the final matrix
            binned_parts.append(np.asarray(apply_bins(mapper, Xb)))

        def _flush_raw():
            nonlocal buffered
            for Xb in raw_buf:
                _bin(Xb)
            raw_buf.clear()
            buffered = 0

        for batch in batches:
            if isinstance(batch, tuple):
                Xc, yc, wc = (batch + (None, None))[:3]
            else:
                Xc, yc, wc = batch, None, None
            Xc = np.asarray(Xc, np.float32)
            if Xc.ndim != 2:
                raise ValueError(f"chunk must be 2-D, got {Xc.shape}")
            chunk_nan = np.isnan(Xc).any(axis=0)
            nan_seen = (chunk_nan if nan_seen is None
                        else (nan_seen | chunk_nan))
            if yc is not None:
                y_parts.append(np.asarray(yc, np.float32))
            if wc is not None:
                w_parts.append(np.asarray(wc, np.float32))
            if mapper is None:
                raw_buf.append(Xc)
                buffered += len(Xc)
                if buffered >= bin_sample_count:
                    sample = np.concatenate(raw_buf)[:bin_sample_count]
                    mapper = compute_bin_mapper(
                        sample, max_bin, bin_sample_count,
                        categorical_features, seed,
                        min_data_in_bin=min_data_in_bin,
                        max_bin_by_feature=max_bin_by_feature)
                    _flush_raw()
            else:
                _bin(Xc)
        if mapper is None:
            if not raw_buf:
                raise ValueError("from_batches got an empty batch iterator")
            sample = np.concatenate(raw_buf)
            mapper = compute_bin_mapper(
                sample, max_bin, bin_sample_count, categorical_features,
                seed, min_data_in_bin=min_data_in_bin,
                max_bin_by_feature=max_bin_by_feature)
            _flush_raw()
        if not binned_parts:
            raise ValueError("from_batches got an empty batch iterator")
        # a NaN the mapper never allocated a missing bin for would clamp
        # into the last VALUE bin — a silently different model than
        # Dataset(X) on the same data (code-review r5). Fail loud instead.
        late_nan = nan_seen & ~mapper.nan_mask & ~mapper.is_categorical
        if late_nan.any():
            raise ValueError(
                f"features {np.flatnonzero(late_nan).tolist()} contain NaN "
                "but the streamed sample that fixed the bin boundaries had "
                "none — use a full-stream sample (dataset_from_spark's "
                "two-pass reservoir) or pass a mapper with has_nan set")
        import jax.numpy as jnp

        binned = np.concatenate(binned_parts)
        del binned_parts[:]                # host peak: ~2x binned bytes
        ds = cls.__new__(cls)
        ds.min_data_in_bin = min_data_in_bin
        ds.max_bin_by_feature = max_bin_by_feature
        ds._user_mapper = user_mapper
        ds._sparse = None
        ds.X = None                          # raw floats were never kept
        ds.num_rows, ds.num_features = binned.shape
        ds.mapper = mapper
        ds.binned = jnp.asarray(binned)
        ds.label = np.concatenate(y_parts) if y_parts else None
        ds.weight = np.concatenate(w_parts) if w_parts else None
        ds.init_score = None
        ds.group_sizes = None
        ds.categorical_features = categorical_features
        return ds

    @property
    def shape(self):
        return (self.num_rows, self.num_features)

    def raw_dense(self) -> Optional[np.ndarray]:
        """Dense raw rows for the paths that need them (warm start / mesh
        padding); densifies a kept sparse matrix on demand."""
        if self.X is not None:
            return self.X
        if self._sparse is not None:
            return np.asarray(self._sparse.todense(), np.float32)
        return None

    def block_until_ready(self):
        """Wait for the device-side binned matrix (bench staging helper)."""
        import jax

        jax.block_until_ready(self.binned)
        return self
