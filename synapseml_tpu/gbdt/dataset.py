"""Pre-binned training data — the LightGBM ``Dataset`` concept on TPU.

LightGBM separates dataset construction (``LGBM_DatasetCreateFromMat`` —
quantile binning, the expensive O(N·F·log B) pass) from training
(``LGBM_BoosterUpdateOneIter``); the reference builds the dataset once per
fit and benchmarks only the iteration loop (SURVEY §3.1; reference
dataset/DatasetUtils.scala + LightGBMBase.scala:509-550 do exactly this
split). ``Dataset`` is that same separation TPU-side: binning runs once on
device at construction, the quantized (N, F) uint8/uint16 matrix stays
HBM-resident, and every subsequent ``train_booster(dataset, ...)`` call
skips quantization AND the host→device transfer of the raw floats — which
matters doubly when the chip sits behind a network tunnel.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..ops.quantize import BinMapper, apply_bins, compute_bin_mapper


class Dataset:
    """Bins ``X`` once (device-resident) for repeated training runs.

    Parameters mirror the binning-relevant subset of ``BoosterConfig``
    (max_bin / bin_sample_count / categorical_features / seed). ``label`` /
    ``weight`` / ``init_score`` / ``group_sizes`` ride along so a Dataset is
    a self-contained training input, as in LightGBM's Python API.
    """

    def __init__(
        self,
        X: np.ndarray,
        label: Optional[np.ndarray] = None,
        weight: Optional[np.ndarray] = None,
        init_score: Optional[np.ndarray] = None,
        group_sizes: Optional[np.ndarray] = None,
        categorical_features: Optional[Sequence[int]] = None,
        max_bin: int = 255,
        bin_sample_count: int = 200_000,
        seed: int = 0,
        mapper: Optional[BinMapper] = None,
        keep_raw: bool = True,
    ):
        X = np.asarray(X, np.float32)
        if X.ndim != 2 or X.shape[0] == 0:
            raise ValueError(f"Dataset requires a non-empty 2-D matrix, got {X.shape}")
        self.num_rows, self.num_features = X.shape
        self.mapper = mapper if mapper is not None else compute_bin_mapper(
            X, max_bin, bin_sample_count, categorical_features, seed)
        self.binned = apply_bins(self.mapper, X)   # device (N, F) uint8/16
        self.label = None if label is None else np.asarray(label, np.float32)
        self.weight = None if weight is None else np.asarray(weight, np.float32)
        self.init_score = init_score
        self.group_sizes = group_sizes
        self.categorical_features = categorical_features
        # raw floats kept host-side for paths that need them (warm start /
        # mesh row padding); drop with keep_raw=False to halve host memory
        self.X = X if keep_raw else None

    @property
    def shape(self):
        return (self.num_rows, self.num_features)

    def block_until_ready(self):
        """Wait for the device-side binned matrix (bench staging helper)."""
        import jax

        jax.block_until_ready(self.binned)
        return self
