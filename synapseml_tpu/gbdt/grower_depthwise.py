"""Depthwise (level-batched) tree grower — an OPT-IN growth policy designed
for TPU step economics.

LightGBM (and therefore the reference) grows leaf-wise: 30 strictly
sequential split steps per 31-leaf tree, each with its own histogram kernel
launch, partition, and bookkeeping (grower.py — bitwise LightGBM parity).
On a TPU the sequential-step count itself can dominate: this grower trades
the leaf-wise growth ORDER (trees differ from LightGBM's; quality is
comparable and gated in tests) for level batching:

  * rows are kept partitioned by leaf with every leaf's range starting at a
    CHUNK boundary (tail padding rows carry zero grad/hess/mask), so ONE
    multi-leaf Pallas pass per level histograms EVERY leaf
    (ops/hist_kernel.py:_hist_pallas_level — output block chosen per chunk
    from a scalar-prefetched slot table);
  * one composite sort + one aligned gather re-partitions the whole row set
    per LEVEL (vs one sort per split);
  * split finding is vmapped across the level's leaves.

Per tree: ~depth heavy steps instead of ~num_leaves. Within a level,
splits are applied in gain order (best-first within the level) and the
num_leaves budget truncates the last level by gain, so ``num_leaves``
keeps its meaning. Serialization uses the same TreeArrays/Tree::Split
numbering as the leaf-wise grower, so models save/load/predict
identically (gbdt/model_io.py).

Reference anchor: the hot loop this redesigns is LightGBM C++
ConstructHistograms/Split driven through LGBM_BoosterUpdateOneIter
(booster/LightGBMBooster.scala:355-392).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.hist_kernel import level_histograms, pad_bins, features_padded
from .grower import (BITS, _chunk, GrowerConfig, _best_for_leaf,
                     _finalize_tree, _init_split_state, _leaf_output,
                     _maybe_psum, _node_mask_fn, _pad_cat_nbins,
                     _pad_grow_inputs, _winning_cat_bitset)


class _DepthState(NamedTuple):
    bT: jnp.ndarray              # (FP, CAP) i32 bins, slot-partitioned
    gs: jnp.ndarray              # (CAP,) f32
    hs: jnp.ndarray              # (CAP,) f32
    ms: jnp.ndarray              # (CAP,) f32 in-bag mask (0 on padding)
    pos: jnp.ndarray             # (CAP,) i32 original row (Np = padding)
    rleaf: jnp.ndarray           # (CAP,) i32 leaf id per row
    leaf_start: jnp.ndarray      # (L,) i32 row base (chunk-aligned)
    leaf_len: jnp.ndarray        # (L,) i32 REAL row count
    mask_id: jnp.ndarray         # (L,) i32 per-node feature-mask id
    level: jnp.ndarray           # () i32
    progress: jnp.ndarray        # () bool — any split applied last level
    hist: jnp.ndarray            # (L, FP, B, 3)
    bgain: jnp.ndarray
    bfeat: jnp.ndarray
    bbin: jnp.ndarray
    bdl: jnp.ndarray
    bcl: jnp.ndarray
    depth: jnp.ndarray
    leaf_parent: jnp.ndarray
    leaf_is_right: jnp.ndarray
    split_feature: jnp.ndarray
    split_bin: jnp.ndarray
    split_gain: jnp.ndarray
    split_type: jnp.ndarray
    default_left: jnp.ndarray
    cat_bitset: jnp.ndarray
    left_child: jnp.ndarray
    right_child: jnp.ndarray
    internal_value: jnp.ndarray
    internal_count: jnp.ndarray
    num_splits: jnp.ndarray


class _LevelPlan(NamedTuple):
    """One level's applied split decisions in per-leaf broadcast form —
    everything a row (resident CAP-array or streamed chunk) needs to route
    itself: index by the row's current leaf id. Produced by
    :func:`_apply_level_splits`, consumed by :func:`_route_level`; shared by
    the resident depthwise grower and the out-of-core streamed grower
    (gbdt/stream.py), which routes CHUNKS of rows against the same plan."""

    do: jnp.ndarray          # (L,) bool — leaf split this level
    fsel: jnp.ndarray        # (L,) i32 split feature
    bsel: jnp.ndarray        # (L,) i32 bin threshold
    dl: jnp.ndarray          # (L,) bool default-left
    cat: jnp.ndarray         # (L,) bool categorical split
    bits: jnp.ndarray        # (L, bw) u32 categorical bitset
    right_of: jnp.ndarray    # (L,) i32 right-child leaf (identity if unsplit)


def _level_candidates(s, cfg: GrowerConfig, L: int):
    """(do, order) for the level ``s.level``: which leaves split, in gain
    order, with the num_leaves budget truncating by gain."""
    exists = jnp.arange(L) <= s.num_splits
    gains_d = jnp.where(exists & (s.depth == s.level), s.bgain, -jnp.inf)
    want = gains_d > cfg.min_gain_to_split
    order = jnp.argsort(-gains_d).astype(jnp.int32)
    rank = jnp.zeros(L, jnp.int32).at[order].set(
        jnp.arange(L, dtype=jnp.int32))
    budget = (L - 1) - s.num_splits
    return want & (rank < budget), order


def _apply_level_splits(s, do, order, catp, catb, cfg: GrowerConfig, B: int,
                        bw: int, L: int):
    """Apply one level's splits in gain order (bookkeeping only — small
    arrays; the heavy per-row work is batched by the caller). ``s`` is any
    NamedTuple state carrying the tree-bookkeeping fields of
    :func:`grower._init_split_state` plus ``mask_id`` — the resident
    ``_DepthState`` and the streamed grower's state both qualify. Returns
    ``(s2, plan)``: the updated state and the :class:`_LevelPlan` rows route
    against."""
    plan0 = _LevelPlan(
        do=do,
        fsel=jnp.zeros(L, jnp.int32),
        bsel=jnp.zeros(L, jnp.int32),
        dl=jnp.zeros(L, bool),
        cat=jnp.zeros(L, bool),
        bits=jnp.zeros((L, bw), jnp.uint32),
        right_of=jnp.arange(L, dtype=jnp.int32))   # identity when unsplit

    def apply_one(k, carry):
        s, plan = carry
        l = order[k]

        def live(args):
            s, plan = args
            gain_l = s.bgain[l]
            fsel, bsel, dl = s.bfeat[l], s.bbin[l], s.bdl[l]
            hist_parent = s.hist[l]
            totals = hist_parent[0].sum(axis=0)
            G_l, H_l, C_l = totals[0], totals[1], totals[2]
            bitset, cat_split = _winning_cat_bitset(
                hist_parent, fsel, bsel, catp, cfg, B, bw, catb)
            i_node = s.num_splits
            new_right = i_node + 1
            parent_out = _leaf_output(G_l, H_l, cfg) * cfg.learning_rate
            p = s.leaf_parent[l]
            p_idx = jnp.maximum(p, 0)
            lc = s.left_child.at[p_idx].set(
                jnp.where((p >= 0) & ~s.leaf_is_right[l], i_node,
                          s.left_child[p_idx]))
            rc = s.right_child.at[p_idx].set(
                jnp.where((p >= 0) & s.leaf_is_right[l], i_node,
                          s.right_child[p_idx]))
            lc = lc.at[i_node].set(~l)
            rc = rc.at[i_node].set(~new_right)
            s2 = s._replace(
                depth=s.depth.at[l].add(1).at[new_right].set(
                    s.depth[l] + 1),
                leaf_parent=s.leaf_parent.at[l].set(i_node)
                                        .at[new_right].set(i_node),
                leaf_is_right=s.leaf_is_right.at[l].set(False)
                                             .at[new_right].set(True),
                mask_id=s.mask_id.at[l].set(i_node * 2)
                                 .at[new_right].set(i_node * 2 + 1),
                split_feature=s.split_feature.at[i_node].set(fsel),
                split_bin=s.split_bin.at[i_node].set(bsel),
                split_gain=s.split_gain.at[i_node].set(gain_l),
                split_type=s.split_type.at[i_node].set(
                    cat_split.astype(jnp.int32)),
                default_left=s.default_left.at[i_node].set(dl),
                cat_bitset=s.cat_bitset.at[i_node].set(bitset),
                left_child=lc,
                right_child=rc,
                internal_value=s.internal_value.at[i_node].set(parent_out),
                internal_count=s.internal_count.at[i_node].set(
                    C_l.astype(jnp.int32)),
                num_splits=s.num_splits + 1,
            )
            plan2 = plan._replace(
                fsel=plan.fsel.at[l].set(fsel),
                bsel=plan.bsel.at[l].set(bsel),
                dl=plan.dl.at[l].set(dl),
                cat=plan.cat.at[l].set(cat_split),
                bits=plan.bits.at[l].set(bitset),
                right_of=plan.right_of.at[l].set(new_right))
            return (s2, plan2)

        return lax.cond(do[l], live, lambda a: a, (s, plan))

    return lax.fori_loop(0, L, apply_one, (s, plan0))


def _route_level(bT, rleaf, plan: _LevelPlan, nanp, cfg: GrowerConfig,
                 bw: int):
    """Vectorized per-row routing of one level's applied splits over any row
    block: ``bT`` (FP, R) bins, ``rleaf`` (R,) current leaf ids → (R,) new
    leaf ids. Per-row split params come from the plan via the row's leaf (vs
    ``grower._route_right``'s single-split scalars); the bitset is one word
    row per row's leaf."""
    split_row = plan.do[rleaf]
    fr = plan.fsel[rleaf]
    binrow = jnp.take_along_axis(bT, fr[None, :], axis=0)[0]
    gr = binrow > plan.bsel[rleaf]
    gr = jnp.where(binrow == nanp[fr], ~plan.dl[rleaf], gr)
    if cfg.has_categorical:
        w = jnp.take_along_axis(
            plan.bits[rleaf],
            jnp.clip(binrow >> 5, 0, bw - 1).astype(jnp.int32)[:, None],
            axis=1)[:, 0]
        member = ((w >> (binrow & 31).astype(jnp.uint32)) & 1).astype(bool)
        gr = jnp.where(plan.cat[rleaf], ~member, gr)
    return jnp.where(split_row & gr, plan.right_of[rleaf], rleaf)


def _grow_tree_impl_depthwise(binned, grad, hess, in_bag, feature_active,
                              is_categorical, monotone, nan_bins,
                              cfg: GrowerConfig, axis_name: Optional[str],
                              node_key=None, cat_nbins=None):
    n, f = binned.shape
    L = cfg.num_leaves
    B = pad_bins(cfg.num_bins)
    FP = features_padded(f)
    chunk = _chunk()     # resolved ONCE per trace: within-trace consistency
    Np = -(-n // chunk) * chunk
    CAP = Np + L * chunk                    # every leaf rounds up to a chunk
    CAPC = CAP // chunk
    bw = (B + BITS - 1) // BITS
    l1 = jnp.float32(cfg.lambda_l1)
    l2 = jnp.float32(cfg.lambda_l2)
    max_levels = cfg.max_depth if cfg.max_depth > 0 else L - 1

    bT0, gs0, hs0, ms0, featp, catp, monop, nanp = _pad_grow_inputs(
        binned, grad, hess, in_bag, feature_active, is_categorical, monotone,
        nan_bins, FP, Np)
    pad = CAP - Np
    bTc = jnp.pad(bT0, ((0, 0), (0, pad)))
    gsc = jnp.pad(gs0, (0, pad))
    hsc = jnp.pad(hs0, (0, pad))
    msc = jnp.pad(ms0, (0, pad))
    # original row id per position; Np marks padding (out-of-bounds for the
    # final scatter into an Np-sized buffer -> dropped)
    posc = jnp.pad(jnp.arange(Np, dtype=jnp.int32), (0, pad),
                   constant_values=Np)

    nmask = _node_mask_fn(cfg, featp, f, node_key)
    catb = _pad_cat_nbins(cat_nbins, f, FP, B)

    def best_of(hist_leaf, fmask):
        return _best_for_leaf(hist_leaf, fmask, catp, monop, nanp, cfg, l1,
                              l2, catb)

    def level_pass(bT, gs, hs, ms, leaf_start, rleaf, leaf_len, exists):
        """One multi-leaf histogram pass + vmapped split finding."""
        hist = level_histograms(bT, gs, hs, ms, leaf_start // chunk, rleaf,
                                B, L)
        # mask BEFORE the psum and by the shard-UNIFORM ``exists`` only:
        # every existing leaf owns >= 1 chunk (all-padding chunks produce
        # zeros), while non-existent slots' kernel blocks are uninitialized.
        # leaf_len is shard-LOCAL — masking by it would zero a leaf that is
        # empty on this shard but populated on another, diverging the
        # shards' split decisions.
        del leaf_len
        hist = jnp.where(exists[:, None, None, None], hist, 0.0)
        return _maybe_psum(hist, axis_name, cfg.hist_allreduce_dtype)

    # ---- root ------------------------------------------------------------
    rleaf0 = jnp.zeros(CAP, jnp.int32)
    leaf_start0 = jnp.zeros(L, jnp.int32).at[1:].set(CAP)
    leaf_len0 = jnp.zeros(L, jnp.int32).at[0].set(Np)
    exists0 = jnp.arange(L) == 0
    hist0 = level_pass(bTc, gsc, hsc, msc, leaf_start0, rleaf0, leaf_len0,
                       exists0)
    rg, rf, rb, rdl, rcl, _ = best_of(hist0[0], nmask(jnp.int32(2 * (L - 1))))

    base = _init_split_state(L, B, bw, hist0[0], rg, rf, rb, rdl, rcl, FP)
    base["hist"] = hist0
    init = _DepthState(
        bT=bTc, gs=gsc, hs=hsc, ms=msc, pos=posc, rleaf=rleaf0,
        leaf_start=leaf_start0, leaf_len=leaf_len0,
        mask_id=jnp.full(L, 2 * (L - 1), jnp.int32),
        level=jnp.int32(0), progress=jnp.bool_(True), **base)

    def cond(s: _DepthState):
        return (s.progress & (s.num_splits < L - 1)
                & (s.level < max_levels))

    def body(s: _DepthState) -> _DepthState:
        d = s.level
        do, order = _level_candidates(s, cfg, L)

        # ---- stage (a): apply the level's splits in gain order ----------
        # (bookkeeping only — small arrays; the heavy work is batched below)
        s, plan = _apply_level_splits(s, do, order, catp, catb, cfg, B, bw,
                                      L)

        # ---- route every row by its leaf's split (vectorized) -----------
        new_rleaf = _route_level(s.bT, s.rleaf, plan, nanp, cfg, bw)
        # padding rows sort to the very end and are regenerated per slot
        is_pad = s.pos >= Np
        sort_leaf = jnp.where(is_pad, L, new_rleaf)

        # ---- one composite sort + aligned gather re-partitions ----------
        shift = max(CAP - 1, 1).bit_length()
        if shift + (L + 1).bit_length() <= 32:
            comp = ((sort_leaf.astype(jnp.uint32) << shift)
                    | jnp.arange(CAP, dtype=jnp.uint32))
            src_sorted = (jnp.sort(comp)
                          & jnp.uint32((1 << shift) - 1)).astype(jnp.int32)
        else:   # u32 composite would overflow (huge CAP x many leaves)
            src_sorted = jnp.argsort(sort_leaf, stable=True
                                     ).astype(jnp.int32)
        counts = jnp.bincount(jnp.where(is_pad, L, new_rleaf), length=L + 1
                              )[:L].astype(jnp.int32)
        first_sorted = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                        jnp.cumsum(counts)[:-1]])
        exists2 = jnp.arange(L) <= s.num_splits
        cap_chunks = jnp.where(exists2, jnp.maximum(-(-counts // chunk), 1),
                               0)
        base_chunk = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                      jnp.cumsum(cap_chunks)[:-1]])
        leaf_start2 = jnp.where(exists2, base_chunk * chunk, CAP)
        # destination -> source: slot of q via its chunk, rank within slot
        qchunk = jnp.arange(CAP, dtype=jnp.int32) // chunk
        slot_q = (jnp.searchsorted(base_chunk, qchunk, side="right")
                  .astype(jnp.int32) - 1)
        slot_q = jnp.clip(slot_q, 0, L - 1)
        r_q = jnp.arange(CAP, dtype=jnp.int32) - leaf_start2[slot_q]
        valid_q = (r_q >= 0) & (r_q < counts[slot_q])
        src_q = src_sorted[jnp.clip(first_sorted[slot_q] + r_q, 0, CAP - 1)]
        src_q = jnp.where(valid_q, src_q, 0)

        bT2 = jnp.where(valid_q[None, :], s.bT[:, src_q], 0)
        gs2 = jnp.where(valid_q, s.gs[src_q], 0.0)
        hs2 = jnp.where(valid_q, s.hs[src_q], 0.0)
        ms2 = jnp.where(valid_q, s.ms[src_q], 0.0)
        pos2 = jnp.where(valid_q, s.pos[src_q], Np)
        rleaf2 = slot_q

        # ---- ONE multi-leaf histogram pass + vmapped split finding ------
        hist2 = level_pass(bT2, gs2, hs2, ms2, leaf_start2, rleaf2, counts,
                           exists2)
        masks = jax.vmap(nmask)(s.mask_id)
        bg, bf, bb, bdl_, bcl, _ = jax.vmap(best_of)(hist2, masks)
        # leaves that existed before this level keep candidacy rules via
        # depth; values are recomputed from identical data (same rows)
        return s._replace(
            bT=bT2, gs=gs2, hs=hs2, ms=ms2, pos=pos2, rleaf=rleaf2,
            leaf_start=leaf_start2, leaf_len=counts,
            level=d + 1, progress=do.any(),
            hist=hist2, bgain=jnp.where(exists2, bg, -jnp.inf),
            bfeat=bf, bbin=bb, bdl=bdl_, bcl=bcl,
        )

    s = lax.while_loop(cond, body, init) if L > 1 else init
    tree = _finalize_tree(s, cfg, L)
    node_of_row = jnp.zeros(Np, jnp.int32).at[s.pos].set(
        s.rleaf, mode="drop")[:n]
    return tree, node_of_row
