"""UnrollImage + ImageSetAugmenter.

Reference: core/.../image/UnrollImage.scala:169-204 (image → flat vector
column, the bridge from image data to vector-consuming estimators) and
opencv/.../ImageSetAugmenter.scala (flip-based augmentation that doubles the
dataset)."""

from __future__ import annotations

import numpy as np

from ..core.params import Param, HasInputCol, HasOutputCol
from ..core.pipeline import Transformer
from ..core.table import Table


class UnrollImage(Transformer, HasInputCol, HasOutputCol):
    """Flatten an image column (H,W,C arrays) into a 2-D float vector column."""

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "features")
        super().__init__(**kwargs)

    def _transform(self, df: Table) -> Table:
        imgs = df[self.inputCol]
        flat = [np.asarray(imgs[i], np.float32).ravel() for i in range(df.num_rows)]
        dims = {len(f) for f in flat}
        if len(dims) > 1:
            raise ValueError(
                f"UnrollImage requires uniformly-sized images; got flattened "
                f"lengths {sorted(dims)} — resize/crop first (ops.image)")
        d = dims.pop() if dims else 0
        out = np.stack(flat) if flat else np.zeros((0, d), np.float32)
        return df.with_column(self.outputCol, out)


class ImageSetAugmenter(Transformer, HasInputCol, HasOutputCol):
    """Double the dataset with horizontal (and optionally vertical) flips."""
    flipLeftRight = Param("flipLeftRight", "Add left-right flipped copies", bool, True)
    flipUpDown = Param("flipUpDown", "Add up-down flipped copies", bool, False)

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "images")
        super().__init__(**kwargs)

    def _transform(self, df: Table) -> Table:
        imgs = df[self.inputCol]
        base = (df.rename({self.inputCol: self.outputCol})
                if self.inputCol != self.outputCol else df.copy())
        pieces = [base]
        for flag, axis in ((self.flipLeftRight, 1), (self.flipUpDown, 0)):
            if not flag:
                continue
            flipped = np.empty(df.num_rows, object)
            for i in range(df.num_rows):
                flipped[i] = np.flip(np.asarray(imgs[i]), axis=axis).copy()
            # preserve base's column order exactly (concat requires it)
            t = Table({c: (flipped if c == self.outputCol else base[c])
                       for c in base.columns})
            pieces.append(t)
        return pieces[0].concat(*pieces[1:]) if len(pieces) > 1 else pieces[0]
