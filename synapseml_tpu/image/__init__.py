"""Image utilities (JVM `image/` package analog, SURVEY §2.7):
Superpixel clustering (SLIC) for image LIME/SHAP, SuperpixelTransformer,
UnrollImage, ImageSetAugmenter."""

from .superpixel import slic_segments, grid_segments, Superpixel, SuperpixelTransformer
from .unroll import UnrollImage, ImageSetAugmenter

__all__ = ["slic_segments", "grid_segments", "Superpixel", "SuperpixelTransformer",
           "UnrollImage", "ImageSetAugmenter"]
