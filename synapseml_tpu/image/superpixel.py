"""Superpixel segmentation.

Reference: core/.../image/Superpixel.scala:147+ — SLIC-style clustering used by
image LIME/SHAP samplers, and SuperpixelTransformer. The reference's cluster
loop is scalar JVM code; here the SLIC iterations are vectorized NumPy
(assignment via distance to K cluster centers in (L,a,b,x,y)-ish space done as
one broadcast op per iteration — maps to XLA cleanly if moved on-device, but
segmentation is a host-side preprocessing step feeding the TPU explainers)."""

from __future__ import annotations


import numpy as np

from ..core.params import Param, HasInputCol, HasOutputCol
from ..core.pipeline import Transformer
from ..core.table import Table


def grid_segments(h: int, w: int, cell: int = 16) -> np.ndarray:
    """Regular-grid fallback segmentation: (h, w) int32 segment ids."""
    gy = np.arange(h) // cell
    gx = np.arange(w) // cell
    ncols = (w + cell - 1) // cell
    return (gy[:, None] * ncols + gx[None, :]).astype(np.int32)


def slic_segments(img: np.ndarray, cell_size: int = 16, modifier: float = 10.0,
                  iters: int = 5) -> np.ndarray:
    """SLIC superpixels: k-means in (color, compactness-weighted position).

    img: (H, W, C) float or uint8. Returns (H, W) int32 segment labels
    relabeled to 0..K-1. `cell_size`/`modifier` mirror Superpixel.scala's
    cellSize/modifier params."""
    img = np.asarray(img, np.float32)
    if img.ndim == 2:
        img = img[..., None]
    h, w, c = img.shape
    s = max(min(int(cell_size), h, w), 2)  # clamp so tiny images get >= 1 center
    # initial centers on a regular grid
    ys = np.arange(s // 2, h, s)
    xs = np.arange(s // 2, w, s)
    if len(ys) == 0 or len(xs) == 0:
        return np.zeros((h, w), np.int32)  # degenerate image: one segment
    cy, cx = np.meshgrid(ys, xs, indexing="ij")
    cy, cx = cy.ravel().astype(np.float32), cx.ravel().astype(np.float32)
    k = len(cy)
    centers_col = img[cy.astype(int), cx.astype(int)]                  # (K, C)
    yy, xx = np.meshgrid(np.arange(h, dtype=np.float32),
                         np.arange(w, dtype=np.float32), indexing="ij")
    ratio = (modifier / s) ** 2
    flat = img.reshape(-1, c)
    pos = np.stack([yy.ravel(), xx.ravel()], 1)                        # (HW, 2)
    labels = np.zeros(h * w, np.int32)
    for _ in range(max(iters, 1)):
        # distance of every pixel to every center (vectorized; K is small)
        d_col = ((flat[:, None, :] - centers_col[None]) ** 2).sum(-1)  # (HW, K)
        d_pos = (pos[:, None, 0] - cy[None]) ** 2 + (pos[:, None, 1] - cx[None]) ** 2
        labels = np.argmin(d_col + ratio * d_pos, axis=1).astype(np.int32)
        # recompute centers
        for j in range(k):
            m = labels == j
            if m.any():
                centers_col[j] = flat[m].mean(0)
                cy[j] = pos[m, 0].mean()
                cx[j] = pos[m, 1].mean()
    # relabel contiguously
    uniq, labels = np.unique(labels, return_inverse=True)
    return labels.reshape(h, w).astype(np.int32)


class Superpixel:
    """Functional facade matching the reference's Superpixel object."""

    @staticmethod
    def cluster(img: np.ndarray, cell_size: int = 16, modifier: float = 130.0,
                iters: int = 5) -> np.ndarray:
        return slic_segments(img, cell_size, modifier, iters)

    @staticmethod
    def masked_image(img: np.ndarray, segments: np.ndarray, mask: np.ndarray,
                     fill: float = 0.0) -> np.ndarray:
        """Zero/fill the superpixels where mask[seg]==0 (the LIME censoring op)."""
        keep = np.asarray(mask)[segments].astype(bool)
        out = np.array(img, np.float32, copy=True)
        out[~keep] = fill
        return out


class SuperpixelTransformer(Transformer, HasInputCol, HasOutputCol):
    """Adds a segmentation (H, W) label map column for an image column
    (reference: image/SuperpixelTransformer.scala)."""
    cellSize = Param("cellSize", "Approximate superpixel cell size (pixels)", float, 16.0)
    modifier = Param("modifier", "Compactness modifier", float, 130.0)

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "superpixels")
        super().__init__(**kwargs)

    def _transform(self, df: Table) -> Table:
        imgs = df[self.inputCol]
        segs = np.empty(df.num_rows, object)
        for i in range(df.num_rows):
            segs[i] = slic_segments(np.asarray(imgs[i]), int(self.cellSize), self.modifier)
        return df.with_column(self.outputCol, segs)
