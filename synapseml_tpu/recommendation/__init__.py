"""Recommendation — SAR and ranking utilities.

Reference: core/src/main/scala/com/microsoft/azure/synapse/ml/recommendation/
(SAR.scala:36-210, SARModel.scala, RankingAdapter.scala, RankingEvaluator.scala,
RankingTrainValidationSplit.scala, RecommendationIndexer.scala; SURVEY.md §2.7).
The reference assembles the item-item co-occurrence and affinity matrices with
sparse Breeze products inside Spark UDFs; here both are dense device matmuls
(affinity [U,I] @ similarity [I,I] on the MXU) with the same similarity
definitions (cooccurrence / jaccard / lift) and time-decayed affinities.
"""

from .indexer import RecommendationIndexer, RecommendationIndexerModel
from .sar import SAR, SARModel
from .ranking import (RankingAdapter, RankingAdapterModel, RankingEvaluator,
                      RankingTrainValidationSplit)

__all__ = [
    "RecommendationIndexer", "RecommendationIndexerModel",
    "SAR", "SARModel",
    "RankingAdapter", "RankingAdapterModel",
    "RankingEvaluator", "RankingTrainValidationSplit",
]
