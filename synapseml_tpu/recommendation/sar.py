"""SAR — Smart Adaptive Recommendations.

Reference: recommendation/SAR.scala:36-210 and SARModel.scala. Semantics kept:

* **Item-item similarity** from the user-item interaction matrix ``A`` (binary
  occurrence, items below ``supportThreshold`` dropped): co-occurrence
  ``C = Aᵀ A``; ``jaccard(i,j) = c_ij / (c_ii + c_jj − c_ij)``;
  ``lift(i,j) = c_ij / (c_ii · c_jj)`` (SAR.scala:184-196).
* **User affinity** with exponential time decay: each (user, item, rating, t)
  contributes ``rating · 2^(−(t_ref − t) / T_half)`` where ``T_half`` is
  ``timeDecayCoeff`` days (SAR.scala:87-96); without a time column the rating
  itself is the affinity.
* **Scoring**: recommendations rank ``affinity @ similarity`` — one [U,I]×[I,I]
  MXU matmul here, versus per-row sparse Breeze products in UDFs there.
"""

from __future__ import annotations

from datetime import datetime, timezone
from typing import Optional

import numpy as np

from ..core.inference import BucketedRunner
from ..core.params import Param, Params
from ..core.pipeline import Estimator, Model
from ..core.table import Table

_SIMS = ("cooccurrence", "jaccard", "lift")

_MAX_USERS_PER_CHUNK = 256


class _SARParams(Params):
    userCol = Param("userCol", "Column of user indices (0..numUsers-1)", str, "user")
    itemCol = Param("itemCol", "Column of item indices (0..numItems-1)", str, "item")
    ratingCol = Param("ratingCol", "Column of ratings", str, "rating")
    timeCol = Param("timeCol", "Time of activity", str, "time")
    similarityFunction = Param(
        "similarityFunction",
        "Defines the similarity function to be used by the model: "
        "lift, jaccard, cooccurrence", str, "jaccard",
        validator=lambda v: v if v in _SIMS else (_ for _ in ()).throw(
            ValueError(f"similarityFunction must be one of {_SIMS}, got {v!r}")))
    supportThreshold = Param("supportThreshold",
                             "Minimum number of ratings per item", int, 4)
    timeDecayCoeff = Param("timeDecayCoeff",
                           "Half-life of the time decay, in days", int, 30)
    startTime = Param("startTime",
                      "Custom 'now' reference time for historical data", str)
    startTimeFormat = Param("startTimeFormat", "Format for startTime", str,
                            "%Y-%m-%d %H:%M:%S")
    activityTimeFormat = Param("activityTimeFormat",
                               "Format for the time column when it is strings",
                               str, "%Y-%m-%d %H:%M:%S")


class SAR(Estimator, _SARParams):
    """Fit the affinity and similarity matrices (reference SAR.scala)."""

    def _fit(self, df: Table) -> "SARModel":
        users = np.asarray(df[self.getUserCol()], dtype=np.int64)
        items = np.asarray(df[self.getItemCol()], dtype=np.int64)
        n_users = int(users.max()) + 1 if users.size else 0
        n_items = int(items.max()) + 1 if items.size else 0
        ratings = (np.asarray(df[self.getRatingCol()], dtype=np.float32)
                   if self.getRatingCol() in df else np.ones(len(users), np.float32))

        # --- occurrence matrix + support filter ------------------------
        occ = np.zeros((n_users, n_items), dtype=np.float32)
        occ[users, items] = 1.0
        support = occ.sum(axis=0)
        active = support >= self.getSupportThreshold()
        occ[:, ~active] = 0.0

        sim = _similarity(occ, self.getSimilarityFunction())

        # --- time-decayed affinity -------------------------------------
        decay = np.ones(len(users), dtype=np.float32)
        if self.getTimeCol() in df:
            t = _to_epoch_minutes(df[self.getTimeCol()], self.getActivityTimeFormat())
            if self.isSet("startTime"):
                ref = datetime.strptime(
                    self.getStartTime(), self.getStartTimeFormat()
                ).replace(tzinfo=timezone.utc).timestamp() / 60.0
            else:
                ref = t.max()
            half_life_min = float(self.getTimeDecayCoeff()) * 24 * 60
            decay = np.exp2(-(ref - t) / half_life_min).astype(np.float32)
        affinity = np.zeros((n_users, n_items), dtype=np.float32)
        np.add.at(affinity, (users, items), ratings * decay)

        return SARModel(itemSimilarity=sim, userAffinity=affinity,
                        **{p: self.get(p) for p in self._paramMap})


class SARModel(Model, _SARParams):
    itemSimilarity = Param("itemSimilarity", "[I, I] item-item similarity",
                           is_complex=True)
    userAffinity = Param("userAffinity", "[U, I] time-decayed user affinity",
                         is_complex=True)

    def getItemDataFrame(self) -> Table:
        sim = self.get("itemSimilarity")
        return Table({self.getItemCol(): np.arange(sim.shape[0]),
                      "jaccardList": sim})

    def getUserDataFrame(self) -> Table:
        aff = self.get("userAffinity")
        return Table({self.getUserCol(): np.arange(aff.shape[0]),
                      "flatList": aff})

    def _score_runner(self) -> BucketedRunner:
        """Per-model cached :class:`BucketedRunner` over user rows: the
        similarity matrix rides as a closed-over device constant, the
        request-sized user dimension pads to the bucket ladder so scoring
        compiles once per bucket, not once per distinct query size."""
        sim_np = self.get("itemSimilarity")
        cached = getattr(self, "_runner_cache", None)
        if cached is not None and cached[0] is sim_np:
            return cached[1]
        import jax.numpy as jnp

        sim = jnp.asarray(sim_np)
        runner = BucketedRunner(lambda aff: aff @ sim,
                                max_batch_size=_MAX_USERS_PER_CHUNK,
                                name="sar_scores")
        self._runner_cache = (sim_np, runner)
        return runner

    def _scores(self, users: Optional[np.ndarray] = None) -> np.ndarray:
        """affinity[users] @ similarity — only the requested user rows are
        multiplied (the full [U,I]·[I,I] product is never materialized for
        subset queries)."""
        aff = self.get("userAffinity")
        if users is not None:
            aff = aff[users]
        aff = np.asarray(aff, dtype=np.float32)
        if aff.shape[0] == 0:
            return np.zeros((0, self.get("itemSimilarity").shape[0]), np.float32)
        return np.asarray(self._score_runner()(aff))

    def _transform(self, df: Table) -> Table:
        """Score (user, item) pairs — predicted rating column."""
        u = np.asarray(df[self.getUserCol()], dtype=np.int64)
        i = np.asarray(df[self.getItemCol()], dtype=np.int64)
        uniq, inv = np.unique(u, return_inverse=True)
        scores = self._scores(uniq)
        return df.with_column("prediction", scores[inv, i].astype(np.float32))

    def recommend_for_all_users(self, num_items: int) -> Table:
        """Top ``num_items`` per user (SARModel.scala:48-56): columns user,
        recommendations=[item indices], ratings=[scores]."""
        import jax

        scores = self._scores()
        k = min(num_items, scores.shape[1])
        vals, idx = jax.lax.top_k(scores, k)
        return Table({
            self.getUserCol(): np.arange(scores.shape[0]),
            "recommendations": np.asarray(idx),
            "ratings": np.asarray(vals),
        })

    def recommend_for_user_subset(self, df: Table, num_items: int) -> Table:
        import jax

        users = np.unique(np.asarray(df[self.getUserCol()], dtype=np.int64))
        scores = self._scores(users)
        k = min(num_items, scores.shape[1])
        vals, idx = jax.lax.top_k(scores, k)
        return Table({
            self.getUserCol(): users,
            "recommendations": np.asarray(idx),
            "ratings": np.asarray(vals),
        })

    recommendForAllUsers = recommend_for_all_users
    recommendForUserSubset = recommend_for_user_subset


def _similarity(occ: np.ndarray, kind: str) -> np.ndarray:
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _sim(o):
        c = o.T @ o  # co-occurrence [I, I] — MXU
        diag = jnp.diag(c)
        if kind == "jaccard":
            denom = diag[:, None] + diag[None, :] - c
            return jnp.where(denom > 0, c / denom, 0.0)
        if kind == "lift":
            denom = diag[:, None] * diag[None, :]
            return jnp.where(denom > 0, c / denom, 0.0)
        return c

    return np.asarray(_sim(jnp.asarray(occ)))


def _to_epoch_minutes(col: np.ndarray, fmt: str) -> np.ndarray:
    if np.issubdtype(col.dtype, np.datetime64):
        return col.astype("datetime64[s]").astype(np.float64) / 60.0
    if col.dtype == object or col.dtype.kind in "US":
        return np.asarray([
            datetime.strptime(str(v), fmt).replace(tzinfo=timezone.utc).timestamp()
            for v in col], dtype=np.float64) / 60.0
    return np.asarray(col, dtype=np.float64) / 60.0  # numeric epoch seconds
