"""RecommendationIndexer — raw user/item ids → contiguous integer indices.

Reference: recommendation/RecommendationIndexer.scala (wraps two StringIndexers
and exposes recover-transformers). SAR needs dense [U, I] matrices, so ids are
mapped to 0..n-1; the fitted model also recovers original ids on output tables.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ..core.params import Param, Params
from ..core.pipeline import Estimator, Model
from ..core.table import Table


class _IndexerParams(Params):
    userInputCol = Param("userInputCol", "User column", str, "user")
    userOutputCol = Param("userOutputCol", "User index column", str)
    itemInputCol = Param("itemInputCol", "Item column", str, "item")
    itemOutputCol = Param("itemOutputCol", "Item index column", str)
    ratingCol = Param("ratingCol", "Rating column", str, "rating")


class RecommendationIndexer(Estimator, _IndexerParams):
    def _fit(self, df: Table) -> "RecommendationIndexerModel":
        users = _vocabulary(df[self.getUserInputCol()])
        items = _vocabulary(df[self.getItemInputCol()])
        return RecommendationIndexerModel(
            userMap=users, itemMap=items,
            **{p: self.get(p) for p in self._paramMap})


class RecommendationIndexerModel(Model, _IndexerParams):
    userMap = Param("userMap", "user id -> index", is_complex=True)
    itemMap = Param("itemMap", "item id -> index", is_complex=True)

    def _transform(self, df: Table) -> Table:
        out = df.copy()
        umap: Dict[Any, int] = self.get("userMap")
        imap: Dict[Any, int] = self.get("itemMap")
        u_out = self.get("userOutputCol") or self.getUserInputCol() + "_idx"
        i_out = self.get("itemOutputCol") or self.getItemInputCol() + "_idx"
        if self.getUserInputCol() in df:
            out[u_out] = np.asarray(
                [umap[v] for v in df[self.getUserInputCol()]], dtype=np.int32)
        if self.getItemInputCol() in df:
            out[i_out] = np.asarray(
                [imap[v] for v in df[self.getItemInputCol()]], dtype=np.int32)
        return out

    @property
    def num_users(self) -> int:
        return len(self.get("userMap"))

    @property
    def num_items(self) -> int:
        return len(self.get("itemMap"))

    def recover_users(self, idx) -> List[Any]:
        inv = _inverse(self.get("userMap"))
        return [inv[int(i)] for i in np.asarray(idx).ravel()]

    def recover_items(self, idx) -> List[Any]:
        inv = _inverse(self.get("itemMap"))
        return [inv[int(i)] for i in np.asarray(idx).ravel()]

    recoverUsers = recover_users
    recoverItems = recover_items


def _vocabulary(col: np.ndarray) -> Dict[Any, int]:
    seen: Dict[Any, int] = {}
    for v in col:
        key = v.item() if isinstance(v, np.generic) else v
        if key not in seen:
            seen[key] = len(seen)
    return seen


def _inverse(m: Dict[Any, int]) -> Dict[int, Any]:
    return {i: v for v, i in m.items()}
