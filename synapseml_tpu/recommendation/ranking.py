"""Ranking adapter, evaluator, and train/validation split.

Reference: recommendation/RankingAdapter.scala, RankingEvaluator.scala
(AdvancedRankingMetrics:16-97), RankingTrainValidationSplit.scala. The adapter
turns a recommender into a Transformer that emits per-user ``prediction`` (top-k
recommended item indices) and ``label`` (actually-interacted item indices)
array columns; the evaluator computes ranking metrics over those columns; the
split does a per-user holdout and selects the best param map.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..core.params import Param, Params
from ..core.pipeline import Estimator, Model
from ..core.table import Table

_METRICS = ("ndcgAt", "map", "precisionAtk", "recallAtK", "diversityAtK",
            "maxDiversity", "mrr", "fcp")


class _RankingParams(Params):
    userCol = Param("userCol", "User index column", str, "user")
    itemCol = Param("itemCol", "Item index column", str, "item")
    ratingCol = Param("ratingCol", "Rating column", str, "rating")
    k = Param("k", "Number of recommendations", int, 10)


class RankingAdapter(Estimator, _RankingParams):
    """Wrap a recommender so fit/transform speak (prediction, label) arrays
    (reference RankingAdapter.scala: mode=allUsers)."""

    recommender = Param("recommender", "Underlying recommender estimator (SAR)",
                        is_complex=True)
    mode = Param("mode", "Recommendation mode", str, "allUsers")

    def _fit(self, df: Table) -> "RankingAdapterModel":
        rec = self.get("recommender")
        if rec is None:
            raise ValueError("RankingAdapter: recommender is not set")
        model = rec.copy().fit(df)
        passthrough = {p: self.get(p) for p in self._paramMap
                       if p != "recommender"}
        return RankingAdapterModel(recommenderModel=model, **passthrough)


class RankingAdapterModel(Model, _RankingParams):
    recommenderModel = Param("recommenderModel", "Fitted recommender",
                             is_complex=True)
    mode = Param("mode", "Recommendation mode", str, "allUsers")

    def _transform(self, df: Table) -> Table:
        model = self.get("recommenderModel")
        recs = model.recommend_for_user_subset(df, self.getK())
        rec_of = {int(u): list(map(int, r)) for u, r in
                  zip(recs[self.getUserCol()], recs["recommendations"])}
        users = np.asarray(df[self.getUserCol()], dtype=np.int64)
        items = np.asarray(df[self.getItemCol()], dtype=np.int64)
        truth: Dict[int, List[int]] = {}
        for u, i in zip(users, items):
            truth.setdefault(int(u), []).append(int(i))
        uniq = sorted(truth)
        pred = np.empty(len(uniq), dtype=object)
        label = np.empty(len(uniq), dtype=object)
        for r, u in enumerate(uniq):
            pred[r] = rec_of.get(u, [])
            label[r] = truth[u]
        return Table({self.getUserCol(): np.asarray(uniq),
                      "prediction": pred, "label": label})


class RankingEvaluator(Params):
    """Ranking metrics over (prediction, label) array columns.

    Reference: RankingEvaluator.scala / AdvancedRankingMetrics:24-97. Metrics:
    ndcgAt (binary relevance), map, precisionAtk, recallAtK, mrr,
    diversityAtK (#unique recommended / nItems), maxDiversity
    (#unique in labels ∪ recommendations / nItems), fcp (fraction of
    predicted-order pairs concordant with relevance).
    """

    metricName = Param("metricName", f"One of {_METRICS}", str, "ndcgAt",
                       validator=lambda v: v if v in _METRICS else
                       (_ for _ in ()).throw(ValueError(
                           f"metricName must be one of {_METRICS}, got {v!r}")))
    k = Param("k", "Cutoff for @k metrics", int, 10)
    nItems = Param("nItems", "Number of items (for diversity metrics)", int, -1)
    predictionCol = Param("predictionCol", "Prediction column", str, "prediction")
    labelCol = Param("labelCol", "Label column", str, "label")

    def isLargerBetter(self) -> bool:
        return True

    def evaluate(self, df: Table) -> float:
        return self.get_metrics(df)[self.getMetricName()]

    def get_metrics(self, df: Table) -> Dict[str, float]:
        preds = [list(p) for p in df[self.getPredictionCol()]]
        labels = [list(l) for l in df[self.getLabelCol()]]
        k = self.getK()
        ndcg, ap, prec, rec, mrr, fcp = [], [], [], [], [], []
        rec_items, lab_items = set(), set()
        for p, l in zip(preds, labels):
            lset = set(l)
            rec_items.update(p)
            lab_items.update(l)
            hits = [1.0 if x in lset else 0.0 for x in p]
            # ndcg@k (binary relevance)
            dcg = sum(h / np.log2(i + 2) for i, h in enumerate(hits[:k]))
            idcg = sum(1.0 / np.log2(i + 2) for i in range(min(k, len(lset))))
            ndcg.append(dcg / idcg if idcg > 0 else 0.0)
            # average precision (full list)
            got, ap_sum = 0, 0.0
            for i, h in enumerate(hits):
                if h:
                    got += 1
                    ap_sum += got / (i + 1.0)
            ap.append(ap_sum / max(len(lset), 1))
            prec.append(sum(hits[:k]) / float(k))
            rec.append(sum(hits[:k]) / max(len(lset), 1))
            mrr.append(next((1.0 / (i + 1) for i, h in enumerate(hits) if h), 0.0))
            pairs = concord = 0
            for i in range(len(hits)):
                for j in range(i + 1, len(hits)):
                    pairs += 1
                    concord += hits[i] >= hits[j]
            fcp.append(concord / pairs if pairs else 0.0)
        n_items = self.getNItems()
        if n_items <= 0:
            n_items = max(len(rec_items | lab_items), 1)
        return {
            "ndcgAt": float(np.mean(ndcg)) if ndcg else 0.0,
            "map": float(np.mean(ap)) if ap else 0.0,
            "mapk": float(np.mean(ap)) if ap else 0.0,
            "precisionAtk": float(np.mean(prec)) if prec else 0.0,
            "recallAtK": float(np.mean(rec)) if rec else 0.0,
            "mrr": float(np.mean(mrr)) if mrr else 0.0,
            "fcp": float(np.mean(fcp)) if fcp else 0.0,
            "diversityAtK": len(rec_items) / n_items,
            "maxDiversity": len(rec_items | lab_items) / n_items,
        }

    getMetrics = get_metrics


class RankingTrainValidationSplit(Estimator, _RankingParams):
    """Per-user holdout + grid search over a recommender's params
    (reference RankingTrainValidationSplit.scala)."""

    estimator = Param("estimator", "Recommender estimator", is_complex=True)
    evaluator = Param("evaluator", "RankingEvaluator", is_complex=True)
    estimatorParamMaps = Param("estimatorParamMaps",
                               "list of {param: value} dicts", is_complex=True)
    trainRatio = Param("trainRatio", "Fraction of each user's rows for training",
                       float, 0.75)
    seed = Param("seed", "Split seed", int, 0)

    def _split(self, df: Table):
        users = np.asarray(df[self.getUserCol()], dtype=np.int64)
        rng = np.random.default_rng(self.getSeed())
        train_mask = np.zeros(len(users), dtype=bool)
        for u in np.unique(users):
            idx = np.flatnonzero(users == u)
            n_train = max(1, int(round(len(idx) * self.getTrainRatio())))
            chosen = rng.permutation(idx)[:n_train]
            train_mask[chosen] = True
        return df.take(np.flatnonzero(train_mask)), df.take(np.flatnonzero(~train_mask))

    def _fit(self, df: Table) -> "RankingTrainValidationSplitModel":
        est = self.get("estimator")
        ev: RankingEvaluator = self.get("evaluator") or RankingEvaluator()
        grids: List[dict] = self.get("estimatorParamMaps") or [{}]
        train, val = self._split(df)
        results = []
        for grid in grids:
            adapter = RankingAdapter(
                recommender=est.copy(grid), k=ev.getK(),
                userCol=self.getUserCol(), itemCol=self.getItemCol(),
                ratingCol=self.getRatingCol())
            model = adapter.fit(train)
            metric = ev.evaluate(model.transform(val))
            results.append((metric, grid, model))
        results.sort(key=lambda r: r[0], reverse=ev.isLargerBetter())
        best_metric, best_grid, best_model = results[0]
        return RankingTrainValidationSplitModel(
            bestModel=best_model, validationMetrics=[r[0] for r in results],
            bestParams=best_grid, bestMetric=best_metric)


class RankingTrainValidationSplitModel(Model):
    bestModel = Param("bestModel", "Best fitted RankingAdapterModel", is_complex=True)
    validationMetrics = Param("validationMetrics", "Metric per grid", is_complex=True)
    bestParams = Param("bestParams", "Winning param map", is_complex=True)
    bestMetric = Param("bestMetric", "Winning metric value", float)

    def _transform(self, df: Table) -> Table:
        return self.get("bestModel").transform(df)
