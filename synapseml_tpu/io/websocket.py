"""Minimal RFC 6455 websocket client — the transport under the streaming
Speech SDK transformer (services/speech.py SpeechToTextSDK).

The reference ships Microsoft's Speech SDK native websocket stack
(cognitive/.../services/speech/SpeechToTextSDK.scala); this is a dependency-
free client implementing the pieces that protocol needs: the HTTP Upgrade
handshake, client-masked text/binary frames (FIN-only, no fragmentation on
send), ping/pong, and close. The socket is injectable so tests drive the full
protocol against an in-process fake server (SURVEY §4.6 fake-backend style).
"""

from __future__ import annotations

import base64
import hashlib
import os
import socket
import ssl
import struct
from typing import Dict, Optional, Tuple
from urllib.parse import urlparse

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT, OP_TEXT, OP_BINARY, OP_CLOSE, OP_PING, OP_PONG = 0, 1, 2, 8, 9, 10


class WebSocketError(RuntimeError):
    pass


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise WebSocketError("connection closed mid-frame")
        buf += chunk
    return buf


def encode_frame(opcode: int, payload: bytes, mask: bool = True,
                 fin: bool = True) -> bytes:
    """One websocket frame (client frames are masked per RFC 6455 §5.3)."""
    head = bytearray([(0x80 if fin else 0) | opcode])
    n = len(payload)
    mask_bit = 0x80 if mask else 0
    if n < 126:
        head.append(mask_bit | n)
    elif n < (1 << 16):
        head.append(mask_bit | 126)
        head += struct.pack(">H", n)
    else:
        head.append(mask_bit | 127)
        head += struct.pack(">Q", n)
    if mask:
        key = os.urandom(4)
        head += key
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return bytes(head) + payload


def decode_frame(sock) -> Tuple[int, bool, bytes]:
    """Read one frame → (opcode, fin, payload). Unmasks if masked."""
    b0, b1 = _recv_exact(sock, 2)
    fin = bool(b0 & 0x80)
    opcode = b0 & 0x0F
    masked = bool(b1 & 0x80)
    n = b1 & 0x7F
    if n == 126:
        n = struct.unpack(">H", _recv_exact(sock, 2))[0]
    elif n == 127:
        n = struct.unpack(">Q", _recv_exact(sock, 8))[0]
    key = _recv_exact(sock, 4) if masked else None
    payload = _recv_exact(sock, n) if n else b""
    if key:
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return opcode, fin, payload


class WebSocketClient:
    """Client connection. ``sock`` may be injected (tests / custom
    transports); otherwise TCP (+TLS for wss) is opened from the url."""

    def __init__(self, url: str, headers: Optional[Dict[str, str]] = None,
                 sock=None, timeout: float = 30.0):
        self.url = url
        u = urlparse(url)
        self.host = u.hostname or "localhost"
        self.port = u.port or (443 if u.scheme == "wss" else 80)
        self.resource = (u.path or "/") + (f"?{u.query}" if u.query else "")
        self.headers = dict(headers or {})
        self._sock = sock
        self.timeout = timeout
        self._open = False

    def connect(self) -> "WebSocketClient":
        if self._sock is None:
            raw = socket.create_connection((self.host, self.port),
                                           timeout=self.timeout)
            if self.url.startswith("wss"):
                raw = ssl.create_default_context().wrap_socket(
                    raw, server_hostname=self.host)
            self._sock = raw
        key = base64.b64encode(os.urandom(16)).decode()
        lines = [f"GET {self.resource} HTTP/1.1",
                 f"Host: {self.host}:{self.port}",
                 "Upgrade: websocket", "Connection: Upgrade",
                 f"Sec-WebSocket-Key: {key}", "Sec-WebSocket-Version: 13"]
        lines += [f"{k}: {v}" for k, v in self.headers.items()]
        self._sock.sendall(("\r\n".join(lines) + "\r\n\r\n").encode())
        # read the 101 response
        resp = b""
        while b"\r\n\r\n" not in resp:
            chunk = self._sock.recv(4096)
            if not chunk:
                raise WebSocketError("handshake: connection closed")
            resp += chunk
        status = resp.split(b"\r\n", 1)[0].decode()
        if " 101 " not in status + " ":
            raise WebSocketError(f"handshake rejected: {status}")
        accept_expected = base64.b64encode(hashlib.sha1(
            (key + _GUID).encode()).digest()).decode()
        for line in resp.split(b"\r\n"):
            if line.lower().startswith(b"sec-websocket-accept:"):
                got = line.split(b":", 1)[1].strip().decode()
                if got != accept_expected:
                    raise WebSocketError("handshake: bad Sec-WebSocket-Accept")
        self._open = True
        return self

    def send_text(self, text: str) -> None:
        self._sock.sendall(encode_frame(OP_TEXT, text.encode()))

    def send_binary(self, payload: bytes) -> None:
        self._sock.sendall(encode_frame(OP_BINARY, payload))

    def recv(self) -> Tuple[int, bytes]:
        """Next data frame → (opcode, payload). Answers pings; reassembles
        fragmented messages; raises on close."""
        msg = b""
        op_first = None
        while True:
            opcode, fin, payload = decode_frame(self._sock)
            if opcode == OP_PING:
                self._sock.sendall(encode_frame(OP_PONG, payload))
                continue
            if opcode == OP_PONG:
                continue
            if opcode == OP_CLOSE:
                self._open = False
                raise WebSocketError("closed by peer")
            if opcode in (OP_TEXT, OP_BINARY):
                op_first = opcode if op_first is None else op_first
                msg += payload
            elif opcode == OP_CONT:
                msg += payload
            if fin:
                return op_first if op_first is not None else opcode, msg

    def close(self) -> None:
        if self._open and self._sock is not None:
            try:
                self._sock.sendall(encode_frame(OP_CLOSE, b""))
            except OSError:
                pass
        self._open = False
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def __enter__(self):
        return self.connect() if not self._open else self

    def __exit__(self, *exc):
        self.close()


def server_handshake(sock) -> Dict[str, str]:
    """Server side of the Upgrade handshake (used by the in-process fake
    Speech server in tests). Returns the request headers."""
    req = b""
    while b"\r\n\r\n" not in req:
        chunk = sock.recv(4096)
        if not chunk:
            raise WebSocketError("handshake: client hung up")
        req += chunk
    headers = {}
    for line in req.split(b"\r\n")[1:]:
        if b":" in line:
            k, v = line.split(b":", 1)
            headers[k.strip().decode().lower()] = v.strip().decode()
    key = headers.get("sec-websocket-key", "")
    accept = base64.b64encode(hashlib.sha1(
        (key + _GUID).encode()).digest()).decode()
    sock.sendall((f"HTTP/1.1 101 Switching Protocols\r\n"
                  f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
                  f"Sec-WebSocket-Accept: {accept}\r\n\r\n").encode())
    return headers
