"""Distributed serving — one embedded server per mesh process, plus a
load-balancing gateway with cross-process request forwarding.

Reference: DistributedHTTPSource (core/.../streaming/DistributedHTTPSource.scala:
203-312) runs a ``JVMSharedServer`` inside EVERY executor JVM and a
``WorkerServer`` per partition (continuous/HTTPSourceV2.scala:485-713) with a
request queue, a reply-by-id routing table, and crashed-partition request
rehydration. Notably the reference's own cross-machine forwarding
(``InternalHandler``, ``replyTo`` for a non-local machine) is
``NotImplementedError`` — traffic distribution is left to an external load
balancer. Here the same worker-per-process architecture is kept (each process
on the mesh embeds a :class:`~synapseml_tpu.io.serving.ServingServer` running
the SAME jitted pipeline on its local shard of capacity), and the internal
routing layer is actually implemented: a :class:`ServingGateway` pools
keep-alive connections to every worker, picks the least-loaded one per
request, relays the reply to the caller (reply-by-id across processes), and
retries on a sibling when a worker dies mid-request (the rehydration analog).

TPU framing: serving is host-side IO; each process owns one chip (or a
local-device slice), so "the process holding capacity" = the worker whose
in-flight count is lowest. The pipeline inside each worker is a jitted XLA
program; micro-batching happens inside ServingServer exactly as in the
single-node mode.
"""

from __future__ import annotations

import http.client
import queue
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Sequence

from ..core.logging import record_failure
from ..core.resilience import DEADLINE_HEADER, CircuitBreaker, Deadline
from ..core.table import Table
from .serving import ServingServer, _PendingRequest


def _detect_local_ip() -> str:
    """Routable local address: the UDP-connect trick reads the kernel's
    chosen source interface without sending a packet —
    gethostbyname(gethostname()) resolves to 127.0.x.1 on common /etc/hosts
    configs, which would advertise an unreachable worker."""
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))   # no packets are sent
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()


class _WorkerLink:
    """Connection pool + in-flight accounting + passive health for one
    downstream worker. Health is a three-state circuit breaker
    (core/resilience.py) fed only by the traffic that flows anyway: repeated
    transport failures OPEN the link (skipped by selection), an elapsed
    cooldown admits exactly one HALF-OPEN probe, and a probe success closes
    it again."""

    def __init__(self, host: str, port: int, timeout: float,
                 breaker: Optional[CircuitBreaker] = None):
        self.host, self.port = host, port
        self.timeout = timeout
        self.inflight = 0
        self.breaker = breaker or CircuitBreaker()
        self.ok_count = 0
        self.fail_count = 0
        self._pool: "queue.LifoQueue[http.client.HTTPConnection]" = \
            queue.LifoQueue()
        self._lock = threading.Lock()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # back-compat views of the breaker state (older health consumers)
    @property
    def failures(self) -> int:
        return self.breaker.consecutive_failures

    @property
    def down_until(self) -> float:
        return self.breaker.open_until if \
            self.breaker.state == CircuitBreaker.OPEN else 0.0

    def _get_conn(self) -> Optional[http.client.HTTPConnection]:
        """Pooled connection or None (callers then dial fresh)."""
        try:
            return self._pool.get_nowait()
        except queue.Empty:
            return None

    def forward(self, method: str, path: str, body: bytes,
                headers: Dict[str, str]) -> tuple:
        """One forwarded request; returns (status, body). Raises on transport
        failure (caller retries on a sibling). A failure on a POOLED
        keep-alive connection retries once on a FRESH one first: workers
        close idle connections after ~30s (serving.py Handler.timeout), and
        that stale-socket error must not read as a dead worker — it would
        cool down every healthy worker after any idle period."""
        def send(conn):
            try:
                conn.request(method, path, body=body, headers=headers)
                r = conn.getresponse()
                payload = r.read()
                self._pool.put(conn)
                return r.status, payload
            except Exception:
                conn.close()       # broken conn must not re-pool
                raise

        pooled = self._get_conn()
        if pooled is not None:
            try:
                return send(pooled)
            except Exception:
                pass               # stale keep-alive conn: retry fresh below
        return send(http.client.HTTPConnection(self.host, self.port,
                                               timeout=self.timeout))

    def mark_ok(self) -> None:
        with self._lock:
            self.ok_count += 1
        self.breaker.record_success()

    def mark_failed(self) -> None:
        with self._lock:
            self.fail_count += 1
        self.breaker.record_failure()
        record_failure("gateway.backend_failure", worker=self.url)

    def health(self, now: float) -> Dict:
        return {"url": self.url, "inflight": self.inflight,
                "ok": self.ok_count, "failed": self.fail_count,
                "down": not self.breaker.available(now),
                **self.breaker.snapshot()}


class ServingGateway:
    """Public endpoint forwarding to per-process workers (the implemented
    version of the reference's stubbed InternalHandler shuffle routing).

    ``mode``: ``least_loaded`` (default — route to the worker with the fewest
    in-flight forwards) or ``round_robin``. A worker that fails a forward
    trips its circuit breaker toward OPEN (``breaker_threshold`` consecutive
    transport failures; ``cooldown`` seconds out, escalating on repeated
    trips) and the request retries on a sibling; an OPEN worker is skipped
    entirely until its cooldown admits a half-open probe. Only when every
    worker fails — or every breaker is open — does the client see a fast 502
    (single-request semantics preserved: at-most-once per worker, the reply
    returns to the original caller's still-open connection — reply-by-id
    across processes). A client ``X-Deadline-Ms`` budget is re-anchored here
    and propagated to the worker, and sibling retries stop once it expires."""

    def __init__(self, worker_urls: Sequence[str], host: str = "127.0.0.1",
                 port: int = 0, api_path: str = "/",
                 mode: str = "least_loaded", forward_timeout: float = 30.0,
                 cooldown: float = 1.0, breaker_threshold: int = 3,
                 max_retries: Optional[int] = None,
                 local_worker: Optional[ServingServer] = None,
                 local_index: Optional[int] = None):
        if mode not in ("least_loaded", "round_robin"):
            raise ValueError(f"unknown load-balancing mode {mode!r}")
        self.breaker_threshold = breaker_threshold
        self.links: List[_WorkerLink] = []
        for u in worker_urls:
            hostport = u.split("//", 1)[-1].split("/", 1)[0]
            h, _, p = hostport.partition(":")
            self.links.append(_WorkerLink(
                h, int(p or 80), forward_timeout,
                breaker=CircuitBreaker(failure_threshold=breaker_threshold,
                                       cooldown=cooldown)))
        # the co-located worker (same process as the gateway): requests
        # routed to it enqueue DIRECTLY into its micro-batch queue instead
        # of paying a loopback HTTP round trip — the reference gets the same
        # effect from its shared-JVM SharedSingleton server. Identified by
        # INDEX in worker_urls (ports collide across hosts); port matching
        # is the single-host fallback.
        self._local = local_worker
        self._local_link = None
        if local_worker is not None:
            if local_index is not None:
                if not 0 <= local_index < len(self.links):
                    raise ValueError(
                        f"local_index {local_index} out of range for "
                        f"{len(self.links)} workers")
                self._local_link = self.links[local_index]
            else:
                # single-host fallback: host AND port must match — ports
                # alone collide across hosts (the normal StatefulSet
                # topology), and mis-marking a remote link as local would
                # silently starve that worker. A worker bound to the
                # wildcard address matches only link hosts that resolve to
                # THIS machine (loopback or the detected interface address).
                self_hosts = {"127.0.0.1", "localhost", local_worker.host}
                if local_worker.host in ("0.0.0.0", "::", ""):
                    self_hosts.add(_detect_local_ip())
                for l in self.links:
                    if l.port == local_worker.port and l.host in self_hosts:
                        self._local_link = l
                        break
        if not self.links:
            raise ValueError("gateway needs at least one worker url")
        self.host, self.port = host, port
        self.api_path = api_path
        self.mode = mode
        self.forward_timeout = forward_timeout
        self.cooldown = cooldown
        self.max_retries = (len(self.links) if max_retries is None
                            else max_retries)
        self._rr = 0
        self._lock = threading.Lock()
        self._httpd = None
        self.stats = {"forwarded": 0, "retried": 0, "failed": 0}

    # --- worker selection ----------------------------------------------
    def _pick(self, exclude: set) -> Optional[_WorkerLink]:
        now = time.monotonic()
        with self._lock:
            up = [l for l in self.links
                  if id(l) not in exclude and l.breaker.available(now)]
            if not up:
                # every remaining worker's breaker is OPEN inside its
                # cooldown: fail fast (the breaker's whole point) instead of
                # dialing known-bad backends
                return None
            if self.mode == "round_robin":
                self._rr += 1
                order = up[self._rr % len(up):] + up[:self._rr % len(up)]
            else:
                order = sorted(up, key=lambda l: l.inflight)
            # try_acquire consumes the single half-open probe slot; a link
            # that loses the probe race falls through to the next candidate
            for link in order:
                if link.breaker.try_acquire(now):
                    return link
            return None

    def _forward(self, method: str, path: str, body: bytes,
                 headers: Dict[str, str],
                 deadline: Optional[Deadline] = None) -> tuple:
        tried: set = set()
        last_err = None
        for _ in range(self.max_retries):
            if deadline is not None and deadline.expired():
                record_failure("gateway.deadline_expired")
                return 504, b'{"error": "deadline exceeded at gateway"}'
            link = self._pick(tried)
            if link is None:
                break
            tried.add(id(link))
            with self._lock:
                link.inflight += 1
            try:
                if deadline is not None:
                    # re-anchor the remaining budget for the next hop
                    headers = {**headers,
                               DEADLINE_HEADER: deadline.header_value()}
                if link is self._local_link:
                    status, payload = self._forward_local(body, deadline)
                else:
                    status, payload = link.forward(method, path, body,
                                                   headers)
                link.mark_ok()
                with self._lock:
                    self.stats["forwarded"] += 1
                return status, payload
            except Exception as e:  # transport failure -> retry on sibling
                last_err = e
                link.mark_failed()
                with self._lock:
                    self.stats["retried"] += 1
                record_failure("gateway.retry", worker=link.url)
            finally:
                with self._lock:
                    link.inflight -= 1
        with self._lock:
            self.stats["failed"] += 1
        record_failure("gateway.no_backend")
        return 502, (b'{"error": "no serving worker reachable: %s"}'
                     % str(last_err).encode()[:200])

    def _forward_local(self, body: bytes,
                       deadline: Optional[Deadline] = None) -> tuple:
        """In-process fast path: enqueue into the co-located worker's
        micro-batch queue and wait for its reply-by-id, skipping the
        loopback HTTP hop entirely."""
        if self._local._stop.is_set() or self._local._draining.is_set():
            # fail as fast as the HTTP path's ECONNREFUSED / 503 would: the
            # queue accepts puts forever, but a stopped serve loop never
            # replies and a draining one should shed
            raise ConnectionError("local serving worker is stopped/draining")
        budget = min(self.forward_timeout, self._local.reply_timeout)
        if deadline is not None:
            budget = min(budget, deadline.remaining())
        req = _PendingRequest(
            id=uuid.uuid4().hex, method="POST", path=self.api_path,
            headers={}, body=body, deadline=Deadline.after(budget),
            admitted_at=time.monotonic())
        try:
            self._local._queue.put_nowait(req)
        except queue.Full:
            # the local worker's bounded admission queue applies to the
            # fast path too — a full queue reads as an overloaded worker
            # and the sibling retry takes over
            raise ConnectionError("local serving worker queue full")
        # the gateway's failover bound applies here exactly as it does to an
        # HTTP forward — a wedged local serve loop must not stall requests
        # past forward_timeout before the sibling retry
        if not req.reply_event.wait(budget):
            raise TimeoutError("local worker reply timeout")
        status, _headers, payload = req.response
        return status, payload

    # --- embedded public server ----------------------------------------
    def start(self) -> "ServingGateway":
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True
            timeout = 30

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                fwd_headers = {"Content-Type": self.headers.get(
                    "Content-Type", "application/json"),
                    "Content-Length": str(len(body))}
                # no header -> no gateway deadline (forward_timeout already
                # bounds each attempt; a synthetic deadline equal to it
                # would starve the sibling retry). An explicit budget is
                # capped at the gateway's own total-work bound.
                raw = self.headers.get(DEADLINE_HEADER)
                deadline = (None if raw is None else Deadline.from_header_ms(
                    raw, outer.forward_timeout * outer.max_retries))
                status, payload = outer._forward("POST", outer.api_path,
                                                 body, fwd_headers,
                                                 deadline=deadline)
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):  # noqa: N802  — health/stats endpoint
                import json as _json

                now = time.monotonic()
                body = _json.dumps({
                    "workers": [l.health(now) for l in outer.links],
                    **outer.stats}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        class _Server(ThreadingHTTPServer):
            request_queue_size = 256
            daemon_threads = True

        self._httpd = _Server((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}{self.api_path}"

    def __enter__(self) -> "ServingGateway":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class DistributedServingServer:
    """Mesh-wide serving: every process starts a worker ServingServer running
    ``handler`` on its local capacity; worker addresses are exchanged over the
    distributed backend (the DCN rendezvous the reference does through Spark's
    driver); process 0 additionally exposes the public gateway.

    Single-process fallback: with no distributed backend this degrades to one
    worker + gateway on the same host (still exercising the forwarding hop).
    """

    def __init__(self, handler: Callable[[Table], Table],
                 host: Optional[str] = None, gateway_port: int = 0,
                 worker_port: int = 0, mode: str = "least_loaded",
                 max_batch_size: int = 64, max_batch_latency: float = 0.0,
                 advertise_host: Optional[str] = None):
        self.handler = handler
        # None = auto: loopback single-process; all interfaces when the
        # advertised address must be reachable from OTHER hosts
        self.host = host
        # multi-host: the address OTHER processes reach this worker at
        # (default: auto-detected routable interface address)
        self.advertise_host = advertise_host
        self.gateway_port = gateway_port
        self.worker_port = worker_port
        self.mode = mode
        self.max_batch_size = max_batch_size
        self.max_batch_latency = max_batch_latency
        self.worker: Optional[ServingServer] = None
        self.gateway: Optional[ServingGateway] = None

    _local_ip = staticmethod(_detect_local_ip)

    def _gather_worker_addrs(self, port: int) -> List[str]:
        """All-gather (ip, port) across processes. Ports ride a tiny int
        array through the collective layer — the only cross-process exchange
        serving needs (requests themselves flow over plain HTTP)."""
        import jax

        if jax.process_count() == 1:
            return [f"http://{self.host or '127.0.0.1'}:{port}"]
        import numpy as np
        from jax.experimental import multihost_utils

        import socket

        ip = self.advertise_host or self._local_ip()
        # IP ships as 4 octets (NOT one packed u32: jax's x64-disabled
        # default would downcast the int64 array to int32 and overflow)
        octets = [int(b) for b in socket.inet_aton(ip)]
        local = np.asarray([octets + [port]], np.int32)
        allv = np.asarray(multihost_utils.process_allgather(local))
        allv = allv.reshape(-1, 5)
        return [f"http://{a}.{b}.{c}.{d}:{int(p)}"
                for a, b, c, d, p in allv]

    def start(self) -> "DistributedServingServer":
        import jax

        multi = jax.process_count() > 1
        bind = self.host or ("0.0.0.0" if multi else "127.0.0.1")
        self.worker = ServingServer(
            self.handler, host=bind, port=self.worker_port,
            max_batch_size=self.max_batch_size,
            max_batch_latency=self.max_batch_latency).start()
        urls = self._gather_worker_addrs(self.worker.port)
        if jax.process_index() == 0:
            self.gateway = ServingGateway(
                urls, host=bind, port=self.gateway_port,
                mode=self.mode, local_worker=self.worker,
                local_index=jax.process_index()).start()
        return self

    def stop(self) -> None:
        if self.gateway is not None:
            self.gateway.stop()
        if self.worker is not None:
            self.worker.stop()

    @property
    def url(self) -> str:
        """Public endpoint (gateway on process 0, else the local worker)."""
        if self.gateway is not None:
            return self.gateway.url
        return self.worker.url

    def __enter__(self) -> "DistributedServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
