"""Distributed serving — a fault-tolerant multi-host fabric: per-process
workers, a load-balancing gateway with dynamic membership, bucket-aware
routing, and zero-downtime model hot-swap.

Reference: DistributedHTTPSource (core/.../streaming/DistributedHTTPSource.scala:
203-312) runs a ``JVMSharedServer`` inside EVERY executor JVM and a
``WorkerServer`` per partition (continuous/HTTPSourceV2.scala:485-713) with a
request queue, a reply-by-id routing table, and crashed-partition request
rehydration. Notably the reference's own cross-machine forwarding
(``InternalHandler``, ``replyTo`` for a non-local machine) is
``NotImplementedError`` — traffic distribution is left to an external load
balancer. Here the same worker-per-process architecture is kept (each process
on the mesh embeds a :class:`~synapseml_tpu.io.serving.ServingServer` running
the SAME jitted pipeline on its local shard of capacity), and the internal
routing layer is actually implemented — and made dynamic:

* **Membership** — workers register and heartbeat with the gateway
  (``POST /__fabric/heartbeat``; :class:`WorkerAgent` is the worker-side
  reporter). Missed heartbeats EVICT a link — distinct from a breaker OPEN:
  eviction frees the link's routing state (connection pool, affinity,
  selection slot) while OPEN keeps the link and re-probes it. An evicted
  worker that heartbeats again rejoins cleanly, and brand-new workers can
  join a RUNNING gateway, which is the autoscaling hook
  :class:`FabricSupervisor` drives from queue-depth gauges.
* **Bucket-aware routing** — heartbeats advertise each worker's warmed
  bucket ladder (``BucketedRunner.warm_buckets()``); the gateway prefers
  the replica whose AOT cache already covers a request's batch bucket and
  keeps same-shape traffic sticky on one replica, falling back to
  least-loaded whenever the hint is absent or stale. Routing degrades,
  never fails: any shape-inference or staleness problem means "route by
  load", exactly the pre-fabric behavior.
* **Failover** — per-worker three-state circuit breakers, sibling retry on
  transport failure, deadline re-anchoring per hop, fast 502 only when no
  backend remains. The fabric invariant (chaos-proven by
  ``tests/test_fabric.py``): an ACCEPTED request (non-503) is never
  dropped — it completes on some worker or fails its own deadline with a
  504, even under worker kill, heartbeat partition, or kill-mid-swap.

TPU framing: serving is host-side IO; each process owns one chip (or a
local-device slice), so "the process holding capacity" = the worker whose
in-flight count is lowest — unless a warm-cache hint says a sibling can skip
an XLA compile. The pipeline inside each worker is a jitted XLA program;
micro-batching happens inside ServingServer exactly as in the single-node
mode, and model hot-swap is the worker-local
:class:`~synapseml_tpu.io.serving.ModelRegistry`.
"""

from __future__ import annotations

import http.client
import json as _json
import queue
import random
import threading
import time
import uuid
from typing import (Callable, Dict, List, Optional, Sequence, Tuple,
                    Union)

from ..core.gossip import ConsistentHashRing, GossipState
from ..core.logging import record_failure
from ..core.qos import (DEFAULT_TENANT, TENANT_HEADER, BudgetLeaseLedger,
                        QoSController)
from ..core.resilience import (DEADLINE_HEADER, CircuitBreaker, Deadline,
                               Membership)
from ..core.table import Table
from .serving import ModelRegistry, ServingServer, _PendingRequest

#: Gateway control-plane path prefix — requests here are membership traffic,
#: never forwarded to a worker.
FABRIC_PATH_PREFIX = "/__fabric/"

#: Optional client hint: row count of a batched payload, for bucket-aware
#: routing without parsing the body.
SHAPE_ROWS_HEADER = "X-Batch-Rows"

# Heartbeat chaos hook: WorkerAgent consults it before every beat; a falsy
# return drops the beat on the floor (a network partition between worker and
# gateway that leaves the DATA path intact — the nastiest membership case).
# Installed by testing.chaos.chaos_heartbeat_partition; single global hook.
_HEARTBEAT_HOOK: Optional[Callable[[str], bool]] = None

# Control-plane chaos hook: the gateway replicator consults it before every
# gossip exchange with ``(source_gateway_id, peer_url)``; a falsy return
# drops the exchange — a partition of the REPLICATED control plane that
# leaves data paths and worker heartbeats intact. Installed by
# testing.chaos.chaos_control_plane_partition; single global hook.
_GOSSIP_HOOK: Optional[Callable[[str, str], bool]] = None


def _detect_local_ip() -> str:
    """Routable local address: the UDP-connect trick reads the kernel's
    chosen source interface without sending a packet —
    gethostbyname(gethostname()) resolves to 127.0.x.1 on common /etc/hosts
    configs, which would advertise an unreachable worker."""
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))   # no packets are sent
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()


def _parse_hostport(url: str) -> Tuple[str, int]:
    """``http://h:p[/...]`` (or bare ``h:p``) -> (host, port)."""
    hostport = url.split("//", 1)[-1].split("/", 1)[0]
    h, _, p = hostport.partition(":")
    return h, int(p or 80)


class _GatewayStats:
    """Locked counters for the gateway (the ServingMetrics pattern from
    io/serving.py): handler threads increment concurrently, so every
    mutation and read takes the lock — the bare-dict += this replaces lost
    updates under contention. ``__getitem__`` keeps the historical
    ``gw.stats["forwarded"]`` read surface."""

    _COUNTERS = ("forwarded", "retried", "failed", "heartbeats", "joined",
                 "rejoined", "evicted", "deregistered",
                 "gossip_exchanges", "gossip_failed", "entries_merged",
                 "rate_limited")

    def __init__(self):
        self._lock = threading.Lock()
        self._c = {k: 0 for k in self._COUNTERS}

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._c[name] += n

    def __getitem__(self, name: str) -> int:
        with self._lock:
            return self._c[name]

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._c)


class _WorkerLink:
    """Connection pool + in-flight accounting + passive health for one
    downstream worker. Health is a three-state circuit breaker
    (core/resilience.py) fed only by the traffic that flows anyway: repeated
    transport failures OPEN the link (skipped by selection), an elapsed
    cooldown admits exactly one HALF-OPEN probe, and a probe success closes
    it again. Membership state (heartbeat-advertised warm buckets, queue
    depth, model version) rides on the link for routing reads."""

    def __init__(self, host: str, port: int, timeout: float,
                 breaker: Optional[CircuitBreaker] = None,
                 tenant_breaker_factory: Optional[Callable] = None):
        self.host, self.port = host, port
        self.timeout = timeout
        self.inflight = 0
        self.breaker = breaker or CircuitBreaker()
        self.ok_count = 0
        self.fail_count = 0
        # membership-advertised routing state (updated by heartbeats; all
        # advisory — routing must work with every field at its default)
        self.worker_id: Optional[str] = None
        self.warm_buckets: Tuple[int, ...] = ()
        self.queue_depth: int = 0
        self.version: Optional[str] = None
        # per-(tenant, model) advertisement: tenant -> {"version",
        # "warm_buckets"} — the multi-tenant warm-ladder/version routing
        # inputs (advisory, like everything heartbeat-carried)
        self.tenants: Dict[str, dict] = {}
        # per-tenant passive health: the LINK breaker is transport-level
        # (this worker is unreachable for everyone); a TENANT breaker is
        # "this worker is 5xxing tenant T" (bad model version, poisoned
        # state) — T's traffic skips the replica while other tenants keep
        # using it
        self._tenant_breaker_factory = tenant_breaker_factory or \
            CircuitBreaker
        self.tenant_breakers: Dict[str, CircuitBreaker] = {}
        self._pool: "queue.LifoQueue[http.client.HTTPConnection]" = \
            queue.LifoQueue()
        self._lock = threading.Lock()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # back-compat views of the breaker state (older health consumers)
    @property
    def failures(self) -> int:
        return self.breaker.consecutive_failures

    @property
    def down_until(self) -> float:
        return self.breaker.open_until if \
            self.breaker.state == CircuitBreaker.OPEN else 0.0

    def update_membership(self, info: Dict) -> None:
        with self._lock:
            if "id" in info and info["id"]:
                self.worker_id = str(info["id"])
            if "warm_buckets" in info:
                try:
                    self.warm_buckets = tuple(
                        sorted(int(b) for b in info["warm_buckets"]))
                except (TypeError, ValueError):
                    pass    # advisory data: garbage degrades, never breaks
            if "queue_depth" in info:
                try:
                    self.queue_depth = int(info["queue_depth"])
                except (TypeError, ValueError):
                    pass
            if "version" in info and info["version"] is not None:
                self.version = str(info["version"])
            if isinstance(info.get("tenants"), dict):
                tenants = {}
                for t, entry in info["tenants"].items():
                    if not isinstance(entry, dict):
                        continue    # advisory: garbage degrades
                    parsed = {}
                    if entry.get("version") is not None:
                        parsed["version"] = str(entry["version"])
                    try:
                        parsed["warm_buckets"] = tuple(sorted(
                            int(b) for b in entry.get("warm_buckets", ())))
                    except (TypeError, ValueError):
                        parsed["warm_buckets"] = ()
                    tenants[str(t)] = parsed
                self.tenants = tenants

    def covers_bucket(self, rows: int,
                      tenant: Optional[str] = None) -> bool:
        """Does this worker's advertised warm ladder already hold a compiled
        bucket for a ``rows``-row micro-batch? With a tenant, THAT tenant's
        advertised ladder is consulted (falling back to the worker-wide one
        when the tenant never advertised). False when nothing was ever
        advertised — staleness degrades to load-based routing."""
        with self._lock:
            ladder = self.warm_buckets
            if tenant is not None:
                entry = self.tenants.get(tenant)
                if entry is not None and entry.get("warm_buckets"):
                    ladder = entry["warm_buckets"]
            return any(rows <= b for b in ladder)

    def tenant_available(self, tenant: Optional[str], now: float) -> bool:
        """Non-mutating per-tenant health read (selection-loop safe); a
        tenant with no breaker yet is healthy by definition."""
        if tenant is None:
            return True
        with self._lock:
            breaker = self.tenant_breakers.get(tenant)
        return breaker is None or breaker.available(now)

    def mark_tenant(self, tenant: Optional[str], ok: bool) -> None:
        """Feed a forwarded reply's verdict to the tenant's breaker: 5xx
        replies for tenant T on this replica eventually OPEN (T's traffic
        skips it) without touching the transport breaker or other
        tenants."""
        if tenant is None:
            return
        with self._lock:
            breaker = self.tenant_breakers.get(tenant)
            if breaker is None:
                if ok:
                    return          # no state to close; don't allocate
                breaker = self.tenant_breakers[tenant] = \
                    self._tenant_breaker_factory()
        if ok:
            breaker.record_success()
        else:
            breaker.record_failure()
            record_failure("gateway.tenant_backend_failure",
                           worker=self.url, tenant=tenant)

    def close(self) -> None:
        """Free routing state on eviction: every pooled keep-alive
        connection is closed (an evicted worker's sockets must not linger
        until GC)."""
        while True:
            try:
                self._pool.get_nowait().close()
            except queue.Empty:
                return
            except OSError:
                pass

    def _get_conn(self) -> Optional[http.client.HTTPConnection]:
        """Pooled connection or None (callers then dial fresh)."""
        try:
            return self._pool.get_nowait()
        except queue.Empty:
            return None

    def forward(self, method: str, path: str, body: bytes,
                headers: Dict[str, str]) -> tuple:
        """One forwarded request; returns (status, body). Raises on transport
        failure (caller retries on a sibling). A failure on a POOLED
        keep-alive connection retries once on a FRESH one first: workers
        close idle connections after ~30s (serving.py Handler.timeout), and
        that stale-socket error must not read as a dead worker — it would
        cool down every healthy worker after any idle period."""
        def send(conn):
            try:
                conn.request(method, path, body=body, headers=headers)
                r = conn.getresponse()
                payload = r.read()
                self._pool.put(conn)
                return r.status, payload
            except Exception:
                conn.close()       # broken conn must not re-pool
                raise

        pooled = self._get_conn()
        if pooled is not None:
            try:
                return send(pooled)
            except Exception:
                pass               # stale keep-alive conn: retry fresh below
        return send(http.client.HTTPConnection(self.host, self.port,
                                               timeout=self.timeout))

    def mark_ok(self) -> None:
        with self._lock:
            self.ok_count += 1
        self.breaker.record_success()

    def mark_failed(self) -> None:
        with self._lock:
            self.fail_count += 1
        self.breaker.record_failure()
        record_failure("gateway.backend_failure", worker=self.url)

    def health(self, now: float) -> Dict:
        with self._lock:
            member = {"worker_id": self.worker_id,
                      "warm_buckets": list(self.warm_buckets),
                      "queue_depth": self.queue_depth,
                      "version": self.version,
                      "tenants": {
                          t: {**{k: (list(v) if isinstance(v, tuple)
                                     else v) for k, v in e.items()},
                              **({"breaker": self.tenant_breakers[t]
                                  .snapshot()}
                                 if t in self.tenant_breakers else {})}
                          for t, e in self.tenants.items()}}
        return {"url": self.url, "inflight": self.inflight,
                "ok": self.ok_count, "failed": self.fail_count,
                "down": not self.breaker.available(now),
                **member, **self.breaker.snapshot()}


class ServingGateway:
    """Public endpoint forwarding to per-process workers (the implemented
    version of the reference's stubbed InternalHandler shuffle routing),
    with dynamic membership.

    ``mode``: ``least_loaded`` (default — route to the worker with the
    fewest in-flight forwards, upgraded to bucket-aware when heartbeats
    advertise warm ladders) or ``round_robin``. A worker that fails a
    forward trips its circuit breaker toward OPEN (``breaker_threshold``
    consecutive transport failures; ``cooldown`` seconds out, escalating on
    repeated trips) and the request retries on a sibling; an OPEN worker is
    skipped entirely until its cooldown admits a half-open probe. Only when
    every worker fails — or every breaker is open — does the client see a
    fast 502 (single-request semantics preserved: at-most-once per worker,
    the reply returns to the original caller's still-open connection —
    reply-by-id across processes). A client ``X-Deadline-Ms`` budget is
    re-anchored here and propagated to the worker, and sibling retries stop
    once it expires.

    Membership: links created from ``worker_urls`` are STATIC members —
    they never expire, preserving the fixed-list deployment mode. The
    moment a worker heartbeats (``POST /__fabric/heartbeat``) it becomes a
    dynamic member: ``heartbeat_timeout`` seconds of silence EVICTS it
    (link removed, pooled connections closed, affinity forgotten —
    ``gateway.worker_evicted``), and a later heartbeat from the same url
    rejoins it with a fresh breaker. New workers may heartbeat-join a
    running gateway at any time. Breaker OPEN and eviction are deliberately
    different states: OPEN is "failing traffic right now, keep probing";
    evicted is "gone — free everything, welcome it back if it returns".
    """

    def __init__(self, worker_urls: Sequence[str], host: str = "127.0.0.1",
                 port: int = 0, api_path: str = "/",
                 mode: str = "least_loaded", forward_timeout: float = 30.0,
                 cooldown: float = 1.0, breaker_threshold: int = 3,
                 max_retries: Optional[int] = None,
                 local_worker: Optional[ServingServer] = None,
                 local_index: Optional[int] = None,
                 heartbeat_timeout: float = 3.0,
                 clock=time.monotonic,
                 gateway_id: Optional[str] = None,
                 peers: Sequence[str] = (),
                 gossip_interval: float = 0.25,
                 gossip_timeout: float = 2.0,
                 peer_timeout: Optional[float] = None,
                 lease_ttl: float = 2.0,
                 qos: Optional[QoSController] = None):
        if mode not in ("least_loaded", "round_robin"):
            raise ValueError(f"unknown load-balancing mode {mode!r}")
        self.breaker_threshold = breaker_threshold
        self.forward_timeout = forward_timeout
        self.cooldown = cooldown
        self._clock = clock
        self.membership = Membership(timeout=heartbeat_timeout, clock=clock)
        self.links: List[_WorkerLink] = []
        for u in worker_urls:
            link = self._make_link(u)
            self.links.append(link)
            # static member: a configured URL with no heartbeat reporter
            # stays routable forever (liveness is the breaker's job alone)
            self.membership.beat(link.url, static=True)
        # the co-located worker (same process as the gateway): requests
        # routed to it enqueue DIRECTLY into its micro-batch queue instead
        # of paying a loopback HTTP round trip — the reference gets the same
        # effect from its shared-JVM SharedSingleton server. Identified by
        # INDEX in worker_urls (ports collide across hosts); port matching
        # is the single-host fallback.
        self._local = local_worker
        self._local_link = None
        if local_worker is not None:
            if local_index is not None:
                if not 0 <= local_index < len(self.links):
                    raise ValueError(
                        f"local_index {local_index} out of range for "
                        f"{len(self.links)} workers")
                self._local_link = self.links[local_index]
            else:
                # single-host fallback: host AND port must match — ports
                # alone collide across hosts (the normal StatefulSet
                # topology), and mis-marking a remote link as local would
                # silently starve that worker. A worker bound to the
                # wildcard address matches only link hosts that resolve to
                # THIS machine (loopback or the detected interface address).
                self_hosts = {"127.0.0.1", "localhost", local_worker.host}
                if local_worker.host in ("0.0.0.0", "::", ""):
                    self_hosts.add(_detect_local_ip())
                for l in self.links:
                    if l.port == local_worker.port and l.host in self_hosts:
                        self._local_link = l
                        break
        if not self.links:
            raise ValueError("gateway needs at least one worker url")
        self.host, self.port = host, port
        self.api_path = api_path
        self.mode = mode
        # None = dynamic: retry across however many workers exist NOW (the
        # membership can grow/shrink after start)
        self._max_retries_cfg = max_retries
        self._rr = 0
        self._lock = threading.Lock()
        self._httpd = None
        self.stats = _GatewayStats()
        # shape-affinity routing table: shape key -> worker url. Sticky
        # same-shape traffic concentrates each shape's bucket ladder onto
        # one replica's AOT cache. Bounded FIFO; purely advisory.
        self._affinity: Dict = {}
        self._affinity_cap = 256
        # --- federation: replicated control plane over /__fabric/gossip ---
        # Every gateway holds a GossipState whether or not it has peers; the
        # replicator thread only runs once a peer is configured, so the
        # single-gateway deployment pays nothing.
        self.gateway_id = gateway_id or uuid.uuid4().hex[:12]
        self.gossip = GossipState(self.gateway_id, clock=clock)
        self.gossip_interval = gossip_interval
        self.gossip_timeout = gossip_timeout
        # a peer gateway whose liveness entry stops advancing for this long
        # is dead: its ring arcs rehash and its leases expire
        self.peer_timeout = peer_timeout if peer_timeout is not None \
            else max(4.0 * gossip_interval, 1.0)
        self.lease_ttl = lease_ttl
        # edge-tier QoS: when set, THIS gateway admits per-tenant with its
        # leased share of the class's GLOBAL rate (core/qos.py lease math)
        self.qos = qos
        self.leases = BudgetLeaseLedger(ttl=lease_ttl, clock=clock)
        self.ring = ConsistentHashRing()
        self._active_tenants: Dict[str, float] = {}
        self._peer_urls: List[str] = []
        self._peer_state: Dict[str, dict] = {}   # url -> exchange health
        self._peer_rr = 0
        self.public_url: Optional[str] = None    # resolved in start()
        self._killed = threading.Event()
        self._repl_stop = threading.Event()
        self._repl_thread: Optional[threading.Thread] = None
        for p in peers:
            self.add_peer(p)

    # --- membership -----------------------------------------------------
    def _make_link(self, url: str) -> _WorkerLink:
        h, p = _parse_hostport(url)
        mk = lambda: CircuitBreaker(  # noqa: E731
            failure_threshold=self.breaker_threshold, cooldown=self.cooldown)
        return _WorkerLink(h, p, self.forward_timeout, breaker=mk(),
                           tenant_breaker_factory=mk)

    def register_worker(self, url: str, _replicate: bool = True,
                        **info) -> _WorkerLink:
        """Programmatic join: add (or refresh) a worker link on a RUNNING
        gateway. Idempotent by url; an evicted worker re-registering gets a
        fresh link and breaker (clean rejoin). This is also what a
        ``/__fabric/heartbeat`` from an unknown url does. On a federated
        gateway the (re)registration replicates as a ``member/<url>``
        gossip entry so every peer gateway can route to the worker;
        ``_replicate=False`` is the merge path applying a PEER's entry
        (replicated state must not re-publish — the origin's epoch already
        carries it)."""
        h, p = _parse_hostport(url)
        canonical = f"http://{h}:{p}"
        with self._lock:
            link = next((l for l in self.links if l.url == canonical), None)
            created = link is None
            if created:
                link = self._make_link(canonical)
                self.links.append(link)
        fields = {k: v for k, v in info.items() if k in (
            "queue_depth", "warm_buckets", "version", "id", "tenants")}
        admitted = self.membership.beat(canonical, **fields)
        link.update_membership(info)
        if created:
            self.stats.incr("rejoined" if admitted == "rejoin"
                            else "joined")
            record_failure("gateway.worker_joined", worker=canonical)
        if _replicate and self.federated:
            self.gossip.publish(
                f"member/{canonical}",
                {k: (list(v) if isinstance(v, tuple) else v)
                 for k, v in fields.items()})
        return link

    def deregister_worker(self, url: str) -> bool:
        """Voluntary leave (clean scale-down): evict immediately without
        waiting for the heartbeat timeout."""
        h, p = _parse_hostport(url)
        return self._evict(f"http://{h}:{p}", reason="deregistered")

    def _evict(self, url: str, reason: str = "evicted",
               only_if_expired: bool = False,
               _replicate: bool = True) -> bool:
        """Remove a worker from routing entirely and free its state. The
        counterpart of breaker OPEN: OPEN keeps the link and re-probes;
        eviction forgets it (until a rejoin). ``only_if_expired`` is the
        lazy-sweep mode: staleness is re-checked under the membership lock
        (:meth:`Membership.evict_if_expired`), so a worker whose rejoin
        beat raced the sweep keeps its link and affinity. On a federated
        gateway the eviction replicates as a tombstone — peers must not
        resurrect a dead worker at the next exchange."""
        if only_if_expired:
            if not self.membership.evict_if_expired(url):
                return False
        else:
            self.membership.evict(url)
        with self._lock:
            link = next((l for l in self.links if l.url == url), None)
            if link is None:
                return False
            self.links.remove(link)
            if link is self._local_link:
                self._local_link = None
            # forget this worker's shape affinities so sticky routing
            # re-pins surviving replicas on the next request
            self._affinity = {k: v for k, v in self._affinity.items()
                              if v != url}
        link.close()
        self.stats.incr("deregistered" if reason == "deregistered"
                        else "evicted")
        record_failure(f"gateway.worker_{reason}", worker=url)
        if _replicate and self.federated:
            self.gossip.retract(f"member/{url}")
        return True

    def _sweep_expired(self) -> None:
        """Evict every member whose heartbeat is overdue. Called lazily
        from the selection path and the health endpoint — no sweeper
        thread to leak. Per-member staleness is re-checked under the lock,
        closing the rejoin-during-lazy-eviction race."""
        for url in self.membership.expired():
            self._evict(url, reason="evicted", only_if_expired=True)

    def evict_stale(self) -> list:
        """Explicit idle sweep: the lazy :meth:`_sweep_expired` only runs
        on the routing/health path, so a gateway receiving ZERO traffic
        holds dead workers indefinitely. Supervisor loops
        (:meth:`FabricSupervisor.step`) call this on their own cadence;
        evictions are counted under ``fabric.evicted_idle``."""
        stale = self.membership.expired()
        evicted = [url for url in stale
                   if self._evict(url, reason="evicted",
                                  only_if_expired=True)]
        if evicted:
            record_failure("fabric.evicted_idle", n=len(evicted),
                           members=[str(u) for u in evicted])
        return evicted

    def _handle_control(self, path: str, body: bytes) -> Tuple[int, dict]:
        """Membership control-plane dispatch for ``/__fabric/*`` POSTs."""
        try:
            payload = _json.loads(body.decode()) if body else {}
        except ValueError:
            return 400, {"error": "control payload must be JSON"}
        if not isinstance(payload, dict):
            return 400, {"error": "control payload must be a JSON object"}
        op = path[len(FABRIC_PATH_PREFIX):].strip("/")
        if op == "gossip":
            # anti-entropy push-pull: merge the peer's entries, reply with
            # full local state (their merge of our reply completes the
            # round — one exchange converges both sides)
            self._absorb(str(payload.get("from", "")),
                         payload.get("clock", 0),
                         payload.get("entries", ()))
            return 200, {"ok": True, "from": self.gateway_id,
                         "clock": self.gossip.lamport,
                         "entries": self.gossip.wire()}
        if not payload.get("url"):
            return 400, {"error": "control payload needs a worker 'url'"}
        if op in ("heartbeat", "register"):
            before = set(self.membership.members())
            info = {k: v for k, v in payload.items() if k != "url"}
            link = self.register_worker(str(payload["url"]), **info)
            self.stats.incr("heartbeats")
            self._sweep_expired()
            with self._lock:
                n_workers = len(self.links)
            return 200, {"ok": True, "worker": link.url,
                         "known": link.url in before,
                         "workers": n_workers,
                         # live gateway peers, so WorkerAgent learns every
                         # gateway it can fail its beats over to
                         "gateway_id": self.gateway_id,
                         "peers": self.gateway_urls()}
        if op == "deregister":
            gone = self.deregister_worker(str(payload["url"]))
            with self._lock:
                n_workers = len(self.links)
            return 200, {"ok": True, "removed": gone,
                         "workers": n_workers}
        return 404, {"error": f"unknown fabric op {op!r}"}

    # --- federation: replicated control plane ---------------------------
    @property
    def federated(self) -> bool:
        with self._lock:
            return bool(self._peer_urls)

    def alive(self) -> bool:
        """False once chaos hard-killed this gateway (kill_gateway) — the
        coordinator-liveness input to a survivable PromotionBroadcast."""
        return not self._killed.is_set()

    def add_peer(self, url: str) -> None:
        """Teach this gateway a peer gateway's address (idempotent). The
        replicator thread starts with the first peer on a RUNNING gateway;
        peers added before :meth:`start` begin exchanging at start."""
        h, p = _parse_hostport(url)
        base = f"http://{h}:{p}"
        with self._lock:
            if base not in self._peer_urls:
                self._peer_urls.append(base)
                self._peer_state[base] = {"last_ok": None, "failures": 0,
                                          "clock": 0}
        if self._httpd is not None:
            self._start_replicator()

    def gateway_urls(self) -> List[str]:
        """Public urls of every gateway believed alive (self included) —
        what heartbeat acks advertise so workers can fail over."""
        urls = [self.public_url] if self.public_url else []
        now = self._clock()
        for info in self._peers_alive(now).values():
            if info["alive"] and info["url"] and info["url"] not in urls:
                urls.append(info["url"])
        return urls

    def tenant_home(self, tenant: str) -> Optional[str]:
        """Consistent-hash tenant→gateway affinity: the public url of the
        gateway that should front ``tenant``. Every converged gateway
        computes the same answer; a gateway death rehashes ONLY the dead
        gateway's tenants (ring minimal movement), so warm-ladder routing
        keeps seeing stable (tenant, shape) streams on the survivors."""
        return self.ring.node_for(tenant) or self.public_url

    def _absorb(self, src_id: str, clock, entries) -> List:
        """Merge a peer's entries + clock (request or reply side) and
        apply every accepted entry to local routing/QoS state."""
        if src_id and src_id != self.gateway_id:
            try:
                self.gossip.observe_peer_clock(src_id, int(clock))
            except (TypeError, ValueError):
                pass
        accepted = self.gossip.merge(entries)
        if accepted:
            self.stats.incr("entries_merged", len(accepted))
            self._apply_entries(accepted)
        return accepted

    def _apply_entries(self, accepted) -> None:
        """Fold accepted gossip entries into live gateway state: member
        entries register/evict worker links (so ANY gateway routes to ANY
        worker from converged state), lease entries feed the budget
        ledger, gateway entries refresh the affinity ring. ``promo/``
        records are read lazily by broadcast recovery, not here."""
        ring_dirty = False
        for e in accepted:
            if e.key.startswith("member/"):
                url = e.key[len("member/"):]
                if e.value is None:
                    self._evict(url, reason="evicted", _replicate=False)
                else:
                    self.register_worker(url, _replicate=False, **e.value)
            elif e.key.startswith("lease/"):
                parts = e.key.split("/", 2)
                if len(parts) != 3:
                    continue
                _, tenant, holder = parts
                if e.value is None:
                    self.leases.release(tenant, holder)
                else:
                    self.leases.observe(tenant, holder)
                if self.qos is not None:
                    self.qos.set_rate_share(
                        tenant, self.leases.share(tenant, self.gateway_id))
            elif e.key.startswith("gateway/"):
                ring_dirty = True
        if ring_dirty:
            self._refresh_ring(self._clock())

    # --- federation: edge QoS with leased sub-budgets -------------------
    def edge_admit(self, tenant: str):
        """Edge-tier admission: this gateway's token bucket refills at its
        LEASED share of the tenant's global rate (1/n live leaseholders),
        so K gateways admitting independently enforce one fabric-wide
        per-tenant contract. First contact claims the lease immediately;
        the replicator renews it every tick and retracts it after
        ``lease_ttl`` of tenant silence."""
        self._touch_tenant(tenant)
        decision = self.qos.admit(tenant)
        if not decision.ok:
            self.stats.incr("rate_limited")
        return decision

    def _touch_tenant(self, tenant: str) -> None:
        now = self._clock()
        with self._lock:
            new = tenant not in self._active_tenants
            self._active_tenants[tenant] = now
        if new and self.federated:
            self._renew_lease(tenant)

    def _renew_lease(self, tenant: str) -> None:
        self.gossip.publish(f"lease/{tenant}/{self.gateway_id}",
                            {"holder": self.gateway_id})
        self.leases.observe(tenant, self.gateway_id)
        if self.qos is not None:
            self.qos.set_rate_share(
                tenant, self.leases.share(tenant, self.gateway_id))

    def _renew_leases(self, now: float) -> None:
        with self._lock:
            active = dict(self._active_tenants)
        for tenant, last in active.items():
            if now - last > self.lease_ttl:
                # tenant went quiet here: release our slice so surviving
                # enforcers' shares grow back toward the full contract
                with self._lock:
                    self._active_tenants.pop(tenant, None)
                self.gossip.retract(f"lease/{tenant}/{self.gateway_id}")
                self.leases.release(tenant, self.gateway_id)
            else:
                self._renew_lease(tenant)
        if self.qos is not None:
            for tenant in set(self.leases.tenants()) | set(active):
                self.qos.set_rate_share(
                    tenant, self.leases.share(tenant, self.gateway_id))

    # --- federation: replicator loop ------------------------------------
    def _peers_alive(self, now: float) -> Dict[str, dict]:
        """Peer gateways by id, judged on how recently their liveness
        entry advanced LOCALLY (no cross-host clocks): a peer whose entry
        went ``peer_timeout`` without advancing is dead — partitioned or
        killed — and its arcs leave the affinity ring."""
        out: Dict[str, dict] = {}
        for key, info in self.gossip.items("gateway/").items():
            gid = key[len("gateway/"):]
            if gid == self.gateway_id:
                continue
            at = self.gossip.advanced_at(key)
            age = (now - at) if at is not None else float("inf")
            out[gid] = {"url": info.get("url"),
                        "last_advance_age_s": round(age, 3),
                        "alive": age <= self.peer_timeout}
        return out

    def _refresh_ring(self, now: float) -> None:
        want = {self.public_url} if self.public_url else set()
        for info in self._peers_alive(now).values():
            if info["alive"] and info["url"]:
                want.add(info["url"])
        for node in self.ring.nodes():
            if node not in want:
                self.ring.remove(node)
                record_failure("gateway.peer_left_ring", peer=node)
        for node in want:
            self.ring.add(node)

    def _exchange_once(self) -> bool:
        """One push-pull anti-entropy exchange with the next peer in
        round-robin order. Chaos (``_GOSSIP_HOOK``) or transport failure
        drops the exchange — never the gateway."""
        with self._lock:
            if not self._peer_urls:
                return False
            self._peer_rr += 1
            peer = self._peer_urls[self._peer_rr % len(self._peer_urls)]
        hook = _GOSSIP_HOOK
        if hook is not None and not hook(self.gateway_id, peer):
            self.stats.incr("gossip_failed")
            return False
        body = _json.dumps({"from": self.gateway_id,
                            "clock": self.gossip.lamport,
                            "entries": self.gossip.wire()}).encode()
        h, p = _parse_hostport(peer)
        try:
            conn = http.client.HTTPConnection(h, p,
                                              timeout=self.gossip_timeout)
            try:
                conn.request("POST", FABRIC_PATH_PREFIX + "gossip",
                             body=body,
                             headers={"Content-Type": "application/json"})
                reply = _json.loads(conn.getresponse().read().decode())
            finally:
                conn.close()
        except Exception:  # noqa: BLE001 — a dead peer is routine here
            with self._lock:
                state = self._peer_state.get(peer)
                if state is not None:
                    state["failures"] += 1
            self.stats.incr("gossip_failed")
            record_failure("gateway.gossip_exchange_failed", peer=peer)
            return False
        self._absorb(str(reply.get("from", "")), reply.get("clock", 0),
                     reply.get("entries", ()))
        with self._lock:
            state = self._peer_state.get(peer)
            if state is not None:
                state["last_ok"] = self._clock()
                try:
                    state["clock"] = int(reply.get("clock", 0))
                except (TypeError, ValueError):
                    pass
        self.stats.incr("gossip_exchanges")
        return True

    def _replicate_once(self) -> None:
        now = self._clock()
        # our own liveness entry: the advancing epoch IS the heartbeat
        self.gossip.publish(f"gateway/{self.gateway_id}",
                            {"url": self.public_url})
        self._renew_leases(now)
        self._refresh_ring(now)
        self._exchange_once()

    def _replicate_loop(self) -> None:
        while not self._repl_stop.is_set() and not self._killed.is_set():
            try:
                self._replicate_once()
            except Exception:  # noqa: BLE001 — replication must not die
                record_failure("gateway.gossip_error")
            self._repl_stop.wait(self.gossip_interval)

    def _start_replicator(self) -> None:
        with self._lock:
            if self._repl_thread is not None:
                return
            self._repl_thread = threading.Thread(
                target=self._replicate_loop, daemon=True,
                name=f"gossip-{self.gateway_id}")
        self._replicate_once()      # eager first advertisement + exchange
        self._repl_thread.start()

    def federation_snapshot(self) -> dict:
        """Control-plane observability: replication lag (entries behind
        the newest epoch known anywhere), peer liveness, ring membership,
        lease state — the numbers that show a partition before it bites."""
        now = self._clock()
        with self._lock:
            peer_state = {u: dict(s) for u, s in self._peer_state.items()}
        for s in peer_state.values():
            last = s.pop("last_ok", None)
            s["last_exchange_age_s"] = (round(now - last, 3)
                                        if last is not None else None)
        return {"gateway_id": self.gateway_id,
                "public_url": self.public_url,
                "clock": self.gossip.lamport,
                "entries_behind": self.gossip.entries_behind(),
                "peers": self._peers_alive(now),
                "exchanges": peer_state,
                "ring": self.ring.nodes(),
                "leases": self.leases.snapshot(),
                "gossip": self.gossip.snapshot()}

    # --- worker selection ----------------------------------------------
    def _shape_hint(self, body: bytes,
                    headers=None) -> Optional[Tuple[int, Optional[tuple]]]:
        """(rows, shape_key) inferred from a request, or None. The hint is
        ADVISORY and this helper must degrade, never fail: any parse
        problem, oversized body, or unfamiliar payload shape returns None
        and routing falls back to least-loaded. An explicit
        ``X-Batch-Rows`` header skips body parsing entirely."""
        try:
            if headers is not None:
                raw = headers.get(SHAPE_ROWS_HEADER)
                if raw:
                    return max(int(raw), 1), None
            if not body or len(body) > 4096 or body[:1] != b"{":
                return None
            obj = _json.loads(body)
            if not isinstance(obj, dict):
                return None
            for k in sorted(obj):
                v = obj[k]
                if isinstance(v, list) and v:
                    if isinstance(v[0], list):
                        # batched payload: rows x features
                        return len(v), (k, len(v[0]))
                    return 1, (k, len(v))
            return None
        except Exception:  # noqa: BLE001 — a hint must never fail a request
            return None

    def _pick(self, exclude: set,
              hint: Optional[Tuple[int, Optional[tuple]]] = None,
              tenant: Optional[str] = None) -> Optional[_WorkerLink]:
        now = self._clock()
        self._sweep_expired()
        # the gateway lock guards only the membership LIST; breaker/tenant
        # probes take each link's own lock, so they run on a snapshot —
        # the router never nests the gateway lock around a link lock
        with self._lock:
            candidates = list(self.links)
        up = [l for l in candidates
              if id(l) not in exclude and l.breaker.available(now)
              and l.tenant_available(tenant, now)]
        if not up:
            # every remaining worker's breaker is OPEN inside its
            # cooldown (transport-wide, or for THIS tenant): fail fast
            # (the breaker's whole point) instead of dialing known-bad
            # backends
            return None
        if self.mode == "round_robin":
            with self._lock:
                self._rr += 1
                rr = self._rr
            order = up[rr % len(up):] + up[:rr % len(up)]
        else:
            order = self._bucket_aware_order(up, hint, tenant)
        # try_acquire consumes the single half-open probe slot; a link
        # that loses the probe race falls through to the next candidate
        for link in order:
            if link.breaker.try_acquire(now):
                if hint is not None and hint[1] is not None:
                    with self._lock:
                        self._pin_affinity((tenant, hint[1]), link.url)
                return link
        return None

    def _bucket_aware_order(self, up: List[_WorkerLink], hint,
                            tenant: Optional[str] = None
                            ) -> List[_WorkerLink]:
        """Least-loaded order, upgraded by routing hints when present:
        (1) replicas whose advertised warm ladder already covers the
        request's bucket sort first (an AOT-cache hit beats an idle replica
        that would pay an XLA compile) — per-TENANT ladders when the
        workers advertise them, (2) the (tenant, shape) sticky affinity
        replica wins ties (each tenant's same-shape traffic concentrates
        one cache), and (3) in-flight load breaks the rest. With no hint —
        or stale/absent bucket info — this IS plain least-loaded. Takes
        _lock only for the affinity read; the covers_bucket probes call
        into each link's own lock and must not nest under it."""
        if hint is None:
            return sorted(up, key=lambda l: l.inflight)
        rows, key = hint
        with self._lock:
            sticky = (self._affinity.get((tenant, key))
                      if key is not None else None)
        return sorted(up, key=lambda l: (
            0 if l.covers_bucket(rows, tenant) else 1,
            0 if sticky is not None and l.url == sticky else 1,
            l.inflight))

    def _tenant_blocked(self, tenant: Optional[str]) -> bool:
        """Is the fabric up but THIS tenant quarantined on every reachable
        replica? That is a per-tenant 503 (the tenant's own isolation
        boundary), not a 502 (fabric down)."""
        if tenant is None:
            return False
        now = self._clock()
        with self._lock:
            candidates = list(self.links)
        up = [l for l in candidates if l.breaker.available(now)]
        return bool(up) and not any(
            l.tenant_available(tenant, now) for l in up)

    def _pin_affinity(self, key, url: str) -> None:
        # caller holds _lock
        if key not in self._affinity and \
                len(self._affinity) >= self._affinity_cap:
            self._affinity.pop(next(iter(self._affinity)))
        self._affinity[key] = url

    def _forward(self, method: str, path: str, body: bytes,
                 headers: Dict[str, str],
                 deadline: Optional[Deadline] = None,
                 hint: Optional[tuple] = None,
                 tenant: Optional[str] = None) -> tuple:
        tried: set = set()
        last_err = None
        last_shed: Optional[tuple] = None
        # dynamic retry bound: one attempt per CURRENT member by default
        # (membership can grow/shrink while the gateway runs)
        with self._lock:
            retries = (self._max_retries_cfg
                       if self._max_retries_cfg is not None
                       else max(len(self.links), 1))
        for _ in range(retries):
            if deadline is not None and deadline.expired():
                record_failure("gateway.deadline_expired")
                return 504, b'{"error": "deadline exceeded at gateway"}'
            link = self._pick(tried, hint, tenant)
            if link is None:
                break
            tried.add(id(link))
            with self._lock:
                link.inflight += 1
                is_local = link is self._local_link
            try:
                if deadline is not None:
                    # re-anchor the remaining budget for the next hop
                    headers = {**headers,
                               DEADLINE_HEADER: deadline.header_value()}
                if is_local:
                    status, payload = self._forward_local(body, deadline,
                                                          tenant)
                else:
                    status, payload = link.forward(method, path, body,
                                                   headers)
                link.mark_ok()
                # per-tenant passive health: 5xx replies (handler throw,
                # NaN guard, bad version) count against THIS replica for
                # THIS tenant; anything below 500 — including the
                # tenant's own 429s — is a healthy replica for it
                link.mark_tenant(tenant, ok=status < 500)
                if status == 503:
                    # shed failover: a 503 is the worker's backpressure
                    # (admission queue full or draining), not a broken
                    # link — no breaker penalty, but a sibling may have
                    # capacity, so the request fails over instead of
                    # surfacing one replica's shed to the client. Only
                    # when EVERY candidate sheds does the 503 go out.
                    last_shed = (status, payload)
                    self.stats.incr("retried")
                    record_failure("gateway.shed_failover", worker=link.url)
                    continue
                self.stats.incr("forwarded")
                return status, payload
            except Exception as e:  # transport failure -> retry on sibling
                last_err = e
                link.mark_failed()
                self.stats.incr("retried")
                record_failure("gateway.retry", worker=link.url)
            finally:
                with self._lock:
                    link.inflight -= 1
        if last_shed is not None:
            # every reachable worker shed: the honest answer is the 503
            # (client backoff), not a 502 pretending the fabric is down
            self.stats.incr("forwarded")
            return last_shed
        if self._tenant_blocked(tenant):
            # the fabric is up — it is THIS tenant that is open-circuited
            # on every replica (bad version, NaN storm): a per-tenant 503
            # at the gateway boundary, never a 502 that would read as a
            # fabric outage to every other tenant's operators
            self.stats.incr("forwarded")
            record_failure("gateway.tenant_quarantined", tenant=tenant)
            return 503, _json.dumps(
                {"error": "tenant quarantined", "tenant": tenant}).encode()
        self.stats.incr("failed")
        record_failure("gateway.no_backend")
        return 502, (b'{"error": "no serving worker reachable: %s"}'
                     % str(last_err).encode()[:200])

    def _forward_local(self, body: bytes,
                       deadline: Optional[Deadline] = None,
                       tenant: Optional[str] = None) -> tuple:
        """In-process fast path: enqueue into the co-located worker's
        micro-batch queue and wait for its reply-by-id, skipping the
        loopback HTTP hop entirely."""
        if self._local._stop.is_set() or self._local._draining.is_set():
            # fail as fast as the HTTP path's ECONNREFUSED / 503 would: the
            # queue accepts puts forever, but a stopped serve loop never
            # replies and a draining one should shed
            raise ConnectionError("local serving worker is stopped/draining")
        if tenant is not None and self._local.qos is not None:
            # the fast path honors the worker's per-tenant QoS boundary
            # exactly like its HTTP admission would
            decision = self._local.qos.admit(tenant)
            if not decision.ok:
                return decision.status, _json.dumps(
                    {"error": decision.reason, "tenant": tenant}).encode()
        budget = min(self.forward_timeout, self._local.reply_timeout)
        if deadline is not None:
            budget = min(budget, deadline.remaining())
        req = _PendingRequest(
            id=uuid.uuid4().hex, method="POST", path=self.api_path,
            headers={}, body=body, deadline=Deadline.after(budget),
            admitted_at=time.monotonic(),
            # the fast path pins the active (tenant, version) exactly like
            # the worker's own admission path (hot-swap consistency)
            handler=(self._local.handler if tenant is None
                     else self._local.handler_for(tenant)),
            tenant=tenant if tenant is not None else DEFAULT_TENANT)
        try:
            self._local._queue.put_nowait(req)
        except queue.Full:
            # the local worker's bounded admission queue applies to the
            # fast path too — a full queue reads as an overloaded worker
            # and the sibling retry takes over
            raise ConnectionError("local serving worker queue full")
        # the gateway's failover bound applies here exactly as it does to an
        # HTTP forward — a wedged local serve loop must not stall requests
        # past forward_timeout before the sibling retry
        if not req.reply_event.wait(budget):
            raise TimeoutError("local worker reply timeout")
        status, _headers, payload = req.response
        return status, payload

    # --- embedded public server ----------------------------------------
    def start(self) -> "ServingGateway":
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True
            timeout = 30

            def _reply_json(self, status: int, payload: bytes):
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                if self.path.startswith(FABRIC_PATH_PREFIX):
                    status, resp = outer._handle_control(self.path, body)
                    self._reply_json(status, _json.dumps(resp).encode())
                    return
                fwd_headers = {"Content-Type": self.headers.get(
                    "Content-Type", "application/json"),
                    "Content-Length": str(len(body))}
                tenant = self.headers.get(TENANT_HEADER)
                if tenant:
                    tenant = tenant.strip() or None
                if tenant:
                    # the tenant identity rides every hop: the worker's own
                    # QoS admission and handler pinning key on it
                    fwd_headers[TENANT_HEADER] = tenant
                if tenant is not None and outer.qos is not None:
                    # edge-tier admission at the gateway boundary: this
                    # gateway's leased share of the tenant's GLOBAL rate
                    # (federation lease math) — shed here costs no
                    # forward hop and no worker handler time
                    decision = outer.edge_admit(tenant)
                    if not decision.ok:
                        self._reply_json(decision.status, _json.dumps(
                            {"error": decision.reason,
                             "tenant": tenant}).encode())
                        return
                # no header -> no gateway deadline (forward_timeout already
                # bounds each attempt; a synthetic deadline equal to it
                # would starve the sibling retry). An explicit budget is
                # capped at the gateway's own total-work bound.
                raw = self.headers.get(DEADLINE_HEADER)
                with outer._lock:
                    n_links = max(len(outer.links), 1)
                cap = outer.forward_timeout * (
                    outer._max_retries_cfg
                    if outer._max_retries_cfg is not None else n_links)
                deadline = (None if raw is None
                            else Deadline.from_header_ms(raw, cap))
                status, payload = outer._forward(
                    "POST", outer.api_path, body, fwd_headers,
                    deadline=deadline,
                    hint=outer._shape_hint(body, self.headers),
                    tenant=tenant)
                self._reply_json(status, payload)

            def do_GET(self):  # noqa: N802  — health/stats endpoint
                outer._sweep_expired()
                now = outer._clock()
                with outer._lock:
                    links = list(outer.links)
                body = _json.dumps({
                    "workers": [l.health(now) for l in links],
                    "membership": outer.membership.snapshot(now),
                    "mode": outer.mode,
                    "federation": outer.federation_snapshot(),
                    **outer.stats.snapshot()}).encode()
                self._reply_json(200, body)

            def log_message(self, *args):
                pass

        class _Server(ThreadingHTTPServer):
            request_queue_size = 256
            daemon_threads = True

        self._httpd = _Server((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        # assigned exactly once, before the serve/replicator threads exist;
        # read-only afterwards (start() happens-before both thread starts)
        self.public_url = f"http://{self.host}:{self.port}"  # lint-ok: thread-shared write precedes thread start
        self.ring.add(self.public_url)
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        if self.federated:
            self._start_replicator()
        return self

    def stop(self) -> None:
        self._repl_stop.set()
        if self._repl_thread is not None:
            self._repl_thread.join(timeout=self.gossip_interval + 1.0)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}{self.api_path}"

    @property
    def control_url(self) -> str:
        """Base of the membership control plane (heartbeats POST here)."""
        return f"http://{self.host}:{self.port}{FABRIC_PATH_PREFIX}"

    def __enter__(self) -> "ServingGateway":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def federate(gateways: Sequence[ServingGateway]) -> None:
    """Wire started gateways into one federated tier: every gateway learns
    every other as a gossip peer, starting the anti-entropy replicators.
    After convergence (a few ``gossip_interval`` ticks) any gateway routes
    to any worker, tenant homes agree fabric-wide, and per-tenant budgets
    are enforced as leased shares of one global contract."""
    for gw in gateways:
        for other in gateways:
            if other is not gw:
                gw.add_peer(f"http://{other.host}:{other.port}")


class WorkerAgent:
    """Worker-side membership reporter: a daemon thread POSTing periodic
    heartbeats to the gateway's control plane. Each beat advertises the
    worker's reachable url, queue depth, warmed bucket ladder
    (``BucketedRunner.warm_buckets()`` when the handler exposes a runner),
    and active model version (when a ``ModelRegistry`` is attached) — the
    inputs to the gateway's bucket-aware routing and the
    :class:`FabricSupervisor`'s scaling decisions.

    Failure model: a failed beat (gateway down, partition) is COUNTED and
    otherwise ignored — the worker keeps serving and keeps beating, so a
    healed partition rejoins automatically. ``stop()`` sends a best-effort
    deregister (clean leave) unless ``deregister=False``.

    **Gateway failover**: ``gateway_url`` may be a list, and every
    heartbeat ack carries the live gateway set (federation gossip), which
    the agent learns. When the primary gateway is unreachable the SAME
    beat retries against each other known gateway with jittered backoff
    (thundering-herd protection when a whole fleet rehomes at once); the
    first gateway that acks becomes the new primary — a dead gateway
    re-homes its workers within one heartbeat interval instead of
    silently orphaning them. ``failed`` counts beats NO gateway took;
    ``failed_over`` counts re-homings.
    """

    def __init__(self, worker: ServingServer,
                 gateway_url: Union[str, Sequence[str]],
                 advertise_url: Optional[str] = None,
                 worker_id: Optional[str] = None,
                 interval: float = 0.5, timeout: float = 2.0,
                 failover_backoff: float = 0.05):
        urls = [gateway_url] if isinstance(gateway_url, str) \
            else list(gateway_url)
        if not urls:
            raise ValueError("WorkerAgent needs at least one gateway url")
        self._gw_lock = threading.Lock()
        self._controls: List[str] = []
        for u in urls:
            base = self._control_base(u)
            if base not in self._controls:
                self._controls.append(base)
        self._primary = 0
        self.failover_backoff = failover_backoff
        self.worker = worker
        wh, wp = _parse_hostport(advertise_url or worker.url)
        self.advertise_url = f"http://{wh}:{wp}"
        self.worker_id = worker_id or uuid.uuid4().hex[:12]
        self.interval = interval
        self.timeout = timeout
        self.sent = 0
        self.dropped = 0          # chaos-partitioned beats
        self.failed = 0           # beats no known gateway acknowledged
        self.failed_over = 0      # beats that re-homed to another gateway
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _control_base(url: str) -> str:
        h, p = _parse_hostport(url)
        return f"http://{h}:{p}{FABRIC_PATH_PREFIX}"

    @property
    def _control(self) -> str:
        """Primary control endpoint (back-compat read surface)."""
        with self._gw_lock:
            return self._controls[self._primary]

    def gateways(self) -> List[str]:
        with self._gw_lock:
            return list(self._controls)

    def _learn_peers(self, ack: dict) -> None:
        """Fold the gateway's advertised live-peer set into the failover
        list — a worker pointed at ONE federated gateway learns the rest
        from its first ack."""
        peers = ack.get("peers")
        if not isinstance(peers, list):
            return
        for u in peers:
            try:
                base = self._control_base(str(u))
            except (TypeError, ValueError):
                continue
            with self._gw_lock:
                if base not in self._controls:
                    self._controls.append(base)

    def payload(self) -> dict:
        p = {"id": self.worker_id, "url": self.advertise_url,
             "queue_depth": int(self.worker._queue.qsize())}
        runner = getattr(self.worker.handler, "runner", None)
        if runner is not None and callable(
                getattr(runner, "warm_buckets", None)):
            try:
                p["warm_buckets"] = [int(b) for b in runner.warm_buckets()]
            except Exception:  # noqa: BLE001 — advertisement is advisory
                pass
        registry = getattr(self.worker, "registry", None)
        if registry is not None:
            p["version"] = registry.active
        # per-(tenant, model) advertisement: each tenant's active version
        # and warm AOT ladder, so the gateway can route (tenant, shape) →
        # warmest replica and spot mixed-version fabrics per tenant
        tenants = {}
        for t, h in dict(self.worker.tenant_handlers).items():
            entry: dict = {}
            reg = self.worker.registries.get(t)
            if reg is not None:
                entry["version"] = reg.active
            runner = getattr(h, "runner", None)
            if runner is not None and callable(
                    getattr(runner, "warm_buckets", None)):
                try:
                    entry["warm_buckets"] = [
                        int(b) for b in runner.warm_buckets()]
                except Exception:  # noqa: BLE001 — advisory
                    pass
            tenants[t] = entry
        if tenants:
            p["tenants"] = tenants
        return p

    def _post(self, op: str, payload: dict) -> dict:
        return self._post_to(self._control, op, payload)

    def _post_to(self, control: str, op: str, payload: dict) -> dict:
        import urllib.request

        req = urllib.request.Request(
            control + op, data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            raw = r.read()
        try:
            ack = _json.loads(raw.decode())
        except ValueError:
            ack = {}
        return ack if isinstance(ack, dict) else {}

    def beat(self) -> bool:
        """One heartbeat. Returns True when A gateway acknowledged it;
        False for a chaos-dropped beat or when every known gateway is
        unreachable (both benign: the next beat retries and a healed
        partition rejoins). On primary-gateway failure the beat fails over
        through the other known gateways with jittered backoff; the first
        responder becomes the new primary."""
        hook = _HEARTBEAT_HOOK
        if hook is not None and not hook(self.worker_id):
            self.dropped += 1
            return False
        payload = self.payload()
        with self._gw_lock:
            primary = self._primary
            order = [primary] + [i for i in range(len(self._controls))
                                 if i != primary]
            controls = list(self._controls)
        for attempt, idx in enumerate(order):
            if attempt:
                # jittered backoff between failover attempts: a dead
                # gateway rehomes a whole fleet at once, and the jitter
                # spreads the stampede across the survivors
                time.sleep(random.uniform(0.5, 1.5)
                           * self.failover_backoff)
            try:
                ack = self._post_to(controls[idx], "heartbeat", payload)
            except Exception:  # noqa: BLE001 — gateway down != worker down
                continue
            if idx != primary:
                with self._gw_lock:
                    self._primary = idx
                self.failed_over += 1
                record_failure("fabric.heartbeat_failover",
                               worker=self.worker_id,
                               gateway=controls[idx])
            self.sent += 1
            self._learn_peers(ack)
            return True
        self.failed += 1
        return False

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.beat()
            self._stop.wait(self.interval)

    def start(self) -> "WorkerAgent":
        self.beat()                        # eager join before first interval
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self, deregister: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + self.timeout)
        if deregister:
            # best-effort clean leave, trying each known gateway once
            for control in self.gateways():
                try:
                    self._post_to(control, "deregister",
                                  {"url": self.advertise_url})
                    break
                except Exception:  # noqa: BLE001
                    continue

    def __enter__(self) -> "WorkerAgent":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class FabricSupervisor:
    """Queue-depth-driven autoscaling hook over a running gateway.

    The membership layer makes scaling possible (workers join/leave a live
    gateway); this supervisor makes it a policy: when the mean advertised
    queue depth across alive workers exceeds ``scale_up_depth`` it calls
    ``spawn_fn()`` (user-supplied: start a process, schedule a pod — the
    new worker's own heartbeat joins it), and when depth falls below
    ``scale_down_depth`` with more than ``min_workers`` alive it calls
    ``retire_fn(url)`` with the least-loaded worker (whose agent then
    drains and deregisters). ``decide()`` is pure — deterministic to test —
    and ``step()`` applies one decision; ``start()`` runs steps on a daemon
    thread for deployments that want the loop managed here.
    """

    def __init__(self, gateway: ServingGateway,
                 spawn_fn: Callable[[], object],
                 retire_fn: Optional[Callable[[str], object]] = None,
                 min_workers: int = 1, max_workers: int = 8,
                 scale_up_depth: float = 4.0, scale_down_depth: float = 0.5,
                 interval: float = 1.0):
        if min_workers < 1 or max_workers < min_workers:
            raise ValueError("need 1 <= min_workers <= max_workers")
        if scale_down_depth >= scale_up_depth:
            raise ValueError("scale_down_depth must be < scale_up_depth "
                             "(hysteresis band)")
        self.gateway = gateway
        self.spawn_fn = spawn_fn
        self.retire_fn = retire_fn
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.scale_up_depth = scale_up_depth
        self.scale_down_depth = scale_down_depth
        self.interval = interval
        self.spawned = 0
        self.retired = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def observe(self) -> Tuple[int, float]:
        """(alive workers, mean advertised queue depth)."""
        with self.gateway._lock:
            links = list(self.gateway.links)
        if not links:
            return 0, 0.0
        depths = [l.queue_depth for l in links]
        return len(links), sum(depths) / len(depths)

    def decide(self, n_alive: int, mean_depth: float) -> Optional[str]:
        """Pure scaling policy: "up", "down", or None (hysteresis band)."""
        if n_alive < self.min_workers:
            return "up"
        if mean_depth > self.scale_up_depth and n_alive < self.max_workers:
            return "up"
        if mean_depth < self.scale_down_depth and n_alive > self.min_workers:
            return "down" if self.retire_fn is not None else None
        return None

    def step(self) -> Optional[str]:
        """Observe -> decide -> act once; returns the action taken. Each
        step also runs the explicit membership sweep — the supervisor is
        the "own cadence" caller :meth:`ServingGateway.evict_stale` needs
        so an idle fabric still decays dead workers."""
        self.gateway.evict_stale()
        n, depth = self.observe()
        action = self.decide(n, depth)
        if action == "up":
            self.spawn_fn()
            self.spawned += 1
            record_failure("gateway.scale_up", workers=n,
                           mean_depth=round(depth, 3))
        elif action == "down":
            with self.gateway._lock:
                idle = sorted(self.gateway.links,
                              key=lambda l: (l.queue_depth, l.inflight))
            victim = next((l for l in idle
                           if l is not self.gateway._local_link), None)
            if victim is None:
                return None
            self.retire_fn(victim.url)
            self.retired += 1
            record_failure("gateway.scale_down", worker=victim.url,
                           mean_depth=round(depth, 3))
        return action

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception:  # noqa: BLE001 — a bad spawn must not kill
                record_failure("gateway.supervisor_error")  # the loop
            self._stop.wait(self.interval)

    def start(self) -> "FabricSupervisor":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 1.0)


class BroadcastError(RuntimeError):
    """A fabric-wide promotion broadcast failed AND recovery converged the
    fabric back to the old version (or could not complete at all). Either
    way no worker is left on a half-promoted version — the error reports
    that the NEW version did not take, not that the fabric is mixed."""


class CoordinatorDied(RuntimeError):
    """The gateway coordinating a promotion broadcast died mid-round
    (chaos ``kill_gateway``). The dead coordinator performs NO cleanup —
    its thread unwinds with registries possibly mixed between staged and
    committed. The round is NOT lost: its phase record is replicated
    control-plane state, and a surviving peer's
    :meth:`PromotionBroadcast.recover` reads it and drives the round to
    commit or abort (never leaving workers split across versions)."""


class PromotionBroadcast:
    """Two-phase fabric-wide promotion: one gate approval flips EVERY
    worker's registry to the same version, atomically per worker, with no
    mixed-version fabric on any failure path.

    Phase 1 — **prepare**: each worker's :class:`~synapseml_tpu.io.serving.
    ModelRegistry` stages and AOT-warms the candidate OFF its hot path
    (:meth:`ModelRegistry.prepare`), holding its swap lock so racing
    single-shot swaps lose deterministically. Any prepare failure aborts
    every already-prepared worker → the fabric never left the old version.

    Phase 2 — **commit**: each worker flips (:meth:`ModelRegistry.commit`).
    A commit failure (injected kill at the ``commit`` swap-point) leaves
    that worker's version STAGED with the lock held, so the broadcast first
    retries the commit (kill-once chaos converges forward: all workers on
    the NEW version). If a worker still cannot commit, recovery converges
    BACKWARD instead: its stage is aborted and every already-committed
    worker rolls back — all workers on the OLD gate-approved version.

    **Coordinator death** (federated mode): pass ``control`` (a
    :class:`~synapseml_tpu.core.gossip.GossipState` — any publish/items/
    entry surface) and ``alive`` (a liveness probe for the coordinating
    gateway, e.g. ``gw.alive``). The round's phase then replicates as a
    ``promo/<version>`` record at every 2PC transition (``preparing`` →
    ``prepared`` → ``committed``/``aborted``), and the coordinator checks
    ``alive()`` before each per-worker step — a chaos kill raises
    :class:`CoordinatorDied` mid-round, leaving registries mixed between
    staged (swap lock stranded in the dead thread) and committed. A
    surviving peer holding the replicated record calls :meth:`recover`:
    the ``prepared`` decision record drives the round FORWARD (adopt each
    orphaned stage via :meth:`ModelRegistry.take_over_staged`, commit),
    while a round still ``preparing`` converges BACKWARD (adopt + abort,
    roll back any commits) — either way exactly one version serves
    fabric-wide. Without ``control`` the single-coordinator behavior is
    unchanged: per-worker atomicity is the chaos-tested floor.
    """

    def __init__(self, registries: Sequence[ModelRegistry],
                 commit_retries: int = 1, control=None,
                 node_id: str = "coordinator",
                 alive: Optional[Callable[[], bool]] = None):
        if not registries:
            raise ValueError("broadcast needs at least one registry")
        self.registries = list(registries)
        self.commit_retries = commit_retries
        self.control = control
        self.node_id = node_id
        self.alive = alive
        self.broadcasts = 0
        self.aborted = 0
        self.rolled_back = 0
        self.recoveries = 0

    def _record_phase(self, version: str, phase: str) -> None:
        if self.control is not None:
            self.control.publish(
                f"promo/{version}",
                {"phase": phase, "version": version,
                 "coordinator": self.node_id,
                 "workers": len(self.registries)})

    def _check_alive(self, version: str) -> None:
        if self.alive is not None and not self.alive():
            record_failure("gateway.broadcast_coordinator_died",
                           version=version)
            raise CoordinatorDied(
                f"coordinating gateway died mid-broadcast of {version!r}; "
                "a surviving peer must recover the round from its "
                "replicated phase record")

    def active_versions(self) -> List[str]:
        return [r.active for r in self.registries]

    def converged(self) -> bool:
        """All workers on one version — the no-mixed-fabric invariant."""
        return len(set(self.active_versions())) == 1

    def broadcast(self, version: str, handler: Callable,
                  warmup: bool = True) -> str:
        old = {id(r): r.active for r in self.registries}
        prepared: List[ModelRegistry] = []
        self._record_phase(version, "preparing")
        try:
            for reg in self.registries:
                self._check_alive(version)
                reg.prepare(version, handler, warmup=warmup)
                prepared.append(reg)
        except CoordinatorDied:
            # the dead coordinator does NO cleanup (its process is gone);
            # the replicated "preparing" record tells a surviving peer to
            # converge the round backward
            raise
        except Exception as e:  # noqa: BLE001 — abort-all: old version holds
            for reg in prepared:
                reg.abort()
            self.aborted += 1
            self._record_phase(version, "aborted")
            record_failure("gateway.broadcast_aborted", version=version,
                           stage="prepare", error=type(e).__name__)
            raise BroadcastError(
                f"prepare of {version!r} failed on worker "
                f"{len(prepared)}/{len(self.registries)} "
                f"({type(e).__name__}: {e}); every worker is still on its "
                "old version") from e
        # every worker is staged: the 2PC decision point. The replicated
        # "prepared" record IS the commit decision — a surviving peer that
        # reads it drives the round forward even if we die on the next line
        self._record_phase(version, "prepared")
        committed: List[ModelRegistry] = []
        failed: List[ModelRegistry] = []
        for reg in self.registries:
            self._check_alive(version)     # CoordinatorDied mid-commit
            for attempt in range(1 + self.commit_retries):
                try:
                    reg.commit(version)
                    committed.append(reg)
                    break
                except Exception as e:  # noqa: BLE001
                    record_failure("gateway.broadcast_commit_retry",
                                   version=version,
                                   error=type(e).__name__)
                    if attempt == self.commit_retries:
                        failed.append(reg)
        if not failed:
            self.broadcasts += 1
            self._record_phase(version, "committed")
            record_failure("gateway.broadcast_completed", version=version,
                           workers=len(self.registries))
            return version
        # backward convergence: some worker cannot take the new version —
        # abort its stage and roll every committed worker back, so the
        # fabric converges on ONE (old, gate-approved) version
        for reg in failed:
            reg.abort()
        for reg in committed:
            try:
                prev = old[id(reg)]
                reg.swap_to(prev, reg.versions[prev], warmup=False)
            except Exception:  # noqa: BLE001 — best effort; chaos-bounded
                record_failure("gateway.broadcast_rollback_failed",
                               version=version)
        self.rolled_back += 1
        self._record_phase(version, "aborted")
        record_failure("gateway.broadcast_rolled_back", version=version,
                       failed=len(failed))
        raise BroadcastError(
            f"commit of {version!r} failed on {len(failed)} worker(s); "
            "fabric rolled back to the old version")

    # -- surviving-peer recovery -----------------------------------------
    def in_doubt(self) -> Optional[Tuple[str, str]]:
        """(version, phase) of the newest round left in doubt by a dead
        coordinator — phase ``preparing`` or ``prepared`` — else None."""
        if self.control is None:
            return None
        pending = []
        for key, rec in self.control.items("promo/").items():
            if rec.get("phase") in ("preparing", "prepared"):
                entry = self.control.entry(key)
                pending.append((entry.epoch if entry is not None else 0,
                                str(rec.get("version", "")),
                                str(rec["phase"])))
        if not pending:
            return None
        _, version, phase = max(pending)
        return version, phase

    def recover(self) -> Optional[Tuple[str, str]]:
        """Drive a dead coordinator's in-doubt round to its end from the
        replicated phase record; returns ``(version, outcome)`` with
        outcome ``"committed"`` or ``"aborted"``, or None when no round
        needs recovery. Called by a surviving peer gateway (same registry
        set, converged control plane). A ``prepared`` record means every
        worker staged and the decision to commit was made: adopt each
        orphaned stage (:meth:`ModelRegistry.take_over_staged` — legal
        only because the owning thread is dead) and commit it. A round
        still ``preparing`` never decided: abort every stage and roll
        back any stray commit. Either way the fabric ends on exactly one
        version, and the final phase replicates so other survivors do not
        re-recover the same round."""
        pending = self.in_doubt()
        if pending is None:
            return None
        version, phase = pending
        record_failure("gateway.broadcast_recovery", version=version,
                       phase=phase)
        if phase == "prepared":
            outcome = self._recover_forward(version)
        else:
            outcome = self._recover_backward(version)
        self.recoveries += 1
        self._record_phase(version, outcome)
        record_failure("gateway.broadcast_recovered", version=version,
                       outcome=outcome)
        return version, outcome

    def _recover_forward(self, version: str) -> str:
        stranded: List[ModelRegistry] = []
        for reg in self.registries:
            if reg.active == version:
                continue            # the coordinator committed this one
            try:
                if reg.take_over_staged():
                    reg.commit(version)
                else:
                    stranded.append(reg)    # no stage, not active
            except Exception:  # noqa: BLE001 — converge backward below
                stranded.append(reg)
        if not stranded:
            self.broadcasts += 1
            return "committed"
        return self._recover_backward(version)

    def _recover_backward(self, version: str) -> str:
        for reg in self.registries:
            try:
                if reg.take_over_staged():
                    reg.abort()
            except Exception:  # noqa: BLE001 — a live owner keeps its lock
                record_failure("gateway.broadcast_recovery_skip",
                               version=version)
            if reg.active == version:
                # committed before the coordinator died: roll back so the
                # fabric converges on the OLD gate-approved version
                try:
                    reg.rollback()
                except Exception:  # noqa: BLE001
                    record_failure("gateway.broadcast_rollback_failed",
                                   version=version)
        self.aborted += 1
        return "aborted"


class DistributedServingServer:
    """Mesh-wide serving: every process starts a worker ServingServer running
    ``handler`` on its local capacity; worker addresses are exchanged over the
    distributed backend (the DCN rendezvous the reference does through Spark's
    driver); process 0 additionally exposes the public gateway, and every
    process runs a :class:`WorkerAgent` heartbeating to it — so the fabric
    started static becomes dynamic the moment it is up (dead workers evict,
    restarted ones rejoin, new ones may join).

    Single-process fallback: with no distributed backend this degrades to one
    worker + gateway on the same host (still exercising the forwarding hop
    and the heartbeat loop).
    """

    def __init__(self, handler: Callable[[Table], Table],
                 host: Optional[str] = None, gateway_port: int = 0,
                 worker_port: int = 0, mode: str = "least_loaded",
                 max_batch_size: int = 64, max_batch_latency: float = 0.0,
                 advertise_host: Optional[str] = None,
                 heartbeat_interval: float = 0.5,
                 heartbeat_timeout: float = 3.0):
        self.handler = handler
        # None = auto: loopback single-process; all interfaces when the
        # advertised address must be reachable from OTHER hosts
        self.host = host
        # multi-host: the address OTHER processes reach this worker at
        # (default: auto-detected routable interface address)
        self.advertise_host = advertise_host
        self.gateway_port = gateway_port
        self.worker_port = worker_port
        self.mode = mode
        self.max_batch_size = max_batch_size
        self.max_batch_latency = max_batch_latency
        self.heartbeat_interval = heartbeat_interval
        self.heartbeat_timeout = heartbeat_timeout
        self.worker: Optional[ServingServer] = None
        self.gateway: Optional[ServingGateway] = None
        self.agent: Optional[WorkerAgent] = None

    _local_ip = staticmethod(_detect_local_ip)

    def _gather_worker_addrs(self, port: int) -> List[str]:
        """All-gather (ip, port) across processes. Ports ride a tiny int
        array through the collective layer — the only cross-process exchange
        serving needs (requests themselves flow over plain HTTP).

        Constraint: the advertised address must be an IPv4 dotted-quad (it
        ships as exactly 4 octets on the wire). IPv6 and hostnames are
        rejected with a clear error instead of silently mangling the
        address — resolve the name / pick the v4 interface address first."""
        import jax

        if jax.process_count() == 1:
            return [f"http://{self.host or '127.0.0.1'}:{port}"]
        import numpy as np
        from jax.experimental import multihost_utils

        import socket

        ip = self.advertise_host or self._local_ip()
        # IP ships as 4 octets (NOT one packed u32: jax's x64-disabled
        # default would downcast the int64 array to int32 and overflow)
        try:
            octets = [int(b) for b in socket.inet_aton(ip)]
        except OSError as e:
            raise ValueError(
                f"advertise_host {ip!r} is not an IPv4 dotted-quad address; "
                "the worker-address exchange ships exactly 4 octets over "
                "the collective wire, so IPv6 addresses and hostnames are "
                "not supported here — pass the host's IPv4 interface "
                "address (e.g. advertise_host='10.0.0.12'), resolving any "
                "hostname yourself first") from e
        local = np.asarray([octets + [port]], np.int32)
        allv = np.asarray(multihost_utils.process_allgather(local))
        allv = allv.reshape(-1, 5)
        return [f"http://{a}.{b}.{c}.{d}:{int(p)}"
                for a, b, c, d, p in allv]

    def start(self) -> "DistributedServingServer":
        import jax

        multi = jax.process_count() > 1
        bind = self.host or ("0.0.0.0" if multi else "127.0.0.1")
        self.worker = ServingServer(
            self.handler, host=bind, port=self.worker_port,
            max_batch_size=self.max_batch_size,
            max_batch_latency=self.max_batch_latency).start()
        urls = self._gather_worker_addrs(self.worker.port)
        if jax.process_index() == 0:
            self.gateway = ServingGateway(
                urls, host=bind, port=self.gateway_port,
                mode=self.mode, local_worker=self.worker,
                local_index=jax.process_index(),
                heartbeat_timeout=self.heartbeat_timeout).start()
        # every process learns the gateway address (process 0's advertised
        # ip + the resolved gateway port) and starts heartbeating to it
        gw_url = self._gather_gateway_url()
        if gw_url is not None:
            self.agent = WorkerAgent(
                self.worker, gw_url,
                advertise_url=urls[jax.process_index()],
                interval=self.heartbeat_interval).start()
        return self

    def _gather_gateway_url(self) -> Optional[str]:
        """Gateway address on every process: process 0 contributes its
        advertised ip + gateway port; everyone takes row 0."""
        import jax

        if jax.process_count() == 1:
            return self.gateway.url if self.gateway is not None else None
        import numpy as np
        import socket
        from jax.experimental import multihost_utils

        ip = self.advertise_host or self._local_ip()
        octets = [int(b) for b in socket.inet_aton(ip)]
        port = self.gateway.port if self.gateway is not None else 0
        local = np.asarray([octets + [port]], np.int32)
        allv = np.asarray(
            multihost_utils.process_allgather(local)).reshape(-1, 5)
        a, b, c, d, p = allv[0]
        return f"http://{a}.{b}.{c}.{d}:{int(p)}"

    def stop(self) -> None:
        if self.agent is not None:
            self.agent.stop()
        if self.gateway is not None:
            self.gateway.stop()
        if self.worker is not None:
            self.worker.stop()

    @property
    def url(self) -> str:
        """Public endpoint (gateway on process 0, else the local worker)."""
        if self.gateway is not None:
            return self.gateway.url
        return self.worker.url

    def __enter__(self) -> "DistributedServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
