"""Serving — embedded HTTP server feeding micro-batches through a pipeline.

Reference: the Spark Serving layer (SURVEY.md §3.5): custom streaming sources
embedding web servers (HTTPSourceV2.scala:485-713 ``WorkerServer`` with request
queue + reply-by-id sink, HTTPSource.scala head-node variant, ServingUDFs.scala
``makeReplyUDF``). The reference queues requests into Spark micro-batches and
replies through a sink keyed by request id; here a threaded HTTP server queues
requests, a serving loop drains the queue into a ``Table`` micro-batch, runs
the user pipeline (one jitted program for model transforms), and writes each
row's reply back to its still-open connection — same architecture, no Spark.

Resilience model (docs/resilience.md; fault-tested by
tests/test_chaos_serving.py via testing/chaos.py):

* **Bounded admission** — the request queue holds at most ``max_queue_size``
  entries; overload is shed as an immediate 503 instead of growing latency
  without bound.
* **Deadline propagation** — a client ``X-Deadline-Ms`` header (remaining
  budget, capped by ``reply_timeout``) rides the request: the connection
  thread 504s at the deadline no matter what, and batch formation drops
  already-expired requests without spending handler time on them. Handlers
  that accept a ``budget=`` keyword receive the batch's remaining seconds.
* **Failure isolation** — a handler exception fails only the poisoned rows:
  the batch is retried row-by-row (``isolate_failures``) so one bad payload
  cannot 500 its co-batched neighbors.
* **Graceful drain** — ``stop()`` first refuses new work (503) while
  in-flight requests complete, then tears the server down.
* **Zero-downtime model hot-swap** — :class:`ModelRegistry` stages a new
  handler version (optionally loaded from a digest-verified
  ``core.checkpoint.CheckpointStore`` checkpoint), AOT-warms it off the hot
  path, and atomically flips the serving pointer; every request is pinned
  at admission to the handler version that accepted it, so a swap can never
  change the program answering an in-flight request, and a failed
  load/build/warmup rolls back with the old version never having stopped.
* **Multi-tenant isolation** (docs/resilience.md, "Multi-tenant fleet") —
  with a :class:`~synapseml_tpu.core.qos.QoSController`, requests carry
  ``X-Tenant``; each tenant gets its own serving pointer + registry
  (``add_tenant``), its own admission contract (token bucket → 429,
  quarantine breaker → 503, bounded weighted-fair queue lane), and its own
  failure accounting — a tenant that floods, throws, or NaN-storms is shed
  at ITS boundary while other tenants' p99 and availability hold.

``ServingServer.metrics`` exposes queue depth/age gauges and shed/error/
deadline counters; the same events also land in the process-wide
``core.logging`` failure counters.

Throughput model (docs/serving-perf.md; perf-tested by
tests/test_inference_runtime.py):

* **Two-stage pipeline** — the serve loop only *forms* batches (queue drain
  + JSON decode already happened on the connection threads; here it is
  deadline triage + Table assembly) and hands them to a dedicated executor
  thread through a depth-1 handoff, so batch N+1's formation overlaps batch
  N's handler/device execution and reply encoding.
* **Blocking batch window** — batch formation waits on
  ``queue.get(timeout=remaining_window)`` instead of a sleep/poll spin: no
  burned CPU inside the window and less jitter at low load.
* **Shape-bucketed handlers** — a handler built on
  :class:`~synapseml_tpu.core.inference.BucketedRunner` (e.g.
  ``Booster.serving_fn()``) compiles one XLA program per bucket instead of
  one per observed batch size; ``start()`` invokes the handler's
  ``warmup()`` (when it has one) so every bucket is compiled before the
  first request, and the metrics GET surfaces the runner's per-bucket
  compile/hit counters under ``"runner"``.
"""

from __future__ import annotations

import json as _json
import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core.logging import record_failure
from ..core.qos import (DEFAULT_TENANT, TENANT_HEADER, QoSController,
                        WeightedFairQueue)
from ..core.resilience import DEADLINE_HEADER, Deadline
from ..core.table import Table


@dataclass
class _PendingRequest:
    """CachedRequest analog (HTTPSourceV2.scala:530-539)."""
    id: str
    method: str
    path: str
    headers: Dict[str, str]
    body: bytes
    reply_event: threading.Event = field(default_factory=threading.Event)
    response: Optional[tuple] = None  # (status, headers, body)
    deadline: Optional[Deadline] = None
    admitted_at: float = 0.0          # monotonic enqueue time (queue age)
    # the handler VERSION this request was admitted under (hot-swap pinning:
    # a model swap mid-flight must not change the program that answers an
    # already-accepted request). None -> whatever is active at batch time.
    handler: Optional[Callable] = None
    # X-Tenant this request was admitted under: pins (tenant, version) so a
    # per-tenant swap stays atomic per tenant, routes the request through
    # its tenant's WeightedFairQueue lane, and keys outcome feedback to the
    # tenant's own QoS breaker
    tenant: str = DEFAULT_TENANT


class ServingMetrics:
    """Thread-safe counters + gauges for one server (the queue-depth/age and
    shed/error observability the chaos suite asserts on)."""

    _COUNTERS = ("accepted", "shed", "drain_rejected", "completed",
                 "handler_errors", "isolated_rows", "deadline_dropped",
                 "deadline_expired", "batches")

    def __init__(self, queue_ref: "queue.Queue"):
        self._q = queue_ref
        self._lock = threading.Lock()
        self._c = {k: 0 for k in self._COUNTERS}
        self.last_batch_size = 0
        self.last_queue_age_s = 0.0   # oldest-request age at batch formation

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._c[name] += n

    def observe_batch(self, size: int, oldest_age_s: float) -> None:
        with self._lock:
            self._c["batches"] += 1
            self.last_batch_size = size
            self.last_queue_age_s = oldest_age_s

    def __getitem__(self, name: str) -> int:
        with self._lock:
            return self._c[name]

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._c)
            out["queue_depth"] = self._q.qsize()
            out["last_batch_size"] = self.last_batch_size
            out["last_queue_age_s"] = round(self.last_queue_age_s, 6)
        return out


def request_to_table(requests: List[_PendingRequest]) -> Table:
    """Micro-batch of queued requests → Table(id, value) — the serving source
    schema (id + request struct)."""
    ids = np.array([r.id for r in requests], dtype=object)
    vals = np.empty(len(requests), dtype=object)
    for i, r in enumerate(requests):
        try:
            vals[i] = _json.loads(r.body.decode()) if r.body else None
        except Exception:
            vals[i] = r.body
    return Table({"id": ids, "value": vals})


def respond_with(df: Table, id_col: str = "id", value_col: str = "reply",
                 status_col: Optional[str] = None) -> Dict[str, tuple]:
    """Table → {request id: (status, body)} — the reply-UDF analog
    (ServingUDFs.scala makeReplyUDF).

    Column lookups are hoisted out of the per-row loop, and homogeneous
    numeric reply columns take a single vectorized ``tolist()`` pass (one
    device→host materialization + one bulk conversion) instead of per-row
    numpy indexing + scalar boxing — the reply-encode side of the serving
    hot path."""
    ids = df[id_col].tolist()
    col = df[value_col]
    n = df.num_rows
    if status_col and status_col in df:
        statuses = [int(s) for s in df[status_col].tolist()]
    else:
        statuses = None
    if col.dtype != object:
        # homogeneous numeric/bool column (scalar or fixed-width vector
        # replies): one bulk pass yields plain Python values json.dumps
        # takes directly
        vals = col.tolist()
    else:
        vals = []
        for v in col:
            if isinstance(v, np.ndarray):
                v = v.tolist()
            elif isinstance(v, np.generic):
                v = v.item()
            vals.append(v)
    out = {}
    dumps = _json.dumps
    for i in range(n):
        status = statuses[i] if statuses is not None else 200
        out[str(ids[i])] = (status, dumps(vals[i]).encode())
    return out


class ServingServer:
    """spark.readStream.server()...writeStream.server() analog.

    ``handler``: Table(id, value) -> Table(id, reply) — typically a fitted
    PipelineModel wrapped to map columns. Batching: requests are collected for
    up to ``maxBatchLatency`` seconds or ``maxBatchSize`` rows, whichever
    first (micro-batch trigger analog), then run through the handler as ONE
    batch — on TPU that is one jitted call, which is where the reference's
    "sub-millisecond" story becomes a batched-throughput story.

    A handler may declare a ``budget`` keyword parameter to receive the
    batch's remaining deadline budget in seconds (None when every request in
    the batch is deadline-less).
    """

    def __init__(self, handler: Callable[[Table], Table],
                 host: str = "127.0.0.1", port: int = 8898,
                 api_path: str = "/", max_batch_size: int = 64,
                 max_batch_latency: float = 0.005,
                 reply_timeout: float = 30.0,
                 max_queue_size: int = 1024,
                 isolate_failures: bool = True,
                 drain_timeout: float = 10.0,
                 warmup: bool = True,
                 qos: Optional[QoSController] = None):
        self.handler = handler
        self.host, self.port = host, port
        self.api_path = api_path
        self.max_batch_size = max_batch_size
        self.max_batch_latency = max_batch_latency
        self.reply_timeout = reply_timeout
        self.max_queue_size = max_queue_size
        self.isolate_failures = isolate_failures
        self.drain_timeout = drain_timeout
        self.warmup = warmup
        self.registry: Optional["ModelRegistry"] = None  # hot-swap registry
        # multi-tenant mode: per-tenant serving pointers + registries keyed
        # by X-Tenant; ``handler`` stays the default-tenant fallback so a
        # single-tenant server is the degenerate case of the same machinery
        self.qos = qos
        self.tenant_handlers: Dict[str, Callable] = {}
        self.registries: Dict[str, "ModelRegistry"] = {}
        if qos is not None:
            # per-tenant bounded lanes + weighted-fair dequeue; same
            # queue.Queue surface, so the pipeline above is unchanged
            self._queue = WeightedFairQueue(maxsize=max_queue_size, qos=qos)
        else:
            self._queue: "queue.Queue[_PendingRequest]" = queue.Queue(
                maxsize=max_queue_size)
        # two-stage pipeline handoff (batch formation → execution): depth 1
        # lets the serve loop form batch N+1 while the executor runs batch N
        self._handoff: "queue.Queue" = queue.Queue(maxsize=1)
        self.metrics = ServingMetrics(self._queue)
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._idle = threading.Event()   # no batch forming/queued/executing
        self._idle.set()
        self._inflight_stages = 0        # guarded by _stage_lock
        self._stage_lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []
        # budget-kwarg detection is per HANDLER (hot-swap can install a new
        # one at any time); keyed by id() with the handler kept alive in the
        # value so a recycled id can never alias a dead handler's signature
        self._budget_sig: Dict[int, tuple] = {}

    def _takes_budget(self, handler: Callable) -> bool:
        hit = self._budget_sig.get(id(handler))
        if hit is not None and hit[0] is handler:
            return hit[1]
        try:
            import inspect

            takes = "budget" in inspect.signature(handler).parameters
        except (TypeError, ValueError):
            takes = False
        self._budget_sig[id(handler)] = (handler, takes)
        return takes

    # --- multi-tenant surface ------------------------------------------
    def handler_for(self, tenant: str) -> Callable:
        """Active serving pointer for a tenant (default-tenant fallback:
        ``self.handler``) — the per-tenant analog of ``self.handler``, read
        once at admission to pin (tenant, version)."""
        return self.tenant_handlers.get(tenant, self.handler)

    def add_tenant(self, tenant: str, handler: Callable,
                   qos_class=None, version: str = "v0",
                   warmup: Optional[bool] = None) -> "ModelRegistry":
        """Register a tenant: its serving pointer, its own hot-swap
        :class:`ModelRegistry`, and (when the server is QoS-enabled) its
        admission contract. Warms the handler's bucket ladder unless the
        server was built with ``warmup=False``."""
        if qos_class is not None and self.qos is not None:
            self.qos.assign(tenant, qos_class)
        warm = getattr(handler, "warmup", None)
        if (self.warmup if warmup is None else warmup) and callable(warm):
            warm()
        self.tenant_handlers[tenant] = handler
        return ModelRegistry(self, version=version, tenant=tenant)

    def tenant_snapshot(self) -> dict:
        """Per-tenant observability: active version + swap history and the
        tenant handler's BucketedRunner compile/hit counters — the
        per-tenant accounting over the SHARED runner fleet/compile cache."""
        out = {}
        for tenant, handler in self.tenant_handlers.items():
            entry: dict = {}
            reg = self.registries.get(tenant)
            if reg is not None:
                entry["model"] = reg.snapshot()
            runner = getattr(handler, "runner", None)
            if runner is not None and callable(getattr(runner, "stats",
                                                       None)):
                entry["runner"] = runner.stats()
            out[tenant] = entry
        return out

    # --- embedded server (WorkerServer analog) -------------------------
    def _make_handler_class(self):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive: clients reuse the connection (and this
            # handler's thread) across requests instead of paying TCP setup +
            # thread spawn per request — the dominant term at sub-ms latencies
            protocol_version = "HTTP/1.1"
            # response headers+body go out in several small writes; without
            # TCP_NODELAY, Nagle + delayed ACK stalls each reply ~40 ms
            disable_nagle_algorithm = True
            # bound idle keep-alive connections: without a socket timeout each
            # idle client pins its handler thread in readline() forever and
            # stop() cannot quiesce them (timeout → close_connection)
            timeout = 30

            def _reply_error(self, status: int, body: bytes = b"",
                             retry_after: Optional[int] = None):
                self.send_response(status)
                if retry_after is not None:
                    self.send_header("Retry-After", str(retry_after))
                if body:
                    self.send_header("Content-Type", "application/json")
                # explicit Content-Length always: HTTP/1.1 keep-alive clients
                # block on a missing one
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def do_POST(self):  # noqa: N802
                if "chunked" in self.headers.get("Transfer-Encoding",
                                                 "").lower():
                    # chunked bodies are not parsed; reading 0 bytes would
                    # desync the keep-alive stream (the chunk data would be
                    # parsed as the next request), so reject and close
                    self._reply_error(411)  # Length Required
                    self.close_connection = True
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                # admission control BEFORE queueing: a draining/stopped
                # server refuses new work fast instead of letting it ride
                # into a queue nobody will drain
                if outer._draining.is_set() or outer._stop.is_set():
                    outer.metrics.incr("drain_rejected")
                    record_failure("serving.drain_rejected")
                    self._reply_error(
                        503, b'{"error": "server is draining"}',
                        retry_after=1)
                    return
                tenant = (self.headers.get(TENANT_HEADER)
                          or DEFAULT_TENANT).strip() or DEFAULT_TENANT
                if outer.qos is not None:
                    # per-tenant QoS boundary: a quarantined tenant sheds
                    # at ITS 503, a rate-limited one at ITS 429 — neither
                    # touches the shared queue or another tenant's budget
                    decision = outer.qos.admit(tenant)
                    if not decision.ok:
                        outer.metrics.incr("shed")
                        self._reply_error(
                            decision.status,
                            _json.dumps({"error": decision.reason,
                                         "tenant": tenant}).encode(),
                            retry_after=1)
                        return
                deadline = Deadline.from_header_ms(
                    self.headers.get(DEADLINE_HEADER),
                    outer.reply_timeout)
                req = _PendingRequest(
                    id=uuid.uuid4().hex, method="POST", path=self.path,
                    headers=dict(self.headers), body=body,
                    deadline=deadline, admitted_at=time.monotonic(),
                    # pin the ACTIVE (tenant, version) at admission: a
                    # model hot-swap between now and batch execution must
                    # not change the program answering this request, and a
                    # swap of tenant A must never touch tenant B's pin
                    handler=outer.handler_for(tenant),
                    tenant=tenant)
                try:
                    outer._queue.put_nowait(req)
                except queue.Full:
                    # load shedding: bounded queue + immediate 503 — the
                    # overload contract (fast rejection, not slow timeout).
                    # Under QoS the bound is the TENANT's own lane, so a
                    # flooding tenant sheds here while others keep landing
                    outer.metrics.incr("shed")
                    record_failure("serving.shed")
                    self._reply_error(
                        503, b'{"error": "server overloaded"}',
                        retry_after=1)
                    return
                outer.metrics.incr("accepted")
                if not req.reply_event.wait(deadline.remaining()):
                    # deadline breach: bounded-latency 504 even if the
                    # handler is wedged — the connection never hangs past
                    # the request's budget
                    outer.metrics.incr("deadline_expired")
                    record_failure("serving.deadline_expired")
                    self._reply_error(504)
                    return
                status, headers, payload = req.response
                self.send_response(status)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):  # noqa: N802  — metrics/health endpoint
                snap = {"draining": outer._draining.is_set(),
                        **outer.metrics.snapshot()}
                # a BucketedRunner-backed handler surfaces its per-bucket
                # compile/hit counters (zero steady-state compiles after
                # warmup is the serving perf contract)
                runner = getattr(outer.handler, "runner", None)
                if runner is not None and callable(
                        getattr(runner, "stats", None)):
                    snap["runner"] = runner.stats()
                if outer.registry is not None:
                    snap["model"] = outer.registry.snapshot()
                if outer.qos is not None:
                    snap["qos"] = outer.qos.snapshot()
                if outer.tenant_handlers:
                    snap["tenants"] = outer.tenant_snapshot()
                body = _json.dumps(snap).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet
                pass

        return Handler

    # --- micro-batch serve loop ----------------------------------------
    def _run_batch(self, batch: List[_PendingRequest]) -> None:
        now = time.monotonic()
        # batch-formation deadline check: an expired request gets its 504
        # here and never costs handler time (its connection thread has
        # usually already answered; setting the response is idempotent)
        live: List[_PendingRequest] = []
        for r in batch:
            if r.deadline is not None and r.deadline.expired():
                r.response = (504, {}, b'{"error": "deadline exceeded"}')
                r.reply_event.set()
                self.metrics.incr("deadline_dropped")
                record_failure("serving.deadline_dropped")
            else:
                live.append(r)
        if not live:
            return
        oldest = min(r.admitted_at for r in live)
        self.metrics.observe_batch(len(live), now - oldest)
        budgets = [r.deadline.remaining() for r in live
                   if r.deadline is not None]
        budget = min(budgets) if budgets else None
        # hot-swap pinning: a batch formed across a swap boundary may mix
        # requests admitted under different handler versions — each group
        # runs through the version it was admitted under (order preserved)
        groups: List[tuple] = []
        for r in live:
            h = r.handler if r.handler is not None else self.handler
            if groups and groups[-1][0] is h:
                groups[-1][1].append(r)
            else:
                groups.append((h, [r]))
        replies: Dict[str, tuple] = {}
        for h, group in groups:
            replies.update(self._call_handler(group, budget, h))
        by_id = {r.id: r for r in live}
        for rid, (status, payload) in replies.items():
            req = by_id.get(rid)
            if req is None:
                continue
            if (self.qos is not None and status == 200
                    and (b"NaN" in payload or b"Infinity" in payload)):
                # NaN-storm guard: json.dumps emits literal NaN/Infinity
                # for non-finite floats — a corrupted model must fail at
                # ITS tenant's 500 boundary (feeding its quarantine
                # breaker), not hand garbage to the client
                status = 500
                payload = _json.dumps(
                    {"error": "non-finite model output"}).encode()
                replies[rid] = (status, payload)
                record_failure("serving.nonfinite_reply",
                               tenant=req.tenant)
            req.response = (status, {}, payload)
            req.reply_event.set()
        # requests the handler dropped get an error instead of a hang
        for r in live:
            if r.response is None:
                r.response = (500, {}, b'{"error": "no reply produced"}')
                r.reply_event.set()
        if self.qos is not None:
            self._feed_qos(live, replies)
        self.metrics.incr("completed", len(live))

    def _feed_qos(self, live: List[_PendingRequest],
                  replies: Dict[str, tuple]) -> None:
        """Feed batch outcomes back to the per-tenant breakers: 5xx rows
        (handler throw, isolation failure, non-finite reply) count against
        THEIR tenant only; successes close that tenant's breaker."""
        ok: Dict[str, int] = {}
        bad: Dict[str, List[bool]] = {}
        for r in live:
            status, payload = replies.get(
                r.id, (r.response[0] if r.response else 500, b""))
            if status >= 500:
                bad.setdefault(r.tenant, []).append(
                    b"non-finite" in payload)
            else:
                ok[r.tenant] = ok.get(r.tenant, 0) + 1
        for tenant, n in ok.items():
            self.qos.record_success(tenant, n)
        for tenant, flags in bad.items():
            nonfinite = [f for f in flags if f]
            finite = [f for f in flags if not f]
            if finite:
                self.qos.record_failure(tenant, len(finite))
            if nonfinite:
                self.qos.record_failure(tenant, len(nonfinite),
                                        nonfinite=True)

    def _invoke(self, df: Table, budget: Optional[float],
                handler: Optional[Callable] = None):
        handler = self.handler if handler is None else handler
        if self._takes_budget(handler):
            return handler(df, budget=budget)
        return handler(df)

    def _call_handler(self, batch: List[_PendingRequest],
                      budget: Optional[float],
                      handler: Optional[Callable] = None) -> Dict[str, tuple]:
        df = request_to_table(batch)
        try:
            out = self._invoke(df, budget, handler)
            return respond_with(out) if isinstance(out, Table) else out
        except Exception as e:  # noqa: BLE001
            self.metrics.incr("handler_errors")
            record_failure("serving.handler_error", error=type(e).__name__)
            if not self.isolate_failures or len(batch) == 1:
                err = _json.dumps({"error": str(e)}).encode()
                return {r.id: (500, err) for r in batch}
        # failure isolation: rerun row-by-row so one poisoned payload fails
        # alone instead of 500ing the whole micro-batch
        replies: Dict[str, tuple] = {}
        for r in batch:
            try:
                out = self._invoke(request_to_table([r]), budget, handler)
                one = respond_with(out) if isinstance(out, Table) else out
                replies[r.id] = one.get(
                    r.id, (500, b'{"error": "no reply produced"}'))
            except Exception as e:  # noqa: BLE001
                self.metrics.incr("isolated_rows")
                record_failure("serving.isolated_row",
                               error=type(e).__name__)
                replies[r.id] = (500, _json.dumps(
                    {"error": str(e)}).encode())
        return replies

    # two-stage idle accounting: _idle is set only when no stage holds work
    # (forming, queued in the handoff, or executing) — drain() relies on it
    def _stage_enter(self) -> None:
        with self._stage_lock:
            self._inflight_stages += 1
            self._idle.clear()

    def _stage_exit(self) -> None:
        with self._stage_lock:
            self._inflight_stages -= 1
            if self._inflight_stages == 0:
                self._idle.set()

    def _serve_loop(self) -> None:
        """Stage 1 — micro-batch formation: drain queue → batch → handoff.

        Execution happens on the dedicated stage-2 thread (_exec_loop), so
        forming batch N+1 (queue drain + deadline triage; the JSON decode /
        ``np`` assembly follows in request_to_table) overlaps batch N's
        handler/device execution and reply encoding."""
        while True:
            batch: List[_PendingRequest] = []
            try:
                batch.append(self._queue.get(timeout=0.05))
            except queue.Empty:
                if self._stop.is_set():
                    self._handoff.put(None)   # release stage 2, then exit
                    return          # stopped AND queue drained: loop exits
                continue
            self._stage_enter()     # forming
            try:
                # drain the existing backlog for free (batching under load
                # costs no latency), then wait out the remaining
                # batch-formation window BLOCKED on the queue (no poll spin:
                # batch formation costs no CPU and no sleep-quantum jitter)
                deadline = time.monotonic() + self.max_batch_latency
                while len(batch) < self.max_batch_size:
                    try:
                        batch.append(self._queue.get_nowait())
                        continue
                    except queue.Empty:
                        pass
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        batch.append(self._queue.get(timeout=remaining))
                    except queue.Empty:
                        break       # window elapsed with no new arrivals
                self._stage_enter()           # batch now owned by stage 2
                self._handoff.put(batch)
            finally:
                self._stage_exit()  # formation done

    def _exec_loop(self) -> None:
        """Stage 2 — execution: handoff → handler → reply by id."""
        while True:
            batch = self._handoff.get()
            if batch is None:
                return
            try:
                self._run_batch(batch)
            finally:
                self._stage_exit()

    def start(self) -> "ServingServer":
        class _Server(ThreadingHTTPServer):
            # default backlog of 5 resets connections under concurrent load
            request_queue_size = 256
            daemon_threads = True

        # AOT warmup BEFORE the listener opens: a BucketedRunner-backed
        # handler (Booster.serving_fn(), docs/serving-perf.md) compiles its
        # whole bucket ladder here, so no request ever waits on XLA
        warm = getattr(self.handler, "warmup", None)
        if self.warmup and callable(warm):
            warm()
        self._httpd = _Server((self.host, self.port),
                              self._make_handler_class())
        self.port = self._httpd.server_address[1]  # resolve port 0
        t1 = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t2 = threading.Thread(target=self._serve_loop, daemon=True)
        t3 = threading.Thread(target=self._exec_loop, daemon=True)
        t1.start()
        t2.start()
        t3.start()
        self._threads = [t1, t2, t3]
        return self

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Refuse new requests (503) and wait until the queue is empty and
        the serve loop is idle. Returns True when fully drained."""
        self._draining.set()
        deadline = time.monotonic() + (self.drain_timeout
                                       if timeout is None else timeout)
        while time.monotonic() < deadline:
            if self._queue.empty() and self._idle.is_set():
                return True
            time.sleep(0.005)
        return self._queue.empty() and self._idle.is_set()

    def stop(self, drain: bool = True,
             drain_timeout: Optional[float] = None) -> None:
        """Graceful by default: in-flight requests complete (new ones get
        503 while draining), then the serve loop and listener shut down.
        ``drain=False`` tears down immediately — queued requests get their
        504 from their own deadline."""
        if drain and not self._stop.is_set():
            self.drain(drain_timeout)
        self._stop.set()
        # join stage 1 (which releases stage 2 via the None sentinel), then
        # stage 2; both are daemons, so a wedged handler cannot block exit
        for t in self._threads[1:]:
            if t.is_alive():
                t.join(timeout=1.0)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}{self.api_path}"

    def __enter__(self) -> "ServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# --- zero-downtime model hot-swap -----------------------------------------
# Swap-point hook: the registry calls _swap_point(stage, version) at every
# state transition; normally a no-op, testing.chaos.ChaosSwap installs a
# killer here so "die at any swap stage, old version never stops serving"
# is a CI property instead of a hope.

_SWAP_HOOK: Optional[Callable[[str, str], None]] = None


def _swap_point(stage: str, version: str) -> None:
    hook = _SWAP_HOOK
    if hook is not None:
        hook(stage, version)


class SwapError(RuntimeError):
    """A model swap failed (bad checkpoint, builder error, warmup failure,
    injected kill). The previously active version is still serving —
    raising this never interrupts traffic."""


class ModelRegistry:
    """Versioned handler registry driving zero-downtime hot-swap for one
    :class:`ServingServer`.

    Swap state machine (docs/resilience.md, "Multi-host fabric")::

        idle -> load -> build -> warmup -> flip -> done
                  \\        \\        \\
                   +--------+--------+--> rolled_back (old version serving)

    * ``load`` — read + digest-verify the checkpoint from a
      :class:`~synapseml_tpu.core.checkpoint.CheckpointStore` (a corrupt or
      torn checkpoint fails HERE, via the store's manifest verification).
    * ``build`` — ``builder(checkpoint) -> handler`` constructs the new
      version's handler (model deserialization, runner construction).
    * ``warmup`` — the new handler's AOT bucket ladder compiles OFF the hot
      path (the old version keeps serving throughout; this is the expensive
      stage and it costs traffic nothing).
    * ``flip`` — one atomic assignment of the server's serving pointer.
      Requests admitted before the flip are PINNED to the old handler
      (``_PendingRequest.handler``) and complete on it; requests admitted
      after run the new version. No drain, no gap, no 5xx.

    A failure (or injected kill) at load/build/warmup rolls back: the flip
    never happened, the old version never stopped serving, and the attempt
    is recorded (``swap_failures``, ``serving.swap_failed`` counter). A kill
    AFTER the flip leaves the new version serving — either side of the flip
    is a consistent fabric.

    Old versions stay registered (instant :meth:`rollback`); :meth:`retire`
    drops one after waiting for the server's in-flight stages to go idle —
    the drain machinery's idle accounting, reused so a retire can never
    yank a handler out from under a pinned in-flight batch.

    **Multi-tenant mode** (``tenant=...``): the registry drives ONE tenant's
    serving pointer (``server.tenant_handlers[tenant]``) instead of the
    server-wide ``server.handler`` — each tenant gets its own registry, its
    own version history, and its own atomic flip; admission pins
    ``handler_for(tenant)``, so tenant A's swap can never change the program
    answering tenant B's in-flight (or future) requests.

    **Swap concurrency**: two racing promoters are resolved by a
    non-blocking swap lock with a deterministic loser — the second caller
    gets ``SwapError("swap in progress")`` immediately instead of queueing
    behind (and then blindly overwriting) the first. The lock is reentrant
    so :meth:`swap_from_store` can delegate to :meth:`swap_to`, and so the
    two-phase :meth:`prepare`/:meth:`commit` pair (promotion broadcast)
    holds it across the prepare window — a racing single-shot swap loses to
    an in-flight broadcast the same deterministic way.
    """

    def __init__(self, server: ServingServer,
                 version: str = "v0", keep_versions: int = 3,
                 tenant: Optional[str] = None):
        if keep_versions < 2:
            raise ValueError("keep_versions must be >= 2 (active + rollback)")
        self.server = server
        self.keep_versions = keep_versions
        self.tenant = tenant
        self._lock = threading.Lock()       # registry state
        # one swap at a time, non-blocking acquire (deterministic loser);
        # reentrant: swap_from_store -> swap_to and prepare -> commit run
        # on one owning thread
        self._swap_lock = threading.RLock()
        self._staged: Optional[tuple] = None   # (version, handler) prepared
        # the thread holding the swap lock across a prepare window — read
        # by take_over_staged to prove the coordinator is DEAD before a
        # surviving peer adopts its orphaned stage
        self._swap_owner: Optional[threading.Thread] = None
        initial = (server.handler if tenant is None
                   else server.handler_for(tenant))
        self.versions: Dict[str, Callable] = {version: initial}
        self.active = version
        self.history: List[str] = [version]
        self.swaps = 0
        self.swap_failures = 0
        self.last_error: Optional[str] = None
        if tenant is None:
            server.registry = self
        else:
            server.tenant_handlers.setdefault(tenant, initial)
            server.registries[tenant] = self

    def _acquire_swap(self) -> None:
        if not self._swap_lock.acquire(blocking=False):
            record_failure("serving.swap_conflict", tenant=self.tenant)
            raise SwapError("swap in progress")
        with self._lock:
            self._swap_owner = threading.current_thread()
        if self._staged is not None:
            # the lock is reentrant (prepare -> commit on one thread), so a
            # same-thread single-shot swap racing an open prepare window
            # acquires — it must still lose deterministically
            self._swap_lock.release()
            record_failure("serving.swap_conflict", tenant=self.tenant)
            raise SwapError("swap in progress")

    def _install(self, handler: Callable) -> None:
        """The flip itself: one atomic assignment of this registry's
        serving pointer (tenant-scoped in multi-tenant mode)."""
        if self.tenant is None:
            self.server.handler = handler
        else:
            self.server.tenant_handlers[self.tenant] = handler

    # -- swap pipeline --
    def swap_to(self, version: str, handler: Callable,
                warmup: bool = True) -> str:
        """Stage ``handler`` as ``version``, warm it off the hot path, and
        atomically flip the server to it. Raises :class:`SwapError` on any
        pre-flip failure (old version still serving). Returns ``version``."""
        self._acquire_swap()
        try:
            # only Exception-derived faults roll back: PreemptionError is
            # BaseException on purpose (a real SIGTERM kills the process,
            # it does not roll back a swap)
            try:
                _swap_point("build", version)
                warm = getattr(handler, "warmup", None)
                if warmup and callable(warm):
                    _swap_point("warmup", version)
                    warm()          # old version serves during the compile
                _swap_point("flip", version)
            except Exception as e:  # noqa: BLE001
                with self._lock:
                    self.swap_failures += 1
                    self.last_error = f"{type(e).__name__}: {e}"
                record_failure("serving.swap_failed", version=version,
                               stage="pre-flip", error=type(e).__name__)
                raise SwapError(
                    f"swap to {version!r} failed before the flip "
                    f"({type(e).__name__}: {e}); "
                    f"{self.active!r} is still serving") from e
            # the flip: one atomic pointer assignment — admission pins the
            # handler per request, so either side of this line is consistent
            self._record_flip(version, handler)
            record_failure("serving.swap_completed", version=version)
            _swap_point("done", version)
            self._prune()
            return version
        finally:
            self._swap_lock.release()

    def _record_flip(self, version: str, handler: Callable) -> None:
        with self._lock:
            self.versions[version] = handler
            self.active = version
            if version in self.history:
                self.history.remove(version)
            self.history.append(version)
            self.swaps += 1
            self.last_error = None
        self._install(handler)

    # -- two-phase swap (promotion broadcast) --
    def prepare(self, version: str, handler: Callable,
                warmup: bool = True) -> str:
        """Phase 1 of a fabric-wide swap: stage + AOT-warm ``handler`` OFF
        the hot path and hold the swap lock, WITHOUT flipping. The old
        version keeps serving; a racing swap loses with
        ``SwapError("swap in progress")``. Follow with :meth:`commit` (the
        atomic flip) or :meth:`abort` (discard, old version untouched) —
        from the same thread (the lock is owned by it)."""
        self._acquire_swap()
        try:
            _swap_point("prepare", version)
            warm = getattr(handler, "warmup", None)
            if warmup and callable(warm):
                _swap_point("warmup", version)
                warm()
        except Exception as e:  # noqa: BLE001
            self._swap_lock.release()
            with self._lock:
                self.swap_failures += 1
                self.last_error = f"{type(e).__name__}: {e}"
            record_failure("serving.swap_failed", version=version,
                           stage="prepare", error=type(e).__name__)
            raise SwapError(
                f"prepare of {version!r} failed "
                f"({type(e).__name__}: {e}); "
                f"{self.active!r} is still serving") from e
        self._staged = (version, handler)
        return version

    def commit(self, version: Optional[str] = None) -> str:
        """Phase 2: atomically flip to the prepared version and release the
        swap lock. A failure AT the commit point (injected kill) leaves the
        version staged and the lock held — :meth:`commit` may be retried,
        or :meth:`abort` discards. Without a matching :meth:`prepare` this
        raises :class:`SwapError`."""
        staged = self._staged
        if staged is None:
            raise SwapError("commit without a prepared version")
        staged_version, handler = staged
        if version is not None and version != staged_version:
            raise SwapError(
                f"commit of {version!r} but {staged_version!r} is staged")
        _swap_point("commit", staged_version)   # chaos kill point
        self._record_flip(staged_version, handler)
        self._staged = None
        self._swap_lock.release()
        record_failure("serving.swap_completed", version=staged_version)
        _swap_point("done", staged_version)
        self._prune()
        return staged_version

    def take_over_staged(self) -> bool:
        """Adopt an orphaned prepare window after its coordinator died.

        A prepare holds the swap RLock in the COORDINATOR's thread; if that
        thread dies mid-broadcast the stage is stranded — an RLock can
        never be released by another thread, so a surviving peer could
        neither :meth:`commit` nor :meth:`abort`. This transfers ownership:
        only when the owning thread is provably dead (``is_alive()`` is
        False), the abandoned lock object is REPLACED with a fresh one
        acquired by the caller, who may then drive the staged version to
        commit or abort exactly as the coordinator would have. A live
        owner raises :class:`SwapError` — takeover is recovery, never
        preemption. Returns False when nothing is staged (the coordinator
        finished or never prepared here); True when the caller now owns
        the stage (idempotent for the owner itself)."""
        with self._lock:
            staged = self._staged
            owner = self._swap_owner
        if staged is None:
            return False
        if owner is threading.current_thread():
            return True
        if owner is not None and owner.is_alive():
            raise SwapError(
                f"staged swap to {staged[0]!r} is owned by live thread "
                f"{owner.name!r}; takeover requires a dead coordinator")
        fresh = threading.RLock()
        fresh.acquire()
        with self._lock:
            self._swap_lock = fresh
            self._swap_owner = threading.current_thread()
        record_failure("serving.swap_takeover", version=staged[0],
                       tenant=self.tenant)
        return True

    def abort(self) -> bool:
        """Discard a prepared version and release the swap lock; the old
        version never stopped serving. Idempotent (False when nothing is
        staged)."""
        if self._staged is None:
            return False
        version = self._staged[0]
        self._staged = None
        self._swap_lock.release()
        record_failure("serving.swap_aborted", version=version,
                       tenant=self.tenant)
        return True

    def swap_from_store(self, store, builder: Callable,
                        step: Optional[int] = None,
                        warmup: bool = True) -> str:
        """Load a checkpoint (digest-verified by the store's manifest),
        build a handler from it via ``builder(checkpoint)``, and swap to it.
        ``step=None`` loads the newest VERIFIABLE checkpoint. A corrupt
        checkpoint, missing store, or builder failure raises
        :class:`SwapError` with the old version still serving."""
        # hold the swap lock across load+build as well (reentrant for the
        # delegated swap_to): two promoters racing swap_from_store must
        # resolve to one winner and one SwapError("swap in progress"), not
        # interleaved load/build/flip stages
        self._acquire_swap()
        try:
            return self._swap_from_store_locked(store, builder, step, warmup)
        finally:
            self._swap_lock.release()

    def _swap_from_store_locked(self, store, builder: Callable,
                                step: Optional[int],
                                warmup: bool) -> str:
        try:
            _swap_point("load", "?")
            ckpt = (store.load_step(step) if step is not None
                    else store.load_latest())
        except Exception as e:  # noqa: BLE001
            with self._lock:
                self.swap_failures += 1
                self.last_error = f"{type(e).__name__}: {e}"
            record_failure("serving.swap_failed", stage="load",
                           error=type(e).__name__)
            raise SwapError(
                f"swap aborted: checkpoint load failed ({e}); "
                f"{self.active!r} is still serving") from e
        if ckpt is None:
            with self._lock:
                self.swap_failures += 1
                self.last_error = "no verifiable checkpoint"
            record_failure("serving.swap_failed", stage="load",
                           error="CheckpointError")
            raise SwapError(
                "swap aborted: the store holds no verifiable checkpoint; "
                f"{self.active!r} is still serving")
        version = ckpt.version
        with self._lock:
            if version == self.active:
                return version    # already serving these exact bytes
        try:
            handler = builder(ckpt)
        except Exception as e:  # noqa: BLE001
            with self._lock:
                self.swap_failures += 1
                self.last_error = f"{type(e).__name__}: {e}"
            record_failure("serving.swap_failed", version=version,
                           stage="build", error=type(e).__name__)
            raise SwapError(
                f"swap to {version!r} aborted: builder failed ({e}); "
                f"{self.active!r} is still serving") from e
        return self.swap_to(version, handler, warmup=warmup)

    # -- rollback / retention --
    def rollback(self) -> str:
        """Flip back to the previously active version (still registered).
        Raises :class:`SwapError` when there is nothing to roll back to."""
        with self._lock:
            if len(self.history) < 2:
                raise SwapError("no previous version to roll back to")
            prev = self.history[-2]
            handler = self.versions[prev]
        return self.swap_to(prev, handler, warmup=False)

    def retire(self, version: str, wait_idle: bool = True,
               timeout: float = 10.0) -> bool:
        """Drop an inactive version. With ``wait_idle`` the call first waits
        for the server's pipeline stages to go idle (the drain machinery's
        accounting), so a pinned in-flight batch can never lose its handler.
        Returns False when the version is active or unknown."""
        with self._lock:
            if version == self.active or version not in self.versions:
                return False
        if wait_idle:
            self.server._idle.wait(timeout)
        with self._lock:
            if version == self.active:   # re-check: a swap may have raced
                return False
            self.versions.pop(version, None)
            if version in self.history:
                self.history.remove(version)
        return True

    def _prune(self) -> None:
        while True:
            with self._lock:
                if len(self.history) <= self.keep_versions:
                    return
                victim = self.history[0]
            if not self.retire(victim, wait_idle=True):
                return

    def snapshot(self) -> dict:
        with self._lock:
            return {"active": self.active,
                    "versions": list(self.history),
                    "swaps": self.swaps,
                    "swap_failures": self.swap_failures,
                    "last_error": self.last_error}
