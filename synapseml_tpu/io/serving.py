"""Serving — embedded HTTP server feeding micro-batches through a pipeline.

Reference: the Spark Serving layer (SURVEY.md §3.5): custom streaming sources
embedding web servers (HTTPSourceV2.scala:485-713 ``WorkerServer`` with request
queue + reply-by-id sink, HTTPSource.scala head-node variant, ServingUDFs.scala
``makeReplyUDF``). The reference queues requests into Spark micro-batches and
replies through a sink keyed by request id; here a threaded HTTP server queues
requests, a serving loop drains the queue into a ``Table`` micro-batch, runs
the user pipeline (one jitted program for model transforms), and writes each
row's reply back to its still-open connection — same architecture, no Spark.
"""

from __future__ import annotations

import json as _json
import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core.table import Table


@dataclass
class _PendingRequest:
    """CachedRequest analog (HTTPSourceV2.scala:530-539)."""
    id: str
    method: str
    path: str
    headers: Dict[str, str]
    body: bytes
    reply_event: threading.Event = field(default_factory=threading.Event)
    response: Optional[tuple] = None  # (status, headers, body)


def request_to_table(requests: List[_PendingRequest]) -> Table:
    """Micro-batch of queued requests → Table(id, value) — the serving source
    schema (id + request struct)."""
    ids = np.array([r.id for r in requests], dtype=object)
    vals = np.empty(len(requests), dtype=object)
    for i, r in enumerate(requests):
        try:
            vals[i] = _json.loads(r.body.decode()) if r.body else None
        except Exception:
            vals[i] = r.body
    return Table({"id": ids, "value": vals})


def respond_with(df: Table, id_col: str = "id", value_col: str = "reply",
                 status_col: Optional[str] = None) -> Dict[str, tuple]:
    """Table → {request id: (status, body)} — the reply-UDF analog
    (ServingUDFs.scala makeReplyUDF)."""
    out = {}
    statuses = df[status_col] if status_col and status_col in df else None
    for i in range(df.num_rows):
        val = df[value_col][i]
        if isinstance(val, np.ndarray):
            val = val.tolist()
        elif isinstance(val, np.generic):
            val = val.item()
        status = int(statuses[i]) if statuses is not None else 200
        out[str(df[id_col][i])] = (status, _json.dumps(val).encode())
    return out


class ServingServer:
    """spark.readStream.server()...writeStream.server() analog.

    ``handler``: Table(id, value) -> Table(id, reply) — typically a fitted
    PipelineModel wrapped to map columns. Batching: requests are collected for
    up to ``maxBatchLatency`` seconds or ``maxBatchSize`` rows, whichever
    first (micro-batch trigger analog), then run through the handler as ONE
    batch — on TPU that is one jitted call, which is where the reference's
    "sub-millisecond" story becomes a batched-throughput story.
    """

    def __init__(self, handler: Callable[[Table], Table],
                 host: str = "127.0.0.1", port: int = 8898,
                 api_path: str = "/", max_batch_size: int = 64,
                 max_batch_latency: float = 0.005,
                 reply_timeout: float = 30.0):
        self.handler = handler
        self.host, self.port = host, port
        self.api_path = api_path
        self.max_batch_size = max_batch_size
        self.max_batch_latency = max_batch_latency
        self.reply_timeout = reply_timeout
        self._queue: "queue.Queue[_PendingRequest]" = queue.Queue()
        self._stop = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []

    # --- embedded server (WorkerServer analog) -------------------------
    def _make_handler_class(self):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive: clients reuse the connection (and this
            # handler's thread) across requests instead of paying TCP setup +
            # thread spawn per request — the dominant term at sub-ms latencies
            protocol_version = "HTTP/1.1"
            # response headers+body go out in several small writes; without
            # TCP_NODELAY, Nagle + delayed ACK stalls each reply ~40 ms
            disable_nagle_algorithm = True
            # bound idle keep-alive connections: without a socket timeout each
            # idle client pins its handler thread in readline() forever and
            # stop() cannot quiesce them (timeout → close_connection)
            timeout = 30

            def do_POST(self):  # noqa: N802
                if "chunked" in self.headers.get("Transfer-Encoding",
                                                 "").lower():
                    # chunked bodies are not parsed; reading 0 bytes would
                    # desync the keep-alive stream (the chunk data would be
                    # parsed as the next request), so reject and close
                    self.send_response(411)  # Length Required
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    self.close_connection = True
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                req = _PendingRequest(
                    id=uuid.uuid4().hex, method="POST", path=self.path,
                    headers=dict(self.headers), body=body)
                outer._queue.put(req)
                if not req.reply_event.wait(outer.reply_timeout):
                    self.send_response(504)
                    # explicit empty body: HTTP/1.1 keep-alive clients block
                    # on a missing Content-Length
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                status, headers, payload = req.response
                self.send_response(status)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):  # quiet
                pass

        return Handler

    def _serve_loop(self) -> None:
        """Micro-batch trigger: drain queue → handler → reply by id."""
        while not self._stop.is_set():
            batch: List[_PendingRequest] = []
            try:
                batch.append(self._queue.get(timeout=0.05))
            except queue.Empty:
                continue
            # drain the existing backlog for free (batching under load costs
            # no latency), then optionally wait out the batch-formation window
            deadline = time.monotonic() + self.max_batch_latency
            while len(batch) < self.max_batch_size:
                try:
                    batch.append(self._queue.get_nowait())
                except queue.Empty:
                    if time.monotonic() >= deadline:
                        break
                    time.sleep(0.0005)
            df = request_to_table(batch)
            by_id = {r.id: r for r in batch}
            try:
                out = self.handler(df)
                replies = respond_with(out) if isinstance(out, Table) else out
            except Exception as e:  # noqa: BLE001
                err = _json.dumps({"error": str(e)}).encode()
                replies = {r.id: (500, err) for r in batch}
            for rid, (status, payload) in replies.items():
                req = by_id.get(rid)
                if req is not None:
                    req.response = (status, {}, payload)
                    req.reply_event.set()
            # requests the handler dropped get an error instead of a hang
            for r in batch:
                if r.response is None:
                    r.response = (500, {}, b'{"error": "no reply produced"}')
                    r.reply_event.set()

    def start(self) -> "ServingServer":
        class _Server(ThreadingHTTPServer):
            # default backlog of 5 resets connections under concurrent load
            request_queue_size = 256
            daemon_threads = True

        self._httpd = _Server((self.host, self.port),
                              self._make_handler_class())
        self.port = self._httpd.server_address[1]  # resolve port 0
        t1 = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t2 = threading.Thread(target=self._serve_loop, daemon=True)
        t1.start()
        t2.start()
        self._threads = [t1, t2]
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}{self.api_path}"

    def __enter__(self) -> "ServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
