"""Binary & image file datasources.

Reference: io/binary/BinaryFileFormat.scala (path+bytes DataFrame source) and
org/apache/spark/ml/source/image/PatchedImageFileFormat.scala (image schema
source). Here: directory walks producing Tables with (path, bytes) or
(path, image array) columns; image decode goes through ops/image so tensors
are ready for the TPU preprocessing path.
"""

from __future__ import annotations

import fnmatch
import os
from typing import List, Optional

import numpy as np

from ..core.table import Table

_IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".ppm", ".npy")


def _walk(path: str, pattern: Optional[str], recursive: bool) -> List[str]:
    out: List[str] = []
    if os.path.isfile(path):
        return [path]
    for root, dirs, files in os.walk(path):
        for f in sorted(files):
            if pattern is None or fnmatch.fnmatch(f, pattern):
                out.append(os.path.join(root, f))
        if not recursive:
            break
    return out


def read_binary_files(path: str, pattern: Optional[str] = None,
                      recursive: bool = True) -> Table:
    """Directory → Table(path, bytes) (BinaryFileFormat analog)."""
    paths = _walk(path, pattern, recursive)
    blobs = np.empty(len(paths), dtype=object)
    for i, p in enumerate(paths):
        with open(p, "rb") as f:
            blobs[i] = f.read()
    return Table({"path": np.asarray(paths, dtype=object), "bytes": blobs})


def read_image_dir(path: str, pattern: Optional[str] = None,
                   recursive: bool = True,
                   drop_invalid: bool = True) -> Table:
    """Directory → Table(path, image) with HWC float arrays
    (PatchedImageFileFormat analog; dropInvalid matches the reference's
    tolerant decode at ImageTransformer.scala:688-699)."""
    from ..ops.image import decode_image_bytes

    paths = [p for p in _walk(path, pattern, recursive)
             if p.lower().endswith(_IMAGE_EXTS)]
    imgs, kept = [], []
    for p in paths:
        try:
            if p.lower().endswith(".npy"):  # pre-decoded array file
                imgs.append(np.load(p))
            else:
                with open(p, "rb") as f:
                    imgs.append(decode_image_bytes(f.read()))
            kept.append(p)
        except Exception:
            if not drop_invalid:
                raise
    col = np.empty(len(imgs), dtype=object)
    for i, im in enumerate(imgs):
        col[i] = im
    return Table({"path": np.asarray(kept, dtype=object), "image": col})


def load_numeric_csv(path: str, has_header: bool = True) -> "np.ndarray":
    """Dense float32 ingest for training matrices: C++ fast path
    (native.read_numeric_csv) with a numpy fallback. Empty/unparseable
    fields become NaN (routed by the GBDT engine's learned default_left)."""
    from ..native import read_numeric_csv

    out = read_numeric_csv(path, has_header)
    if out is not None:
        return out
    # fallback matches the native reader's delimiter handling (comma or tab)
    with open(path) as f:
        first = f.readline()
    delim = "\t" if ("\t" in first and "," not in first) else ","
    out = np.genfromtxt(path, delimiter=delim,
                        skip_header=1 if has_header else 0,
                        dtype=np.float32)
    if out.ndim == 1:
        # genfromtxt flattens both 1-row and 1-column files; the first line
        # disambiguates: no delimiter there means a single-column file
        out = out.reshape(-1, 1) if delim not in first else out[None, :]
    return out
