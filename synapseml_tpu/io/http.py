"""HTTP-on-Table client layer.

Reference: io/http/HTTPTransformer.scala:93-147 (per-partition pooled async
clients, ``concurrency``/``timeout``/``concurrentTimeout``, handler function),
SimpleHTTPTransformer.scala (url + input/output parsers + errorCol +
mini-batching), HTTPSchema.scala (request/response structs), Parsers.scala,
RESTHelpers.scala (retry on 429/5xx with backoff). The reference rides Apache
HttpClient futures inside Spark partitions; here requests fan out over a
thread pool (IO-bound — threads are right even under the GIL) and land back as
columns.
"""

from __future__ import annotations

import json as _json
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core.logging import record_failure
from ..core.params import Param, HasInputCol, HasOutputCol
from ..core.pipeline import Transformer
from ..core.resilience import RetryBudget
from ..core.table import Table


@dataclass
class HTTPRequestData:
    """HTTPSchema.scala request struct analog."""
    url: str = ""
    method: str = "POST"
    headers: Dict[str, str] = field(default_factory=dict)
    entity: Optional[bytes] = None

    @staticmethod
    def from_json_body(url: str, body: Any,
                       headers: Optional[Dict[str, str]] = None
                       ) -> "HTTPRequestData":
        h = {"Content-Type": "application/json"}
        h.update(headers or {})
        return HTTPRequestData(url=url, method="POST", headers=h,
                               entity=_json.dumps(body).encode())


@dataclass
class HTTPResponseData:
    """HTTPSchema.scala response struct analog."""
    status_code: int = 0
    reason: str = ""
    headers: Dict[str, str] = field(default_factory=dict)
    entity: Optional[bytes] = None

    def json(self) -> Any:
        return _json.loads(self.entity.decode()) if self.entity else None

    @property
    def text(self) -> str:
        return self.entity.decode("utf-8", "replace") if self.entity else ""


_RETRY_CODES = (429, 500, 502, 503, 504)


def send_with_retries(req: HTTPRequestData, timeout: float = 60.0,
                      retries: int = 3, backoff: float = 0.5,
                      opener=None,
                      retry_budget: Optional[RetryBudget] = None
                      ) -> HTTPResponseData:
    """RESTHelpers.scala analog: retry 429/5xx with exponential backoff.

    ``opener`` substitutes the transport (anything with
    ``.open(request, timeout=)`` — e.g. a chaos injector from
    :mod:`synapseml_tpu.testing.chaos`). ``retry_budget`` caps AGGREGATE
    retry volume across callers sharing the bucket: each retry (not the
    first attempt) spends one token, and an empty bucket ends the retry
    loop early — the client-side brake on retry storms against an already
    overloaded service. None = unbounded retries (per-call knobs only)."""
    last: Optional[HTTPResponseData] = None
    for attempt in range(retries + 1):
        try:
            r = urllib.request.Request(req.url, data=req.entity,
                                       headers=req.headers,
                                       method=req.method)
            open_fn = opener.open if opener else urllib.request.urlopen
            with open_fn(r, timeout=timeout) as resp:
                return HTTPResponseData(
                    status_code=resp.status, reason=getattr(resp, "reason", ""),
                    headers=dict(resp.headers), entity=resp.read())
        except urllib.error.HTTPError as e:
            last = HTTPResponseData(status_code=e.code, reason=str(e.reason),
                                    headers=dict(e.headers or {}),
                                    entity=e.read())
            if e.code not in _RETRY_CODES:
                return last
            record_failure("http.retryable_status", status=e.code)
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            last = HTTPResponseData(status_code=0, reason=str(e))
            record_failure("http.transport_error", error=type(e).__name__)
        if attempt < retries:
            if retry_budget is not None and not retry_budget.try_spend():
                record_failure("http.retry_budget_exhausted", url=req.url)
                break
            time.sleep(backoff * (2 ** attempt))
    return last or HTTPResponseData(status_code=0, reason="no attempts")


def dispatch_with_handler(req: HTTPRequestData, timeout: float, retries: int,
                          backoff: float, handler=None, opener=None,
                          retry_budget: Optional[RetryBudget] = None
                          ) -> HTTPResponseData:
    """Single dispatch point for handler-or-default sending (shared by
    HTTPTransformer and the services layer)."""
    send = lambda r: send_with_retries(r, timeout, retries, backoff,  # noqa: E731
                                       opener=opener,
                                       retry_budget=retry_budget)
    return handler(req, send) if handler is not None else send(req)


class HTTPTransformer(Transformer, HasInputCol, HasOutputCol):
    """Column of HTTPRequestData → column of HTTPResponseData
    (reference HTTPTransformer.scala:93-147)."""

    concurrency = Param("concurrency", "max simultaneous requests", int, 1)
    timeout = Param("timeout", "per-request timeout, seconds", float, 60.0)
    concurrentTimeout = Param("concurrentTimeout",
                              "overall timeout for a batch of concurrent "
                              "requests (None = wait forever)", float)
    handler = Param("handler", "function (HTTPRequestData, send) -> "
                    "HTTPResponseData overriding the default sender",
                    is_complex=True)
    maxRetries = Param("maxRetries", "retries for 429/5xx responses", int, 3)
    backoff = Param("backoff", "initial backoff, seconds", float, 0.5)
    opener = Param("opener", "transport override with .open(request, "
                   "timeout=) — e.g. a chaos injector", is_complex=True)
    retryBudget = Param("retryBudget", "shared RetryBudget token bucket "
                        "capping aggregate retry volume", is_complex=True)

    def setHandler(self, f: Callable) -> "HTTPTransformer":
        return self.set("handler", f)

    def _send_one(self, req: HTTPRequestData) -> HTTPResponseData:
        return dispatch_with_handler(req, self.getTimeout(),
                                     self.getMaxRetries(), self.getBackoff(),
                                     self.get("handler"),
                                     opener=self.get("opener"),
                                     retry_budget=self.get("retryBudget"))

    def _transform(self, df: Table) -> Table:
        import time as _time

        reqs: List[HTTPRequestData] = list(df[self.getInputCol()])
        workers = max(1, min(self.getConcurrency(),
                             df.concurrency_hint or self.getConcurrency()))
        if workers == 1:
            out = [self._send_one(r) for r in reqs]
        else:
            # concurrentTimeout is a SHARED wall-clock deadline for the whole
            # batch (reference awaitWithTimeout over the future batch)
            budget = self.get("concurrentTimeout")
            deadline = None if budget is None else _time.monotonic() + budget
            pool = ThreadPoolExecutor(max_workers=workers)
            try:
                futures = [pool.submit(self._send_one, r) for r in reqs]
                out = []
                for f in futures:
                    remaining = (None if deadline is None
                                 else max(deadline - _time.monotonic(), 0.0))
                    try:
                        out.append(f.result(timeout=remaining))
                    except FuturesTimeout:
                        # a done future raised the worker's own TimeoutError
                        # (same builtin type on py>=3.11) — propagate it; an
                        # undone future means the batch deadline expired
                        if f.done():
                            raise
                        pool.shutdown(wait=False, cancel_futures=True)
                        raise TimeoutError(
                            "HTTPTransformer: batch exceeded "
                            f"concurrentTimeout={budget}s") from None
            finally:
                pool.shutdown(wait=False, cancel_futures=True)
        col = np.empty(len(out), dtype=object)
        col[:] = out
        return df.with_column(self.getOutputCol(), col)


# --- parsers (Parsers.scala analogs) ---------------------------------------

class JSONInputParser(Transformer, HasInputCol, HasOutputCol):
    """Row value → JSON POST HTTPRequestData."""
    url = Param("url", "target url", str)
    headers = Param("headers", "extra headers", is_complex=True)

    def _transform(self, df: Table) -> Table:
        vals = df[self.getInputCol()]
        out = np.empty(len(vals), dtype=object)
        for i, v in enumerate(vals):
            body = v.tolist() if isinstance(v, np.ndarray) else \
                (v.item() if isinstance(v, np.generic) else v)
            out[i] = HTTPRequestData.from_json_body(
                self.getUrl(), body, self.get("headers"))
        return df.with_column(self.getOutputCol(), out)


class CustomInputParser(Transformer, HasInputCol, HasOutputCol):
    """User function value → HTTPRequestData."""
    udf = Param("udf", "value -> HTTPRequestData", is_complex=True)

    def setUDF(self, f: Callable) -> "CustomInputParser":
        return self.set("udf", f)

    def _transform(self, df: Table) -> Table:
        f = self.get("udf")
        vals = df[self.getInputCol()]
        out = np.empty(len(vals), dtype=object)
        for i, v in enumerate(vals):
            out[i] = f(v)
        return df.with_column(self.getOutputCol(), out)


class JSONOutputParser(Transformer, HasInputCol, HasOutputCol):
    """HTTPResponseData → parsed JSON (optionally projected by dataType keys)."""
    postProcessor = Param("postProcessor", "optional json -> value function",
                          is_complex=True)

    def _transform(self, df: Table) -> Table:
        post = self.get("postProcessor")
        resps = df[self.getInputCol()]
        out = np.empty(len(resps), dtype=object)
        for i, r in enumerate(resps):
            val = r.json() if r is not None and r.entity else None
            out[i] = post(val) if post is not None and val is not None else val
        return df.with_column(self.getOutputCol(), out)


class StringOutputParser(Transformer, HasInputCol, HasOutputCol):
    def _transform(self, df: Table) -> Table:
        resps = df[self.getInputCol()]
        out = np.array([r.text if r is not None else "" for r in resps],
                       dtype=object)
        return df.with_column(self.getOutputCol(), out)


class CustomOutputParser(Transformer, HasInputCol, HasOutputCol):
    udf = Param("udf", "HTTPResponseData -> value", is_complex=True)

    def setUDF(self, f: Callable) -> "CustomOutputParser":
        return self.set("udf", f)

    def _transform(self, df: Table) -> Table:
        f = self.get("udf")
        resps = df[self.getInputCol()]
        out = np.empty(len(resps), dtype=object)
        for i, r in enumerate(resps):
            out[i] = f(r)
        return df.with_column(self.getOutputCol(), out)


class SimpleHTTPTransformer(Transformer, HasInputCol, HasOutputCol):
    """Input parse → HTTP → output parse, with error column
    (reference SimpleHTTPTransformer.scala:65-180)."""

    url = Param("url", "service url", str)
    inputParser = Param("inputParser", "value -> HTTPRequestData transformer",
                        is_complex=True)
    outputParser = Param("outputParser", "HTTPResponseData -> value "
                         "transformer", is_complex=True)
    errorCol = Param("errorCol", "column to hold http errors", str)
    concurrency = Param("concurrency", "max simultaneous requests", int, 1)
    timeout = Param("timeout", "per-request timeout, seconds", float, 60.0)
    handler = Param("handler", "custom send handler", is_complex=True)
    opener = Param("opener", "transport override with .open(request, "
                   "timeout=) — e.g. a chaos injector", is_complex=True)
    retryBudget = Param("retryBudget", "shared RetryBudget token bucket "
                        "capping aggregate retry volume", is_complex=True)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        if not self.isSet("errorCol"):
            self.set("errorCol", self.uid + "_errors")

    def _transform(self, df: Table) -> Table:
        in_parser = self.get("inputParser") or JSONInputParser()
        in_parser = in_parser.copy()  # never mutate the caller's parser
        in_parser.set("inputCol", self.getInputCol())
        in_parser.set("outputCol", "__request")
        if in_parser.hasParam("url") and self.isSet("url"):
            in_parser.set("url", self.getUrl())

        http = HTTPTransformer(inputCol="__request", outputCol="__response",
                               concurrency=self.getConcurrency(),
                               timeout=self.getTimeout())
        if self.get("handler") is not None:
            http.setHandler(self.get("handler"))
        for p in ("opener", "retryBudget"):
            if self.get(p) is not None:
                http.set(p, self.get(p))

        out_parser = (self.get("outputParser") or JSONOutputParser()).copy()
        out_parser.set("inputCol", "__response")
        out_parser.set("outputCol", self.getOutputCol())

        cur = out_parser.transform(http.transform(in_parser.transform(df)))
        resps = cur["__response"]
        errors = np.empty(len(resps), dtype=object)
        for i, r in enumerate(resps):
            errors[i] = (None if r is not None and 200 <= r.status_code < 300
                         else {"statusCode": getattr(r, "status_code", 0),
                               "reason": getattr(r, "reason", "no response")})
        cur = cur.with_column(self.getErrorCol(), errors)
        del cur["__request"], cur["__response"]
        return cur
