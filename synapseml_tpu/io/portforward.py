"""SSH port forwarding helper.

Reference: core/.../io/http/PortForwarding.scala — forwards a local port to a
remote host over ssh (used to reach driver-side services from notebooks).
Implemented over the system ``ssh`` binary (no paramiko in the image); each
forward is a managed subprocess.
"""

from __future__ import annotations

import atexit
import shutil
import subprocess
from typing import Dict, Optional

_forwards: Dict[int, subprocess.Popen] = {}

# a notebook that never calls stop_forwarding would otherwise leave ssh
# children running (and unreaped) past interpreter exit
atexit.register(lambda: stop_forwarding())


def forward_port(remote_host: str, remote_port: int, local_port: int,
                 ssh_user: Optional[str] = None,
                 ssh_opts: Optional[list] = None) -> subprocess.Popen:
    """Start ``ssh -N -L local:localhost:remote`` to ``remote_host``; returns
    the process (also tracked for stop_forwarding)."""
    if shutil.which("ssh") is None:
        raise EnvironmentError("ssh binary not available for port forwarding")
    if local_port in _forwards:
        stop_forwarding(local_port)  # reusing a port replaces its forward
    target = f"{ssh_user}@{remote_host}" if ssh_user else remote_host
    cmd = ["ssh", "-N", "-o", "StrictHostKeyChecking=no",
           "-L", f"{local_port}:localhost:{remote_port}", target]
    if ssh_opts:
        cmd = cmd[:1] + list(ssh_opts) + cmd[1:]
    proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    _forwards[local_port] = proc
    return proc


def stop_forwarding(local_port: Optional[int] = None) -> None:
    """Stop one forward (or all when ``local_port`` is None)."""
    ports = [local_port] if local_port is not None else list(_forwards)
    for p in ports:
        proc = _forwards.pop(p, None)
        if proc is None:
            continue
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        proc.wait()  # reap — no zombies
