"""SSH port forwarding helper.

Reference: core/.../io/http/PortForwarding.scala — forwards a local port to a
remote host over ssh (used to reach driver-side services from notebooks).
Implemented over the system ``ssh`` binary (no paramiko in the image); each
forward is a managed subprocess.
"""

from __future__ import annotations

import atexit
import shlex
import shutil
import subprocess
from typing import Dict, List, Optional, Sequence

_forwards: Dict[int, subprocess.Popen] = {}
_remotes: List[subprocess.Popen] = []

# a notebook that never calls stop_forwarding would otherwise leave ssh
# children running (and unreaped) past interpreter exit
atexit.register(lambda: stop_forwarding())
atexit.register(lambda: reap_remote())


def forward_port(remote_host: str, remote_port: int, local_port: int,
                 ssh_user: Optional[str] = None,
                 ssh_opts: Optional[list] = None) -> subprocess.Popen:
    """Start ``ssh -N -L local:localhost:remote`` to ``remote_host``; returns
    the process (also tracked for stop_forwarding)."""
    if shutil.which("ssh") is None:
        raise EnvironmentError("ssh binary not available for port forwarding")
    if local_port in _forwards:
        stop_forwarding(local_port)  # reusing a port replaces its forward
    target = f"{ssh_user}@{remote_host}" if ssh_user else remote_host
    cmd = ["ssh", "-N", "-o", "StrictHostKeyChecking=no",
           "-L", f"{local_port}:localhost:{remote_port}", target]
    if ssh_opts:
        cmd = cmd[:1] + list(ssh_opts) + cmd[1:]
    proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    _forwards[local_port] = proc
    return proc


def stop_forwarding(local_port: Optional[int] = None) -> None:
    """Stop one forward (or all when ``local_port`` is None)."""
    ports = [local_port] if local_port is not None else list(_forwards)
    for p in ports:
        proc = _forwards.pop(p, None)
        if proc is None:
            continue
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        proc.wait()  # reap — no zombies


def remote_spawn(host: Optional[str], argv: Sequence[str],
                 ssh_user: Optional[str] = None,
                 ssh_opts: Optional[list] = None,
                 env: Optional[dict] = None) -> subprocess.Popen:
    """Start a worker command on ``host`` — the cross-host ``spawn_fn`` hook
    for ``parallel.elastic.TrainingSupervisor`` (the supervisor itself is
    placement-agnostic; this closes the ROADMAP "spawn_fn is process-local"
    gap). ``host`` None/""/"localhost"/"127.0.0.1" runs the command as a
    plain local subprocess (no ssh dependency — what tests and single-box
    gangs use); anything else runs it over the same managed-``ssh``
    discipline as :func:`forward_port`. The returned ``Popen`` is tracked
    and reaped at interpreter exit (:func:`reap_remote`)."""
    argv = [str(a) for a in argv]
    if host in (None, "", "localhost", "127.0.0.1"):
        proc = subprocess.Popen(argv, env=env,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
    else:
        if shutil.which("ssh") is None:
            raise EnvironmentError(
                "ssh binary not available for cross-host spawn")
        target = f"{ssh_user}@{host}" if ssh_user else host
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no"]
        if ssh_opts:
            cmd += list(ssh_opts)
        # env is exported inline: ssh has no Popen-style env plumbing
        exports = " ".join(f"{k}={shlex.quote(str(v))}"
                           for k, v in (env or {}).items())
        remote_cmd = " ".join(shlex.quote(a) for a in argv)
        cmd += [target, f"{exports} {remote_cmd}".strip()]
        proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
    _remotes.append(proc)
    return proc


def reap_remote(proc: Optional[subprocess.Popen] = None,
                timeout: float = 5.0) -> None:
    """Terminate + reap one spawned worker (or all when ``proc`` is None).
    Same no-zombies discipline as :func:`stop_forwarding`."""
    victims = [proc] if proc is not None else list(_remotes)
    for p in victims:
        try:
            _remotes.remove(p)
        except ValueError:
            pass   # already reaped by an earlier call
        if p.poll() is None:
            p.terminate()
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
        p.wait()
