"""PowerBI streaming-dataset writer.

Reference: io/powerbi/PowerBIWriter.scala — POSTs row batches as JSON to a
Power BI push-dataset URL with retry/backoff. Host-side REST only; batches
rows to respect the API's row-per-request limits.
"""

from __future__ import annotations

import json as _json

from ..core.table import Table
from .http import HTTPRequestData, send_with_retries


class PowerBIWriter:
    def __init__(self, url: str, batch_size: int = 1000, retries: int = 3,
                 timeout: float = 60.0):
        self.url = url
        self.batch_size = batch_size
        self.retries = retries
        self.timeout = timeout

    def write(self, df: Table) -> int:
        """POST the table in batches; returns number of rows written."""
        rows = df.to_pandas().to_dict(orient="records")
        written = 0
        for start in range(0, len(rows), self.batch_size):
            chunk = rows[start:start + self.batch_size]
            req = HTTPRequestData.from_json_body(self.url, {"rows": chunk})
            resp = send_with_retries(req, timeout=self.timeout,
                                     retries=self.retries)
            if not 200 <= resp.status_code < 300:
                raise RuntimeError(
                    f"PowerBI write failed at row {start}: "
                    f"{resp.status_code} {resp.reason}")
            written += len(chunk)
        return written
