"""Shared double-buffered host→device ingestion layer — ONE chunk pump for
every streaming consumer in the repo.

Three loops used to own three ad-hoc prefetch pipelines: the dl trainer's
``_prefetch`` deque (``TrainConfig.prefetch_batches``), the online loops'
drain-poll thread, and (new, the reason this module exists) the out-of-core
GBDT data plane (``gbdt/stream.py``), which re-streams the quantized feature
matrix from host memory once per tree level. They now share this layer:

:class:`ChunkPump`
    A bounded-depth chunk pipeline. ``place(chunk)`` (typically a sharded
    ``jax.device_put``) is applied to chunk ``k+1`` while the consumer
    computes on chunk ``k`` — JAX dispatch is async, so merely HOLDING the
    placed-but-unconsumed chunks keeps their host→device transfers in
    flight. Two drive modes:

    * ``threaded=False`` (dl default): a synchronous lookahead deque —
      exactly the seed ``_prefetch`` semantics, no thread, transfers overlap
      through async dispatch alone.
    * ``threaded=True`` (gbdt streaming): a named non-daemon producer thread
      pulls + places ahead of the consumer so the HOST side of a transfer
      (pageable-memory copy, binning, decompression) also overlaps compute.
      The thread is joined on EVERY exit path — ``__iter__`` closes the pump
      in a ``finally`` so early consumer exits (break, error, preemption)
      cannot leak it (tools/analysis resource-discipline scope).

    Every chunk boundary is a :func:`~synapseml_tpu.core.checkpoint.
    preemption_point` and an elastic-watchdog heartbeat (``phase=...``), so
    the pump composes with the PR 2 checkpoint machinery and the PR 10
    watchdogs for free: a ``ChaosPreemption`` kill lands BETWEEN chunks, the
    producer is joined, and the consumer's snapshot/resume contract applies.

:func:`pump_polling`
    The drain-poll skeleton the online loops run: drive a DESTRUCTIVE
    ``step()`` (e.g. ``FeedbackLog.drain`` + update) until ``stop`` is set,
    sleeping ``interval`` when idle. Deliberately NOT a lookahead pump:
    draining is destructive, and pre-draining in a producer thread would
    break the preemption-before-drain invariant (a kill at the update
    boundary must lose no event) — so the shared layer offers the polling
    shape as a first-class primitive instead of forcing lookahead on it.

Chunk geometry (:func:`stream_chunk_rows` / :func:`stream_depth`) resolves
explicit arg > ``SYNAPSEML_TPU_STREAM_CHUNK_ROWS`` / ``_STREAM_DEPTH`` env >
tuned file (``docs/tuned_defaults.json``, TPU-gated) > a one-time
host→device bandwidth micro-probe recorded in the ``core/tuned.py``
measurement store, capped by the ``SYNAPSEML_TPU_STREAM_MEM_BUDGET`` byte
budget (the knob the out-of-core bench uses to simulate a 10x-undersized
device). See docs/out-of-core.md.
"""

from __future__ import annotations

import mmap as _mmap
import os
import queue
import threading
import time
from collections import deque
from typing import Callable, Iterable, Optional

import numpy as np

# Chunk-corruption hook for the chaos suite (testing/chaos.py installs it):
# called as hook(k, chunk) -> chunk on the PRODUCER side before placement, so
# an injected delay/truncation/kill exercises the exact path a slow or dying
# data source would. Same single-global-hook pattern as dl.trainer's
# _CHAOS_BATCH_HOOK.
_CHAOS_CHUNK_HOOK = None

# Disk-read corruption hook (testing/chaos.py installs it): called as
# hook(k, arr) -> arr on every chunk READ FROM DISK (DiskChunkSource and the
# StreamedDataset cache_dir readback) — a separate global from
# _CHAOS_CHUNK_HOOK so a disk fault does not double-fire through the pump's
# chunk hook. The hook may return a truncated array (torn read) or raise
# OSError(EIO) (dying disk); both surface loudly at the consumer.
_CHAOS_DISK_HOOK = None

_DONE = object()     # end-of-stream sentinel on the producer queue


class ChunkStreamError(RuntimeError):
    """The producer died mid-stream (source raised, or chaos killed it);
    re-raised on the consumer side at the next chunk boundary."""


class ChunkPump:
    """Bounded-depth host→device chunk pipeline over ``source``.

    ``source``: any iterable of host chunks. ``place``: chunk -> placed
    chunk (``jax.device_put`` / sharding; identity when None). ``depth``:
    chunks placed AHEAD of the one being consumed (double-buffering = 1+).
    ``phase``: when set, each boundary fires ``preemption_point(phase,
    step_base + k)`` and beats the installed elastic watchdog — the
    composition contract chaos tests rely on. ``step_base`` keeps boundary
    steps globally monotonic across the many pumps one training run opens
    (each level pass is a fresh pump), so a chaos kill targets a unique
    boundary.
    """

    def __init__(self, source: Iterable, place: Optional[Callable] = None,
                 depth: int = 2, threaded: bool = False,
                 phase: Optional[str] = None, step_base: int = 0,
                 name: str = "ingest"):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._source = iter(source)
        self._place = place if place is not None else (lambda c: c)
        self.depth = int(depth)
        self.threaded = bool(threaded)
        self.phase = phase
        self.step_base = int(step_base)
        self.name = name
        self.chunks_produced = 0     # pulled from source (producer side)
        self.chunks_consumed = 0     # yielded to the consumer
        self._q: queue.Queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._err: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    # -- producer side ----------------------------------------------------
    def _pull(self):
        """One produce step: next source chunk → chaos hook → place."""
        try:
            chunk = next(self._source)
        except StopIteration:
            return _DONE
        hook = _CHAOS_CHUNK_HOOK
        if hook is not None:
            chunk = hook(self.chunks_produced, chunk)
        # producer-private while the pump thread runs; the consumer only
        # reads it after _DONE arrives through _q, and the queue put/get
        # pair is the happens-before edge
        self.chunks_produced += 1  # lint-ok: thread-shared queue handoff
        return self._place(chunk)

    def _produce(self) -> None:
        try:
            while not self._stop.is_set():
                item = self._pull()
                if item is _DONE:
                    break
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.05)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # noqa: BLE001 — ferried to the consumer
            # written before the finally-block puts _DONE; the consumer
            # reads it only after get() returns _DONE, so the queue
            # handoff publishes the error
            self._err = e  # lint-ok: thread-shared queue handoff
        finally:
            # always deliver end-of-stream; close() drains concurrently so
            # this can never deadlock against a vanished consumer
            while not self._stop.is_set():
                try:
                    self._q.put(_DONE, timeout=0.05)
                    break
                except queue.Full:
                    continue

    def _start(self) -> None:
        if self._thread is None and not self._closed:
            self._thread = threading.Thread(
                target=self._produce, name=f"chunk-pump.{self.name}")
            self._thread.start()

    def _sync_pull(self):
        """``_pull`` under the threaded-mode error contract: source/place
        failures surface as :class:`ChunkStreamError` in BOTH modes, so
        consumers never care which side of the thread the producer ran on."""
        try:
            return self._pull()
        except BaseException as e:  # noqa: BLE001 — same contract as _produce
            raise ChunkStreamError(
                f"chunk producer {self.name!r} died at chunk "
                f"{self.chunks_produced}: {e!r}") from e

    # -- consumer side ----------------------------------------------------
    def _boundary(self) -> None:
        """Chunk boundary: preemption point + watchdog heartbeat."""
        step = self.step_base + self.chunks_consumed
        if self.phase is not None:
            from ..core.checkpoint import preemption_point

            preemption_point(self.phase, step)
        from ..parallel.elastic import current_watchdog

        wd = current_watchdog()
        if wd is not None:
            wd.beat(self.phase or self.name, step)

    def __iter__(self):
        try:
            if self.threaded:
                self._start()
                while True:
                    item = self._q.get()
                    if item is _DONE:
                        if self._err is not None:
                            raise ChunkStreamError(
                                f"chunk producer {self.name!r} died at chunk "
                                f"{self.chunks_produced}: {self._err!r}"
                            ) from self._err
                        return
                    self._boundary()
                    yield item
                    self.chunks_consumed += 1
            else:
                # synchronous lookahead (the seed dl _prefetch semantics):
                # refill BEFORE yielding so the next transfer is dispatched
                # while the consumer computes on the popped chunk
                q: deque = deque()
                while len(q) < self.depth:
                    item = self._sync_pull()
                    if item is _DONE:
                        break
                    q.append(item)
                while q:
                    out = q.popleft()
                    item = self._sync_pull()
                    if item is not _DONE:
                        q.append(item)
                    self._boundary()
                    yield out
                    self.chunks_consumed += 1
        finally:
            self.close()

    def close(self) -> None:
        """Stop the producer and JOIN it (idempotent; called from every
        ``__iter__`` exit path and from ``__exit__``). The queue is drained
        while joining so a blocked ``put`` can never wedge the join."""
        self._stop.set()
        t = self._thread
        while t is not None and t.is_alive():
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            t.join(0.05)
        self._thread = None
        self._closed = True

    def __enter__(self) -> "ChunkPump":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def pump_polling(step: Callable[[], bool], stop: threading.Event,
                 interval: float,
                 on_error: Optional[Callable[[Exception], None]] = None
                 ) -> None:
    """Drive a destructive drain ``step`` until ``stop`` is set.

    ``step() -> bool`` returns whether it did work; idle iterations wait
    ``interval`` on the stop event. ``Exception`` from a step is routed to
    ``on_error`` (count + keep draining — a poisoned batch must not kill the
    loop); ``BaseException`` (notably ``PreemptionError``) propagates and
    kills the loop like a real SIGTERM would. This is the online loops'
    ``_run`` body hoisted into the shared ingestion layer — the polling
    shape, NOT a lookahead pump, because the step's drain is destructive and
    must stay behind its own preemption point."""
    while not stop.is_set():
        try:
            worked = step()
        except Exception as e:  # noqa: BLE001 — loop must outlive bad input
            if on_error is not None:
                on_error(e)
            worked = False
        if not worked:
            stop.wait(interval)


# ---------------------------------------------------------------------------
# Chunk geometry: explicit > env > tuned file > measured micro-probe
# ---------------------------------------------------------------------------

_PROBE_BYTES = 4 << 20         # one device_put of 4 MiB prices the link
_TARGET_CHUNK_S = 8e-3         # chunk ≈ 8 ms of transfer: deep enough to
                               # amortize dispatch, shallow enough that
                               # depth×chunk stays a sliver of device memory
_MIN_CHUNK_ROWS = 1024
_MAX_CHUNK_ROWS = 1 << 20
_FALLBACK_CHUNK_ROWS = 65536


def _probe_h2d_bandwidth() -> float:
    """Measured host→device bytes/s (one-time; cached in the core/tuned.py
    measurement store under ``("h2d_bytes_per_s", platform)``)."""
    import jax
    import numpy as np

    buf = np.zeros(_PROBE_BYTES, np.uint8)
    jax.device_put(buf[:1024]).block_until_ready()      # warm the path
    t0 = time.perf_counter()
    jax.device_put(buf).block_until_ready()
    dt = max(time.perf_counter() - t0, 1e-9)
    return _PROBE_BYTES / dt


def mem_budget_bytes() -> Optional[int]:
    """The simulated device-memory cap for streaming chunk state
    (``SYNAPSEML_TPU_STREAM_MEM_BUDGET``, bytes), or None. The out-of-core
    bench sets this to dataset_bytes/10 to prove ≥10x-beyond-memory
    training on CPU hosts that have no real HBM wall."""
    v = os.environ.get("SYNAPSEML_TPU_STREAM_MEM_BUDGET")
    if not v:
        return None
    return max(int(v), 1)


_LAST_CHUNK_DECISION = None


def last_chunk_decision():
    """Provenance dict of the most recent model-resolved chunk geometry
    (``core.perfmodel.suggest_chunk_rows``), or None when the probe branch
    has not run (explicit/env/tuned bypass) or the model was unavailable."""
    return _LAST_CHUNK_DECISION


def _perfmodel_chunk_rows(row_bytes: int, depth: int, fallback_rows: int,
                          h2d_bps) -> int:
    global _LAST_CHUNK_DECISION
    try:
        from ..core import perfmodel

        rows, dec = perfmodel.suggest_chunk_rows(
            row_bytes, int(depth), int(fallback_rows), h2d_bps=h2d_bps)
        _LAST_CHUNK_DECISION = dec.provenance()
        return int(rows)
    except Exception:
        return int(fallback_rows)


def stream_chunk_rows(row_bytes: int, explicit: Optional[int] = None,
                      depth: int = 2,
                      read_bps: Optional[float] = None) -> int:
    """Rows per streamed chunk for rows of ``row_bytes`` each.

    Resolution: ``explicit`` arg > ``SYNAPSEML_TPU_STREAM_CHUNK_ROWS`` env >
    tuned file ``stream_chunk_rows`` (TPU-gated, docs/tuned_defaults.json) >
    bandwidth micro-probe (chunk ≈ ``_TARGET_CHUNK_S`` of measured link
    time). ``read_bps``, when given (disk-backed sources), is the measured
    disk read bandwidth: a chunk crosses disk→host then host→device
    serially, so the probe branch prices the HARMONIC combination of the two
    links rather than the h2d link alone. Whatever wins is then capped so
    ``(depth+1)`` in-flight chunks fit the
    ``SYNAPSEML_TPU_STREAM_MEM_BUDGET`` byte budget when one is set."""
    from ..core import tuned as _tuned

    global _LAST_CHUNK_DECISION
    _LAST_CHUNK_DECISION = None   # set again iff the probe branch runs
    row_bytes = max(int(row_bytes), 1)
    rows = explicit
    if rows is None:
        env = os.environ.get("SYNAPSEML_TPU_STREAM_CHUNK_ROWS")
        if env:
            rows = int(env)
    if rows is None:
        v = _tuned.tuned_engine_defaults().get("stream_chunk_rows")
        if v is not None:
            rows = int(v)
    if rows is None:
        plat = _tuned.initialized_platform()
        bw = None
        if plat is None:
            rows = _FALLBACK_CHUNK_ROWS
        else:
            bw = _tuned.measured_or(("h2d_bytes_per_s", plat),
                                    _probe_h2d_bandwidth)
            if read_bps:
                # disk feeds the link back-to-back per chunk: effective
                # bytes/s is the series combination of the two stages
                bw = 1.0 / (1.0 / bw + 1.0 / float(read_bps))
            rows = int(bw * _TARGET_CHUNK_S / row_bytes)
        # the [min, max] clamp disciplines only the PROBE estimate — an
        # explicit/env/tuned value is operator intent and wins as given
        rows = min(max(rows, _MIN_CHUNK_ROWS), _MAX_CHUNK_ROWS)
        # recorded io_chunk_rows rows (bench_oocore_gbdt) can displace the
        # probe formula; without a measured match the formula IS the model's
        # analytic optimum, so this is identity
        rows = _perfmodel_chunk_rows(row_bytes, depth, rows, bw)
    rows = max(int(rows), 1)
    budget = mem_budget_bytes()
    if budget is not None:
        cap = budget // (row_bytes * (int(depth) + 1))
        rows = max(min(rows, cap), 1)
    return rows


def stream_depth(explicit: Optional[int] = None) -> int:
    """In-flight chunk depth: explicit > ``SYNAPSEML_TPU_STREAM_DEPTH`` env >
    tuned file ``stream_depth`` > 2 (double buffering)."""
    from ..core import tuned as _tuned

    if explicit is not None:
        return max(int(explicit), 1)
    env = os.environ.get("SYNAPSEML_TPU_STREAM_DEPTH")
    if env:
        return max(int(env), 1)
    v = _tuned.tuned_engine_defaults().get("stream_depth")
    if v is not None:
        return max(int(v), 1)
    return 2


# ---------------------------------------------------------------------------
# Disk-backed chunk source: mmap'd .npy / raw-uint8 reader
# ---------------------------------------------------------------------------

def _disk_hook(k, arr):
    hook = _CHAOS_DISK_HOOK
    return arr if hook is None else hook(k, arr)


def _npy_header(f):
    """``(shape, dtype, data_offset)`` of an open ``.npy`` file (versions
    1.0/2.0, C-order only — the layouts ``np.save`` actually writes)."""
    version = np.lib.format.read_magic(f)
    if version == (1, 0):
        shape, fortran, dtype = np.lib.format.read_array_header_1_0(f)
    elif version == (2, 0):
        shape, fortran, dtype = np.lib.format.read_array_header_2_0(f)
    else:
        raise ValueError(f"unsupported .npy format version {version}")
    if fortran:
        raise ValueError(".npy file is Fortran-ordered; the disk chunk "
                         "source needs C-order rows")
    return shape, dtype, f.tell()


def _probe_disk_bandwidth(path: str) -> float:
    """Measured disk→host bytes/s for ``path``'s filesystem: one sequential
    read of up to ``_PROBE_BYTES``. An upper bound when the page cache is
    warm — acceptable, because a warm cache means the disk stage genuinely
    is that fast for this stream."""
    n = min(os.path.getsize(path), _PROBE_BYTES)
    t0 = time.perf_counter()
    with open(path, "rb") as f:
        f.read(max(int(n), 1))
    dt = max(time.perf_counter() - t0, 1e-9)
    return max(int(n), 1) / dt


def read_chunk_file(path: str, k: int = 0):
    """Read one whole cached ``.npy`` chunk file through the chaos disk hook
    — the training-time readback path for ``StreamedDataset(cache_dir=...)``
    spilled chunks. Returns a fresh host array (never a live mmap view)."""
    with open(path, "rb") as f:
        shape, dtype, off = _npy_header(f)
        mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
        flat = np.frombuffer(mm, dtype=dtype,
                             count=int(np.prod(shape)), offset=off)
        try:
            out = np.array(flat.reshape(shape))
        finally:
            # frombuffer holds an exported pointer into the map: drop it
            # before close() or it raises BufferError
            del flat
            mm.close()
    return _disk_hook(int(k), out)


class DiskChunkSource:
    """Memory-mapped on-disk chunk reader — host RAM stops being the ceiling.

    A callable usable directly as ``StreamedDataset(batches=...)``: each call
    opens ``path``, maps it read-only, and yields ``(X, y, w)`` row-chunk
    tuples (``y``/``w`` are ``None`` unless ``labels``/``weights`` arrays
    were given — labels are 1/F the stream and stay in RAM). Two layouts:

    * ``.npy`` (default): header parsed for shape/dtype; must be a C-order
      2-D ``(rows, features)`` array.
    * raw: a headerless binary of ``rows × num_features`` elements of
      ``dtype`` (default uint8) — pass ``num_features`` (and ``dtype`` for
      non-uint8), set ``raw=True``.

    Each yielded chunk is COPIED out of the map (the map is closed when the
    generator exits, so no view may escape), and routed through the chaos
    disk hook so the fault suite can inject torn reads / EIO exactly where a
    real disk would. ``read_bytes_per_s`` is a cached one-time sequential
    micro-probe of the backing filesystem; ``StreamedDataset.prepare`` folds
    it into the chunk-geometry pricing.
    """

    def __init__(self, path: str, rows_per_chunk: int = _FALLBACK_CHUNK_ROWS,
                 raw: bool = False, num_features: Optional[int] = None,
                 dtype=None, labels=None, weights=None):
        self.path = os.fspath(path)
        self.rows_per_chunk = max(int(rows_per_chunk), 1)
        self.raw = bool(raw)
        self.labels = labels
        self.weights = weights
        if self.raw:
            if num_features is None:
                raise ValueError("raw disk source needs num_features")
            self._dtype = np.dtype(dtype if dtype is not None else np.uint8)
            itemsize = self._dtype.itemsize * int(num_features)
            n = os.path.getsize(self.path) // itemsize
            self._shape = (int(n), int(num_features))
            self._offset = 0
        else:
            if num_features is not None or dtype is not None:
                raise ValueError("num_features/dtype are raw-layout knobs; "
                                 ".npy files carry their own header")
            with open(self.path, "rb") as f:
                shape, dt, off = _npy_header(f)
            if len(shape) != 2:
                raise ValueError(f".npy disk source must be 2-D (rows, "
                                 f"features), got shape {shape}")
            self._shape, self._dtype, self._offset = shape, dt, off
        self.n_rows, self.num_features = int(self._shape[0]), int(self._shape[1])
        self._read_bps: Optional[float] = None

    @property
    def read_bytes_per_s(self) -> float:
        if self._read_bps is None:
            from ..core import tuned as _tuned

            plat = _tuned.initialized_platform()
            if plat is not None:
                self._read_bps = float(_tuned.measured_or(
                    ("disk_read_bytes_per_s", plat),
                    lambda: _probe_disk_bandwidth(self.path)))
            else:
                self._read_bps = _probe_disk_bandwidth(self.path)
        return self._read_bps

    def __call__(self):
        n, F, R = self.n_rows, self.num_features, self.rows_per_chunk
        f = open(self.path, "rb")
        try:
            mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
            flat = np.frombuffer(mm, dtype=self._dtype,
                                 count=n * F, offset=self._offset)
            arr = flat.reshape(n, F)
            try:
                for k, a in enumerate(range(0, n, R)):
                    X = _disk_hook(k, np.array(arr[a:a + R]))
                    c = int(X.shape[0])       # hook may tear the read short
                    sl = slice(a, a + c)
                    y = None if self.labels is None else self.labels[sl]
                    w = None if self.weights is None else self.weights[sl]
                    yield (X, y, w)
            finally:
                # frombuffer holds an exported pointer into the map: drop
                # every view before close() or it raises BufferError
                del flat, arr
                mm.close()
        finally:
            f.close()
