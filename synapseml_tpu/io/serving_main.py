"""Serving gateway CLI: load a saved pipeline/model and serve it over HTTP.

``python -m synapseml_tpu.io.serving_main --model /path/to/saved_stage
[--host 0.0.0.0] [--port 8898] [--output-col prediction]``

The deployment-unit analog of the reference's Spark Serving query + helm
chart (tools/helm; HTTPSourceV2.scala WorkerServer): requests POST a JSON
object of column values, micro-batched into ONE jitted transform per batch,
and each request receives its row's output column back.
"""

from __future__ import annotations

import argparse
import signal
import sys

import numpy as np


def build_handler(stage, output_col: str):
    from ..core.table import Table

    def handler(df: Table) -> Table:
        n = df.num_rows
        cols: dict = {}
        for i, v in enumerate(df["value"]):
            if not isinstance(v, dict):
                raise ValueError("request body must be a JSON object of "
                                 "column values")
            for k, val in v.items():
                cols.setdefault(k, [None] * n)[i] = val
        batch = Table({k: np.asarray(v, dtype=object)
                       for k, v in cols.items()})
        out = stage.transform(batch)
        col = output_col if output_col in out.columns else out.columns[-1]
        return Table({"id": df["id"], "reply": out[col]})

    return handler


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model",
                    help="path of a saved PipelineStage (stage.save dir); "
                         "required unless running as --gateway-workers")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8898)
    ap.add_argument("--output-col", default="prediction")
    ap.add_argument("--max-batch-size", type=int, default=64)
    ap.add_argument("--max-batch-latency", type=float, default=0.005)
    ap.add_argument("--gateway-workers", default=None,
                    help="comma-separated worker URLs: run as a forwarding "
                         "gateway (io/distributed_serving.py) instead of a "
                         "model worker; --model is ignored")
    ap.add_argument("--lb-mode", default="least_loaded",
                    choices=["least_loaded", "round_robin"])
    args = ap.parse_args(argv)

    if args.gateway_workers:
        from .distributed_serving import ServingGateway

        server = ServingGateway(args.gateway_workers.split(","),
                                host=args.host, port=args.port,
                                mode=args.lb_mode)
        server.start()
        print(f"gateway → {len(server.links)} workers at {server.url}",
              flush=True)
    else:
        if not args.model:
            ap.error("--model is required (unless --gateway-workers)")
        from ..core.pipeline import PipelineStage
        from .serving import ServingServer

        stage = PipelineStage.load(args.model)
        server = ServingServer(build_handler(stage, args.output_col),
                               host=args.host, port=args.port,
                               max_batch_size=args.max_batch_size,
                               max_batch_latency=args.max_batch_latency)
        server.start()
        print(f"serving {type(stage).__name__} at {server.url}", flush=True)
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop:
            signal.pause()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
