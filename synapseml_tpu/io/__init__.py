"""IO — HTTP-on-DataFrame client layer, serving, binary/image datasources.

Reference: core io layer (SURVEY.md §1 L4): io/http/HTTPTransformer.scala:93-147,
SimpleHTTPTransformer.scala, HTTPSchema.scala, Parsers.scala, RESTHelpers.scala;
serving sources/sinks (HTTPSourceV2.scala:485-713 WorkerServer, HTTPSinkV2.scala,
ServingUDFs.scala); io/binary/BinaryFileFormat.scala and the patched image
datasource; io/powerbi/PowerBIWriter.scala. The reference builds these on Spark
streaming internals; here the client layer is an async pooled executor over
table columns and serving is an embedded threaded HTTP server feeding
micro-batches through a fitted pipeline.
"""

from .http import (CustomInputParser, CustomOutputParser, HTTPRequestData,
                   HTTPResponseData, HTTPTransformer, JSONInputParser,
                   JSONOutputParser, SimpleHTTPTransformer, StringOutputParser)
from .distributed_serving import (BroadcastError, CoordinatorDied,
                                  DistributedServingServer,
                                  FabricSupervisor, PromotionBroadcast,
                                  ServingGateway, WorkerAgent, federate)
from .serving import (ModelRegistry, ServingServer, SwapError,
                      request_to_table, respond_with)
from .binary import read_binary_files, read_image_dir
from .powerbi import PowerBIWriter

__all__ = [
    "HTTPRequestData", "HTTPResponseData", "HTTPTransformer",
    "SimpleHTTPTransformer", "JSONInputParser", "CustomInputParser",
    "JSONOutputParser", "StringOutputParser", "CustomOutputParser",
    "ServingServer", "ServingGateway", "DistributedServingServer",
    "WorkerAgent", "FabricSupervisor", "ModelRegistry", "SwapError",
    "PromotionBroadcast", "BroadcastError", "CoordinatorDied", "federate",
    "request_to_table", "respond_with",
    "read_binary_files", "read_image_dir", "PowerBIWriter",
]
