"""ValueIndexer / IndexToValue (reference: core/.../featurize/ValueIndexer.scala,
IndexToValue.scala — categorical value <-> index with metadata)."""

from __future__ import annotations

import numpy as np

from ..core.params import Param, HasInputCol, HasOutputCol
from ..core.pipeline import Estimator, Model, Transformer
from ..core.table import Table


class ValueIndexer(Estimator, HasInputCol, HasOutputCol):
    """Learn value→index mapping (sorted distinct values; index 0..K-1)."""

    def _fit(self, df: Table) -> "ValueIndexerModel":
        vals = np.unique(np.asarray(df[self.inputCol]))
        return ValueIndexerModel(inputCol=self.inputCol,
                                 outputCol=self.outputCol,
                                 levels=[v.item() if hasattr(v, "item") else v for v in vals])


class ValueIndexerModel(Model, HasInputCol, HasOutputCol):
    levels = Param("levels", "Ordered distinct values; index = position", list)
    unknownIndex = Param("unknownIndex", "Index for unseen values (-1 default)", int, -1)

    def _transform(self, df: Table) -> Table:
        lut = {v: i for i, v in enumerate(self.levels)}
        a = df[self.inputCol]
        out = np.fromiter((lut.get(v.item() if hasattr(v, "item") else v,
                                   self.unknownIndex) for v in a),
                          dtype=np.int64, count=len(a))
        return df.with_column(self.outputCol, out)


class IndexToValue(Transformer, HasInputCol, HasOutputCol):
    """Inverse mapping using a ValueIndexerModel's levels."""
    levels = Param("levels", "Ordered distinct values", list)

    def _transform(self, df: Table) -> Table:
        levels = self.levels
        idx = np.asarray(df[self.inputCol], np.int64)
        vals = np.array([levels[i] if 0 <= i < len(levels) else None for i in idx],
                        dtype=object)
        return df.with_column(self.outputCol, vals)
