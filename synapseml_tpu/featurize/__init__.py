"""Featurization (SURVEY §2.7 featurize/, 9 files in reference).

Auto-featurization (Featurize), missing-value cleaning, value indexing, count
selection, type conversion, and text featurization (TextFeaturizer, MultiNGram,
PageSplitter)."""

from .clean import CleanMissingData, CleanMissingDataModel
from .convert import DataConversion
from .featurize import Featurize, FeaturizeModel
from .indexer import IndexToValue, ValueIndexer, ValueIndexerModel
from .select import CountSelector, CountSelectorModel
from .text import MultiNGram, PageSplitter, TextFeaturizer, TextFeaturizerModel

__all__ = ["Featurize", "FeaturizeModel", "CleanMissingData", "CleanMissingDataModel",
           "ValueIndexer", "ValueIndexerModel", "IndexToValue", "CountSelector",
           "CountSelectorModel", "DataConversion", "TextFeaturizer",
           "TextFeaturizerModel", "MultiNGram", "PageSplitter"]
