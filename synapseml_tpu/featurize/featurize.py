"""Featurize: automatic featurization of mixed-type columns into one dense
feature matrix (reference: core/.../featurize/Featurize.scala:35+ — assembles
an imputation + indexing/one-hot + assembler pipeline; here one estimator that
learns per-column plans and emits a single 2-D float column)."""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..core.params import Param, HasInputCols, HasOutputCol
from ..core.pipeline import Estimator, Model
from ..core.table import Table
from ..vw.hashing import murmur3_32


class Featurize(Estimator, HasInputCols, HasOutputCol):
    oneHotEncodeCategoricals = Param("oneHotEncodeCategoricals",
                                     "One-hot (vs index) categorical columns", bool, True)
    numFeatures = Param("numFeatures", "Hash dimension for high-cardinality "
                        "string columns", int, 256)
    imputeMissing = Param("imputeMissing", "Mean-impute missing numerics", bool, True)

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "features")
        super().__init__(**kwargs)

    def _fit(self, df: Table) -> "FeaturizeModel":
        cols = list(self.inputCols or [c for c in df.columns if c != self.outputCol])
        plans: List[Dict] = []
        for c in cols:
            a = df[c]
            if a.ndim == 2:
                plans.append({"col": c, "kind": "vector", "dim": int(a.shape[1])})
            elif np.issubdtype(a.dtype, np.number) or a.dtype == bool:
                vals = np.asarray(a, np.float64)
                finite = vals[np.isfinite(vals)]
                plans.append({"col": c, "kind": "numeric",
                              "fill": float(finite.mean()) if len(finite) else 0.0})
            else:
                levels = [str(v) for v in np.unique([str(x) for x in a])]
                if self.oneHotEncodeCategoricals and len(levels) <= self.numFeatures:
                    plans.append({"col": c, "kind": "onehot", "levels": levels})
                else:
                    plans.append({"col": c, "kind": "hash", "dim": int(self.numFeatures)})
        return FeaturizeModel(inputCols=cols, outputCol=self.outputCol, plans=plans,
                              imputeMissing=self.imputeMissing)


class FeaturizeModel(Model, HasInputCols, HasOutputCol):
    plans = Param("plans", "Per-column featurization plans", list)
    imputeMissing = Param("imputeMissing", "Mean-impute missing numerics", bool, True)

    def _transform(self, df: Table) -> Table:
        n = df.num_rows
        pieces = []
        for plan in self.plans:
            a = df[plan["col"]]
            kind = plan["kind"]
            if kind == "vector":
                pieces.append(np.asarray(a, np.float32))
            elif kind == "numeric":
                v = np.asarray(a, np.float64)
                if self.imputeMissing:
                    v = np.where(np.isfinite(v), v, plan["fill"])
                pieces.append(v.astype(np.float32)[:, None])
            elif kind == "onehot":
                lut = {v: i for i, v in enumerate(plan["levels"])}
                out = np.zeros((n, len(plan["levels"])), np.float32)
                for i in range(n):
                    j = lut.get(str(a[i]))
                    if j is not None:
                        out[i, j] = 1.0
                pieces.append(out)
            elif kind == "hash":
                d = plan["dim"]
                out = np.zeros((n, d), np.float32)
                for i in range(n):
                    out[i, murmur3_32(str(a[i]).encode("utf-8")) % d] = 1.0
                pieces.append(out)
        return df.with_column(self.outputCol, np.concatenate(pieces, axis=1))

    @property
    def feature_dim(self) -> int:
        total = 0
        for p in self.plans:
            total += {"vector": p.get("dim", 0), "numeric": 1,
                      "onehot": len(p.get("levels", [])), "hash": p.get("dim", 0)}[p["kind"]]
        return total
