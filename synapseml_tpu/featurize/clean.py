"""CleanMissingData: impute NaNs per column (reference:
core/.../featurize/CleanMissingData.scala — Mean/Median/Custom modes)."""

from __future__ import annotations


import numpy as np

from ..core.params import Param, HasInputCols, HasOutputCols
from ..core.pipeline import Estimator, Model
from ..core.table import Table


class CleanMissingData(Estimator, HasInputCols, HasOutputCols):
    cleaningMode = Param("cleaningMode", "Mean | Median | Custom", str, "Mean")
    customValue = Param("customValue", "Fill value for Custom mode", float)

    def _fit(self, df: Table) -> "CleanMissingDataModel":
        cols = list(self.inputCols or df.columns)
        fills = []
        for c in cols:
            a = np.asarray(df[c], np.float64)
            finite = a[np.isfinite(a)]
            if self.cleaningMode == "Custom":
                fills.append(float(self.customValue))
            elif self.cleaningMode == "Median":
                fills.append(float(np.median(finite)) if len(finite) else 0.0)
            else:
                fills.append(float(finite.mean()) if len(finite) else 0.0)
        return CleanMissingDataModel(
            inputCols=cols, outputCols=list(self.outputCols or cols), fillValues=fills)


class CleanMissingDataModel(Model, HasInputCols, HasOutputCols):
    fillValues = Param("fillValues", "Per-column fill values", list)

    def _transform(self, df: Table) -> Table:
        out = df.copy()
        for c, o, v in zip(self.inputCols, self.outputCols, self.fillValues):
            a = np.asarray(df[c], np.float64)
            out[o] = np.where(np.isfinite(a), a, v).astype(np.float32)
        return out
