"""Text featurization.

Reference: core/.../featurize/text/{TextFeaturizer,MultiNGram,PageSplitter}.scala.
TextFeaturizer = tokenize → (stopwords) → n-grams → hashing TF → IDF, one
estimator. The hashed term-frequency matrix is a dense (N, numFeatures) float
array — ready to feed TPU estimators directly."""

from __future__ import annotations

import re
from typing import List

import numpy as np

from ..core.params import Param, HasInputCol, HasOutputCol
from ..core.pipeline import Estimator, Model, Transformer
from ..core.table import Table
from ..vw.hashing import murmur3_32

_DEFAULT_STOPWORDS = frozenset(
    "a an and are as at be by for from has he in is it its of on that the to was "
    "were will with".split())


def _tokenize(text: str, pattern: str, to_lower: bool, min_len: int) -> List[str]:
    if to_lower:
        text = text.lower()
    toks = re.split(pattern, text)
    return [t for t in toks if len(t) >= min_len]


def _ngrams(tokens: List[str], n: int) -> List[str]:
    if n <= 1:
        return list(tokens)
    return [" ".join(tokens[i:i + n]) for i in range(len(tokens) - n + 1)]


def _hash_tf(terms: List[str], num_features: int, binary: bool) -> np.ndarray:
    v = np.zeros(num_features, np.float32)
    if len(terms) >= 64:
        from ..native import murmur3_32_batch

        idx = murmur3_32_batch(terms, 0, vw_numeric_names=False, mask=0)
        if idx is not None:
            idx = idx % num_features
            if binary:
                v[np.unique(idx)] = 1.0
            else:
                np.add.at(v, idx, 1.0)
            return v
    for t in terms:
        j = murmur3_32(t.encode("utf-8")) % num_features
        v[j] = 1.0 if binary else v[j] + 1.0
    return v


class TextFeaturizer(Estimator, HasInputCol, HasOutputCol):
    """One-stop text → feature-vector estimator (TextFeaturizer.scala)."""
    useTokenizer = Param("useTokenizer", "Tokenize the input", bool, True)
    tokenizerPattern = Param("tokenizerPattern", "Split regex", str, r"\W+")
    toLowercase = Param("toLowercase", "Lowercase before tokenizing", bool, True)
    minTokenLength = Param("minTokenLength", "Minimum token length", int, 1)
    useStopWordsRemover = Param("useStopWordsRemover", "Remove stop words", bool, False)
    useNGram = Param("useNGram", "Produce n-grams", bool, False)
    nGramLength = Param("nGramLength", "n-gram length", int, 2)
    numFeatures = Param("numFeatures", "Hashing-TF dimension (dense TPU-resident matrix; default 4096 — the reference uses 2^18 sparse)", int, 1 << 12)
    binary = Param("binary", "Binary term presence instead of counts", bool, False)
    useIDF = Param("useIDF", "Apply inverse document frequency weighting", bool, True)
    minDocFreq = Param("minDocFreq", "Minimum document frequency for IDF", int, 1)

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "features")
        super().__init__(**kwargs)

    def _terms(self, text: str) -> List[str]:
        toks = (_tokenize(str(text), self.tokenizerPattern, self.toLowercase,
                          self.minTokenLength)
                if self.useTokenizer else str(text).split())
        if self.useStopWordsRemover:
            toks = [t for t in toks if t not in _DEFAULT_STOPWORDS]
        return _ngrams(toks, self.nGramLength) if self.useNGram else toks

    def _fit(self, df: Table) -> "TextFeaturizerModel":
        n = df.num_rows
        d = self.numFeatures
        idf = np.zeros(d, np.float64)
        for i in range(n):
            tf = _hash_tf(self._terms(df[self.inputCol][i]), d, binary=True)
            idf += tf
        df_counts = idf
        idf = np.where(df_counts >= self.minDocFreq,
                       np.log((n + 1.0) / (df_counts + 1.0)), 0.0)
        m = TextFeaturizerModel(
            inputCol=self.inputCol, outputCol=self.outputCol,
            useTokenizer=self.useTokenizer, tokenizerPattern=self.tokenizerPattern,
            toLowercase=self.toLowercase, minTokenLength=self.minTokenLength,
            useStopWordsRemover=self.useStopWordsRemover, useNGram=self.useNGram,
            nGramLength=self.nGramLength, numFeatures=d, binary=self.binary,
            useIDF=self.useIDF)
        m.idf_ = idf.astype(np.float32)
        return m


class TextFeaturizerModel(Model, HasInputCol, HasOutputCol):
    useTokenizer = Param("useTokenizer", "Tokenize the input", bool, True)
    tokenizerPattern = Param("tokenizerPattern", "Split regex", str, r"\W+")
    toLowercase = Param("toLowercase", "Lowercase before tokenizing", bool, True)
    minTokenLength = Param("minTokenLength", "Minimum token length", int, 1)
    useStopWordsRemover = Param("useStopWordsRemover", "Remove stop words", bool, False)
    useNGram = Param("useNGram", "Produce n-grams", bool, False)
    nGramLength = Param("nGramLength", "n-gram length", int, 2)
    numFeatures = Param("numFeatures", "Hashing-TF dimension (dense TPU-resident matrix; default 4096 — the reference uses 2^18 sparse)", int, 1 << 12)
    binary = Param("binary", "Binary term presence", bool, False)
    useIDF = Param("useIDF", "Apply IDF weighting", bool, True)

    idf_: np.ndarray = None

    _terms = TextFeaturizer._terms

    def _can_use_native_tf(self, docs) -> bool:
        """The C tokenizer (split on non-alnum bytes, ascii lowercase) matches
        the default Python pipeline only for plain-ASCII documents with the
        stock settings — guard exactly to keep feature vectors identical."""
        return (self.useTokenizer and self.tokenizerPattern == r"\W+"
                and self.toLowercase and not self.useStopWordsRemover
                and not self.useNGram
                and self.numFeatures & (self.numFeatures - 1) == 0
                and all(isinstance(t, str) and t.isascii() and "_" not in t
                        for t in docs))

    def _transform(self, df: Table) -> Table:
        n = df.num_rows
        docs = [str(t) for t in df[self.inputCol]]
        X = None
        if n >= 64 and self._can_use_native_tf(docs):
            from ..native import hash_tf as native_tf

            X = native_tf(docs, self.numFeatures,
                          min_len=self.minTokenLength, binary=self.binary)
        if X is None:
            X = np.zeros((n, self.numFeatures), np.float32)
            for i in range(n):
                X[i] = _hash_tf(self._terms(docs[i]), self.numFeatures,
                                self.binary)
        if self.useIDF and self.idf_ is not None:
            X = X * self.idf_[None, :]
        return df.with_column(self.outputCol, X)

    def _save_extra(self, path: str) -> None:
        import os
        if self.idf_ is not None:
            np.save(os.path.join(path, "idf.npy"), self.idf_)

    def _load_extra(self, path: str) -> None:
        import os
        f = os.path.join(path, "idf.npy")
        if os.path.exists(f):
            self.idf_ = np.load(f)


class MultiNGram(Transformer, HasInputCol, HasOutputCol):
    """Concatenate n-grams of several lengths (MultiNGram.scala)."""
    lengths = Param("lengths", "N-gram lengths to produce", list, [1, 2, 3])

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "ngrams")
        super().__init__(**kwargs)

    def _transform(self, df: Table) -> Table:
        out = np.empty(df.num_rows, object)
        for i in range(df.num_rows):
            toks = list(df[self.inputCol][i])
            grams: List[str] = []
            for n in (self.lengths or [1]):
                grams.extend(_ngrams(toks, int(n)))
            out[i] = grams
        return df.with_column(self.outputCol, out)


class PageSplitter(Transformer, HasInputCol, HasOutputCol):
    """Split text into pages within [minimum, maximum] character bounds on
    whitespace boundaries where possible (PageSplitter.scala)."""
    maximumPageLength = Param("maximumPageLength", "Max chars per page", int, 5000)
    minimumPageLength = Param("minimumPageLength", "Preferred min chars per page", int, 4500)
    boundaryRegex = Param("boundaryRegex", "Preferred split boundary", str, r"\s")

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "pages")
        super().__init__(**kwargs)

    def _transform(self, df: Table) -> Table:
        out = np.empty(df.num_rows, object)
        for i in range(df.num_rows):
            text = str(df[self.inputCol][i])
            pages = []
            start = 0
            while start < len(text):
                end = min(start + self.maximumPageLength, len(text))
                if end < len(text):
                    # prefer a boundary in [min, max)
                    window = text[start + self.minimumPageLength:end]
                    m = list(re.finditer(self.boundaryRegex, window))
                    if m:
                        end = start + self.minimumPageLength + m[-1].end()
                pages.append(text[start:end])
                start = end
            out[i] = pages
        return df.with_column(self.outputCol, out)
