"""DataConversion: cast columns between types (reference:
core/.../featurize/DataConversion.scala — convertTo boolean/byte/short/integer/
long/float/double/string/toCategorical/clearCategorical/date)."""

from __future__ import annotations

import numpy as np

from ..core.params import Param
from ..core.pipeline import Transformer
from ..core.table import Table

_CASTS = {
    "boolean": np.bool_, "byte": np.int8, "short": np.int16, "integer": np.int32,
    "long": np.int64, "float": np.float32, "double": np.float64,
}


class DataConversion(Transformer):
    cols = Param("cols", "Columns to convert", list)
    convertTo = Param("convertTo", "Target type: boolean|byte|short|integer|long|"
                      "float|double|string|date", str, "double")
    dateTimeFormat = Param("dateTimeFormat", "Format for date conversion", str,
                           "yyyy-MM-dd HH:mm:ss")

    def _transform(self, df: Table) -> Table:
        out = df.copy()
        for c in (self.cols or []):
            a = df[c]
            t = self.convertTo
            if t == "string":
                out[c] = np.array([str(v) for v in a], dtype=object)
            elif t == "date":
                fmt = self.dateTimeFormat
                if (a.dtype == object or a.dtype.kind in "US") and fmt:
                    from datetime import datetime
                    # translate the reference's Java-style pattern to strptime
                    py_fmt = (fmt.replace("yyyy", "%Y").replace("MM", "%m")
                              .replace("dd", "%d").replace("HH", "%H")
                              .replace("mm", "%M").replace("ss", "%S"))

                    def parse_one(v):
                        try:
                            return np.datetime64(
                                datetime.strptime(str(v), py_fmt), "s")
                        except ValueError:
                            # ISO-8601 strings parse regardless of the format
                            return np.datetime64(str(v), "s")

                    out[c] = np.array([parse_one(v) for v in a],
                                      dtype="datetime64[s]")
                else:
                    out[c] = np.asarray(a, dtype="datetime64[s]")
            elif t in _CASTS:
                out[c] = np.asarray(a, dtype=object if a.dtype == object else a.dtype
                                    ).astype(_CASTS[t])
            else:
                raise ValueError(f"unknown convertTo {t!r}; options: "
                                 f"{sorted(_CASTS) + ['string', 'date']}")
        return out
