"""DataConversion: cast columns between types (reference:
core/.../featurize/DataConversion.scala — convertTo boolean/byte/short/integer/
long/float/double/string/toCategorical/clearCategorical/date)."""

from __future__ import annotations

import numpy as np

from ..core.params import Param
from ..core.pipeline import Transformer
from ..core.table import Table

_CASTS = {
    "boolean": np.bool_, "byte": np.int8, "short": np.int16, "integer": np.int32,
    "long": np.int64, "float": np.float32, "double": np.float64,
}

# Java SimpleDateFormat letter runs → strptime directives. Longest runs first;
# single-letter tokens (M/d/H/m/s) map to the same non-padded-tolerant
# directives, matching SimpleDateFormat's lenient parse of e.g. "M/d/yyyy".
_JAVA_TOKENS = [
    ("yyyy", "%Y"), ("yyy", "%Y"), ("yy", "%y"), ("y", "%Y"),
    ("MMMM", "%B"), ("MMM", "%b"), ("MM", "%m"), ("M", "%m"),
    ("dd", "%d"), ("d", "%d"), ("HH", "%H"), ("H", "%H"),
    ("hh", "%I"), ("h", "%I"), ("mm", "%M"), ("m", "%M"),
    ("ss", "%S"), ("s", "%S"), ("SSS", "%f"), ("SS", "%f"), ("S", "%f"),
    ("a", "%p"),
    ("EEEE", "%A"), ("EEE", "%a"), ("zzz", "%Z"), ("z", "%Z"),
    ("XXX", "%z"), ("XX", "%z"), ("X", "%z"), ("Z", "%z"),
]


def _java_to_strptime(fmt: str) -> str:
    """Translate a Java SimpleDateFormat pattern (incl. single-letter tokens
    and 'quoted literals') to a strptime format string."""
    out, i = [], 0
    while i < len(fmt):
        c = fmt[i]
        if c == "'":
            # quoted literal: '' is a literal quote (inside or outside a
            # quoted run), 'text' is verbatim
            if i + 1 < len(fmt) and fmt[i + 1] == "'":
                out.append("'")
                i += 2
                continue
            i += 1
            closed = False
            while i < len(fmt):
                if fmt[i] == "'":
                    if i + 1 < len(fmt) and fmt[i + 1] == "'":
                        out.append("'")
                        i += 2
                        continue
                    i += 1
                    closed = True
                    break
                out.append("%%" if fmt[i] == "%" else fmt[i])
                i += 1
            if not closed:
                raise ValueError(f"unterminated quote in dateTimeFormat {fmt!r}")
            continue
        for tok, rep in _JAVA_TOKENS:
            if fmt.startswith(tok, i):
                out.append(rep)
                i += len(tok)
                break
        else:
            if c.isalpha():
                raise ValueError(
                    f"unsupported pattern letter {c!r} in dateTimeFormat "
                    f"{fmt!r}")
            out.append("%%" if c == "%" else c)
            i += 1
    return "".join(out)


class DataConversion(Transformer):
    cols = Param("cols", "Columns to convert", list)
    convertTo = Param("convertTo", "Target type: boolean|byte|short|integer|long|"
                      "float|double|string|date", str, "double")
    dateTimeFormat = Param("dateTimeFormat", "Format for date conversion", str,
                           "yyyy-MM-dd HH:mm:ss")

    def _transform(self, df: Table) -> Table:
        out = df.copy()
        for c in (self.cols or []):
            a = df[c]
            t = self.convertTo
            if t == "string":
                out[c] = np.array([str(v) for v in a], dtype=object)
            elif t == "date":
                fmt = self.dateTimeFormat
                if (a.dtype == object or a.dtype.kind in "US") and fmt:
                    from datetime import datetime
                    try:
                        py_fmt = _java_to_strptime(fmt)
                    except ValueError:
                        # untranslatable pattern: ISO-8601 per-value fallback
                        py_fmt = None

                    def parse_one(v):
                        if py_fmt is not None:
                            try:
                                return np.datetime64(
                                    datetime.strptime(str(v), py_fmt), "s")
                            except ValueError:
                                pass
                        try:
                            # ISO-8601 strings parse regardless of the format
                            return np.datetime64(str(v), "s")
                        except ValueError:
                            raise ValueError(
                                f"cannot parse {v!r} with dateTimeFormat "
                                f"{fmt!r} and it is not ISO-8601") from None

                    out[c] = np.array([parse_one(v) for v in a],
                                      dtype="datetime64[s]")
                else:
                    out[c] = np.asarray(a, dtype="datetime64[s]")
            elif t in _CASTS:
                out[c] = np.asarray(a, dtype=object if a.dtype == object else a.dtype
                                    ).astype(_CASTS[t])
            else:
                raise ValueError(f"unknown convertTo {t!r}; options: "
                                 f"{sorted(_CASTS) + ['string', 'date']}")
        return out
