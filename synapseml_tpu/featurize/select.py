"""CountSelector: drop all-zero feature slots (reference:
core/.../featurize/CountSelector.scala — CountBasedFeatureSelector)."""

from __future__ import annotations

import numpy as np

from ..core.params import Param, HasInputCol, HasOutputCol
from ..core.pipeline import Estimator, Model
from ..core.table import Table


class CountSelector(Estimator, HasInputCol, HasOutputCol):
    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "features")
        kwargs.setdefault("outputCol", "features")
        super().__init__(**kwargs)

    def _fit(self, df: Table) -> "CountSelectorModel":
        X = np.asarray(df[self.inputCol], np.float64)
        keep = np.nonzero((X != 0).any(axis=0))[0]
        return CountSelectorModel(inputCol=self.inputCol, outputCol=self.outputCol,
                                  indices=[int(i) for i in keep])


class CountSelectorModel(Model, HasInputCol, HasOutputCol):
    indices = Param("indices", "Kept feature-slot indices", list)

    def _transform(self, df: Table) -> Table:
        X = np.asarray(df[self.inputCol], np.float32)
        return df.with_column(self.outputCol, X[:, np.asarray(self.indices, np.int64)])
