"""Native host-helper library: build, load, and ctypes bindings.

The NativeLoader analog (reference: core/.../core/env/NativeLoader.java
extracts .so files from the jar and System.load()s them per executor;
lightgbm/.../LightGBMUtils.scala:31-34). Here: the .so is compiled from
src/synapseml_native.cpp on first use when a compiler is present (wheel builds
ship it prebuilt), loaded via ctypes, and every binding has a pure-Python
fallback — ``available()`` says which path is active.

Bindings:
  murmur3_32_batch(names, seed(s), vw_numeric_names, mask) -> uint32[n]
  hash_tf(docs, num_features, seed, min_len, binary) -> float32[n, dim]
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence, Union

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libsynapseml_native.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    src = os.path.join(_DIR, "src", "synapseml_native.cpp")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-o", _SO, src],
            check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.sml_murmur3_32.restype = ctypes.c_uint32
    lib.sml_murmur3_32.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                   ctypes.c_uint32]
    lib.sml_hash_batch.restype = None
    lib.sml_hash_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint32,
        ctypes.c_int, ctypes.c_uint32, ctypes.c_void_p]
    lib.sml_hash_batch_seeded.restype = None
    lib.sml_hash_batch_seeded.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_uint32, ctypes.c_void_p]
    lib.sml_hash_tf.restype = None
    lib.sml_hash_tf.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint32,
        ctypes.c_uint32, ctypes.c_int64, ctypes.c_int, ctypes.c_void_p]
    if hasattr(lib, "csv_dims"):
        lib.csv_dims.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                 ctypes.POINTER(ctypes.c_int64),
                                 ctypes.POINTER(ctypes.c_int64)]
        lib.csv_dims.restype = ctypes.c_int
        lib.csv_read_f32.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                     ctypes.c_int64, ctypes.c_int64,
                                     ctypes.POINTER(ctypes.c_float)]
        lib.csv_read_f32.restype = ctypes.c_int64
    return lib


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        if not hasattr(lib, "csv_dims") and _build():
            # stale .so predating the CSV reader: rebuilt above; reload
            try:
                lib = ctypes.CDLL(_SO)
            except OSError:
                pass  # keep the old lib — CSV falls back to numpy
        _lib = _bind(lib)
        return _lib


def available() -> bool:
    return _load() is not None


def _pack(strings: Sequence[str]):
    """Concatenate utf-8 names + int64 offsets (n+1)."""
    encoded = [s.encode("utf-8") for s in strings]
    offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in encoded], out=offsets[1:])
    buf = b"".join(encoded)
    return np.frombuffer(buf, dtype=np.uint8), offsets


def murmur3_32(data: bytes, seed: int = 0) -> int:
    lib = _load()
    if lib is None:
        from ..vw.hashing import murmur3_32 as py_hash

        return py_hash(data, seed)
    return int(lib.sml_murmur3_32(data, len(data), seed & 0xFFFFFFFF))


def murmur3_32_batch(names: Sequence[str],
                     seed: Union[int, np.ndarray] = 0,
                     vw_numeric_names: bool = True,
                     mask: int = 0) -> Optional[np.ndarray]:
    """Hash a batch of names; ``seed`` may be a scalar or per-name uint32
    array. Returns None when the native library is unavailable (callers keep
    their Python path)."""
    lib = _load()
    if lib is None:
        return None
    buf, offsets = _pack(names)
    n = len(names)
    out = np.empty(n, dtype=np.uint32)
    buf_p = buf.ctypes.data_as(ctypes.c_void_p) if buf.size else None
    if isinstance(seed, (int, np.integer)):
        lib.sml_hash_batch(buf_p, offsets.ctypes.data_as(ctypes.c_void_p),
                           n, int(seed) & 0xFFFFFFFF,
                           int(vw_numeric_names), mask & 0xFFFFFFFF,
                           out.ctypes.data_as(ctypes.c_void_p))
    else:
        seeds = np.ascontiguousarray(seed, dtype=np.uint32)
        lib.sml_hash_batch_seeded(
            buf_p, offsets.ctypes.data_as(ctypes.c_void_p), n,
            seeds.ctypes.data_as(ctypes.c_void_p), int(vw_numeric_names),
            mask & 0xFFFFFFFF, out.ctypes.data_as(ctypes.c_void_p))
    return out


def hash_tf(docs: Sequence[str], num_features: int, seed: int = 0,
            min_len: int = 1, binary: bool = False) -> Optional[np.ndarray]:
    """Tokenize (non-alnum split, ascii lowercase) + hashing-TF each document
    into a [n, num_features] dense matrix; num_features must be a power of 2.
    Returns None when unavailable."""
    lib = _load()
    if lib is None or num_features & (num_features - 1):
        return None
    buf, offsets = _pack(docs)
    out = np.zeros((len(docs), num_features), dtype=np.float32)
    buf_p = buf.ctypes.data_as(ctypes.c_void_p) if buf.size else None
    lib.sml_hash_tf(buf_p, offsets.ctypes.data_as(ctypes.c_void_p),
                    len(docs), seed & 0xFFFFFFFF, (num_features - 1),
                    min_len, int(binary),
                    out.ctypes.data_as(ctypes.c_void_p))
    return out


def read_numeric_csv(path: str, has_header: bool = True):
    """Dense float32 matrix from a numeric CSV via the C++ reader (empty /
    non-numeric fields -> NaN, LightGBM's missing convention); None when the
    native library is unavailable (callers fall back to numpy). The native
    data-plane analog of the reference's chunked dataset aggregation
    (dataset/DatasetAggregator.scala:117-589)."""
    lib = _load()
    if lib is None or not hasattr(lib, "csv_dims"):
        return None     # no native lib, or a stale .so without the symbols
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.csv_dims(path.encode(), int(has_header),
                      ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0 or rows.value <= 0 or cols.value <= 0:
        return None
    out = np.empty((rows.value, cols.value), np.float32)
    got = lib.csv_read_f32(path.encode(), int(has_header), rows.value,
                           cols.value,
                           out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    if got < 0:
        return None
    return out[:got]
