// synapseml_tpu native host helpers.
//
// The reference ships its hot host-side primitives as C++ (LightGBM/VW/OpenCV
// via JNI; .so bootstrap in core/.../core/env/NativeLoader.java). The TPU
// rebuild keeps device compute in XLA, but the host-side feature-hashing path
// (VW-compatible murmur3 over millions of strings — vw/.../
// VowpalWabbitMurmurWithPrefix.scala is the reference's JVM copy of it) is
// pure string churn, so it lives here. Exposed as a plain C ABI for ctypes.
//
// Build: `make` in synapseml_tpu/native (g++ -O3 -shared -fPIC); loaded by
// synapseml_tpu/native/__init__.py with a transparent Python fallback.

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <cstdio>
#include <cmath>

namespace {

constexpr uint32_t C1 = 0xCC9E2D51u;
constexpr uint32_t C2 = 0x1B873593u;

inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85EBCA6Bu;
  h ^= h >> 13;
  h *= 0xC2B2AE35u;
  h ^= h >> 16;
  return h;
}

uint32_t murmur3_32(const uint8_t* data, size_t len, uint32_t seed) {
  uint32_t h = seed;
  const size_t nblocks = len / 4;
  for (size_t i = 0; i < nblocks; ++i) {
    uint32_t k;
    std::memcpy(&k, data + i * 4, 4);  // little-endian hosts only (x86/ARM)
    k *= C1;
    k = rotl32(k, 15);
    k *= C2;
    h ^= k;
    h = rotl32(h, 13);
    h = h * 5 + 0xE6546B64u;
  }
  const uint8_t* tail = data + nblocks * 4;
  uint32_t k = 0;
  switch (len & 3) {
    case 3: k ^= static_cast<uint32_t>(tail[2]) << 16; [[fallthrough]];
    case 2: k ^= static_cast<uint32_t>(tail[1]) << 8;  [[fallthrough]];
    case 1:
      k ^= tail[0];
      k *= C1;
      k = rotl32(k, 15);
      k *= C2;
      h ^= k;
  }
  h ^= static_cast<uint32_t>(len);
  return fmix32(h);
}

// VW semantics: names that parse as (optionally negative) integers index
// directly as int(name) + seed instead of being hashed.
bool parse_int_name(const uint8_t* s, size_t len, int64_t* out) {
  if (len == 0) return false;
  size_t i = 0;
  bool neg = false;
  if (s[0] == '-') {
    if (len == 1) return false;
    neg = true;
    i = 1;
  }
  int64_t v = 0;
  for (; i < len; ++i) {
    if (s[i] < '0' || s[i] > '9') return false;
    v = v * 10 + (s[i] - '0');
    // cap mirrored in python _int_name — keep the two in lockstep
    if (v > (int64_t{1} << 40)) return false;
  }
  *out = neg ? -v : v;
  return true;
}

}  // namespace

extern "C" {

// Single-string hash (murmur3 x86_32).
uint32_t sml_murmur3_32(const uint8_t* data, uint64_t len, uint32_t seed) {
  return murmur3_32(data, static_cast<size_t>(len), seed);
}

// Batch feature hashing over a packed string buffer.
//   buf:     concatenated utf-8 bytes of all names
//   offsets: n+1 int64 offsets into buf (name i = buf[offsets[i]:offsets[i+1]])
//   vw_numeric_names: when nonzero, integer-looking names index directly
//                     (int(name) + seed) — VW's default string-hash behavior
//   mask:    applied as index & mask when nonzero
// Writes n uint32 hashes to out.
void sml_hash_batch(const uint8_t* buf, const int64_t* offsets, int64_t n,
                    uint32_t seed, int vw_numeric_names, uint32_t mask,
                    uint32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* s = buf + offsets[i];
    const size_t len = static_cast<size_t>(offsets[i + 1] - offsets[i]);
    uint32_t h;
    int64_t as_int;
    if (vw_numeric_names && parse_int_name(s, len, &as_int)) {
      h = static_cast<uint32_t>((as_int + static_cast<int64_t>(seed)));
    } else {
      h = murmur3_32(s, len, seed);
    }
    out[i] = mask ? (h & mask) : h;
  }
}

// Batch hashing with a per-string seed array (namespace seeds).
void sml_hash_batch_seeded(const uint8_t* buf, const int64_t* offsets,
                           int64_t n, const uint32_t* seeds,
                           int vw_numeric_names, uint32_t mask,
                           uint32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* s = buf + offsets[i];
    const size_t len = static_cast<size_t>(offsets[i + 1] - offsets[i]);
    uint32_t h;
    int64_t as_int;
    if (vw_numeric_names && parse_int_name(s, len, &as_int)) {
      h = static_cast<uint32_t>((as_int + static_cast<int64_t>(seeds[i])));
    } else {
      h = murmur3_32(s, len, seeds[i]);
    }
    out[i] = mask ? (h & mask) : h;
  }
}

// Tokenize-and-hash: split each document on non-alphanumeric bytes,
// lowercase ASCII, hash each token of length >= min_len into [0, mask],
// accumulating term counts into out[doc * (mask+1) + idx]. The TextFeaturizer
// hashing-TF hot path (featurize/text.py) without per-token Python objects.
void sml_hash_tf(const uint8_t* buf, const int64_t* doc_offsets, int64_t n_docs,
                 uint32_t seed, uint32_t mask, int64_t min_len, int binary,
                 float* out) {
  const int64_t dim = static_cast<int64_t>(mask) + 1;
  uint8_t token[4096];
  for (int64_t d = 0; d < n_docs; ++d) {
    const uint8_t* s = buf + doc_offsets[d];
    const int64_t len = doc_offsets[d + 1] - doc_offsets[d];
    float* row = out + d * dim;
    int64_t tlen = 0;
    for (int64_t i = 0; i <= len; ++i) {
      uint8_t c = (i < len) ? s[i] : 0;
      bool alnum = (c >= '0' && c <= '9') || (c >= 'a' && c <= 'z') ||
                   (c >= 'A' && c <= 'Z') || c >= 0x80;
      if (alnum) {
        if (c >= 'A' && c <= 'Z') c += 32;  // ascii lowercase
        if (tlen < static_cast<int64_t>(sizeof(token))) token[tlen++] = c;
      } else if (tlen > 0) {
        if (tlen >= min_len) {
          uint32_t idx = murmur3_32(token, static_cast<size_t>(tlen), seed)
                         & mask;
          if (binary) {
            row[idx] = 1.0f;
          } else {
            row[idx] += 1.0f;
          }
        }
        tlen = 0;
      }
    }
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Fast numeric-CSV reader — the data-plane native path (the reference's
// dataset marshaling layer, dataset/DatasetAggregator.scala:117-589, is C++
// chunked-array aggregation behind SWIG; here the hot ingest loop is native
// and the Python Table wraps the filled float32 buffer zero-copy).
// Parses comma/tab-separated floats with optional header; empty fields and
// unparseable tokens become NaN (LightGBM's missing convention).
extern "C" {

// First pass: count rows (excluding header) and columns. Returns 0 on
// success, nonzero on IO error.
int csv_dims(const char* path, int has_header, int64_t* out_rows,
             int64_t* out_cols) {
  FILE* f = fopen(path, "rb");
  if (!f) return 1;
  int64_t rows = 0, cols = 0;
  int64_t line_cols = 1;
  int c, prev = '\n';
  int first_line = 1;
  while ((c = fgetc(f)) != EOF) {
    if (c == ',' || c == '\t') {
      if (first_line) line_cols++;
    } else if (c == '\n') {
      if (prev != '\n') {  // skip blank lines
        if (first_line) { cols = line_cols; first_line = 0; }
        rows++;
      }
      line_cols = 1;
    }
    prev = c;
  }
  if (prev != '\n' && prev != EOF) rows++;  // trailing line without newline
  if (first_line && rows > 0) cols = line_cols;
  fclose(f);
  if (has_header && rows > 0) rows--;
  *out_rows = rows;
  *out_cols = cols;
  return 0;
}

// Second pass: fill the caller-allocated row-major float32 buffer.
// Returns number of rows actually parsed (or -1 on IO error).
int64_t csv_read_f32(const char* path, int has_header, int64_t rows,
                     int64_t cols, float* out) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  // buffered line reader
  const size_t BUF = 1 << 20;
  char* buf = static_cast<char*>(malloc(BUF));
  if (!buf) { fclose(f); return -1; }
  int64_t r = 0;
  int skipped_header = has_header ? 0 : 1;
  while (r < rows && fgets(buf, BUF, f)) {
    size_t len = strlen(buf);
    if (len + 1 >= BUF && buf[len - 1] != '\n') {
      // physical line exceeds the buffer: refuse to mis-parse — signal error
      free(buf);
      fclose(f);
      return -2;
    }
    // skip blank lines
    char* p = buf;
    while (*p == ' ' || *p == '\r') p++;
    if (*p == '\n' || *p == '\0') continue;
    if (!skipped_header) { skipped_header = 1; continue; }
    for (int64_t j = 0; j < cols; j++) {
      while (*p == ' ') p++;
      char* end = p;
      if (*p == '\0' || *p == '\n' || *p == '\r' || *p == ',' || *p == '\t') {
        out[r * cols + j] = NAN;  // empty field
      } else {
        float v = strtof(p, &end);
        out[r * cols + j] = (end == p) ? NAN : v;
        p = end;
      }
      // advance past the delimiter (or to line end)
      while (*p != '\0' && *p != ',' && *p != '\t' && *p != '\n') p++;
      if (*p == ',' || *p == '\t') p++;
    }
    r++;
  }
  free(buf);
  fclose(f);
  return r;
}

}  // extern "C"
