"""Column plumbing and function-application stages.

Reference: core/.../stages/{UDFTransformer,Lambda,Cacher,Timer,Repartition,
Explode,DropColumns,SelectColumns,RenameColumn}.scala (SURVEY.md §2.7).
"""

from __future__ import annotations

import time
from typing import Callable

import numpy as np

from ..core.logging import logger as _logger
from ..core.params import Param, HasInputCol, HasInputCols, HasOutputCol
from ..core.pipeline import PipelineStage, Transformer
from ..core.table import Table


class UDFTransformer(Transformer, HasInputCol, HasInputCols, HasOutputCol):
    """Apply a user function to one or more columns.

    Reference: stages/UDFTransformer.scala. The function receives the input
    column array(s) (whole-column, vectorized — not per-row as in Spark) and
    must return an array of the same length. Set ``vectorized=False`` to wrap a
    per-row scalar function instead.
    """

    udf = Param("udf", "User defined function: column array(s) -> column array",
                is_complex=True)
    vectorized = Param("vectorized", "Whether udf operates on whole columns", bool, True)

    def setUDF(self, f: Callable) -> "UDFTransformer":
        return self.set("udf", f)

    def _transform(self, df: Table) -> Table:
        f = self.get("udf")
        if f is None:
            raise ValueError("UDFTransformer: udf is not set")
        if self.isSet("inputCols"):
            args = [df[c] for c in self.getInputCols()]
        else:
            args = [df[self.getInputCol()]]
        if self.getVectorized():
            out = f(*args)
        else:
            out = np.asarray([f(*vals) for vals in zip(*args)])
        return df.with_column(self.getOutputCol(), np.asarray(out))


class Lambda(Transformer):
    """Arbitrary Table → Table function stage.

    Reference: stages/Lambda.scala (transformFunc + optional transformSchemaFunc).
    """

    transformFunc = Param("transformFunc", "Table -> Table function", is_complex=True)

    def setTransform(self, f: Callable[[Table], Table]) -> "Lambda":
        return self.set("transformFunc", f)

    def _transform(self, df: Table) -> Table:
        f = self.get("transformFunc")
        if f is None:
            raise ValueError("Lambda: transformFunc is not set")
        out = f(df)
        return out if isinstance(out, Table) else Table(out)


class Cacher(Transformer):
    """Materialize the table (device arrays → host, lazy chains → concrete).

    Reference: stages/Cacher.scala (df.cache()). Columnar Tables are already
    materialized numpy; this forces any lazily-wrapped columns to concrete
    arrays and optionally keeps a reference so repeated upstream recompute is
    avoided when used inside Pipelines.
    """

    disable = Param("disable", "Whether or disable the cacher", bool, False)

    def _transform(self, df: Table) -> Table:
        if self.getDisable():
            return df
        out = Table({k: np.asarray(df[k]) for k in df.columns})
        self._cached = out
        return out


class Timer(Transformer):
    """Time a wrapped stage's fit/transform and record it.

    Reference: stages/Timer.scala (logs to stdout / returns time in a column).
    """

    stage = Param("stage", "The stage to time", is_complex=True)
    logToScala = Param("logToScala", "Whether to output the time to the log", bool, True)
    disableMaterialization = Param(
        "disableMaterialization", "Whether to disable timing (so that one can turn it off for evaluation)",
        bool, True)

    def setStage(self, stage: PipelineStage) -> "Timer":
        return self.set("stage", stage)

    def fit(self, df: Table, params=None):
        inner = self.get("stage")
        t0 = time.perf_counter()
        model = inner.fit(df)
        self.elapsed_fit_s = time.perf_counter() - t0
        if self.getLogToScala():
            _logger.info("Timer[%s].fit took %.4fs", type(inner).__name__, self.elapsed_fit_s)
        out = Timer(logToScala=self.getLogToScala())
        out.set("stage", model)
        return out

    def _transform(self, df: Table) -> Table:
        inner = self.get("stage")
        t0 = time.perf_counter()
        out = inner.transform(df)
        self.elapsed_transform_s = time.perf_counter() - t0
        if self.getLogToScala():
            _logger.info("Timer[%s].transform took %.4fs",
                         type(inner).__name__, self.elapsed_transform_s)
        return out


class Repartition(Transformer):
    """Record a target shard count for downstream SPMD execution.

    Reference: stages/Repartition.scala (df.repartition(n) / coalesce). A Table
    is one host-resident block; sharding happens when an estimator lays data on
    the mesh, so this stage attaches the intended shard count as a hint column
    metadata (``table.shard(n)`` consumes it) and optionally reorders rows
    round-robin so contiguous shards are balanced.
    """

    n = Param("n", "Number of partitions", int, 1)
    disable = Param("disable", "Whether to disable repartitioning (so that one can turn it off for evaluation)",
                    bool, False)

    def _transform(self, df: Table) -> Table:
        if self.getDisable():
            return df
        n = self.getN()
        out = df.copy()
        out.num_shards_hint = n
        return out


class Explode(Transformer, HasInputCol, HasOutputCol):
    """One output row per element of a list column, other columns repeated.

    Reference: stages/Explode.scala.
    """

    def _transform(self, df: Table) -> Table:
        col = df[self.getInputCol()]
        out_name = self.getOutputCol() if self.isSet("outputCol") else self.getInputCol()
        lengths = np.asarray([len(np.atleast_1d(v)) for v in col])
        rep_idx = np.repeat(np.arange(df.num_rows), lengths)
        out = Table()
        for name in df.columns:
            if name == self.getInputCol():
                continue
            out[name] = df[name][rep_idx]
        out[out_name] = np.concatenate([np.atleast_1d(v) for v in col]) if len(col) else np.array([])
        return out


class DropColumns(Transformer):
    """Reference: stages/DropColumns.scala."""

    cols = Param("cols", "Comma separated list of column names", list)

    def setCols(self, cols) -> "DropColumns":
        return self.set("cols", list(cols))

    def _transform(self, df: Table) -> Table:
        return df.drop(*self.getCols())


class SelectColumns(Transformer):
    """Reference: stages/SelectColumns.scala."""

    cols = Param("cols", "Comma separated list of selected column names", list)

    def setCols(self, cols) -> "SelectColumns":
        return self.set("cols", list(cols))

    def _transform(self, df: Table) -> Table:
        return df.select(self.getCols())


class RenameColumn(Transformer, HasInputCol, HasOutputCol):
    """Reference: stages/RenameColumn.scala."""

    def _transform(self, df: Table) -> Table:
        return df.rename({self.getInputCol(): self.getOutputCol()})
