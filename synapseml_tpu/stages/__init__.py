"""Generic pipeline stages.

TPU-native analogs of the reference's ``core/.../stages/`` package (21 files,
SURVEY.md §2.7): mini-batching, flattening, UDF application, repartitioning,
column plumbing, text preprocessing, summarization, and class balancing —
re-expressed over the columnar :class:`~synapseml_tpu.core.table.Table` instead
of Spark DataFrames. Batching here feeds jitted TPU programs (fixed shapes),
which is why FixedMiniBatchTransformer supports padding to a static batch size.
"""

from .batchers import (  # noqa: F401
    DynamicMiniBatchTransformer,
    FixedMiniBatchTransformer,
    FlattenBatch,
    TimeIntervalMiniBatchTransformer,
)
from .basic import (  # noqa: F401
    Cacher,
    DropColumns,
    Explode,
    Lambda,
    RenameColumn,
    SelectColumns,
    Repartition,
    Timer,
    UDFTransformer,
)
from .balance import ClassBalancer, ClassBalancerModel, StratifiedRepartition  # noqa: F401
from .ensemble import EnsembleByKey, PartitionConsolidator  # noqa: F401
from .text import TextPreprocessor, UnicodeNormalize  # noqa: F401
from .summarize import SummarizeData  # noqa: F401
from .adapter import MultiColumnAdapter  # noqa: F401
