"""MultiColumnAdapter — apply a single-column stage across many columns.

Reference: core/.../stages/MultiColumnAdapter.scala (SURVEY.md §2.7): clones a
unary ``baseStage`` once per (inputCol, outputCol) pair and chains them into a
PipelineModel.
"""

from __future__ import annotations

from ..core.params import Param, HasInputCols, HasOutputCols
from ..core.pipeline import Estimator, Model, PipelineModel
from ..core.table import Table


class MultiColumnAdapter(Estimator, HasInputCols, HasOutputCols):
    baseStage = Param("baseStage", "Base stage to apply to every column", is_complex=True)

    def setBaseStage(self, stage) -> "MultiColumnAdapter":
        return self.set("baseStage", stage)

    def _pairs(self):
        ins, outs = self.getInputCols(), self.getOutputCols()
        if len(ins) != len(outs):
            raise ValueError("inputCols and outputCols must have the same length")
        return list(zip(ins, outs))

    def _fit(self, df: Table) -> Model:
        base = self.get("baseStage")
        fitted = []
        cur = df
        for in_col, out_col in self._pairs():
            stage = base.copy()
            stage.set("inputCol", in_col)
            stage.set("outputCol", out_col)
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
            else:
                model = stage
            cur = model.transform(cur)
            fitted.append(model)
        return PipelineModel(fitted)
