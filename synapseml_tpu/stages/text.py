"""Text preprocessing stages.

Reference: core/.../stages/TextPreprocessor.scala and UnicodeNormalize.scala
(SURVEY.md §2.7).
"""

from __future__ import annotations

import unicodedata
from typing import Dict

import numpy as np

from ..core.params import Param, HasInputCol, HasOutputCol
from ..core.pipeline import Transformer
from ..core.table import Table


class _Trie:
    """Longest-match replacement trie (reference: TextPreprocessor.scala Trie —
    normalization map applied by walking the text with longest-prefix match)."""

    __slots__ = ("children", "value")

    def __init__(self):
        self.children: Dict[str, "_Trie"] = {}
        self.value = None

    def insert(self, key: str, value: str):
        node = self
        for ch in key:
            node = node.children.setdefault(ch, _Trie())
        node.value = value

    def translate(self, text: str) -> str:
        out = []
        i, n = 0, len(text)
        while i < n:
            node, j, best, best_end = self, i, None, i
            while j < n and text[j] in node.children:
                node = node.children[text[j]]
                j += 1
                if node.value is not None:
                    best, best_end = node.value, j
            if best is not None:
                out.append(best)
                i = best_end
            else:
                out.append(text[i])
                i += 1
        return "".join(out)


class TextPreprocessor(Transformer, HasInputCol, HasOutputCol):
    """Apply a longest-match normalization map to a string column.

    Reference: stages/TextPreprocessor.scala (``map`` param, Trie-based
    longest-prefix replacement, optional lowercasing before matching).
    """

    map = Param("map", "Map of substring match to replacement", dict, None)
    normFunc = Param("normFunc", "Name of normalization function to apply before map "
                     "(identity|lowercase)", str, "identity")

    def setMap(self, m: dict) -> "TextPreprocessor":
        return self.set("map", dict(m))

    def _transform(self, df: Table) -> Table:
        trie = _Trie()
        for k, v in (self.get("map") or {}).items():
            trie.insert(k, v)
        lower = self.getNormFunc() == "lowercase"
        col = df[self.getInputCol()]
        out = np.asarray([trie.translate(str(s).lower() if lower else str(s)) for s in col],
                         dtype=object)
        return df.with_column(self.getOutputCol(), out)


class UnicodeNormalize(Transformer, HasInputCol, HasOutputCol):
    """Unicode NFC/NFD/NFKC/NFKD normalization + optional lowercase.

    Reference: stages/UnicodeNormalize.scala (``form`` param, java.text.Normalizer).
    """

    form = Param("form", "Unicode normalization form: NFC, NFD, NFKC, NFKD", str, "NFKD")
    lower = Param("lower", "Lowercase all characters", bool, True)

    def _transform(self, df: Table) -> Table:
        form, lower = self.getForm(), self.getLower()
        col = df[self.getInputCol()]

        def norm(s):
            t = unicodedata.normalize(form, str(s))
            return t.lower() if lower else t

        out = np.asarray([norm(s) for s in col], dtype=object)
        return df.with_column(self.getOutputCol(), out)
