"""Class balancing and stratified repartitioning.

Reference: core/.../stages/ClassBalancer.scala and StratifiedRepartition.scala
(SURVEY.md §2.7).
"""

from __future__ import annotations

import numpy as np

from ..core.params import Param, HasInputCol, HasOutputCol, HasLabelCol, HasSeed
from ..core.pipeline import Estimator, Model
from ..core.table import Table
from .basic import Transformer


class ClassBalancer(Estimator, HasInputCol, HasOutputCol):
    """Compute inverse-frequency instance weights for imbalanced classes.

    Reference: stages/ClassBalancer.scala — groupBy(inputCol).count, weight =
    maxCount / count, broadcast-joined back as ``outputCol``.
    """

    outputCol = Param("outputCol", "The name of the output column", str, "weight")
    broadcastJoin = Param("broadcastJoin", "Whether to broadcast the class to weight mapping to the worker",
                          bool, True)

    def _fit(self, df: Table) -> "ClassBalancerModel":
        col = df[self.getInputCol()]
        values, counts = np.unique(col, return_counts=True)
        weights = counts.max() / counts.astype(np.float64)
        model = ClassBalancerModel(
            inputCol=self.getInputCol(), outputCol=self.getOutputCol())
        model._values = values
        model._weights = weights
        return model


class ClassBalancerModel(Model, HasInputCol, HasOutputCol):
    _values: np.ndarray
    _weights: np.ndarray

    def _transform(self, df: Table) -> Table:
        col = df[self.getInputCol()]
        idx = np.searchsorted(self._values, col)
        idx = np.clip(idx, 0, len(self._values) - 1)
        w = np.where(self._values[idx] == col, self._weights[idx], 1.0)
        return df.with_column(self.getOutputCol(), w)

    def _save_extra(self, path: str) -> None:
        np.savez(f"{path}/balancer.npz", values=self._values, weights=self._weights)

    def _load_extra(self, path: str) -> None:
        data = np.load(f"{path}/balancer.npz", allow_pickle=True)
        self._values, self._weights = data["values"], data["weights"]


class StratifiedRepartition(Transformer, HasLabelCol, HasSeed):
    """Re-order/resample rows so each of N contiguous shards sees every class.

    Reference: stages/StratifiedRepartition.scala (mode equal/original/mixed via
    DistributedStratifiedRepartition). Here shards are contiguous row ranges
    (Table.shard), so stratification = interleaving rows by class:

    * ``original``: preserve class proportions, round-robin classes across the
      table so every contiguous shard matches the global distribution.
    * ``equal``: resample (with replacement for minority classes) so every class
      has equal count, then interleave.
    * ``mixed``: like original but guarantees each class appears at least
      ``minClassOccurrence`` times per shard-sized block.
    """

    mode = Param("mode", "Specify equal to repartition with replacement across all labels, "
                 "specify original to keep the ratios in the original dataset, or specify "
                 "mixed to use a heuristic", str, "mixed")

    def _transform(self, df: Table) -> Table:
        labels = df[self.getLabelCol()]
        rng = np.random.default_rng(self.getSeed())
        classes, inv = np.unique(labels, return_inverse=True)
        idx_by_class = [np.flatnonzero(inv == c) for c in range(len(classes))]
        mode = self.getMode()
        if mode == "equal":
            target = max(len(ix) for ix in idx_by_class)
            idx_by_class = [
                ix if len(ix) == target else rng.choice(ix, size=target, replace=True)
                for ix in idx_by_class]
        pools = [rng.permutation(ix) for ix in idx_by_class]
        # proportional interleave: emit classes at evenly spaced positions
        total = sum(len(p) for p in pools)
        order = np.empty(total, dtype=np.int64)
        positions = []
        for ci, p in enumerate(pools):
            # fractional positions spread uniformly over [0, 1)
            pos = (np.arange(len(p)) + (ci + 1) / (len(pools) + 1)) / len(p)
            positions.append(pos)
        flat_idx = np.concatenate(pools)
        flat_pos = np.concatenate(positions)
        order = flat_idx[np.argsort(flat_pos, kind="stable")]
        return df.take(order)
