"""Mini-batching stages.

Reference: core/.../stages/MiniBatchTransformer.scala:55-253 and
stages/Batchers.scala:11-130 (Dynamic/Fixed/TimeInterval iterators), plus
FlattenBatch (the inverse). In the reference these convert row iterators into
rows-of-Seqs for batch-oriented transformers (ONNXModel, HTTP, cognitive). Here
a "batched" Table has object-dtype columns whose elements are per-batch numpy
arrays; FixedMiniBatchTransformer can also pad the trailing batch so every
batch has one static shape — what a jitted TPU program wants.
"""

from __future__ import annotations

import time

import numpy as np

from ..core.params import Param
from ..core.pipeline import Transformer
from ..core.table import Table


def _to_batched(df: Table, sizes: list) -> Table:
    """Slice each column into len(sizes) batches (object arrays of arrays)."""
    out = Table()
    bounds = np.cumsum([0] + list(sizes))
    for name in df.columns:
        col = df[name]
        batched = np.empty(len(sizes), dtype=object)
        for i in range(len(sizes)):
            batched[i] = col[bounds[i]:bounds[i + 1]]
        out[name] = batched
    return out


class FixedMiniBatchTransformer(Transformer):
    """Group rows into fixed-size batches.

    Reference: FixedMiniBatchTransformer (stages/MiniBatchTransformer.scala:150-180,
    FixedBatchIterator stages/Batchers.scala:31-47). ``buffered`` there uses a
    background thread; irrelevant in columnar execution. Extension: ``padBatches``
    repeats trailing rows so every batch is exactly ``batchSize`` — static shapes
    keep XLA from recompiling on the ragged final batch.
    """

    batchSize = Param("batchSize", "The max size of the buffer", int, 10)
    maxBufferSize = Param("maxBufferSize", "The max size of the buffer", int, 2147483647)
    buffered = Param("buffered", "Whether to buffer batches in advance", bool, False)
    padBatches = Param(
        "padBatches",
        "Pad the final batch to batchSize by repeating trailing rows (adds a "
        "'__pad__' boolean column marking synthetic rows)", bool, False)

    def _transform(self, df: Table) -> Table:
        n = df.num_rows
        bs = self.getBatchSize()
        if n == 0:
            return _to_batched(df, [])
        if self.getPadBatches() and n % bs != 0:
            reps = bs - (n % bs)
            filler = df.take(np.arange(reps) % n)
            pad_flag = np.concatenate([np.zeros(n, bool), np.ones(reps, bool)])
            df = df.concat(filler).with_column("__pad__", pad_flag)
            n += reps
        sizes = [bs] * (n // bs) + ([n % bs] if n % bs else [])
        return _to_batched(df, sizes)


class DynamicMiniBatchTransformer(Transformer):
    """Batch "whatever is available now" — one batch per poll.

    Reference: DynamicMiniBatchTransformer (stages/MiniBatchTransformer.scala:100-126,
    DynamicBufferedBatcher stages/Batchers.scala:49-99). On a materialized Table
    the whole input is available, so this yields a single batch capped at
    ``maxBatchSize`` (matching the reference's semantics when the upstream
    iterator is already drained).
    """

    maxBatchSize = Param("maxBatchSize", "The max size of the buffer", int, 2147483647)

    def _transform(self, df: Table) -> Table:
        n = df.num_rows
        cap = self.getMaxBatchSize()
        if n == 0:
            return _to_batched(df, [])
        sizes = [min(cap, n - s) for s in range(0, n, cap)]
        return _to_batched(df, sizes)


class TimeIntervalMiniBatchTransformer(Transformer):
    """Batch by wall-clock interval while consuming a row stream.

    Reference: TimeIntervalMiniBatchTransformer (stages/MiniBatchTransformer.scala:128-148,
    TimeIntervalBatcher stages/Batchers.scala:101-130). Meaningful for streaming
    serving queues; on a static Table all rows are already available within one
    interval, so this produces a single batch (capped by ``maxBatchSize``), and
    the interval applies when used inside the serving gateway's polling loop.
    """

    millisToWait = Param("millisToWait", "The time to wait before constructing a batch", int, 1000)
    maxBatchSize = Param("maxBatchSize", "The max size of the buffer", int, 2147483647)

    def _transform(self, df: Table) -> Table:
        n = df.num_rows
        cap = self.getMaxBatchSize()
        if n == 0:
            return _to_batched(df, [])
        sizes = [min(cap, n - s) for s in range(0, n, cap)]
        return _to_batched(df, sizes)

    def wait_interval(self) -> None:
        time.sleep(self.getMillisToWait() / 1000.0)


class FlattenBatch(Transformer):
    """Explode batched columns back into one row per element.

    Reference: FlattenBatch (stages/MiniBatchTransformer.scala:200-253). Drops
    rows marked synthetic by FixedMiniBatchTransformer(padBatches=True).
    """

    keepPadding = Param("keepPadding", "Keep rows marked as padding ('__pad__')", bool, False)

    def _transform(self, df: Table) -> Table:
        out = Table()
        for name in df.columns:
            col = df[name]
            if col.dtype == object and len(col) and isinstance(col[0], np.ndarray):
                flat = np.concatenate([np.atleast_1d(b) for b in col]) if len(col) else col
            else:
                flat = col
            out[name] = flat
        if "__pad__" in out and not self.getKeepPadding():
            out = out.filter(~out["__pad__"].astype(bool)).drop("__pad__")
        return out
