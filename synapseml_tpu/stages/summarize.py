"""Dataset summarization stage.

Reference: core/.../stages/SummarizeData.scala (SURVEY.md §2.7) — emits one row
per input column with counts / quantiles / basic statistics / error rates,
toggled by boolean params.
"""

from __future__ import annotations

import numpy as np

from ..core.params import Param
from ..core.pipeline import Transformer
from ..core.table import Table


class SummarizeData(Transformer):
    counts = Param("counts", "Compute count statistics (count, unique, missing)", bool, True)
    basic = Param("basic", "Compute basic statistics (mean, stddev, min, max)", bool, True)
    sample = Param("sample", "Compute sample statistics (variance, skew, kurtosis)", bool, True)
    percentiles = Param("percentiles", "Compute percentiles (0.5, 1, 5, 25, 50, 75, 95, 99, 99.5)", bool, True)
    errorThreshold = Param("errorThreshold", "Threshold for quantiles - 0 is exact", float, 0.0)

    _PCTS = [0.005, 0.01, 0.05, 0.25, 0.50, 0.75, 0.95, 0.99, 0.995]

    def _transform(self, df: Table) -> Table:
        rows = []
        for name in df.columns:
            col = df[name]
            if col.ndim != 1:
                continue
            row = {"Feature": name}
            numeric = np.issubdtype(col.dtype, np.number)
            vals = col.astype(np.float64) if numeric else None
            finite = vals[np.isfinite(vals)] if numeric else None
            if self.getCounts():
                row["Count"] = len(col)
                row["Unique Value Count"] = len(np.unique(col[~_is_missing(col)]))
                row["Missing Value Count"] = int(_is_missing(col).sum())
            if self.getBasic():
                row["Mean"] = float(finite.mean()) if numeric and len(finite) else np.nan
                row["Standard Deviation"] = float(finite.std(ddof=1)) if numeric and len(finite) > 1 else np.nan
                row["Min"] = float(finite.min()) if numeric and len(finite) else np.nan
                row["Max"] = float(finite.max()) if numeric and len(finite) else np.nan
            if self.getSample():
                if numeric and len(finite) > 2:
                    m = finite.mean()
                    s = finite.std(ddof=1)
                    z = (finite - m) / s if s > 0 else np.zeros_like(finite)
                    row["Sample Variance"] = float(s ** 2)
                    row["Sample Skewness"] = float((z ** 3).mean())
                    row["Sample Kurtosis"] = float((z ** 4).mean() - 3.0)
                else:
                    row["Sample Variance"] = row["Sample Skewness"] = row["Sample Kurtosis"] = np.nan
            if self.getPercentiles():
                for p in self._PCTS:
                    key = f"Quantile {p*100:g}%"
                    row[key] = float(np.quantile(finite, p)) if numeric and len(finite) else np.nan
            rows.append(row)
        return Table.from_rows(rows)


def _is_missing(col: np.ndarray) -> np.ndarray:
    if np.issubdtype(col.dtype, np.number):
        return ~np.isfinite(col.astype(np.float64))
    return np.asarray([v is None or (isinstance(v, str) and v == "") for v in col])
