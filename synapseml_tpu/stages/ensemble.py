"""Key-wise ensembling and partition consolidation.

Reference: core/.../stages/EnsembleByKey.scala and PartitionConsolidator.scala:22-51
(SURVEY.md §2.7, §2.2 "Rate-limit consolidation").
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.params import Param
from ..core.pipeline import Transformer
from ..core.table import Table


class EnsembleByKey(Transformer):
    """Group rows by key column(s) and aggregate chosen columns.

    Reference: stages/EnsembleByKey.scala — groupBy(keys).agg(strategy(col));
    strategy ``mean`` over scalar or vector columns; ``collapseGroup`` controls
    whether one row per group is returned or the aggregate is joined back onto
    every row; ``vectorDims`` validated against actual widths.
    """

    keys = Param("keys", "Keys to group by", list)
    cols = Param("cols", "Cols to ensemble", list)
    strategy = Param("strategy", "How to ensemble the scores, ex: mean", str, "mean")
    collapseGroup = Param("collapseGroup", "Whether to collapse all items in group to one entry", bool, True)

    def setKeys(self, keys) -> "EnsembleByKey":
        return self.set("keys", list(keys))

    def setCols(self, cols) -> "EnsembleByKey":
        return self.set("cols", list(cols))

    def _transform(self, df: Table) -> Table:
        keys: List[str] = self.getKeys()
        cols: List[str] = self.getCols()
        if self.getStrategy() != "mean":
            raise ValueError(f"Unsupported strategy {self.getStrategy()!r} (reference supports mean)")
        key_arrays = [df[k] for k in keys]
        combo = np.rec.fromarrays(key_arrays) if len(key_arrays) > 1 else key_arrays[0]
        uniq, inv = np.unique(combo, return_inverse=True)
        n_groups = len(uniq)

        agg = {}
        for c in cols:
            col = df[c]
            dense = col if col.ndim == 2 else col.astype(np.float64)[:, None]
            sums = np.zeros((n_groups, dense.shape[1]), dtype=np.float64)
            np.add.at(sums, inv, dense)
            counts = np.bincount(inv, minlength=n_groups).astype(np.float64)
            mean = sums / counts[:, None]
            agg[f"mean({c})"] = mean if col.ndim == 2 else mean[:, 0]

        if self.getCollapseGroup():
            first_idx = np.zeros(n_groups, dtype=np.int64)
            seen = np.full(n_groups, -1, dtype=np.int64)
            for i, g in enumerate(inv):
                if seen[g] < 0:
                    seen[g] = i
            first_idx = seen
            out = Table({k: df[k][first_idx] for k in keys})
            for name, arr in agg.items():
                out[name] = arr
            return out
        out = df.copy()
        for name, arr in agg.items():
            out[name] = arr[inv]
        return out


class PartitionConsolidator(Transformer):
    """Funnel many shards' rows through few workers (rate-limited services).

    Reference: stages/PartitionConsolidator.scala:22-51 — data from all
    partitions flows through ``Consolidator`` queues so only a bounded number of
    concurrent workers issue requests. In the columnar runtime rows are already
    consolidated on the host; this stage exists so pipelines carry the same
    concurrency intent: it re-shards the table to ``numPartitions`` hint and
    downstream HTTP stages read ``concurrency`` from it.
    """

    numPartitions = Param("numPartitions", "Number of partitions to consolidate down to", int, 1)
    concurrency = Param("concurrency", "Max simultaneous requests downstream", int, 1)

    def _transform(self, df: Table) -> Table:
        out = df.copy()
        out.num_shards_hint = self.getNumPartitions()
        out.concurrency_hint = self.getConcurrency()
        return out
