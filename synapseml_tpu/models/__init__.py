from .gbdt import (  # noqa: F401
    LightGBMClassifier,
    LightGBMClassificationModel,
    LightGBMRegressor,
    LightGBMRegressionModel,
    LightGBMRanker,
    LightGBMRankerModel,
)
