"""LightGBM-capability estimators: Classifier / Regressor / Ranker.

The estimator surface of the reference's lightgbm module (SURVEY.md §2.3):
LightGBMClassifier.scala, LightGBMRegressor.scala, LightGBMRanker.scala and the
~90-param surface of params/LightGBMParams.scala + BaseTrainParams.scala, on top
of this framework's TPU GBDT engine (synapseml_tpu.gbdt) instead of SWIG/JNI
calls into lightgbmlib.

Param-parity notes:
  * camelCase param names match the reference so code ports 1:1.
  * Cluster-plumbing params that exist only because of Spark/JNI mechanics
    (useBarrierExecutionMode, driverListenPort, timeout, numTasks, chunkSize,
    matrixType, executionMode, dataTransferMode, useSingleDatasetMode,
    maxStreamingOMPThreads, ...) are accepted for API compatibility but are
    no-ops on TPU: pods are gang-scheduled SPMD, there is no rendezvous ring to
    configure (SURVEY §5.8).
  * ``numBatches`` batching with warm start reproduces LightGBMBase.scala:39-64.
  * ``passThroughArgs`` accepts raw LightGBM-style "key=value" text overriding
    structured params — the reference's escape hatch (LightGBMParams.scala).
  * Accepted-but-inert by design beyond the Spark-plumbing set:
    ``objectiveSeed`` (our objectives draw no randomness), ``deterministic``
    (training is deterministic by construction), ``verbosity`` /
    ``isProvideTrainingMetric`` (use core.logging spans), ``isEnableSparse``
    (sparse input auto-detects), ``repartitionByGroupingColumn`` (the ranker
    always sorts group-contiguously — the param's true behavior), and the
    advanced monotone modes ``monotoneConstraintsMethod`` /
    ``monotonePenalty`` (the basic method is enforced; the advanced
    relaxations are an accuracy/speed trade the basic mode upper-bounds).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core import (Estimator, HasFeaturesCol, HasGroupCol, HasInitScoreCol,
                    HasLabelCol, HasPredictionCol, HasProbabilityCol,
                    HasRawPredictionCol, HasValidationIndicatorCol, HasWeightCol,
                    Model, Param, Table, feature_matrix)
from ..gbdt.boosting import Booster, BoosterConfig, train_booster


class _LightGBMParams(HasFeaturesCol, HasLabelCol, HasWeightCol,
                      HasValidationIndicatorCol, HasInitScoreCol, HasPredictionCol):
    # core boosting params (defaults = LightGBM defaults, as in the reference)
    numIterations = Param("numIterations", "Number of boosting iterations", int, 100)
    learningRate = Param("learningRate", "Shrinkage rate", float, 0.1)
    numLeaves = Param("numLeaves", "Max leaves per tree", int, 31)
    maxBin = Param("maxBin", "Max number of feature bins", int, 255)
    maxDepth = Param("maxDepth", "Max tree depth (-1 = unlimited)", int, -1)
    boostingType = Param("boostingType", "gbdt, rf, dart or goss", str, "gbdt")
    lambdaL1 = Param("lambdaL1", "L1 regularization", float, 0.0)
    lambdaL2 = Param("lambdaL2", "L2 regularization", float, 0.0)
    minDataInLeaf = Param("minDataInLeaf", "Min rows per leaf", int, 20)
    minSumHessianInLeaf = Param("minSumHessianInLeaf", "Min hessian sum per leaf", float, 1e-3)
    minGainToSplit = Param("minGainToSplit", "Min gain to perform a split", float, 0.0)
    baggingFraction = Param("baggingFraction", "Row subsample fraction", float, 1.0)
    baggingFreq = Param("baggingFreq", "Resample bagging every k iterations (0=off)", int, 0)
    baggingSeed = Param("baggingSeed", "Bagging seed", int, 3)
    featureFraction = Param("featureFraction", "Feature subsample fraction per tree", float, 1.0)
    featureFractionByNode = Param("featureFractionByNode", "Feature subsample fraction per node", float, 1.0)
    posBaggingFraction = Param("posBaggingFraction", "Positive-class bagging fraction", float, 1.0)
    negBaggingFraction = Param("negBaggingFraction", "Negative-class bagging fraction", float, 1.0)
    maxDeltaStep = Param("maxDeltaStep", "Max absolute leaf output", float, 0.0)
    earlyStoppingRound = Param("earlyStoppingRound", "Early stopping patience (0=off)", int, 0)
    improvementTolerance = Param("improvementTolerance", "Min metric improvement", float, 0.0)
    metric = Param("metric", "Eval metric for validation", str)
    dropRate = Param("dropRate", "DART tree drop probability", float, 0.1)
    maxDrop = Param("maxDrop", "DART max trees dropped per iteration", int, 50)
    skipDrop = Param("skipDrop", "DART probability of skipping dropout", float, 0.5)
    uniformDrop = Param("uniformDrop", "DART uniform drop", bool, False)
    topRate = Param("topRate", "GOSS large-gradient keep fraction", float, 0.2)
    otherRate = Param("otherRate", "GOSS small-gradient sample fraction", float, 0.1)
    monotoneConstraints = Param("monotoneConstraints", "Per-feature -1/0/+1 constraints", list)
    monotoneConstraintsMethod = Param("monotoneConstraintsMethod", "basic/intermediate/advanced", str, "basic")
    monotonePenalty = Param("monotonePenalty", "Monotone split penalty", float, 0.0)
    categoricalSlotIndexes = Param("categoricalSlotIndexes", "Categorical feature indices", list)
    categoricalSlotNames = Param("categoricalSlotNames", "Categorical feature names", list)
    slotNames = Param("slotNames", "Feature names", list)
    seed = Param("seed", "Main random seed", int, 0)
    objectiveSeed = Param("objectiveSeed", "Objective seed", int, 5)
    dataRandomSeed = Param("dataRandomSeed", "Data random seed", int, 1)
    boostFromAverage = Param("boostFromAverage", "Initialize score to label average", bool, True)
    numBatches = Param("numBatches", "Split training into N sequential warm-started batches", int, 0)
    modelString = Param("modelString", "Initial model string to continue training from", str)
    binSampleCount = Param("binSampleCount", "Rows sampled for bin boundaries", int, 200000)
    catSmooth = Param("catSmooth", "Categorical smoothing", float, 10.0)
    maxCatThreshold = Param("maxCatThreshold", "Max categories on one split side", int, 32)
    verbosity = Param("verbosity", "Verbosity", int, -1)
    leafPredictionCol = Param("leafPredictionCol", "Output column for leaf indices", str)
    featuresShapCol = Param("featuresShapCol", "Output column for SHAP values", str)
    predictDisableShapeCheck = Param("predictDisableShapeCheck", "Disable shape check at predict", bool, False)
    passThroughArgs = Param("passThroughArgs", "Raw LightGBM-style 'key=value' args overriding params", str)
    # Spark/JNI-plumbing compat no-ops (see module docstring)
    useBarrierExecutionMode = Param("useBarrierExecutionMode", "no-op on TPU (gang-scheduled)", bool, False)
    useSingleDatasetMode = Param("useSingleDatasetMode", "no-op on TPU (one process per host)", bool, True)
    executionMode = Param("executionMode", "no-op on TPU", str, "streaming")
    dataTransferMode = Param("dataTransferMode", "no-op on TPU", str, "streaming")
    numTasks = Param("numTasks", "no-op on TPU", int, 0)
    numThreads = Param("numThreads", "no-op (XLA manages threads)", int, 0)
    chunkSize = Param("chunkSize", "no-op on TPU", int, 10000)
    matrixType = Param("matrixType", "no-op on TPU (auto)", str, "auto")
    defaultListenPort = Param("defaultListenPort", "no-op on TPU", int, 12400)
    driverListenPort = Param("driverListenPort", "no-op on TPU", int, 0)
    timeout = Param("timeout", "no-op on TPU", float, 1200.0)
    maxStreamingOMPThreads = Param("maxStreamingOMPThreads", "no-op on TPU", int, 16)
    microBatchSize = Param("microBatchSize", "no-op on TPU", int, 100)
    topK = Param("topK", "Voting-parallel top-K (distributed histogram vote)", int, 20)
    parallelism = Param("parallelism", "data_parallel or voting_parallel "
                        "(LightGBMParams.scala:25-29)", str, "data_parallel")
    isProvideTrainingMetric = Param("isProvideTrainingMetric", "Log training metrics", bool, False)
    deterministic = Param("deterministic", "Deterministic training", bool, False)
    isEnableSparse = Param("isEnableSparse", "Enable sparse optimization", bool, True)
    minDataPerBin = Param("minDataPerBin", "Minimum sample rows per bin "
                          "(under-filled bins merge)", int, 3)
    maxBinByFeature = Param("maxBinByFeature", "Per-feature max bin counts",
                            list, None)
    catl2 = Param("catl2", "Extra L2 applied to categorical split gains",
                  float, 10.0)
    dropSeed = Param("dropSeed", "DART drop-selection seed (0 = derive from "
                     "seed)", int, 0)
    featureFractionSeed = Param("featureFractionSeed", "Feature-sampling seed "
                                "(0 = derive from seed)", int, 0)
    extraSeed = Param("extraSeed", "Extra sampling seed (0 = derive from "
                      "seed)", int, 0)
    startIteration = Param("startIteration", "First boosting round used at "
                           "prediction time", int, 0)
    maxCatToOnehot = Param("maxCatToOnehot", "One-vs-rest categorical splits "
                           "at or below this many categories", int, 4)
    minDataPerGroup = Param("minDataPerGroup", "Minimum rows per categorical "
                            "group considered for splitting", int, 100)
    xGBoostDartMode = Param("xGBoostDartMode", "XGBoost-style DART "
                            "normalization (learning-rate weighted)", bool,
                            False)
    fobj = Param("fobj", "Custom objective: fn(score, label, weight) -> "
                 "(grad, hess) arrays (the reference's FObjTrait/FObjParam)",
                 is_complex=True)
    samplingSubsetSize = Param("samplingSubsetSize", "Boundary-sample size "
                               "when subset sampling; 0 defers to "
                               "binSampleCount", int, 0)
    repartitionByGroupingColumn = Param("repartitionByGroupingColumn",
                                        "Kept for API parity: rows are "
                                        "group-contiguous by construction "
                                        "here (no partitions to repartition)",
                                        bool, True)
    referenceDataset = Param("referenceDataset", "Precomputed BinMapper (or "
                             "gbdt.Dataset) reused for binning — the "
                             "reference-dataset broadcast analog",
                             is_complex=True)
    useMissing = Param("useMissing", "Handle missing values specially", bool, True)
    zeroAsMissing = Param("zeroAsMissing", "Treat zero as missing", bool, False)

    def _reference_mapper(self, X=None):
        """referenceDataset param → BinMapper (accepts a Dataset too).
        With ``X`` (the post-missing-params training matrix): validate that
        every feature carrying NaN has a missing bin — a reference mapper
        built WITHOUT the same zeroAsMissing/useMissing mapping would bin
        those rows into the last real bin at fit yet route them as missing
        at predict, silently corrupting the model."""
        ref = self.get("referenceDataset")
        if ref is None:
            return None
        mapper = getattr(ref, "mapper", ref)
        if X is not None:
            need = np.isnan(np.asarray(X)).any(axis=0)
            have = np.asarray(mapper.nan_mask)
            bad = np.flatnonzero(need[: len(have)] & ~have)
            if bad.size:
                raise ValueError(
                    "referenceDataset's bin mapper has no missing bin for "
                    f"feature(s) {bad.tolist()} that contain missing values "
                    "after useMissing/zeroAsMissing preprocessing; build the "
                    "reference dataset from identically-preprocessed data")
        return mapper

    def _base_config(self, **overrides) -> BoosterConfig:
        mc = self.get("monotoneConstraints")
        cfg = BoosterConfig(
            num_iterations=self.getNumIterations(),
            learning_rate=self.getLearningRate(),
            num_leaves=self.getNumLeaves(),
            max_bin=self.getMaxBin(),
            max_depth=self.getMaxDepth(),
            boosting_type=self.getBoostingType(),
            lambda_l1=self.getLambdaL1(),
            lambda_l2=self.getLambdaL2(),
            min_data_in_leaf=self.getMinDataInLeaf(),
            min_sum_hessian_in_leaf=self.getMinSumHessianInLeaf(),
            min_gain_to_split=self.getMinGainToSplit(),
            bagging_fraction=self.getBaggingFraction(),
            bagging_freq=self.getBaggingFreq(),
            feature_fraction=self.getFeatureFraction(),
            feature_fraction_bynode=self.getFeatureFractionByNode(),
            pos_bagging_fraction=self.getPosBaggingFraction(),
            neg_bagging_fraction=self.getNegBaggingFraction(),
            max_delta_step=self.getMaxDeltaStep(),
            early_stopping_round=self.getEarlyStoppingRound(),
            metric=self.get("metric"),
            drop_rate=self.getDropRate(),
            max_drop=self.getMaxDrop(),
            skip_drop=self.getSkipDrop(),
            uniform_drop=self.getUniformDrop(),
            top_rate=self.getTopRate(),
            other_rate=self.getOtherRate(),
            monotone_constraints=mc,
            seed=self.getSeed(),
            boost_from_average=self.getBoostFromAverage(),
            bin_sample_count=(self.getSamplingSubsetSize()
                              or self.getBinSampleCount()),
            cat_smooth=self.getCatSmooth(),
            cat_l2=self.getCatl2(),
            min_data_in_bin=self.getMinDataPerBin(),
            max_bin_by_feature=self.get("maxBinByFeature"),
            drop_seed=self.getDropSeed(),
            feature_fraction_seed=self.getFeatureFractionSeed(),
            extra_seed=self.getExtraSeed(),
            bagging_seed=self.getBaggingSeed(),
            improvement_tolerance=self.getImprovementTolerance(),
            data_random_seed=(self.get("dataRandomSeed")
                              if self.isSet("dataRandomSeed") else None),
            zero_as_missing=(bool(self.get("zeroAsMissing"))
                             and bool(self.get("useMissing"))),
            start_iteration=self.getStartIteration(),
            max_cat_threshold=self.getMaxCatThreshold(),
            max_cat_to_onehot=self.getMaxCatToOnehot(),
            min_data_per_group=self.getMinDataPerGroup(),
            xgboost_dart_mode=self.getXGBoostDartMode(),
            tree_learner=("voting" if self.getParallelism() == "voting_parallel"
                          else "feature" if self.getParallelism() == "feature_parallel"
                          else "auto" if self.getParallelism() == "auto"
                          else "data"),
            top_k=self.getTopK(),
        )
        for k, v in overrides.items():
            setattr(cfg, k, v)
        self._apply_pass_through(cfg)
        return cfg

    def _apply_pass_through(self, cfg: BoosterConfig) -> None:
        """passThroughArgs: 'k1=v1 k2=v2' raw overrides (LightGBMParams.scala)."""
        raw = self.get("passThroughArgs")
        if not raw:
            return
        for tok in raw.split():
            if "=" not in tok:
                continue
            key, _, val = tok.partition("=")
            if hasattr(cfg, key):
                cur = getattr(cfg, key)
                typ = type(cur) if cur is not None else str
                if typ is bool:
                    setattr(cfg, key, val.lower() in ("1", "true", "yes"))
                elif typ in (int, float):
                    setattr(cfg, key, typ(float(val)))
                else:
                    setattr(cfg, key, val)

    def _categorical_indexes(self, feature_names: Optional[List[str]]) -> List[int]:
        """categorical-slot detection (LightGBMBase.scala:167-198)."""
        idx = list(self.get("categoricalSlotIndexes") or [])
        names = self.get("categoricalSlotNames") or []
        if names and feature_names:
            idx += [feature_names.index(n) for n in names if n in feature_names]
        return sorted(set(int(i) for i in idx))

    def _apply_missing_params(self, X: np.ndarray) -> np.ndarray:
        """useMissing / zeroAsMissing preprocessing (BinMapper missing-type
        election in native LightGBM): useMissing=False coerces NaN to 0
        (missing handling disabled); zeroAsMissing=True maps exact zeros to
        NaN so they land in the missing bin, with the booster's
        zero_as_missing flag making traversal + serialization route zeros
        (missing_type=zero) — see Booster._missing_types."""
        if not self.get("useMissing"):
            return np.nan_to_num(X, nan=0.0)
        if self.get("zeroAsMissing"):
            X = np.asarray(X, np.float32).copy()
            # |x| <= kZeroThreshold (1e-35) folds into the zero bin in
            # native LightGBM, and predict-time traversal routes the same
            # band — exact zeros only would score tiny values differently
            # at fit vs transform
            X[np.abs(X) <= 1e-35] = np.nan
        return X

    def _extract_training_arrays(self, df: Table):
        X = self._apply_missing_params(
            feature_matrix(df, self.getFeaturesCol()))
        y = np.asarray(df[self.getLabelCol()], np.float32)
        w = (np.asarray(df[self.get("weightCol")], np.float32)
             if self.get("weightCol") and self.get("weightCol") in df else None)
        init = (np.asarray(df[self.get("initScoreCol")], np.float32)
                if self.get("initScoreCol") and self.get("initScoreCol") in df else None)
        return X, y, w, init

    def _split_validation(self, df: Table):
        vcol = self.get("validationIndicatorCol")
        if vcol and vcol in df:
            mask = np.asarray(df[vcol], bool)
            return df.filter(~mask), df.filter(mask)
        return df, None


class _LightGBMModelBase(Model, HasFeaturesCol, HasPredictionCol):
    leafPredictionCol = Param("leafPredictionCol", "Output column for leaf indices", str)
    featuresShapCol = Param("featuresShapCol", "Output column for SHAP values", str)
    predictDisableShapeCheck = Param(
        "predictDisableShapeCheck",
        "Truncate/pad prediction features to the trained width instead of "
        "raising on mismatch", bool, False)

    def __init__(self, booster: Optional[Booster] = None, **kwargs):
        super().__init__(**kwargs)
        self.booster = booster

    # --- persistence of the native model string --------------------------
    def _save_extra(self, path: str) -> None:
        import os

        if self.booster is not None:
            self.booster.save_native(os.path.join(path, "model.txt"))

    def _load_extra(self, path: str) -> None:
        import os

        p = os.path.join(path, "model.txt")
        if os.path.exists(p):
            with open(p) as fh:
                self.booster = Booster.from_model_string(fh.read())

    def dumpModel(self, num_iteration: int = -1) -> str:
        """JSON model dump (LightGBMModelMethods/Booster dumpModel parity)."""
        return self.booster.dump_model(num_iteration)

    def saveNativeModel(self, path: str, overwrite: bool = True) -> None:
        """LightGBMModelMethods.saveNativeModel parity."""
        import os

        if os.path.exists(path) and not overwrite:
            raise FileExistsError(path)
        self.booster.save_native(path)

    def getBoosterBestIteration(self) -> int:
        """Best iteration from early stopping (-1 without validation) —
        LightGBMModelMethods.getBoosterBestIteration parity."""
        return int(self.booster.best_iteration)

    def getBoosterBestScore(self):
        """Best validation metric value from training (None without
        validation) — the Booster.best_score surface."""
        return self.booster.best_score

    def getBoosterNumTotalIterations(self) -> int:
        return self.booster.num_trees // self.booster.models_per_iter

    def getBoosterNumTotalModel(self) -> int:
        return self.booster.num_trees

    def getBoosterNumFeatures(self) -> int:
        return self.booster.mapper.num_features

    def getBoosterNumClasses(self) -> int:
        return self.booster.num_class

    def getNativeModel(self) -> str:
        return self.booster.model_string()

    def getFeatureImportances(self, importance_type: str = "split"):
        return list(self.booster.feature_importances(importance_type))

    def getFeatureShaps(self, X) -> np.ndarray:
        return self.booster.feature_shap(np.asarray(X, np.float32))

    def _predict_matrix(self, df: Table) -> np.ndarray:
        """Feature matrix for prediction: validates the width against the
        trained model (clear error instead of an opaque gather failure);
        predictDisableShapeCheck=True instead truncates / zero-pads, the
        native predict_disable_shape_check behavior."""
        X = feature_matrix(df, self.getFeaturesCol())
        nf = self.booster.mapper.num_features
        if X.shape[1] != nf:
            if not self.get("predictDisableShapeCheck"):
                raise ValueError(
                    f"prediction data has {X.shape[1]} features but the "
                    f"model was trained with {nf}; set "
                    "predictDisableShapeCheck=True to truncate/pad")
            if X.shape[1] > nf:
                X = X[:, :nf]
            else:
                X = np.concatenate(
                    [X, np.zeros((X.shape[0], nf - X.shape[1]),
                                 X.dtype)], axis=1)
        return X

    def _maybe_extra_cols(self, out: Table, X) -> Table:
        if self.get("leafPredictionCol"):
            out = out.with_column(self.get("leafPredictionCol"),
                                  self.booster.predict_leaf(X).astype(np.float64))
        if self.get("featuresShapCol"):
            out = out.with_column(self.get("featuresShapCol"),
                                  self.booster.feature_shap(X))
        return out


# ---------------------------------------------------------------------------
# Classifier
# ---------------------------------------------------------------------------

class LightGBMClassifier(Estimator, _LightGBMParams, HasProbabilityCol, HasRawPredictionCol):
    """Binary / multiclass GBDT classifier (reference: LightGBMClassifier.scala)."""

    objective = Param("objective", "binary or multiclass", str, "binary")
    isUnbalance = Param("isUnbalance", "Adjust for unbalanced binary labels", bool, False)
    maxNumClasses = Param("maxNumClasses", "Upper bound on auto-detected "
                          "label classes (guards runaway continuous labels)",
                          int, 100)
    scalePosWeight = Param("scalePosWeight", "Positive-class weight multiplier", float, 1.0)
    thresholds = Param("thresholds", "Per-class prediction thresholds", list)

    def _fit(self, df: Table) -> "LightGBMClassificationModel":
        train_df, valid_df = self._split_validation(df)
        X, y, w, init = self._extract_training_arrays(train_df)
        # map arbitrary label values to 0..K-1 (objectives assume contiguous
        # class ids); the model maps predictions back through classes_
        classes, y_idx = np.unique(y, return_inverse=True)
        num_class = len(classes)
        if num_class < 2:
            raise ValueError(f"need at least 2 label classes, got {classes}")
        if num_class > self.getMaxNumClasses():
            raise ValueError(
                f"detected {num_class} label classes, above maxNumClasses="
                f"{self.getMaxNumClasses()} — a continuous label column was "
                "likely passed to the classifier (raise maxNumClasses if "
                "this cardinality is intended)")
        y = y_idx.astype(np.float32)
        objective = self.getObjective()
        if objective == "binary" and num_class > 2:
            objective = "multiclass"
        cfg = self._base_config(objective=objective,
                                num_class=(num_class if objective != "binary" else 1))
        if self.getIsUnbalance() and objective == "binary":
            npos = max(float((y > 0).sum()), 1.0)
            nneg = max(float((y <= 0).sum()), 1.0)
            w = (w if w is not None else np.ones_like(y)) * np.where(y > 0, nneg / npos, 1.0)
        elif self.getScalePosWeight() != 1.0 and objective == "binary":
            w = (w if w is not None else np.ones_like(y)) * np.where(
                y > 0, self.getScalePosWeight(), 1.0)

        valid = None
        if valid_df is not None and valid_df.num_rows:
            Xv, yv, _, _ = self._extract_training_arrays(valid_df)
            yv = np.searchsorted(classes, yv).astype(np.float32)
            valid = (Xv, yv)

        booster = self._run_batches(X, y, w, init, cfg, valid)
        model = LightGBMClassificationModel(booster)
        model.classes_ = classes.astype(np.float64)
        self._copy_model_params(model)
        return model

    def _run_batches(self, X, y, w, init, cfg, valid):
        """numBatches warm-started sequential fits (LightGBMBase.scala:39-64),
        instrumented with phase spans (LightGBMPerformance analog, §5.1)."""
        from ..core.logging import InstrumentationMeasures

        measures = InstrumentationMeasures()
        cats = self._categorical_indexes(self.get("slotNames"))
        init_model = None
        if self.get("modelString"):
            init_model = Booster.from_model_string(self.get("modelString"))
        nb = self.getNumBatches()
        if nb and nb > 1:
            rng = np.random.default_rng(self.getSeed())
            perm = rng.permutation(len(y))
            parts = np.array_split(perm, nb)
            bst = init_model
            for part in parts:
                bst = train_booster(X[part], y[part], cfg,
                                    sample_weight=None if w is None else w[part],
                                    init_score=None if init is None else init[part],
                                    categorical_features=cats, valid=valid,
                                    feature_names=self.get("slotNames"), init_model=bst,
                                    fobj=self.get("fobj"),
                                    mapper=self._reference_mapper(X[part]),
                                    measures=measures)
        else:
            bst = train_booster(X, y, cfg, sample_weight=w, init_score=init,
                                categorical_features=cats, valid=valid,
                                feature_names=self.get("slotNames"),
                                init_model=init_model, fobj=self.get("fobj"),
                                mapper=self._reference_mapper(X),
                                measures=measures)
        self._log_base("trainingMeasures", measures.report())
        return bst

    def _copy_model_params(self, model):
        for p in ("featuresCol", "predictionCol", "probabilityCol", "rawPredictionCol",
                  "leafPredictionCol", "featuresShapCol", "thresholds",
                  "predictDisableShapeCheck"):
            if self.hasParam(p) and model.hasParam(p) and self.isSet(p):
                model.set(p, self.get(p))


class LightGBMClassificationModel(_LightGBMModelBase, HasProbabilityCol, HasRawPredictionCol):
    thresholds = Param("thresholds", "Per-class prediction thresholds", list)

    classes_: Optional[np.ndarray] = None   # original label values, index = class id

    def _transform(self, df: Table) -> Table:
        X = self._predict_matrix(df)
        raw = self.booster.raw_score(X)
        prob = self.booster.predict(X)
        out = df
        if raw.ndim == 1:
            raw2 = np.stack([-raw, raw], axis=1)
            prob2 = np.stack([1 - prob, prob], axis=1)
        else:
            raw2, prob2 = raw, prob
        out = out.with_column(self.getRawPredictionCol(), raw2)
        out = out.with_column(self.getProbabilityCol(), prob2)
        th = self.get("thresholds")
        scaled = prob2 / np.asarray(th)[None, :] if th else prob2
        pred = np.argmax(scaled, 1)
        if self.classes_ is not None:
            pred = np.asarray(self.classes_)[pred]
        out = out.with_column(self.getPredictionCol(), pred.astype(np.float64))
        return self._maybe_extra_cols(out, X)

    def _save_extra(self, path: str) -> None:
        import os

        super()._save_extra(path)
        if self.classes_ is not None:
            np.save(os.path.join(path, "classes.npy"), np.asarray(self.classes_))

    def _load_extra(self, path: str) -> None:
        import os

        super()._load_extra(path)
        p = os.path.join(path, "classes.npy")
        if os.path.exists(p):
            self.classes_ = np.load(p)


# ---------------------------------------------------------------------------
# Regressor
# ---------------------------------------------------------------------------

class LightGBMRegressor(Estimator, _LightGBMParams):
    """GBDT regressor (reference: LightGBMRegressor.scala). Objectives:
    regression, regression_l1, huber, fair, poisson, quantile, mape, gamma,
    tweedie."""

    objective = Param("objective", "Regression objective", str, "regression")
    alpha = Param("alpha", "Huber/quantile alpha", float, 0.9)
    tweedieVariancePower = Param("tweedieVariancePower", "Tweedie variance power", float, 1.5)

    _run_batches = LightGBMClassifier._run_batches
    _copy_model_params = LightGBMClassifier._copy_model_params

    def _fit(self, df: Table) -> "LightGBMRegressionModel":
        train_df, valid_df = self._split_validation(df)
        X, y, w, init = self._extract_training_arrays(train_df)
        cfg = self._base_config(objective=self.getObjective(),
                                alpha=self.getAlpha(),
                                tweedie_variance_power=self.getTweedieVariancePower())
        valid = None
        if valid_df is not None and valid_df.num_rows:
            Xv, yv, _, _ = self._extract_training_arrays(valid_df)
            valid = (Xv, yv)
        booster = self._run_batches(X, y, w, init, cfg, valid)
        model = LightGBMRegressionModel(booster)
        self._copy_model_params(model)
        return model


class LightGBMRegressionModel(_LightGBMModelBase):
    def _transform(self, df: Table) -> Table:
        X = self._predict_matrix(df)
        out = df.with_column(self.getPredictionCol(), self.booster.predict(X).astype(np.float64))
        return self._maybe_extra_cols(out, X)


# ---------------------------------------------------------------------------
# Ranker
# ---------------------------------------------------------------------------

class LightGBMRanker(Estimator, _LightGBMParams, HasGroupCol):
    """LambdaRank GBDT (reference: LightGBMRanker.scala). Rows are re-sorted
    group-contiguously before training — the analog of the reference's
    repartitionForGroupColumn (LightGBMRanker.scala:88-116)."""

    objective = Param("objective", "Ranking objective", str, "lambdarank")
    maxPosition = Param("maxPosition", "NDCG truncation for optimization", int, 20)
    labelGain = Param("labelGain", "Relevance gains per label value", list)
    evalAt = Param("evalAt", "NDCG@k eval positions", list, [1, 2, 3, 4, 5])

    _copy_model_params = LightGBMClassifier._copy_model_params

    def _fit(self, df: Table) -> "LightGBMRankerModel":
        train_df, valid_df = self._split_validation(df)
        gcol = self.getGroupCol()
        train_df = train_df.sort_by(gcol)       # group-contiguous layout
        X, y, w, init = self._extract_training_arrays(train_df)
        groups = np.asarray(train_df[gcol])
        _, sizes = np.unique(groups, return_counts=True)
        cfg = self._base_config(objective="lambdarank",
                                lambdarank_truncation_level=self.getMaxPosition(),
                                eval_at=tuple(self.getEvalAt()),
                                label_gain=tuple(self.get("labelGain") or ()))
        valid = None
        if valid_df is not None and valid_df.num_rows:
            valid_df = valid_df.sort_by(gcol)
            Xv, yv, _, _ = self._extract_training_arrays(valid_df)
            _, sv = np.unique(np.asarray(valid_df[gcol]), return_counts=True)
            valid = (Xv, yv, None, sv)
        cats = self._categorical_indexes(self.get("slotNames"))
        booster = train_booster(X, y, cfg, sample_weight=w, init_score=init,
                                categorical_features=cats, group_sizes=sizes,
                                valid=valid, feature_names=self.get("slotNames"),
                                fobj=self.get("fobj"),
                                mapper=self._reference_mapper(X))
        model = LightGBMRankerModel(booster)
        self._copy_model_params(model)
        return model


class LightGBMRankerModel(_LightGBMModelBase):
    def _transform(self, df: Table) -> Table:
        X = self._predict_matrix(df)
        out = df.with_column(self.getPredictionCol(), self.booster.predict(X).astype(np.float64))
        return self._maybe_extra_cols(out, X)
