"""ONNXModel — batch inference Transformer over an imported ONNX graph.

Reference: deep-learning/.../onnx/ONNXModel.scala:145-423. Parity points:
``modelPayload`` bytes param; ``feedDict`` (onnx input ← table column) and
``fetchDict`` (output column ← onnx output, including *intermediate* tensors —
the model-slicing feature at ONNXModel.scala:203-227); mini-batched execution
(miniBatchSize); ``softMaxDict``/``argMaxDict`` post-transforms
(ONNXModel.scala:258-301). Where the reference creates an ORT session per
partition and runs batches through JNI, this imports the graph once into a
jitted XLA function and streams device-resident batches through it.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core.params import Param
from ..core.pipeline import Model as _Model, Transformer
from ..core.table import Table
from .importer import OnnxFunction, fold_constants
from .protoio import DTYPES, Model as ProtoModel


class ONNXModel(Transformer):
    modelPayload = Param("modelPayload", "Array of bytes containing the "
                         "serialized ONNX model", is_complex=True)
    feedDict = Param("feedDict", "map: ONNX input name -> table column",
                     is_complex=True)
    fetchDict = Param("fetchDict", "map: output column -> ONNX output name "
                      "(intermediate tensor names allowed)", is_complex=True)
    miniBatchSize = Param("miniBatchSize", "batch size for inference", int, 64)
    softMaxDict = Param("softMaxDict", "map: input col -> output col to "
                        "softmax", is_complex=True)
    argMaxDict = Param("argMaxDict", "map: input col -> output col to argmax",
                       is_complex=True)
    deviceType = Param("deviceType", "kept for API parity (CPU/CUDA there; "
                       "TPU via jax here)", str)
    optimizationLevel = Param("optimizationLevel", "kept for API parity; XLA "
                              "always optimizes", str, "ALL_OPT")
    floatPrecision = Param("floatPrecision", "float32 | bfloat16 — bfloat16 "
                           "runs matmuls/convs as bf16 MXU operands with f32 "
                           "accumulation (TPU mixed-precision inference)",
                           str, "float32")
    maxLoopTrips = Param("maxLoopTrips", "static iteration cap for runtime "
                         "ONNX Loop nodes whose trip count is data-dependent "
                         "AND that have scan outputs (XLA needs a static "
                         "buffer; outputs are zero-padded past the exit)",
                         int, 128)

    # class-level defaults so instances materialized by save/load or copy
    # (which bypass __init__) still lazy-init their caches
    _fn_cache: Optional[OnnxFunction] = None
    _runner_cache: Optional[dict] = None

    # --- model loading (reference setModelLocation / setModelPayload) ----
    def setModelPayload(self, payload: bytes) -> "ONNXModel":
        self._fn_cache = None
        self._runner_cache = {}
        return self.set("modelPayload", payload)

    def setModelLocation(self, path: str) -> "ONNXModel":
        with open(path, "rb") as f:
            return self.setModelPayload(f.read())

    def setFeedDict(self, d: Dict[str, str]) -> "ONNXModel":
        return self.set("feedDict", dict(d))

    def setFetchDict(self, d: Dict[str, str]) -> "ONNXModel":
        self._fn_cache = None
        return self.set("fetchDict", dict(d))

    def setSoftMaxDict(self, d: Dict[str, str]) -> "ONNXModel":
        return self.set("softMaxDict", dict(d))

    def setArgMaxDict(self, d: Dict[str, str]) -> "ONNXModel":
        return self.set("argMaxDict", dict(d))

    def setMiniBatchSize(self, v: int) -> "ONNXModel":
        return self.set("miniBatchSize", v)

    # --- introspection ---------------------------------------------------
    def _onnx_fn(self) -> OnnxFunction:
        # rebuild when floatPrecision changed through ANY setter route (the
        # cached function bakes the precision into its weights)
        if (self._fn_cache is not None
                and self._fn_cache.precision != self.getFloatPrecision()):
            self._fn_cache = None
            self._runner_cache = None
        if self._fn_cache is None:
            payload = self.get("modelPayload")
            if payload is None:
                raise ValueError("ONNXModel: modelPayload is not set")
            model = fold_constants(ProtoModel.parse(bytes(payload)))
            fetch = self.get("fetchDict") or {}
            outputs = sorted(fetch.values()) if fetch else None
            self._fn_cache = OnnxFunction(
                model, outputs, precision=self.getFloatPrecision(),
                max_loop_trips=self.get("maxLoopTrips"))
        return self._fn_cache

    def modelInput(self) -> Dict[str, dict]:
        fn = self._onnx_fn()
        return {n: {"shape": fn.input_info[n].shape if n in fn.input_info else None,
                    "dtype": np.dtype(DTYPES.get(
                        fn.input_info[n].elem_type, np.float32)).name
                    if n in fn.input_info else "float32"}
                for n in fn.graph_inputs}

    def modelOutput(self) -> List[str]:
        return list(self._onnx_fn().outputs)

    # --- execution -------------------------------------------------------
    def _transform(self, df: Table) -> Table:
        fn = self._onnx_fn()
        feed: Dict[str, str] = self.get("feedDict") or {
            n: n for n in fn.graph_inputs}
        fetch: Dict[str, str] = self.get("fetchDict") or {
            o: o for o in fn.outputs}
        out_of = {onnx_name: col for col, onnx_name in fetch.items()}

        # dtype coercion per declared graph input (coerceBatchedDf analog)
        cols: Dict[str, np.ndarray] = {}
        for onnx_name, col in feed.items():
            arr = df[col]
            if arr.dtype == object:
                arr = np.stack([np.asarray(v) for v in arr])
            vi = fn.input_info.get(onnx_name)
            want = DTYPES.get(vi.elem_type, np.float32) if vi else np.float32
            cols[onnx_name] = np.asarray(arr).astype(want, copy=False)

        n = df.num_rows
        bs = min(self.getMiniBatchSize(), max(n, 1))
        names = list(cols)

        out = df.copy()
        if n == 0:
            for o in fn.outputs:
                out[out_of.get(o, o)] = np.zeros((0,))
            return self._post_transforms(out)

        # mini-batched execution through the shared bucketed runner
        # (core/inference.py): full miniBatchSize chunks plus a bucket-padded
        # tail — the tail pads to a small shape ladder (padded rows are a
        # vectorized last-row gather, sliced back off the outputs) instead of
        # the old np.repeat duplication up to the full batch size, and each
        # bucket's XLA program compiles exactly once per model
        runner = self._runner_for(fn, names, bs)
        res = runner(*[cols[m] for m in names])
        if not isinstance(res, (tuple, list)):
            res = (res,)
        for o, r in zip(fn.outputs, res):
            out[out_of.get(o, o)] = np.asarray(r)
        return self._post_transforms(out)

    def _runner_for(self, fn: OnnxFunction, names: List[str],
                    batch_size: int):
        from ..core.inference import BucketedRunner

        if self._runner_cache is None:
            self._runner_cache = {}
        key = (tuple(names), tuple(fn.outputs), batch_size)
        if key not in self._runner_cache:
            self._runner_cache[key] = BucketedRunner(
                fn.as_jax(names)[0], max_batch_size=batch_size,
                name="onnx.model")
        return self._runner_cache[key]

    def _post_transforms(self, df: Table) -> Table:
        import jax

        for kind, mapping in (("softMaxDict", self.get("softMaxDict")),
                              ("argMaxDict", self.get("argMaxDict"))):
            for src, dst in (mapping or {}).items():
                if src not in df:
                    raise ValueError(
                        f"ONNXModel.{kind}: source column {src!r} not in the "
                        f"transformed output (columns: {df.columns}); update "
                        "the dict when changing fetchDict")
                if kind == "softMaxDict":
                    df = df.with_column(dst, np.asarray(jax.nn.softmax(
                        np.asarray(df[src], np.float32), axis=-1)))
                else:
                    df = df.with_column(dst, np.argmax(
                        np.asarray(df[src]), axis=-1).astype(np.float64))
        return df

    # persistence: the payload is a complex param, nothing extra needed
    def sliceAtOutput(self, output_name: str) -> "ONNXModel":
        """New ONNXModel fetching an intermediate tensor (headless-model
        helper; reference ONNXModel slicing + ImageFeaturizer headless mode)."""
        sliced = self.copy()
        sliced.setFetchDict({output_name: output_name})
        sliced.set("softMaxDict", None)  # post-ops referenced the old outputs
        sliced.set("argMaxDict", None)
        return sliced
