"""ONNX inference on TPU — importer, batch transformer, hub, featurizer.

Reference: deep-learning module ONNX components (ONNXModel.scala:145-423,
ONNXRuntime.scala:25-107, ONNXUtils.scala, ONNXHub.scala,
ImageFeaturizer.scala; SURVEY.md §2.4 / N5). The reference executes via ONNX
Runtime JNI sessions per Spark partition; here ONNX protobufs are parsed
directly (protoio.py — no onnx package needed), imported into pure JAX
functions (importer.py + ops.py registry), and executed as jitted XLA programs
with mini-batched, device-resident tensors.
"""

from .protoio import Attribute, Graph, Model, Node, Tensor, ValueInfo
from .importer import OnnxFunction, fold_constants, import_model
from .model import ONNXModel
from .hub import ONNXHub, ONNXModelInfo
from .featurizer import ImageFeaturizer
from .ops import REGISTRY as OP_REGISTRY

__all__ = [
    "Attribute", "Graph", "Model", "Node", "Tensor", "ValueInfo",
    "OnnxFunction", "fold_constants", "import_model",
    "ONNXModel", "ONNXHub", "ONNXModelInfo", "ImageFeaturizer",
    "OP_REGISTRY", "booster_to_onnx",
]


def __getattr__(name):
    # lazy: treeensemble pulls the gbdt package (and jax) — eager import
    # would defeat this package's jax-free import design (ops._jnp deferral)
    if name == "booster_to_onnx":
        from .treeensemble import booster_to_onnx

        return booster_to_onnx
    raise AttributeError(name)
