"""ONNX op → JAX implementations.

Coverage targets ResNet-class CNNs and BERT-class transformers first
(SURVEY.md §7 hard part 4), plus the elementwise/shape plumbing common in
exported graphs. Each impl takes (node, *input arrays) and returns one array
or a tuple. Everything is traceable: ops with shape-valued inputs (Reshape,
Slice, ...) require those inputs to be constants (initializers or Constant
nodes), which the importer folds before tracing — the standard static-shape
discipline for XLA.
"""

from __future__ import annotations

from typing import Callable, Dict

import numpy as np


def _jnp():
    import jax.numpy as jnp

    return jnp


REGISTRY: Dict[str, Callable] = {}


def op(*names):
    def deco(fn):
        for n in names:
            REGISTRY[n] = fn
        return fn

    return deco


def _static(x, name, node):
    """Shape-carrying inputs must be compile-time constants."""
    if hasattr(x, "aval") and not isinstance(x, np.ndarray):
        try:
            return np.asarray(x)
        except Exception:
            raise ValueError(
                f"{node.op_type} '{node.name}': input {name} must be a "
                "constant (initializer / Constant node) for XLA static shapes")
    return np.asarray(x)


# --- elementwise -----------------------------------------------------------

@op("Add")
def _add(node, a, b):
    return a + b


@op("Sub")
def _sub(node, a, b):
    return a - b


@op("Mul")
def _mul(node, a, b):
    return a * b


@op("Div")
def _div(node, a, b):
    return a / b


@op("Pow")
def _pow(node, a, b):
    return a ** b


@op("Neg")
def _neg(node, a):
    return -a


@op("Sqrt")
def _sqrt(node, a):
    return _jnp().sqrt(a)


@op("Exp")
def _exp(node, a):
    return _jnp().exp(a)


@op("Log")
def _log(node, a):
    return _jnp().log(a)


@op("Abs")
def _abs(node, a):
    return _jnp().abs(a)


@op("Erf")
def _erf(node, a):
    import jax

    return jax.scipy.special.erf(a)


@op("Relu")
def _relu(node, a):
    return _jnp().maximum(a, 0)


@op("LeakyRelu")
def _leaky(node, a):
    alpha = node.attr("alpha", 0.01)
    return _jnp().where(a >= 0, a, alpha * a)


@op("Sigmoid")
def _sigmoid(node, a):
    import jax

    return jax.nn.sigmoid(a)


@op("Tanh")
def _tanh(node, a):
    return _jnp().tanh(a)


@op("Gelu")
def _gelu(node, a):
    import jax

    return jax.nn.gelu(a, approximate=node.attr("approximate", "none") != "none")


@op("Clip")
def _clip(node, a, *mm):
    jnp = _jnp()
    lo = mm[0] if len(mm) > 0 else node.attr("min")
    hi = mm[1] if len(mm) > 1 else node.attr("max")
    return jnp.clip(a, lo, hi)


@op("Min")
def _min(node, *xs):
    jnp = _jnp()
    out = xs[0]
    for x in xs[1:]:
        out = jnp.minimum(out, x)
    return out


@op("Max")
def _max(node, *xs):
    jnp = _jnp()
    out = xs[0]
    for x in xs[1:]:
        out = jnp.maximum(out, x)
    return out


@op("Sum")
def _sum(node, *xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@op("Where")
def _where(node, c, a, b):
    return _jnp().where(c, a, b)


@op("Equal")
def _equal(node, a, b):
    return a == b


@op("Greater")
def _greater(node, a, b):
    return a > b


@op("Less")
def _less(node, a, b):
    return a < b


@op("Not")
def _not(node, a):
    return ~a


@op("Cast")
def _cast(node, a):
    from .protoio import DTYPES

    return a.astype(DTYPES[node.attr("to")])


@op("Identity", "Dropout")
def _identity(node, a, *rest):
    return a


# --- reductions / normalization -------------------------------------------

def _axes(node, extra_inputs, rank):
    axes = node.attr("axes")
    if axes is None and extra_inputs:
        axes = [int(v) for v in np.asarray(extra_inputs[0]).ravel()]
    if axes is None:
        axes = list(range(rank))
    return tuple(int(a) % rank for a in axes)


@op("ReduceMean")
def _rmean(node, a, *rest):
    keep = bool(node.attr("keepdims", 1))
    return _jnp().mean(a, axis=_axes(node, rest, a.ndim), keepdims=keep)


@op("ReduceSum")
def _rsum(node, a, *rest):
    keep = bool(node.attr("keepdims", 1))
    return _jnp().sum(a, axis=_axes(node, rest, a.ndim), keepdims=keep)


@op("ReduceMax")
def _rmax(node, a, *rest):
    keep = bool(node.attr("keepdims", 1))
    return _jnp().max(a, axis=_axes(node, rest, a.ndim), keepdims=keep)


@op("Softmax")
def _softmax(node, a):
    import jax

    return jax.nn.softmax(a, axis=node.attr("axis", -1))


@op("LogSoftmax")
def _logsoftmax(node, a):
    import jax

    return jax.nn.log_softmax(a, axis=node.attr("axis", -1))


@op("ArgMax")
def _argmax(node, a):
    axis = node.attr("axis", 0)
    keep = bool(node.attr("keepdims", 1))
    out = _jnp().argmax(a, axis=axis)
    return _jnp().expand_dims(out, axis) if keep else out


@op("LayerNormalization")
def _layernorm(node, x, scale, bias=None):
    jnp = _jnp()
    # ONNX: normalization runs over axes [axis .. rank-1], not just `axis`
    axis = node.attr("axis", -1) % x.ndim
    axes = tuple(range(axis, x.ndim))
    eps = node.attr("epsilon", 1e-5)
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) / jnp.sqrt(var + eps) * scale
    return out + bias if bias is not None else out


@op("BatchNormalization")
def _batchnorm(node, x, scale, bias, mean, var):
    jnp = _jnp()
    eps = node.attr("epsilon", 1e-5)
    shape = [1, -1] + [1] * (x.ndim - 2)  # params along channel dim (NCHW)
    return ((x - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + eps)
            * scale.reshape(shape) + bias.reshape(shape))


# --- matmul / linear -------------------------------------------------------

@op("MatMul")
def _matmul(node, a, b):
    return _jnp().matmul(a, b)


@op("Gemm")
def _gemm(node, a, b, c=None):
    jnp = _jnp()
    alpha = node.attr("alpha", 1.0)
    beta = node.attr("beta", 1.0)
    if node.attr("transA", 0):
        a = a.T
    if node.attr("transB", 0):
        b = b.T
    out = alpha * (a @ b)
    if c is not None:
        out = out + beta * c
    return out


@op("Einsum")
def _einsum(node, *xs):
    return _jnp().einsum(node.attr("equation"), *xs)


# --- conv / pool (NCHW, matching ONNX layout) ------------------------------

def _conv_pads(node, spatial):
    pads = node.attr("pads")
    auto = node.attr("auto_pad", "NOTSET")
    if pads is not None:
        half = len(pads) // 2
        return [(pads[i], pads[i + half]) for i in range(half)], auto
    return [(0, 0)] * spatial, auto


def _same_pads(in_sizes, kernel, strides, dils, lower: bool):
    """Explicit SAME padding; SAME_LOWER puts the odd element at the start
    (XLA's 'SAME' string is SAME_UPPER, so SAME_LOWER needs explicit pads)."""
    out = []
    for size, k, s, d in zip(in_sizes, kernel, strides, dils):
        eff = (k - 1) * d + 1
        total = max((int(np.ceil(size / s)) - 1) * s + eff - size, 0)
        small, big = total // 2, total - total // 2
        out.append((big, small) if lower else (small, big))
    return out


@op("Conv")
def _conv(node, x, w, b=None, *, preferred=None):
    import jax

    jnp = _jnp()
    spatial = x.ndim - 2
    strides = node.attr("strides", [1] * spatial)
    dil = node.attr("dilations", [1] * spatial)
    groups = node.attr("group", 1)
    pads, auto = _conv_pads(node, spatial)
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        padding = _same_pads(x.shape[2:], w.shape[2:], strides, dil,
                             lower=(auto == "SAME_LOWER"))
    else:
        padding = pads
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape,
        ("NCHW", "OIHW", "NCHW") if spatial == 2 else
        ("NCW", "OIW", "NCW") if spatial == 1 else
        ("NCDHW", "OIDHW", "NCDHW"))
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        rhs_dilation=dil, dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=preferred or jnp.float32)
    if b is not None:
        out = out + b.reshape((1, -1) + (1,) * spatial)
    return out


def _pool(node, x, kind):
    import jax

    jnp = _jnp()
    spatial = x.ndim - 2
    k = node.attr("kernel_shape")
    strides = node.attr("strides", [1] * spatial)
    pads, auto = _conv_pads(node, spatial)
    window = (1, 1) + tuple(k)
    strides_full = (1, 1) + tuple(strides)
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        padding = [(0, 0), (0, 0)] + _same_pads(
            x.shape[2:], k, strides, [1] * spatial,
            lower=(auto == "SAME_LOWER"))
    else:
        padding = [(0, 0), (0, 0)] + pads
    if kind == "max":
        return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window,
                                     strides_full, padding)
    ones = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add, window,
                                 strides_full, padding)
    s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides_full,
                              padding)
    if node.attr("count_include_pad", 0):
        return s / float(np.prod(k))
    return s / ones


@op("MaxPool")
def _maxpool(node, x):
    return _pool(node, x, "max")


@op("AveragePool")
def _avgpool(node, x):
    return _pool(node, x, "avg")


@op("GlobalAveragePool")
def _gap(node, x):
    return _jnp().mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)


@op("GlobalMaxPool")
def _gmp(node, x):
    return _jnp().max(x, axis=tuple(range(2, x.ndim)), keepdims=True)


# --- shape plumbing --------------------------------------------------------

@op("Reshape")
def _reshape(node, x, shape):
    shape = [int(v) for v in _static(shape, "shape", node).ravel()]
    # ONNX: 0 means copy input dim
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return x.reshape(shape)


@op("Flatten")
def _flatten(node, x):
    axis = node.attr("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis else 1
    return x.reshape(lead, -1)


@op("Transpose")
def _transpose(node, x):
    perm = node.attr("perm", list(range(x.ndim))[::-1])
    return _jnp().transpose(x, perm)


@op("Concat")
def _concat(node, *xs):
    return _jnp().concatenate(xs, axis=node.attr("axis", 0))


@op("Split")
def _split(node, x, *rest):
    jnp = _jnp()
    axis = node.attr("axis", 0)
    splits = node.attr("split")
    if splits is None and rest:
        splits = [int(v) for v in _static(rest[0], "split", node).ravel()]
    if splits is None:
        n_out = len(node.outputs)
        return tuple(jnp.split(x, n_out, axis=axis))
    idx = np.cumsum(splits)[:-1].tolist()
    return tuple(jnp.split(x, idx, axis=axis))


@op("Squeeze")
def _squeeze(node, x, *rest):
    axes = node.attr("axes")
    if axes is None and rest:
        axes = [int(v) for v in _static(rest[0], "axes", node).ravel()]
    if axes is None:
        return _jnp().squeeze(x)
    return _jnp().squeeze(x, axis=tuple(int(a) % x.ndim for a in axes))


@op("Unsqueeze")
def _unsqueeze(node, x, *rest):
    axes = node.attr("axes")
    if axes is None and rest:
        axes = [int(v) for v in _static(rest[0], "axes", node).ravel()]
    out = x
    for a in sorted(int(a) for a in axes):
        out = _jnp().expand_dims(out, a)
    return out


@op("Gather")
def _gather(node, x, idx):
    return _jnp().take(x, idx.astype("int32"), axis=node.attr("axis", 0))


@op("Slice")
def _slice(node, x, *rest):
    if rest:  # opset >= 10: starts/ends/axes/steps as inputs
        starts = [int(v) for v in _static(rest[0], "starts", node).ravel()]
        ends = [int(v) for v in _static(rest[1], "ends", node).ravel()]
        axes = ([int(v) for v in _static(rest[2], "axes", node).ravel()]
                if len(rest) > 2 else list(range(len(starts))))
        steps = ([int(v) for v in _static(rest[3], "steps", node).ravel()]
                 if len(rest) > 3 else [1] * len(starts))
    else:
        starts = node.attr("starts")
        ends = node.attr("ends")
        axes = node.attr("axes", list(range(len(starts))))
        steps = [1] * len(starts)
    sl = [slice(None)] * x.ndim
    for s, e, a, st in zip(starts, ends, axes, steps):
        sl[int(a) % x.ndim] = slice(s, None if e >= 2 ** 31 - 1 else e, st)
    return x[tuple(sl)]


@op("Expand")
def _expand(node, x, shape):
    jnp = _jnp()
    shape = [int(v) for v in _static(shape, "shape", node).ravel()]
    # ONNX Expand = broadcast with 1s allowed on either side
    target = list(np.broadcast_shapes(tuple(x.shape), tuple(shape)))
    return jnp.broadcast_to(x, target)


@op("Shape")
def _shape(node, x):
    return np.asarray(x.shape, dtype=np.int64)


@op("Constant")
def _constant(node):
    t = node.attr("value")
    if t is not None:
        return t.array()
    for k in ("value_float", "value_int"):
        v = node.attr(k)
        if v is not None:
            return np.asarray(v)
    raise ValueError(f"Constant node {node.name}: no value attribute")


@op("ConstantOfShape")
def _const_of_shape(node, shape):
    shape = [int(v) for v in _static(shape, "shape", node).ravel()]
    t = node.attr("value")
    fill = t.array().ravel()[0] if t is not None else np.float32(0)
    return _jnp().full(shape, fill, dtype=np.asarray(fill).dtype)


@op("Pad")
def _pad(node, x, *rest):
    jnp = _jnp()
    pads = node.attr("pads")
    if pads is None and rest:
        pads = [int(v) for v in _static(rest[0], "pads", node).ravel()]
    value = node.attr("value", 0.0)
    if len(rest) > 1 and rest[1] is not None:  # '' input name -> None (skipped)
        value = float(np.asarray(rest[1]).ravel()[0])
    half = len(pads) // 2
    if len(rest) > 2 and rest[2] is not None:  # opset-18 axes input
        axes = [int(a) % x.ndim
                for a in _static(rest[2], "axes", node).ravel()]
        widths = [(0, 0)] * x.ndim
        for j, a in enumerate(axes):
            widths[a] = (pads[j], pads[j + half])
    else:
        widths = [(pads[i], pads[i + half]) for i in range(half)]
    mode = node.attr("mode", "constant")
    if mode == "constant":
        return jnp.pad(x, widths, constant_values=value)
    return jnp.pad(x, widths, mode={"reflect": "reflect", "edge": "edge"}[mode])


@op("Tile")
def _tile(node, x, reps):
    reps = [int(v) for v in _static(reps, "repeats", node).ravel()]
    return _jnp().tile(x, reps)


@op("Range")
def _range(node, start, limit, delta):
    s = float(np.asarray(start).ravel()[0])
    l = float(np.asarray(limit).ravel()[0])
    d = float(np.asarray(delta).ravel()[0])
    return np.arange(s, l, d).astype(np.asarray(start).dtype)


@op("Resize")
def _resize(node, x, *rest):
    """Nearest/linear resize (scales or sizes input); enough for CNN heads."""
    import jax

    jnp = _jnp()
    # inputs: roi (ignored), scales, sizes
    sizes = None
    if len(rest) >= 3 and rest[2] is not None:
        sizes = [int(v) for v in _static(rest[2], "sizes", node).ravel()]
    elif len(rest) >= 2 and rest[1] is not None and np.asarray(rest[1]).size:
        scales = np.asarray(_static(rest[1], "scales", node)).ravel()
        sizes = [int(round(s * d)) for s, d in zip(scales, x.shape)]
    if sizes is None:
        raise ValueError("Resize: needs scales or sizes")
    method = {"nearest": "nearest", "linear": "linear", "cubic": "cubic"}[
        node.attr("mode", "nearest")]
    return jax.image.resize(x, sizes, method=method)


# --- extended coverage: UNet / EfficientNet / detection-class graphs --------

@op("Reciprocal")
def _reciprocal(node, x):
    return 1.0 / x


@op("Floor")
def _floor(node, x):
    return _jnp().floor(x)


@op("Ceil")
def _ceil(node, x):
    return _jnp().ceil(x)


@op("Round")
def _round(node, x):
    return _jnp().round(x)


@op("Sin")
def _sin(node, x):
    return _jnp().sin(x)


@op("Cos")
def _cos(node, x):
    return _jnp().cos(x)


@op("Mod")
def _mod(node, a, b):
    if node.attr("fmod", 0):
        return _jnp().fmod(a, b)
    return _jnp().mod(a, b)


@op("And")
def _and(node, a, b):
    return a & b


@op("Or")
def _or(node, a, b):
    return a | b


@op("Xor")
def _xor(node, a, b):
    return a ^ b


@op("PRelu")
def _prelu(node, x, slope):
    return _jnp().where(x >= 0, x, slope * x)


@op("Elu")
def _elu(node, x):
    alpha = node.attr("alpha", 1.0)
    jnp = _jnp()
    return jnp.where(x >= 0, x, alpha * (jnp.exp(x) - 1.0))


@op("Selu")
def _selu(node, x):
    alpha = node.attr("alpha", 1.67326319217681884765625)
    gamma = node.attr("gamma", 1.05070102214813232421875)
    jnp = _jnp()
    return gamma * jnp.where(x >= 0, x, alpha * (jnp.exp(x) - 1.0))


@op("HardSigmoid")
def _hardsigmoid(node, x):
    alpha = node.attr("alpha", 0.2)
    beta = node.attr("beta", 0.5)
    return _jnp().clip(alpha * x + beta, 0.0, 1.0)


@op("HardSwish")
def _hardswish(node, x):
    # onnx HardSwish: x * HardSigmoid(x; 1/6, 0.5)
    return x * _jnp().clip(x / 6.0 + 0.5, 0.0, 1.0)


@op("Softplus")
def _softplus(node, x):
    import jax

    return jax.nn.softplus(x)


@op("ReduceMin")
def _reduce_min(node, x, *rest):
    keep = bool(node.attr("keepdims", 1))
    return _jnp().min(x, axis=_axes(node, rest, x.ndim), keepdims=keep)


@op("ReduceProd")
def _reduce_prod(node, x, *rest):
    keep = bool(node.attr("keepdims", 1))
    return _jnp().prod(x, axis=_axes(node, rest, x.ndim), keepdims=keep)


@op("ReduceL2")
def _reduce_l2(node, x, *rest):
    keep = bool(node.attr("keepdims", 1))
    jnp = _jnp()
    return jnp.sqrt(jnp.sum(x * x, axis=_axes(node, rest, x.ndim),
                            keepdims=keep))


@op("ArgMin")
def _argmin(node, x):
    if node.attr("select_last_index", 0):
        raise ValueError("ArgMin: select_last_index not supported")
    axis = node.attr("axis", 0)
    keep = bool(node.attr("keepdims", 1))
    out = _jnp().argmin(x, axis=axis)
    return _jnp().expand_dims(out, axis) if keep else out


@op("CumSum")
def _cumsum(node, x, axis):
    ax = int(np.asarray(_static(axis, "axis", node)).ravel()[0])
    jnp = _jnp()
    if node.attr("reverse", 0):
        x = jnp.flip(x, ax)
    out = jnp.cumsum(x, axis=ax)
    if node.attr("exclusive", 0):
        out = jnp.roll(out, 1, ax)
        idx = [slice(None)] * out.ndim
        idx[ax] = 0
        out = out.at[tuple(idx)].set(0)
    if node.attr("reverse", 0):
        out = jnp.flip(out, ax)
    return out


@op("OneHot")
def _onehot(node, indices, depth, values):
    jnp = _jnp()
    d = int(np.asarray(_static(depth, "depth", node)).ravel()[0])
    axis = node.attr("axis", -1)
    off, on = values[0], values[1]
    raw = jnp.asarray(indices).astype(jnp.int32)
    idx = jnp.where(raw < 0, raw + d, raw)     # negatives wrap once (spec)
    in_range = (idx >= 0) & (idx < d)
    oh = _one_hot_at_axis(jnp.where(in_range, idx, 0), d, axis)
    # out-of-range indices produce an all-off row (spec), not a wrapped hot
    oh = oh * jnp.expand_dims(in_range, axis if axis >= 0 else oh.ndim + axis
                              ).astype(oh.dtype)
    # output dtype follows the values tensor (spec)
    return (oh * (on - off) + off).astype(np.asarray(values).dtype)


def _one_hot_at_axis(idx, depth, axis):
    import jax

    oh = jax.nn.one_hot(idx, depth)                    # appended last axis
    if axis != -1 and axis != oh.ndim - 1:
        oh = _jnp().moveaxis(oh, -1, axis if axis >= 0 else axis + oh.ndim)
    return oh


@op("TopK")
def _topk(node, x, k):
    import jax

    jnp = _jnp()
    kk = int(np.asarray(_static(k, "k", node)).ravel()[0])
    axis = node.attr("axis", -1)
    largest = bool(node.attr("largest", 1))
    xm = jnp.moveaxis(x, axis, -1)
    vals, idx = jax.lax.top_k(xm if largest else -xm, kk)
    if not largest:
        vals = -vals
    return (jnp.moveaxis(vals, -1, axis),
            jnp.moveaxis(idx.astype(jnp.int64), -1, axis))


@op("Trilu")
def _trilu(node, x, k=None):
    jnp = _jnp()
    kk = int(np.asarray(_static(k, "k", node)).ravel()[0]) if k is not None else 0
    if node.attr("upper", 1):
        return jnp.triu(x, kk)
    return jnp.tril(x, kk)


@op("DepthToSpace")
def _depth_to_space(node, x):
    b = node.attr("blocksize")
    n, c, h, w = x.shape
    jnp = _jnp()
    if node.attr("mode", "DCR") == "DCR":
        t = x.reshape(n, b, b, c // (b * b), h, w)
        t = t.transpose(0, 3, 4, 1, 5, 2)
    else:  # CRD
        t = x.reshape(n, c // (b * b), b, b, h, w)
        t = t.transpose(0, 1, 4, 2, 5, 3)
    return t.reshape(n, c // (b * b), h * b, w * b)


@op("SpaceToDepth")
def _space_to_depth(node, x):
    b = node.attr("blocksize")
    n, c, h, w = x.shape
    t = x.reshape(n, c, h // b, b, w // b, b)
    t = t.transpose(0, 3, 5, 1, 2, 4)
    return t.reshape(n, c * b * b, h // b, w // b)


@op("InstanceNormalization")
def _instance_norm(node, x, scale, bias):
    jnp = _jnp()
    eps = node.attr("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = x.mean(axis=axes, keepdims=True)
    var = ((x - mean) ** 2).mean(axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mean) / jnp.sqrt(var + eps) * scale.reshape(shape) \
        + bias.reshape(shape)


@op("GroupNormalization")
def _group_norm(node, x, scale, bias):
    jnp = _jnp()
    eps = node.attr("epsilon", 1e-5)
    g = node.attr("num_groups")
    n, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    t = x.reshape((n, g, c // g) + spatial)
    axes = tuple(range(2, t.ndim))
    mean = t.mean(axis=axes, keepdims=True)
    var = ((t - mean) ** 2).mean(axis=axes, keepdims=True)
    t = (t - mean) / jnp.sqrt(var + eps)
    t = t.reshape((n, c) + spatial)
    if scale.shape[0] == g and g != c:
        # opset 18-20: per-GROUP scale/bias, broadcast over the group's channels
        scale = jnp.repeat(scale, c // g)
        bias = jnp.repeat(bias, c // g)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return t * scale.reshape(shape) + bias.reshape(shape)


@op("ConvTranspose")
def _conv_transpose(node, x, w, b=None):
    import jax

    jnp = _jnp()
    spatial = x.ndim - 2
    strides = node.attr("strides", [1] * spatial)
    dil = node.attr("dilations", [1] * spatial)
    groups = node.attr("group", 1)
    pads = node.attr("pads", [0] * (2 * spatial))
    out_pad = node.attr("output_padding", [0] * spatial)
    if groups != 1:
        raise ValueError("ConvTranspose: group > 1 not supported")
    if node.attr("auto_pad", "NOTSET") not in ("NOTSET", "VALID"):
        raise ValueError("ConvTranspose: auto_pad SAME_* not supported "
                         "(export with explicit pads)")
    if node.attr("output_shape") is not None:
        raise ValueError("ConvTranspose: output_shape attribute not supported "
                         "(use pads/output_padding)")
    # onnx W is (Cin, Cout/groups, *k); gradient-style transposed conv:
    # lhs_dilation = strides, effective padding = k - 1 - pad
    k = w.shape[2:]
    half = len(pads) // 2
    padding = []
    for i in range(spatial):
        eff = (k[i] - 1) * dil[i]
        padding.append((eff - pads[i], eff - pads[i + half] + out_pad[i]))
    wt = jnp.swapaxes(w, 0, 1)                     # (Cout, Cin, *k)
    wt = jnp.flip(wt, axis=tuple(range(2, wt.ndim)))
    dn = jax.lax.conv_dimension_numbers(
        x.shape, wt.shape,
        ("NCHW", "OIHW", "NCHW") if spatial == 2 else
        ("NCW", "OIW", "NCW") if spatial == 1 else
        ("NCDHW", "OIDHW", "NCDHW"))
    out = jax.lax.conv_general_dilated(
        x, wt, window_strides=[1] * spatial, padding=padding,
        lhs_dilation=strides, rhs_dilation=dil, dimension_numbers=dn,
        preferred_element_type=jnp.float32)
    if b is not None:
        out = out + b.reshape((1, -1) + (1,) * spatial)
    return out


# --- recurrent (RNN / GRU / LSTM) ------------------------------------------
# The reference's onnxruntime backend executes exported recurrent models
# (ONNXModel.scala); here each cell is a lax.scan over the sequence axis —
# XLA-friendly static control flow, one fused step program per direction.
# Layouts follow the ONNX spec: X (seq, batch, input); W (dirs, G*hidden,
# input); R (dirs, G*hidden, hidden); B (dirs, 2*G*hidden);
# Y (seq, dirs, batch, hidden); Y_h (dirs, batch, hidden).

def _rnn_direction_inputs(node, x, seq_lens):
    if seq_lens is not None:
        raise ValueError(f"{node.op_type} '{node.name}': sequence_lens is "
                         "not supported (pad to a static length)")
    if node.attr("layout", 0) != 0:
        raise ValueError(f"{node.op_type} '{node.name}': layout=1 is not "
                         "supported")
    direction = node.attr("direction", b"forward")
    if isinstance(direction, bytes):
        direction = direction.decode()
    dirs = {"forward": [False], "reverse": [True],
            "bidirectional": [False, True]}[direction]
    return dirs


def _rnn_scan(step, x, h0, reverse):
    """Run one direction; x (seq, batch, in) → (ys (seq, batch, hid), hT)."""
    from jax import lax

    xs = x[::-1] if reverse else x
    hT, ys = lax.scan(step, h0, xs)
    return (ys[::-1] if reverse else ys), hT


def _rnn_act(name, default, node, clip=None, alpha=None, beta=None):
    """Activation by ONNX name; ``clip`` (the op's cell-clip threshold)
    clamps the pre-activation, matching onnxruntime. ``alpha``/``beta``
    come from the node's activation_alpha/activation_beta lists."""
    import jax

    jnp = _jnp()
    if name is None:
        name = default
    if isinstance(name, bytes):
        name = name.decode()
    a = 0.2 if alpha is None else float(alpha)
    b = 0.5 if beta is None else float(beta)
    table = {"Sigmoid": jax.nn.sigmoid,
             "Tanh": jnp.tanh,
             "Relu": lambda v: jnp.maximum(v, 0.0),
             "LeakyRelu": lambda v: jnp.where(
                 v >= 0, v, (0.01 if alpha is None else float(alpha)) * v),
             "HardSigmoid": lambda v: jnp.clip(a * v + b, 0.0, 1.0)}
    if name not in table:
        raise ValueError(
            f"{node.op_type} '{node.name}': activation {name!r} is not "
            f"supported (supported: {sorted(table)})")
    act = table[name]
    if clip is not None:
        c = float(clip)
        return lambda v: act(jnp.clip(v, -c, c))
    return act


def _act_param(node, attr, i):
    vals = node.attr(attr) or []
    return vals[i] if i < len(vals) else None


@op("RNN")
def _rnn(node, x, w, r, b=None, seq_lens=None, initial_h=None):
    jnp = _jnp()
    dirs = _rnn_direction_inputs(node, x, seq_lens)
    hidden = node.attr("hidden_size", r.shape[-1])
    acts = node.attr("activations") or []
    clip = node.attr("clip")
    batch = x.shape[1]
    ys_all, hT_all = [], []
    for d, reverse in enumerate(dirs):
        Wd, Rd = w[d], r[d]
        bias = (b[d][:hidden] + b[d][hidden:]) if b is not None else 0.0
        f = _rnn_act(acts[d] if d < len(acts) else None, "Tanh", node, clip,
                     _act_param(node, "activation_alpha", d),
                     _act_param(node, "activation_beta", d))
        h0 = (initial_h[d] if initial_h is not None
              else jnp.zeros((batch, hidden), x.dtype))

        def step(h, xt, Wd=Wd, Rd=Rd, bias=bias, f=f):
            h = f(xt @ Wd.T + h @ Rd.T + bias)
            return h, h

        ys, hT = _rnn_scan(step, x, h0, reverse)
        ys_all.append(ys)
        hT_all.append(hT)
    y = jnp.stack(ys_all, axis=1)               # (seq, dirs, batch, hidden)
    return y, jnp.stack(hT_all, axis=0)


@op("GRU")
def _gru(node, x, w, r, b=None, seq_lens=None, initial_h=None):
    jnp = _jnp()
    dirs = _rnn_direction_inputs(node, x, seq_lens)
    hidden = node.attr("hidden_size", r.shape[-1])
    lbr = node.attr("linear_before_reset", 0)
    acts = node.attr("activations") or []
    clip = node.attr("clip")
    batch = x.shape[1]
    ys_all, hT_all = [], []
    for d, reverse in enumerate(dirs):
        Wd, Rd = w[d], r[d]                     # (3H, in), (3H, H); z,r,h
        Wb = b[d][: 3 * hidden] if b is not None else jnp.zeros(3 * hidden,
                                                                x.dtype)
        Rb = b[d][3 * hidden:] if b is not None else jnp.zeros(3 * hidden,
                                                               x.dtype)
        f = _rnn_act(acts[2 * d] if 2 * d < len(acts) else None, "Sigmoid",
                     node, clip, _act_param(node, "activation_alpha", 2 * d),
                     _act_param(node, "activation_beta", 2 * d))
        g = _rnn_act(acts[2 * d + 1] if 2 * d + 1 < len(acts) else None,
                     "Tanh", node, clip,
                     _act_param(node, "activation_alpha", 2 * d + 1),
                     _act_param(node, "activation_beta", 2 * d + 1))
        h0 = (initial_h[d] if initial_h is not None
              else jnp.zeros((batch, hidden), x.dtype))
        H = hidden

        def step(h, xt, Wd=Wd, Rd=Rd, Wb=Wb, Rb=Rb, f=f, g=g, H=H):
            gx = xt @ Wd.T + Wb                  # (batch, 3H)
            gr = h @ Rd.T
            z = f(gx[:, :H] + gr[:, :H] + Rb[:H])
            rt = f(gx[:, H:2 * H] + gr[:, H:2 * H] + Rb[H:2 * H])
            if lbr:   # torch exports linear_before_reset=1
                hh = g(gx[:, 2 * H:] + rt * (gr[:, 2 * H:] + Rb[2 * H:]))
            else:
                hh = g(gx[:, 2 * H:] + (rt * h) @ Rd[2 * H:].T + Rb[2 * H:])
            h = (1.0 - z) * hh + z * h
            return h, h

        ys, hT = _rnn_scan(step, x, h0, reverse)
        ys_all.append(ys)
        hT_all.append(hT)
    return jnp.stack(ys_all, axis=1), jnp.stack(hT_all, axis=0)


@op("LSTM")
def _lstm(node, x, w, r, b=None, seq_lens=None, initial_h=None,
          initial_c=None, p=None):
    jnp = _jnp()
    dirs = _rnn_direction_inputs(node, x, seq_lens)
    hidden = node.attr("hidden_size", r.shape[-1])
    acts = node.attr("activations") or []
    clip = node.attr("clip")
    if node.attr("input_forget", 0):
        raise ValueError(f"LSTM '{node.name}': input_forget=1 is not "
                         "supported")
    batch = x.shape[1]
    ys_all, hT_all, cT_all = [], [], []
    for d, reverse in enumerate(dirs):
        Wd, Rd = w[d], r[d]                     # (4H, in); gate order i,o,f,c
        bias = ((b[d][: 4 * hidden] + b[d][4 * hidden:])
                if b is not None else 0.0)
        pe = p[d] if p is not None else jnp.zeros(3 * hidden, x.dtype)
        f_ = _rnn_act(acts[3 * d] if 3 * d < len(acts) else None, "Sigmoid",
                      node, clip, _act_param(node, "activation_alpha", 3 * d),
                      _act_param(node, "activation_beta", 3 * d))
        g_ = _rnn_act(acts[3 * d + 1] if 3 * d + 1 < len(acts) else None,
                      "Tanh", node, clip,
                      _act_param(node, "activation_alpha", 3 * d + 1),
                      _act_param(node, "activation_beta", 3 * d + 1))
        h_ = _rnn_act(acts[3 * d + 2] if 3 * d + 2 < len(acts) else None,
                      "Tanh", node, clip,
                      _act_param(node, "activation_alpha", 3 * d + 2),
                      _act_param(node, "activation_beta", 3 * d + 2))
        h0 = (initial_h[d] if initial_h is not None
              else jnp.zeros((batch, hidden), x.dtype))
        c0 = (initial_c[d] if initial_c is not None
              else jnp.zeros((batch, hidden), x.dtype))
        H = hidden

        def step(carry, xt, Wd=Wd, Rd=Rd, bias=bias, pe=pe,
                 f_=f_, g_=g_, h_=h_, H=H):
            h, c = carry
            gates = xt @ Wd.T + h @ Rd.T + bias  # (batch, 4H) i,o,f,c
            # peephole tensor P is concatenated [Pi, Po, Pf] (ONNX spec)
            i = f_(gates[:, :H] + pe[:H] * c)
            o_pre = gates[:, H:2 * H]
            fg = f_(gates[:, 2 * H:3 * H] + pe[2 * H:] * c)
            ct = g_(gates[:, 3 * H:])
            c = fg * c + i * ct
            o = f_(o_pre + pe[H:2 * H] * c)
            h = o * h_(c)
            return (h, c), h

        ys, (hT, cT) = _rnn_scan(step, x, (h0, c0), reverse)
        ys_all.append(ys)
        hT_all.append(hT)
        cT_all.append(cT)
    return (jnp.stack(ys_all, axis=1), jnp.stack(hT_all, axis=0),
            jnp.stack(cT_all, axis=0))


# --- ai.onnx.ml tree ensembles ---------------------------------------------
# The reference ecosystem's documented GBDT-serving path is LightGBM ->
# onnxmltools (TreeEnsembleClassifier/Regressor, ai.onnx.ml domain) ->
# ONNXModel (reference: website Quickstart - ONNX Model Inference.md, which
# pip-installs onnxmltools and calls convert_lightgbm). These impls execute
# such graphs natively: the static node tables are preprocessed host-side at
# trace time into flat arrays, and traversal is a depth-bounded vectorized
# gather loop over (batch, tree) — no data-dependent Python control flow, so
# the whole ensemble jits into one XLA program.

_TREE_MODES = {"LEAF": 0, "BRANCH_LEQ": 1, "BRANCH_LT": 2, "BRANCH_GTE": 3,
               "BRANCH_GT": 4, "BRANCH_EQ": 5, "BRANCH_NEQ": 6}


def _tree_tables(node):
    """Flatten the node attribute lists into global arrays + per-tree roots.
    Returns (feat, value, mode, true_g, false_g, miss_true, roots, depth,
    gidx map) — all numpy (static)."""
    tids = np.asarray(node.attr("nodes_treeids"), np.int64)
    nids = np.asarray(node.attr("nodes_nodeids"), np.int64)
    feat = np.asarray(node.attr("nodes_featureids"), np.int64)
    vals = np.asarray(node.attr("nodes_values"), np.float32)
    true_ids = np.asarray(node.attr("nodes_truenodeids"), np.int64)
    false_ids = np.asarray(node.attr("nodes_falsenodeids"), np.int64)
    modes = [m if isinstance(m, str) else m.decode()
             for m in node.attr("nodes_modes")]
    miss = np.asarray(node.attr("nodes_missing_value_tracks_true",
                                [0] * len(tids)), np.int64)
    mode_i = np.asarray([_TREE_MODES[m] for m in modes], np.int64)

    gidx = {(int(t), int(n)): i for i, (t, n) in enumerate(zip(tids, nids))}
    trees = sorted(set(int(t) for t in tids))
    roots = np.asarray([gidx[(t, 0)] if (t, 0) in gidx
                        else min(i for i, tt in enumerate(tids) if tt == t)
                        for t in trees], np.int64)
    # child pointers -> global indices (leaves self-loop so the fixed-depth
    # walk is idempotent past a leaf)
    tg = np.arange(len(tids), dtype=np.int64)
    fg = np.arange(len(tids), dtype=np.int64)
    for i in range(len(tids)):
        if mode_i[i] != 0:
            tg[i] = gidx[(int(tids[i]), int(true_ids[i]))]
            fg[i] = gidx[(int(tids[i]), int(false_ids[i]))]
    # static max depth by walking (host-side; attrs are compile-time)
    depth = 0
    for r in roots:
        d, frontier, seen = 0, [int(r)], set()
        while frontier:
            d += 1
            nxt = []
            for i in frontier:
                if i in seen or mode_i[i] == 0:
                    continue
                seen.add(i)
                nxt += [int(tg[i]), int(fg[i])]
            frontier = nxt
            if d > 512:
                raise ValueError("TreeEnsemble: node graph too deep/cyclic")
        depth = max(depth, d)
    return feat, vals, mode_i, tg, fg, miss, roots, depth, gidx


def _tree_walk(X, tables):
    """(N, T) final (leaf) global node index per sample per tree."""
    jnp = _jnp()
    feat, vals, mode_i, tg, fg, miss, roots, depth, _ = tables
    feat_j = jnp.asarray(feat)
    vals_j = jnp.asarray(vals)
    mode_j = jnp.asarray(mode_i)
    tg_j = jnp.asarray(tg)
    fg_j = jnp.asarray(fg)
    miss_j = jnp.asarray(miss)
    X = X.astype(jnp.float32)
    pos = jnp.broadcast_to(jnp.asarray(roots)[None, :],
                           (X.shape[0], len(roots)))
    for _ in range(depth):
        f = feat_j[pos]                        # (N, T)
        v = vals_j[pos]
        m = mode_j[pos]
        x = jnp.take_along_axis(X, f, axis=1)
        isnan = jnp.isnan(x)
        cmp = jnp.stack([jnp.zeros_like(x, bool), x <= v, x < v, x >= v,
                         x > v, x == v, x != v], 0)
        go_true = jnp.take_along_axis(
            cmp, m[None], axis=0)[0]
        go_true = jnp.where(isnan, miss_j[pos] == 1, go_true)
        nxt = jnp.where(go_true, tg_j[pos], fg_j[pos])
        pos = jnp.where(m == 0, pos, nxt)
    return pos


def _leaf_weight_table(tables, treeids, nodeids, out_ids, weights, n_out):
    """(G, n_out) accumulated leaf weights keyed by global node index."""
    gidx = tables[8]
    G = len(tables[0])
    table = np.zeros((G, n_out), np.float32)
    for t, n, c, w in zip(treeids, nodeids, out_ids, weights):
        table[gidx[(int(t), int(n))], int(c)] += np.float32(w)
    return table


def _post_transform_name(node) -> str:
    pt = node.attr("post_transform", "NONE")
    return pt if isinstance(pt, str) else pt.decode()


def _post_transform(node, scores):
    jnp = _jnp()
    pt = _post_transform_name(node)
    if pt == "NONE":
        return scores
    if pt == "LOGISTIC":
        import jax

        return jax.nn.sigmoid(scores)
    if pt == "SOFTMAX":
        import jax

        return jax.nn.softmax(scores, axis=-1)
    if pt == "SOFTMAX_ZERO":
        # spec: softmax over the NON-ZERO score entries only; exact-zero
        # entries keep probability 0 (all-zero rows degrade to uniform)
        nz = scores != 0
        e = jnp.where(nz, jnp.exp(scores - jnp.max(
            jnp.where(nz, scores, -jnp.inf), axis=-1, keepdims=True)), 0.0)
        denom = e.sum(axis=-1, keepdims=True)
        uniform = jnp.full_like(scores, 1.0 / scores.shape[-1])
        return jnp.where(denom > 0, e / jnp.maximum(denom, 1e-30), uniform)
    raise ValueError(f"TreeEnsemble post_transform {pt!r} not supported")


@op("TreeEnsembleClassifier")
def _tree_classifier(node, X):
    jnp = _jnp()
    tables = _tree_tables(node)
    labels = node.attr("classlabels_int64s")
    if labels is None:
        raise ValueError("TreeEnsembleClassifier: only int64 class labels "
                         "are supported (classlabels_strings absent)")
    labels = np.asarray(labels, np.int64)
    cls_ids = np.asarray(node.attr("class_ids"), np.int64)
    ncols = int(cls_ids.max()) + 1 if len(cls_ids) else 1
    base_attr = node.attr("base_values")
    if base_attr is not None:
        nb = len(np.asarray(base_attr).ravel())
        if nb != ncols and not (nb == len(labels) and nb >= ncols):
            raise ValueError(
                f"TreeEnsembleClassifier: base_values has {nb} entries; "
                f"expected {ncols} (weight columns) or {len(labels)} "
                "(class labels, when that covers every weight column)")
        # ORT semantics: a base value per LABEL widens the score matrix —
        # weight columns land at their class_ids, other columns are base-only
        ncols = max(ncols, nb)
    table = _leaf_weight_table(tables, node.attr("class_treeids"),
                               node.attr("class_nodeids"), cls_ids,
                               node.attr("class_weights"), ncols)
    base = np.asarray(base_attr if base_attr is not None
                      else [0.0] * ncols, np.float32)
    pos = _tree_walk(X, tables)
    scores = jnp.asarray(table)[pos].sum(axis=1) + jnp.asarray(base)
    # onnxmltools-style binary emission: one weight column for two labels.
    # ONNX Runtime expands BEFORE a softmax-family transform ([-s, s]) and
    # AFTER logistic/none ([1-p, p]) — softmax over a single column would
    # otherwise collapse to all-ones
    binary_one_col = len(labels) == 2 and ncols == 1
    pt = _post_transform_name(node)
    if binary_one_col and pt in ("SOFTMAX", "SOFTMAX_ZERO"):
        scores = jnp.concatenate([-scores, scores], axis=1)
        binary_one_col = False
    z = _post_transform(node, scores)
    if binary_one_col:
        z = jnp.concatenate([1.0 - z, z], axis=1)
    lab = jnp.asarray(labels)[jnp.argmax(z, axis=1)]
    return lab, z


@op("TreeEnsembleRegressor")
def _tree_regressor(node, X):
    jnp = _jnp()
    tables = _tree_tables(node)
    n_targets = int(node.attr("n_targets", 1))
    table = _leaf_weight_table(tables, node.attr("target_treeids"),
                               node.attr("target_nodeids"),
                               node.attr("target_ids"),
                               node.attr("target_weights"), n_targets)
    base = np.asarray(node.attr("base_values", [0.0] * n_targets),
                      np.float32)
    agg = node.attr("aggregate_function", "SUM")
    agg = agg if isinstance(agg, str) else agg.decode()
    pos = _tree_walk(X, tables)
    per_tree = _jnp().asarray(table)[pos]            # (N, T, n_targets)
    if agg == "SUM":
        scores = per_tree.sum(axis=1)
    elif agg == "AVERAGE":
        scores = per_tree.mean(axis=1)
    elif agg == "MIN":
        scores = per_tree.min(axis=1)
    elif agg == "MAX":
        scores = per_tree.max(axis=1)
    else:
        raise ValueError(f"TreeEnsembleRegressor aggregate {agg!r}")
    return _post_transform(node, scores + jnp.asarray(base))


# --- quantized inference (QDQ + QLinear + integer ops) ----------------------
# The reference executes quantized graphs through onnxruntime's int8 kernels
# (ONNXRuntime.scala sessions). On TPU, int8 buys nothing over bf16 on the
# MXU, so the faithful-and-fast strategy is dequantize -> float op ->
# requantize: numerically the standard QDQ reference semantics (the spec
# defines QLinear* ops BY that decomposition), with the float math riding
# the existing Conv/MatMul impls.

def _qparams(scale, zp):
    """Broadcastable (scale, zero_point) as f32 — per-tensor scalars or
    per-axis 1-D vectors (caller reshapes for the axis)."""
    jnp = _jnp()
    return jnp.asarray(scale, jnp.float32), jnp.asarray(zp, jnp.float32)


def _axis_shape(v, ndim, axis):
    if getattr(v, "ndim", 0) == 1 and v.shape[0] > 1:
        shape = [1] * ndim
        shape[axis] = v.shape[0]
        return v.reshape(shape)
    return v


def _dequant(x, scale, zp, axis, ndim=None):
    jnp = _jnp()
    s, z = _qparams(scale, zp)
    ndim = ndim if ndim is not None else x.ndim
    s = _axis_shape(s, ndim, axis)
    z = _axis_shape(z, ndim, axis)
    return (x.astype(jnp.float32) - z) * s


def _quant(x, scale, zp, axis, dtype):
    jnp = _jnp()
    s, z = _qparams(scale, zp)
    s = _axis_shape(s, x.ndim, axis)
    z = _axis_shape(z, x.ndim, axis)
    info = np.iinfo(dtype)
    q = jnp.clip(jnp.round(x / s) + z, info.min, info.max)
    return q.astype(dtype)


@op("DequantizeLinear")
def _dequantize_linear(node, x, scale, zp=None):
    if zp is None:
        zp = np.zeros((), np.int32)
    return _dequant(x, scale, zp, node.attr("axis", 1))


@op("QuantizeLinear")
def _quantize_linear(node, x, scale, zp=None):
    # zp may be graph-computed (a tracer under jit): read .dtype directly,
    # never np.asarray
    dtype = np.uint8 if zp is None else zp.dtype
    if zp is None:
        zp = np.zeros((), np.uint8)
    return _quant(x, scale, zp, node.attr("axis", 1), dtype)


@op("DynamicQuantizeLinear")
def _dynamic_quantize_linear(node, x):
    """uint8 dynamic quantization (spec formula: range always spans 0)."""
    jnp = _jnp()
    xmin = jnp.minimum(x.min(), 0.0)
    xmax = jnp.maximum(x.max(), 0.0)
    scale = (xmax - xmin) / 255.0
    scale = jnp.where(scale == 0, 1.0, scale)
    zp = jnp.clip(jnp.round(-xmin / scale), 0, 255)
    q = jnp.clip(jnp.round(x / scale) + zp, 0, 255).astype(jnp.uint8)
    return q, scale.astype(jnp.float32), zp.astype(jnp.uint8)


@op("QLinearConv")
def _qlinear_conv(node, x, xs, xzp, w, ws, wzp, ys, yzp, b=None):
    jnp = _jnp()
    xf = _dequant(x, xs, xzp, 1)
    wf = _dequant(w, ws, wzp, 0)          # weight quant axis = output chan
    out = _conv(node, xf, wf)
    if b is not None:
        # bias is int32 with scale xs*ws (spec), zero_point 0
        bs = (jnp.asarray(xs, jnp.float32)
              * jnp.asarray(ws, jnp.float32).reshape(-1))
        bf = b.astype(jnp.float32) * bs
        out = out + bf.reshape((1, -1) + (1,) * (out.ndim - 2))
    return _quant(out, ys, yzp, 1,
                  yzp.dtype if hasattr(yzp, "dtype") else np.uint8)


@op("QLinearMatMul")
def _qlinear_matmul(node, a, as_, azp, b, bs, bzp, ys, yzp):
    # 1-D a-side params are per-ROW (axis ndim-2); b-side per-COLUMN
    af = _dequant(a, as_, azp, a.ndim - 2)
    bf = _dequant(b, bs, bzp, b.ndim - 1)
    out = af @ bf
    return _quant(out, ys, yzp, out.ndim - 1,
                  yzp.dtype if hasattr(yzp, "dtype") else np.uint8)


def _int_shift(v, zp, axis):
    """v - zero_point in int32 (exact integer arithmetic, spec-required:
    f32 accumulation rounds past 2^24, which BERT-sized K exceeds); a 1-D
    zero point broadcasts along ``axis``."""
    jnp = _jnp()
    out = v.astype(jnp.int32)
    if zp is None:
        return out
    z = jnp.asarray(zp, jnp.int32)
    return out - _axis_shape(z, v.ndim, axis)


@op("MatMulInteger")
def _matmul_integer(node, a, b, azp=None, bzp=None):
    # a-side 1-D zero point is per-ROW, b-side per-COLUMN (spec)
    ai = _int_shift(a, azp, a.ndim - 2)
    bi = _int_shift(b, bzp, b.ndim - 1)
    return ai @ bi                         # int32 matmul: exact


@op("ConvInteger")
def _conv_integer(node, x, w, xzp=None, wzp=None):
    jnp = _jnp()
    xi = _int_shift(x, xzp, 1)             # per-input-channel
    wi = _int_shift(w, wzp, 0)             # per-output-channel
    # one conv lowering (_conv) for float and integer: int32 accumulation
    # via preferred_element_type keeps the spec-exact arithmetic
    return _conv(node, xi, wi, preferred=jnp.int32)


# --- scatter/gather family + detection ops ---------------------------------

@op("IsNaN")
def _isnan(node, x):
    return _jnp().isnan(x)


@op("IsInf")
def _isinf(node, x):
    jnp = _jnp()
    pos = bool(node.attr("detect_positive", 1))
    neg = bool(node.attr("detect_negative", 1))
    return ((jnp.isposinf(x) & pos) | (jnp.isneginf(x) & neg))


@op("Sign")
def _sign(node, x):
    return _jnp().sign(x)


@op("ReduceLogSumExp")
def _rlogsumexp(node, x, *rest):
    import jax

    keep = bool(node.attr("keepdims", 1))
    return jax.scipy.special.logsumexp(x, axis=_axes(node, rest, x.ndim),
                                       keepdims=keep)


@op("GatherElements")
def _gather_elements(node, x, idx):
    jnp = _jnp()
    axis = node.attr("axis", 0) % x.ndim
    idx = jnp.where(idx < 0, idx + x.shape[axis], idx)
    return jnp.take_along_axis(x, idx.astype(jnp.int64), axis=axis)


@op("ScatterElements")
def _scatter_elements(node, x, idx, updates):
    jnp = _jnp()
    x = jnp.asarray(x)            # graph inputs may arrive as numpy: .at
    axis = node.attr("axis", 0) % x.ndim
    red = node.attr("reduction", "none")
    red = red if isinstance(red, str) else red.decode()
    idx = jnp.where(idx < 0, idx + x.shape[axis], idx).astype(jnp.int64)
    # build full index grids: every element of `updates` lands at the same
    # multi-index as its position, except along `axis` where idx rules
    grids = list(jnp.meshgrid(*[jnp.arange(s) for s in idx.shape],
                              indexing="ij"))
    grids[axis] = idx
    return _scatter_reduce(x.at[tuple(grids)], updates, red,
                           "ScatterElements")


def _scatter_reduce(ref, updates, red, op_name):
    if red == "none":
        return ref.set(updates)
    if red == "add":
        return ref.add(updates)
    if red == "mul":
        return ref.multiply(updates)
    if red == "max":
        return ref.max(updates)
    if red == "min":
        return ref.min(updates)
    raise ValueError(f"{op_name} reduction {red!r}")


@op("GatherND")
def _gather_nd(node, x, idx):
    b = int(node.attr("batch_dims", 0))
    if b:
        raise ValueError("GatherND: batch_dims > 0 not supported yet")
    k = idx.shape[-1]
    flat_idx = tuple(idx[..., i] for i in range(k))
    return x[flat_idx]


@op("ScatterND")
def _scatter_nd(node, x, idx, updates):
    jnp = _jnp()
    x = jnp.asarray(x)            # graph inputs may arrive as numpy: .at
    red = node.attr("reduction", "none")
    red = red if isinstance(red, str) else red.decode()
    k = idx.shape[-1]
    flat_idx = tuple(idx[..., i] for i in range(k))
    return _scatter_reduce(x.at[flat_idx], updates, red, "ScatterND")


@op("RoiAlign")
def _roi_align(node, x, rois, batch_indices):
    """(num_rois, C, oh, ow) bilinear ROI pooling (Mask R-CNN family).
    Supports output_height/width, spatial_scale, sampling_ratio and both
    coordinate_transformation_modes (half_pixel / output_half_pixel).

    Documented deviation (static shapes under jit): sampling_ratio=0, which
    the spec defines as the ADAPTIVE ceil(roi_size/output_size) samples per
    bin, uses the static upper bound ceil(map_size/output_size) instead —
    more samples at shifted positions than ORT for small ROIs. Export with
    an explicit sampling_ratio for bit-matched parity."""
    jnp = _jnp()
    oh = int(node.attr("output_height", 1))
    ow = int(node.attr("output_width", 1))
    scale = float(node.attr("spatial_scale", 1.0))
    sr = int(node.attr("sampling_ratio", 0))
    mode = node.attr("mode", "avg")
    mode = mode if isinstance(mode, str) else mode.decode()
    ctm = node.attr("coordinate_transformation_mode", "half_pixel")
    ctm = ctm if isinstance(ctm, str) else ctm.decode()
    offset = 0.5 if ctm == "half_pixel" else 0.0
    x = jnp.asarray(x, jnp.float32)   # vmap's traced batch_index needs jnp
    N, C, H, W = x.shape

    def one_roi(roi, bi):
        x1, y1, x2, y2 = (roi * scale) - offset
        rh, rw = y2 - y1, x2 - x1
        if ctm != "half_pixel":
            # the min-size-1 clamp is the LEGACY (output_half_pixel) rule;
            # half_pixel mode uses the true ROI extent (ONNX spec)
            rh = jnp.maximum(rh, 1.0)
            rw = jnp.maximum(rw, 1.0)
        bh, bw = rh / oh, rw / ow
        s_h = sr if sr > 0 else int(np.ceil(H / oh))
        s_w = sr if sr > 0 else int(np.ceil(W / ow))
        # sample grid: s_h x s_w points per output cell
        iy = (y1 + (jnp.arange(oh)[:, None] + (jnp.arange(s_h)[None, :]
              + 0.5) / s_h) * bh).reshape(-1)          # (oh*s_h,)
        ix = (x1 + (jnp.arange(ow)[:, None] + (jnp.arange(s_w)[None, :]
              + 0.5) / s_w) * bw).reshape(-1)          # (ow*s_w,)

        def bilinear(img, yy, xx):
            yy = jnp.clip(yy, 0.0, H - 1)
            xx = jnp.clip(xx, 0.0, W - 1)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            y1_ = jnp.minimum(y0 + 1, H - 1)
            x1_ = jnp.minimum(x0 + 1, W - 1)
            wy = yy - y0
            wx = xx - x0
            g = img[:, y0[:, None], x0[None, :]] * ((1 - wy)[:, None]
                                                    * (1 - wx)[None, :])
            g += img[:, y0[:, None], x1_[None, :]] * ((1 - wy)[:, None]
                                                      * wx[None, :])
            g += img[:, y1_[:, None], x0[None, :]] * (wy[:, None]
                                                      * (1 - wx)[None, :])
            g += img[:, y1_[:, None], x1_[None, :]] * (wy[:, None]
                                                       * wx[None, :])
            return g                                   # (C, len(yy), len(xx))

        img = x[bi]                                    # (C, H, W)
        samples = bilinear(img, iy, ix)                # (C, oh*s_h, ow*s_w)
        samples = samples.reshape(C, oh, s_h, ow, s_w)
        if mode == "max":
            return samples.max(axis=(2, 4))
        return samples.mean(axis=(2, 4))

    import jax

    return jax.vmap(one_roi)(rois.astype(jnp.float32),
                             batch_indices.astype(jnp.int32))


@op("NonMaxSuppression")
def _nms(node, boxes, scores, max_out=None, iou_thr=None, score_thr=None):
    """selected_indices (S, 3) of [batch, class, box]. XLA needs static
    shapes, so S = batch * classes * max_output_boxes_per_class and unused
    slots are PADDED with -1 rows (documented deviation from ORT's dynamic
    output; max_output_boxes_per_class must be a constant)."""
    jnp = _jnp()
    if max_out is None:
        raise ValueError("NonMaxSuppression: max_output_boxes_per_class "
                         "input is required (static bound for XLA)")
    M = int(np.asarray(_static(max_out, "max_output_boxes_per_class",
                               node)).ravel()[0])
    iou_t = (jnp.asarray(iou_thr, jnp.float32).ravel()[0]
             if iou_thr is not None else jnp.float32(0.0))
    score_t = (jnp.asarray(score_thr, jnp.float32).ravel()[0]
               if score_thr is not None else -jnp.inf)
    center = node.attr("center_point_box", 0)
    B, nC, nB = scores.shape

    if center:
        cx, cy, w, h = (boxes[..., 0], boxes[..., 1], boxes[..., 2],
                        boxes[..., 3])
        y1, x1 = cy - h / 2, cx - w / 2
        y2, x2 = cy + h / 2, cx + w / 2
    else:
        y1, x1, y2, x2 = (boxes[..., 0], boxes[..., 1], boxes[..., 2],
                          boxes[..., 3])
        y1, y2 = jnp.minimum(y1, y2), jnp.maximum(y1, y2)
        x1, x2 = jnp.minimum(x1, x2), jnp.maximum(x1, x2)
    area = (y2 - y1) * (x2 - x1)                        # (B, nB)

    def iou(b):
        yy1 = jnp.maximum(y1[b][:, None], y1[b][None, :])
        xx1 = jnp.maximum(x1[b][:, None], x1[b][None, :])
        yy2 = jnp.minimum(y2[b][:, None], y2[b][None, :])
        xx2 = jnp.minimum(x2[b][:, None], x2[b][None, :])
        inter = (jnp.maximum(yy2 - yy1, 0.0) * jnp.maximum(xx2 - xx1, 0.0))
        return inter / jnp.maximum(area[b][:, None] + area[b][None, :]
                                   - inter, 1e-9)

    import jax
    from jax import lax

    def per_class(iou_mat, sc):
        """Greedy NMS: M iterations of pick-best + suppress."""
        def body(_, carry):
            alive, picked, n = carry
            masked = jnp.where(alive, sc, -jnp.inf)
            i = jnp.argmax(masked)
            ok = masked[i] > score_t
            alive2 = alive & (iou_mat[i] <= iou_t)
            alive2 = alive2.at[i].set(False)
            picked2 = picked.at[n].set(jnp.where(ok, i, -1))
            return (jnp.where(ok, alive2, alive & False),
                    picked2, n + ok.astype(jnp.int32))

        alive0 = jnp.ones(sc.shape[0], bool)
        picked0 = jnp.full((M,), -1, jnp.int32)
        _, picked, _ = lax.fori_loop(0, M, body, (alive0, picked0,
                                                  jnp.int32(0)))
        return picked

    def per_batch(iou_mat, sc_b):
        return jax.vmap(lambda s: per_class(iou_mat, s))(sc_b)

    iou_all = jax.vmap(iou)(jnp.arange(B))              # (B, nB, nB)
    picked = jax.vmap(per_batch)(iou_all, scores)       # (B, nC, M)
    b_idx = jnp.broadcast_to(jnp.arange(B)[:, None, None], picked.shape)
    c_idx = jnp.broadcast_to(jnp.arange(nC)[None, :, None], picked.shape)
    valid = picked >= 0
    out = jnp.stack([jnp.where(valid, b_idx, -1),
                     jnp.where(valid, c_idx, -1), picked], axis=-1)
    return out.reshape(-1, 3).astype(jnp.int64)


# --- com.microsoft contrib ops (ORT-optimized transformer graphs) ----------
# onnxruntime's transformer optimizer rewrites exported BERT-class graphs
# into fused contrib ops (domain com.microsoft). The reference's ONNXModel
# executes such graphs through ORT itself; supporting the common fusion set
# here means users can feed ORT-OPTIMIZED model files, not just raw exports.
# The registry dispatches on op_type (domains carry no separate namespace
# in this executor), matching how these names are unique in practice.

@op("FusedMatMul")
def _fused_matmul(node, a, b):
    jnp = _jnp()
    if node.attr("transBatchA", 0) or node.attr("transBatchB", 0):
        raise ValueError("FusedMatMul: transBatchA/transBatchB not "
                         "supported")
    if node.attr("transA", 0):
        a = jnp.swapaxes(a, -1, -2)
    if node.attr("transB", 0):
        b = jnp.swapaxes(b, -1, -2)
    return node.attr("alpha", 1.0) * (a @ b)


@op("FastGelu")
def _fast_gelu(node, x, bias=None):
    import jax

    if bias is not None:
        x = x + bias
    return jax.nn.gelu(x, approximate=True)     # the tanh approximation


@op("BiasGelu")
def _bias_gelu(node, x, bias):
    import jax

    return jax.nn.gelu(x + bias, approximate=False)


@op("QuickGelu")
def _quick_gelu(node, x):
    import jax

    return x * jax.nn.sigmoid(node.attr("alpha", 1.702) * x)


@op("SkipLayerNormalization")
def _skip_layernorm(node, x, skip, gamma, beta=None, bias=None):
    jnp = _jnp()
    eps = node.attr("epsilon", 1e-12)
    h = x + skip
    if bias is not None:
        h = h + bias
    mean = jnp.mean(h, axis=-1, keepdims=True)
    var = jnp.var(h, axis=-1, keepdims=True)
    out = (h - mean) / jnp.sqrt(var + eps) * gamma
    if beta is not None:
        out = out + beta
    # contrib outputs: (out, mean, inv_std_var, input_skip_bias_sum)
    return out, mean, 1.0 / jnp.sqrt(var + eps), h


@op("EmbedLayerNormalization")
def _embed_layernorm(node, ids, seg_ids, word_emb, pos_emb, seg_emb=None,
                     gamma=None, beta=None, mask=None, position_ids=None):
    jnp = _jnp()
    eps = node.attr("epsilon", 1e-12)
    ids = ids.astype(jnp.int32)
    h = word_emb[ids]
    if position_ids is not None:
        h = h + pos_emb[position_ids.astype(jnp.int32)]
    else:
        h = h + pos_emb[jnp.arange(ids.shape[1])][None, :, :]
    if seg_emb is not None and seg_ids is not None:
        h = h + seg_emb[seg_ids.astype(jnp.int32)]
    mean = jnp.mean(h, axis=-1, keepdims=True)
    var = jnp.var(h, axis=-1, keepdims=True)
    out = (h - mean) / jnp.sqrt(var + eps)
    if gamma is not None:
        out = out * gamma
    if beta is not None:
        out = out + beta
    mask_index = (mask.astype(jnp.int32).sum(axis=1)
                  if mask is not None
                  else jnp.full((ids.shape[0],), ids.shape[1], jnp.int32))
    return out, mask_index


@op("Attention")
def _attention(node, x, w, b=None, mask_index=None, past=None,
               attention_bias=None):
    """com.microsoft fused self-attention: input (B, S, Hin), packed QKV
    weight (Hin, 3*Hout), bias (3*Hout). Supports num_heads, unidirectional,
    and the raw (B, S) 0/1 key-padding mask form of mask_index (the form
    the ORT optimizer emits for BERT); past/present KV caches are not
    supported."""
    import jax

    jnp = _jnp()
    if past is not None:
        raise ValueError("Attention: past/present KV cache not supported")
    nh = int(node.attr("num_heads"))
    uni = bool(node.attr("unidirectional", 0))
    B, S, _ = x.shape
    H3 = w.shape[1]
    sizes = node.attr("qkv_hidden_sizes")
    if sizes:
        qh, kh, vh = (int(v_) for v_ in sizes)
        if qh + kh + vh != H3 or qh != kh:
            raise ValueError("Attention: qkv_hidden_sizes must sum to the "
                             "packed width with q == k")
    else:
        qh = kh = vh = H3 // 3
    qkv = x @ w
    if b is not None:
        qkv = qkv + b
    q, k, v = (qkv[..., :qh], qkv[..., qh:qh + kh], qkv[..., qh + kh:])

    def heads(t, hsz):
        return t.reshape(B, S, nh, hsz // nh).transpose(0, 2, 1, 3)

    q, k, v = heads(q, qh), heads(k, kh), heads(v, vh)
    # custom scale attr when present; ORT's default is 1/sqrt(q head size)
    scale = node.attr("scale", 0.0) or 1.0 / np.sqrt(qh // nh)
    out = _sdpa_core(q, k, v, scale, attention_bias, mask_index,
                     causal=uni, op_name="Attention")
    return out.transpose(0, 2, 1, 3).reshape(B, S, vh)


def _sdpa_core(q, k, v, scale, attention_bias, key_padding_mask, causal,
               op_name):
    """Scaled-dot-product-attention shared by the com.microsoft fused ops
    (Attention / MultiHeadAttention): (B, nh, S, D) head tensors in, same
    layout out; ORT's -10000 masking convention for the raw (B, Skv)
    key-padding mask and the causal (unidirectional) triangle."""
    import jax

    jnp = _jnp()
    logits = (q @ k.transpose(0, 1, 3, 2)) * scale       # (B,nh,Sq,Skv)
    if attention_bias is not None:
        logits = logits + attention_bias
    if key_padding_mask is not None:
        if key_padding_mask.ndim != 2:
            raise ValueError(f"{op_name}: only the raw (B, Skv) "
                             "key-padding mask form is supported")
        keymask = key_padding_mask.astype(bool)[:, None, None, :]
        logits = jnp.where(keymask, logits, -10000.0)
    if causal:
        s_q, s_kv = q.shape[2], k.shape[2]
        tri = (jnp.arange(s_q)[:, None] >= jnp.arange(s_kv)[None, :])
        logits = jnp.where(tri[None, None], logits, -10000.0)
    probs = jax.nn.softmax(logits, axis=-1)
    return probs @ v                                     # (B,nh,Sq,D)


# --- coverage wideners (round 5): the remaining deterministic standard ops
# a torch exporter can emit. Each is validated against torch's own CPU
# implementation in tests/test_onnx_extended_ops.py where torch has one.

@op("Hardmax")
def _hardmax(node, x):
    import jax

    jnp = _jnp()
    axis = int(node.attr("axis", -1))
    idx = jnp.argmax(x, axis=axis)
    return jax.nn.one_hot(idx, x.shape[axis], axis=axis, dtype=x.dtype)


@op("Celu")
def _celu(node, x):
    jnp = _jnp()
    a = float(node.attr("alpha", 1.0))
    return jnp.maximum(x, 0.0) + jnp.minimum(
        0.0, a * (jnp.exp(x / a) - 1.0))


@op("Mish")
def _mish(node, x):
    import jax

    jnp = _jnp()
    return x * jnp.tanh(jax.nn.softplus(x))


@op("Shrink")
def _shrink(node, x):
    jnp = _jnp()
    lambd = float(node.attr("lambd", 0.5))
    bias = float(node.attr("bias", 0.0))
    return jnp.where(x < -lambd, x + bias,
                     jnp.where(x > lambd, x - bias,
                               jnp.zeros_like(x)))


@op("ThresholdedRelu")
def _thresholded_relu(node, x):
    jnp = _jnp()
    a = float(node.attr("alpha", 1.0))
    return jnp.where(x > a, x, jnp.zeros_like(x))


@op("BitShift")
def _bitshift(node, x, y):
    jnp = _jnp()
    d = node.attr("direction")
    d = d if isinstance(d, str) else (d or b"LEFT").decode()
    return jnp.left_shift(x, y) if d.upper() == "LEFT" \
        else jnp.right_shift(x, y)


@op("EyeLike")
def _eyelike(node, x):
    jnp = _jnp()
    k = int(node.attr("k", 0))
    dt = node.attr("dtype")
    from .protoio import DTYPES

    if dt is not None:
        dtype = DTYPES.get(int(dt))
        if dtype is None:
            raise ValueError(f"EyeLike: unsupported dtype code {int(dt)}")
    else:
        dtype = x.dtype
    return jnp.eye(x.shape[0], x.shape[1], k=k, dtype=dtype)


@op("Det")
def _det(node, x):
    jnp = _jnp()
    return jnp.linalg.det(x)


@op("LRN")
def _lrn(node, x):
    """Cross-channel local response normalization (NCHW, channel axis 1):
    y = x / (bias + alpha/size * window_sum(x^2))^beta — AlexNet-era op
    still present in exported legacy vision models."""
    jnp = _jnp()
    alpha = float(node.attr("alpha", 1e-4))
    beta = float(node.attr("beta", 0.75))
    bias = float(node.attr("bias", 1.0))
    size = int(node.attr("size"))
    half_lo = (size - 1) // 2
    half_hi = size // 2
    sq = x * x
    pad = [(0, 0)] * sq.ndim
    pad[1] = (half_lo, half_hi)
    padded = jnp.pad(sq, pad)
    win = sum(padded[:, i:i + x.shape[1]] for i in range(size))
    return x / (bias + (alpha / size) * win) ** beta


@op("GridSample")
def _grid_sample(node, x, grid):
    """2-D bilinear/nearest grid sampling (torch F.grid_sample export):
    x (N, C, Hin, Win), grid (N, Hout, Wout, 2) with xy in [-1, 1];
    zeros / border padding, align_corners both ways."""
    jnp = _jnp()
    mode = node.attr("mode", "linear")
    mode = mode if isinstance(mode, str) else mode.decode()
    pad_mode = node.attr("padding_mode", "zeros")
    pad_mode = pad_mode if isinstance(pad_mode, str) else pad_mode.decode()
    align = bool(node.attr("align_corners", 0))
    if mode not in ("linear", "bilinear", "nearest"):
        raise ValueError(f"GridSample: mode {mode!r} not supported")
    if pad_mode not in ("zeros", "border"):
        raise ValueError(f"GridSample: padding_mode {pad_mode!r} "
                         "not supported")
    N, C, H, W = x.shape
    gx, gy = grid[..., 0], grid[..., 1]          # (N, Ho, Wo), in [-1, 1]
    if align:
        fx = (gx + 1.0) * 0.5 * (W - 1)
        fy = (gy + 1.0) * 0.5 * (H - 1)
    else:
        fx = ((gx + 1.0) * W - 1.0) * 0.5
        fy = ((gy + 1.0) * H - 1.0) * 0.5

    # flatten spatial, one take_along_axis per corner
    flat = x.reshape(N, C, H * W)

    def gather(ix, iy):
        inb = ((ix >= 0) & (ix < W) & (iy >= 0) & (iy < H))
        cx = jnp.clip(ix, 0, W - 1)
        cy = jnp.clip(iy, 0, H - 1)
        lin = (cy * W + cx).reshape(N, 1, -1)    # (N, 1, Ho*Wo)
        v = jnp.take_along_axis(flat, jnp.broadcast_to(
            lin, (N, C, lin.shape[-1])), axis=2)
        v = v.reshape(N, C, *ix.shape[1:])
        if pad_mode == "zeros":
            v = v * inb[:, None].astype(v.dtype)
        return v

    if mode == "nearest":
        return gather(jnp.round(fx).astype(jnp.int32),
                      jnp.round(fy).astype(jnp.int32))
    x0 = jnp.floor(fx).astype(jnp.int32)
    y0 = jnp.floor(fy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1
    wx = (fx - x0).astype(x.dtype)[:, None]
    wy = (fy - y0).astype(x.dtype)[:, None]
    v00, v01 = gather(x0, y0), gather(x1, y0)
    v10, v11 = gather(x0, y1), gather(x1, y1)
    top = v00 * (1 - wx) + v01 * wx
    bot = v10 * (1 - wx) + v11 * wx
    return top * (1 - wy) + bot * wy


@op("MultiHeadAttention")
def _multi_head_attention(node, query, key=None, value=None, bias=None,
                          key_padding_mask=None, attention_bias=None,
                          past_key=None, past_value=None):
    """com.microsoft MultiHeadAttention (the newer ORT fusion): separate
    (B, S, hidden) q/k/v with optional packed (3*hidden) bias, raw (B, Skv)
    key-padding mask, additive attention bias, and the unidirectional
    (causal) attribute; KV caches and packed-QKV query forms are not
    supported."""
    if past_key is not None or past_value is not None:
        raise ValueError("MultiHeadAttention: past KV cache not supported")
    if key is None or value is None:
        raise ValueError("MultiHeadAttention: packed-QKV query form not "
                         "supported (pass separate key/value)")
    nh = int(node.attr("num_heads"))
    B, Sq, Hq = query.shape
    if bias is not None:
        query = query + bias[:Hq]
        key = key + bias[Hq:Hq + key.shape[-1]]
        value = value + bias[Hq + key.shape[-1]:]

    def heads(t):
        return t.reshape(B, t.shape[1], nh, -1).transpose(0, 2, 1, 3)

    q, k, v = heads(query), heads(key), heads(value)
    scale = node.attr("scale", 0.0) or 1.0 / np.sqrt(Hq // nh)
    out = _sdpa_core(q, k, v, scale, attention_bias, key_padding_mask,
                     causal=bool(node.attr("unidirectional", 0)),
                     op_name="MultiHeadAttention")
    return out.transpose(0, 2, 1, 3).reshape(B, Sq, -1)


# ONNX's Random* ops are "implementation-defined" without a seed; here they
# are DETERMINISTIC — jax.random keyed by the seed attr (0 when absent) —
# because a traced XLA program cannot carry hidden RNG state, and serving
# reproducibility is a feature, not a bug.

def _random_common(node, shape, like_dtype=None):
    import jax

    from .protoio import DTYPES

    dt = node.attr("dtype")
    if dt is not None:
        dtype = DTYPES.get(int(dt))
        if dtype is None:
            raise ValueError(f"Random*: unsupported dtype code {int(dt)}")
    else:
        # spec: the Like forms inherit the input tensor's dtype
        dtype = like_dtype if like_dtype is not None else np.float32
    seed = node.attr("seed")
    if seed is not None:
        key = jax.random.PRNGKey(int(seed))
    else:
        # seed-less nodes must still DECORRELATE from each other: key off
        # the node's (graph-unique) first output name, stably hashed —
        # python's str hash is per-process randomized, crc32 is not
        import zlib

        ident = (node.outputs[0] if node.outputs else node.name) or "rng"
        key = jax.random.PRNGKey(zlib.crc32(ident.encode()))
    return key, tuple(int(s) for s in shape), dtype


@op("RandomNormal")
def _random_normal(node):
    import jax

    key, shape, dtype = _random_common(node, node.attr("shape"))
    mean = float(node.attr("mean", 0.0))
    scale = float(node.attr("scale", 1.0))
    return mean + scale * jax.random.normal(key, shape, dtype)


@op("RandomUniform")
def _random_uniform(node):
    import jax

    key, shape, dtype = _random_common(node, node.attr("shape"))
    low = float(node.attr("low", 0.0))
    high = float(node.attr("high", 1.0))
    return jax.random.uniform(key, shape, dtype, low, high)


@op("RandomNormalLike")
def _random_normal_like(node, x):
    import jax

    key, shape, dtype = _random_common(node, x.shape, like_dtype=x.dtype)
    mean = float(node.attr("mean", 0.0))
    scale = float(node.attr("scale", 1.0))
    return mean + scale * jax.random.normal(key, shape, dtype)


@op("RandomUniformLike")
def _random_uniform_like(node, x):
    import jax

    key, shape, dtype = _random_common(node, x.shape, like_dtype=x.dtype)
    low = float(node.attr("low", 0.0))
    high = float(node.attr("high", 1.0))
    return jax.random.uniform(key, shape, dtype, low, high)


@op("Multinomial")
def _multinomial(node, x):
    """Categorical sampling from unnormalized LOG-probabilities per row
    (the ONNX input is unnormalized log-probs); deterministic via the
    shared Random* seeding, dtype attr honored (spec default int32)."""
    import jax

    jnp = _jnp()
    n = int(node.attr("sample_size", 1))
    key, _, dtype = _random_common(node, (), like_dtype=np.int32)
    out = jax.random.categorical(key, jnp.asarray(x), axis=-1,
                                 shape=(n,) + (x.shape[0],))
    return out.T.astype(dtype)
