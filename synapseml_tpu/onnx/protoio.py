"""Minimal protobuf wire-format IO for ONNX files.

The environment ships no ``onnx`` package, and the reference reads models
through ONNX Runtime's Java API (deep-learning/.../onnx/ONNXRuntime.scala:25-44)
— neither is a fit here. ONNX files are ordinary protobuf, and the subset of
messages needed for inference (ModelProto → GraphProto → Node/Tensor/
Attribute/ValueInfo) decodes with a ~hundred-line wire reader. A matching
writer exists so tests (and users) can construct models without external deps.

Field numbers follow onnx/onnx.proto3 (public schema):
  ModelProto:   ir_version=1, opset_import=8, graph=7
  GraphProto:   node=1, name=2, initializer=5, input=11, output=12
  NodeProto:    input=1, output=2, name=3, op_type=4, attribute=5
  AttributeProto: name=1, f=2, i=3, s=4, t=5, g=6, floats=7, ints=8, strings=9, type=20
  TensorProto:  dims=1, data_type=2, float_data=4, int32_data=5, string_data=6,
                int64_data=7, name=8, raw_data=9, double_data=10, uint64_data=11
  ValueInfoProto: name=1, type=2; TypeProto.tensor_type=1 {elem_type=1, shape=2}
  TensorShapeProto.dim=1 {dim_value=1, dim_param=2}
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# TensorProto.DataType enum (onnx.proto3)
DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
          6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64,
          12: np.uint32, 13: np.uint64}
DTYPE_CODES = {np.dtype(v): k for k, v in DTYPES.items()}


# --------------------------------------------------------------------------
# wire primitives

def _read_varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(out: bytearray, value: int) -> None:
    if value < 0:  # int64 negatives (e.g. -1 dynamic dims) are 64-bit 2's-compl
        value &= (1 << 64) - 1
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _fields(data: bytes):
    """Yield (field_number, wire_type, value) over a message body."""
    buf = memoryview(data)
    pos, end = 0, len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        fnum, wtype = tag >> 3, tag & 7
        if wtype == 0:  # varint
            val, pos = _read_varint(buf, pos)
        elif wtype == 1:  # 64-bit
            val = bytes(buf[pos:pos + 8])
            pos += 8
        elif wtype == 2:  # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = bytes(buf[pos:pos + ln])
            pos += ln
        elif wtype == 5:  # 32-bit
            val = bytes(buf[pos:pos + 4])
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        yield fnum, wtype, val


def _emit(out: bytearray, fnum: int, wtype: int, payload) -> None:
    _write_varint(out, (fnum << 3) | wtype)
    if wtype == 0:
        _write_varint(out, payload)
    elif wtype in (1, 5):  # fixed 64/32-bit: raw bytes, no length prefix
        out.extend(payload)
    else:
        _write_varint(out, len(payload))
        out.extend(payload)


def _packed_or_repeated_ints(wtype: int, val) -> List[int]:
    if wtype == 0:
        return [val]
    out, buf, pos = [], memoryview(val), 0
    while pos < len(buf):
        v, pos = _read_varint(buf, pos)
        out.append(v)
    return out


def _signed(v: int) -> int:
    """varints store int64 two's-complement in 64 bits."""
    return v - (1 << 64) if v >= (1 << 63) else v


# --------------------------------------------------------------------------
# message classes

@dataclass
class Attribute:
    name: str = ""
    type: int = 0  # 1=FLOAT 2=INT 3=STRING 4=TENSOR 5=GRAPH 6=FLOATS
    #                7=INTS 8=STRINGS (AttributeProto.AttributeType enum)
    f: float = 0.0
    i: int = 0
    s: bytes = b""
    t: Optional["Tensor"] = None
    g: Optional["Graph"] = None   # subgraph (If/Loop/Scan bodies)
    floats: List[float] = field(default_factory=list)
    ints: List[int] = field(default_factory=list)
    strings: List[bytes] = field(default_factory=list)

    @property
    def value(self) -> Any:
        return {1: self.f, 2: self.i, 3: self.s.decode("utf-8", "replace"),
                4: self.t, 5: self.g, 6: list(self.floats),
                7: list(self.ints),
                8: [s.decode("utf-8", "replace") for s in self.strings]
                }.get(self.type)

    @staticmethod
    def parse(data: bytes) -> "Attribute":
        a = Attribute()
        for fnum, wtype, val in _fields(data):
            if fnum == 1:
                a.name = val.decode()
            elif fnum == 2:
                a.f = struct.unpack("<f", val)[0]
            elif fnum == 3:
                a.i = _signed(val)
            elif fnum == 4:
                a.s = val
            elif fnum == 5:
                a.t = Tensor.parse(val)
            elif fnum == 6:
                a.g = Graph.parse(val)
            elif fnum == 7:
                a.floats += (list(struct.unpack(f"<{len(val)//4}f", val))
                             if wtype == 2 else [struct.unpack("<f", val)[0]])
            elif fnum == 8:
                a.ints += [_signed(v) for v in _packed_or_repeated_ints(wtype, val)]
            elif fnum == 9:
                a.strings.append(val)
            elif fnum == 20:
                a.type = val
        if a.type == 0:  # infer when writer omitted the type enum
            if a.floats:
                a.type = 6
            elif a.ints:
                a.type = 7
            elif a.strings:
                a.type = 8
            elif a.t is not None:
                a.type = 4
            elif a.g is not None:
                a.type = 5
            elif a.s:
                a.type = 3
        return a

    def encode(self) -> bytes:
        out = bytearray()
        _emit(out, 1, 2, self.name.encode())
        if self.type == 1:
            _emit(out, 2, 5, struct.pack("<f", self.f))
        elif self.type == 2:
            _emit(out, 3, 0, self.i & ((1 << 64) - 1))
        elif self.type == 3:
            _emit(out, 4, 2, self.s)
        elif self.type == 4 and self.t is not None:
            _emit(out, 5, 2, self.t.encode())
        elif self.type == 5 and self.g is not None:
            _emit(out, 6, 2, self.g.encode())
        elif self.type == 6:
            _emit(out, 7, 2, struct.pack(f"<{len(self.floats)}f", *self.floats))
        elif self.type == 7:
            packed = bytearray()
            for v in self.ints:
                _write_varint(packed, v & ((1 << 64) - 1))
            _emit(out, 8, 2, bytes(packed))
        elif self.type == 8:
            for s in self.strings:
                _emit(out, 9, 2, s)
        _emit(out, 20, 0, self.type)
        return bytes(out)


@dataclass
class Tensor:
    name: str = ""
    dims: List[int] = field(default_factory=list)
    data_type: int = 1
    raw: bytes = b""
    values: Optional[np.ndarray] = None

    def array(self) -> np.ndarray:
        if self.values is not None:
            return self.values
        dt = DTYPES.get(self.data_type)
        if dt is None:
            raise ValueError(f"unsupported tensor data_type {self.data_type}")
        arr = np.frombuffer(self.raw, dtype=dt) if self.raw else \
            np.zeros(int(np.prod(self.dims or [0])), dtype=dt)
        return arr.reshape(self.dims).copy()

    @staticmethod
    def parse(data: bytes) -> "Tensor":
        t = Tensor()
        f32, i32, i64, f64 = [], [], [], []
        for fnum, wtype, val in _fields(data):
            if fnum == 1:
                t.dims += [_signed(v) for v in _packed_or_repeated_ints(wtype, val)]
            elif fnum == 2:
                t.data_type = val
            elif fnum == 4:
                f32 += (list(struct.unpack(f"<{len(val)//4}f", val))
                        if wtype == 2 else [struct.unpack("<f", val)[0]])
            elif fnum == 5:
                i32 += [_signed(v) for v in _packed_or_repeated_ints(wtype, val)]
            elif fnum == 7:
                i64 += [_signed(v) for v in _packed_or_repeated_ints(wtype, val)]
            elif fnum == 8:
                t.name = val.decode()
            elif fnum == 9:
                t.raw = val
            elif fnum == 10:
                f64 += (list(struct.unpack(f"<{len(val)//8}d", val))
                        if wtype == 2 else [struct.unpack("<d", val)[0]])
        if not t.raw:
            if f32:
                t.values = np.asarray(f32, np.float32).reshape(t.dims)
            elif i64:
                t.values = np.asarray(i64, np.int64).reshape(t.dims)
            elif i32:
                if t.data_type == 10:  # fp16 in int32_data holds BIT PATTERNS
                    t.values = (np.asarray(i32, dtype=np.uint16)
                                .view(np.float16).reshape(t.dims))
                else:
                    dt = DTYPES.get(t.data_type, np.int32)
                    t.values = np.asarray(i32).astype(dt).reshape(t.dims)
            elif f64:
                t.values = np.asarray(f64, np.float64).reshape(t.dims)
        return t

    @staticmethod
    def from_array(name: str, arr: np.ndarray) -> "Tensor":
        arr = np.ascontiguousarray(arr)
        code = DTYPE_CODES.get(arr.dtype)
        if code is None:
            raise ValueError(f"unsupported dtype {arr.dtype}")
        return Tensor(name=name, dims=list(arr.shape), data_type=code,
                      raw=arr.tobytes())

    def encode(self) -> bytes:
        out = bytearray()
        for d in self.dims:
            _emit(out, 1, 0, d)
        _emit(out, 2, 0, self.data_type)
        _emit(out, 8, 2, self.name.encode())
        raw = self.raw or (self.values.tobytes() if self.values is not None else b"")
        _emit(out, 9, 2, raw)
        return bytes(out)


@dataclass
class Node:
    op_type: str = ""
    inputs: List[str] = field(default_factory=list)
    outputs: List[str] = field(default_factory=list)
    name: str = ""
    attrs: Dict[str, Attribute] = field(default_factory=dict)
    domain: str = ""              # NodeProto field 7 (e.g. "ai.onnx.ml")

    def attr(self, name: str, default: Any = None) -> Any:
        a = self.attrs.get(name)
        return default if a is None else a.value

    @staticmethod
    def parse(data: bytes) -> "Node":
        n = Node()
        for fnum, _, val in _fields(data):
            if fnum == 1:
                n.inputs.append(val.decode())
            elif fnum == 2:
                n.outputs.append(val.decode())
            elif fnum == 3:
                n.name = val.decode()
            elif fnum == 4:
                n.op_type = val.decode()
            elif fnum == 5:
                a = Attribute.parse(val)
                n.attrs[a.name] = a
            elif fnum == 7:
                n.domain = val.decode()
        return n

    def encode(self) -> bytes:
        out = bytearray()
        for s in self.inputs:
            _emit(out, 1, 2, s.encode())
        for s in self.outputs:
            _emit(out, 2, 2, s.encode())
        _emit(out, 3, 2, self.name.encode())
        _emit(out, 4, 2, self.op_type.encode())
        for a in self.attrs.values():
            _emit(out, 5, 2, a.encode())
        if self.domain:
            _emit(out, 7, 2, self.domain.encode())
        return bytes(out)


@dataclass
class ValueInfo:
    name: str = ""
    elem_type: int = 1
    shape: List[Any] = field(default_factory=list)  # int or str (dim_param)

    @staticmethod
    def parse(data: bytes) -> "ValueInfo":
        vi = ValueInfo()
        for fnum, _, val in _fields(data):
            if fnum == 1:
                vi.name = val.decode()
            elif fnum == 2:  # TypeProto
                for f2, _, v2 in _fields(val):
                    if f2 == 1:  # tensor_type
                        for f3, _, v3 in _fields(v2):
                            if f3 == 1:
                                vi.elem_type = v3
                            elif f3 == 2:  # shape
                                for f4, _, v4 in _fields(v3):
                                    if f4 == 1:  # dim
                                        dim: Any = -1
                                        for f5, _, v5 in _fields(v4):
                                            if f5 == 1:
                                                dim = _signed(v5)
                                            elif f5 == 2:
                                                dim = v5.decode()
                                        vi.shape.append(dim)
        return vi

    def encode(self) -> bytes:
        shape = bytearray()
        for d in self.shape:
            dim = bytearray()
            if isinstance(d, str):
                _emit(dim, 2, 2, d.encode())
            else:
                _emit(dim, 1, 0, int(d))
            _emit(shape, 1, 2, bytes(dim))
        tt = bytearray()
        _emit(tt, 1, 0, self.elem_type)
        _emit(tt, 2, 2, bytes(shape))
        tp = bytearray()
        _emit(tp, 1, 2, bytes(tt))
        out = bytearray()
        _emit(out, 1, 2, self.name.encode())
        _emit(out, 2, 2, bytes(tp))
        return bytes(out)


@dataclass
class Graph:
    nodes: List[Node] = field(default_factory=list)
    name: str = "graph"
    initializers: Dict[str, Tensor] = field(default_factory=dict)
    inputs: List[ValueInfo] = field(default_factory=list)
    outputs: List[ValueInfo] = field(default_factory=list)

    @staticmethod
    def parse(data: bytes) -> "Graph":
        g = Graph()
        for fnum, _, val in _fields(data):
            if fnum == 1:
                g.nodes.append(Node.parse(val))
            elif fnum == 2:
                g.name = val.decode()
            elif fnum == 5:
                t = Tensor.parse(val)
                g.initializers[t.name] = t
            elif fnum == 11:
                g.inputs.append(ValueInfo.parse(val))
            elif fnum == 12:
                g.outputs.append(ValueInfo.parse(val))
        return g

    def encode(self) -> bytes:
        out = bytearray()
        for n in self.nodes:
            _emit(out, 1, 2, n.encode())
        _emit(out, 2, 2, self.name.encode())
        for t in self.initializers.values():
            _emit(out, 5, 2, t.encode())
        for vi in self.inputs:
            _emit(out, 11, 2, vi.encode())
        for vi in self.outputs:
            _emit(out, 12, 2, vi.encode())
        return bytes(out)


@dataclass
class Model:
    graph: Graph = field(default_factory=Graph)
    ir_version: int = 8
    opset: int = 17
    producer_name: str = ""   # ModelProto field 2 (e.g. "pytorch" — lets
                              # tests prove a fixture came from a third party)
    ml_opset: Optional[int] = None   # ai.onnx.ml domain version, when used

    @staticmethod
    def parse(data: bytes) -> "Model":
        m = Model()
        for fnum, _, val in _fields(data):
            if fnum == 1:
                m.ir_version = val
            elif fnum == 2:
                m.producer_name = bytes(val).decode("utf-8", "replace")
            elif fnum == 7:
                m.graph = Graph.parse(val)
            elif fnum == 8:  # OperatorSetIdProto: (domain, version)
                dom, ver = "", None
                for f2, _, v2 in _fields(val):
                    if f2 == 1:
                        dom = bytes(v2).decode("utf-8", "replace")
                    elif f2 == 2:
                        ver = _signed(v2)
                if ver is not None:
                    # a domain'd entry (ai.onnx.ml) must not clobber the
                    # default-domain opset (onnxmltools graphs carry both)
                    if dom in ("", "ai.onnx"):
                        m.opset = ver
                    elif dom == "ai.onnx.ml":
                        m.ml_opset = ver
        return m

    @staticmethod
    def load(path: str) -> "Model":
        with open(path, "rb") as f:
            return Model.parse(f.read())

    def encode(self) -> bytes:
        out = bytearray()
        _emit(out, 1, 0, self.ir_version)
        opset = bytearray()
        _emit(opset, 1, 2, b"")  # default domain
        _emit(opset, 2, 0, self.opset)
        _emit(out, 8, 2, bytes(opset))
        if self.ml_opset is not None:
            mlset = bytearray()
            _emit(mlset, 1, 2, b"ai.onnx.ml")
            _emit(mlset, 2, 0, self.ml_opset)
            _emit(out, 8, 2, bytes(mlset))
        _emit(out, 7, 2, self.graph.encode())
        return bytes(out)

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            f.write(self.encode())
