"""ONNX graph → jittable JAX function.

Reference behavior being replaced: ONNX Runtime session execution
(deep-learning/.../onnx/ONNXRuntime.scala:25-44 create, :58-107 batch apply)
and graph surgery for fetching intermediate outputs
(ONNXModel.scala:203-227, ONNXUtils.scala). Here the graph is imported once
into a pure function ``f(inputs) -> outputs`` that XLA compiles for TPU; "model
slicing at an intermediate output" is just asking the evaluator for that tensor
name — the dead tail of the graph is never traced.

Constant folding: nodes whose inputs are all initializers/constants are
evaluated at import (host, numpy semantics via jax) so shape-valued tensors
(Reshape targets, Slice indices) are static by the time the function is jitted.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .ops import REGISTRY
from .protoio import Graph, Model, Node


class OnnxFunction:
    """Callable wrapper: ``fn(feeds: dict) -> dict`` over requested outputs."""

    def __init__(self, model: Model, outputs: Optional[Sequence[str]] = None,
                 precision: str = "float32"):
        if precision not in ("float32", "bfloat16"):
            raise ValueError(f"precision must be 'float32' or 'bfloat16', "
                             f"got {precision!r}")
        self.model = model
        self.precision = precision
        g = model.graph
        self.graph_inputs = [vi.name for vi in g.inputs
                             if vi.name not in g.initializers]
        self.input_info = {vi.name: vi for vi in g.inputs}
        self.outputs = list(outputs) if outputs else [vi.name for vi in g.outputs]
        self._plan = self._make_plan(g, self.outputs)
        # decode weights ONCE — Tensor.array() copies, and models carry
        # hundreds of MB of initializers; only tensors the sliced plan
        # actually reads are decoded (dead-tail weights stay raw bytes)
        used = {i for n in self._plan for i in n.inputs} | set(self.outputs)
        self._weights = {k: t.array() for k, t in g.initializers.items()
                         if k in used}
        self._bf16 = None
        if precision == "bfloat16":
            # TPU-native mixed precision: f32 tensors ride the MXU as bf16
            # operands (matmul/conv still accumulate in f32 via
            # preferred_element_type); halves weight storage and roughly
            # doubles/triples MXU throughput vs f32 on v5e-class chips
            import jax.numpy as jnp

            self._bf16 = jnp.bfloat16
            self._weights = {k: (v.astype(jnp.bfloat16)
                                 if getattr(v, "dtype", None) == np.float32
                                 else v)
                             for k, v in self._weights.items()}

    @staticmethod
    def _make_plan(g: Graph, outputs: Sequence[str]) -> List[Node]:
        """Nodes needed for ``outputs``, in topological order (graph slicing:
        the ONNXModel.scala:203-227 analog)."""
        producer: Dict[str, Node] = {}
        for n in g.nodes:
            for o in n.outputs:
                producer[o] = n
        known = set(g.initializers) | {vi.name for vi in g.inputs}
        plan: List[Node] = []
        done = set()      # node ids fully emitted
        in_stack = set()  # node ids on the current path (cycle check)
        # iterative post-order DFS — exported transformer graphs routinely
        # exceed Python's recursion limit in depth
        work: List[Tuple[str, bool]] = [(o, False) for o in reversed(outputs)]
        while work:
            name, expanded = work.pop()
            if name == "" or name in known:
                continue
            n = producer.get(name)
            if n is None:
                raise ValueError(f"tensor {name!r} has no producer and is not "
                                 f"a graph input/initializer")
            if expanded:
                in_stack.discard(id(n))
                if id(n) not in done:
                    done.add(id(n))
                    plan.append(n)
                continue
            if id(n) in done:
                continue
            if id(n) in in_stack:
                raise ValueError(f"cycle through {name!r}")
            in_stack.add(id(n))
            work.append((name, True))
            for i in reversed(n.inputs):
                work.append((i, False))
        return plan

    def _down(self, v):
        if self._bf16 is not None and getattr(v, "dtype", None) == np.float32:
            return v.astype(self._bf16)
        return v

    def __call__(self, feeds: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        env: Dict[str, np.ndarray] = dict(self._weights)
        for name in self.graph_inputs:
            if name not in feeds:
                raise ValueError(
                    f"missing input {name!r}; expected {self.graph_inputs}")
        for name, v in feeds.items():
            env[name] = self._down(v)
        for node in self._plan:
            impl = REGISTRY.get(node.op_type)
            if impl is None:
                raise NotImplementedError(
                    f"ONNX op {node.op_type!r} (node {node.name!r}) is not "
                    f"supported; supported: {sorted(REGISTRY)}")
            args = [env[i] if i else None for i in node.inputs]
            out = impl(node, *args)
            if not isinstance(out, tuple):
                out = (out,)
            for name, val in zip(node.outputs, out):
                if name:
                    # matmul/conv emit f32 accumulations; fold back to bf16 so
                    # the NEXT MXU op also reads bf16 operands — EXCEPT for
                    # explicit Cast nodes: a graph-mandated f32 island (e.g.
                    # guarding a softmax) keeps the precision it asked for
                    env[name] = (val if node.op_type == "Cast"
                                 else self._down(val))
        bf16 = self._bf16
        return {o: (env[o].astype(np.float32)
                    if bf16 is not None
                    and getattr(env[o], "dtype", None) == bf16
                    else env[o])
                for o in self.outputs}

    def as_jax(self, names: Optional[List[str]] = None):
        """(fn, input_names): positional jit-friendly callable. ``names``
        overrides the positional input ordering (default: graph order)."""
        names = list(names) if names is not None else list(self.graph_inputs)

        def fn(*arrays):
            return tuple(self({n: a for n, a in zip(names, arrays)}).values())

        return fn, names


def import_model(model_bytes: bytes,
                 outputs: Optional[Sequence[str]] = None) -> OnnxFunction:
    return OnnxFunction(Model.parse(model_bytes), outputs)


def fold_constants(model: Model) -> Model:
    """Evaluate nodes with all-constant inputs once, promoting results to
    initializers (host-side; keeps Reshape/Slice args static under jit)."""
    g = model.graph
    const = dict(g.initializers)
    env = {k: t.array() for k, t in const.items()}
    keep: List[Node] = []
    from .protoio import Tensor

    for node in g.nodes:
        impl = REGISTRY.get(node.op_type)
        inputs_const = all((not i) or (i in env) for i in node.inputs)
        # Shape of a known-rank input is NOT constant in general (batch dim);
        # only fold Shape when the producer value is itself constant.
        if impl is not None and inputs_const and node.op_type != "Shape":
            try:
                out = impl(node, *[env[i] if i else None for i in node.inputs])
            except Exception:
                keep.append(node)
                continue
            if not isinstance(out, tuple):
                out = (out,)
            for name, val in zip(node.outputs, out):
                if name:
                    env[name] = np.asarray(val)
                    g.initializers[name] = Tensor.from_array(name, np.asarray(val))
        else:
            keep.append(node)
    g.nodes = keep
    return model
