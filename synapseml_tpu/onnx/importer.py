"""ONNX graph → jittable JAX function.

Reference behavior being replaced: ONNX Runtime session execution
(deep-learning/.../onnx/ONNXRuntime.scala:25-44 create, :58-107 batch apply)
and graph surgery for fetching intermediate outputs
(ONNXModel.scala:203-227, ONNXUtils.scala). Here the graph is imported once
into a pure function ``f(inputs) -> outputs`` that XLA compiles for TPU; "model
slicing at an intermediate output" is just asking the evaluator for that tensor
name — the dead tail of the graph is never traced.

Constant folding: nodes whose inputs are all initializers/constants are
evaluated at import (host, numpy semantics via jax) so shape-valued tensors
(Reshape targets, Slice indices) are static by the time the function is jitted.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .ops import REGISTRY
from .protoio import Graph, Model, Node


class OnnxFunction:
    """Callable wrapper: ``fn(feeds: dict) -> dict`` over requested outputs.

    Control flow: constant-condition If / constant-trip Loop are resolved at
    import (inlined/unrolled below); DATA-dependent If/Loop/Scan execute at
    runtime through lax.cond / lax.while_loop / lax.scan (the ONNX Runtime
    parity surface — deep-learning/.../onnx/ONNXModel.scala:145-423 runs any
    such graph through ORT). XLA's static-shape model imposes two honest
    restrictions, both validated loudly: If branches must produce matching
    shapes/dtypes, and a Loop with scan outputs needs a static trip bound
    (``max_loop_trips`` caps it when the trip count is data-dependent; scan
    outputs are zero-padded to the bound when the loop exits early, and an
    eager run that HITS the cap with its condition still true raises — under
    jit that truncation cannot be detected and is silent).
    """

    def __init__(self, model: Model, outputs: Optional[Sequence[str]] = None,
                 precision: str = "float32", max_loop_trips: int = 128):
        if precision not in ("float32", "bfloat16"):
            raise ValueError(f"precision must be 'float32' or 'bfloat16', "
                             f"got {precision!r}")
        if int(max_loop_trips) < 1:
            raise ValueError(f"max_loop_trips must be >= 1, "
                             f"got {max_loop_trips}")
        self.model = model
        self.precision = precision
        self.max_loop_trips = int(max_loop_trips)
        g = model.graph
        # shared fixpoint: unrolling a Loop can expose constant Ifs and
        # vice versa (nested control flow) — alternate until neither changes
        for _ in range(32):
            if not (_inline_constant_ifs(g) | _unroll_constant_loops(g)):
                break
        self.graph_inputs = [vi.name for vi in g.inputs
                             if vi.name not in g.initializers]
        self.input_info = {vi.name: vi for vi in g.inputs}
        self.outputs = list(outputs) if outputs else [vi.name for vi in g.outputs]
        self._plan = self._make_plan(g, self.outputs)
        # decode weights ONCE — Tensor.array() copies, and models carry
        # hundreds of MB of initializers; only tensors the sliced plan
        # actually reads are decoded (dead-tail weights stay raw bytes).
        # _node_reads includes subgraph-captured names: a runtime If/Loop
        # body referencing an outer initializer by name must find it decoded
        used = ({i for n in self._plan for i in _node_reads(n)}
                | set(self.outputs))
        self._weights = {k: t.array() for k, t in g.initializers.items()
                         if k in used}
        self._bf16 = None
        if precision == "bfloat16":
            # TPU-native mixed precision: f32 tensors ride the MXU as bf16
            # operands (matmul/conv still accumulate in f32 via
            # preferred_element_type); halves weight storage and roughly
            # doubles/triples MXU throughput vs f32 on v5e-class chips
            import jax.numpy as jnp

            self._bf16 = jnp.bfloat16
            self._weights = {k: (v.astype(jnp.bfloat16)
                                 if getattr(v, "dtype", None) == np.float32
                                 else v)
                             for k, v in self._weights.items()}

    @staticmethod
    def _make_plan(g: Graph, outputs: Sequence[str]) -> List[Node]:
        """Nodes needed for ``outputs``, in topological order (graph slicing:
        the ONNXModel.scala:203-227 analog)."""
        producer: Dict[str, Node] = {}
        for n in g.nodes:
            for o in n.outputs:
                producer[o] = n
        known = set(g.initializers) | {vi.name for vi in g.inputs}
        plan: List[Node] = []
        done = set()      # node ids fully emitted
        in_stack = set()  # node ids on the current path (cycle check)
        # iterative post-order DFS — exported transformer graphs routinely
        # exceed Python's recursion limit in depth
        work: List[Tuple[str, bool]] = [(o, False) for o in reversed(outputs)]
        while work:
            name, expanded = work.pop()
            if name == "" or name in known:
                continue
            n = producer.get(name)
            if n is None:
                raise ValueError(f"tensor {name!r} has no producer and is not "
                                 f"a graph input/initializer")
            if expanded:
                in_stack.discard(id(n))
                if id(n) not in done:
                    done.add(id(n))
                    plan.append(n)
                continue
            if id(n) in done:
                continue
            if id(n) in in_stack:
                raise ValueError(f"cycle through {name!r}")
            in_stack.add(id(n))
            work.append((name, True))
            for i in reversed(_node_reads(n)):
                work.append((i, False))
        return plan

    def _down(self, v):
        if self._bf16 is not None and getattr(v, "dtype", None) == np.float32:
            return v.astype(self._bf16)
        return v

    def __call__(self, feeds: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        env: Dict[str, np.ndarray] = dict(self._weights)
        for name in self.graph_inputs:
            if name not in feeds:
                raise ValueError(
                    f"missing input {name!r}; expected {self.graph_inputs}")
        for name, v in feeds.items():
            env[name] = self._down(v)
        self._run_nodes(self._plan, env)
        bf16 = self._bf16
        return {o: (env[o].astype(np.float32)
                    if bf16 is not None
                    and getattr(env[o], "dtype", None) == bf16
                    else env[o])
                for o in self.outputs}

    def _run_nodes(self, nodes: Sequence[Node], env: Dict) -> None:
        """Evaluate ``nodes`` (topological) into ``env`` in place — shared by
        the top-level plan and by control-flow subgraph bodies (which call it
        under a lax.cond/while_loop/scan trace)."""
        for node in nodes:
            if node.op_type in ("If", "Loop", "Scan"):
                out = getattr(self, "_exec_" + node.op_type.lower())(node, env)
            else:
                impl = REGISTRY.get(node.op_type)
                if impl is None:
                    raise NotImplementedError(
                        f"ONNX op {node.op_type!r} (node {node.name!r}) is "
                        f"not supported; supported: {sorted(REGISTRY)}")
                args = [env[i] if i else None for i in node.inputs]
                out = impl(node, *args)
            if not isinstance(out, tuple):
                out = (out,)
            for name, val in zip(node.outputs, out):
                if name:
                    # matmul/conv emit f32 accumulations; fold back to bf16 so
                    # the NEXT MXU op also reads bf16 operands — EXCEPT for
                    # explicit Cast nodes: a graph-mandated f32 island (e.g.
                    # guarding a softmax) keeps the precision it asked for
                    env[name] = (val if node.op_type == "Cast"
                                 else self._down(val))

    def _sub_info(self, sub: Graph) -> Tuple[Dict, List[str]]:
        """(decoded initializers, sorted captured names) for a control-flow
        subgraph, cached per graph object — bodies execute once per
        minibatch and must not re-decode weights or re-walk scopes each
        time (the top-level decode-ONCE policy, extended to subgraphs)."""
        if not hasattr(self, "_subcache"):
            self._subcache = {}
        info = self._subcache.get(id(sub))
        if info is None:
            info = ({k: self._down(t.array())
                     for k, t in sub.initializers.items()},
                    sorted(_free_names(sub)))
            self._subcache[id(sub)] = info
        return info

    def _run_subgraph(self, sub: Graph, bindings: Dict) -> tuple:
        """Run a control-flow body: fresh scope = decoded body initializers,
        overwritten by formal-input/captured ``bindings`` (Loop always binds
        iter/cond/carried OVER an initializer naming a body input — that
        initializer is the input's default, not the carried chain)."""
        sub_env = dict(self._sub_info(sub)[0])
        sub_env.update(bindings)
        self._run_nodes(sub.nodes, sub_env)
        return tuple(sub_env[vi.name] for vi in sub.outputs)

    def _exec_if(self, node: Node, env: Dict):
        """Data-dependent If → lax.cond. Both branches trace; XLA requires
        them to produce matching shapes/dtypes (validated loudly)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        then_g, else_g = node.attr("then_branch"), node.attr("else_branch")
        if then_g is None or else_g is None:
            raise ValueError(f"If node {node.name!r}: missing branch subgraph")
        for bname, br in (("then", then_g), ("else", else_g)):
            if len(br.outputs) != len(node.outputs):
                raise ValueError(
                    f"If node {node.name!r}: {bname} branch declares "
                    f"{len(br.outputs)} outputs but the If node has "
                    f"{len(node.outputs)}")
        captured = sorted(set(self._sub_info(then_g)[1])
                          | set(self._sub_info(else_g)[1]))
        cap_vals = tuple(env[c] for c in captured)

        def branch(sub):
            return lambda ops: self._run_subgraph(sub,
                                                  dict(zip(captured, ops)))

        # abstract-trace both branches up front: a mismatch gets a
        # descriptive error; a genuine op failure keeps its own traceback
        a_then = jax.eval_shape(branch(then_g), cap_vals)
        a_else = jax.eval_shape(branch(else_g), cap_vals)
        bad = [(t, e) for t, e in zip(a_then, a_else)
               if t.shape != e.shape or t.dtype != e.dtype]
        if bad:
            raise ValueError(
                f"If node {node.name!r}: a runtime (data-dependent) If needs "
                f"both branches to produce matching shapes/dtypes — XLA "
                f"compiles both and selects at run time. Mismatches: "
                + "; ".join(f"then {t.shape}/{t.dtype} vs else "
                            f"{e.shape}/{e.dtype}" for t, e in bad))
        pred = jnp.asarray(env[node.inputs[0]]).ravel()[0] != 0
        return lax.cond(pred, branch(then_g), branch(else_g), cap_vals)

    def _exec_loop(self, node: Node, env: Dict):
        """Data-dependent Loop → lax.while_loop. Carried-only loops support a
        fully dynamic trip count/condition; scan outputs need a static buffer
        (trip count when statically known, else ``max_loop_trips``) and are
        zero-padded past the actual exit iteration."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        body = node.attr("body")
        if body is None:
            raise ValueError(f"Loop node {node.name!r}: missing body graph")
        m_name = node.inputs[0] if node.inputs else ""
        c_name = node.inputs[1] if len(node.inputs) > 1 else ""
        carried_names = list(node.inputs[2:])
        n_carried = len(carried_names)
        n_scan = len(node.outputs) - n_carried
        body_in = [vi.name for vi in body.inputs]
        if len(body_in) != 2 + n_carried or n_scan < 0 or \
                len(body.outputs) != 1 + n_carried + n_scan:
            raise ValueError(
                f"Loop node {node.name!r}: body signature mismatch — body "
                f"({len(body_in)} in, {len(body.outputs)} out) vs node "
                f"({n_carried} carried, {n_scan} scan outputs)")
        captured = self._sub_info(body)[1]
        cap = {c: env[c] for c in captured}
        m_val = env[m_name] if m_name else None
        cond0 = env[c_name] if c_name else np.asarray(True)
        m_static = None
        if m_val is not None:
            try:
                m_static = int(np.asarray(m_val).ravel()[0])
            except (jax.errors.ConcretizationTypeError, TypeError,
                    jax.errors.TracerArrayConversionError):
                m_static = None        # trip count is data-dependent
            if m_static is not None and m_static >= 2**31 - 1:
                # torch serializes `while cond:` as Loop with trip_count
                # INT64_MAX — an unbounded sentinel, not a real bound (an
                # int32 compare against it would overflow and never iterate)
                m_val = m_static = None
        bound = m_static if m_static is not None else self.max_loop_trips

        def run_body(i, c, carried):
            bindings = dict(cap)
            bindings[body_in[0]] = jnp.asarray(i, jnp.int32)
            bindings[body_in[1]] = c
            bindings.update(zip(body_in[2:], carried))
            outs = self._run_subgraph(body, bindings)
            cond_out = jnp.asarray(outs[0]).ravel()[0] != 0
            return (cond_out, tuple(outs[1:1 + n_carried]),
                    tuple(outs[1 + n_carried:]))

        carried0 = tuple(jnp.asarray(env[i]) for i in carried_names)
        c0 = jnp.asarray(cond0).ravel()[0] != 0
        # one abstract body trace: scan-output shapes AND a descriptive
        # carried-aval invariance check (while_loop's own TypeError would
        # shadow genuine op errors if we blanket-caught it)
        a_cond, a_carried, a_scans = jax.eval_shape(
            lambda c, car: run_body(0, c, car), c0, carried0)
        bad = [(v, a) for v, a in zip(carried0, a_carried)
               if v.shape != a.shape or v.dtype != a.dtype]
        if bad:
            raise ValueError(
                f"Loop node {node.name!r}: carried state must keep a fixed "
                f"shape/dtype across iterations (XLA while_loop). "
                "Mismatches: " + "; ".join(
                    f"in {i.shape}/{i.dtype} vs out {o.shape}/{o.dtype}"
                    for i, o in bad))
        bufs0 = tuple(jnp.zeros((bound,) + s.shape, s.dtype)
                      for s in a_scans) if n_scan else ()

        def cond_fn(st):
            i, c = st[0], st[1]
            ok = c
            if m_val is not None:
                m = jnp.asarray(m_val, jnp.int32).ravel()[0]
                # a traced INT64_MAX while-sentinel wraps negative at the
                # x64-disabled boundary; any negative M means "no bound"
                ok = ok & ((i < m) | (m < 0))
            if n_scan and m_static is None:
                ok = ok & (i < bound)   # scan buffers are statically sized
            return ok

        def body_fn(st):
            i, c, carried, bufs = st
            c2, carried2, scans = run_body(i, c, carried)
            bufs2 = tuple(b.at[i].set(s) for b, s in zip(bufs, scans))
            return (i + 1, c2, carried2, bufs2)

        final_i, final_c, carried, bufs = lax.while_loop(
            cond_fn, body_fn, (jnp.int32(0), c0, carried0, bufs0))
        if n_scan and m_static is None:
            # the static scan buffer imposed the cap; exiting WITH the
            # condition still true means results were truncated — raise
            # when that is concretely checkable (eager path); under jit
            # the check cannot run and the truncation is documented
            try:
                if bool(final_c) and int(final_i) >= bound:
                    raise ValueError(
                        f"Loop node {node.name!r}: exited at "
                        f"max_loop_trips={bound} with its condition still "
                        f"true — scan outputs would be truncated. Raise "
                        f"max_loop_trips.")
            except jax.errors.ConcretizationTypeError:
                pass       # traced: the cap is not concretely checkable
        return tuple(carried) + tuple(bufs)

    def _exec_scan(self, node: Node, env: Dict):
        """ONNX Scan → lax.scan (the natural fit: fixed trip count from the
        scan-input length, carried state + stacked outputs)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        body = node.attr("body")
        n_scan_in = int(node.attr("num_scan_inputs", 0))
        if body is None or not n_scan_in:
            raise ValueError(f"Scan node {node.name!r}: missing body or "
                             f"num_scan_inputs")
        n_state = len(node.inputs) - n_scan_in
        n_scan_out = len(node.outputs) - n_state
        body_in = [vi.name for vi in body.inputs]
        if len(body_in) != len(node.inputs) or n_state < 0 or \
                n_scan_out < 0 or len(body.outputs) != len(node.outputs):
            raise ValueError(
                f"Scan node {node.name!r}: body signature mismatch")
        in_axes = node.attr("scan_input_axes") or [0] * n_scan_in
        in_dirs = node.attr("scan_input_directions") or [0] * n_scan_in
        out_axes = node.attr("scan_output_axes") or [0] * n_scan_out
        out_dirs = node.attr("scan_output_directions") or [0] * n_scan_out
        init = tuple(jnp.asarray(env[i]) for i in node.inputs[:n_state])
        xs = []
        for k, nm in enumerate(node.inputs[n_state:]):
            x = jnp.moveaxis(jnp.asarray(env[nm]), int(in_axes[k]), 0)
            if int(in_dirs[k]):
                x = jnp.flip(x, 0)
            xs.append(x)
        captured = self._sub_info(body)[1]
        cap = {c: env[c] for c in captured}

        def f(carry, x):
            bindings = dict(cap)
            bindings.update(zip(body_in[:n_state], carry))
            bindings.update(zip(body_in[n_state:], x))
            outs = self._run_subgraph(body, bindings)
            return tuple(outs[:n_state]), tuple(outs[n_state:])

        a_carry, _ = jax.eval_shape(f, init, tuple(x[0] for x in xs))
        bad = [(v, a) for v, a in zip(init, a_carry)
               if v.shape != a.shape or v.dtype != a.dtype]
        if bad:
            raise ValueError(
                f"Scan node {node.name!r}: carried state must keep a fixed "
                f"shape/dtype across iterations (lax.scan). Mismatches: "
                + "; ".join(f"in {i.shape}/{i.dtype} vs out "
                            f"{o.shape}/{o.dtype}" for i, o in bad))
        carry, ys = lax.scan(f, init, tuple(xs))
        ys2 = []
        for k, y in enumerate(ys):
            if int(out_dirs[k]):
                y = jnp.flip(y, 0)
            ys2.append(jnp.moveaxis(y, 0, int(out_axes[k])))
        return tuple(carry) + tuple(ys2)

    def as_jax(self, names: Optional[List[str]] = None):
        """(fn, input_names): positional jit-friendly callable. ``names``
        overrides the positional input ordering (default: graph order)."""
        names = list(names) if names is not None else list(self.graph_inputs)

        def fn(*arrays):
            return tuple(self({n: a for n, a in zip(names, arrays)}).values())

        return fn, names


def _free_names(sub: Graph) -> set:
    """Outer-scope tensor names a subgraph captures: referenced by its nodes
    (or returned as passthrough outputs) but neither produced inside it, nor
    among its initializers, nor its formal inputs. Nested subgraphs recurse —
    an inner capture bound at this level is not free here."""
    bound = ({o for n in sub.nodes for o in n.outputs if o}
             | set(sub.initializers) | {vi.name for vi in sub.inputs})
    free = set()
    for n in sub.nodes:
        for i in n.inputs:
            if i and i not in bound:
                free.add(i)
        for a in n.attrs.values():
            if a.g is not None:
                free |= _free_names(a.g) - bound
    for vi in sub.outputs:
        if vi.name and vi.name not in bound:
            free.add(vi.name)
    return free


def _node_reads(n: Node) -> List[str]:
    """Every outer tensor ``n`` consumes: declared inputs plus names its
    subgraph attributes capture by scope (If branches / Loop & Scan bodies
    reference outer tensors that never appear in node.inputs)."""
    reads = list(n.inputs)
    for a in n.attrs.values():
        if a.g is not None:
            reads.extend(sorted(_free_names(a.g)))
    return reads


def _resolve_constant(g: Graph, name: str, _depth: int = 0,
                      _producers=None, _memo=None):
    """The value of tensor ``name`` when derivable from initializers through
    constant-only ops; None when it depends on a graph input. Host-side
    mini-fold of just the ancestor chain, with a producer map + memo so
    shared-fan-in (diamond) chains resolve once, not once per path."""
    if name in g.initializers:
        return g.initializers[name].array()
    if _depth > 64:
        return None
    if _producers is None:
        _producers = {o: n for n in g.nodes for o in n.outputs if o}
    if _memo is None:
        _memo = {}
    if name in _memo:
        return _memo[name]
    _memo[name] = None               # cycle guard / negative cache
    producer = _producers.get(name)
    if producer is None or producer.op_type in ("Shape", "If"):
        return None
    impl = REGISTRY.get(producer.op_type)
    if impl is None:
        return None
    args = []
    for i in producer.inputs:
        if not i:
            args.append(None)
            continue
        v = _resolve_constant(g, i, _depth + 1, _producers, _memo)
        if v is None:
            return None
        args.append(v)
    try:
        out = impl(producer, *args)
    except Exception:
        return None
    if not isinstance(out, tuple):
        out = (out,)
    for o, v in zip(producer.outputs, out):
        _memo[o] = np.asarray(v)
    return _memo.get(name)


def _rename_in_subgraph(sub: Graph, rename: dict) -> Graph:
    """Copy of ``sub`` with CAPTURED outer-tensor references renamed.
    Names the subgraph itself produces or initializes are its own scope and
    stay untouched; nested subgraphs recurse."""
    shadowed = ({o for n in sub.nodes for o in n.outputs if o}
                | set(sub.initializers))
    eff = {k: v for k, v in rename.items() if k not in shadowed}
    out = copy.copy(sub)
    out.nodes = []
    for n in sub.nodes:
        n2 = copy.copy(n)
        n2.inputs = [eff.get(i, i) for i in n.inputs]
        if any(a.g is not None for a in n.attrs.values()):
            n2.attrs = {k: copy.copy(a) for k, a in n.attrs.items()}
            for a in n2.attrs.values():
                if a.g is not None:
                    a.g = _rename_in_subgraph(a.g, eff)
        out.nodes.append(n2)
    return out


def _clone_subgraph_nodes(nodes, rename: dict, prefix: str):
    """Copies of subgraph nodes with tensor references remapped, names
    prefixed, and NESTED subgraph attributes rename-fixed — the one shared
    scoping-sensitive block for If inlining and Loop unrolling."""
    out = []
    for n2 in nodes:
        n3 = copy.copy(n2)
        n3.inputs = [rename.get(i, i) for i in n2.inputs]
        n3.outputs = [rename.get(o, o) for o in n2.outputs]
        n3.name = prefix + (n2.name or n2.op_type)
        if any(a.g is not None for a in n2.attrs.values()):
            n3.attrs = {k: copy.copy(a) for k, a in n2.attrs.items()}
            for a in n3.attrs.values():
                if a.g is not None:
                    a.g = _rename_in_subgraph(a.g, rename)
        out.append(n3)
    return out


def _inline_constant_ifs(g: Graph) -> bool:
    """Replace every If node whose condition is derivable from constants
    with its chosen branch, inlined (TorchScript-exported models branch on
    traced config flags that serialize as constants — opset If semantics:
    branch subgraphs have no inputs and capture outer tensors by name).
    Branch-internal tensors are prefixed to avoid collisions; branch
    outputs map positionally onto the If node's outputs. Runs to fixpoint
    so nested constant Ifs inline too. A DATA-dependent If stays in place
    and executes at runtime through lax.cond (OnnxFunction._exec_if) —
    inlining the constant case keeps XLA from compiling both branches."""
    any_change = False
    changed = True
    while changed:
        changed = False
        for idx, node in enumerate(list(g.nodes)):
            if node.op_type != "If":
                continue
            cond = _resolve_constant(g, node.inputs[0])
            if cond is None:
                continue
            branch = node.attr("then_branch" if bool(np.asarray(cond).ravel()
                                                     [0])
                               else "else_branch")
            if branch is None:
                continue
            prefix = (node.name or f"if_{idx}") + "/"
            if len(branch.outputs) != len(node.outputs):
                raise ValueError(
                    f"If node {node.name or idx!r}: chosen branch declares "
                    f"{len(branch.outputs)} outputs but the If node has "
                    f"{len(node.outputs)} — malformed model")
            # branch outputs (positional) -> If outputs; a branch output the
            # branch neither produces nor initializes is a PASSTHROUGH of a
            # captured outer tensor — bridge it with Identity instead of
            # renaming the outer tensor
            produced = {o for n2 in branch.nodes for o in n2.outputs if o}
            rename, bridges = {}, []
            for vi, out in zip(branch.outputs, node.outputs):
                if vi.name in produced or vi.name in branch.initializers:
                    rename[vi.name] = out
                else:
                    bridges.append(Node(op_type="Identity",
                                        inputs=[vi.name], outputs=[out],
                                        name=prefix + "passthrough"))
            internal = (produced | set(branch.initializers)) - set(rename)
            rename.update({t: prefix + t for t in internal})
            for t, tensor in branch.initializers.items():
                g.initializers[rename.get(t, t)] = tensor
            g.nodes[idx:idx + 1] = _clone_subgraph_nodes(
                branch.nodes, rename, prefix) + bridges
            changed = True
            any_change = True
            break            # indices shifted: restart the scan
    return any_change


def _unroll_constant_loops(g: Graph) -> bool:
    """Unroll Loop nodes whose trip count is a derivable constant and whose
    condition stays constant-true (for-loop exports: fixed-length decoding,
    per-layer stacks). Loop body signature (opset): inputs
    (iter_num, cond_in, carried...), outputs (cond_out, carried_out...,
    scan_outputs...); scan outputs stack along a new axis 0 via Unsqueeze +
    Concat of per-iteration slices. Data-dependent trip counts / conditions
    stay in place and execute through lax.while_loop
    (OnnxFunction._exec_loop); unrolling the constant case gives XLA
    straight-line code to fuse across iterations."""
    from .protoio import Attribute, Tensor

    any_change = False
    changed = True
    while changed:
        changed = False
        for idx, node in enumerate(list(g.nodes)):
            if node.op_type != "Loop":
                continue
            body = node.attr("body")
            if body is None:
                continue
            m_name = node.inputs[0] if node.inputs else ""
            cond_name = node.inputs[1] if len(node.inputs) > 1 else ""
            m_val = _resolve_constant(g, m_name) if m_name else None
            cond0 = (_resolve_constant(g, cond_name) if cond_name
                     else np.asarray(True))
            if m_val is None or cond0 is None or not bool(
                    np.asarray(cond0).ravel()[0]):
                continue
            trips = int(np.asarray(m_val).ravel()[0])
            n_carried = len(node.inputs) - 2
            n_scan = len(node.outputs) - n_carried
            body_in = [vi.name for vi in body.inputs]
            body_out = [vi.name for vi in body.outputs]
            # only unroll when the body's cond_out is the unchanged cond_in
            # (possibly through an Identity chain) or a constant-true —
            # otherwise the loop is data-dependent
            src = body_out[0]
            body_producers = {o: n2 for n2 in body.nodes
                              for o in n2.outputs if o}
            for _ in range(16):
                p = body_producers.get(src)
                if p is not None and p.op_type == "Identity":
                    src = p.inputs[0]
                else:
                    break
            cond_out_const = _resolve_constant(body, body_out[0])
            if not (src == (body_in[1] if len(body_in) > 1 else None)
                    or (cond_out_const is not None
                        and bool(np.asarray(cond_out_const).ravel()[0]))):
                continue
            if trips > 256 or trips < 0:
                continue      # unrolling a huge loop would explode the graph
            if trips == 0 and n_scan > 0:
                continue      # empty scan stack has no static encoding here

            prefix0 = (node.name or f"loop_{idx}") + "/"
            new_nodes: List[Node] = []
            carried = list(node.inputs[2:])
            scan_parts: List[List[str]] = [[] for _ in range(n_scan)]
            produced = {o for n2 in body.nodes for o in n2.outputs if o}
            # body initializers are iteration-invariant: hoist ONCE under
            # the loop prefix. An initializer that names a body INPUT is
            # that input's DEFAULT value — Loop always supplies
            # iter/cond/carried, so the default must not shadow the bound
            # outer tensor (it would corrupt the carried chain).
            init_rename = {t: prefix0 + t for t in body.initializers
                           if t not in body_in}
            for t, tensor in body.initializers.items():
                if t not in body_in:
                    g.initializers[init_rename[t]] = tensor
            for it in range(trips):
                pfx = f"{prefix0}it{it}/"
                rename = dict(init_rename)
                # bind body inputs: iter_num + cond -> constants, carried ->
                # current values
                it_name = pfx + "iter"
                g.initializers[it_name] = Tensor.from_array(
                    it_name, np.asarray(it, np.int64))
                rename[body_in[0]] = it_name
                cd_name = pfx + "cond"
                g.initializers[cd_name] = Tensor.from_array(
                    cd_name, np.asarray(True))
                if len(body_in) > 1:
                    rename[body_in[1]] = cd_name
                for bi, cur in zip(body_in[2:], carried):
                    rename[bi] = cur
                internal = produced - set(rename)
                rename.update({t: pfx + t for t in internal})
                new_nodes.extend(_clone_subgraph_nodes(body.nodes, rename,
                                                       pfx))
                carried = [rename.get(o, o) for o in
                           body_out[1:1 + n_carried]]
                for s in range(n_scan):
                    src = rename.get(body_out[1 + n_carried + s],
                                     body_out[1 + n_carried + s])
                    un = pfx + f"scan{s}_unsq"
                    ax = pfx + f"scan{s}_axes"
                    g.initializers[ax] = Tensor.from_array(
                        ax, np.asarray([0], np.int64))
                    new_nodes.append(Node(op_type="Unsqueeze",
                                          inputs=[src, ax], outputs=[un],
                                          name=un))
                    scan_parts[s].append(un)
            # final wiring: carried outputs + stacked scan outputs
            for out_name, cur in zip(node.outputs[:n_carried], carried):
                new_nodes.append(Node(op_type="Identity", inputs=[cur],
                                      outputs=[out_name],
                                      name=prefix0 + "carry_out"))
            for s in range(n_scan):
                out_name = node.outputs[n_carried + s]
                cat = Node(op_type="Concat", inputs=scan_parts[s],
                           outputs=[out_name], name=prefix0 + f"scan{s}")
                cat.attrs["axis"] = Attribute(name="axis", type=2, i=0)
                new_nodes.append(cat)
            g.nodes[idx:idx + 1] = new_nodes
            changed = True
            any_change = True
            break
    return any_change


def import_model(model_bytes: bytes,
                 outputs: Optional[Sequence[str]] = None) -> OnnxFunction:
    return OnnxFunction(Model.parse(model_bytes), outputs)


def fold_constants(model: Model) -> Model:
    """Evaluate nodes with all-constant inputs once, promoting results to
    initializers (host-side; keeps Reshape/Slice args static under jit)."""
    g = model.graph
    const = dict(g.initializers)
    env = {k: t.array() for k, t in const.items()}
    keep: List[Node] = []
    from .protoio import Tensor

    for node in g.nodes:
        impl = REGISTRY.get(node.op_type)
        inputs_const = all((not i) or (i in env) for i in node.inputs)
        # Shape of a known-rank input is NOT constant in general (batch dim);
        # only fold Shape when the producer value is itself constant.
        if impl is not None and inputs_const and node.op_type != "Shape":
            try:
                out = impl(node, *[env[i] if i else None for i in node.inputs])
            except Exception:
                keep.append(node)
                continue
            if not isinstance(out, tuple):
                out = (out,)
            for name, val in zip(node.outputs, out):
                if name:
                    env[name] = np.asarray(val)
                    g.initializers[name] = Tensor.from_array(name, np.asarray(val))
        else:
            keep.append(node)
    g.nodes = keep
    return model
