"""ImageFeaturizer — headless CNN features from images.

Reference: deep-learning/.../onnx/ImageFeaturizer.scala (ONNXHub model +
ImageTransformer preprocessing; ``headless=True`` fetches the layer before the
classifier). Composes the framework's TPU image preprocessing
(ops/image.py) with ONNXModel: decode/resize/normalize → CHW tensor → imported
graph → feature vector (or logits when ``headless=False``).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.params import Param, HasInputCol, HasOutputCol
from ..core.pipeline import Transformer
from ..core.table import Table
from .model import ONNXModel


class ImageFeaturizer(Transformer, HasInputCol, HasOutputCol):
    headless = Param("headless", "fetch the penultimate (feature) tensor "
                     "instead of the final output", bool, True)
    onnxModel = Param("onnxModel", "the ONNXModel to run", is_complex=True)
    featureTensorName = Param("featureTensorName", "intermediate tensor to "
                              "fetch when headless (defaults to the input of "
                              "the last MatMul/Gemm node)", str)
    imageHeight = Param("imageHeight", "resize height", int, 224)
    imageWidth = Param("imageWidth", "resize width", int, 224)
    channelNormalizationMeans = Param("channelNormalizationMeans",
                                      "per-channel means", list,
                                      [0.485, 0.456, 0.406])
    channelNormalizationStds = Param("channelNormalizationStds",
                                     "per-channel stds", list,
                                     [0.229, 0.224, 0.225])
    scaleFactor = Param("scaleFactor", "pixel scale before normalize", float,
                        1.0 / 255.0)

    # cache of the configured (sliced) model so repeated transforms reuse the
    # parsed graph and its jit executables instead of recompiling per call
    _cfg_cache: Optional[tuple] = None

    def setModel(self, model: ONNXModel) -> "ImageFeaturizer":
        self._cfg_cache = None
        return self.set("onnxModel", model)

    def _configured_model(self, base: ONNXModel, fn, input_name: str) -> ONNXModel:
        # key holds `base` itself (not id()) — keeping the reference alive
        # prevents CPython id reuse from serving a stale sliced model
        key = (base, self.getHeadless(),
               self.get("featureTensorName"), self.getOutputCol())
        if (self._cfg_cache is not None and self._cfg_cache[0][0] is base
                and self._cfg_cache[0][1:] == key[1:]):
            return self._cfg_cache[1]
        model = base.copy()
        if self.getHeadless():
            model.setFetchDict({self.getOutputCol(): self._headless_output(base)})
        else:
            model.setFetchDict({self.getOutputCol(): fn.outputs[0]})
        model.set("softMaxDict", None)
        model.set("argMaxDict", None)
        model.setFeedDict({input_name: "__image_tensor"})
        self._cfg_cache = (key, model)
        return model

    def setModelPayload(self, payload: bytes) -> "ImageFeaturizer":
        return self.set("onnxModel", ONNXModel(modelPayload=payload))

    def _headless_output(self, base: ONNXModel) -> str:
        if self.isSet("featureTensorName"):
            return self.getFeatureTensorName()
        # default: the (non-weight) input of the last MatMul/Gemm — the
        # penultimate representation in classifier CNNs
        fn = base._onnx_fn()
        g = fn.model.graph
        inits = set(g.initializers)
        for node in reversed(g.nodes):
            if node.op_type in ("Gemm", "MatMul"):
                for i in node.inputs:
                    if i and i not in inits:
                        return i
        raise ValueError(
            "could not infer a feature tensor (no MatMul/Gemm head); set "
            "featureTensorName explicitly")

    def _transform(self, df: Table) -> Table:
        from ..ops import image as I

        base: Optional[ONNXModel] = self.get("onnxModel")
        if base is None:
            raise ValueError("ImageFeaturizer: onnxModel is not set")
        fn = base._onnx_fn()
        input_name = fn.graph_inputs[0]

        imgs = df[self.getInputCol()]
        if imgs.dtype == object:
            imgs = np.stack([np.asarray(v, dtype=np.float32) for v in imgs])
        batch = I.resize(np.asarray(imgs, np.float32),
                         self.getImageHeight(), self.getImageWidth())
        batch = I.normalize(batch, self.getChannelNormalizationMeans(),
                            self.getChannelNormalizationStds(),
                            scale=self.getScaleFactor())
        batch = I.to_chw(batch)

        model = self._configured_model(base, fn, input_name)

        work = df.with_column("__image_tensor",
                              np.asarray(batch, dtype=np.float32))
        out = model.transform(work)
        del out["__image_tensor"]
        feat = out[self.getOutputCol()]
        if feat.ndim > 2:  # flatten CNN feature maps to vectors
            out[self.getOutputCol()] = feat.reshape(feat.shape[0], -1)
        return out
