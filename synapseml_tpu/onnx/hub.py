"""ONNXHub — model-zoo manifest client with a local cache.

Reference: deep-learning/.../onnx/ONNXHub.scala (downloads models from the
onnx/models GitHub manifest, verifies sha256, caches locally). This
environment has no network egress, so downloads are gated: the manifest and
models resolve from the local cache dir (``SYNAPSEML_TPU_ONNX_HUB`` or
``~/.synapseml_tpu/onnx_hub``); a missing entry raises with instructions
rather than attempting a fetch. The API shape (list_models / get_model_info /
load) matches the reference so code written against it ports over.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional

_DEFAULT_REPO = "onnx/models:main"


def _cache_dir() -> str:
    return os.environ.get(
        "SYNAPSEML_TPU_ONNX_HUB",
        os.path.join(os.path.expanduser("~"), ".synapseml_tpu", "onnx_hub"))


@dataclass
class ONNXModelInfo:
    model: str
    model_path: str
    opset: int
    metadata: Dict


class ONNXHub:
    """Manifest-driven model registry (reference ONNXHub.scala)."""

    def __init__(self, cache_dir: Optional[str] = None):
        self.cache_dir = cache_dir or _cache_dir()

    def _manifest_path(self) -> str:
        return os.path.join(self.cache_dir, "ONNX_HUB_MANIFEST.json")

    def get_manifest(self) -> List[ONNXModelInfo]:
        path = self._manifest_path()
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"ONNX hub manifest not found at {path}. This environment has "
                "no network egress; place ONNX_HUB_MANIFEST.json (from the "
                "onnx/models repo) and the model files under "
                f"{self.cache_dir} to use the hub.")
        with open(path) as f:
            raw = json.load(f)
        return [ONNXModelInfo(m["model"], m["model_path"],
                              m.get("opset_version", 0), m.get("metadata", {}))
                for m in raw]

    def list_models(self, model: Optional[str] = None,
                    tags: Optional[List[str]] = None) -> List[ONNXModelInfo]:
        infos = self.get_manifest()
        if model:
            infos = [i for i in infos if model.lower() in i.model.lower()]
        if tags:
            tset = {t.lower() for t in tags}
            infos = [i for i in infos
                     if tset & {str(t).lower()
                                for t in i.metadata.get("tags", [])}]
        return infos

    def get_model_info(self, model: str,
                       opset: Optional[int] = None) -> ONNXModelInfo:
        matches = [i for i in self.get_manifest()
                   if i.model.lower() == model.lower()]
        if not matches:
            raise KeyError(f"model {model!r} not in manifest")
        if opset is not None:
            matches = [i for i in matches if i.opset == opset]
            if not matches:
                raise KeyError(f"model {model!r} has no opset {opset}")
        return max(matches, key=lambda i: i.opset)

    def load(self, model: str, opset: Optional[int] = None) -> bytes:
        info = self.get_model_info(model, opset)
        path = os.path.join(self.cache_dir, info.model_path)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"model file {path} missing from the local hub cache "
                "(no network egress to download it)")
        with open(path, "rb") as f:
            data = f.read()
        want = info.metadata.get("model_sha")
        if want:
            got = hashlib.sha256(data).hexdigest()
            if got != want:
                raise ValueError(f"sha256 mismatch for {model}: {got} != {want}")
        return data

    getModelInfo = get_model_info
    listModels = list_models
