"""Deterministic ONNX model generators — real-architecture graphs for tests
and benchmarks (VERDICT next-round #5: a >=50-node model with conv / pool /
gemm / layernorm / attention ops, exercised end-to-end through the importer
and ONNXModel, the parity surface of ONNXModel.scala:145-423).

The zero-egress environment has no model zoo, so the "real pretrained model"
is generated: genuine ResNet architecture (bottleneck residual blocks,
BatchNormalization folded as inference-mode) and a genuine transformer
encoder (multi-head self-attention + LayerNormalization + GELU MLP), with
seeded random weights, written through our own protobuf writer
(onnx/protoio.py) so the bytes are a spec-conformant .onnx file.
"""

from __future__ import annotations

from typing import List

import numpy as np

from .protoio import Attribute, Graph, Model, Node, Tensor, ValueInfo

_F32 = 1  # TensorProto.FLOAT


def _attr(name: str, v) -> Attribute:
    if isinstance(v, bool):
        return Attribute(name=name, type=2, i=int(v))
    if isinstance(v, int):
        return Attribute(name=name, type=2, i=v)
    if isinstance(v, float):
        return Attribute(name=name, type=1, f=v)
    if isinstance(v, str):
        return Attribute(name=name, type=3, s=v.encode())
    if isinstance(v, (list, tuple)):
        if all(isinstance(x, int) for x in v):
            return Attribute(name=name, type=7, ints=list(v))
        return Attribute(name=name, type=6, floats=[float(x) for x in v])
    raise TypeError(f"unsupported attribute value {v!r}")


def _vi(name: str, shape) -> ValueInfo:
    return ValueInfo(name=name, elem_type=_F32, shape=list(shape))


class _G:
    """Tiny graph builder: tracks nodes, initializers, and a name counter."""

    def __init__(self, seed: int):
        self.rng = np.random.default_rng(seed)
        self.nodes: List[Node] = []
        self.inits = {}
        self.n = 0

    def name(self, op: str) -> str:
        self.n += 1
        return f"{op.lower()}_{self.n}"

    def weight(self, shape, scale=None) -> str:
        nm = f"w_{self.n}_{'x'.join(map(str, shape))}"
        self.n += 1
        fan_in = int(np.prod(shape[1:])) if len(shape) > 1 else shape[0]
        s = scale if scale is not None else 1.0 / max(np.sqrt(fan_in), 1.0)
        arr = (self.rng.standard_normal(shape) * s).astype(np.float32)
        self.inits[nm] = Tensor.from_array(nm, arr)
        return nm

    def const(self, arr, nm=None) -> str:
        nm = nm or f"c_{self.n}"
        self.n += 1
        self.inits[nm] = Tensor.from_array(nm, np.asarray(arr))
        return nm

    def add(self, op: str, inputs, attrs=None, out=None) -> str:
        out = out or self.name(op)
        self.nodes.append(Node(op_type=op, inputs=list(inputs), outputs=[out],
                               name=out,
                               attrs={k: _attr(k, v) for k, v in
                                      (attrs or {}).items()}))
        return out

    def conv(self, x, cin, cout, k, stride=1) -> str:
        w = self.weight((cout, cin, k, k))
        pad = k // 2
        return self.add("Conv", [x, w],
                        {"strides": [stride, stride],
                         "pads": [pad, pad, pad, pad],
                         "kernel_shape": [k, k]})

    def bn(self, x, c) -> str:
        scale = self.const(np.abs(self.rng.standard_normal(c)).astype(np.float32) * 0.5 + 0.75)
        bias = self.const((self.rng.standard_normal(c) * 0.1).astype(np.float32))
        mean = self.const((self.rng.standard_normal(c) * 0.1).astype(np.float32))
        var = self.const(np.abs(self.rng.standard_normal(c)).astype(np.float32) * 0.1 + 0.9)
        return self.add("BatchNormalization", [x, scale, bias, mean, var],
                        {"epsilon": 1e-5})


def make_resnet(depth: int = 50, num_classes: int = 1000, seed: int = 0,
                image_size: int = 224) -> Model:
    """Genuine ResNet graph (bottleneck for depth>=50, basic blocks below);
    input 'data' (N, 3, S, S) → output 'logits' (N, num_classes)."""
    cfgs = {18: ([2, 2, 2, 2], False), 34: ([3, 4, 6, 3], False),
            50: ([3, 4, 6, 3], True), 101: ([3, 4, 23, 3], True)}
    blocks, bottleneck = cfgs[depth]
    g = _G(seed)
    x = g.conv("data", 3, 64, 7, stride=2)
    x = g.bn(x, 64)
    x = g.add("Relu", [x])
    x = g.add("MaxPool", [x], {"kernel_shape": [3, 3], "strides": [2, 2],
                               "pads": [1, 1, 1, 1]})
    cin = 64
    widths = [64, 128, 256, 512]
    for stage, (w, nb) in enumerate(zip(widths, blocks)):
        for b in range(nb):
            stride = 2 if (stage > 0 and b == 0) else 1
            cout = w * (4 if bottleneck else 1)
            shortcut = x
            if stride != 1 or cin != cout:
                shortcut = g.conv(x, cin, cout, 1, stride)
                shortcut = g.bn(shortcut, cout)
            if bottleneck:
                y = g.conv(x, cin, w, 1)
                y = g.bn(y, w)
                y = g.add("Relu", [y])
                y = g.conv(y, w, w, 3, stride)
                y = g.bn(y, w)
                y = g.add("Relu", [y])
                y = g.conv(y, w, cout, 1)
                y = g.bn(y, cout)
            else:
                y = g.conv(x, cin, w, 3, stride)
                y = g.bn(y, w)
                y = g.add("Relu", [y])
                y = g.conv(y, w, cout, 3)
                y = g.bn(y, cout)
            x = g.add("Add", [y, shortcut])
            x = g.add("Relu", [x], out=f"stage{stage}_block{b}_out")
            cin = cout
    x = g.add("GlobalAveragePool", [x])
    x = g.add("Flatten", [x], {"axis": 1}, out="features")
    wfc = g.weight((cin, num_classes))
    bfc = g.const(np.zeros(num_classes, np.float32))
    g.add("Gemm", ["features", wfc, bfc], {"alpha": 1.0, "beta": 1.0},
          out="logits")
    graph = Graph(nodes=g.nodes, initializers=g.inits,
                  inputs=[_vi("data", ["N", 3, image_size, image_size])],
                  outputs=[_vi("logits", ["N", num_classes])],
                  name=f"resnet{depth}")
    return Model(graph=graph, opset=13)


def make_transformer_encoder(num_layers: int = 2, d_model: int = 64,
                             num_heads: int = 4, seq_len: int = 32,
                             d_ff: int = 256, num_classes: int = 2,
                             seed: int = 1) -> Model:
    """Transformer encoder (pre-LN, full multi-head self-attention with
    Transpose/MatMul/Softmax, GELU MLP) over float input 'embeddings'
    (N, seq, d_model) → 'logits' (N, num_classes) via mean pooling."""
    g = _G(seed)
    hd = d_model // num_heads
    x = "embeddings"
    inv_sqrt = g.const(np.float32(1.0 / np.sqrt(hd)))
    for layer in range(num_layers):
        ln_s = g.const(np.ones(d_model, np.float32))
        ln_b = g.const(np.zeros(d_model, np.float32))
        h = g.add("LayerNormalization", [x, ln_s, ln_b], {"axis": -1,
                                                          "epsilon": 1e-5})
        # QKV projections
        heads_out = []
        proj = {}
        for nm in ("q", "k", "v"):
            w = g.weight((d_model, d_model))
            p = g.add("MatMul", [h, w])
            # (N, S, D) -> (N, S, H, hd) -> (N, H, S, hd)
            p = g.add("Reshape", [p, g.const(np.asarray([0, seq_len, num_heads,
                                                         hd], np.int64))])
            proj[nm] = g.add("Transpose", [p], {"perm": [0, 2, 1, 3]})
        kt = g.add("Transpose", [proj["k"]], {"perm": [0, 1, 3, 2]})
        att = g.add("MatMul", [proj["q"], kt])
        att = g.add("Mul", [att, inv_sqrt])
        att = g.add("Softmax", [att], {"axis": -1})
        ctx = g.add("MatMul", [att, proj["v"]])
        ctx = g.add("Transpose", [ctx], {"perm": [0, 2, 1, 3]})
        ctx = g.add("Reshape", [ctx, g.const(np.asarray([0, seq_len, d_model],
                                                        np.int64))])
        wo = g.weight((d_model, d_model))
        ctx = g.add("MatMul", [ctx, wo])
        x = g.add("Add", [x, ctx])
        # MLP
        ln2_s = g.const(np.ones(d_model, np.float32))
        ln2_b = g.const(np.zeros(d_model, np.float32))
        h2 = g.add("LayerNormalization", [x, ln2_s, ln2_b], {"axis": -1,
                                                             "epsilon": 1e-5})
        w1 = g.weight((d_model, d_ff))
        h2 = g.add("MatMul", [h2, w1])
        h2 = g.add("Gelu", [h2])
        w2 = g.weight((d_ff, d_model))
        h2 = g.add("MatMul", [h2, w2])
        x = g.add("Add", [x, h2], out=f"layer{layer}_out")
    pooled = g.add("ReduceMean", [x], {"axes": [1], "keepdims": 0},
                   out="pooled")
    wcls = g.weight((d_model, num_classes))
    bcls = g.const(np.zeros(num_classes, np.float32))
    g.add("Gemm", ["pooled", wcls, bcls], {"alpha": 1.0, "beta": 1.0},
          out="logits")
    graph = Graph(nodes=g.nodes, initializers=g.inits,
                  inputs=[_vi("embeddings", ["N", seq_len, d_model])],
                  outputs=[_vi("logits", ["N", num_classes])],
                  name="tiny_transformer_encoder")
    return Model(graph=graph, opset=13)


def make_unet(base: int = 8, depth: int = 3, image_size: int = 32,
              in_ch: int = 3, out_ch: int = 1, seed: int = 2) -> Model:
    """Genuine UNet encoder-decoder (Conv + GroupNorm + skip Concats,
    ConvTranspose upsampling, Sigmoid head) — exercises the extended op set
    the way segmentation/diffusion exports do."""
    g = _G(seed)

    def block(x, cin, cout):
        x = g.conv(x, cin, cout, 3)
        gs = g.const(np.ones(cout, np.float32))
        gb = g.const(np.zeros(cout, np.float32))
        x = g.add("GroupNormalization", [x, gs, gb],
                  {"num_groups": max(1, cout // 4), "epsilon": 1e-5})
        return g.add("HardSwish", [x])

    x = "image"
    skips = []
    ch = in_ch
    # encoder
    for d in range(depth):
        cout = base * (2 ** d)
        x = block(x, ch, cout)
        skips.append((x, cout))
        x = g.add("MaxPool", [x], {"kernel_shape": [2, 2],
                                   "strides": [2, 2]})
        ch = cout
    # bottleneck
    x = block(x, ch, ch * 2)
    ch = ch * 2
    # decoder
    for d in reversed(range(depth)):
        cskip = base * (2 ** d)
        wt = g.weight((ch, cskip, 2, 2))
        x = g.add("ConvTranspose", [x, wt],
                  {"strides": [2, 2], "kernel_shape": [2, 2]})
        skip, _ = skips[d]
        x = g.add("Concat", [x, skip], {"axis": 1})
        x = block(x, cskip * 2, cskip)
        ch = cskip
    w_head = g.weight((out_ch, ch, 1, 1))
    x = g.add("Conv", [x, w_head], {"kernel_shape": [1, 1]})
    g.add("Sigmoid", [x], out="mask")
    graph = Graph(nodes=g.nodes, initializers=g.inits,
                  inputs=[_vi("image", ["N", in_ch, image_size, image_size])],
                  outputs=[_vi("mask", ["N", out_ch, image_size, image_size])],
                  name="tiny_unet")
    return Model(graph=graph, opset=21)
