"""Trained GBDT Booster → ONNX TreeEnsemble graph.

The reference ecosystem's documented serving path for LightGBM models is
train → ``onnxmltools.convert_lightgbm`` → ONNXModel inference (reference:
website "Quickstart - ONNX Model Inference" notebook, which pip-installs
onnxmltools). This module is the native analog: it serializes a trained
:class:`~synapseml_tpu.gbdt.boosting.Booster` into an ``ai.onnx.ml``
TreeEnsembleClassifier / TreeEnsembleRegressor graph that both this repo's
executor (onnx/ops.py) and standard ONNX runtimes understand, so a GBDT
model can ride the same ONNXModel serving surface as any deep model.

Emission choices (spec-clean, exactly matching Booster.predict):
  * binary       → Classifier, per-leaf class-1 weights, base_values
                   [0, base], post_transform SOFTMAX (softmax([0, s]) ==
                   sigmoid(s), so probabilities match bit-for-tolerance)
  * multiclass   → Classifier, tree t contributes to class t % k,
                   post_transform SOFTMAX
  * regression   → Regressor, SUM aggregate, raw ensemble output (link
                   functions like poisson's exp are NOT applied — same as
                   LightGBM's own converter)
Categorical splits and rf (average_output) are rejected: BRANCH_EQ cannot
express LightGBM bitset membership, and averaged output has no faithful
TreeEnsemble encoding.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..gbdt.model_io import _tree_dump_seq
from .modelgen import _attr, _vi
from .protoio import Attribute, Graph, Model, Node


def _strs_attr(name: str, values: List[str]) -> Attribute:
    return Attribute(name=name, type=8,
                     strings=[v.encode() for v in values])


def booster_to_onnx(booster, input_name: str = "input",
                    num_iteration: int = -1) -> Model:
    """Serialize a trained Booster as an ONNX TreeEnsemble model.

    Outputs: classifier graphs expose ``label`` (int64) and
    ``probabilities`` (N, num_class); regressor graphs expose ``variable``
    (N, 1) — the onnxmltools naming, so downstream column wiring written
    for converted LightGBM models ports over unchanged.
    """
    cfg = booster.config
    if booster.average_output:
        raise NotImplementedError(
            "booster_to_onnx: rf/average_output has no faithful "
            "TreeEnsemble encoding (weights are averaged, not summed)")
    if int(getattr(cfg, "start_iteration", 0)) > 0:
        raise NotImplementedError(
            "booster_to_onnx: start_iteration prediction windows are not "
            "expressible in a TreeEnsemble (every tree contributes)")
    objective = cfg.objective
    classifier = objective in ("binary", "multiclass", "softmax",
                               "multiclassova")
    # sigmoid-family objectives apply sigmoid(cfg.sigmoid * raw); the graph
    # has no sigmoid-slope attribute, so the slope is folded into every leaf
    # weight and base value instead (probabilities then match exactly)
    ova = objective == "multiclassova"
    slope = float(cfg.sigmoid) if objective == "binary" or ova else 1.0
    k = booster.models_per_iter
    n_features = booster.mapper.num_features

    nodes_treeids: List[int] = []
    nodes_nodeids: List[int] = []
    nodes_featureids: List[int] = []
    nodes_values: List[float] = []
    nodes_modes: List[str] = []
    nodes_true: List[int] = []
    nodes_false: List[int] = []
    nodes_miss: List[int] = []
    leaf_treeids: List[int] = []
    leaf_nodeids: List[int] = []
    leaf_outids: List[int] = []
    leaf_weights: List[float] = []

    for ti, tree, thr, weight, _base_shift in _tree_dump_seq(
            booster, num_iteration):
        ns = int(tree.num_splits)
        if ns and np.asarray(tree.split_type)[:ns].any():
            raise NotImplementedError(
                "booster_to_onnx: categorical splits cannot be expressed "
                "as TreeEnsemble BRANCH_* modes (LightGBM's own converter "
                "has the same limitation)")
        out_id = ti % k if classifier and k > 1 else (
            1 if classifier else 0)
        lv = np.asarray(tree.leaf_value, np.float64) * float(weight) * slope
        if ns == 0:
            # single-leaf tree: one LEAF node, id 0
            nodes_treeids.append(ti)
            nodes_nodeids.append(0)
            nodes_featureids.append(0)
            nodes_values.append(0.0)
            nodes_modes.append("LEAF")
            nodes_true.append(0)
            nodes_false.append(0)
            nodes_miss.append(0)
            leaf_treeids.append(ti)
            leaf_nodeids.append(0)
            leaf_outids.append(out_id)
            leaf_weights.append(float(lv[0]))
            continue
        sf = np.asarray(tree.split_feature)[:ns]
        th = np.asarray(thr, np.float64)[:ns]
        dl = np.asarray(tree.default_left)[:ns]
        lc = np.asarray(tree.left_child)[:ns]
        rc = np.asarray(tree.right_child)[:ns]

        def node_id(c: int) -> int:
            # internal i -> i; leaf l (encoded ~l) -> ns + l
            return int(c) if c >= 0 else ns + int(~c)

        for i in range(ns):
            nodes_treeids.append(ti)
            nodes_nodeids.append(i)
            nodes_featureids.append(int(sf[i]))
            # our traversal is x <= thr -> left; +inf thresholds (top-bin
            # sentinel) stay +inf: BRANCH_LEQ with value=inf sends every
            # finite x left, matching the binned path
            nodes_values.append(float(th[i]))
            nodes_modes.append("BRANCH_LEQ")
            nodes_true.append(node_id(int(lc[i])))
            nodes_false.append(node_id(int(rc[i])))
            nodes_miss.append(int(bool(dl[i])))
        for leaf in range(ns + 1):
            nodes_treeids.append(ti)
            nodes_nodeids.append(ns + leaf)
            nodes_featureids.append(0)
            nodes_values.append(0.0)
            nodes_modes.append("LEAF")
            nodes_true.append(ns + leaf)
            nodes_false.append(ns + leaf)
            nodes_miss.append(0)
            leaf_treeids.append(ti)
            leaf_nodeids.append(ns + leaf)
            leaf_outids.append(out_id)
            leaf_weights.append(float(lv[leaf]))

    common = {
        "nodes_treeids": _attr("nodes_treeids", nodes_treeids),
        "nodes_nodeids": _attr("nodes_nodeids", nodes_nodeids),
        "nodes_featureids": _attr("nodes_featureids", nodes_featureids),
        "nodes_values": Attribute(name="nodes_values", type=6,
                                  floats=[float(v) for v in nodes_values]),
        "nodes_modes": _strs_attr("nodes_modes", nodes_modes),
        "nodes_truenodeids": _attr("nodes_truenodeids", nodes_true),
        "nodes_falsenodeids": _attr("nodes_falsenodeids", nodes_false),
        "nodes_missing_value_tracks_true":
            _attr("nodes_missing_value_tracks_true", nodes_miss),
    }
    base = np.asarray(booster.base_score, np.float64) * slope
    if classifier:
        n_class = max(k, 2)
        if k == 1:
            base_values = [0.0, float(base[0])]
        else:
            base_values = [float(b) for b in base[:n_class]]
        attrs = dict(common)
        attrs["classlabels_int64s"] = _attr("classlabels_int64s",
                                            list(range(n_class)))
        attrs["class_treeids"] = _attr("class_treeids", leaf_treeids)
        attrs["class_nodeids"] = _attr("class_nodeids", leaf_nodeids)
        attrs["class_ids"] = _attr("class_ids", leaf_outids)
        attrs["class_weights"] = Attribute(
            name="class_weights", type=6,
            floats=[float(w) for w in leaf_weights])
        attrs["base_values"] = Attribute(
            name="base_values", type=6, floats=base_values)
        # ova applies an UNNORMALIZED per-class sigmoid (objectives.py) —
        # LOGISTIC, not SOFTMAX; binary rides softmax([0, s]) == sigmoid(s)
        attrs["post_transform"] = _attr("post_transform",
                                        "LOGISTIC" if ova else "SOFTMAX")
        node = Node(op_type="TreeEnsembleClassifier", inputs=[input_name],
                    outputs=["label", "probabilities"],
                    name="tree_ensemble", attrs=attrs)
        outputs = [_vi("label", ["N"]), _vi("probabilities", ["N", n_class])]
        outputs[0].elem_type = 7          # int64 labels
    else:
        attrs = dict(common)
        attrs["n_targets"] = _attr("n_targets", 1)
        attrs["target_treeids"] = _attr("target_treeids", leaf_treeids)
        attrs["target_nodeids"] = _attr("target_nodeids", leaf_nodeids)
        attrs["target_ids"] = _attr("target_ids", leaf_outids)
        attrs["target_weights"] = Attribute(
            name="target_weights", type=6,
            floats=[float(w) for w in leaf_weights])
        attrs["base_values"] = Attribute(
            name="base_values", type=6, floats=[float(base[0])])
        attrs["post_transform"] = _attr("post_transform", "NONE")
        attrs["aggregate_function"] = _attr("aggregate_function", "SUM")
        node = Node(op_type="TreeEnsembleRegressor", inputs=[input_name],
                    outputs=["variable"], name="tree_ensemble", attrs=attrs)
        outputs = [_vi("variable", ["N", 1])]
    node.domain = "ai.onnx.ml"

    graph = Graph(nodes=[node], initializers={},
                  inputs=[_vi(input_name, ["N", n_features])],
                  outputs=outputs, name="gbdt_tree_ensemble")
    return Model(graph=graph, opset=17, ml_opset=3)
