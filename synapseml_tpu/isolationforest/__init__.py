"""Isolation forest anomaly detection.

Reference: core/.../isolationforest/IsolationForest.scala:17-72 — a thin wrapper
over LinkedIn's com.linkedin.isolation-forest estimator (SURVEY.md §2 N8:
"Own iForest implementation (vectorizable in XLA)"). Here the forest itself is
implemented: trees are grown host-side on small subsamples (cheap), encoded as
flat arrays, and scoring is a batched fixed-depth gather walk over all trees at
once under ``jit`` — no per-row recursion.
"""

from .iforest import IsolationForest, IsolationForestModel

__all__ = ["IsolationForest", "IsolationForestModel"]
