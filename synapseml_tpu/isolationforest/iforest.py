"""Isolation forest: array-encoded trees + batched XLA scoring.

Algorithm per Liu/Ting/Zhou: each tree isolates a subsample by random
(feature, split) choices to depth ceil(log2(maxSamples)); the anomaly score is
``2^(−E[pathLength]/c(n))``. Params mirror the LinkedIn estimator the reference
wraps (isolationforest/IsolationForest.scala:17-72): numEstimators, maxSamples,
maxFeatures, contamination, bootstrap, randomSeed, featuresCol, scoreCol,
predictionCol.

TPU design: a tree is four aligned arrays (featureIdx, threshold, leftChild,
pathLen); the forest stacks them [T, maxNodes]. Scoring walks all rows through
all trees simultaneously: ``maxDepth`` rounds of gathers (leaves self-loop), so
the jitted program is a static loop of vectorized gathers — no recursion, no
dynamic shapes.
"""

from __future__ import annotations


import numpy as np

from ..core.params import Param, HasFeaturesCol, HasPredictionCol
from ..core.pipeline import Estimator, Model
from ..core.table import Table, feature_matrix


def _c(n: float) -> float:
    """Average BST unsuccessful-search path length (normalizer)."""
    if n <= 1:
        return 0.0
    return 2.0 * (np.log(n - 1.0) + 0.5772156649) - 2.0 * (n - 1.0) / n


class _IForestParams(HasFeaturesCol, HasPredictionCol):
    numEstimators = Param("numEstimators", "Number of trees", int, 100)
    maxSamples = Param("maxSamples", "Subsample size per tree (<=1.0 means "
                       "fraction of rows)", float, 256.0)
    maxFeatures = Param("maxFeatures", "Fraction (or count) of features per tree",
                        float, 1.0)
    contamination = Param("contamination", "Expected outlier fraction; 0 means "
                          "no label thresholding", float, 0.0)
    contaminationError = Param("contaminationError",
                               "Tolerated error on contamination (unused on "
                               "exact quantiles; kept for API parity)", float, 0.0)
    bootstrap = Param("bootstrap", "Sample with replacement", bool, False)
    randomSeed = Param("randomSeed", "Seed", int, 1)
    scoreCol = Param("scoreCol", "Output column for anomaly score", str,
                     "outlierScore")


class IsolationForest(Estimator, _IForestParams):
    def _fit(self, df: Table) -> "IsolationForestModel":
        X = _matrix(df, self.getFeaturesCol())
        n, d = X.shape
        if n == 0:
            raise ValueError("IsolationForest: empty dataset")
        rng = np.random.default_rng(self.getRandomSeed())

        ms = self.getMaxSamples()
        sub = int(round(ms * n)) if ms <= 1.0 else int(ms)
        sub = max(2, min(sub, n))
        mf = self.getMaxFeatures()
        n_feat = int(round(mf * d)) if mf <= 1.0 else int(mf)
        n_feat = max(1, min(n_feat, d))
        max_depth = int(np.ceil(np.log2(sub)))
        max_nodes = 2 ** (max_depth + 1) - 1
        T = self.getNumEstimators()

        feat = np.zeros((T, max_nodes), dtype=np.int32)
        thresh = np.zeros((T, max_nodes), dtype=np.float32)
        left = np.zeros((T, max_nodes), dtype=np.int32)  # right = left+1; 0 = leaf
        plen = np.zeros((T, max_nodes), dtype=np.float32)

        for t in range(T):
            rows = (rng.integers(0, n, size=sub) if self.getBootstrap()
                    else rng.permutation(n)[:sub])
            feats = rng.permutation(d)[:n_feat]
            _grow(X[rows][:, feats], feats, rng, max_depth,
                  feat[t], thresh[t], left[t], plen[t])

        scores = _score(X, feat, thresh, left, plen, sub)
        thr = (float(np.quantile(scores, 1.0 - self.getContamination()))
               if self.getContamination() > 0 else None)
        return IsolationForestModel(
            forest={"feat": feat, "thresh": thresh, "left": left, "plen": plen,
                    "subSize": sub, "threshold": thr},
            **{p: self.get(p) for p in self._paramMap})


class IsolationForestModel(Model, _IForestParams):
    forest = Param("forest", "Array-encoded forest + score threshold",
                   is_complex=True)

    def _transform(self, df: Table) -> Table:
        f = self.get("forest")
        X = _matrix(df, self.getFeaturesCol())
        scores = _score(X, f["feat"], f["thresh"], f["left"], f["plen"],
                        f["subSize"])
        out = df.with_column(self.getScoreCol(), scores.astype(np.float64))
        thr = f.get("threshold")
        label = (scores >= thr) if thr is not None else np.zeros(len(scores), bool)
        return out.with_column(self.getPredictionCol(), label.astype(np.float64))


def _grow(Xs: np.ndarray, feats: np.ndarray, rng, max_depth: int,
          feat: np.ndarray, thresh: np.ndarray, left: np.ndarray,
          plen: np.ndarray) -> None:
    """Grow one tree into the preallocated arrays (host-side, subsample-sized)."""
    next_free = [1]

    def build(node: int, idx: np.ndarray, depth: int) -> None:
        n_here = idx.size
        lo = Xs[idx].min(axis=0) if n_here else None
        hi = Xs[idx].max(axis=0) if n_here else None
        if depth >= max_depth or n_here <= 1 or lo is None or (lo == hi).all():
            left[node] = 0  # leaf
            plen[node] = depth + _c(max(n_here, 1))
            return
        # random feature among those that still vary
        varying = np.flatnonzero(hi > lo)
        j = int(varying[rng.integers(0, varying.size)])
        s = float(rng.uniform(lo[j], hi[j]))
        feat[node] = feats[j]
        thresh[node] = s
        l = next_free[0]
        next_free[0] += 2
        left[node] = l
        go_left = Xs[idx, j] < s
        build(l, idx[go_left], depth + 1)
        build(l + 1, idx[~go_left], depth + 1)

    build(0, np.arange(Xs.shape[0]), 0)


_SCORE_CACHE = {}


def _score(X: np.ndarray, feat, thresh, left, plen, sub_size: int) -> np.ndarray:
    """Batched forest walk: rows × trees advance one level per iteration of a
    static ``fori_loop`` (leaves self-loop via child index 0 check)."""
    import jax
    import jax.numpy as jnp

    max_depth = int(np.ceil(np.log2(sub_size)))
    key = max_depth
    fn = _SCORE_CACHE.get(key)
    if fn is None:
        def score_fn(x, feat, thresh, left, plen):
            T = feat.shape[0]
            tree_ix = jnp.arange(T)[None, :]  # broadcast over rows

            def walk(cur, _):
                # cur: [rows, T] node index per (row, tree)
                f = feat[tree_ix, cur]      # [rows, T] feature at node
                th = thresh[tree_ix, cur]
                lf = left[tree_ix, cur]
                xv = jnp.take_along_axis(x, f, axis=1)  # row's value of f
                nxt = jnp.where(lf == 0, cur, jnp.where(xv < th, lf, lf + 1))
                return nxt, None

            cur = jnp.zeros((x.shape[0], T), dtype=jnp.int32)
            cur, _ = jax.lax.scan(walk, cur, None, length=max_depth + 1)
            path = plen[tree_ix, cur]  # [rows, T]
            return path.mean(axis=1)

        fn = _SCORE_CACHE.setdefault(key, jax.jit(score_fn))
    mean_path = np.asarray(fn(jnp.asarray(X, dtype=jnp.float32),
                              jnp.asarray(feat), jnp.asarray(thresh),
                              jnp.asarray(left), jnp.asarray(plen)))
    return np.exp2(-mean_path / _c(float(sub_size)))


def _matrix(df: Table, col: str) -> np.ndarray:
    X = feature_matrix(df, col)
    if X.ndim != 2:
        raise ValueError(f"features column {col!r} must be 2-D vectors")
    return X
