"""Azure AI Vision + Face transformers.

Reference: cognitive/.../services/vision/ComputerVision.scala (~787 LoC:
AnalyzeImage, DescribeImage, OCR, RecognizeText, TagImage, GenerateThumbnails)
and services/face/Face.scala (DetectFace, ...). Images go either as a URL
(``imageUrlCol``) or raw bytes (``imageBytesCol``, octet-stream body).
"""

from __future__ import annotations


from ..core.params import Param
from .base import HasAsyncReply, HasSetLocation


class _VisionBase(HasSetLocation):
    imageUrlCol = Param("imageUrlCol", "column of image urls", str)
    imageBytesCol = Param("imageBytesCol", "column of image bytes", str)
    urlPath = "vision/v3.2/analyze"

    def _prepare_headers(self, df, i):
        h = super()._prepare_headers(df, i)
        if self.isSet("imageBytesCol"):
            h["Content-Type"] = "application/octet-stream"
        return h

    def _prepare_body(self, df, i):
        if self.isSet("imageBytesCol"):
            b = df[self.getImageBytesCol()][i]
            return bytes(b) if b is not None else None
        if self.isSet("imageUrlCol"):
            u = df[self.getImageUrlCol()][i]
            return {"url": str(u)} if u is not None else None
        raise ValueError(f"{type(self).__name__}: set imageUrlCol or "
                         "imageBytesCol")


class AnalyzeImage(_VisionBase):
    visualFeatures = Param("visualFeatures", "features to extract", list,
                           ["Categories"])
    details = Param("details", "detail domains", list)
    descriptionExclude = Param("descriptionExclude", "models to exclude", list)

    def _prepare_url(self, df, i):
        q = "?visualFeatures=" + ",".join(self.getVisualFeatures())
        d = self.get("details")
        if d:
            q += "&details=" + ",".join(d)
        return super()._prepare_url(df, i) + q


class DescribeImage(_VisionBase):
    urlPath = "vision/v3.2/describe"
    maxCandidates = Param("maxCandidates", "number of captions", int, 1)

    def _prepare_url(self, df, i):
        return (super()._prepare_url(df, i)
                + f"?maxCandidates={self.getMaxCandidates()}")


class TagImage(_VisionBase):
    urlPath = "vision/v3.2/tag"


class OCR(_VisionBase):
    urlPath = "vision/v3.2/ocr"
    detectOrientation = Param("detectOrientation", "detect text orientation",
                              bool, True)

    def _prepare_url(self, df, i):
        return (super()._prepare_url(df, i)
                + f"?detectOrientation={str(self.getDetectOrientation()).lower()}")


class GenerateThumbnails(_VisionBase):
    urlPath = "vision/v3.2/generateThumbnail"
    width = Param("width", "thumbnail width", int, 64)
    height = Param("height", "thumbnail height", int, 64)
    smartCropping = Param("smartCropping", "smart-crop", bool, True)

    def _prepare_url(self, df, i):
        return (super()._prepare_url(df, i)
                + f"?width={self.getWidth()}&height={self.getHeight()}"
                  f"&smartCropping={str(self.getSmartCropping()).lower()}")

    def _parse_response(self, parsed, df, i):
        return parsed  # thumbnail bytes (non-JSON) come back as text fallback


class DetectFace(_VisionBase):
    urlPath = "face/v1.0/detect"
    returnFaceAttributes = Param("returnFaceAttributes", "attributes to return",
                                 list)
    returnFaceLandmarks = Param("returnFaceLandmarks", "return landmarks",
                                bool, False)

    def _prepare_url(self, df, i):
        q = f"?returnFaceLandmarks={str(self.getReturnFaceLandmarks()).lower()}"
        attrs = self.get("returnFaceAttributes")
        if attrs:
            q += "&returnFaceAttributes=" + ",".join(attrs)
        return super()._prepare_url(df, i) + q


class ReadImage(HasAsyncReply, _VisionBase):
    """Async Read OCR (reference vision/ComputerVision.scala ReadImage): POST
    returns 202 + Operation-Location; the shared HasAsyncReply flow polls it
    until succeeded/failed (synthetic 504 on poll exhaustion)."""

    urlPath = "vision/v3.2/read/analyze"


class RecognizeText(ReadImage):
    """Legacy recognizeText endpoint (reference RecognizeText) — same async
    submit/poll protocol as Read."""

    urlPath = "vision/v2.0/recognizeText"
    mode = Param("mode", "Handwritten|Printed", str, "Printed")

    def _prepare_url(self, df, i):
        return _VisionBase._prepare_url(self, df, i) + f"?mode={self.getMode()}"


class RecognizeDomainSpecificContent(_VisionBase):
    """Domain-model analysis, e.g. celebrities/landmarks (reference
    RecognizeDomainSpecificContent)."""

    model = Param("model", "domain model name", str, "celebrities")

    def _prepare_url(self, df, i):
        u = self.get("url")
        if not u:
            raise ValueError("set url or location first")
        base = u.split("/vision/")[0]
        return f"{base}/vision/v3.2/models/{self.getModel()}/analyze"


class _FaceIdBase(HasSetLocation):
    """Face ops over previously-detected faceIds (reference face/Face.scala:
    json bodies, no image payload)."""

    def _json_cols(self, df, i, mapping):
        body = {}
        for key, (pname, required) in mapping.items():
            v = self._resolve(pname, df, i)
            if v is None and required:
                return None
            if v is not None:
                body[key] = v.tolist() if hasattr(v, "tolist") else v
        return body


class FindSimilarFace(_FaceIdBase):
    urlPath = "face/v1.0/findsimilars"
    faceIdCol = Param("faceIdCol", "query faceId column", str, "faceId")
    faceListId = Param("faceListId", "face list to search", str)
    faceIds = Param("faceIds", "candidate faceIds", is_complex=True)
    maxNumOfCandidatesReturned = Param("maxNumOfCandidatesReturned",
                                       "max candidates", int, 20)
    mode = Param("mode", "matchPerson|matchFace", str, "matchPerson")

    def _prepare_body(self, df, i):
        fid = df[self.getFaceIdCol()][i]
        if fid is None:
            return None
        body = {"faceId": str(fid),
                "maxNumOfCandidatesReturned":
                    self.getMaxNumOfCandidatesReturned(),
                "mode": self.getMode()}
        if self.isSet("faceListId"):
            body["faceListId"] = self.get("faceListId")
        ids = self._resolve("faceIds", df, i)
        if ids is not None:
            body["faceIds"] = list(ids)
        return body


class GroupFaces(_FaceIdBase):
    urlPath = "face/v1.0/group"
    faceIdsCol = Param("faceIdsCol", "column of faceId lists", str, "faceIds")

    def _prepare_body(self, df, i):
        ids = df[self.getFaceIdsCol()][i]
        return {"faceIds": list(ids)} if ids is not None else None


class IdentifyFaces(_FaceIdBase):
    urlPath = "face/v1.0/identify"
    faceIdsCol = Param("faceIdsCol", "column of faceId lists", str, "faceIds")
    personGroupId = Param("personGroupId", "person group", str)
    largePersonGroupId = Param("largePersonGroupId", "large person group", str)
    maxNumOfCandidatesReturned = Param("maxNumOfCandidatesReturned",
                                       "max candidates", int, 1)
    confidenceThreshold = Param("confidenceThreshold", "identify threshold",
                                float)

    def _prepare_body(self, df, i):
        ids = df[self.getFaceIdsCol()][i]
        if ids is None:
            return None
        body = {"faceIds": list(ids),
                "maxNumOfCandidatesReturned":
                    self.getMaxNumOfCandidatesReturned()}
        for k in ("personGroupId", "largePersonGroupId"):
            if self.isSet(k):
                body[k] = self.get(k)
        thr = self.get("confidenceThreshold")
        if thr is not None:
            body["confidenceThreshold"] = thr
        return body


class VerifyFaces(_FaceIdBase):
    urlPath = "face/v1.0/verify"
    faceId1Col = Param("faceId1Col", "first faceId column", str, "faceId1")
    faceId2Col = Param("faceId2Col", "second faceId column", str, "faceId2")

    def _prepare_body(self, df, i):
        f1 = df[self.getFaceId1Col()][i]
        f2 = df[self.getFaceId2Col()][i]
        if f1 is None or f2 is None:
            return None
        return {"faceId1": str(f1), "faceId2": str(f2)}
