"""Azure AI Vision + Face transformers.

Reference: cognitive/.../services/vision/ComputerVision.scala (~787 LoC:
AnalyzeImage, DescribeImage, OCR, RecognizeText, TagImage, GenerateThumbnails)
and services/face/Face.scala (DetectFace, ...). Images go either as a URL
(``imageUrlCol``) or raw bytes (``imageBytesCol``, octet-stream body).
"""

from __future__ import annotations

from typing import List, Optional

from ..core.params import Param
from .base import HasSetLocation


class _VisionBase(HasSetLocation):
    imageUrlCol = Param("imageUrlCol", "column of image urls", str)
    imageBytesCol = Param("imageBytesCol", "column of image bytes", str)
    urlPath = "vision/v3.2/analyze"

    def _prepare_headers(self, df, i):
        h = super()._prepare_headers(df, i)
        if self.isSet("imageBytesCol"):
            h["Content-Type"] = "application/octet-stream"
        return h

    def _prepare_body(self, df, i):
        if self.isSet("imageBytesCol"):
            b = df[self.getImageBytesCol()][i]
            return bytes(b) if b is not None else None
        if self.isSet("imageUrlCol"):
            u = df[self.getImageUrlCol()][i]
            return {"url": str(u)} if u is not None else None
        raise ValueError(f"{type(self).__name__}: set imageUrlCol or "
                         "imageBytesCol")


class AnalyzeImage(_VisionBase):
    visualFeatures = Param("visualFeatures", "features to extract", list,
                           ["Categories"])
    details = Param("details", "detail domains", list)
    descriptionExclude = Param("descriptionExclude", "models to exclude", list)

    def _prepare_url(self, df, i):
        q = "?visualFeatures=" + ",".join(self.getVisualFeatures())
        d = self.get("details")
        if d:
            q += "&details=" + ",".join(d)
        return super()._prepare_url(df, i) + q


class DescribeImage(_VisionBase):
    urlPath = "vision/v3.2/describe"
    maxCandidates = Param("maxCandidates", "number of captions", int, 1)

    def _prepare_url(self, df, i):
        return (super()._prepare_url(df, i)
                + f"?maxCandidates={self.getMaxCandidates()}")


class TagImage(_VisionBase):
    urlPath = "vision/v3.2/tag"


class OCR(_VisionBase):
    urlPath = "vision/v3.2/ocr"
    detectOrientation = Param("detectOrientation", "detect text orientation",
                              bool, True)

    def _prepare_url(self, df, i):
        return (super()._prepare_url(df, i)
                + f"?detectOrientation={str(self.getDetectOrientation()).lower()}")


class GenerateThumbnails(_VisionBase):
    urlPath = "vision/v3.2/generateThumbnail"
    width = Param("width", "thumbnail width", int, 64)
    height = Param("height", "thumbnail height", int, 64)
    smartCropping = Param("smartCropping", "smart-crop", bool, True)

    def _prepare_url(self, df, i):
        return (super()._prepare_url(df, i)
                + f"?width={self.getWidth()}&height={self.getHeight()}"
                  f"&smartCropping={str(self.getSmartCropping()).lower()}")

    def _parse_response(self, parsed, df, i):
        return parsed  # thumbnail bytes (non-JSON) come back as text fallback


class DetectFace(_VisionBase):
    urlPath = "face/v1.0/detect"
    returnFaceAttributes = Param("returnFaceAttributes", "attributes to return",
                                 list)
    returnFaceLandmarks = Param("returnFaceLandmarks", "return landmarks",
                                bool, False)

    def _prepare_url(self, df, i):
        q = f"?returnFaceLandmarks={str(self.getReturnFaceLandmarks()).lower()}"
        attrs = self.get("returnFaceAttributes")
        if attrs:
            q += "&returnFaceAttributes=" + ",".join(attrs)
        return super()._prepare_url(df, i) + q
