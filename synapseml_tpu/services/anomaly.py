"""Anomaly Detector transformers.

Reference: cognitive/.../services/anomaly/AnomalyDetection.scala (~1279 LoC:
DetectLastAnomaly, DetectAnomalies, SimpleDetectAnomalies, and the
multivariate train/poll lifecycle in SimpleDetectMultivariateAnomaly). The
univariate detectors POST a ``{series, granularity}`` body; the multivariate
estimator's long-running train/poll flow is represented by
``DetectMultivariateAnomaly`` with explicit submit/poll helpers.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from ..core.params import Param
from ..core.pipeline import Estimator
from ..core.table import Table
from ..io.http import HTTPRequestData
from .base import HasAsyncReply, HasSetLocation


class _AnomalyBase(HasSetLocation):
    seriesCol = Param("seriesCol", "column of [{timestamp, value}] series",
                      str, "series")
    granularity = Param("granularity", "yearly|monthly|weekly|daily|hourly|"
                        "minutely|secondly", str, "monthly")
    maxAnomalyRatio = Param("maxAnomalyRatio", "max anomaly ratio", float)
    sensitivity = Param("sensitivity", "sensitivity 0-99", int)
    customInterval = Param("customInterval", "custom interval", int)
    urlPath = "anomalydetector/v1.0/timeseries/last/detect"

    def _prepare_body(self, df, i):
        series = df[self.getSeriesCol()][i]
        if series is None:
            return None
        body: Dict[str, Any] = {
            "series": [dict(p) for p in series],
            "granularity": self._resolve("granularity", df, i, "monthly")}
        for name in ("maxAnomalyRatio", "sensitivity", "customInterval"):
            v = self._resolve(name, df, i)
            if v is not None:
                body[name] = v
        return body


class DetectLastAnomaly(_AnomalyBase):
    urlPath = "anomalydetector/v1.0/timeseries/last/detect"


class DetectAnomalies(_AnomalyBase):
    urlPath = "anomalydetector/v1.0/timeseries/entire/detect"


class SimpleDetectAnomalies(DetectAnomalies):
    """Groups rows into series by ``groupbyCol`` then detects batch-wise
    (reference SimpleDetectAnomalies)."""

    groupbyCol = Param("groupbyCol", "column defining series groups", str)
    timestampCol = Param("timestampCol", "timestamp column", str, "timestamp")
    valueCol = Param("valueCol", "value column", str, "value")

    def _transform(self, df: Table) -> Table:
        import numpy as np

        gcol = self.get("groupbyCol")
        if not gcol:
            return super()._transform(df)
        groups = df[gcol]
        series_col = np.empty(df.num_rows, dtype=object)
        for g in np.unique(groups):
            rows = np.flatnonzero(groups == g)
            series = [{"timestamp": str(df[self.getTimestampCol()][r]),
                       "value": float(df[self.getValueCol()][r])}
                      for r in rows]
            for r in rows:
                series_col[r] = series
        work = df.with_column(self.getSeriesCol(), series_col)
        return super()._transform(work)


class DetectMultivariateAnomaly(HasAsyncReply, _AnomalyBase):
    """Multivariate anomaly detection with the reference's train → poll →
    infer lifecycle (SimpleDetectMultivariateAnomaly). ``train`` submits the
    model and polls until ready; ``_prepare_body`` runs inference."""

    # model training takes minutes: widen the shared LRO defaults
    pollInterval = Param("pollInterval", "seconds between status polls",
                         float, 5.0)
    maxPollRetries = Param("maxPollRetries", "max status polls", int, 120)

    @staticmethod
    def _status_of(info: dict) -> str:
        # model status lives under modelInfo.status
        return str((info.get("modelInfo") or {}).get("status",
                                                     info.get("status", "")))

    def _send_raw(self, req):
        """One request without the LRO interception (train() drives its own
        modelId-aware poll loop)."""
        from .base import CognitiveServiceBase

        return CognitiveServiceBase._send_one(self, req)

    modelId = Param("modelId", "trained model id", str)
    startTime = Param("startTime", "series start (ISO)", str)
    endTime = Param("endTime", "series end (ISO)", str)
    dataSource = Param("dataSource", "blob url of training data", str)
    urlPath = "anomalydetector/v1.1/multivariate/models"

    def train(self) -> str:
        """Submit a training job and poll until READY; returns modelId."""
        base = self.get("url")
        if not base:
            raise ValueError("set url/location first")
        body = {"dataSource": self.get("dataSource"),
                "startTime": self.get("startTime"),
                "endTime": self.get("endTime")}
        resp = self._send_raw(HTTPRequestData.from_json_body(
            base, body, self._prepare_headers(None, None)))
        if resp is None or not 200 <= resp.status_code < 300:
            raise RuntimeError(f"train submit failed: "
                               f"{getattr(resp, 'status_code', None)}")
        loc = resp.headers.get("Location", "")
        model_id = loc.rstrip("/").rsplit("/", 1)[-1]
        self.set("modelId", model_id)
        status_url = loc or f"{base}/{model_id}"
        for _ in range(self.getMaxPollRetries()):
            s = self._send_raw(HTTPRequestData(
                url=status_url, method="GET",
                headers=self._prepare_headers(None, None)))
            info = s.json() if s and s.entity else {}
            status = (info.get("modelInfo") or {}).get("status", "")
            if status in ("READY", "FAILED"):
                if status == "FAILED":
                    raise RuntimeError(f"model training failed: {info}")
                return model_id
            time.sleep(self.getPollInterval())
        raise TimeoutError("model training did not finish in time")

    def _prepare_url(self, df, i):
        mid = self._resolve("modelId", df, i)
        if not mid:
            raise ValueError("modelId not set — call train() first")
        return f"{self.get('url').rstrip('/')}/{mid}:detect-last"

    def _prepare_body(self, df, i):
        series = df[self.getSeriesCol()][i]
        return {"variables": series} if series is not None else None


class DetectLastMultivariateAnomaly(DetectMultivariateAnomaly):
    """Synchronous last-point multivariate detection (reference
    DetectLastMultivariateAnomaly — POST {modelId}:detect-last)."""


class SimpleFitMultivariateAnomaly(Estimator, DetectMultivariateAnomaly):
    """Estimator facade over the train → poll lifecycle (reference
    SimpleFitMultivariateAnomaly): ``fit`` submits training, polls to READY
    and returns a SimpleDetectMultivariateAnomaly bound to the model id.
    Training reads from the ``dataSource`` blob, so ``fit()`` may be called
    without a dataframe."""

    def fit(self, df=None, params=None):
        if df is None:
            return self._fit(None)
        return super().fit(df, params)

    def _fit(self, df: Optional[Table] = None) -> "SimpleDetectMultivariateAnomaly":
        model_id = self.train()
        m = SimpleDetectMultivariateAnomaly()
        for p in ("url", "subscriptionKey", "seriesCol", "pollInterval",
                  "maxPollRetries", "handler"):
            if self.isSet(p):
                m.set(p, self.get(p))
        m.set("modelId", model_id)
        return m


class SimpleDetectMultivariateAnomaly(DetectMultivariateAnomaly):
    """Batch multivariate inference with the async result poll (reference
    SimpleDetectMultivariateAnomaly: POST {modelId}:detect-batch → resultId →
    poll results/{resultId})."""

    topContributorCount = Param("topContributorCount",
                                "contributors per anomaly", int, 10)

    def _prepare_url(self, df, i):
        mid = self._resolve("modelId", df, i)
        if not mid:
            raise ValueError("modelId not set — fit first")
        return f"{self.get('url').rstrip('/')}/{mid}:detect-batch"

    def _prepare_body(self, df, i):
        series = df[self.getSeriesCol()][i]
        if series is None:
            return None
        body = {"variables": series,
                "topContributorCount": self.getTopContributorCount()}
        for k in ("startTime", "endTime"):
            v = self._resolve(k, df, i)
            if v is not None:
                body[k] = v
        return body

    @staticmethod
    def _status_of(info: dict) -> str:
        # batch-detect results report under summary.status
        return str((info.get("summary") or {}).get("status",
                                                   info.get("status", "")))
