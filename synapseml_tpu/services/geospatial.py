"""Azure Maps geospatial transformers.

Reference: cognitive/.../services/geospatial/ (~667 LoC: Geocoders.scala
AddressGeocoder/ReverseAddressGeocoder batch jobs, CheckPointInPolygon.scala,
AzureMapsTraits). Azure Maps uses ``subscription-key`` as a query parameter
rather than a header.
"""

from __future__ import annotations


from ..core.params import Param
from .base import CognitiveServiceBase

_ATLAS = "https://atlas.microsoft.com"


class _AzureMapsBase(CognitiveServiceBase):
    apiVersion = Param("apiVersion", "API version", str, "1.0")

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        if not self.isSet("url"):
            self.set("url", _ATLAS)

    def _key_query(self, df, i) -> str:
        key = self._resolve("subscriptionKey", df, i)
        return f"&subscription-key={key}" if key else ""


def _coords_present(df, stage, i) -> bool:
    """Null lat/lon rows are skipped (null output), matching the base
    protocol's _prepare_body-returns-None convention."""
    import numpy as np

    lat = df[stage.getLatitudeCol()][i]
    lon = df[stage.getLongitudeCol()][i]
    def ok(v):
        return v is not None and not (isinstance(v, float) and np.isnan(v))
    return ok(lat) and ok(lon)


class AddressGeocoder(_AzureMapsBase):
    """Address → coordinates (reference Geocoders.scala AddressGeocoder)."""

    addressCol = Param("addressCol", "column of address strings", str,
                       "address")

    def _prepare_method(self):
        return "GET"

    def _prepare_url(self, df, i):
        from urllib.parse import quote

        q = quote(str(df[self.getAddressCol()][i]))
        return (f"{self.get('url').rstrip('/')}/search/address/json"
                f"?api-version={self.getApiVersion()}&query={q}"
                + self._key_query(df, i))

    def _prepare_body(self, df, i):
        return b"" if df[self.getAddressCol()][i] is not None else None

    def _parse_response(self, parsed, df, i):
        try:
            return parsed["results"]
        except (KeyError, TypeError):
            return parsed


class ReverseAddressGeocoder(_AzureMapsBase):
    """(lat, lon) → address (reference ReverseAddressGeocoder)."""

    latitudeCol = Param("latitudeCol", "latitude column", str, "lat")
    longitudeCol = Param("longitudeCol", "longitude column", str, "lon")

    def _prepare_method(self):
        return "GET"

    def _prepare_url(self, df, i):
        lat = float(df[self.getLatitudeCol()][i])
        lon = float(df[self.getLongitudeCol()][i])
        return (f"{self.get('url').rstrip('/')}/search/address/reverse/json"
                f"?api-version={self.getApiVersion()}&query={lat},{lon}"
                + self._key_query(df, i))

    def _prepare_body(self, df, i):
        return b"" if _coords_present(df, self, i) else None

    def _parse_response(self, parsed, df, i):
        try:
            return parsed["addresses"]
        except (KeyError, TypeError):
            return parsed


class CheckPointInPolygon(_AzureMapsBase):
    """Point-in-polygon check against an uploaded geofence
    (reference CheckPointInPolygon.scala)."""

    latitudeCol = Param("latitudeCol", "latitude column", str, "lat")
    longitudeCol = Param("longitudeCol", "longitude column", str, "lon")
    userDataIdentifier = Param("userDataIdentifier",
                               "udid of the uploaded polygon set", str)

    def _prepare_method(self):
        return "GET"

    def _prepare_url(self, df, i):
        udid = self._resolve("userDataIdentifier", df, i)
        if not udid:
            raise ValueError("CheckPointInPolygon: userDataIdentifier not set")
        lat = float(df[self.getLatitudeCol()][i])
        lon = float(df[self.getLongitudeCol()][i])
        return (f"{self.get('url').rstrip('/')}/spatial/pointInPolygon/json"
                f"?api-version={self.getApiVersion()}&udid={udid}"
                f"&lat={lat}&lon={lon}" + self._key_query(df, i))

    def _prepare_body(self, df, i):
        if not self._resolve("userDataIdentifier", df, i):
            raise ValueError("CheckPointInPolygon: userDataIdentifier not set")
        return b"" if _coords_present(df, self, i) else None

    def _parse_response(self, parsed, df, i):
        try:
            return parsed["result"]
        except (KeyError, TypeError):
            return parsed
