"""Speech service transformers.

Reference: cognitive/.../services/speech/ (~1265 LoC: SpeechToText REST +
SpeechToTextSDK websocket streaming + ConversationTranscription,
TextToSpeech). The REST short-audio path posts bytes → transcript JSON;
SpeechToTextSDK implements the Speech websocket protocol (USP framing:
header-block text messages, length-prefixed binary audio messages, turn
lifecycle) over io/websocket.py with an injectable transport so tests drive
it against an in-process fake service.
"""

from __future__ import annotations

import datetime as _dt
import json as _json
import uuid as _uuid
from typing import List

import numpy as np

from ..core.params import Param
from ..core.table import Table
from .base import CognitiveServiceBase, HasAsyncReply


class SpeechToText(CognitiveServiceBase):
    """Short-audio recognition (reference SpeechToText.scala)."""

    audioDataCol = Param("audioDataCol", "column of WAV bytes", str, "audio")
    language = Param("language", "recognition language", str, "en-US")
    format = Param("format", "simple or detailed", str, "simple")
    profanity = Param("profanity", "masked|removed|raw", str, "masked")

    def setLocation(self, location: str):
        return self.set("url", f"https://{location}.stt.speech.microsoft.com/"
                               "speech/recognition/conversation/cognitiveservices/v1")

    def _prepare_url(self, df, i):
        return (super()._prepare_url(df, i)
                + f"?language={self._resolve('language', df, i, 'en-US')}"
                  f"&format={self.getFormat()}"
                  f"&profanity={self.getProfanity()}")

    def _prepare_headers(self, df, i):
        h = super()._prepare_headers(df, i)
        h["Content-Type"] = "audio/wav; codecs=audio/pcm; samplerate=16000"
        return h

    def _prepare_body(self, df, i):
        b = df[self.getAudioDataCol()][i]
        return bytes(b) if b is not None else None


def _usp_timestamp() -> str:
    return _dt.datetime.now(_dt.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"


def usp_text_message(path: str, request_id: str, body: dict) -> str:
    """Speech USP text message: header block + blank line + JSON body."""
    return (f"Path: {path}\r\nX-RequestId: {request_id}\r\n"
            f"X-Timestamp: {_usp_timestamp()}\r\n"
            "Content-Type: application/json; charset=utf-8\r\n\r\n"
            + _json.dumps(body))


def usp_audio_message(request_id: str, chunk: bytes) -> bytes:
    """Speech USP binary message: big-endian u16 header length + headers +
    audio payload (empty payload = end of stream)."""
    headers = (f"Path: audio\r\nX-RequestId: {request_id}\r\n"
               f"X-Timestamp: {_usp_timestamp()}\r\n"
               "Content-Type: audio/x-wav\r\n").encode()
    return len(headers).to_bytes(2, "big") + headers + chunk


def usp_parse_text(msg: bytes):
    """(headers-dict, json-body) of a server USP text message."""
    head, _, body = msg.partition(b"\r\n\r\n")
    headers = {}
    for line in head.split(b"\r\n"):
        if b":" in line:
            k, v = line.split(b":", 1)
            headers[k.strip().decode().lower()] = v.strip().decode()
    try:
        parsed = _json.loads(body.decode("utf-8")) if body else {}
    except ValueError:
        parsed = {"raw": body.decode("utf-8", "replace")}
    return headers, parsed


class SpeechToTextSDK(SpeechToText):
    """Streaming recognition over the Speech websocket protocol (reference
    speech/SpeechToTextSDK.scala — the SDK's USP transport): connect,
    send speech.config + audio chunks, collect speech.phrase events until
    turn.end. ``wsTransport`` injects a connected socket-like object (tests /
    tunnels); by default a TLS websocket is opened to the region endpoint.
    """

    mode = Param("mode", "conversation|dictation|interactive", str,
                 "conversation")
    chunkSize = Param("chunkSize", "audio bytes per websocket message", int,
                      8192)
    streamIntermediateResults = Param(
        "streamIntermediateResults",
        "include speech.hypothesis events in the output", bool, False)
    wsTransport = Param("wsTransport", "callable url,headers -> socket-like "
                        "(test/tunnel injection)", is_complex=True)

    def _ws_path(self, df, i) -> str:
        mode = self._resolve("mode", df, i, "conversation")
        return f"/speech/recognition/{mode}/cognitiveservices/v1"

    def _ws_url(self, df, i):
        base = self.get("url") or ""
        if base.startswith("http"):
            base = "ws" + base[4:]
        lang = self._resolve("language", df, i, "en-US")
        if "/speech/" not in base and "/transcribe" not in base:
            base = base.rstrip("/") + self._ws_path(df, i)
        sep = "&" if "?" in base else "?"
        return f"{base}{sep}language={lang}&format={self.getFormat()}"

    def setLocation(self, location: str):
        return self.set(
            "url", f"wss://{location}.stt.speech.microsoft.com")

    def _recognize_one(self, audio: bytes, df, i) -> List[dict]:
        from ..io.websocket import WebSocketClient, WebSocketError

        url = self._ws_url(df, i)
        headers = {"X-ConnectionId": _uuid.uuid4().hex}
        key = self._resolve("subscriptionKey", df, i)
        if key:
            headers["Ocp-Apim-Subscription-Key"] = str(key)
        tok = self._resolve("AADToken", df, i)
        if tok:
            headers["Authorization"] = f"Bearer {tok}"
        transport = self.get("wsTransport")
        sock = transport(url, headers) if transport else None
        ws = WebSocketClient(url, headers=headers, sock=sock,
                             timeout=self.getTimeout())
        request_id = _uuid.uuid4().hex
        events: List[dict] = []
        with ws:
            ws.send_text(usp_text_message("speech.config", request_id, {
                "context": {"system": {"name": "synapseml_tpu"},
                            "os": {"platform": "python"}}}))
            cs = max(1, self.getChunkSize())
            for off in range(0, len(audio), cs):
                ws.send_binary(usp_audio_message(request_id,
                                                 audio[off:off + cs]))
            ws.send_binary(usp_audio_message(request_id, b""))  # end stream
            want_hyp = self.get("streamIntermediateResults")
            while True:
                try:
                    opcode, payload = ws.recv()
                except WebSocketError:
                    break
                if opcode != 1:          # only text messages carry events
                    continue
                hdrs, body = usp_parse_text(payload)
                path = hdrs.get("path", "")
                if path == "speech.phrase" or (want_hyp and
                                               path == "speech.hypothesis"):
                    events.append(dict(body, **{"_path": path}))
                if path == "turn.end":
                    break
        return events

    def _transform(self, df: Table) -> Table:
        n = df.num_rows
        out = np.empty(n, dtype=object)
        err = np.empty(n, dtype=object)
        col = self.getAudioDataCol()
        for i in range(n):
            b = df[col][i]
            if b is None:
                out[i] = None
                err[i] = None
                continue
            try:
                out[i] = self._recognize_one(bytes(b), df, i)
                err[i] = None
            except Exception as e:  # noqa: BLE001 — per-row error column
                out[i] = None
                err[i] = {"error": str(e)[:500]}
        res = df.with_column(self.get("outputCol"), out)
        return res.with_column(self.get("errorCol"), err)


class ConversationTranscription(SpeechToTextSDK):
    """Multi-speaker transcription over the same websocket protocol
    (reference speech/ConversationTranscription.scala): the conversation
    transcription service endpoint (cts domain, /transcribe path), same USP
    framing."""

    def _ws_path(self, df, i) -> str:
        return "/speech/recognition/transcribe/cognitiveservices/v1"

    def setLocation(self, location: str):
        return self.set(
            "url", f"wss://{location}.cts.speech.microsoft.com")


class SpeakerEmotionInference(CognitiveServiceBase):
    """SSML voice-style inference for dialog text (reference
    speech/SpeakerEmotionInference.scala): POST text → per-segment style
    annotations used to build expressive SSML."""

    textCol = Param("textCol", "column of texts", str, "text")
    locale = Param("locale", "text locale", str, "en-US")
    voiceName = Param("voiceName", "voice for synthesis hints", str,
                      "en-US-JennyNeural")

    def setLocation(self, location: str):
        return self.set("url", f"https://{location}.api.cognitive.microsoft."
                               "com/cognitiveservices/v1/ssml/inference")

    def _prepare_body(self, df, i):
        text = df[self.getTextCol()][i]
        if text is None:
            return None
        return {"text": str(text),
                "locale": self._resolve("locale", df, i, "en-US"),
                "voiceName": self._resolve("voiceName", df, i)}


class TextToSpeech(CognitiveServiceBase):
    """SSML → audio bytes (reference TextToSpeech.scala)."""

    textCol = Param("textCol", "column of texts", str, "text")
    voiceName = Param("voiceName", "synthesis voice", str,
                      "en-US-JennyNeural")
    language = Param("language", "voice language", str, "en-US")
    outputFormat = Param("outputFormat", "audio format", str,
                         "riff-16khz-16bit-mono-pcm")

    def setLocation(self, location: str):
        return self.set("url", f"https://{location}.tts.speech.microsoft.com/"
                               "cognitiveservices/v1")

    def _prepare_headers(self, df, i):
        h = super()._prepare_headers(df, i)
        h["Content-Type"] = "application/ssml+xml"
        h["X-Microsoft-OutputFormat"] = self.getOutputFormat()
        return h

    def _prepare_body(self, df, i):
        from xml.sax.saxutils import escape, quoteattr

        text = df[self.getTextCol()][i]
        if text is None:
            return None
        voice = self._resolve("voiceName", df, i, "en-US-JennyNeural")
        lang = self._resolve("language", df, i, "en-US")
        ssml = (f"<speak version='1.0' xml:lang={quoteattr(str(lang))}>"
                f"<voice name={quoteattr(str(voice))}>"
                f"{escape(str(text))}</voice></speak>")
        return ssml.encode()

    def _parse_response(self, parsed, df, i):
        return parsed  # audio bytes arrive via text fallback; kept raw


class AnalyzeDocument(HasAsyncReply):
    """Document Intelligence (Form Recognizer) analyze with LRO polling
    (reference cognitive/.../services/form/FormRecognizer.scala, ~849 LoC —
    AnalyzeDocument submits then polls the operation-location via the shared
    HasAsyncReply flow)."""

    imageBytesCol = Param("imageBytesCol", "column of document bytes", str)
    imageUrlCol = Param("imageUrlCol", "column of document urls", str)
    modelId = Param("modelId", "prebuilt-layout, prebuilt-invoice, ...", str,
                    "prebuilt-layout")
    apiVersion = Param("apiVersion", "API version", str, "2023-07-31")

    def setLocation(self, location: str):
        return self.set("url",
                        f"https://{location}.api.cognitive.microsoft.com")

    def _prepare_url(self, df, i):
        return (f"{self.get('url').rstrip('/')}/formrecognizer/documentModels/"
                f"{self.getModelId()}:analyze?api-version={self.getApiVersion()}")

    def _prepare_headers(self, df, i):
        h = super()._prepare_headers(df, i)
        if self.isSet("imageBytesCol"):
            h["Content-Type"] = "application/octet-stream"
        return h

    def _prepare_body(self, df, i):
        if self.isSet("imageBytesCol"):
            b = df[self.getImageBytesCol()][i]
            return bytes(b) if b is not None else None
        u = df[self.getImageUrlCol()][i]
        return {"urlSource": str(u)} if u is not None else None

