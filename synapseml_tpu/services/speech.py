"""Speech service transformers.

Reference: cognitive/.../services/speech/ (~1265 LoC: SpeechToText REST +
SpeechToTextSDK websocket streaming, TextToSpeech). The REST short-audio path
is implemented (bytes → transcript JSON, SSML → audio bytes); the websocket
streaming variant is out of scope for a host-side wrapper and documented as
such on SpeechToTextSDK.
"""

from __future__ import annotations

from typing import Optional

from ..core.params import Param
from .base import CognitiveServiceBase


class SpeechToText(CognitiveServiceBase):
    """Short-audio recognition (reference SpeechToText.scala)."""

    audioDataCol = Param("audioDataCol", "column of WAV bytes", str, "audio")
    language = Param("language", "recognition language", str, "en-US")
    format = Param("format", "simple or detailed", str, "simple")
    profanity = Param("profanity", "masked|removed|raw", str, "masked")

    def setLocation(self, location: str):
        return self.set("url", f"https://{location}.stt.speech.microsoft.com/"
                               "speech/recognition/conversation/cognitiveservices/v1")

    def _prepare_url(self, df, i):
        return (super()._prepare_url(df, i)
                + f"?language={self._resolve('language', df, i, 'en-US')}"
                  f"&format={self.getFormat()}"
                  f"&profanity={self.getProfanity()}")

    def _prepare_headers(self, df, i):
        h = super()._prepare_headers(df, i)
        h["Content-Type"] = "audio/wav; codecs=audio/pcm; samplerate=16000"
        return h

    def _prepare_body(self, df, i):
        b = df[self.getAudioDataCol()][i]
        return bytes(b) if b is not None else None


class SpeechToTextSDK(SpeechToText):
    """Reference streams via the Speech SDK websocket
    (speech/SpeechToTextSDK.scala); this build routes through the REST
    short-audio endpoint — same output schema for clips <= 60s."""


class TextToSpeech(CognitiveServiceBase):
    """SSML → audio bytes (reference TextToSpeech.scala)."""

    textCol = Param("textCol", "column of texts", str, "text")
    voiceName = Param("voiceName", "synthesis voice", str,
                      "en-US-JennyNeural")
    language = Param("language", "voice language", str, "en-US")
    outputFormat = Param("outputFormat", "audio format", str,
                         "riff-16khz-16bit-mono-pcm")

    def setLocation(self, location: str):
        return self.set("url", f"https://{location}.tts.speech.microsoft.com/"
                               "cognitiveservices/v1")

    def _prepare_headers(self, df, i):
        h = super()._prepare_headers(df, i)
        h["Content-Type"] = "application/ssml+xml"
        h["X-Microsoft-OutputFormat"] = self.getOutputFormat()
        return h

    def _prepare_body(self, df, i):
        from xml.sax.saxutils import escape, quoteattr

        text = df[self.getTextCol()][i]
        if text is None:
            return None
        voice = self._resolve("voiceName", df, i, "en-US-JennyNeural")
        lang = self._resolve("language", df, i, "en-US")
        ssml = (f"<speak version='1.0' xml:lang={quoteattr(str(lang))}>"
                f"<voice name={quoteattr(str(voice))}>"
                f"{escape(str(text))}</voice></speak>")
        return ssml.encode()

    def _parse_response(self, parsed, df, i):
        return parsed  # audio bytes arrive via text fallback; kept raw


class AnalyzeDocument(CognitiveServiceBase):
    """Document Intelligence (Form Recognizer) analyze with LRO polling
    (reference cognitive/.../services/form/FormRecognizer.scala, ~849 LoC —
    AnalyzeDocument submits then polls the operation-location)."""

    imageBytesCol = Param("imageBytesCol", "column of document bytes", str)
    imageUrlCol = Param("imageUrlCol", "column of document urls", str)
    modelId = Param("modelId", "prebuilt-layout, prebuilt-invoice, ...", str,
                    "prebuilt-layout")
    apiVersion = Param("apiVersion", "API version", str, "2023-07-31")
    pollInterval = Param("pollInterval", "seconds between polls", float, 1.0)
    maxPollRetries = Param("maxPollRetries", "max polls", int, 60)

    def setLocation(self, location: str):
        return self.set("url",
                        f"https://{location}.api.cognitive.microsoft.com")

    def _prepare_url(self, df, i):
        return (f"{self.get('url').rstrip('/')}/formrecognizer/documentModels/"
                f"{self.getModelId()}:analyze?api-version={self.getApiVersion()}")

    def _prepare_headers(self, df, i):
        h = super()._prepare_headers(df, i)
        if self.isSet("imageBytesCol"):
            h["Content-Type"] = "application/octet-stream"
        return h

    def _prepare_body(self, df, i):
        if self.isSet("imageBytesCol"):
            b = df[self.getImageBytesCol()][i]
            return bytes(b) if b is not None else None
        u = df[self.getImageUrlCol()][i]
        return {"urlSource": str(u)} if u is not None else None

    def _send_one(self, req):
        """Submit + poll the Operation-Location (LRO)."""
        import time as _t

        from ..io.http import HTTPRequestData

        from ..io.http import HTTPResponseData

        first = super()._send_one(req)
        if first is None or first.status_code not in (200, 201, 202):
            return first
        loc = first.headers.get("Operation-Location")
        if not loc:
            return first
        headers = {k: v for k, v in req.headers.items()
                   if k.lower() != "content-type"}
        poll = None
        for _ in range(self.getMaxPollRetries()):
            poll = super()._send_one(HTTPRequestData(
                url=loc, method="GET", headers=headers))
            if poll is None:
                break
            info = poll.json() if poll.entity else {}
            if info.get("status") in ("succeeded", "failed"):
                return poll
            _t.sleep(self.getPollInterval())
        # poll exhausted/errored: report a timeout, NOT the 202 submit ack
        return HTTPResponseData(
            status_code=504,
            reason=f"operation at {loc} did not complete within "
                   f"{self.getMaxPollRetries()} polls",
            entity=(poll.entity if poll is not None else None))
