"""OpenAI / Azure OpenAI transformers.

Reference: cognitive/.../services/openai/ (OpenAICompletion.scala,
OpenAIChatCompletion.scala, OpenAIEmbedding.scala, OpenAIPrompt.scala:22+,
OpenAI.scala shared params). Request/response shapes follow the Azure OpenAI
REST API; ``deploymentName`` + base url compose the endpoint, and every
sampling param is a ServiceParam (scalar or column).
"""

from __future__ import annotations

import json as _json
import re
from typing import Any, Dict, List

import numpy as np

from ..core.params import Param
from ..core.table import Table
from .base import CognitiveServiceBase


class _OpenAIBase(CognitiveServiceBase):
    deploymentName = Param("deploymentName", "the name of the deployment", str)
    apiVersion = Param("apiVersion", "the API version to use", str,
                       "2024-02-01")
    maxTokens = Param("maxTokens", "maximum tokens to generate", int)
    temperature = Param("temperature", "sampling temperature", float)
    topP = Param("topP", "nucleus sampling probability", float)
    stop = Param("stop", "stop sequence(s)", is_complex=True)
    user = Param("user", "end-user id for abuse monitoring", str)

    _endpoint = "completions"

    def _prepare_headers(self, df, i):
        h = super()._prepare_headers(df, i)
        key = self._resolve("subscriptionKey", df, i)
        if key:  # OpenAI-style auth in addition to the Azure header
            h["api-key"] = str(key)
        return h

    def _prepare_url(self, df: Table, i: int) -> str:
        base = self.get("url")
        if not base:
            raise ValueError(f"{type(self).__name__}: url not set (setUrl("
                             "'https://<resource>.openai.azure.com/'))")
        dep = self._resolve("deploymentName", df, i)
        if not dep:
            raise ValueError("deploymentName is not set")
        return (f"{base.rstrip('/')}/openai/deployments/{dep}/"
                f"{self._endpoint}?api-version={self.getApiVersion()}")

    def _common_body(self, df, i) -> Dict[str, Any]:
        body: Dict[str, Any] = {}
        for name, key in (("maxTokens", "max_tokens"),
                          ("temperature", "temperature"),
                          ("topP", "top_p"), ("stop", "stop"),
                          ("user", "user")):
            v = self._resolve(name, df, i)
            if v is not None:
                body[key] = v
        return body


class OpenAICompletion(_OpenAIBase):
    """Text completion (reference OpenAICompletion.scala)."""

    promptCol = Param("promptCol", "column of prompts", str, "prompt")
    batchPromptCol = Param("batchPromptCol", "column of prompt lists", str)

    _endpoint = "completions"

    def _prepare_body(self, df, i):
        body = self._common_body(df, i)
        if self.isSet("batchPromptCol"):
            body["prompt"] = list(df[self.getBatchPromptCol()][i])
        else:
            body["prompt"] = str(df[self.getPromptCol()][i])
        return body

    def _parse_response(self, parsed, df, i):
        return parsed  # full choices payload (text at choices[*].text)


class OpenAIChatCompletion(_OpenAIBase):
    """Chat completion (reference OpenAIChatCompletion.scala);
    ``messagesCol`` holds a list of {role, content} dicts per row."""

    messagesCol = Param("messagesCol", "column of message lists", str,
                        "messages")

    _endpoint = "chat/completions"

    def _prepare_body(self, df, i):
        body = self._common_body(df, i)
        msgs = df[self.getMessagesCol()][i]
        body["messages"] = list(msgs)
        return body


class OpenAIEmbedding(_OpenAIBase):
    """Embeddings (reference OpenAIEmbedding.scala); output column holds the
    embedding vector as a numpy array (device-ready)."""

    textCol = Param("textCol", "column of texts to embed", str, "text")

    _endpoint = "embeddings"

    def _prepare_body(self, df, i):
        return {"input": str(df[self.getTextCol()][i])}

    def _parse_response(self, parsed, df, i):
        try:
            return np.asarray(parsed["data"][0]["embedding"], dtype=np.float32)
        except (KeyError, IndexError, TypeError):
            return None


class OpenAIPrompt(_OpenAIBase):
    """Prompt templating over table columns (reference OpenAIPrompt.scala:22+):
    ``promptTemplate='classify: {text}'`` renders per row, runs completion (or
    chat), and post-processes the answer (csv/json/regex)."""

    promptTemplate = Param("promptTemplate", "template with {column} "
                           "placeholders", str)
    postProcessing = Param("postProcessing", "one of '', 'csv', 'json', "
                           "'regex'", str, "")
    postProcessingOptions = Param("postProcessingOptions",
                                  "options (e.g. {'regex': ..., 'regexGroup': "
                                  "0})", is_complex=True)
    systemPrompt = Param("systemPrompt", "system message for chat models", str)
    useChat = Param("useChat", "use the chat endpoint", bool, True)

    @property
    def _endpoint(self):  # type: ignore[override]
        return "chat/completions" if self.getUseChat() else "completions"

    def _render(self, df: Table, i: int) -> str:
        tpl = self.get("promptTemplate")
        if tpl is None:
            raise ValueError("OpenAIPrompt: promptTemplate is not set")

        def sub(m):
            col = m.group(1)
            return str(df[col][i])

        return re.sub(r"\{(\w+)\}", sub, tpl)

    def _prepare_body(self, df, i):
        body = self._common_body(df, i)
        prompt = self._render(df, i)
        if self.getUseChat():
            msgs: List[Dict[str, str]] = []
            sys = self.get("systemPrompt")
            if sys:
                msgs.append({"role": "system", "content": sys})
            msgs.append({"role": "user", "content": prompt})
            body["messages"] = msgs
        else:
            body["prompt"] = prompt
        return body

    def _parse_response(self, parsed, df, i):
        try:
            if self.getUseChat():
                text = parsed["choices"][0]["message"]["content"]
            else:
                text = parsed["choices"][0]["text"]
        except (KeyError, IndexError, TypeError):
            return None
        mode = self.getPostProcessing()
        opts = self.get("postProcessingOptions") or {}
        if mode == "csv":
            return [s.strip() for s in text.split(opts.get("delimiter", ","))]
        if mode == "json":
            try:
                return _json.loads(text)
            except Exception:
                return None
        if mode == "regex":
            m = re.search(opts.get("regex", "(.*)"), text)
            return m.group(int(opts.get("regexGroup", 0))) if m else None
        return text.strip()
