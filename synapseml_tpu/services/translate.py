"""Azure Translator transformers.

Reference: cognitive/.../services/translate/ (~885 LoC: Translate,
Transliterate, Detect, BreakSentence, DictionaryLookup). All POST arrays of
``{Text: ...}`` to api.cognitive.microsofttranslator.com endpoints.
"""

from __future__ import annotations


from ..core.params import Param
from .base import CognitiveServiceBase

_BASE = "https://api.cognitive.microsofttranslator.com"


class _TranslatorBase(CognitiveServiceBase):
    textCol = Param("textCol", "column of input texts", str, "text")
    apiVersion = Param("apiVersion", "API version", str, "3.0")
    subscriptionRegion = Param("subscriptionRegion", "resource region", str)
    _path = "translate"

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        if not self.isSet("url"):
            self.set("url", _BASE)

    def _query(self, df, i) -> str:
        return f"?api-version={self.getApiVersion()}"

    def _prepare_url(self, df, i):
        return f"{self.get('url').rstrip('/')}/{self._path}{self._query(df, i)}"

    def _prepare_headers(self, df, i):
        h = super()._prepare_headers(df, i)
        region = self._resolve("subscriptionRegion", df, i)
        if region:
            h["Ocp-Apim-Subscription-Region"] = str(region)
        return h

    def _prepare_body(self, df, i):
        text = df[self.getTextCol()][i]
        if text is None:
            return None
        texts = text if isinstance(text, (list, tuple)) else [text]
        return [{"Text": str(t)} for t in texts]


class Translate(_TranslatorBase):
    toLanguage = Param("toLanguage", "target language(s)", is_complex=True)
    fromLanguage = Param("fromLanguage", "source language", str)
    _path = "translate"

    def _query(self, df, i):
        to = self._resolve("toLanguage", df, i)
        if to is None:
            raise ValueError("Translate: toLanguage is not set")
        to_list = to if isinstance(to, (list, tuple)) else [to]
        q = f"?api-version={self.getApiVersion()}"
        for t in to_list:
            q += f"&to={t}"
        frm = self._resolve("fromLanguage", df, i)
        if frm:
            q += f"&from={frm}"
        return q


class Detect(_TranslatorBase):
    _path = "detect"


class BreakSentence(_TranslatorBase):
    _path = "breaksentence"


class Transliterate(_TranslatorBase):
    language = Param("language", "source language", str)
    fromScript = Param("fromScript", "source script", str)
    toScript = Param("toScript", "target script", str)
    _path = "transliterate"

    def _query(self, df, i):
        vals = {n: self._resolve(n, df, i)
                for n in ("language", "fromScript", "toScript")}
        missing = [n for n, v in vals.items() if v is None]
        if missing:
            raise ValueError(f"Transliterate: {', '.join(missing)} not set")
        return (f"?api-version={self.getApiVersion()}"
                f"&language={vals['language']}"
                f"&fromScript={vals['fromScript']}"
                f"&toScript={vals['toScript']}")


class DictionaryLookup(_TranslatorBase):
    fromLanguage = Param("fromLanguage", "source language", str)
    toLanguage = Param("toLanguage", "target language", is_complex=True)
    _path = "dictionary/lookup"

    def _query(self, df, i):
        frm = self._resolve("fromLanguage", df, i)
        to = self._resolve("toLanguage", df, i)
        if frm is None or to is None:
            raise ValueError(
                "DictionaryLookup: fromLanguage and toLanguage must be set")
        return f"?api-version={self.getApiVersion()}&from={frm}&to={to}"


class DictionaryExamples(_TranslatorBase):
    """Dictionary usage examples (reference translate/Translator.scala
    DictionaryExamples): POST [{Text, Translation}] pairs."""

    fromLanguage = Param("fromLanguage", "source language", str, "en")
    toLanguage = Param("toLanguage", "target language", str)
    translationCol = Param("translationCol", "column of normalized "
                           "translations (paired with textCol)", str)
    _path = "dictionary/examples"

    def _query(self, df, i):
        to = self._resolve("toLanguage", df, i)
        if to is None:
            raise ValueError("DictionaryExamples: toLanguage is not set")
        return (f"?api-version={self.getApiVersion()}"
                f"&from={self._resolve('fromLanguage', df, i, 'en')}&to={to}")

    def _prepare_body(self, df, i):
        text = df[self.getTextCol()][i]
        if text is None:
            return None
        trans = (df[self.get("translationCol")][i]
                 if self.isSet("translationCol") else text)
        texts = text if isinstance(text, (list, tuple)) else [text]
        transl = trans if isinstance(trans, (list, tuple)) else [trans]
        return [{"Text": str(t), "Translation": str(tr)}
                for t, tr in zip(texts, transl)]


class DocumentTranslator(CognitiveServiceBase):
    """Asynchronous blob-to-blob document translation (reference
    translate/DocumentTranslator.scala): POST /batches with
    source/target container urls; output = operation status url."""

    serviceName = Param("serviceName", "translator resource name", str)
    sourceUrl = Param("sourceUrl", "source container SAS url", str)
    targetUrl = Param("targetUrl", "target container SAS url", str)
    targetLanguage = Param("targetLanguage", "target language", str, "fr")
    filterPrefix = Param("filterPrefix", "blob name prefix filter", str)
    storageType = Param("storageType", "Folder|File", str, "Folder")

    def _prepare_url(self, df, i):
        if self.get("url"):
            return self.get("url")
        name = self.get("serviceName")
        if not name:
            raise ValueError("DocumentTranslator: set serviceName or url")
        return (f"https://{name}.cognitiveservices.azure.com/"
                "translator/text/batch/v1.0/batches")

    def _prepare_body(self, df, i):
        src = self._resolve("sourceUrl", df, i)
        tgt = self._resolve("targetUrl", df, i)
        if src is None or tgt is None:
            return None
        source = {"sourceUrl": str(src), "storageSource": "AzureBlob"}
        pre = self._resolve("filterPrefix", df, i)
        if pre:
            source["filter"] = {"prefix": str(pre)}
        return {"inputs": [{
            "source": source,
            "storageType": self._resolve("storageType", df, i, "Folder"),
            "targets": [{"targetUrl": str(tgt), "storageSource": "AzureBlob",
                         "language": self._resolve("targetLanguage", df, i,
                                                   "fr")}]}]}
