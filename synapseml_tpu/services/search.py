"""Azure AI Search writer + Bing search transformer.

Reference: cognitive/.../services/search/AzureSearch.scala (~754 LoC,
AzureSearchWriter indexes DataFrames in batches with mergeOrUpload actions)
and services/bing/BingImageSearch.scala.
"""

from __future__ import annotations

import json as _json
from typing import List, Optional

import numpy as np

from ..core.params import Param
from ..core.table import Table
from ..io.http import HTTPRequestData, send_with_retries
from .base import CognitiveServiceBase


class AzureSearchWriter:
    """Batch-index a Table into an Azure AI Search index
    (reference AzureSearchWriter.stream/write)."""

    def __init__(self, service_name: str, index_name: str, key: str,
                 action_col: str = "@search.action",
                 default_action: str = "mergeOrUpload",
                 batch_size: int = 100, api_version: str = "2023-11-01",
                 url: Optional[str] = None, retries: int = 3):
        self.url = (url or f"https://{service_name}.search.windows.net") \
            + f"/indexes/{index_name}/docs/index?api-version={api_version}"
        self.key = key
        self.action_col = action_col
        self.default_action = default_action
        self.batch_size = batch_size
        self.retries = retries

    def write(self, df: Table) -> int:
        rows = df.to_pandas().to_dict(orient="records")
        written = 0
        for start in range(0, len(rows), self.batch_size):
            chunk = rows[start:start + self.batch_size]
            for r in chunk:
                r.setdefault(self.action_col, self.default_action)
            req = HTTPRequestData.from_json_body(
                self.url, {"value": chunk}, {"api-key": self.key})
            resp = send_with_retries(req, retries=self.retries)
            if not 200 <= resp.status_code < 300:
                raise RuntimeError(f"index batch failed at {start}: "
                                   f"{resp.status_code} {resp.reason}")
            written += len(chunk)
        return written


class BingImageSearch(CognitiveServiceBase):
    """Image search (reference BingImageSearch.scala); emits the raw value
    list — ``downloadFromUrls`` is a helper on the result."""

    qCol = Param("qCol", "column of queries", str, "q")
    count = Param("count", "results per query", int, 10)
    offset = Param("offset", "result offset", int, 0)
    imageType = Param("imageType", "photo|clipart|...", str)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        if not self.isSet("url"):
            self.set("url",
                     "https://api.bing.microsoft.com/v7.0/images/search")

    def _prepare_method(self):
        return "GET"

    def _prepare_url(self, df, i):
        from urllib.parse import quote

        q = quote(str(df[self.getQCol()][i]))
        u = (f"{self.get('url')}?q={q}&count={self.getCount()}"
             f"&offset={self.getOffset()}")
        it = self.get("imageType")
        return u + (f"&imageType={it}" if it else "")

    def _prepare_body(self, df, i):
        return b""  # GET

    def _parse_response(self, parsed, df, i):
        try:
            return [v["contentUrl"] for v in parsed["value"]]
        except (KeyError, TypeError):
            return parsed

    @staticmethod
    def downloadFromUrls(urls: List[str], concurrency: int = 4,
                         timeout: float = 30.0) -> List[Optional[bytes]]:
        from concurrent.futures import ThreadPoolExecutor

        def get(u):
            r = send_with_retries(
                HTTPRequestData(url=u, method="GET", headers={}),
                timeout=timeout, retries=1)
            return r.entity if 200 <= r.status_code < 300 else None

        with ThreadPoolExecutor(max_workers=concurrency) as pool:
            return list(pool.map(get, urls))
