"""Azure AI Search writer + Bing search transformer.

Reference: cognitive/.../services/search/AzureSearch.scala (~754 LoC,
AzureSearchWriter indexes DataFrames in batches with mergeOrUpload actions)
and services/bing/BingImageSearch.scala.
"""

from __future__ import annotations

import json as _json
from typing import List, Optional

import numpy as np

from ..core.params import Param
from ..core.table import Table
from ..io.http import HTTPRequestData, send_with_retries
from .base import CognitiveServiceBase


class AzureSearchWriter:
    """Batch-index a Table into an Azure AI Search index
    (reference AzureSearchWriter.stream/write)."""

    def __init__(self, service_name: str, index_name: str, key: str,
                 action_col: str = "@search.action",
                 default_action: str = "mergeOrUpload",
                 batch_size: int = 100, api_version: str = "2023-11-01",
                 url: Optional[str] = None, retries: int = 3):
        self.url = (url or f"https://{service_name}.search.windows.net") \
            + f"/indexes/{index_name}/docs/index?api-version={api_version}"
        self.key = key
        self.action_col = action_col
        self.default_action = default_action
        self.batch_size = batch_size
        self.retries = retries

    def write(self, df: Table) -> int:
        rows = df.to_pandas().to_dict(orient="records")
        written = 0
        for start in range(0, len(rows), self.batch_size):
            chunk = rows[start:start + self.batch_size]
            for r in chunk:
                r.setdefault(self.action_col, self.default_action)
            req = HTTPRequestData.from_json_body(
                self.url, {"value": chunk}, {"api-key": self.key})
            resp = send_with_retries(req, retries=self.retries)
            if not 200 <= resp.status_code < 300:
                raise RuntimeError(f"index batch failed at {start}: "
                                   f"{resp.status_code} {resp.reason}")
            written += len(chunk)
        return written


class BingImageSearch(CognitiveServiceBase):
    """Image search (reference BingImageSearch.scala); emits the raw value
    list — ``downloadFromUrls`` is a helper on the result."""

    qCol = Param("qCol", "column of queries", str, "q")
    count = Param("count", "results per query", int, 10)
    offset = Param("offset", "result offset", int, 0)
    imageType = Param("imageType", "photo|clipart|...", str)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        if not self.isSet("url"):
            self.set("url",
                     "https://api.bing.microsoft.com/v7.0/images/search")

    def _prepare_method(self):
        return "GET"

    def _prepare_url(self, df, i):
        from urllib.parse import quote

        q = quote(str(df[self.getQCol()][i]))
        u = (f"{self.get('url')}?q={q}&count={self.getCount()}"
             f"&offset={self.getOffset()}")
        it = self.get("imageType")
        return u + (f"&imageType={it}" if it else "")

    def _prepare_body(self, df, i):
        return b""  # GET

    def _parse_response(self, parsed, df, i):
        try:
            return [v["contentUrl"] for v in parsed["value"]]
        except (KeyError, TypeError):
            return parsed

    @staticmethod
    def downloadFromUrls(urls: List[str], concurrency: int = 4,
                         timeout: float = 30.0) -> List[Optional[bytes]]:
        from concurrent.futures import ThreadPoolExecutor

        def get(u):
            r = send_with_retries(
                HTTPRequestData(url=u, method="GET", headers={}),
                timeout=timeout, retries=1)
            return r.entity if 200 <= r.status_code < 300 else None

        with ThreadPoolExecutor(max_workers=concurrency) as pool:
            return list(pool.map(get, urls))


class AddDocuments(CognitiveServiceBase):
    """Push rows into an Azure Search index (reference search/AzureSearch.scala
    AddDocuments transformer — POST indexes/{index}/docs/index with a batch of
    @search.action documents). The standalone writer counterpart is
    AzureSearchWriter above."""

    serviceName = Param("serviceName", "search service name", str)
    indexName = Param("indexName", "target index", str)
    actionCol = Param("actionCol", "per-row @search.action column", str,
                      "@search.action")
    batchSize = Param("batchSize", "rows per indexing batch", int, 100)
    apiVersion = Param("apiVersion", "API version", str, "2023-11-01")

    def _prepare_url(self, df, i):
        if self.get("url"):
            return self.get("url")
        return (f"https://{self.get('serviceName')}.search.windows.net/"
                f"indexes/{self.get('indexName')}/docs/index"
                f"?api-version={self.getApiVersion()}")

    def _prepare_headers(self, df, i):
        h = super()._prepare_headers(df, i)
        key = self._resolve("subscriptionKey", df, i)
        if key:
            h["api-key"] = str(key)
        return h

    def _doc(self, df, i):
        action_col = self.get("actionCol")
        skip = {self.get("outputCol"), self.get("errorCol"), action_col}
        doc = {c: _to_plain(df[c][i]) for c in df.columns if c not in skip}
        doc["@search.action"] = (df[action_col][i]
                                 if action_col in df.columns else "upload")
        return doc

    def _prepare_body(self, df, i):
        # batching handled in _transform; single-row fallback
        return {"value": [self._doc(df, i)]}

    def _transform(self, df):
        import json as _json

        import numpy as np

        from ..io.http import HTTPRequestData

        n = df.num_rows
        bs = max(1, self.getBatchSize())
        out = np.empty(n, dtype=object)
        err = np.empty(n, dtype=object)
        for s in range(0, n, bs):
            rows = range(s, min(s + bs, n))
            body = {"value": [self._doc(df, i) for i in rows]}
            req = HTTPRequestData(
                url=self._prepare_url(df, s), method="POST",
                headers=self._prepare_headers(df, s),
                entity=_json.dumps(body).encode())
            r = self._send_one(req)
            if r is not None and 200 <= r.status_code < 300:
                try:
                    results = r.json().get("value", [])
                except Exception:
                    results = []
                for j, i in enumerate(rows):
                    out[i] = results[j] if j < len(results) else None
                    err[i] = None
            else:
                for i in rows:
                    out[i] = None
                    err[i] = {"statusCode": getattr(r, "status_code", None),
                              "reason": getattr(r, "reason", "send failed")}
        res = df.with_column(self.get("outputCol"), out)
        return res.with_column(self.get("errorCol"), err)


def _to_plain(v):
    import base64

    import numpy as np

    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (bytes, bytearray)):
        # Azure Search binary fields are base64 (Edm.Binary)
        return base64.b64encode(bytes(v)).decode()
    return v
