"""Document Intelligence (Form Recognizer) prebuilt-model transformers.

Reference: cognitive/.../services/form/FormRecognizer.scala (~849 LoC:
AnalyzeLayout, AnalyzeReceipts, AnalyzeBusinessCards, AnalyzeInvoices,
AnalyzeIDDocuments, AnalyzeCustomModel, plus management ops). All share the
submit+poll LRO flow implemented in speech.AnalyzeDocument; these subclasses
pin the prebuilt model ids.
"""

from __future__ import annotations

from ..core.params import Param
from ..core.pipeline import Estimator, Transformer
from .speech import AnalyzeDocument


class AnalyzeLayout(AnalyzeDocument):
    def __init__(self, **kwargs):
        kwargs.setdefault("modelId", "prebuilt-layout")
        super().__init__(**kwargs)


class AnalyzeReceipts(AnalyzeDocument):
    def __init__(self, **kwargs):
        kwargs.setdefault("modelId", "prebuilt-receipt")
        super().__init__(**kwargs)


class AnalyzeBusinessCards(AnalyzeDocument):
    def __init__(self, **kwargs):
        kwargs.setdefault("modelId", "prebuilt-businessCard")
        super().__init__(**kwargs)


class AnalyzeInvoices(AnalyzeDocument):
    def __init__(self, **kwargs):
        kwargs.setdefault("modelId", "prebuilt-invoice")
        super().__init__(**kwargs)


class AnalyzeIDDocuments(AnalyzeDocument):
    def __init__(self, **kwargs):
        kwargs.setdefault("modelId", "prebuilt-idDocument")
        super().__init__(**kwargs)


class AnalyzeDocumentRead(AnalyzeDocument):
    def __init__(self, **kwargs):
        kwargs.setdefault("modelId", "prebuilt-read")
        super().__init__(**kwargs)


class AnalyzeCustomModel(AnalyzeDocument):
    """Custom-trained model: set ``modelId`` to the trained model's id
    (reference AnalyzeCustomModel)."""


class GetCustomModel(AnalyzeDocument):
    """Fetch a custom model's metadata (reference form/FormRecognizer.scala
    GetCustomModel — GET documentModels/{modelId})."""

    includeKeys = Param("includeKeys", "include learned keys", bool, False)

    def _prepare_method(self):
        return "GET"

    def _prepare_body(self, df, i):
        return b""  # GET: non-None sentinel so the row is dispatched

    def _prepare_url(self, df, i):
        base = self.get("url")
        if not base:
            raise ValueError("set url/location first")
        root = base.split("/formrecognizer")[0].rstrip("/")
        mid = self._resolve("modelId", df, i)
        return (f"{root}/formrecognizer/documentModels/{mid}"
                f"?api-version={self.getApiVersion()}")


class ListCustomModels(GetCustomModel):
    """List custom models (reference ListCustomModels — GET documentModels)."""

    def _prepare_url(self, df, i):
        base = self.get("url")
        if not base:
            raise ValueError("set url/location first")
        root = base.split("/formrecognizer")[0].rstrip("/")
        return (f"{root}/formrecognizer/documentModels"
                f"?api-version={self.getApiVersion()}")


class FormOntologyLearner(Estimator):
    """Estimator over AnalyzeDocument outputs: learns the union schema
    ("ontology") of extracted document fields, producing a
    FormOntologyTransformer that projects each document's fields onto the
    learned columns (reference form/FormOntologyLearner.scala)."""

    inputCol = Param("inputCol", "column of analyzeResult outputs", str)

    def _fit(self, df):
        from collections import OrderedDict

        col = self.get("inputCol")
        fields: "OrderedDict[str, str]" = OrderedDict()
        for v in df[col]:
            for doc in ((v or {}).get("analyzeResult", v or {}) or
                        {}).get("documents", []):
                for name, fld in (doc.get("fields") or {}).items():
                    fields.setdefault(name, (fld or {}).get("type", "string"))
        t = FormOntologyTransformer(ontology=dict(fields))
        t.set("inputCol", col)
        return t


class FormOntologyTransformer(Transformer):
    """Projects analyzeResult documents onto the learned ontology columns
    (reference FormOntologyTransformer)."""

    ontology = Param("ontology", "field name -> type", is_complex=True)
    inputCol = Param("inputCol", "column of analyzeResult outputs", str)

    def _transform(self, df):
        import numpy as np

        col = self.get("inputCol")
        onto = self.get("ontology") or {}
        out = df.copy()
        cols = {name: np.empty(df.num_rows, dtype=object) for name in onto}
        for i, v in enumerate(df[col]):
            docs = ((v or {}).get("analyzeResult", v or {}) or
                    {}).get("documents", [])
            flds = (docs[0].get("fields") or {}) if docs else {}
            for name in onto:
                fld = flds.get(name) or {}
                out_v = fld.get("valueString", fld.get("valueNumber",
                                fld.get("content")))
                cols[name][i] = out_v
        for name, arr in cols.items():
            out = out.with_column(name, arr)
        return out
