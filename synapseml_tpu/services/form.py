"""Document Intelligence (Form Recognizer) prebuilt-model transformers.

Reference: cognitive/.../services/form/FormRecognizer.scala (~849 LoC:
AnalyzeLayout, AnalyzeReceipts, AnalyzeBusinessCards, AnalyzeInvoices,
AnalyzeIDDocuments, AnalyzeCustomModel, plus management ops). All share the
submit+poll LRO flow implemented in speech.AnalyzeDocument; these subclasses
pin the prebuilt model ids.
"""

from __future__ import annotations

from ..core.params import Param
from .speech import AnalyzeDocument


class AnalyzeLayout(AnalyzeDocument):
    def __init__(self, **kwargs):
        kwargs.setdefault("modelId", "prebuilt-layout")
        super().__init__(**kwargs)


class AnalyzeReceipts(AnalyzeDocument):
    def __init__(self, **kwargs):
        kwargs.setdefault("modelId", "prebuilt-receipt")
        super().__init__(**kwargs)


class AnalyzeBusinessCards(AnalyzeDocument):
    def __init__(self, **kwargs):
        kwargs.setdefault("modelId", "prebuilt-businessCard")
        super().__init__(**kwargs)


class AnalyzeInvoices(AnalyzeDocument):
    def __init__(self, **kwargs):
        kwargs.setdefault("modelId", "prebuilt-invoice")
        super().__init__(**kwargs)


class AnalyzeIDDocuments(AnalyzeDocument):
    def __init__(self, **kwargs):
        kwargs.setdefault("modelId", "prebuilt-idDocument")
        super().__init__(**kwargs)


class AnalyzeDocumentRead(AnalyzeDocument):
    def __init__(self, **kwargs):
        kwargs.setdefault("modelId", "prebuilt-read")
        super().__init__(**kwargs)


class AnalyzeCustomModel(AnalyzeDocument):
    """Custom-trained model: set ``modelId`` to the trained model's id
    (reference AnalyzeCustomModel)."""
