"""AI-service transformers (host-side).

Reference: module ``cognitive`` (~10.1k LoC, ~65 transformers; SURVEY.md §2.8).
All build on the base machinery in base.py (ServiceParams, auth, retries,
concurrency, shared LRO polling) over the io/http layer — no device work.
Implemented families: OpenAI, language/text analytics, translate (incl.
document translation), vision + face ops, anomaly (incl. the multivariate
fit lifecycle), speech (REST + streaming websocket SDK), document
intelligence (incl. custom-model management and ontology learning), search,
Bing, geospatial.
"""

from .base import (CognitiveServiceBase, HasAsyncReply, HasServiceParams,
                   HasSetLocation)
from .openai import (OpenAIChatCompletion, OpenAICompletion, OpenAIEmbedding,
                     OpenAIPrompt)
from .language import (NER, PII, AnalyzeHealthText, AnalyzeText,
                       EntityDetector, EntityLinking, KeyPhraseExtractor,
                       LanguageDetector, TextAnalyze, TextSentiment)
from .translate import (BreakSentence, Detect, DictionaryExamples,
                        DictionaryLookup, DocumentTranslator, Translate,
                        Transliterate)
from .vision import (OCR, AnalyzeImage, DescribeImage, DetectFace,
                     FindSimilarFace, GenerateThumbnails, GroupFaces,
                     IdentifyFaces, ReadImage,
                     RecognizeDomainSpecificContent, RecognizeText, TagImage,
                     VerifyFaces)
from .anomaly import (DetectAnomalies, DetectLastAnomaly,
                      DetectLastMultivariateAnomaly, DetectMultivariateAnomaly,
                      SimpleDetectAnomalies, SimpleDetectMultivariateAnomaly,
                      SimpleFitMultivariateAnomaly)
from .speech import (AnalyzeDocument, ConversationTranscription,
                     SpeakerEmotionInference, SpeechToText, SpeechToTextSDK,
                     TextToSpeech)
from .search import AddDocuments, AzureSearchWriter, BingImageSearch
from .geospatial import (AddressGeocoder, CheckPointInPolygon,
                         ReverseAddressGeocoder)
from .form import (AnalyzeBusinessCards, AnalyzeCustomModel,
                   AnalyzeDocumentRead, AnalyzeIDDocuments, AnalyzeInvoices,
                   AnalyzeLayout, AnalyzeReceipts, FormOntologyLearner,
                   FormOntologyTransformer, GetCustomModel, ListCustomModels)

__all__ = [
    "CognitiveServiceBase", "HasAsyncReply", "HasServiceParams",
    "HasSetLocation",
    "OpenAICompletion", "OpenAIChatCompletion", "OpenAIEmbedding",
    "OpenAIPrompt",
    "TextSentiment", "KeyPhraseExtractor", "NER", "PII", "EntityLinking",
    "EntityDetector", "LanguageDetector", "AnalyzeHealthText", "AnalyzeText",
    "TextAnalyze",
    "Translate", "Transliterate", "Detect", "BreakSentence",
    "DictionaryLookup", "DictionaryExamples", "DocumentTranslator",
    "AnalyzeImage", "DescribeImage", "TagImage", "OCR", "GenerateThumbnails",
    "ReadImage", "RecognizeText", "RecognizeDomainSpecificContent",
    "DetectFace", "FindSimilarFace", "GroupFaces", "IdentifyFaces",
    "VerifyFaces",
    "DetectLastAnomaly", "DetectAnomalies", "SimpleDetectAnomalies",
    "DetectMultivariateAnomaly", "DetectLastMultivariateAnomaly",
    "SimpleFitMultivariateAnomaly", "SimpleDetectMultivariateAnomaly",
    "SpeechToText", "SpeechToTextSDK", "ConversationTranscription",
    "SpeakerEmotionInference", "TextToSpeech", "AnalyzeDocument",
    "AzureSearchWriter", "AddDocuments", "BingImageSearch",
    "AddressGeocoder", "ReverseAddressGeocoder", "CheckPointInPolygon",
    "AnalyzeLayout", "AnalyzeReceipts", "AnalyzeBusinessCards",
    "AnalyzeInvoices", "AnalyzeIDDocuments", "AnalyzeDocumentRead",
    "AnalyzeCustomModel", "GetCustomModel", "ListCustomModels",
    "FormOntologyLearner", "FormOntologyTransformer",
]
