"""AI-service REST transformers (host-side).

Reference: module ``cognitive`` (~10.1k LoC, ~65 transformers; SURVEY.md §2.8).
All build on the base machinery in base.py (ServiceParams, auth, retries,
concurrency) over the io/http layer — no device work. Implemented families:
OpenAI, language/text analytics, translate, vision, face, anomaly, speech,
document intelligence, search, Bing.
"""

from .base import CognitiveServiceBase, HasServiceParams, HasSetLocation
from .openai import (OpenAIChatCompletion, OpenAICompletion, OpenAIEmbedding,
                     OpenAIPrompt)
from .language import (NER, PII, AnalyzeHealthText, EntityLinking,
                       KeyPhraseExtractor, LanguageDetector, TextSentiment)
from .translate import (BreakSentence, Detect, DictionaryLookup, Translate,
                        Transliterate)
from .vision import (OCR, AnalyzeImage, DescribeImage, DetectFace,
                     GenerateThumbnails, TagImage)
from .anomaly import (DetectAnomalies, DetectLastAnomaly,
                      DetectMultivariateAnomaly, SimpleDetectAnomalies)
from .speech import AnalyzeDocument, SpeechToText, SpeechToTextSDK, TextToSpeech
from .search import AzureSearchWriter, BingImageSearch
from .geospatial import (AddressGeocoder, CheckPointInPolygon,
                         ReverseAddressGeocoder)
from .form import (AnalyzeBusinessCards, AnalyzeCustomModel,
                   AnalyzeDocumentRead, AnalyzeIDDocuments, AnalyzeInvoices,
                   AnalyzeLayout, AnalyzeReceipts)

__all__ = [
    "CognitiveServiceBase", "HasServiceParams", "HasSetLocation",
    "OpenAICompletion", "OpenAIChatCompletion", "OpenAIEmbedding",
    "OpenAIPrompt",
    "TextSentiment", "KeyPhraseExtractor", "NER", "PII", "EntityLinking",
    "LanguageDetector", "AnalyzeHealthText",
    "Translate", "Transliterate", "Detect", "BreakSentence",
    "DictionaryLookup",
    "AnalyzeImage", "DescribeImage", "TagImage", "OCR", "GenerateThumbnails",
    "DetectFace",
    "DetectLastAnomaly", "DetectAnomalies", "SimpleDetectAnomalies",
    "DetectMultivariateAnomaly",
    "SpeechToText", "SpeechToTextSDK", "TextToSpeech", "AnalyzeDocument",
    "AzureSearchWriter", "BingImageSearch",
    "AddressGeocoder", "ReverseAddressGeocoder", "CheckPointInPolygon",
    "AnalyzeLayout", "AnalyzeReceipts", "AnalyzeBusinessCards",
    "AnalyzeInvoices", "AnalyzeIDDocuments", "AnalyzeDocumentRead",
    "AnalyzeCustomModel",
]
