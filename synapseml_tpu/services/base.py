"""AI-service transformer base machinery.

Reference: cognitive/.../services/CognitiveServiceBase.scala:32-518 —
``ServiceParam``s settable as a scalar or a per-row column
(setX / setXCol), ``HasCognitiveServiceInput`` (row → HTTP request with
subscription-key / AAD auth headers), ``HasInternalJsonOutputParser``
(response → typed output column), async pooled execution with retries. These
are host-side transformers (SURVEY.md §2.8): no device work, so the machinery
reuses the io/http layer; the value here is API-surface parity.
"""

from __future__ import annotations

import json as _json
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

import numpy as np

from ..core.params import Param
from ..core.pipeline import Transformer
from ..core.table import Table
from ..io.http import HTTPRequestData, HTTPResponseData


class HasServiceParams(Transformer):
    """Scalar-or-column params (reference HasServiceParams:32-129).

    Subclasses declare service params via ``_service_params`` (name -> doc);
    the metaclass-free approach: ``setX(value)`` sets the scalar,
    ``setXCol(colname)`` binds the value to a column, ``_resolve(name, df, i)``
    reads whichever is set.
    """

    serviceParamCols = Param("serviceParamCols", "map: service param -> "
                             "bound column name", is_complex=True)

    def set_scalar(self, name: str, value: Any):
        return self.set(name, value)

    def set_vector(self, name: str, col: str):
        cols = dict(self.get("serviceParamCols") or {})
        cols[name] = col
        return self.set("serviceParamCols", cols)

    def _resolve(self, name: str, df: Optional[Table] = None,
                 i: Optional[int] = None, default: Any = None) -> Any:
        cols = self.get("serviceParamCols") or {}
        if name in cols:
            if df is None or i is None:
                return default
            v = df[cols[name]][i]
            return v.item() if isinstance(v, np.generic) else v
        v = self.get(name) if self.hasParam(name) else None
        return default if v is None else v

    def __getattr__(self, item):
        # setXCol sugar for every declared param (reference setVectorParam)
        if item.startswith("set") and item.endswith("Col") and len(item) > 6:
            stem = item[3:-3]
            # try lowered-first-letter ("maxTokens") then verbatim ("AADToken")
            for pname in (stem[0].lower() + stem[1:], stem):
                if pname in type(self)._params:
                    def _set(col: str, _p=pname):
                        self.set_vector(_p, col)
                        return self

                    return _set
        raise AttributeError(f"{type(self).__name__} has no attribute {item!r}")


class CognitiveServiceBase(HasServiceParams):
    """Row → HTTP request → JSON → output column
    (reference CognitiveServicesBase:447-518 + HasCognitiveServiceInput:258-359).
    Subclasses override ``_prepare_url``/``_prepare_body``/``_parse_response``.
    """

    subscriptionKey = Param("subscriptionKey", "service subscription key", str)
    aadToken = Param("AADToken", "AAD auth token", str)
    url = Param("url", "service base url", str)
    outputCol = Param("outputCol", "output column", str)
    errorCol = Param("errorCol", "per-row error column", str)
    concurrency = Param("concurrency", "max concurrent requests", int, 1)
    timeout = Param("timeout", "per-request timeout seconds", float, 60.0)
    maxRetries = Param("maxRetries", "retries on 429/5xx", int, 3)
    backoff = Param("backoff", "initial backoff seconds", float, 0.5)
    handler = Param("handler", "(HTTPRequestData, send) -> HTTPResponseData",
                    is_complex=True)
    opener = Param("opener", "transport override with .open(request, "
                   "timeout=) — e.g. a chaos injector", is_complex=True)
    retryBudget = Param("retryBudget", "shared RetryBudget token bucket "
                        "capping aggregate retry volume", is_complex=True)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        if not self.isSet("outputCol"):
            self.set("outputCol", self.uid + "_output")
        if not self.isSet("errorCol"):
            self.set("errorCol", self.uid + "_error")

    # --- overridables ---------------------------------------------------
    def _prepare_url(self, df: Table, i: int) -> str:
        u = self.get("url")
        if not u:
            raise ValueError(f"{type(self).__name__}: url is not set "
                             "(setUrl / setLocation)")
        return u

    def _prepare_body(self, df: Table, i: int) -> Optional[Any]:
        raise NotImplementedError

    def _prepare_method(self) -> str:
        return "POST"

    def _prepare_headers(self, df: Table, i: int) -> Dict[str, str]:
        h = {"Content-Type": "application/json"}
        key = self._resolve("subscriptionKey", df, i)
        if key:
            h["Ocp-Apim-Subscription-Key"] = str(key)
        tok = self._resolve("AADToken", df, i)
        if tok:
            h["Authorization"] = f"Bearer {tok}"
        return h

    def _parse_response(self, parsed: Any, df: Table, i: int) -> Any:
        return parsed

    # --- execution ------------------------------------------------------
    def _send_one(self, req: Optional[HTTPRequestData]) -> Optional[HTTPResponseData]:
        if req is None:
            return None
        from ..io.http import dispatch_with_handler

        return dispatch_with_handler(req, self.getTimeout(),
                                     self.getMaxRetries(), self.getBackoff(),
                                     self.get("handler"),
                                     opener=self.get("opener"),
                                     retry_budget=self.get("retryBudget"))

    def _transform(self, df: Table) -> Table:
        n = df.num_rows
        reqs = []
        for i in range(n):
            body = self._prepare_body(df, i)
            if body is None:
                reqs.append(None)
                continue
            entity = (body if isinstance(body, bytes)
                      else _json.dumps(body).encode())
            reqs.append(HTTPRequestData(
                url=self._prepare_url(df, i), method=self._prepare_method(),
                headers=self._prepare_headers(df, i), entity=entity))

        workers = max(1, self.getConcurrency())
        if workers == 1:
            resps = [self._send_one(r) for r in reqs]
        else:
            with ThreadPoolExecutor(max_workers=workers) as pool:
                resps = list(pool.map(self._send_one, reqs))

        out = np.empty(n, dtype=object)
        err = np.empty(n, dtype=object)
        for i, r in enumerate(resps):
            if r is None:
                out[i] = None
                err[i] = None
            elif 200 <= r.status_code < 300:
                try:
                    parsed = r.json()
                except Exception:
                    parsed = r.text
                out[i] = self._parse_response(parsed, df, i)
                err[i] = None
            else:
                out[i] = None
                err[i] = {"statusCode": r.status_code, "reason": r.reason,
                          "body": r.text[:2000]}
        res = df.with_column(self.get("outputCol"), out)
        return res.with_column(self.get("errorCol"), err)


class HasAsyncReply(CognitiveServiceBase):
    """Shared long-running-operation flow (reference HasAsyncReply:360-416):
    submit → Location/Operation-Location → poll until a terminal status →
    synthetic 504 when polls are exhausted. Subclasses set ``_status_of`` if
    the terminal status lives somewhere other than top-level "status"."""

    pollInterval = Param("pollInterval", "seconds between polls", float, 1.0)
    maxPollRetries = Param("maxPollRetries", "max polls", int, 60)

    _done_states = ("succeeded", "failed", "READY", "FAILED")

    @staticmethod
    def _status_of(info: dict) -> str:
        return str(info.get("status", ""))

    def _send_one(self, req):
        import time as _t

        first = super()._send_one(req)
        if first is None or first.status_code not in (200, 201, 202):
            return first
        # Operation-Location always marks an LRO; a plain Location only does
        # on 201/202 (a 200 with Location is a complete response — return it)
        loc = None
        for k, v in (first.headers or {}).items():
            if k.lower() == "operation-location":
                loc = v
                break
            if k.lower() == "location" and first.status_code in (201, 202):
                loc = v
        if not loc:
            return first
        headers = {k: v for k, v in req.headers.items()
                   if k.lower() != "content-type"}
        poll_req = HTTPRequestData(url=loc, method="GET", headers=headers)
        poll = None
        for _ in range(self.getMaxPollRetries()):
            poll = super()._send_one(poll_req)
            if poll is None:
                break
            try:
                info = poll.json() if poll.entity else {}
            except Exception:
                info = {}
            if self._status_of(info or {}) in self._done_states:
                return poll
            _t.sleep(self.getPollInterval())
        # poll exhausted/errored: report a timeout, NOT the 202 submit ack
        return HTTPResponseData(
            status_code=504,
            reason=f"operation at {loc} did not complete within "
                   f"{self.getMaxPollRetries()} polls",
            entity=(poll.entity if poll is not None else None))


class HasSetLocation(CognitiveServiceBase):
    """setLocation builds the azure domain url (reference HasSetLocation:418-432)."""

    urlPath: str = ""  # subclass constant

    def setLocation(self, location: str):
        # US-gov regions live under .us (reference DomainHelper:433-445)
        tld = "us" if "usgov" in location or "ussec" in location else "com"
        return self.set(
            "url", f"https://{location}.api.cognitive.microsoft.{tld}/"
            + self.urlPath.lstrip("/"))

    def setCustomServiceName(self, name: str):
        return self.set("url", f"https://{name}.cognitiveservices.azure.com/"
                        + self.urlPath.lstrip("/"))
