"""Azure AI Language transformers (sentiment, key phrases, entities, PII,
language detection).

Reference: cognitive/.../services/text/TextAnalytics.scala family (~989 LoC) —
all POST to the analyze-text endpoint with ``{kind, analysisInput{documents}}``
bodies and unwrap ``results.documents``.
"""

from __future__ import annotations


from ..core.params import Param
from .base import HasAsyncReply, HasSetLocation


class _TextAnalyticsBase(HasSetLocation):
    textCol = Param("textCol", "column of input texts", str, "text")
    language = Param("language", "language hint", str, "en")
    apiVersion = Param("apiVersion", "API version", str, "2023-04-01")
    kind = "SentimentAnalysis"  # subclass constant
    urlPath = "language/:analyze-text"

    def _prepare_url(self, df, i):
        return (super()._prepare_url(df, i)
                + f"?api-version={self.getApiVersion()}")

    def _prepare_body(self, df, i):
        text = df[self.getTextCol()][i]
        if text is None:
            return None
        lang = self._resolve("language", df, i, "en")
        return {"kind": self.kind,
                "analysisInput": {"documents": [
                    {"id": "0", "text": str(text), "language": lang}]},
                "parameters": self._parameters()}

    def _parameters(self) -> dict:
        return {}

    def _parse_response(self, parsed, df, i):
        try:
            return parsed["results"]["documents"][0]
        except (KeyError, IndexError, TypeError):
            return parsed


class TextSentiment(_TextAnalyticsBase):
    kind = "SentimentAnalysis"


class KeyPhraseExtractor(_TextAnalyticsBase):
    kind = "KeyPhraseExtraction"


class NER(_TextAnalyticsBase):
    kind = "EntityRecognition"


class PII(_TextAnalyticsBase):
    kind = "PiiEntityRecognition"
    domain = Param("domain", "PII domain filter", str)

    def _parameters(self):
        d = self.get("domain")
        return {"domain": d} if d else {}


class EntityLinking(_TextAnalyticsBase):
    kind = "EntityLinking"


class LanguageDetector(_TextAnalyticsBase):
    kind = "LanguageDetection"

    def _prepare_body(self, df, i):
        text = df[self.getTextCol()][i]
        if text is None:
            return None
        return {"kind": self.kind,
                "analysisInput": {"documents": [{"id": "0", "text": str(text)}]},
                "parameters": {}}


class AnalyzeHealthText(_TextAnalyticsBase):
    kind = "Healthcare"


class EntityDetector(_TextAnalyticsBase):
    """Linked-entity detection (reference text/TextAnalytics.scala
    EntityDetector — the v3 'entities/linking' task)."""

    kind = "EntityLinking"


class AnalyzeText(_TextAnalyticsBase):
    """Unified analyze-text transformer: the task kind is a parameter instead
    of a subclass (reference language/AnalyzeText.scala)."""

    kind = "SentimentAnalysis"
    kindParam = Param("kind", "EntityLinking|EntityRecognition|KeyPhrase"
                      "Extraction|LanguageDetection|PiiEntityRecognition|"
                      "SentimentAnalysis", str, "SentimentAnalysis")

    def _prepare_body(self, df, i):
        self.kind = self._resolve("kind", df, i, "SentimentAnalysis")
        if self.kind == "LanguageDetection":
            text = df[self.getTextCol()][i]
            if text is None:
                return None
            return {"kind": self.kind,
                    "analysisInput": {"documents": [{"id": "0",
                                                     "text": str(text)}]},
                    "parameters": {}}
        return super()._prepare_body(df, i)


class TextAnalyze(HasAsyncReply, _TextAnalyticsBase):
    """Multi-task batch analysis (reference text/TextAnalyze.scala — the
    /analyze-text/jobs endpoint running several task kinds over one batch;
    the 202 + operation-location reply is polled via HasAsyncReply)."""

    tasks = Param("tasks", "map task kind -> parameters", is_complex=True)
    urlPath = "language/analyze-text/jobs"

    def _prepare_url(self, df, i):
        return (HasSetLocation._prepare_url(self, df, i)
                + f"?api-version={self.getApiVersion()}")

    def _prepare_body(self, df, i):
        text = df[self.getTextCol()][i]
        if text is None:
            return None
        lang = self._resolve("language", df, i, "en")
        tasks = self.get("tasks") or {"SentimentAnalysis": {}}
        return {"analysisInput": {"documents": [
                    {"id": "0", "text": str(text), "language": lang}]},
                "tasks": [{"kind": k, "parameters": v or {}}
                          for k, v in tasks.items()]}

    def _parse_response(self, parsed, df, i):
        return parsed
