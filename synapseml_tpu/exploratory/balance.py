"""Balance measures over sensitive features.

Reference formulas (FeatureBalanceMeasure.scala:228-266,
DistributionBalanceMeasure.scala:227-260, AggregateBalanceMeasure.scala:125-160):

* **FeatureBalanceMeasure** — for each sensitive column and each pair of its
  values (A, B), the gap ``M(A) − M(B)`` for association measures M computed
  from p(x)=P(feature=x), p(y)=P(label positive), p(x,y):
  dp = p(x,y)/p(x); sdc = p(x,y)/(p(x)+p(y)); ji = p(x,y)/(p(x)+p(y)−p(x,y));
  llr = ln(p(x,y)/p(y)); pmi = ln(dp); n_pmi_y = pmi/ln p(y);
  n_pmi_xy = pmi/ln p(x,y); s_pmi = ln(p(x,y)²/(p(x)p(y)));
  krc (Kendall rank proxy) and t_test = (p(x,y)−p(x)p(y))/√(p(x)p(y)).
* **DistributionBalanceMeasure** — per sensitive column, observed value
  distribution vs a reference (uniform by default): KL divergence, JS
  distance, inf-norm, total variation, Wasserstein-1, χ² statistic + p-value.
* **AggregateBalanceMeasure** — inequality indices over all value
  probabilities: Atkinson index (ε), Theil L, Theil T.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core.params import Param, HasLabelCol
from ..core.pipeline import Transformer
from ..core.table import Table

_EPS = 1e-12


class _BalanceBase(Transformer):
    sensitiveCols = Param("sensitiveCols", "sensitive feature columns", list)
    verbose = Param("verbose", "include all intermediate measures", bool, False)

    def _probs(self, df: Table, col: str):
        vals, counts = np.unique(df[col], return_counts=True)
        return vals, counts / df.num_rows


class FeatureBalanceMeasure(_BalanceBase, HasLabelCol):
    """Pairwise association gaps between sensitive-feature values
    (reference FeatureBalanceMeasure.scala:38-200)."""

    outputCol = Param("outputCol", "output measures column", str,
                      "FeatureBalanceMeasure")

    def _measures(self, p_x: float, p_y: float, p_xy: float) -> Dict[str, float]:
        dp = p_xy / max(p_x, _EPS)
        pmi = np.log(dp) if dp > 0 else -np.inf
        return {
            "dp": dp,
            "sdc": p_xy / max(p_x + p_y, _EPS),
            "ji": p_xy / max(p_x + p_y - p_xy, _EPS),
            "llr": np.log(max(p_xy, _EPS) / max(p_y, _EPS)),
            "pmi": pmi,
            "n_pmi_y": 0.0 if p_y <= 0 else pmi / np.log(max(p_y, _EPS)),
            "n_pmi_xy": 0.0 if p_xy <= 0 else pmi / np.log(max(p_xy, _EPS)),
            "s_pmi": 0.0 if p_x * p_y <= 0 else np.log(
                max(p_xy, _EPS) ** 2 / (p_x * p_y)),
            "krc": _krc(p_x, p_y, p_xy),
            "t_test": (p_xy - p_x * p_y) / np.sqrt(max(p_x * p_y, _EPS)),
        }

    def _transform(self, df: Table) -> Table:
        label = np.asarray(df[self.getLabelCol()], np.float64) > 0
        p_y = float(label.mean())
        rows = []
        for col in (self.get("sensitiveCols") or []):
            vals, probs = self._probs(df, col)
            per_val = {}
            for v, p_x in zip(vals, probs):
                sel = df[col] == v
                p_xy = float((sel & label).mean())
                per_val[v] = self._measures(float(p_x), p_y, p_xy)
            for i in range(len(vals)):
                for j in range(i + 1, len(vals)):
                    a, b = vals[i], vals[j]
                    gaps = {k: per_val[a][k] - per_val[b][k]
                            for k in per_val[a]}
                    rows.append({"FeatureName": col, "ClassA": a, "ClassB": b,
                                 **gaps})
        return Table.from_rows(rows) if rows else Table(
            {"FeatureName": np.array([], object)})


def _krc(p_x: float, p_y: float, p_xy: float) -> float:
    """Kendall rank correlation proxy (reference FeatureBalanceMeasure:255-263)."""
    a = p_xy - p_x * p_y
    denom = np.sqrt(max(p_x * (1 - p_x) * p_y * (1 - p_y), _EPS))
    return a / denom


class DistributionBalanceMeasure(_BalanceBase):
    """Observed vs reference distribution per sensitive column
    (reference DistributionBalanceMeasure.scala:41-214)."""

    outputCol = Param("outputCol", "output measures column", str,
                      "DistributionBalanceMeasure")
    referenceDistribution = Param(
        "referenceDistribution",
        "list of {value: prob} dicts per sensitive col (default uniform)",
        is_complex=True)

    def _transform(self, df: Table) -> Table:
        refs: Optional[List[dict]] = self.get("referenceDistribution")
        rows = []
        for ci, col in enumerate(self.get("sensitiveCols") or []):
            vals, obs = self._probs(df, col)
            n = len(vals)
            if refs is not None and ci < len(refs) and refs[ci]:
                ref = np.asarray([refs[ci].get(
                    v.item() if isinstance(v, np.generic) else v, 0.0)
                    for v in vals])
            else:
                ref = np.full(n, 1.0 / n)
            kl = float(np.sum(obs * np.log(np.maximum(obs, _EPS)
                                           / np.maximum(ref, _EPS))))
            m = 0.5 * (obs + ref)
            js = float(np.sqrt(max(
                0.5 * np.sum(obs * np.log(np.maximum(obs, _EPS) / m))
                + 0.5 * np.sum(ref * np.log(np.maximum(ref, _EPS) / m)), 0.0)))
            inf_norm = float(np.max(np.abs(obs - ref)))
            tv = float(0.5 * np.sum(np.abs(obs - ref)))
            wasserstein = float(np.mean(np.abs(np.cumsum(obs) - np.cumsum(ref))))
            counts = obs * df.num_rows
            expected = ref * df.num_rows
            chi2 = float(np.sum((counts - expected) ** 2
                                / np.maximum(expected, _EPS)))
            p_value = float(_chi2_sf(chi2, max(n - 1, 1)))
            rows.append({"FeatureName": col, "kl_divergence": kl,
                         "js_dist": js, "inf_norm_dist": inf_norm,
                         "total_variation_dist": tv,
                         "wasserstein_dist": wasserstein,
                         "chi_sq_stat": chi2, "chi_sq_p_value": p_value})
        return Table.from_rows(rows) if rows else Table(
            {"FeatureName": np.array([], object)})


def _chi2_sf(x: float, k: int) -> float:
    """Chi-square survival function via the regularized upper incomplete gamma
    (series/continued-fraction, no scipy dependency)."""
    import math

    if x <= 0:
        return 1.0
    a, half_x = k / 2.0, x / 2.0
    # P(a, x) lower regularized via series; Q = 1 - P (swap for large x)
    if half_x < a + 1:
        term = 1.0 / a
        total = term
        n = 0
        while abs(term) > 1e-12 * abs(total) and n < 500:
            n += 1
            term *= half_x / (a + n)
            total += term
        p = total * math.exp(-half_x + a * math.log(half_x) - math.lgamma(a))
        return max(0.0, min(1.0, 1.0 - p))
    # continued fraction for Q(a, x)
    b = half_x + 1.0 - a
    c = 1e300
    d = 1.0 / b
    h = d
    for i in range(1, 500):
        an = -i * (i - a)
        b += 2.0
        d = an * d + b
        d = 1.0 / (d if abs(d) > 1e-300 else 1e-300)
        c = b + an / (c if abs(c) > 1e-300 else 1e-300)
        delta = d * c
        h *= delta
        if abs(delta - 1.0) < 1e-12:
            break
    q = h * math.exp(-half_x + a * math.log(half_x) - math.lgamma(a))
    return max(0.0, min(1.0, q))


class AggregateBalanceMeasure(_BalanceBase):
    """Inequality indices over the joint sensitive-value distribution
    (reference AggregateBalanceMeasure.scala:30-160)."""

    outputCol = Param("outputCol", "output measures column", str,
                      "AggregateBalanceMeasure")
    epsilon = Param("epsilon", "Atkinson inequality-aversion parameter",
                    float, 1.0)

    def _transform(self, df: Table) -> Table:
        cols = self.get("sensitiveCols") or []
        if not cols:
            return Table({"atkinson_index": np.array([])})
        # joint distribution over the cross product of sensitive values;
        # \x1f separator keeps distinct tuples from colliding after join
        keys = ["\x1f".join(str(df[c][i]) for c in cols)
                for i in range(df.num_rows)]
        _, counts = np.unique(np.asarray(keys), return_counts=True)
        p = counts / counts.sum()
        n = len(p)
        mu = p.mean()
        eps = self.getEpsilon()
        if abs(eps - 1.0) < 1e-12:
            atkinson = 1.0 - float(np.exp(np.mean(np.log(
                np.maximum(p, _EPS)))) / mu)
        else:
            atkinson = 1.0 - float(
                (np.mean(np.maximum(p, _EPS) ** (1 - eps)))
                ** (1.0 / (1 - eps)) / mu)
        theil_l = float(np.mean(np.log(np.maximum(mu / np.maximum(p, _EPS),
                                                  _EPS))))
        theil_t = float(np.mean(p / mu * np.log(np.maximum(p / mu, _EPS))))
        return Table({"atkinson_index": np.array([atkinson]),
                      "theil_l_index": np.array([theil_l]),
                      "theil_t_index": np.array([theil_t]),
                      "num_unique_values": np.array([n])})
