"""Data-balance analysis (Responsible AI exploratory measures).

Reference: core/src/main/scala/com/microsoft/azure/synapse/ml/exploratory/
(FeatureBalanceMeasure.scala, DistributionBalanceMeasure.scala,
AggregateBalanceMeasure.scala, ~770 LoC; SURVEY.md §2.7).
"""

from .balance import (AggregateBalanceMeasure, DistributionBalanceMeasure,
                      FeatureBalanceMeasure)

__all__ = ["FeatureBalanceMeasure", "DistributionBalanceMeasure",
           "AggregateBalanceMeasure"]
