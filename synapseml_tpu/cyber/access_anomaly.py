"""AccessAnomaly — collaborative-filtering anomaly detection for access logs.

Reference: cyber/anomaly/collaborative_filtering.py (AccessAnomaly:616-1078,
AccessAnomalyModel:192-537, ModelNormalizeTransformer:1080-1140) and
anomaly/complement_access.py. Semantics kept:

* likelihoods are scaled per tenant to [lowValue, highValue] (default [5, 10]);
* a user×resource matrix factorization is fit per tenant — implicit-feedback
  ALS (confidence ``1 + alpha·r``) by default, or explicit ALS with
  complement-set negatives (``negScore``, ``complementsetFactor``);
* the anomaly score of an observed (user, res) access is the *negative*
  predicted affinity, normalized per tenant to mean 0 / std 1 on the training
  accesses (higher ⇒ more anomalous); unseen users/resources score 0.

The reference runs Spark ALS jobs; here each tenant solve is a jitted
alternating ridge regression — batched [rank, rank] solves via ``vmap`` — and
scoring is one gather + dot per row.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from ..core.params import Param, Params
from ..core.pipeline import Estimator, Model, Transformer
from ..core.table import Table


class AccessAnomalyConfig:
    """Defaults (reference AccessAnomalyConfig:61-86)."""
    default_tenant_col = "tenant"
    default_user_col = "user"
    default_res_col = "res"
    default_likelihood_col = "likelihood"
    default_output_col = "anomaly_score"


class _AccessAnomalyParams(Params):
    tenantCol = Param("tenantCol", "tenant column partitioning independent "
                      "groups", str, AccessAnomalyConfig.default_tenant_col)
    userCol = Param("userCol", "user column", str,
                    AccessAnomalyConfig.default_user_col)
    resCol = Param("resCol", "resource column", str,
                   AccessAnomalyConfig.default_res_col)
    likelihoodCol = Param("likelihoodCol", "likelihood of the access (e.g. "
                          "counts per time unit)", str,
                          AccessAnomalyConfig.default_likelihood_col)
    outputCol = Param("outputCol", "anomaly score column (mean 0, std 1)", str,
                      AccessAnomalyConfig.default_output_col)
    rankParam = Param("rankParam", "number of latent factors", int, 10)
    maxIter = Param("maxIter", "ALS iterations", int, 25)
    regParam = Param("regParam", "ALS regularization", float, 0.1)
    lowValue = Param("lowValue", "likelihood scaled-range low", float, 5.0)
    highValue = Param("highValue", "likelihood scaled-range high", float, 10.0)
    applyImplicitCf = Param("applyImplicitCf", "implicit-feedback ALS", bool,
                            True)
    alphaParam = Param("alphaParam", "implicit confidence scale", float, 1.0)
    complementsetFactor = Param("complementsetFactor",
                                "negatives per positive (explicit mode)", int, 2)
    negScore = Param("negScore", "score assigned to complement-set pairs "
                     "(explicit mode)", float, 1.0)
    separateTenants = Param("separateTenants", "kept for API parity; tenants "
                            "are always isolated here", bool, False)
    seed = Param("seed", "random seed", int, 0)


class AccessAnomaly(Estimator, _AccessAnomalyParams):
    def _fit(self, df: Table) -> "AccessAnomalyModel":
        tenants = df[self.getTenantCol()]
        models: Dict[Any, dict] = {}
        for t in np.unique(tenants):
            key = t.item() if isinstance(t, np.generic) else t
            models[key] = self._fit_tenant(df.take(np.flatnonzero(tenants == t)))
        return AccessAnomalyModel(
            tenantModels=models, **{p: self.get(p) for p in self._paramMap})

    def _fit_tenant(self, df: Table) -> dict:
        users, u_ix = np.unique(df[self.getUserCol()], return_inverse=True)
        ress, r_ix = np.unique(df[self.getResCol()], return_inverse=True)
        lik = (np.asarray(df[self.getLikelihoodCol()], np.float64)
               if self.getLikelihoodCol() in df else np.ones(df.num_rows))
        # scale likelihood to [lowValue, highValue] (reference :616 lowValue doc)
        lo, hi = self.getLowValue(), self.getHighValue()
        if lik.max() > lik.min():
            lik = lo + (hi - lo) * (lik - lik.min()) / (lik.max() - lik.min())
        else:
            lik = np.full_like(lik, lo)
        n_u, n_r = len(users), len(ress)
        R = np.zeros((n_u, n_r), dtype=np.float32)
        R[u_ix, r_ix] = lik

        if self.getApplyImplicitCf():
            U, V = _als_implicit(R, self.getRankParam(), self.getMaxIter(),
                                 self.getRegParam(), self.getAlphaParam(),
                                 self.getSeed())
        else:
            U, V = _als_explicit(R, self.getRankParam(), self.getMaxIter(),
                                 self.getRegParam(), self.getNegScore(),
                                 self.getComplementsetFactor(), self.getSeed())

        # per-tenant normalization of observed-access scores to mean 0 / std 1
        # (reference ModelNormalizeTransformer:1080-1140); score = -affinity
        raw = -np.einsum("ij,ij->i", U[u_ix], V[r_ix])
        mean, std = float(raw.mean()), float(raw.std()) or 1.0
        return {"users": {u.item() if isinstance(u, np.generic) else u: i
                          for i, u in enumerate(users)},
                "resources": {r.item() if isinstance(r, np.generic) else r: i
                              for i, r in enumerate(ress)},
                "U": U, "V": V, "mean": mean, "std": std}


class AccessAnomalyModel(Model, _AccessAnomalyParams):
    tenantModels = Param("tenantModels",
                         "tenant -> {users, resources, U, V, mean, std}",
                         is_complex=True)

    def _transform(self, df: Table) -> Table:
        models = self.get("tenantModels")
        tenants = df[self.getTenantCol()]
        users = df[self.getUserCol()]
        ress = df[self.getResCol()]
        out = np.zeros(df.num_rows, dtype=np.float64)
        for t in np.unique(tenants):
            key = t.item() if isinstance(t, np.generic) else t
            m = models.get(key)
            if m is None:
                continue
            rows = np.flatnonzero(tenants == t)
            # vectorized per tenant: map to indices once, one batched einsum
            ui = np.asarray([m["users"].get(
                u.item() if isinstance(u, np.generic) else u, -1)
                for u in users[rows]])
            ri = np.asarray([m["resources"].get(
                r.item() if isinstance(r, np.generic) else r, -1)
                for r in ress[rows]])
            valid = (ui >= 0) & (ri >= 0)  # unseen user/resource scores 0
            if not valid.any():
                continue
            raw = -np.einsum("ij,ij->i", m["U"][ui[valid]], m["V"][ri[valid]])
            out[rows[valid]] = (raw - m["mean"]) / m["std"]
        return df.with_column(self.getOutputCol(), out)


class ComplementAccessTransformer(Transformer):
    """Emit (tenant, user, res) pairs NOT present in the input — a sample of
    the complement set (reference anomaly/complement_access.py:13-130)."""

    tenantCol = Param("tenantCol", "tenant column", str,
                      AccessAnomalyConfig.default_tenant_col)
    indexedColNamesArr = Param("indexedColNamesArr", "indexed columns", list)
    complementsetFactor = Param("complementsetFactor",
                                "complement samples per observed row", int, 2)
    seed = Param("seed", "random seed", int, 0)

    def _transform(self, df: Table) -> Table:
        cols = self.get("indexedColNamesArr") or ["user", "res"]
        u_col, r_col = cols[0], cols[1]
        tenants = df[self.getTenantCol()]
        rng = np.random.default_rng(self.getSeed())
        out = {self.getTenantCol(): [], u_col: [], r_col: []}
        for t in np.unique(tenants):
            sel = tenants == t
            us = np.unique(df[u_col][sel])
            rs = np.unique(df[r_col][sel])
            seen = set(zip(df[u_col][sel].tolist(), df[r_col][sel].tolist()))
            want = self.getComplementsetFactor() * int(sel.sum())
            budget = len(us) * len(rs) - len(seen)
            want = min(want, max(budget, 0))
            tries = 0
            emitted = set()
            while len(emitted) < want and tries < 50 * max(want, 1):
                pair = (us[rng.integers(len(us))], rs[rng.integers(len(rs))])
                tries += 1
                if pair in seen or pair in emitted:
                    continue
                emitted.add(pair)
            for u, r in emitted:
                out[self.getTenantCol()].append(t)
                out[u_col].append(u)
                out[r_col].append(r)
        return Table({k: np.asarray(v) for k, v in out.items()})


# --------------------------------------------------------------------------
# ALS solvers (dense, jitted; per-tenant matrices are small)

def _als_implicit(R: np.ndarray, rank: int, iters: int, reg: float,
                  alpha: float, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Implicit-feedback ALS (Hu/Koren/Volinsky): confidence C = 1 + alpha·R,
    preference P = [R > 0]. Batched per-row solves via vmap."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    n_u, n_r = R.shape
    U0 = rng.normal(scale=0.1, size=(n_u, rank)).astype(np.float32)
    V0 = rng.normal(scale=0.1, size=(n_r, rank)).astype(np.float32)

    @jax.jit
    def run(R, U, V):
        P = (R > 0).astype(jnp.float32)
        C = 1.0 + alpha * R
        eye = reg * jnp.eye(rank, dtype=jnp.float32)

        def solve_side(X, Cm, Pm):
            # for each row i: (Xᵀ Cᵢ X + λI) w = Xᵀ Cᵢ pᵢ
            def one(c_row, p_row):
                XtC = X.T * c_row[None, :]
                A = XtC @ X + eye
                b = XtC @ p_row
                return jnp.linalg.solve(A, b)

            return jax.vmap(one)(Cm, Pm)

        def body(_, UV):
            U, V = UV
            U = solve_side(V, C, P)
            V = solve_side(U, C.T, P.T)
            return U, V

        return jax.lax.fori_loop(0, iters, body, (U, V))

    U, V = run(jnp.asarray(R), jnp.asarray(U0), jnp.asarray(V0))
    return np.asarray(U), np.asarray(V)


def _als_explicit(R: np.ndarray, rank: int, iters: int, reg: float,
                  neg_score: float, complement_factor: int, seed: int
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Explicit ALS over observed entries plus complement-set negatives set to
    ``neg_score`` (reference applyImplicitCf=False branch)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    n_u, n_r = R.shape
    obs = R > 0
    # sample complement entries into a weight mask
    W = obs.astype(np.float32).copy()
    Rfull = R.astype(np.float32).copy()
    n_neg = min(complement_factor * int(obs.sum()), obs.size - int(obs.sum()))
    if n_neg > 0:
        flat_closed = np.flatnonzero(~obs.ravel())
        chosen = rng.choice(flat_closed, size=n_neg, replace=False)
        W.ravel()[chosen] = 1.0
        Rfull.ravel()[chosen] = neg_score
    U0 = rng.normal(scale=0.1, size=(n_u, rank)).astype(np.float32)
    V0 = rng.normal(scale=0.1, size=(n_r, rank)).astype(np.float32)

    @jax.jit
    def run(Rm, Wm, U, V):
        eye = reg * jnp.eye(rank, dtype=jnp.float32)

        def solve_side(X, Rt, Wt):
            def one(r_row, w_row):
                XtW = X.T * w_row[None, :]
                A = XtW @ X + eye
                b = XtW @ r_row
                return jnp.linalg.solve(A, b)

            return jax.vmap(one)(Rt, Wt)

        def body(_, UV):
            U, V = UV
            U = solve_side(V, Rm, Wm)
            V = solve_side(U, Rm.T, Wm.T)
            return U, V

        return jax.lax.fori_loop(0, iters, body, (U, V))

    U, V = run(jnp.asarray(Rfull), jnp.asarray(W), jnp.asarray(U0),
               jnp.asarray(V0))
    return np.asarray(U), np.asarray(V)
