"""Per-partition id indexers.

Reference: cyber/feature/indexers.py — IdIndexer maps a string column to
1-based contiguous indices *per partition key* (the tenant), so each tenant's
id space is independent; MultiIndexer bundles several.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.params import Param, Params
from ..core.pipeline import Estimator, Model
from ..core.table import Table


class _IdIndexerParams(Params):
    inputCol = Param("inputCol", "column to index", str)
    partitionKey = Param("partitionKey", "tenant column defining independent "
                         "index spaces", str)
    outputCol = Param("outputCol", "output index column", str)
    resetPerPartition = Param("resetPerPartition",
                              "restart indices at 1 for each partition", bool,
                              True)


class IdIndexer(Estimator, _IdIndexerParams):
    def _fit(self, df: Table) -> "IdIndexerModel":
        part = df[self.getPartitionKey()]
        vals = df[self.getInputCol()]
        vocab: Dict[Any, Dict[Any, int]] = {}
        reset = self.getResetPerPartition()
        global_next = [1]
        for p, v in zip(part, vals):
            p = p.item() if isinstance(p, np.generic) else p
            v = v.item() if isinstance(v, np.generic) else v
            per = vocab.setdefault(p, {})
            if v not in per:
                if reset:
                    per[v] = len(per) + 1
                else:
                    per[v] = global_next[0]
                    global_next[0] += 1
        return IdIndexerModel(vocabulary=vocab,
                              **{p_: self.get(p_) for p_ in self._paramMap})


class IdIndexerModel(Model, _IdIndexerParams):
    vocabulary = Param("vocabulary", "partition -> value -> index",
                       is_complex=True)

    def _transform(self, df: Table) -> Table:
        vocab = self.get("vocabulary")
        part = df[self.getPartitionKey()]
        vals = df[self.getInputCol()]
        out = np.zeros(len(vals), dtype=np.int64)  # 0 = unseen
        for i, (p, v) in enumerate(zip(part, vals)):
            p = p.item() if isinstance(p, np.generic) else p
            v = v.item() if isinstance(v, np.generic) else v
            out[i] = vocab.get(p, {}).get(v, 0)
        return df.with_column(self.getOutputCol(), out)

    def undo_transform(self, df: Table) -> Table:
        vocab = self.get("vocabulary")
        inverse: Dict[Tuple[Any, int], Any] = {
            (p, i): v for p, m in vocab.items() for v, i in m.items()}
        part = df[self.getPartitionKey()]
        idx = df[self.getOutputCol()]
        out = np.empty(len(idx), dtype=object)
        for i, (p, j) in enumerate(zip(part, idx)):
            p = p.item() if isinstance(p, np.generic) else p
            out[i] = inverse.get((p, int(j)))
        return df.with_column(self.getInputCol(), out)


class MultiIndexer(Estimator):
    """Bundle of IdIndexers (reference indexers.py:163-170)."""

    indexers = Param("indexers", "list of IdIndexer", is_complex=True)

    def __init__(self, indexers: Optional[List[IdIndexer]] = None, **kwargs):
        super().__init__(**kwargs)
        if indexers is not None:
            self.set("indexers", indexers)

    def _fit(self, df: Table) -> "MultiIndexerModel":
        models = [ix.fit(df) for ix in (self.get("indexers") or [])]
        return MultiIndexerModel(models=models)


class MultiIndexerModel(Model):
    models = Param("models", "list of IdIndexerModel", is_complex=True)

    def __init__(self, models: Optional[List[IdIndexerModel]] = None, **kwargs):
        super().__init__(**kwargs)
        if models is not None:
            self.set("models", models)

    def get_model_by_input_col(self, input_col: str) -> Optional[IdIndexerModel]:
        for m in self.get("models"):
            if m.getInputCol() == input_col:
                return m
        return None

    def get_model_by_output_col(self, output_col: str) -> Optional[IdIndexerModel]:
        for m in self.get("models"):
            if m.getOutputCol() == output_col:
                return m
        return None

    def _transform(self, df: Table) -> Table:
        cur = df
        for m in self.get("models"):
            cur = m.transform(cur)
        return cur

    def undo_transform(self, df: Table) -> Table:
        cur = df
        for m in self.get("models"):
            cur = m.undo_transform(cur)
        return cur
