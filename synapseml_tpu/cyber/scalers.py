"""Per-partition scalar scalers.

Reference: cyber/feature/scalers.py — StandardScalarScaler (z-score per
partition/tenant, optional target mean/std) and LinearScalarScaler (min-max to
a required range per partition).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..core.params import Param, Params
from ..core.pipeline import Estimator, Model
from ..core.table import Table


class _ScalerParams(Params):
    inputCol = Param("inputCol", "column to scale", str)
    partitionKey = Param("partitionKey", "tenant column", str)
    outputCol = Param("outputCol", "scaled output column", str)


def _per_partition(df: Table, params: _ScalerParams, stat_fn) -> Dict[Any, tuple]:
    part = df[params.getPartitionKey()]
    vals = np.asarray(df[params.getInputCol()], dtype=np.float64)
    stats: Dict[Any, tuple] = {}
    for p in np.unique(part):
        key = p.item() if isinstance(p, np.generic) else p
        stats[key] = stat_fn(vals[part == p])
    return stats


def _apply(df: Table, params: _ScalerParams, stats, map_fn) -> Table:
    part = df[params.getPartitionKey()]
    vals = np.asarray(df[params.getInputCol()], dtype=np.float64)
    out = np.zeros_like(vals)
    for i, (p, v) in enumerate(zip(part, vals)):
        key = p.item() if isinstance(p, np.generic) else p
        out[i] = map_fn(stats[key], v) if key in stats else v
    return df.with_column(params.getOutputCol(), out)


class StandardScalarScaler(Estimator, _ScalerParams):
    coefficientFactor = Param("coefficientFactor", "multiply the standardized "
                              "value", float, 1.0)
    targetMean = Param("targetMean", "mean after scaling", float, 0.0)
    targetStd = Param("targetStd", "std after scaling", float, 1.0)

    def _fit(self, df: Table) -> "StandardScalarScalerModel":
        stats = _per_partition(df, self, lambda v: (float(v.mean()),
                                                    float(v.std()) or 1.0))
        return StandardScalarScalerModel(
            stats=stats, **{p: self.get(p) for p in self._paramMap})


class StandardScalarScalerModel(Model, _ScalerParams):
    stats = Param("stats", "partition -> (mean, std)", is_complex=True)
    coefficientFactor = Param("coefficientFactor", "", float, 1.0)
    targetMean = Param("targetMean", "", float, 0.0)
    targetStd = Param("targetStd", "", float, 1.0)

    def _transform(self, df: Table) -> Table:
        tm, ts = self.getTargetMean(), self.getTargetStd()
        cf = self.getCoefficientFactor()

        def scale(stat, v):
            mean, std = stat
            return cf * (tm + ts * (v - mean) / (std if std else 1.0))

        return _apply(df, self, self.get("stats"), scale)


class LinearScalarScaler(Estimator, _ScalerParams):
    minRequiredValue = Param("minRequiredValue", "output range min", float, 0.0)
    maxRequiredValue = Param("maxRequiredValue", "output range max", float, 1.0)

    def _fit(self, df: Table) -> "LinearScalarScalerModel":
        stats = _per_partition(df, self, lambda v: (float(v.min()),
                                                    float(v.max())))
        return LinearScalarScalerModel(
            stats=stats, **{p: self.get(p) for p in self._paramMap})


class LinearScalarScalerModel(Model, _ScalerParams):
    stats = Param("stats", "partition -> (min, max)", is_complex=True)
    minRequiredValue = Param("minRequiredValue", "", float, 0.0)
    maxRequiredValue = Param("maxRequiredValue", "", float, 1.0)

    def _transform(self, df: Table) -> Table:
        lo, hi = self.getMinRequiredValue(), self.getMaxRequiredValue()

        def scale(stat, v):
            vmin, vmax = stat
            if vmax == vmin:
                return (lo + hi) / 2.0
            return lo + (hi - lo) * (v - vmin) / (vmax - vmin)

        return _apply(df, self, self.get("stats"), scale)
