"""CyberML — access-anomaly detection and cyber feature engineering.

Reference: core/src/main/python/synapse/ml/cyber/ (~2.5k LoC pure PySpark;
SURVEY.md §2.7): anomaly/collaborative_filtering.py (AccessAnomaly — ALS over
user×resource access likelihoods, standardized anomaly scores),
anomaly/complement_access.py, feature/indexers.py, feature/scalers.py.
The reference runs Spark ALS per tenant; here each tenant's factorization is a
dense jitted alternating-ridge solve (vmapped batched linear solves on the MXU).
"""

from .access_anomaly import (AccessAnomaly, AccessAnomalyConfig,
                             AccessAnomalyModel, ComplementAccessTransformer)
from .indexers import IdIndexer, IdIndexerModel, MultiIndexer, MultiIndexerModel
from .scalers import (LinearScalarScaler, LinearScalarScalerModel,
                      StandardScalarScaler, StandardScalarScalerModel)

__all__ = [
    "AccessAnomaly", "AccessAnomalyConfig", "AccessAnomalyModel",
    "ComplementAccessTransformer",
    "IdIndexer", "IdIndexerModel", "MultiIndexer", "MultiIndexerModel",
    "StandardScalarScaler", "StandardScalarScalerModel",
    "LinearScalarScaler", "LinearScalarScalerModel",
]
