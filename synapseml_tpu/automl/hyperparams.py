"""Hyperparameter space definitions (reference: core/.../automl/
{HyperparamBuilder,ParamSpace,DefaultHyperparams}.scala)."""

from __future__ import annotations

import itertools
from typing import Any, Dict, Iterator, List, Sequence

import numpy as np


class DiscreteHyperParam:
    """A finite set of candidate values."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def sample(self, rng: np.random.Generator):
        return self.values[int(rng.integers(0, len(self.values)))]

    def grid(self) -> List[Any]:
        return list(self.values)


class RangeHyperParam:
    """A continuous [low, high) range (log-scale optional)."""

    def __init__(self, low, high, log: bool = False, integer: bool = None):
        self.low, self.high, self.log = low, high, log
        self.integer = (isinstance(low, int) and isinstance(high, int)
                        if integer is None else integer)

    def sample(self, rng: np.random.Generator):
        if self.log:
            v = float(np.exp(rng.uniform(np.log(self.low), np.log(self.high))))
        else:
            v = float(rng.uniform(self.low, self.high))
        return int(round(v)) if self.integer else v

    def grid(self, n: int = 5) -> List[Any]:
        if self.log:
            vals = np.exp(np.linspace(np.log(self.low), np.log(self.high), n))
        else:
            vals = np.linspace(self.low, self.high, n)
        return [int(round(v)) for v in vals] if self.integer else [float(v) for v in vals]


class HyperparamBuilder:
    """Collects (paramName → space) pairs (HyperparamBuilder.scala)."""

    def __init__(self):
        self._space: Dict[str, Any] = {}

    def addHyperparam(self, name: str, space) -> "HyperparamBuilder":
        self._space[name] = space
        return self

    def build(self) -> Dict[str, Any]:
        return dict(self._space)


class GridSpace:
    """Cartesian product of all discrete/gridded spaces (ParamSpace grid)."""

    def __init__(self, space: Dict[str, Any], grid_points: int = 5):
        self.names = list(space)
        self.grids = [space[n].grid() if isinstance(space[n], DiscreteHyperParam)
                      else space[n].grid(grid_points) for n in self.names]

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        for combo in itertools.product(*self.grids):
            yield dict(zip(self.names, combo))


class RandomSpace:
    """Random draws from each space (ParamSpace random)."""

    def __init__(self, space: Dict[str, Any], num_samples: int, seed: int = 0):
        self.space, self.n, self.seed = space, num_samples, seed

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        rng = np.random.default_rng(self.seed)
        for _ in range(self.n):
            yield {k: v.sample(rng) for k, v in self.space.items()}
