"""Spool worker for :class:`automl.scheduler.GangCandidatePool`.

One process per gang rank, launched by the pool's ``TrainingSupervisor`` as
``python -m synapseml_tpu.automl.worker --spool DIR --rank R``. Protocol,
all through atomically-renamed files in the spool directory:

* the pool writes ``task_<id>.json`` — ``{"id", "entry": "pkg.mod:fn",
  "payload": {...}}``;
* a worker CLAIMS a task by renaming it to
  ``task_<id>.claimed.r<rank>.p<pid>`` (rename is atomic: exactly one
  claimant; the pid keys the claim to this process so a respawned rank is a
  different claimant and the pool re-spools the orphan);
* the worker resolves ``entry`` by import, runs ``fn(**payload)`` and writes
  ``result_<id>.json`` — ``{"id", "ok": true, "value": ...}`` or
  ``{"ok": false, "error": ...}`` (the *task* failing is a result; only the
  worker dying is a crash);
* a ``stop`` file in the spool shuts every worker down.

Liveness is the standard ``hb_p<rank>.json`` heartbeat
(``parallel.elastic.HeartbeatWriter`` on a background beater), so a hung
entry point is indistinguishable from a dead worker to the supervisor —
exactly the failure model the scheduler's reaper expects.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import time
import traceback


def _resolve(entry: str):
    mod, _, fn = entry.partition(":")
    if not mod or not fn:
        raise ValueError(f"entry must be 'pkg.mod:fn', got {entry!r}")
    return getattr(importlib.import_module(mod), fn)


def _claim(spool: str, fn: str, rank: int) -> str | None:
    src = os.path.join(spool, fn)
    dst = os.path.join(spool, f"{fn[:-len('.json')]}.claimed"
                              f".r{rank}.p{os.getpid()}")
    try:
        os.rename(src, dst)
        return dst
    except OSError:
        return None        # another rank won the rename race


def run_worker(spool: str, rank: int, poll: float = 0.05,
               max_tasks: int | None = None) -> int:
    """Poll-claim-run loop; returns the number of tasks completed."""
    from ..core.checkpoint import atomic_write_text
    from ..parallel.elastic import HeartbeatWriter

    done = 0
    with HeartbeatWriter(spool, rank, interval=0.25) as hb:
        while max_tasks is None or done < max_tasks:
            if os.path.exists(os.path.join(spool, "stop")):
                break
            claimed = None
            for fn in sorted(os.listdir(spool)):
                if fn.startswith("task_") and fn.endswith(".json"):
                    claimed = _claim(spool, fn, rank)
                    if claimed:
                        break
            if not claimed:
                time.sleep(poll)
                continue
            with open(claimed) as f:
                spec = json.load(f)
            tid = spec["id"]
            hb.beat(f"task_{tid}")
            try:
                value = _resolve(spec["entry"])(**spec.get("payload", {}))
                rec = {"id": tid, "ok": True, "value": value}
            except Exception:  # noqa: BLE001 — a failed task is a result
                rec = {"id": tid, "ok": False,
                       "error": traceback.format_exc(limit=8)}
            atomic_write_text(os.path.join(spool, f"result_{tid}.json"),
                              json.dumps(rec, default=repr))
            os.remove(claimed)
            done += 1
            hb.beat("idle")
    return done


def _echo(value=None, sleep_s: float = 0.0, crash: bool = False):
    """Importable self-test entry point ("synapseml_tpu.automl.worker:_echo")
    for the gang protocol tests: optionally sleeps (hang/kill windows),
    optionally raises (failed-task-is-a-result path), else echoes."""
    if sleep_s:
        time.sleep(float(sleep_s))
    if crash:
        raise RuntimeError("deliberate _echo crash")
    return value


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--spool", required=True)
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--poll", type=float, default=0.05)
    args = ap.parse_args(argv)
    run_worker(args.spool, args.rank, poll=args.poll)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
