"""AutoML (SURVEY §2.7 automl/, 800 LoC in reference): hyperparameter spaces,
TuneHyperparameters (random/grid search with elastic successive-halving
cross-validation — see automl/scheduler.py and docs/automl.md), and
FindBestModel."""

from .hyperparams import (DiscreteHyperParam, GridSpace, HyperparamBuilder,
                          RandomSpace, RangeHyperParam)
from .scheduler import (BracketState, ElasticHalvingScheduler,
                        GangCandidatePool, RungSpec, plan_rungs)
from .tune import FindBestModel, FindBestModelResult, TuneHyperparameters, TuneHyperparametersModel

__all__ = ["DiscreteHyperParam", "RangeHyperParam", "HyperparamBuilder",
           "GridSpace", "RandomSpace", "TuneHyperparameters",
           "TuneHyperparametersModel", "FindBestModel", "FindBestModelResult",
           "RungSpec", "plan_rungs", "BracketState",
           "ElasticHalvingScheduler", "GangCandidatePool"]
