"""AutoML (SURVEY §2.7 automl/, 800 LoC in reference): hyperparameter spaces,
TuneHyperparameters (random/grid search with parallel cross-validation), and
FindBestModel."""

from .hyperparams import (DiscreteHyperParam, GridSpace, HyperparamBuilder,
                          RandomSpace, RangeHyperParam)
from .tune import FindBestModel, FindBestModelResult, TuneHyperparameters, TuneHyperparametersModel

__all__ = ["DiscreteHyperParam", "RangeHyperParam", "HyperparamBuilder",
           "GridSpace", "RandomSpace", "TuneHyperparameters",
           "TuneHyperparametersModel", "FindBestModel", "FindBestModelResult"]
