"""Elastic successive-halving scheduler — preemptible AutoML on the gang.

``automl/tune.py`` used to be a bare ThreadPoolExecutor: no early stopping,
no hang detection, and a crash anywhere wedged one pool slot forever. This
module rebuilds that substrate as an ASHA-style successive-halving bracket
(Li et al., arXiv:1810.05934) in which every candidate is a *preemptible
elastic job*:

* **Rungs** — the resource axis is cumulative CV folds. ``plan_rungs`` lays
  a geometric ladder (``eta``): every candidate runs ``min_resource`` folds
  at rung 0, only the top ``ceil(n/eta)`` advance and run up to
  ``min_resource*eta`` folds, and so on until the survivors of the last rung
  hold full-``total_resource`` CV scores. Execution inside a rung is
  asynchronous (any pool order); promotion happens at a *deterministic rung
  barrier*: survivors are ranked by score with NaN always last and ties
  broken by first-seen candidate index, so two runs of the same bracket —
  interrupted or not — promote identically.
* **Budgeted tasks** — each rung task runs under a
  :func:`~synapseml_tpu.parallel.elastic.run_with_budget` reaper (the
  ``CollectiveWatchdog`` machinery without peer heartbeats): a hung
  candidate raises ``PeerLostError`` at the budget, is scored NaN
  (``automl.candidate_hang``), and its pool slot is freed — the abandoned
  daemon thread cannot wedge the bracket. The budget itself is priced by
  ``core/perfmodel.py`` ("automl_rung" rows) when the model is confident,
  and observed rung times are journaled back as training rows.
* **Crash respawn** — a candidate that raises is retried in place up to
  ``max_attempts`` (``automl.candidate_retry`` per retry); only terminal
  failure scores NaN and counts ``automl.candidate_failure`` once.
* **Checkpointed bracket state** — per-candidate fold scores, attempt
  counters, and every promotion decision persist through ``CheckpointStore``
  (atomic, digest-verified) after every completed task and every barrier,
  keyed by a search *fingerprint* (data digest + space + metric + folds).
  kill -9 at any point — mid-candidate, mid-rung, mid-promotion — resumes to
  the identical best model; a resume against a different fingerprint refuses
  loudly instead of silently reusing stale scores.
* **Gang scheduling** — tasks run on the in-process ``LocalElasticPool`` by
  default; :class:`GangCandidatePool` spools them to a
  ``TrainingSupervisor``-managed gang of ``automl/worker.py`` processes
  (heartbeats, respawn-on-crash, ``kill_rank``-able) for callers whose
  candidate entry points are importable.

``testing.chaos.chaos_candidate`` installs :data:`_CHAOS_HOOK` to inject
seeded crash/hang/NaN/slowdown per (candidate, rung, attempt); because the
action is a pure function of those coordinates plus the seed, a chaotic run
is still deterministic across kill→resume. See docs/automl.md.
"""

from __future__ import annotations

import hashlib
import json
import math
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..core import perfmodel
from ..core.checkpoint import CheckpointStore
from ..core.logging import record_failure
from ..parallel.elastic import PeerLostError, run_with_budget

__all__ = ["RungSpec", "plan_rungs", "BracketState",
           "ElasticHalvingScheduler", "GangCandidatePool",
           "fingerprint_digest", "PERF_KIND"]

#: perfmodel decision family for rung-time rows ("this PR makes the learned
#: cost model price search, not just kernels")
PERF_KIND = "automl_rung"

#: chaos hook slot — ``testing.chaos.chaos_candidate`` installs a callable
#: ``hook(key, rung, attempt) -> Optional[str]`` invoked inside the budgeted
#: task thread; it may raise (crash), block (hang — reaped by the budget),
#: sleep (slowdown) or return ``"nan"`` to poison the metric. Single global
#: slot, same pattern as ``core.checkpoint._PREEMPT_HOOK``.
_CHAOS_HOOK: Optional[Callable[[str, int, int], Optional[str]]] = None

#: watchdog budget = safety × predicted rung seconds (priced mode)
_BUDGET_SAFETY = 4.0
_MIN_PRICED_BUDGET_S = 1.0
_PRICE_MIN_CONFIDENCE = 0.5


# --------------------------------------------------------------------- rungs

@dataclass(frozen=True)
class RungSpec:
    """One rung: ``survivors`` candidates each holding ``resource``
    cumulative folds by the rung's barrier."""
    index: int
    resource: int        # cumulative folds completed at this rung's barrier
    survivors: int       # candidates entering this rung


def plan_rungs(n_candidates: int, total_resource: int, eta: int = 3,
               min_resource: int = 1) -> List[RungSpec]:
    """Geometric successive-halving ladder.

    ``eta <= 1`` (or a single candidate, or no room between ``min_resource``
    and ``total_resource``) degenerates to ONE rung at full resource — the
    exhaustive-CV behavior the pre-bracket searcher had. The final rung is
    always at ``total_resource`` so the winner's metric is a full-CV score,
    directly comparable with exhaustive search.
    """
    n = max(int(n_candidates), 1)
    total = max(int(total_resource), 1)
    lo = max(min(int(min_resource), total), 1)
    if eta <= 1 or n <= 1 or lo >= total:
        return [RungSpec(0, total, n)]
    rungs: List[RungSpec] = []
    res, surv = lo, n
    while True:
        rungs.append(RungSpec(len(rungs), res, surv))
        if res >= total or surv <= 1:
            break
        surv = max(1, math.ceil(surv / eta))
        res = min(total, res * eta)
    if rungs[-1].resource != total:   # cap the ladder at full CV
        rungs.append(RungSpec(len(rungs), total,
                              max(1, math.ceil(rungs[-1].survivors / eta))))
    return rungs


def fingerprint_digest(fingerprint: Dict[str, Any]) -> str:
    """Stable digest of the search identity (data/space/metric/folds)."""
    blob = json.dumps(fingerprint, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:24]


# --------------------------------------------------------------- bracket state

@dataclass
class BracketState:
    """Everything a resume needs, JSON-serializable for ``CheckpointStore``.

    ``fold_scores[key]`` grows monotonically (one entry per completed fold);
    ``promoted[rung]`` records each barrier decision verbatim so a resumed
    bracket REPLAYS past promotions instead of recomputing them — the
    decisions, not just the scores, are part of the checkpoint."""
    fingerprint: str = ""
    fold_scores: Dict[str, List[float]] = field(default_factory=dict)
    final: Dict[str, float] = field(default_factory=dict)
    failed: Dict[str, str] = field(default_factory=dict)   # key -> crash|hang
    attempts: Dict[str, int] = field(default_factory=dict)
    promoted: Dict[str, List[str]] = field(default_factory=dict)  # rung->keys
    rung: int = 0            # first rung whose barrier has NOT been crossed
    events: int = 0          # monotonic save counter (checkpoint step)

    def to_bytes(self) -> bytes:
        return json.dumps({
            "fingerprint": self.fingerprint,
            "fold_scores": self.fold_scores,
            "final": self.final,
            "failed": self.failed,
            "attempts": self.attempts,
            "promoted": self.promoted,
            "rung": self.rung,
            "events": self.events,
        }, sort_keys=True).encode("utf-8")

    @classmethod
    def from_bytes(cls, data: bytes) -> "BracketState":
        d = json.loads(data.decode("utf-8"))
        return cls(fingerprint=d.get("fingerprint", ""),
                   fold_scores={k: [float(s) for s in v]
                                for k, v in d.get("fold_scores", {}).items()},
                   final={k: float(v) for k, v in d.get("final", {}).items()},
                   failed=dict(d.get("failed", {})),
                   attempts={k: int(v)
                             for k, v in d.get("attempts", {}).items()},
                   promoted={k: list(v)
                             for k, v in d.get("promoted", {}).items()},
                   rung=int(d.get("rung", 0)),
                   events=int(d.get("events", 0)))


# ------------------------------------------------------------------ scheduler

class ElasticHalvingScheduler:
    """Run one successive-halving bracket over deduplicated candidates.

    ``run_folds(index, params, lo, hi)`` fits folds ``[lo, hi)`` for one
    candidate and returns their scores (list of floats; NaN allowed). It is
    invoked on a budgeted daemon thread and may raise — ``Exception`` means
    crash (retried), ``PeerLostError``/budget expiry means hang (reaped),
    and ``BaseException`` (``PreemptionError``) aborts the bracket after the
    rung's in-flight siblings drain, so their work is checkpointed first.

    ``candidates``/``keys`` are parallel lists; duplicate keys (a random
    space drawing the same point twice) collapse to ONE execution whose
    score every duplicate shares. ``completed`` maps keys to terminal
    metrics recovered from per-candidate resume records — those keys never
    execute again.
    """

    def __init__(self, run_folds: Callable[[int, Dict[str, Any], int, int],
                                           Sequence[float]],
                 candidates: Sequence[Dict[str, Any]],
                 keys: Sequence[str], *,
                 maximize: bool = True,
                 total_folds: int = 3,
                 eta: int = 0,
                 min_resource: int = 1,
                 parallelism: int = 4,
                 max_attempts: int = 2,
                 budget_s: Optional[float] = None,
                 rung_time_budget_s: Optional[float] = None,
                 store: Optional[CheckpointStore] = None,
                 fingerprint: Optional[Dict[str, Any]] = None,
                 completed: Optional[Dict[str, float]] = None,
                 perf_features: Optional[Dict[str, float]] = None,
                 perf_journal: bool = False,
                 pool: Optional["GangCandidatePool"] = None,
                 gang_task: Optional[Callable[[Dict[str, Any], int, int],
                                              Dict[str, Any]]] = None,
                 invalidate: Optional[Sequence[str]] = None):
        if len(candidates) != len(keys):
            raise ValueError("candidates and keys must be parallel lists")
        self.run_folds = run_folds
        self.maximize = bool(maximize)
        self.total_folds = max(int(total_folds), 1)
        self.parallelism = max(int(parallelism), 1)
        self.max_attempts = max(int(max_attempts), 1)
        self.budget_s = float(budget_s) if budget_s else None
        self.rung_time_budget_s = (float(rung_time_budget_s)
                                   if rung_time_budget_s else None)
        self.store = store
        self.perf_features = dict(perf_features or {})
        self.perf_journal = bool(perf_journal)
        self.pool = pool
        self.gang_task = gang_task

        # dedup: first-seen order defines the execution set AND the
        # deterministic tie-break for promotions
        self.params: Dict[str, Dict[str, Any]] = {}
        self.first_index: Dict[str, int] = {}
        self.order: List[str] = []
        self.duplicates = 0
        for i, (p, k) in enumerate(zip(candidates, keys)):
            if k in self.params:
                self.duplicates += 1
                continue
            self.params[k] = p
            self.first_index[k] = i
            self.order.append(k)

        self.rungs = plan_rungs(len(self.order), self.total_folds,
                                eta=eta, min_resource=min_resource)
        self.fp_digest = fingerprint_digest(fingerprint or {})
        self._lock = threading.Lock()
        self.state = self._restore()
        for k in (invalidate or ()):
            # a corrupt/stale resume record poisons ALL memory of that
            # candidate — its folds recompute from scratch, deterministically
            self.state.fold_scores.pop(k, None)
            self.state.final.pop(k, None)
            self.state.failed.pop(k, None)
            self.state.attempts.pop(k, None)
        for k, v in (completed or {}).items():
            if k in self.params and k not in self.state.final:
                self.state.final[k] = float(v)
        self._record_hooks: List[Callable[[str, float, int], None]] = []

    # -- resume -----------------------------------------------------------
    def _restore(self) -> BracketState:
        if self.store is not None:
            ck = self.store.load_latest()
            if ck is not None:
                saved_fp = str(ck.meta.get("fingerprint", ""))
                if saved_fp != self.fp_digest:
                    raise ValueError(
                        "automl bracket resume refused: checkpoint "
                        f"fingerprint {saved_fp!r} does not match this "
                        f"search {self.fp_digest!r} — the data, search "
                        "space, metric or fold count changed. Point "
                        "checkpointDir at a fresh directory (or delete the "
                        "stale one) instead of silently reusing scores.")
                return BracketState.from_bytes(ck.artifacts["bracket.json"])
        return BracketState(fingerprint=self.fp_digest)

    def _save(self) -> None:
        if self.store is None:
            return
        self.state.events += 1
        self.store.save(self.state.events,
                        {"bracket.json": self.state.to_bytes()},
                        meta={"fingerprint": self.fp_digest})

    def on_candidate_done(self, hook: Callable[[str, float, int],
                                               None]) -> None:
        """Register ``hook(key, metric, folds_done)`` fired (under the state
        lock) when a candidate's participation ends — completion at full
        resource or elimination at a barrier. tune.py journals its
        ``cand_<key>.json`` resume records from here."""
        with self._lock:
            self._record_hooks.append(hook)

    # -- scores -----------------------------------------------------------
    def _mean(self, key: str) -> float:
        if key in self.state.final:
            return self.state.final[key]
        scores = self.state.fold_scores.get(key, [])
        if not scores:
            return float("nan")
        good = [s for s in scores if not math.isnan(s)]
        return sum(good) / len(good) if good else float("nan")

    def results(self) -> Dict[str, Dict[str, float]]:
        """key -> {metric, folds} for every deduplicated candidate."""
        out = {}
        for k in self.order:
            held = self.state.fold_scores.get(k, [])
            # a record-restored candidate has no fold history: report full
            # resource, the only rung a terminal record is written at
            folds = len(held) if held else (
                self.total_folds if k in self.state.final else 0)
            out[k] = {"metric": self._mean(k), "folds": folds}
        return out

    def finalists(self) -> List[str]:
        """Ranked non-NaN survivors of the last rung (may be empty when
        chaos killed every finalist — callers fall back to partial scores)."""
        return list(self.state.promoted.get(str(len(self.rungs) - 1), []))

    # -- perfmodel pricing -------------------------------------------------
    def _fold_features(self, n_folds: int) -> Dict[str, float]:
        f = dict(self.perf_features)
        f["folds"] = float(n_folds)
        return f

    def _predicted_chunk_s(self, n_folds: int) -> perfmodel.Prediction:
        return perfmodel.predict(perfmodel.Candidate(
            kind=PERF_KIND, arm="cv_fold",
            features=self._fold_features(n_folds)))

    def _task_budget(self, n_folds: int) -> Optional[float]:
        """Explicit budget wins; otherwise price one from the learned model
        (safety-factored) when it is confident; otherwise no reaper — a slow
        legitimate candidate must never be killed on a guess."""
        if self.budget_s is not None:
            return self.budget_s
        pred = self._predicted_chunk_s(n_folds)
        if pred.confidence >= _PRICE_MIN_CONFIDENCE and \
                math.isfinite(pred.seconds):
            return max(_MIN_PRICED_BUDGET_S, _BUDGET_SAFETY * pred.seconds)
        return None

    def _journal(self, n_folds: int, observed_s: float, rung: int) -> None:
        if not self.perf_journal:
            return
        try:
            perfmodel.append_training_row(
                PERF_KIND, "cv_fold", self._fold_features(n_folds),
                observed_s, rung=rung)
        except OSError:
            pass    # a read-only journal must not fail the search

    # -- task execution ----------------------------------------------------
    def _execute(self, key: str, rung: RungSpec, lo: int, hi: int,
                 attempt: int) -> Sequence[float]:
        """One attempt: chaos hook, then the fold fits, under the reaper."""
        def _task():
            hook = _CHAOS_HOOK
            action = hook(key, rung.index, attempt) if hook else None
            if action == "nan":
                return [float("nan")] * (hi - lo)
            return self.run_folds(self.first_index[key], self.params[key],
                                  lo, hi)
        budget = self._task_budget(hi - lo)
        if self.pool is not None and self.gang_task is not None:
            return self.pool.run_task(
                self.gang_task(self.params[key], lo, hi),
                budget_s=budget, op=f"automl.cand.{key[:8]}")
        if budget is None:
            return _task()
        return run_with_budget(_task, budget_s=budget,
                               op=f"automl.cand.{key[:8]}")

    def _finish(self, key: str, rung: RungSpec, lo: int,
                scores: Sequence[float], failed: str = "") -> None:
        with self._lock:
            held = self.state.fold_scores.setdefault(key, [])
            if len(held) != lo:     # stale double-completion guard
                return
            held.extend(float(s) for s in scores)
            if failed:
                self.state.failed[key] = failed
            done = failed or len(held) >= self.total_folds
            if done and key not in self.state.final:
                self.state.final[key] = self._mean(key)
                for hook in self._record_hooks:
                    hook(key, self.state.final[key], len(held))
            self._save()

    def _run_task(self, key: str, rung: RungSpec, lo: int, hi: int) -> None:
        attempt = self.state.attempts.get(key, 0)
        while True:
            with self._lock:
                self.state.attempts[key] = attempt
            t0 = time.monotonic()
            try:
                scores = self._execute(key, rung, lo, hi, attempt)
            except PeerLostError as e:
                # hung past the budget: reaped, never retried — the worker
                # thread is abandoned (daemon) and the slot is free
                record_failure("automl.candidate_hang", key=key,
                               rung=rung.index,
                               waited_s=round(e.waited_s, 3))
                self._finish(key, rung, lo, [float("nan")] * (hi - lo),
                             failed="hang")
                return
            except Exception as e:  # noqa: BLE001 — crash isolation
                attempt += 1
                if attempt < self.max_attempts:
                    record_failure("automl.candidate_retry", key=key,
                                   rung=rung.index, attempt=attempt,
                                   error=type(e).__name__)
                    continue
                # one broken candidate must not abort the search: score it
                # NaN (excluded by nanargmax/nanargmin) and keep going.
                # PreemptionError is a BaseException and still propagates.
                record_failure("automl.candidate_failure",
                               index=self.first_index[key],
                               error=type(e).__name__,
                               message=str(e)[:200])
                self._finish(key, rung, lo, [float("nan")] * (hi - lo),
                             failed="crash")
                return
            self._journal(hi - lo, time.monotonic() - t0, rung.index)
            self._finish(key, rung, lo, scores)
            return

    def _run_rung(self, rung: RungSpec, alive: List[str]) -> None:
        todo = []
        for key in alive:
            if key in self.state.final or key in self.state.failed:
                continue
            lo = len(self.state.fold_scores.get(key, []))
            if lo < rung.resource:
                todo.append((key, lo, rung.resource))
        if not todo:
            return
        preempt: Optional[BaseException] = None
        with ThreadPoolExecutor(max_workers=self.parallelism) as ex:
            futs = [ex.submit(self._run_task, key, rung, lo, hi)
                    for key, lo, hi in todo]
            for fut in futs:
                try:
                    fut.result()
                except BaseException as e:  # noqa: BLE001 — PreemptionError
                    # drain the rung's siblings (the with-block joins them)
                    # so their fold scores are checkpointed, THEN re-raise:
                    # the resume recomputes only the truly unfinished work
                    if preempt is None:
                        preempt = e
        if preempt is not None:
            raise preempt

    # -- barriers ----------------------------------------------------------
    def _ranked(self, alive: List[str]) -> List[str]:
        """Non-NaN candidates ranked best-first; index breaks ties. This is
        the single deterministic ordering every promotion derives from."""
        ok = [(k, self._mean(k)) for k in alive
              if not math.isnan(self._mean(k))]
        ok.sort(key=lambda ks: (-ks[1] if self.maximize else ks[1],
                                self.first_index[ks[0]]))
        return [k for k, _ in ok]

    def _quota(self, nxt: RungSpec) -> int:
        """Promotion quota: the ladder's count, optionally trimmed so the
        next rung's PREDICTED cost fits ``rung_time_budget_s`` — this is the
        perfmodel pricing the promotion decision (never below one)."""
        quota = nxt.survivors
        if self.rung_time_budget_s is None:
            return quota
        prev = 0 if nxt.index == 0 else self.rungs[nxt.index - 1].resource
        pred = self._predicted_chunk_s(nxt.resource - prev)
        if pred.confidence >= _PRICE_MIN_CONFIDENCE and \
                math.isfinite(pred.seconds) and pred.seconds > 0:
            affordable = int(self.rung_time_budget_s // pred.seconds)
            quota = max(1, min(quota, affordable))
        return quota

    def _promote(self, rung: RungSpec, alive: List[str],
                 nxt: RungSpec) -> List[str]:
        keep = self._ranked(alive)[: self._quota(nxt)]
        keep.sort(key=lambda k: self.first_index[k])
        with self._lock:
            self.state.promoted[str(rung.index)] = keep
            # elimination is terminal: the candidate's partial-fold mean is
            # its final metric, journaled like any completed candidate
            for k in alive:
                if k not in keep and k not in self.state.final:
                    self.state.final[k] = self._mean(k)
                    for hook in self._record_hooks:
                        hook(k, self.state.final[k],
                             len(self.state.fold_scores.get(k, [])))
            self.state.rung = rung.index + 1
            self._save()
        return keep

    def _finalize(self, rung: RungSpec, alive: List[str]) -> None:
        with self._lock:
            self.state.promoted[str(rung.index)] = self._ranked(alive)
            self.state.rung = rung.index + 1
            self._save()

    # -- driver ------------------------------------------------------------
    def run(self) -> Dict[str, Dict[str, float]]:
        """Execute (or resume) the bracket; returns :meth:`results`."""
        alive = list(self.order)
        for i, rung in enumerate(self.rungs):
            # execution always runs (it is a no-op when every alive
            # candidate already holds this rung's folds) so an invalidated
            # resume record heals by recomputation even inside rungs whose
            # barrier was crossed in a previous life
            self._run_rung(rung, alive)
            if self.state.rung > i:
                # barrier already crossed: REPLAY the recorded decision —
                # resumes never re-litigate promotions
                alive = [k for k in self.state.promoted.get(str(i), alive)
                         if k in self.params]
                continue
            if i + 1 < len(self.rungs):
                alive = self._promote(rung, alive, self.rungs[i + 1])
            else:
                self._finalize(rung, alive)
        return self.results()


# ------------------------------------------------------------------ gang pool

class GangCandidatePool:
    """Candidate tasks on a ``TrainingSupervisor`` gang of spool workers.

    The pool writes ``task_<id>.json`` files into a spool directory; each
    ``automl/worker.py`` process claims one by atomic rename, runs its
    importable entry point, and writes ``result_<id>.json``. Failure
    handling maps onto the scheduler's model exactly:

    * worker crash (or ``kill_rank``) while holding a task → the supervisor
      respawns the rank and the pool re-spools the orphaned task, raising
      nothing (transparent respawn) unless the per-task respawn budget is
      exhausted, at which point the task raises ``RuntimeError`` → the
      scheduler counts a crash;
    * no result within ``budget_s`` → ``PeerLostError`` → the scheduler
      reaps the candidate as hung.

    Entries must be importable (``"pkg.mod:fn"``) — arbitrary closures do
    not cross process boundaries, which is why tune.py defaults to the
    in-process pool and the gang path is opt-in.
    """

    def __init__(self, world_size: int = 2, spool_dir: Optional[str] = None,
                 max_respawns: int = 2, hb_timeout: float = 5.0,
                 poll: float = 0.05, env: Optional[Dict[str, str]] = None):
        import os
        import subprocess
        import sys
        import tempfile

        from ..parallel.elastic import TrainingSupervisor

        self.spool = spool_dir or tempfile.mkdtemp(prefix="automl_spool_")
        os.makedirs(self.spool, exist_ok=True)
        self.poll = float(poll)
        self._ids = 0
        self._lock = threading.Lock()
        self._env = dict(env or {})

        def _spawn(rank: int, world: int, attempt: int):
            e = dict(os.environ)
            e.setdefault("JAX_PLATFORMS", "cpu")
            e.update(self._env)
            # pre-beat from the parent: a missing heartbeat file reads as
            # stale, so without this a freshly-spawned (still importing)
            # worker would be respawned on the very first supervisor step
            from ..core.checkpoint import atomic_write_text
            atomic_write_text(
                os.path.join(self.spool, f"hb_p{rank}.json"),
                json.dumps({"rank": rank, "op": "spawning", "step": 0,
                            "seq": 0, "pid": 0}))
            return subprocess.Popen(
                [sys.executable, "-m", "synapseml_tpu.automl.worker",
                 "--spool", self.spool, "--rank", str(rank)], env=e)

        self.supervisor = TrainingSupervisor(
            _spawn, world_size=world_size, heartbeat_dir=self.spool,
            min_world=1, hb_timeout=hb_timeout, max_respawns=max_respawns,
            interval=poll).start_gang()

    def _next_id(self) -> str:
        with self._lock:
            self._ids += 1
            return f"{self._ids:06d}"

    def run_task(self, task: Dict[str, Any], budget_s: Optional[float] = None,
                 op: str = "gang_task", max_requeues: int = 2) -> Any:
        """Spool one ``{"entry": "pkg.mod:fn", "payload": {...}}`` task and
        block for its result, pumping the supervisor while waiting."""
        import os

        from ..core.checkpoint import atomic_write_text

        requeues = 0
        deadline = (time.monotonic() + budget_s) if budget_s else None
        tid = self._next_id()
        spec = json.dumps({"id": tid, **task}, default=repr)
        pending = os.path.join(self.spool, f"task_{tid}.json")
        result_fn = os.path.join(self.spool, f"result_{tid}.json")
        atomic_write_text(pending, spec)
        t0 = time.monotonic()
        while True:
            if os.path.exists(result_fn):
                with open(result_fn) as f:
                    rec = json.load(f)
                if rec.get("ok"):
                    return rec["value"]
                raise RuntimeError(f"gang task {tid} failed in worker: "
                                   f"{rec.get('error', '?')}")
            with self._lock:     # one pumper at a time
                self.supervisor.step()
            claim = self._claim_of(tid)
            if claim is not None and self._claimant_dead(*claim[1:]):
                # the claiming worker PROCESS died mid-task (claims are
                # keyed by pid — a respawned rank is a different claimant):
                # re-spool for the replacement unless this task has burned
                # its own respawn budget
                requeues += 1
                if requeues > max_requeues:
                    raise RuntimeError(
                        f"gang task {tid}: worker rank {claim[1]} died "
                        f"{requeues} times (respawn budget exhausted)")
                os.rename(os.path.join(self.spool, claim[0]), pending)
            if deadline is not None and time.monotonic() > deadline:
                raise PeerLostError(op, [], time.monotonic() - t0,
                                    detail=f"gang task {tid} produced no "
                                           f"result within {budget_s}s")
            time.sleep(self.poll)

    def _claim_of(self, tid: str):
        """(claim filename, rank, pid) when some worker holds this task."""
        import os

        for fn in sorted(os.listdir(self.spool)):
            if fn.startswith(f"task_{tid}.claimed.r"):
                try:
                    rank_s, pid_s = fn.rsplit(".r", 1)[1].split(".p")
                    return fn, int(rank_s), int(pid_s)
                except ValueError:
                    return None
        return None

    def _claimant_dead(self, rank: int, pid: int) -> bool:
        proc = self.supervisor.procs.get(rank)
        if proc is None or proc.poll() is not None:
            return True
        return proc.pid != pid   # a respawned rank is not the claimant

    def close(self) -> None:
        """Stop the workers (stop file) and reap them (idempotent)."""
        import os

        from ..core.checkpoint import atomic_write_text

        atomic_write_text(os.path.join(self.spool, "stop"), "stop")
        self.supervisor.retire()

    def __enter__(self) -> "GangCandidatePool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
