"""TuneHyperparameters + FindBestModel.

Reference: core/.../automl/TuneHyperparameters.scala:38-228 (random/grid search
with parallel cross-validation over a thread pool; metric selects best) and
FindBestModel.scala (evaluate fitted models on a dataset, pick the winner).

Parallelism note: candidate fits run on a host thread pool like the reference;
each fit's device work is XLA-serialized per chip, so threads mainly overlap
host-side featurization + dispatch. On multi-chip meshes candidates can be
placed on disjoint device subsets by the caller."""

from __future__ import annotations

import hashlib
import json
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List

import numpy as np

from ..core.checkpoint import atomic_write_text, preemption_point
from ..core.logging import record_failure
from ..core.params import Param, HasLabelCol
from ..core.pipeline import Estimator, Model, Transformer
from ..core.table import Table
from ..train.metrics import auc_score, regression_metrics
from .hyperparams import GridSpace, RandomSpace

_MAXIMIZE = {"AUC", "accuracy", "precision", "recall", "f1", "R^2", "ndcg"}


def _evaluate(model: Transformer, df: Table, metric: str, label_col: str) -> float:
    scored = model.transform(df)
    y = np.asarray(df[label_col], np.float64)
    if metric == "AUC":
        s = scored["probability"][:, -1] if "probability" in scored else \
            np.asarray(scored["prediction"], np.float64)
        return auc_score(y, s)
    if metric in ("accuracy", "precision", "recall", "f1"):
        from ..train.metrics import binary_classification_metrics
        return float(binary_classification_metrics(
            y, np.asarray(scored["prediction"], np.float64))[metric])
    m = regression_metrics(y, scored["prediction"])
    return float(m[metric if metric in m else "rmse"])


class TuneHyperparameters(Estimator, HasLabelCol):
    """Random/grid hyperparameter search with k-fold CV."""
    model = Param("model", "Base estimator (its copy is refit per candidate)", object)
    paramSpace = Param("paramSpace", "Dict name→hyperparam space "
                       "(HyperparamBuilder.build())", object)
    searchMode = Param("searchMode", "random | grid", str, "random")
    numRuns = Param("numRuns", "Candidates for random search", int, 10)
    numFolds = Param("numFolds", "Cross-validation folds", int, 3)
    evaluationMetric = Param("evaluationMetric", "AUC | accuracy | f1 | rmse | ...",
                             str, "AUC")
    parallelism = Param("parallelism", "Concurrent candidate fits", int, 4)
    seed = Param("seed", "Search/CV seed", int, 0)
    checkpointDir = Param("checkpointDir", "Directory persisting per-candidate "
                          "results; an interrupted search resumes by skipping "
                          "finished candidates", str, "")

    def _candidates(self) -> List[Dict[str, Any]]:
        space = self.paramSpace
        if self.searchMode == "grid":
            return list(GridSpace(space))
        return list(RandomSpace(space, self.numRuns, self.seed))

    @staticmethod
    def _candidate_key(params: Dict[str, Any]) -> str:
        """Stable identity of one candidate: sha256 over canonical JSON."""
        blob = json.dumps(params, sort_keys=True, default=repr)
        return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:24]

    def _fit(self, df: Table) -> "TuneHyperparametersModel":
        candidates = self._candidates()
        k = max(self.numFolds, 2)
        n = df.num_rows
        rng = np.random.default_rng(self.seed)
        perm = rng.permutation(n)
        folds = np.array_split(perm, k)
        metric = self.evaluationMetric
        maximize = metric in _MAXIMIZE

        # resumable search: each finished candidate's score persists as one
        # atomically-written JSON file keyed by the candidate's param hash,
        # so a preempted search skips straight past completed work
        ckpt_dir = self.checkpointDir or ""
        completed: Dict[str, float] = {}
        if ckpt_dir:
            os.makedirs(ckpt_dir, exist_ok=True)
            for fn in os.listdir(ckpt_dir):
                if not (fn.startswith("cand_") and fn.endswith(".json")):
                    continue
                try:
                    with open(os.path.join(ckpt_dir, fn)) as f:
                        rec = json.load(f)
                    completed[fn[5:-5]] = float(rec["metric"])
                except (OSError, ValueError, KeyError, TypeError):
                    record_failure("automl.candidate_record_corrupt", file=fn)

        def run(indexed) -> float:
            i, params = indexed
            key = self._candidate_key(params)
            if key in completed:
                return completed[key]
            preemption_point("automl.candidate", i)
            try:
                scores = []
                for f in range(k):
                    val_idx = folds[f]
                    train_idx = np.concatenate(
                        [folds[j] for j in range(k) if j != f])
                    est = self.model.copy(extra=params)
                    fitted = est.fit(df.take(train_idx))
                    scores.append(_evaluate(fitted, df.take(val_idx), metric,
                                            self.labelCol))
                val = float(np.nanmean(scores))
            except Exception as e:
                # one broken candidate must not abort the search: score it
                # NaN (excluded by nanargmax/nanargmin) and keep going.
                # PreemptionError is a BaseException and still propagates.
                record_failure("automl.candidate_failure", index=i,
                               error=type(e).__name__, message=str(e)[:200])
                val = float("nan")
            if ckpt_dir:
                atomic_write_text(
                    os.path.join(ckpt_dir, f"cand_{key}.json"),
                    json.dumps({"params": params, "metric": val},
                               default=repr))
            return val

        with ThreadPoolExecutor(max_workers=max(self.parallelism, 1)) as pool:
            results = list(pool.map(run, enumerate(candidates)))

        if np.all(np.isnan(results)):
            raise ValueError("every candidate scored NaN — check labels/folds "
                             "(candidate failures are counted under "
                             "automl.candidate_failure)")
        best_i = int(np.nanargmax(results) if maximize else np.nanargmin(results))
        best_params = candidates[best_i]
        best_model = self.model.copy(extra=best_params).fit(df)
        return TuneHyperparametersModel(
            bestModel=best_model, bestParams=best_params,
            bestMetric=float(results[best_i]),
            allResults=[{"params": c, "metric": r} for c, r in zip(candidates, results)])


class TuneHyperparametersModel(Model):
    bestModel = Param("bestModel", "Winning fitted model", object)
    bestParams = Param("bestParams", "Winning hyperparameters", object)
    bestMetric = Param("bestMetric", "Winning CV metric value", float)
    allResults = Param("allResults", "All (params, metric) results", list)

    def _transform(self, df: Table) -> Table:
        return self.bestModel.transform(df)

    def getBestModel(self):
        return self.bestModel

    def getBestModelInfo(self) -> dict:
        return {"params": self.bestParams, "metric": self.bestMetric}

    def _save_extra(self, path: str) -> None:
        import os
        if self.get("bestModel") is not None:
            self.bestModel.save(os.path.join(path, "bestModel"))

    def _load_extra(self, path: str) -> None:
        import os
        from ..core.pipeline import PipelineStage
        p = os.path.join(path, "bestModel")
        if os.path.isdir(p):
            self.set("bestModel", PipelineStage.load(p))


class FindBestModelResult(Model):
    bestModel = Param("bestModel", "Winning fitted model", object)
    allModelMetrics = Param("allModelMetrics", "Per-model metric values", list)

    def _transform(self, df: Table) -> Table:
        return self.bestModel.transform(df)

    def _save_extra(self, path: str) -> None:
        import os
        if self.get("bestModel") is not None:
            self.bestModel.save(os.path.join(path, "bestModel"))

    def _load_extra(self, path: str) -> None:
        import os
        from ..core.pipeline import PipelineStage
        p = os.path.join(path, "bestModel")
        if os.path.isdir(p):
            self.set("bestModel", PipelineStage.load(p))


class FindBestModel(Estimator, HasLabelCol):
    """Pick the best of several already-fitted models on an evaluation dataset
    (FindBestModel.scala)."""
    models = Param("models", "Fitted Transformer list to compare", list)
    evaluationMetric = Param("evaluationMetric", "Metric name", str, "AUC")

    def _fit(self, df: Table) -> FindBestModelResult:
        models = self.models or []
        if not models:
            raise ValueError("FindBestModel requires a non-empty `models` list")
        metric = self.evaluationMetric
        maximize = metric in _MAXIMIZE
        scores = [_evaluate(m, df, metric, self.labelCol) for m in models]
        if np.all(np.isnan(scores)):
            raise ValueError("every model scored NaN — check labels/metric")
        best = models[int(np.nanargmax(scores) if maximize else np.nanargmin(scores))]
        return FindBestModelResult(
            bestModel=best,
            allModelMetrics=[{"model": type(m).__name__, "metric": s}
                             for m, s in zip(models, scores)])
