"""TuneHyperparameters + FindBestModel on the elastic halving scheduler.

Reference: core/.../automl/TuneHyperparameters.scala:38-228 (random/grid search
with parallel cross-validation over a thread pool; metric selects best) and
FindBestModel.scala (evaluate fitted models on a dataset, pick the winner).

The search substrate is :mod:`automl.scheduler`: every candidate is a
preemptible elastic job — budget-reaped when hung, respawned on crash,
early-stopped by successive-halving rungs (``halvingEta``), and checkpointed
(bracket state + fingerprinted per-candidate ``cand_<sha>.json`` records) so
kill→resume converges to the identical best model. With the default
``halvingEta=0`` the bracket degenerates to one full-CV rung: the classic
exhaustive search, minus none of the fault isolation. See docs/automl.md.

Parallelism note: candidate fits run on a host thread pool like the reference;
each fit's device work is XLA-serialized per chip, so threads mainly overlap
host-side featurization + dispatch. On multi-chip meshes candidates can be
placed on disjoint device subsets by the caller."""

from __future__ import annotations

import hashlib
import json
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Tuple

import numpy as np

from ..core.checkpoint import CheckpointStore, atomic_write_text, \
    preemption_point
from ..core.logging import record_failure
from ..core.params import Param, HasLabelCol
from ..core.pipeline import Estimator, Model, Transformer
from ..core.table import Table
from ..train.metrics import auc_score, regression_metrics
from .scheduler import ElasticHalvingScheduler, fingerprint_digest
from .hyperparams import (DiscreteHyperParam, GridSpace, RandomSpace,
                          RangeHyperParam)

_MAXIMIZE = {"AUC", "accuracy", "precision", "recall", "f1", "R^2", "ndcg"}


def _evaluate(model: Transformer, df: Table, metric: str, label_col: str) -> float:
    scored = model.transform(df)
    y = np.asarray(df[label_col], np.float64)
    if metric == "AUC":
        s = scored["probability"][:, -1] if "probability" in scored else \
            np.asarray(scored["prediction"], np.float64)
        return auc_score(y, s)
    if metric in ("accuracy", "precision", "recall", "f1"):
        from ..train.metrics import binary_classification_metrics
        return float(binary_classification_metrics(
            y, np.asarray(scored["prediction"], np.float64))[metric])
    m = regression_metrics(y, scored["prediction"])
    return float(m[metric if metric in m else "rmse"])


def _space_desc(spec: Any) -> Any:
    """Stable (address-free) description of one hyperparam space — the
    default object repr embeds the instance id, which would make every run
    look like a different search."""
    if isinstance(spec, DiscreteHyperParam):
        return ["discrete", [repr(v) for v in spec.values]]
    if isinstance(spec, RangeHyperParam):
        return ["range", repr(spec.low), repr(spec.high),
                bool(spec.log), bool(spec.integer)]
    return ["opaque", type(spec).__name__,
            sorted((k, repr(v)) for k, v in vars(spec).items()
                   if not k.startswith("_"))]


def _data_digest(df: Table) -> str:
    """Content digest of the training table — one pass of sha256 over every
    column's bytes, so a search resumed on different data is detectable."""
    h = hashlib.sha256()
    for name in sorted(df.columns):
        h.update(name.encode("utf-8"))
        h.update(b"\x00")
        h.update(np.ascontiguousarray(np.asarray(df[name])).tobytes())
    return h.hexdigest()[:24]


def _load_candidate_records(ckpt_dir: str, fp_digest: str
                            ) -> Tuple[Dict[str, float], List[str]]:
    """Read ``cand_<key>.json`` resume records: ``(completed, invalid)``.

    Corrupt records count ``automl.candidate_record_corrupt``; records whose
    fingerprint is missing or names a different data/space/metric/folds
    identity count ``automl.candidate_record_stale``. Both land in
    ``invalid`` so their candidates recompute instead of silently reusing a
    wrong score."""
    completed: Dict[str, float] = {}
    invalid: List[str] = []
    for fn in sorted(os.listdir(ckpt_dir)):
        if not (fn.startswith("cand_") and fn.endswith(".json")):
            continue
        key = fn[5:-5]
        try:
            with open(os.path.join(ckpt_dir, fn)) as f:
                rec = json.load(f)
            val = float(rec["metric"])
        except (OSError, ValueError, KeyError, TypeError):
            record_failure("automl.candidate_record_corrupt", file=fn)
            invalid.append(key)
            continue
        if rec.get("fingerprint") != fp_digest:
            record_failure("automl.candidate_record_stale", file=fn,
                           found=rec.get("fingerprint"), expected=fp_digest)
            invalid.append(key)
            continue
        completed[key] = val
    return completed, invalid


class TuneHyperparameters(Estimator, HasLabelCol):
    """Random/grid hyperparameter search with k-fold CV (elastic bracket)."""
    model = Param("model", "Base estimator (its copy is refit per candidate)", object)
    paramSpace = Param("paramSpace", "Dict name→hyperparam space "
                       "(HyperparamBuilder.build())", object)
    searchMode = Param("searchMode", "random | grid", str, "random")
    numRuns = Param("numRuns", "Candidates for random search", int, 10)
    numFolds = Param("numFolds", "Cross-validation folds", int, 3)
    evaluationMetric = Param("evaluationMetric", "AUC | accuracy | f1 | rmse | ...",
                             str, "AUC")
    parallelism = Param("parallelism", "Concurrent candidate fits", int, 4)
    seed = Param("seed", "Search/CV seed", int, 0)
    checkpointDir = Param("checkpointDir", "Directory persisting the bracket "
                          "state and per-candidate results; an interrupted "
                          "search resumes to the identical best model and "
                          "refuses a resume whose data/space/metric/folds "
                          "fingerprint changed", str, "")
    halvingEta = Param("halvingEta", "Successive-halving reduction factor; "
                       "0/1 disables early stopping (single full-CV rung)",
                       int, 0)
    minResourceFolds = Param("minResourceFolds", "CV folds every candidate "
                             "runs at the first rung when halving", int, 1)
    candidateBudgetSeconds = Param("candidateBudgetSeconds", "Wall-clock "
                                   "budget per candidate rung task; a hung "
                                   "task is reaped and scored NaN. 0 prices "
                                   "the budget from core.perfmodel when "
                                   "confident, else no reaper", float, 0.0)
    maxAttempts = Param("maxAttempts", "Fit attempts per candidate before "
                        "its crash is terminal (scored NaN)", int, 2)
    rungTimeBudgetSeconds = Param("rungTimeBudgetSeconds", "Optional "
                                  "per-rung fit-time budget: the promotion "
                                  "quota is trimmed to what core.perfmodel "
                                  "predicts fits inside it. 0 disables",
                                  float, 0.0)
    perfJournal = Param("perfJournal", "Journal observed rung times as "
                        "automl_rung perfmodel training rows", bool, False)

    def _candidates(self) -> List[Dict[str, Any]]:
        space = self.paramSpace
        if self.searchMode == "grid":
            return list(GridSpace(space))
        return list(RandomSpace(space, self.numRuns, self.seed))

    @staticmethod
    def _candidate_key(params: Dict[str, Any]) -> str:
        """Stable identity of one candidate: sha256 over canonical JSON."""
        blob = json.dumps(params, sort_keys=True, default=repr)
        return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:24]

    def _fingerprint(self, df: Table, k: int, metric: str) -> Dict[str, Any]:
        """Search identity: resume records and bracket checkpoints are only
        valid against the same data, space, metric and fold count."""
        return {
            "data_rows": df.num_rows,
            "data_schema": {c: [str(np.asarray(df[c]).dtype),
                                list(np.asarray(df[c]).shape[1:])]
                            for c in sorted(df.columns)},
            "data_digest": _data_digest(df),
            "space": {name: _space_desc(spec)
                      for name, spec in (self.paramSpace or {}).items()},
            "metric": metric,
            "numFolds": k,
            "searchMode": self.searchMode,
            "numRuns": self.numRuns,
            "seed": self.seed,
            "labelCol": self.labelCol,
        }

    def _fit(self, df: Table) -> "TuneHyperparametersModel":
        candidates = self._candidates()
        keys = [self._candidate_key(p) for p in candidates]
        k = max(self.numFolds, 2)
        n = df.num_rows
        rng = np.random.default_rng(self.seed)
        perm = rng.permutation(n)
        folds = np.array_split(perm, k)
        metric = self.evaluationMetric
        maximize = metric in _MAXIMIZE

        fingerprint = self._fingerprint(df, k, metric)
        fp_digest = fingerprint_digest(fingerprint)

        ckpt_dir = self.checkpointDir or ""
        completed: Dict[str, float] = {}
        invalid: List[str] = []
        store = None
        if ckpt_dir:
            os.makedirs(ckpt_dir, exist_ok=True)
            completed, invalid = _load_candidate_records(ckpt_dir, fp_digest)
            store = CheckpointStore(os.path.join(ckpt_dir, "bracket"),
                                    keep_last=3)

        def run_folds(i: int, params: Dict[str, Any],
                      lo: int, hi: int) -> List[float]:
            if lo == 0:
                preemption_point("automl.candidate", i)
            scores = []
            for f in range(lo, hi):
                val_idx = folds[f]
                train_idx = np.concatenate(
                    [folds[j] for j in range(k) if j != f])
                est = self.model.copy(extra=params)
                fitted = est.fit(df.take(train_idx))
                scores.append(_evaluate(fitted, df.take(val_idx), metric,
                                        self.labelCol))
            return scores

        sch = ElasticHalvingScheduler(
            run_folds, candidates, keys,
            maximize=maximize, total_folds=k,
            eta=self.halvingEta, min_resource=self.minResourceFolds,
            parallelism=max(self.parallelism, 1),
            max_attempts=max(self.maxAttempts, 1),
            budget_s=self.candidateBudgetSeconds or None,
            rung_time_budget_s=self.rungTimeBudgetSeconds or None,
            store=store, fingerprint=fingerprint,
            completed=completed, invalidate=invalid,
            perf_features={"rows": float(n),
                           "cols": float(max(len(df.columns) - 1, 1))},
            perf_journal=bool(self.perfJournal))

        if ckpt_dir:
            def _journal_record(key: str, val: float, folds_done: int,
                                _params=sch.params) -> None:
                atomic_write_text(
                    os.path.join(ckpt_dir, f"cand_{key}.json"),
                    json.dumps({"params": _params[key], "metric": val,
                                "folds": folds_done,
                                "fingerprint": fp_digest}, default=repr))
            sch.on_candidate_done(_journal_record)

        by_key = sch.run()
        results = [by_key[key]["metric"] for key in keys]

        if np.all(np.isnan(results)):
            raise ValueError("every candidate scored NaN — check labels/folds "
                             "(candidate failures are counted under "
                             "automl.candidate_failure)")
        finalists = sch.finalists()
        if finalists:
            best_key = finalists[0]
            best_i = sch.first_index[best_key]
        else:
            # chaos killed every finalist: deterministic fallback to the
            # best partial score across the whole bracket
            best_i = int(np.nanargmax(results) if maximize
                         else np.nanargmin(results))
        best_params = candidates[best_i]
        best_model = self.model.copy(extra=best_params).fit(df)
        return TuneHyperparametersModel(
            bestModel=best_model, bestParams=best_params,
            bestMetric=float(results[best_i]),
            allResults=[{"params": c, "metric": r} for c, r in zip(candidates, results)])


class TuneHyperparametersModel(Model):
    bestModel = Param("bestModel", "Winning fitted model", object)
    bestParams = Param("bestParams", "Winning hyperparameters", object)
    bestMetric = Param("bestMetric", "Winning CV metric value", float)
    allResults = Param("allResults", "All (params, metric) results", list)

    def _transform(self, df: Table) -> Table:
        return self.bestModel.transform(df)

    def getBestModel(self):
        return self.bestModel

    def getBestModelInfo(self) -> dict:
        return {"params": self.bestParams, "metric": self.bestMetric}

    def _save_extra(self, path: str) -> None:
        import os
        if self.get("bestModel") is not None:
            self.bestModel.save(os.path.join(path, "bestModel"))

    def _load_extra(self, path: str) -> None:
        import os
        from ..core.pipeline import PipelineStage
        p = os.path.join(path, "bestModel")
        if os.path.isdir(p):
            self.set("bestModel", PipelineStage.load(p))


class FindBestModelResult(Model):
    bestModel = Param("bestModel", "Winning fitted model", object)
    allModelMetrics = Param("allModelMetrics", "Per-model metric values", list)

    def _transform(self, df: Table) -> Table:
        return self.bestModel.transform(df)

    def _save_extra(self, path: str) -> None:
        import os
        if self.get("bestModel") is not None:
            self.bestModel.save(os.path.join(path, "bestModel"))

    def _load_extra(self, path: str) -> None:
        import os
        from ..core.pipeline import PipelineStage
        p = os.path.join(path, "bestModel")
        if os.path.isdir(p):
            self.set("bestModel", PipelineStage.load(p))


class FindBestModel(Estimator, HasLabelCol):
    """Pick the best of several already-fitted models on an evaluation dataset
    (FindBestModel.scala). Evaluation is parallel and per-model isolated:
    one broken model scores NaN (``automl.model_failure``) instead of
    aborting the comparison — TuneHyperparameters candidate semantics."""
    models = Param("models", "Fitted Transformer list to compare", list)
    evaluationMetric = Param("evaluationMetric", "Metric name", str, "AUC")
    parallelism = Param("parallelism", "Concurrent model evaluations", int, 4)

    def _fit(self, df: Table) -> FindBestModelResult:
        models = self.models or []
        if not models:
            raise ValueError("FindBestModel requires a non-empty `models` list")
        metric = self.evaluationMetric
        maximize = metric in _MAXIMIZE

        def score_one(indexed) -> float:
            i, m = indexed
            try:
                return _evaluate(m, df, metric, self.labelCol)
            except Exception as e:  # noqa: BLE001 — per-model isolation
                record_failure("automl.model_failure", index=i,
                               model=type(m).__name__,
                               error=type(e).__name__, message=str(e)[:200])
                return float("nan")

        with ThreadPoolExecutor(
                max_workers=max(min(self.parallelism, len(models)), 1)) as ex:
            scores = list(ex.map(score_one, enumerate(models)))
        if np.all(np.isnan(scores)):
            raise ValueError("every model scored NaN — check labels/metric "
                             "(model failures are counted under "
                             "automl.model_failure)")
        best = models[int(np.nanargmax(scores) if maximize else np.nanargmin(scores))]
        return FindBestModelResult(
            bestModel=best,
            allModelMetrics=[{"model": type(m).__name__, "metric": s}
                             for m, s in zip(models, scores)])
